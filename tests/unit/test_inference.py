"""Compiled in-plan inference (inference/, physical/compiled_predict.py).

Covers the tentpole contract end to end: tree/linear/kmeans lowering
equivalence vs sklearn ``predict`` (property-style over random fitted
trees, across dtypes and depths), the fused ``compiled_predict`` rung
(one executable, predictions matching the host path), zero-recompile
acceptance for literal variants AND retrained models, the ``predict``
fault site's ladder step-down with breaker charge, the estimator's
``model:`` row + admission interplay, PREDICT over encoded (DICT) inputs,
the SHOW MODELS / DESCRIBE MODEL lowering verdicts, the structured model
error taxonomy, and the HBM ledger's ``model_bytes`` component.
"""
import numpy as np
import pandas as pd
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu import config as config_module
from dask_sql_tpu import observability
from dask_sql_tpu.inference import try_lower
from dask_sql_tpu.resilience import faults
from dask_sql_tpu.resilience.errors import ModelError, QueryError

pytestmark = pytest.mark.inference


@pytest.fixture(autouse=True)
def _restore_global_config():
    keys = ("serving.cache.enabled", "resilience.inject",
            "serving.admission.max_estimated_bytes", "sql.compile.predict",
            "serving.bg_compile.enabled")
    before = {k: config_module.config.get(k) for k in keys}
    faults.reset()
    yield
    config_module.config.update(before)
    faults.reset()


def _ctx(n=3000, seed=0):
    c = Context()
    c.config.update({"serving.cache.enabled": False})
    rng = np.random.RandomState(seed)
    df = pd.DataFrame({
        "x": rng.rand(n),
        "y": rng.rand(n),
        "code": rng.choice([10, 20, 30, 40], n).astype(np.int64),
    })
    df["target"] = (df.x + df.y > 1).astype(np.int64)
    c.create_table("t", df)
    return c, df


def _traced(c, sql):
    tr = observability.QueryTrace(qid="q", sql=sql, metrics=c.metrics,
                                  profiles=c.profiles)
    with observability.activate(tr):
        res = c.sql(sql, return_futures=False)
    return res, tr


def _compiles(tr):
    return [s.name for s in tr.spans if s.name.startswith("compile:")]


# ------------------------------------------------------------- lowering
@pytest.mark.parametrize("maker,classify", [
    (lambda d, s: __import__("sklearn.tree", fromlist=["x"])
     .DecisionTreeRegressor(max_depth=d, random_state=s), False),
    (lambda d, s: __import__("sklearn.tree", fromlist=["x"])
     .DecisionTreeClassifier(max_depth=d, random_state=s), True),
    (lambda d, s: __import__("sklearn.ensemble", fromlist=["x"])
     .RandomForestRegressor(n_estimators=5, max_depth=d, random_state=s),
     False),
    (lambda d, s: __import__("sklearn.ensemble", fromlist=["x"])
     .RandomForestClassifier(n_estimators=5, max_depth=d, random_state=s),
     True),
    (lambda d, s: __import__("sklearn.ensemble", fromlist=["x"])
     .GradientBoostingRegressor(n_estimators=8, max_depth=d,
                                random_state=s), False),
    (lambda d, s: __import__("sklearn.ensemble", fromlist=["x"])
     .GradientBoostingClassifier(n_estimators=6, max_depth=d,
                                 random_state=s), True),
])
@pytest.mark.parametrize("depth", [2, 5])
def test_tree_lowering_equivalence(maker, classify, depth):
    """Property-style: random fitted trees lower to tensor programs whose
    predictions match sklearn ``predict`` across dtypes and depths."""
    import jax
    import jax.numpy as jnp

    for seed, dtype in ((1, np.float64), (2, np.float32), (3, np.int64)):
        rng = np.random.RandomState(seed)
        X = (rng.rand(200, 4) * 100).astype(dtype)
        if classify:
            y = (X[:, 0].astype(np.float64)
                 + X[:, 1].astype(np.float64) > 100).astype(np.int64)
        else:
            y = X.astype(np.float64) @ rng.rand(4) + rng.randn(200)
        model = maker(depth, seed).fit(X, y)
        program, reason = try_lower(model)
        assert program is not None, reason
        Xt = (rng.rand(73, 4) * 100).astype(dtype)
        params = tuple(jnp.asarray(p) for p in program.params)
        out = np.asarray(jax.jit(program.apply)(
            params, jnp.asarray(Xt, dtype=jnp.float64)))
        ref = model.predict(Xt)
        if classify:
            assert (out == ref).all()
        else:
            np.testing.assert_allclose(out, ref, rtol=1e-9, atol=1e-9)


def test_linear_logistic_kmeans_lowering_equivalence():
    import jax.numpy as jnp
    from sklearn.cluster import KMeans
    from sklearn.linear_model import LinearRegression, LogisticRegression

    from dask_sql_tpu.ml import jax_models

    rng = np.random.RandomState(0)
    X = rng.rand(150, 3)
    yreg = X @ rng.rand(3)
    yclf = (X[:, 0] > 0.5).astype(np.int64)
    Xt = rng.rand(40, 3)
    for model, y, classify in (
            (LinearRegression(), yreg, False),
            (LogisticRegression(max_iter=300), yclf, True),
            (KMeans(n_clusters=3, n_init=2, random_state=0), None, True),
            (jax_models.LinearRegression(), yreg, False),
            (jax_models.LogisticRegression(), yclf, True),
            (jax_models.KMeans(n_clusters=3), None, True)):
        model.fit(X) if y is None else model.fit(X, y)
        program, reason = try_lower(model)
        assert program is not None, reason
        params = tuple(jnp.asarray(p) for p in program.params)
        out = np.asarray(program.apply(params,
                                       jnp.asarray(Xt, dtype=jnp.float64)))
        ref = np.asarray(model.predict(Xt))
        if classify:
            assert (out == ref).all(), type(model).__name__
        else:
            np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_scaler_lowers_as_matrix_and_declines_fused_shape():
    from sklearn.preprocessing import StandardScaler

    X = np.random.RandomState(0).rand(64, 3)
    program, _ = try_lower(StandardScaler().fit(X))
    assert program is not None and program.output == "matrix"
    import jax.numpy as jnp

    out = np.asarray(program.apply(
        tuple(jnp.asarray(p) for p in program.params), jnp.asarray(X)))
    np.testing.assert_allclose(out, StandardScaler().fit(X).transform(X),
                               rtol=1e-12)


def test_declines_keep_host_path():
    from sklearn.tree import DecisionTreeClassifier

    from dask_sql_tpu.ml.wrappers import ParallelPostFit

    X = np.random.RandomState(0).rand(64, 2)
    y = np.array(["a", "b"] * 32)  # string labels: no DOUBLE target
    program, reason = try_lower(DecisionTreeClassifier(max_depth=2)
                                .fit(X, y))
    assert program is None and "class" in reason
    wrapped = ParallelPostFit(DecisionTreeClassifier(max_depth=2)
                              .fit(X, (y == "a").astype(int)))
    program, reason = try_lower(wrapped)
    assert program is None and "host" in reason


def test_gbdt_custom_init_and_multioutput_decline():
    """A custom GBDT ``init`` estimator makes the raw-score baseline
    row-dependent, and multi-output trees would silently drop every
    output but the first — both must DECLINE to the host path instead of
    lowering into silently-wrong fused programs."""
    from sklearn.ensemble import (
        GradientBoostingRegressor,
        RandomForestRegressor,
    )
    from sklearn.linear_model import LinearRegression

    rng = np.random.RandomState(0)
    X = rng.rand(200, 3)
    y = X @ rng.rand(3)
    gb = GradientBoostingRegressor(n_estimators=5, max_depth=2,
                                   init=LinearRegression(),
                                   random_state=0).fit(X, y)
    program, reason = try_lower(gb)
    assert program is None
    Y2 = np.stack([y, -y], axis=1)
    rf = RandomForestRegressor(n_estimators=3, max_depth=3,
                               random_state=0).fit(X, Y2)
    program, reason = try_lower(rf)
    assert program is None and "multi-output" in reason


def test_shape_key_stable_across_retrain():
    """The recompile identity bakes the model's SHAPE, never its weights:
    a bounded-depth retrain on different data keys identically."""
    from sklearn.ensemble import GradientBoostingRegressor

    rng = np.random.RandomState(0)
    X = rng.rand(300, 5)
    y = X @ rng.rand(5)
    a = GradientBoostingRegressor(n_estimators=10, max_depth=3,
                                  random_state=1).fit(X, y)
    b = GradientBoostingRegressor(n_estimators=10, max_depth=3,
                                  random_state=9).fit(X[::-1], y[::-1])
    pa, _ = try_lower(a)
    pb, _ = try_lower(b)
    assert pa.shape_key == pb.shape_key
    assert any((np.asarray(x) != np.asarray(y_)).any()
               for x, y_ in zip(pa.params, pb.params))


# ------------------------------------------------------------ fused rung
def _create_forest(c, **kw):
    opts = dict(n_estimators=6, max_depth=4, random_state=0)
    opts.update(kw)
    with_opts = ", ".join(f"{k} = {v}" for k, v in opts.items())
    c.sql(f"""CREATE OR REPLACE MODEL m WITH (
              model_class = 'sklearn.ensemble.RandomForestClassifier',
              target_column = 'target', {with_opts})
              AS (SELECT x, y, target FROM t)""")


def test_fused_predict_one_executable_matches_sklearn():
    c, df = _create_ctx_and_forest()
    res, tr = _traced(
        c, "SELECT * FROM PREDICT(MODEL m, SELECT x, y FROM t "
           "WHERE x < 0.5)")
    # answered on the fused rung: the rung span is present and the host
    # tier never ran (no mid-plan pandas round trip)
    assert any(s.name == "rung:compiled_predict" for s in tr.spans)
    counters = c.metrics.snapshot()["counters"]
    assert counters.get("inference.predict.compiled") == 1
    assert counters.get("inference.predict.host") is None
    assert counters.get("resilience.rung.compiled_predict") == 1
    model, cols = c.get_model(c.schema_name, "m")
    sub = df[df.x < 0.5]
    assert len(res) == len(sub)
    assert (res["target"].to_numpy()
            == model.predict(sub[cols].to_numpy())).all()


def _create_ctx_and_forest():
    c, df = _ctx()
    _create_forest(c)
    return c, df


def test_zero_recompile_for_variant_and_retrain():
    """Acceptance: a second literal variant AND a retrained model both
    serve with zero foreground compile spans."""
    c, df = _ctx()
    _create_forest(c, random_state=3)
    _res, tr1 = _traced(
        c, "SELECT * FROM PREDICT(MODEL m, SELECT x, y FROM t "
           "WHERE x < 0.74)")
    assert _compiles(tr1), "first member should pay the family compiles"
    res2, tr2 = _traced(
        c, "SELECT * FROM PREDICT(MODEL m, SELECT x, y FROM t "
           "WHERE x < 0.75)")
    assert _compiles(tr2) == []
    # retrain with the same hyper-shape: weights swap, executable reused
    _create_forest(c, random_state=11)
    res3, tr3 = _traced(
        c, "SELECT * FROM PREDICT(MODEL m, SELECT x, y FROM t "
           "WHERE x < 0.75)")
    assert _compiles(tr3) == []
    counters = c.metrics.snapshot()["counters"]
    assert counters.get("inference.model.swap") == 1
    # and the swapped executable serves the NEW model's predictions
    model, cols = c.get_model(c.schema_name, "m")
    sub = df[df.x < 0.75]
    assert (res3["target"].to_numpy()
            == model.predict(sub[cols].to_numpy())).all()
    assert any(e["event"] == "model.swap"
               for e in observability.flight.RECORDER.events())


def test_predict_fault_steps_down_with_breaker_charge():
    """The ``predict`` fault site proves compiled_predict -> host predict
    degradation, charged per (family, rung): three consecutive failures
    trip the breaker and the fourth submission skips the rung."""
    c, df = _ctx()
    _create_forest(c)
    sql = ("SELECT * FROM PREDICT(MODEL m, SELECT x, y FROM t "
           "WHERE x < 0.5)")
    model, cols = c.get_model(c.schema_name, "m")
    expected = model.predict(df[df.x < 0.5][cols].to_numpy())
    c.config.update({"resilience.inject": "predict:3"})
    for _ in range(3):
        res = c.sql(sql, return_futures=False)
        assert (res["target"].to_numpy() == expected).all()
    counters = c.metrics.snapshot()["counters"]
    assert counters.get("resilience.degraded.compiled_predict") == 3
    assert counters.get("inference.predict.host") == 3
    assert counters.get("resilience.breaker.trip", 0) >= 1
    # breaker open: the rung is skipped without re-paying the failure
    res = c.sql(sql, return_futures=False)
    assert (res["target"].to_numpy() == expected).all()
    counters = c.metrics.snapshot()["counters"]
    assert counters.get("resilience.breaker.skip.compiled_predict", 0) >= 1


def test_estimator_model_row_and_admission_interplay():
    """PREDICT plans estimate like any other operator: finite bounds, a
    ``model:`` row in EXPLAIN ESTIMATE, and the admission gate can shed
    an over-budget inference plan BEFORE any compile."""
    c, _df = _ctx()
    _create_forest(c)
    rows = c.sql(
        "EXPLAIN ESTIMATE SELECT * FROM PREDICT(MODEL m, "
        "SELECT x, y FROM t WHERE x < 0.5)",
        return_futures=False)
    text = "\n".join(rows[rows.columns[0]].astype(str))
    assert "model: name=m tier=compiled" in text
    assert "param_bytes=" in text
    assert "rows_hi=3000" in text  # finite bounds, not a CustomNode hole
    c.config.update({"serving.admission.max_estimated_bytes": 1024})
    with pytest.raises(QueryError) as ei:
        c.sql("SELECT * FROM PREDICT(MODEL m, SELECT x, y FROM t "
              "WHERE x < 0.5)", return_futures=False)
    assert "bytes" in str(ei.value).lower()
    counters = c.metrics.snapshot()["counters"]
    assert counters.get("serving.shed_estimated_bytes") == 1
    assert counters.get("inference.predict.compiled") is None  # pre-compile


def test_predict_over_encoded_table_feeds_fused_trace():
    """DICT-encoded input columns feed the fused kernel as codes (decode
    in-kernel, survivors only) — no full-table decode before inference."""
    c, df = _ctx()
    tab = c.get_table_data(c.schema_name, "t")
    from dask_sql_tpu.columnar.encodings import Encoding

    assert tab.columns["code"].encoding is Encoding.DICT
    c.sql("""CREATE MODEL dm WITH (
             model_class = 'sklearn.tree.DecisionTreeClassifier',
             target_column = 'target', max_depth = 4, random_state = 0)
             AS (SELECT x, y, code, target FROM t)""")
    before = c.metrics.counter("columnar.encoding.decode")
    res = c.sql("SELECT * FROM PREDICT(MODEL dm, SELECT x, y, code FROM t "
                "WHERE code = 20)", return_futures=False)
    counters = c.metrics.snapshot()["counters"]
    assert counters.get("inference.predict.compiled") == 1
    assert counters.get("columnar.encoding.decode", 0) == before
    model, cols = c.get_model(c.schema_name, "dm")
    sub = df[df.code == 20]
    assert len(res) == len(sub)
    assert (res["target"].to_numpy()
            == model.predict(sub[cols].to_numpy())).all()


# ----------------------------------------------------- operator surfaces
def test_show_models_and_describe_surface_lowering_verdict():
    c, _df = _ctx()
    _create_forest(c)
    c.sql("""CREATE MODEL hostm WITH (
             model_class = 'sklearn.tree.DecisionTreeClassifier',
             wrap_predict = True, target_column = 'target', max_depth = 2)
             AS (SELECT x, y, target FROM t)""")
    models = c.sql("SHOW MODELS", return_futures=False)
    by_name = {r.Model: r for r in models.itertuples()}
    assert by_name["m"].Tier == "compiled"
    assert int(by_name["m"].ParamBytes) > 0
    assert "trees=6" in by_name["m"].Shape
    assert by_name["hostm"].Tier == "host"
    desc = c.sql("DESCRIBE MODEL m", return_futures=False)
    rows = dict(zip(desc["Params"], desc["Value"]))
    assert rows["lowering.tier"] == "compiled"
    assert int(rows["lowering.param_bytes"]) > 0
    assert "depth=4" in rows["lowering.shape"]


def test_model_error_taxonomy():
    c, _df = _ctx()
    # the historically dead experiment_class option now surfaces
    with pytest.raises(ModelError) as ei:
        c.sql("""CREATE MODEL bad WITH (model_class = 'LinearRegression',
                 experiment_class = 'sklearn.model_selection.GridSearchCV',
                 target_column = 'target')
                 AS (SELECT x, y, target FROM t)""", return_futures=False)
    assert ei.value.code == "MODEL_ERROR"
    assert ei.value.error_type == "USER_ERROR"
    with pytest.raises(ModelError) as ei:
        c.sql("""CREATE MODEL bad WITH (model_class = 'NoSuchModel',
                 target_column = 'target')
                 AS (SELECT x, y, target FROM t)""", return_futures=False)
    assert ei.value.code == "MODEL_ERROR"
    with pytest.raises(QueryError) as ei:
        c.sql("SELECT * FROM PREDICT(MODEL ghost, SELECT x, y FROM t)",
              return_futures=False)
    assert ei.value.code == "MODEL_NOT_FOUND"


def test_ledger_tracks_model_bytes():
    c, _df = _ctx()
    assert c.ledger.snapshot()["modelBytes"] == 0
    _create_forest(c)
    c.sql("SELECT * FROM PREDICT(MODEL m, SELECT x, y FROM t "
          "WHERE x < 0.5)", return_futures=False)
    snap = c.ledger.snapshot()
    assert snap["modelBytes"] > 0
    c.ledger.publish(c.metrics)
    gauges = c.metrics.snapshot()["gauges"]
    assert gauges["serving.ledger.model_bytes"] == snap["modelBytes"]
    c.sql("DROP MODEL m", return_futures=False)
    assert c.ledger.snapshot()["modelBytes"] == 0


def test_show_models_verdict_does_not_commit_hbm():
    """Advisory surfaces (SHOW MODELS / DESCRIBE MODEL / the estimator)
    lower WITHOUT committing params to device: a catalog statement must
    not consume HBM for models that never PREDICT.  First fused use
    commits."""
    c, _df = _ctx()
    _create_forest(c)
    c.sql("SHOW MODELS", return_futures=False)
    c.sql("DESCRIBE MODEL m", return_futures=False)
    assert c.ledger.snapshot()["modelBytes"] == 0
    c.sql("SELECT * FROM PREDICT(MODEL m, SELECT x, y FROM t "
          "WHERE x < 0.5)", return_futures=False)
    assert c.ledger.snapshot()["modelBytes"] > 0


def test_fused_predict_batched_members_share_one_stacked_launch():
    """CompiledPredict.run_batched stacks only the family literal prefix
    (model weights ride unmapped — no per-slot weight copies), and every
    member's predictions match the host model over its own literal's
    survivors."""
    import jax.numpy as jnp

    from dask_sql_tpu import inference
    from dask_sql_tpu.physical import compiled_predict as cp

    c, df = _ctx()
    _create_forest(c)
    c.sql("SELECT * FROM PREDICT(MODEL m, SELECT x, y FROM t "
          "WHERE x < 0.3)", return_futures=False)  # builds + caches
    compiled = next(v for k, v in reversed(list(cp._cache.items()))
                    if k[3] == "m")
    model, cols = c.get_model(c.schema_name, "m")
    program, _ = inference.program_for(c, c.schema_name, "m", model,
                                       commit=True)
    table = c.get_table_data(c.schema_name, "t").select(["x", "y"])
    members = [(np.float64(0.25),) + tuple(program.params),
               (np.float64(0.6),) + tuple(program.params)]
    outs = compiled.run_batched(table, members)
    for lit, out in zip((0.25, 0.6), outs):
        sub = df[df.x < lit]
        assert out.num_rows == len(sub)
        got = np.asarray(jnp.ravel(out.columns["target"].data))[
            :out.num_rows]
        assert (got == model.predict(sub[cols].to_numpy())).all()
    # the stacked mask launch must not have duplicated the weight tail:
    # the batched vmap maps ONLY the family prefix
    axes = compiled._mask_batched  # built above
    assert axes is not None


def test_nullable_feature_declines_fused_and_surfaces_on_host():
    """A NULL in a feature column must not silently feed sentinel data
    into the fused kernel: the rung declines at construction and the host
    tier serves it with sklearn's own missing-value routing (or surfaces
    a structured error on models that reject NaN) — never silently-wrong
    fused predictions."""
    c, df = _ctx()
    _create_forest(c)
    df2 = df.copy()
    df2.loc[df2.index[:5], "x"] = np.nan
    c.create_table("tn", df2)
    res = c.sql("SELECT * FROM PREDICT(MODEL m, SELECT x, y FROM tn "
                "WHERE y < 0.9)", return_futures=False)
    counters = c.metrics.snapshot()["counters"]
    assert counters.get("inference.predict.compiled") is None
    assert counters.get("inference.predict.host") == 1
    model, cols = c.get_model(c.schema_name, "m")
    sub = df2[df2.y < 0.9]
    assert (res["target"].to_numpy()
            == model.predict(sub[cols].to_numpy())).all()


def test_bucket_growth_defers_predict_recompile_to_background():
    """Table growth/replacement of a SEEN predict family defers the
    recompile to the background thread (the triggering query serves on
    the host tier) instead of paying a foreground XLA compile — the same
    defer_rebuild policy as the sibling compiled rungs."""
    c, df = _ctx()
    _create_forest(c)
    sql = ("SELECT * FROM PREDICT(MODEL m, SELECT x, y FROM t "
           "WHERE x < 0.5)")
    c.sql(sql, return_futures=False)  # compiles + remembers the bucket
    c.config.update({"serving.bg_compile.enabled": True})
    rng = np.random.RandomState(1)
    big = pd.DataFrame({
        "x": rng.rand(9000), "y": rng.rand(9000),
        "code": rng.choice([10, 20, 30, 40], 9000).astype(np.int64),
    })
    big["target"] = (big.x + big.y > 1).astype(np.int64)
    c.create_table("t", big)  # replacement: new uid, larger pow2 bucket
    res = c.sql(sql, return_futures=False)
    counters = c.metrics.snapshot()["counters"]
    assert counters.get("serving.bg_compile.deferred", 0) >= 1
    assert counters.get("inference.predict.host", 0) >= 1
    model, cols = c.get_model(c.schema_name, "m")
    sub = big[big.x < 0.5]
    assert (res["target"].to_numpy()
            == model.predict(sub[cols].to_numpy())).all()


def test_drop_model_evicts_fused_pipelines():
    """DROP MODEL must not leave cached executables pinning committed
    weights the ledger no longer reports."""
    from dask_sql_tpu.physical import compiled_predict as cp

    c, _df = _ctx()
    _create_forest(c)
    c.sql("SELECT * FROM PREDICT(MODEL m, SELECT x, y FROM t "
          "WHERE x < 0.5)", return_futures=False)
    schema = c.schema_name
    assert any(k[2] == schema and k[3] == "m" for k in cp._cache)
    c.sql("DROP MODEL m", return_futures=False)
    assert not any(k[2] == schema and k[3] == "m" for k in cp._cache)
    assert c.ledger.snapshot()["modelBytes"] == 0


def test_estimator_param_bytes_ride_upper_bound_only():
    """Model params are device-resident only IF the fused rung serves the
    plan (per-plan eligibility can deny it), so they must ride the
    conservative UPPER bound, never the provable admission floor — and
    vanish from the estimate entirely when the rung is off."""
    c, _df = _ctx()
    _create_forest(c)
    sql = ("EXPLAIN ESTIMATE SELECT * FROM PREDICT(MODEL m, "
           "SELECT x, y FROM t WHERE x < 0.5)")
    on = c.sql(sql, return_futures=False)
    on_text = "\n".join(on[on.columns[0]].astype(str))
    assert "tier=compiled" in on_text
    c.config.update({"sql.compile.predict": False})
    off = c.sql(sql, return_futures=False)
    off_text = "\n".join(off[off.columns[0]].astype(str))
    assert "tier=host" in off_text

    def bound(text, tag):
        row = next(r for r in text.splitlines()
                   if r.startswith("estimate:"))
        return int(row.split(tag)[1].split()[0])

    assert bound(off_text, "bytes_lo=") == bound(on_text, "bytes_lo=")
    assert bound(off_text, "bytes_hi=") < bound(on_text, "bytes_hi=")


def test_model_boundary_keeps_resource_taxonomy():
    """MemoryError / XLA RESOURCE_EXHAUSTED inside fit/predict keep their
    degradable resource taxonomy class instead of becoming a USER_ERROR
    ModelError — the host tier is itself a degradation target."""
    from dask_sql_tpu.physical.rel.custom.ml import _model_boundary
    from dask_sql_tpu.resilience.errors import ResourceExhaustedError

    def oom():
        raise MemoryError("predict allocation")

    with pytest.raises(ResourceExhaustedError):
        _model_boundary("PREDICT(MODEL m)", oom)

    def bad():
        raise TypeError("bad feature matrix")

    with pytest.raises(ModelError) as ei:
        _model_boundary("PREDICT(MODEL m)", bad)
    assert ei.value.code == "MODEL_ERROR"


def test_predict_fault_site_is_registered():
    from dask_sql_tpu.resilience.faults import SITE_ERRORS, FaultInjector

    assert "predict" in SITE_ERRORS
    FaultInjector("predict:once")  # parses


def test_compile_predict_off_switch_keeps_host_path():
    c, df = _ctx()
    _create_forest(c)
    c.config.update({"sql.compile.predict": False})
    res = c.sql("SELECT * FROM PREDICT(MODEL m, SELECT x, y FROM t "
                "WHERE x < 0.5)", return_futures=False)
    counters = c.metrics.snapshot()["counters"]
    assert counters.get("inference.predict.compiled") is None
    assert counters.get("inference.predict.host") == 1
    assert len(res) == (df.x < 0.5).sum()
