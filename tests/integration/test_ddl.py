"""DDL / schema / introspection tests (parity: reference test_create.py,
test_schemas.py, test_show.py, test_analyze.py, test_distributeby.py)."""
import os

import pandas as pd
import pytest

from tests.utils import assert_eq


def test_create_table_as(c, df):
    c.sql("CREATE TABLE new_table AS (SELECT a, b FROM df WHERE a = 1)")
    result = c.sql("SELECT * FROM new_table").compute()
    expected = df[df.a == 1]
    assert_eq(result, expected, check_dtype=False)

def test_create_view_lazy(c, df):
    c.sql("CREATE VIEW my_view AS (SELECT a, b FROM df WHERE a = 2)")
    result = c.sql("SELECT COUNT(*) AS n FROM my_view").compute()
    assert result["n"][0] == (df.a == 2).sum()

def test_create_or_replace(c, df):
    c.sql("CREATE TABLE t1 AS (SELECT a FROM df)")
    with pytest.raises(RuntimeError):
        c.sql("CREATE TABLE t1 AS (SELECT b FROM df)")
    c.sql("CREATE OR REPLACE TABLE t1 AS (SELECT b FROM df)")
    assert list(c.sql("SELECT * FROM t1").compute().columns) == ["b"]
    c.sql("CREATE TABLE IF NOT EXISTS t1 AS (SELECT a FROM df)")
    assert list(c.sql("SELECT * FROM t1").compute().columns) == ["b"]

def test_drop_table(c, df):
    c.sql("CREATE TABLE to_drop AS (SELECT a FROM df)")
    c.sql("DROP TABLE to_drop")
    with pytest.raises(Exception):
        c.sql("SELECT * FROM to_drop")
    c.sql("DROP TABLE IF EXISTS to_drop")  # no error

def test_create_table_with_location(c, df_simple, tmp_path):
    path = str(tmp_path / "data.csv")
    df_simple.to_csv(path, index=False)
    c.sql(f"CREATE TABLE from_csv WITH (location = '{path}', format = 'csv')")
    result = c.sql("SELECT * FROM from_csv").compute()
    assert_eq(result, df_simple, check_dtype=False)

def test_create_table_parquet(c, df_simple, tmp_path):
    path = str(tmp_path / "data.parquet")
    df_simple.to_parquet(path)
    c.sql(f"CREATE TABLE from_pq WITH (location = '{path}', format = 'parquet')")
    result = c.sql("SELECT * FROM from_pq").compute()
    assert_eq(result, df_simple, check_dtype=False)

def test_schemas(c):
    c.sql("CREATE SCHEMA other")
    assert "other" in c.schema
    c.sql("USE SCHEMA other")
    assert c.schema_name == "other"
    c.sql("USE SCHEMA root")
    c.sql("ALTER SCHEMA other RENAME TO other2")
    assert "other2" in c.schema and "other" not in c.schema
    c.sql("DROP SCHEMA other2")
    assert "other2" not in c.schema

def test_show_schemas(c):
    result = c.sql("SHOW SCHEMAS").compute()
    assert "root" in list(result["Schema"])

def test_show_tables(c):
    result = c.sql("SHOW TABLES FROM root").compute()
    assert "df_simple" in list(result["Table"])

def test_show_columns(c):
    result = c.sql("SHOW COLUMNS FROM df_simple").compute()
    assert set(result["Column"]) == {"a", "b"}

def test_alter_table(c, df_simple):
    c.create_table("alter_me", df_simple)
    c.sql("ALTER TABLE alter_me RENAME TO altered")
    assert "altered" in c.schema["root"].tables
    c.sql("DROP TABLE altered")

def test_analyze_table(c, df):
    result = c.sql("ANALYZE TABLE df COMPUTE STATISTICS FOR ALL COLUMNS").compute()
    assert "col_name" in result.columns
    assert "a" in result.columns

def test_distribute_by(c, user_table_1):
    result = c.sql("SELECT * FROM user_table_1 DISTRIBUTE BY user_id").compute()
    assert len(result) == len(user_table_1)
    # rows with equal keys must be contiguous after the re-shard
    ids = list(result["user_id"])
    seen = set()
    prev = None
    for x in ids:
        if x != prev:
            assert x not in seen
            seen.add(x)
        prev = x

def test_explain(c, df):
    text = c.explain("SELECT a FROM df WHERE a > 1")
    assert "TableScan" in text

def test_explain_statement(c, df):
    result = c.sql("EXPLAIN SELECT a FROM df").compute()
    assert "PLAN" in result.columns

def test_sample(c, df):
    result = c.sql("SELECT * FROM df TABLESAMPLE BERNOULLI (50) WHERE a >= 1").compute()
    assert 0 < len(result) < len(df)
    result = c.sql("SELECT * FROM df TABLESAMPLE SYSTEM (50) REPEATABLE (42)").compute()
    assert 0 <= len(result) <= len(df)

def test_multiple_statements(c, df):
    result = c.sql("CREATE TABLE ms1 AS (SELECT a FROM df); SELECT COUNT(*) AS n FROM ms1")
    assert result.compute()["n"][0] == len(df)

def test_explain_analyze(c, df):
    result = c.sql("EXPLAIN ANALYZE SELECT a, SUM(b) AS s FROM df GROUP BY a").compute()
    text = "\n".join(result["PLAN"])
    assert "ms" in text and "rows" in text
    assert "Aggregate" in text

def test_case_insensitive_identifiers(c, df):
    result = c.sql("SELECT A FROM DF LIMIT 1",
                   config_options={"sql.identifier.case_sensitive": False}).compute()
    assert list(result.columns) == ["a"]

def test_exceptions_exported():
    from dask_sql_tpu.exceptions import BindError, LexError, ParsingException

    import pytest as _pytest
    from dask_sql_tpu import Context

    c2 = Context()
    with _pytest.raises(ParsingException):
        c2.sql("SELEC 1")

def test_memory_format_published_dataset(c, df_simple):
    from dask_sql_tpu.datacontainer import DataContainer
    from dask_sql_tpu.columnar import Table
    from dask_sql_tpu.input_utils.plugins import publish_dataset, unpublish_dataset

    publish_dataset("shared_ds", DataContainer(Table.from_pandas(df_simple)))
    try:
        c.sql("CREATE TABLE from_mem WITH (location = 'shared_ds', format = 'memory')")
        result = c.sql("SELECT * FROM from_mem").compute()
        assert len(result) == len(df_simple)
    finally:
        unpublish_dataset("shared_ds")
