"""Parquet footer statistics (parity: reference physical/utils/statistics.py:21
— per-file/per-row-group num-rows and per-column min/max read from footers,
no data scan; feeds the optimizer's row-count statistics)."""
from __future__ import annotations

import glob
import os
from typing import Dict, List, Optional


def _paths_for(location: str) -> List[str]:
    if any(ch in location for ch in "*?["):
        return sorted(glob.glob(location))
    if os.path.isdir(location):
        return sorted(glob.glob(os.path.join(location, "**", "*.parquet"), recursive=True))
    return [location]


def parquet_statistics(location: str, columns: Optional[List[str]] = None) -> Optional[dict]:
    """Read footers only.  Returns {"num-rows": int, "columns": {name: {min, max, null_count}}}."""
    try:
        import pyarrow.parquet as pq
    except ImportError:  # pragma: no cover
        return None
    paths = _paths_for(location)
    if not paths:
        return None
    total = 0
    col_stats: Dict[str, dict] = {}
    for path in paths:
        try:
            meta = pq.ParquetFile(path).metadata
        except Exception:  # dsql: allow-broad-except — unreadable footer
            # means "no statistics", never a query failure
            return None
        total += meta.num_rows
        for rg in range(meta.num_row_groups):
            group = meta.row_group(rg)
            for ci in range(group.num_columns):
                col = group.column(ci)
                name = col.path_in_schema
                if columns is not None and name not in columns:
                    continue
                stats = col.statistics
                if stats is None or not stats.has_min_max:
                    continue
                entry = col_stats.setdefault(name, {"min": None, "max": None, "null_count": 0})
                entry["min"] = stats.min if entry["min"] is None else min(entry["min"], stats.min)
                entry["max"] = stats.max if entry["max"] is None else max(entry["max"], stats.max)
                if stats.null_count is not None:
                    entry["null_count"] += stats.null_count
    return {"num-rows": total, "columns": col_stats}


def parquet_schema_fields(location: str):
    """Arrow schema of a parquet dataset (footer only) -> planner Fields."""
    import pyarrow.parquet as pq

    from ...columnar.dtypes import SqlType
    from ...columnar.interop import _arrow_array_to_column  # noqa: F401 (type map ref)
    from ...planner.expressions import Field
    import pyarrow as pa

    paths = _paths_for(location)
    schema = pq.ParquetFile(paths[0]).schema_arrow
    fields = []
    for f in schema:
        t = f.type
        if pa.types.is_string(t) or pa.types.is_large_string(t) or pa.types.is_dictionary(t):
            st = SqlType.VARCHAR
        elif pa.types.is_timestamp(t):
            st = SqlType.TIMESTAMP
        elif pa.types.is_date(t):
            st = SqlType.DATE
        elif pa.types.is_boolean(t):
            st = SqlType.BOOLEAN
        elif pa.types.is_integer(t):
            st = {8: SqlType.TINYINT, 16: SqlType.SMALLINT,
                  32: SqlType.INTEGER}.get(t.bit_width, SqlType.BIGINT)
        elif pa.types.is_floating(t):
            st = SqlType.FLOAT if t == pa.float32() else SqlType.DOUBLE
        elif pa.types.is_decimal(t):
            st = SqlType.DECIMAL
        else:
            st = SqlType.VARCHAR
        fields.append(Field(f.name, st, f.nullable))
    return fields
