"""Query observability: lifecycle tracing, per-fingerprint profiles,
Prometheus exposition, slow-query logging.

The serving stack (admission, result cache, degradation ladder, breaker,
estimator) makes multi-stage decisions per query; this subsystem makes
every stage visible (docs/observability.md):

- `spans`     — the `QueryTrace` span model, contextvar activation, the
                bounded `TraceStore` behind ``/v1/trace/{qid}``, and
                `timed_jit_call` per-rung compile timing;
- `profiles`  — `ProfileStore`: rolling per-fingerprint compile/exec/bytes
                profiles behind ``SHOW PROFILES``, persisted by the
                checkpoint subsystem;
- `prometheus`— text exposition of the MetricsRegistry for
                ``/v1/metrics?format=prometheus``;
- `slowlog`   — threshold-gated span-tree dumps of latency outliers.
"""
from .profiles import ProfileStore
from .prometheus import CONTENT_TYPE as PROMETHEUS_CONTENT_TYPE
from .prometheus import render_prometheus
from .slowlog import maybe_log_slow
from .spans import (
    QueryTrace,
    Span,
    TraceStore,
    activate,
    compile_sink,
    current_trace,
    stage,
    timed_jit_call,
    trace_event,
)

__all__ = [
    "ProfileStore",
    "PROMETHEUS_CONTENT_TYPE",
    "QueryTrace",
    "Span",
    "TraceStore",
    "activate",
    "compile_sink",
    "current_trace",
    "maybe_log_slow",
    "render_prometheus",
    "stage",
    "timed_jit_call",
    "trace_event",
]
