"""Chaos campaign harness: seeded fault storms under concurrent mixed load.

Every resilience mechanism in this package — retry/backoff, the
degradation ladder, circuit breakers, mid-stream repartition, the compile
watchdog, pressure reclaim — was proven by a *targeted* test that arms one
fault site and asserts one recovery path.  Real incidents are not
targeted: a wedged compile, a transient transfer drop and a device OOM
land in the same minute, on different queries, while clients cancel and
the admission queue backs up.  This module is the composition proof: a
deterministic (seeded) campaign arms rotating subsets of EVERY fault-
injection site (resilience/faults.py) as probability specs, drives a
concurrent mixed workload — interactive aggregates, batch scans, streamed
partitioned queries, PREDICT inference, exact-repeat cache hits, random
mid-flight cancels, checkpoint writes — through a real `ServingRuntime`,
and then asserts GLOBAL invariants that must hold after drain no matter
which faults fired in which order:

- every in-flight query table entry reached a terminal state;
- the packing scheduler's byte reservations and the HBM ledger's reserved
  gauge are back to idle (zero) — no leaked reservation on any path;
- every breaker left OPEN admits its half-open trial once its cooldown
  elapses (no permanently-wedged circuit);
- no zombie engine threads survive ``shutdown(wait=True)``;
- the flight-recorder event sequence is causally consistent per query
  (an admit precedes any finish; at most one finish per qid).

Individual query outcomes are free — success, degraded success, retryable
failure, shed, cancel are all acceptable under chaos; what is NOT
acceptable is corrupted engine state after the storm passes.  Exposed as
``bench.py --chaos`` (exits 1 on any violation) and the ``chaos``-marked
test module (tests/unit/test_chaos.py).
"""
from __future__ import annotations

import itertools
import logging
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..runtime import locks as runtime_locks

logger = logging.getLogger(__name__)

#: process-unique campaign nonce folded into every qid: the flight
#: recorder is process-global, so a SECOND campaign in the same process
#: must not see the first campaign's ``query.finish`` events under its
#: own qids when checking per-query causality
_campaign_nonce = itertools.count()

#: every error-raising inject site plus the hang site — the campaign
#: rotates probability-armed subsets of this list (ISSUE 17)
ALL_SITES = ("compile", "oom", "exec_oom", "execute", "checkpoint",
             "spmd", "predict", "partition", "d2h", "compile_hang")

#: base config for a campaign: fast backoff, short breaker cooldown, a
#: short injected hang with a compile deadline it trips, a flight ring
#: big enough that a campaign's events are never evicted mid-run
_BASE_CONFIG = {
    "resilience.retry.max_attempts": 2,
    "resilience.retry.base_s": 0.01,
    "resilience.retry.max_s": 0.05,
    "resilience.breaker.threshold": 2,
    "resilience.breaker.cooldown_s": 0.2,
    "resilience.compile_timeout_ms": 2000.0,
    "resilience.inject.hang_s": 0.05,
    "serving.stream.min_chunk_rows": 64,
    "serving.stream.launch_timeout_ms": 5000.0,
    "observability.flight.capacity": 65536,
}


@dataclass
class ChaosReport:
    """Outcome of one campaign: per-query tallies plus the invariant
    violations (empty = the engine state survived the storm intact)."""

    seed: int
    rounds: int = 0
    submitted: int = 0
    completed: int = 0
    failed: int = 0
    cancelled: int = 0
    shed: int = 0
    violations: List[str] = field(default_factory=list)
    #: per-round armed specs, for reproducing a failure: (round, spec, seed)
    armed: List[Tuple[int, str, int]] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations

    def summary(self) -> str:
        return (f"chaos seed={self.seed}: {self.submitted} queries over "
                f"{self.rounds} rounds ({self.completed} ok, {self.failed} "
                f"failed, {self.cancelled} cancelled, {self.shed} shed); "
                f"{len(self.violations)} invariant violation(s)")


def _build_context(rng: random.Random):
    """A fresh Context with the chaos fixture data: a small table (fast
    interactive aggregates + cache hits), a bigger table sized to force
    streamed routing under the per-query byte gate, and a trained model
    for PREDICT traffic."""
    import numpy as np
    import pandas as pd

    from ..context import Context

    c = Context()
    n_small, n_big = 200, 4096
    c.create_table("t_small", pd.DataFrame({
        "a": np.arange(n_small, dtype=np.float64),
        "b": np.arange(n_small) % 7,
    }))
    c.create_table("t_big", pd.DataFrame({
        "k": np.arange(n_big) % 13,
        "v": rng.random() + np.arange(n_big, dtype=np.float64),
    }))
    df = pd.DataFrame({
        "x": np.linspace(0.0, 1.0, n_small),
        "y": np.linspace(1.0, 0.0, n_small),
    })
    df["target"] = (df.x * 2 + df.y > 1.2).astype(np.int64)
    c.create_table("train", df)
    c.sql("""CREATE MODEL chaos_model WITH (
                 model_class = 'LinearRegression',
                 target_column = 'target'
             ) AS (SELECT x, y, target FROM train)""")
    return c


def _query_mix(stream_budget: int) -> List[Tuple[str, str, Dict]]:
    """(sql, priority_class, per-query config overrides) templates; the
    campaign cycles through them so every round carries every shape."""
    stream_opts = {"serving.admission.max_estimated_bytes": stream_budget}
    return [
        ("SELECT b, SUM(a) AS s FROM t_small GROUP BY b",
         "interactive", {}),
        # exact repeat of the query above: result-cache / reuse traffic
        ("SELECT b, SUM(a) AS s FROM t_small GROUP BY b",
         "interactive", {}),
        ("SELECT COUNT(*) AS n, SUM(a) AS s FROM t_small", "batch", {}),
        # the per-query byte gate forces this one onto a streamed rung
        ("SELECT k, SUM(v) AS s FROM t_big GROUP BY k",
         "interactive", stream_opts),
        ("SELECT k, SUM(v) AS s FROM t_big GROUP BY k",
         "batch", stream_opts),
        ("SELECT * FROM PREDICT(MODEL chaos_model, "
         "SELECT x, y FROM t_small_pred)", "interactive", {}),
        ("SELECT a, b FROM t_small WHERE a > 50", "interactive", {}),
    ]


def run_campaign(seed: int, queries: int = 40, rounds: int = 4,
                 workers: int = 4,
                 state_dir: Optional[str] = None) -> ChaosReport:
    """Run one seeded chaos campaign; deterministic per (seed, queries,
    rounds, workers) in which faults arm where (individual interleavings
    still race — that is the point — but the invariants are
    order-independent).  ``state_dir`` additionally exercises the
    ``checkpoint`` site with one ``save_state`` per round."""
    from .. import config as config_module
    from ..observability import flight
    from ..serving.cache import table_nbytes
    from ..serving.runtime import ServingRuntime
    from ..serving.scheduler import QueryCost
    from . import faults

    rng = random.Random(seed)
    report = ChaosReport(seed=seed)
    saved = list(config_module.config.effective_items())
    faults.reset()
    lock_baseline = runtime_locks.violation_count()
    try:
        config_module.config.update(dict(_BASE_CONFIG))
        context = _build_context(rng)
        big_bytes = table_nbytes(
            context.schema["root"].tables["t_big"].table)
        # per-query gate a third of the big table: full scans exceed it,
        # chunks fit — the streamed templates route instead of shedding
        stream_budget = max(4096, big_bytes // 3)
        # PREDICT input table (left out of _build_context so its name
        # telegraphs its purpose in SHOW QUERIES output)
        context.sql("CREATE TABLE t_small_pred AS "
                    "(SELECT a / 200.0 AS x, b / 7.0 AS y FROM t_small)")
        # device budget for the pressure bands: roomy enough to sit GREEN
        # at idle, tight enough that concurrent reservations + cache
        # growth can push it into YELLOW/RED during a storm
        total_bytes = sum(table_nbytes(dc.table) for dc in
                          context.schema["root"].tables.values())
        config_module.config.update({
            "serving.scheduler.device_budget_bytes": total_bytes * 3,
        })
        runtime = ServingRuntime(workers=workers, metrics=context.metrics,
                                 scheduler_budget_bytes=total_bytes * 2)
        context.serving = runtime
        context.metrics.inc("chaos.campaigns")
        templates = _query_mix(stream_budget)
        qids: List[str] = []
        nonce = next(_campaign_nonce)
        try:
            per_round = max(1, queries // max(1, rounds))
            for rnd in range(rounds):
                n_armed = rng.randint(2, max(2, len(ALL_SITES) // 2))
                sites = rng.sample(ALL_SITES, n_armed)
                spec = ",".join(
                    f"{s}:{rng.choice(('0.2', '0.4', 'once'))}"
                    for s in sites)
                round_seed = rng.randint(0, 1 << 30)
                overrides = {"resilience.inject": spec,
                             "resilience.inject.seed": round_seed}
                report.armed.append((rnd, spec, round_seed))
                context.metrics.inc("chaos.rounds")
                flight.record("chaos.arm", round=rnd, spec=spec,
                              seed=round_seed)
                logger.info("chaos round %d arming %r (seed %d)",
                            rnd, spec, round_seed)
                futures = []
                for i in range(per_round):
                    sql, cls, qopts = templates[
                        (rnd * per_round + i) % len(templates)]
                    qid = f"chaos-{seed}.{nonce}-{rnd}-{i}"

                    def job(ticket, _sql=sql, _opts=dict(qopts)):
                        # overlays are thread-local: armed INSIDE the
                        # worker thread, for this job's extent only
                        with config_module.config.set({**overrides,
                                                       **_opts}):
                            return context.sql(_sql).compute()

                    # dsql: allow-unpaired-effect — settled by _finisher
                    entry = context.live_queries.begin(qid, sql=sql,
                                                       priority_class=cls)
                    try:
                        _, fut, ticket = runtime.submit(
                            job, qid=qid, priority_class=cls,
                            cost=QueryCost(bytes_lo=rng.randint(1024,
                                                                65536)))
                    except Exception:  # dsql: allow-broad-except — a
                        # queue-full shed is a legitimate chaos outcome
                        context.live_queries.discard(qid)
                        report.shed += 1
                        continue
                    entry.ticket = ticket
                    fut.add_done_callback(
                        _finisher(context, qid))
                    report.submitted += 1
                    context.metrics.inc("chaos.queries")
                    qids.append(qid)
                    futures.append((qid, fut, ticket))
                if state_dir is not None and futures:
                    # exercise the checkpoint site mid-storm (failure is
                    # an acceptable outcome; corrupted CURRENT is not —
                    # save_state repoints atomically)
                    try:
                        with config_module.config.set(overrides):
                            context.save_state(state_dir)
                    except Exception:  # dsql: allow-broad-except — the
                        # injected write error is the expected outcome
                        logger.info("chaos checkpoint failed (expected "
                                    "under injection)", exc_info=True)
                # cancel a random ~15% slice mid-flight: the cooperative
                # checkpoints must release reservations exactly once
                for qid, _fut, ticket in futures:
                    if rng.random() < 0.15:
                        flight.record("query.cancel", qid=qid)
                        ticket.cancel()
                for qid, fut, _ticket in futures:
                    try:
                        fut.result(60.0)
                        report.completed += 1
                    except Exception as exc:  # dsql: allow-broad-except —
                        # every failure taxonomy is an acceptable chaos
                        # outcome; the invariants below are the real check
                        from ..serving.admission import QueryCancelledError

                        if isinstance(exc, QueryCancelledError):
                            report.cancelled += 1
                        else:
                            report.failed += 1
                report.rounds += 1
                faults.reset()  # re-arm `once` budgets for the next round
            # drain FIRST: the thread/ledger/reservation invariants are
            # statements about the engine's state after a clean shutdown
            runtime.shutdown(wait=True)
            _check_invariants(report, context, runtime, qids,
                              lock_baseline=lock_baseline)
        finally:
            runtime.shutdown(wait=True)
    finally:
        # every key the campaign touched exists in the defaults, so
        # re-applying the saved effective items restores them all
        config_module.config.update(dict(saved))
        faults.reset()
    for v in report.violations:
        logger.error("chaos invariant violation: %s", v)
    return report


def _finisher(context, qid: str):
    """Done-callback mirroring the server front-end: the submitter owns
    the live entry's terminal state (the worker may retry attempts)."""

    def done(fut):
        from ..serving.admission import QueryCancelledError

        if fut.cancelled():
            context.live_queries.finish(qid, "cancelled")
            return
        exc = fut.exception()
        if exc is None:
            context.live_queries.finish(qid, "done")
        elif isinstance(exc, QueryCancelledError):
            context.live_queries.finish(qid, "cancelled",
                                        getattr(exc, "code", None))
        else:
            context.live_queries.finish(
                qid, "failed",
                getattr(exc, "code", None) or type(exc).__name__)

    return done


def _check_invariants(report: ChaosReport, context, runtime,
                      qids: List[str], lock_baseline: int = 0) -> None:
    """The global post-drain invariants; appends human-readable violation
    strings to the report (and counts ``chaos.violations``)."""
    from ..observability import flight

    def violate(msg: str) -> None:
        report.violations.append(msg)
        context.metrics.inc("chaos.violations")

    # 1. every live-table entry terminal
    live = context.live_queries.live_entries()
    if live:
        violate(f"non-terminal live entries after drain: "
                f"{[(e.qid, e.state) for e in live]}")

    # 2. reservations and ledger back to idle (poll briefly: the last
    # worker's _release runs after its future resolves)
    deadline = time.monotonic() + 5.0
    reserved = context.ledger.reserved_bytes()
    while reserved and time.monotonic() < deadline:
        time.sleep(0.01)
        reserved = context.ledger.reserved_bytes()
    if reserved:
        violate(f"scheduler still holds {reserved} reserved bytes "
                f"after drain")
    snap = context.ledger.snapshot()
    if snap["reservedBytes"] != 0:
        violate(f"ledger reservedBytes={snap['reservedBytes']} != 0 "
                f"after drain")
    if snap["inflightMeasuredBytes"] != 0:
        violate(f"ledger inflightMeasuredBytes="
                f"{snap['inflightMeasuredBytes']} != 0 after drain")

    # 3. every OPEN breaker admits its half-open trial after cooldown
    state = context.breaker.snapshot_state()
    if state["open"]:
        time.sleep(context.breaker.cooldown_s + 0.05)
        for entry in state["open"]:
            key = tuple(entry["key"])
            # invariant probe: the granted trial is intentionally left
            # unsettled — the campaign ends here
            # dsql: allow-unpaired-effect — probe-only grant
            if not context.breaker.allow(key):
                violate(f"breaker {key} still refuses its half-open "
                        f"trial after cooldown")

    # 4. no zombie engine threads past shutdown(wait=True); watchdog
    # helper threads get a grace window to finish their bounded hangs
    for t in runtime._threads:
        if t.is_alive():
            violate(f"serving worker {t.name} alive after "
                    f"shutdown(wait=True)")
    deadline = time.monotonic() + 3.0
    while time.monotonic() < deadline:
        strays = [t.name for t in threading.enumerate()
                  if t.name.startswith(("dsql-warmup", "dsql-bg-compile",
                                        "dsql-compile-watchdog"))
                  and t.is_alive()]
        if not strays:
            break
        time.sleep(0.05)
    else:
        violate(f"zombie background threads after drain: {strays}")

    # 5. flight-recorder causality per submitted qid
    events = flight.RECORDER.events()
    by_qid: Dict[str, List[dict]] = {}
    for e in events:
        q = e.get("qid")
        if q is not None:
            by_qid.setdefault(q, []).append(e)
    for qid in qids:
        evs = by_qid.get(qid, [])
        admits = [e for e in evs if e["event"] == "query.admit"]
        finishes = [e for e in evs if e["event"] == "query.finish"]
        if len(finishes) > 1:
            violate(f"{qid}: {len(finishes)} query.finish events "
                    f"(expected at most 1)")
        if finishes and not admits:
            violate(f"{qid}: query.finish with no query.admit")
        if finishes and admits and admits[0]["ts"] > finishes[0]["ts"]:
            violate(f"{qid}: query.admit after query.finish")

    # 6. no lock-order violation observed (runtime/locks.py sanitizer —
    # a no-op unless the suite armed it; the storm IS the stress test
    # for the declared rank order)
    excess = runtime_locks.violation_count() - lock_baseline
    if excess:
        details = "; ".join(
            f"{v['kind']}: holding {v['holding']} acquiring "
            f"{v['acquiring']} on {v['thread']}"
            for v in runtime_locks.violations()[-excess:])
        violate(f"{excess} lock-order violation(s) during the storm "
                f"({details})")


# ===================================================================== fleet
@dataclass
class FleetChaosReport(ChaosReport):
    """Outcome of one replica-kill campaign across a fleet (ISSUE 18)."""

    kills: int = 0
    promoted: int = 0
    inserts: int = 0
    retried: int = 0

    def summary(self) -> str:
        return (f"fleet chaos seed={self.seed}: {self.submitted} queries "
                f"over {self.rounds} rounds ({self.completed} ok, "
                f"{self.retried} client retries, {self.failed} failed, "
                f"{self.shed} shed), {self.kills} replicas killed, "
                f"{self.promoted} standby promoted, {self.inserts} inserts; "
                f"{len(self.violations)} invariant violation(s)")


def run_fleet_campaign(seed: int, queries: int = 30, rounds: int = 3,
                       replicas: int = 3, clients: int = 4,
                       sync_dir: Optional[str] = None) -> FleetChaosReport:
    """Replica-kill chaos across a router-fronted fleet (ISSUE 18): drive
    the concurrent mixed workload THROUGH the fleet router, kill -9 one
    replica per round mid-workload (round 0 stays clean to warm profiles
    and sync the standby), and assert the fleet-level invariants:

    - ZERO lost queries: every routed statement reaches a terminal state
      with success or a structured retryable outcome (a non-retryable
      failure under pure replica-kill chaos is a violation);
    - INSERT INTO applies exactly once per surviving replica no matter
      how many times failover retried it (epoch fencing): every
      survivor's row count equals base rows + successful inserts, and
      all survivors agree;
    - the promoted standby serves reads (it was promoted, it is READY,
      and it converged to the same row count);
    - router + survivor ledgers reconcile to idle after drain.

    Deterministic per (seed, queries, rounds, replicas) in what is
    submitted and which replica dies when; interleavings race — that is
    the point — but the invariants are order-independent."""
    from concurrent.futures import ThreadPoolExecutor

    from .. import config as config_module
    from ..fleet import READY, build_fleet
    from ..serving.cache import table_nbytes
    from . import faults

    rng = random.Random(seed)
    report = FleetChaosReport(seed=seed)
    saved = list(config_module.config.effective_items())
    faults.reset()
    nonce = next(_campaign_nonce)
    lock_baseline = runtime_locks.violation_count()
    try:
        config_module.config.update({
            **_BASE_CONFIG,
            "fleet.failover.max_attempts": 4,
            "fleet.failover.base_s": 0.01,
            "fleet.result_timeout_s": 30.0,
        })

        def factory():
            c = _build_context(random.Random(seed))
            c.sql("CREATE TABLE t_small_pred AS "
                  "(SELECT a / 200.0 AS x, b / 7.0 AS y FROM t_small)")
            return c

        router, members, replicator = build_fleet(
            factory, replicas=replicas, standby=True, sync_dir=sync_dir)
        base_rows = 200  # t_small rows in the fixture
        big_bytes = table_nbytes(
            members[0].context.schema["root"].tables["t_big"].table)
        templates = _query_mix(max(4096, big_bytes // 3))
        per_round = max(2, queries // max(1, rounds))
        ok_inserts = 0
        lock = threading.Lock()

        def client(sql, cls, qopts, qid, is_insert):
            nonlocal ok_inserts
            delay = 0.02
            for attempt in range(6):
                try:
                    router.execute(sql, qid=qid, priority_class=cls,
                                   config_options=qopts)
                    if is_insert:
                        with lock:
                            ok_inserts += 1
                    return "ok"
                except Exception as exc:  # dsql: allow-broad-except —
                    # outcome taxonomy IS what the campaign classifies
                    if getattr(exc, "retryable", False):
                        if attempt < 5:
                            with lock:
                                report.retried += 1
                            time.sleep(delay)
                            delay *= 2
                            continue
                        return "retryable"
                    return (f"fatal:{getattr(exc, 'code', None) or type(exc).__name__}"
                            f" {exc}")
            return "retryable"

        try:
            with ThreadPoolExecutor(max_workers=clients,
                                    thread_name_prefix="fleet-client") as pool:
                for rnd in range(rounds):
                    tasks = []
                    for i in range(per_round):
                        sql, cls, qopts = templates[
                            (rnd * per_round + i) % len(templates)]
                        qid = f"fleet-{seed}.{nonce}-{rnd}-{i}"
                        tasks.append((sql, cls, qopts, qid, False))
                    for j in range(2):
                        # the router's write log dedupes on the client qid
                        # (retries below re-use it); the per-(round, slot)
                        # tag keeps inserted rows distinguishable
                        tag = 10000 + rnd * 100 + j
                        tasks.append((
                            f"INSERT INTO t_small SELECT a + {tag}, b "
                            f"FROM t_small WHERE a < 1",
                            "interactive", {},
                            f"fleet-ins-{seed}.{nonce}-{rnd}-{j}", True))
                    rng.shuffle(tasks)
                    futures = [pool.submit(client, *t) for t in tasks]
                    report.submitted += len(tasks)
                    if rnd > 0 and rnd < len(members):
                        # kill -9 one replica mid-workload; the standby
                        # absorbs the first death via promotion
                        time.sleep(0.05)
                        victim = members[rnd]
                        if victim.state == READY:
                            logger.info("fleet chaos round %d killing %s",
                                        rnd, victim.name)
                            router.kill(victim.name)
                            report.kills += 1
                    for f in futures:
                        status = f.result(180.0)
                        if status == "ok":
                            report.completed += 1
                        elif status == "retryable":
                            report.shed += 1
                        else:
                            report.failed += 1
                            report.violations.append(
                                f"round {rnd}: non-retryable outcome under "
                                f"replica-kill chaos: {status}")
                    if rnd == 0 and replicator is not None:
                        # quiet window: warm the standby off round-0 state
                        # (snapshot carries table epochs + profiles; the
                        # process compile cache is shared in-process)
                        replicator.sync()
                    report.rounds += 1

            report.inserts = ok_inserts
            promoted = [r for r in router.replicas
                        if r.name == "standby" and r.state == READY]
            report.promoted = len(promoted)
            if report.kills and not promoted:
                report.violations.append(
                    "standby was never promoted despite replica kills")

            # exactly-once INSERT: every surviving replica agrees on
            # base + successful-inserts rows, no more (a double apply
            # would overshoot), no fewer (a lost write would undershoot)
            survivors = [r for r in router.replicas if r.state == READY]
            if not survivors:
                report.violations.append("no surviving replica after chaos")
            expect = base_rows + ok_inserts
            for r in survivors:
                out = r.context.sql("SELECT COUNT(*) AS n FROM t_small",
                                    return_futures=False)
                n = int(out["n"][0])
                if n != expect:
                    report.violations.append(
                        f"{r.name}: t_small has {n} rows, expected "
                        f"{expect} (base {base_rows} + {ok_inserts} "
                        f"inserts applied exactly once)")

            # drain the fleet, then every ledger must reconcile to idle
            for r in survivors:
                r.drain(wait=True)
            checked = list(dict.fromkeys(
                members + list(router.replicas)
                + ([router.standby] if router.standby else [])))
            for r in checked:
                deadline = time.monotonic() + 5.0
                reserved = r.context.ledger.reserved_bytes()
                while reserved and time.monotonic() < deadline:
                    time.sleep(0.01)
                    reserved = r.context.ledger.reserved_bytes()
                if reserved:
                    report.violations.append(
                        f"{r.name}: ledger still holds {reserved} reserved "
                        f"bytes after fleet drain")

            # no lock-order violation observed (runtime/locks.py): the
            # kill/failover/promotion storm exercises the full declared
            # rank order — router apply -> router state -> replica
            # state/write -> plan cache -> registry -> metrics/flight
            excess = runtime_locks.violation_count() - lock_baseline
            if excess:
                details = "; ".join(
                    f"{v['kind']}: holding {v['holding']} acquiring "
                    f"{v['acquiring']} on {v['thread']}"
                    for v in runtime_locks.violations()[-excess:])
                report.violations.append(
                    f"{excess} lock-order violation(s) during the "
                    f"fleet storm ({details})")
        finally:
            router.shutdown()
    finally:
        config_module.config.update(dict(saved))
        faults.reset()
    for v in report.violations:
        logger.error("fleet chaos invariant violation: %s", v)
    return report
