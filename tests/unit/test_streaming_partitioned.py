"""Streaming partitioned execution (streaming/, ISSUE 13).

The acceptance surface: a query whose provable ``peak_bytes.lo`` exceeds
``serving.admission.max_estimated_bytes`` completes — byte-identical to an
unconstrained context — via N>1 streamed partition launches of ONE morsel
executable (zero foreground compiles after the first partition, and zero
for the second streamed run of a family); an injected mid-stream OOM at
the ``partition`` site repartitions and RESUMES from the last completed
partition; exhausted recovery steps down streamed->interpreted charging
the breaker per (family, rung); the shed is the last resort (only when
even one chunk provably cannot fit); the packing scheduler reserves only
the per-chunk footprint and reconciles reservations against measured
bytes on release.
"""
import numpy as np
import pandas as pd
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu import config as config_module
from dask_sql_tpu.resilience import faults
from dask_sql_tpu.serving.admission import EstimatedBytesExceededError
from dask_sql_tpu.serving.cache import table_nbytes

pytestmark = pytest.mark.streaming

N_ROWS = 40_000


@pytest.fixture(autouse=True)
def _fresh_state():
    """Fault budgets, morsel-executable caches and the global config are
    process-wide; every test starts clean and leaves nothing behind."""
    from dask_sql_tpu.streaming import aggregate as stream_agg
    from dask_sql_tpu.streaming import select as stream_sel

    saved = config_module.config.effective_items()
    faults.reset()
    stream_agg.reset_cache()
    stream_sel.reset_cache()
    yield
    config_module.config.update(dict(saved))
    faults.reset()
    stream_agg.reset_cache()
    stream_sel.reset_cache()


def _ctx(n=N_ROWS):
    c = Context()
    c.config.update({"serving.cache.enabled": False})
    rng = np.random.RandomState(7)
    df = pd.DataFrame({
        "k": rng.randint(0, 5, n).astype(np.int64),
        "v": rng.randint(0, 1000, n).astype(np.int64),
        "f": rng.rand(n),
    })
    c.create_table("t", df)
    return c, df


def _budget(c, frac=3):
    """A budget between the one-shot provable floor (the whole resident
    scan) and the per-chunk floor: forces streaming, never shedding."""
    return table_nbytes(c.schema["root"].tables["t"].table) // frac


AGG_Q = ("SELECT k, SUM(v) AS s, COUNT(*) AS n, AVG(v) AS a, "
         "MIN(v) AS mn, MAX(f) AS mx FROM t GROUP BY k ORDER BY k")
SEL_Q = "SELECT k, v * 2 AS v2 FROM t WHERE f > 0.9"


def _stream_counters(c):
    snap = c.metrics.snapshot()["counters"]
    return {k: v for k, v in snap.items()
            if k.startswith(("serving.stream.", "resilience.partition."))}


# -------------------------------------------------- acceptance: streamed run
def test_oversize_aggregate_streams_byte_identical():
    c, _ = _ctx()
    expected = c.sql(AGG_Q, return_futures=False)
    res = c.sql(AGG_Q, return_futures=False, config_options={
        "serving.admission.max_estimated_bytes": _budget(c)})
    # byte-identical to the unconstrained context (int sums/counts/min/max
    # are exact; avg divides exact int states)
    pd.testing.assert_frame_equal(res, expected)
    snap = _stream_counters(c)
    assert snap["serving.stream.admitted"] == 1
    assert snap["serving.stream.partitions"] > 1
    assert snap["serving.stream.rows"] == N_ROWS
    assert c.metrics.counter("resilience.rung.streamed_aggregate") == 1
    # the shed never fired: streaming replaced it
    assert c.metrics.counter("serving.shed_estimated_bytes") == 0


def test_oversize_select_streams_in_global_row_order():
    c, _ = _ctx()
    expected = c.sql(SEL_Q, return_futures=False)
    res = c.sql(SEL_Q, return_futures=False, config_options={
        "serving.admission.max_estimated_bytes": _budget(c)})
    # survivor concatenation preserves global row order — frame-equal
    # without any sort normalization
    pd.testing.assert_frame_equal(res, expected)
    assert c.metrics.counter("serving.stream.partitions") > 1
    assert c.metrics.counter("resilience.rung.streamed_select") == 1


def test_streamed_string_group_keys_match():
    c = Context()
    c.config.update({"serving.cache.enabled": False})
    rng = np.random.RandomState(3)
    df = pd.DataFrame({
        "g": rng.choice(["aa", "bb", "cc", "dd"], N_ROWS),
        "v": rng.randint(0, 100, N_ROWS).astype(np.int64),
    })
    c.create_table("t", df)
    q = "SELECT g, SUM(v) AS s, COUNT(*) AS n FROM t GROUP BY g ORDER BY g"
    expected = c.sql(q, return_futures=False)
    res = c.sql(q, return_futures=False, config_options={
        "serving.admission.max_estimated_bytes": _budget(c)})
    pd.testing.assert_frame_equal(res, expected)
    assert c.metrics.counter("serving.stream.partitions") > 1


# ------------------------------------------- admission x streaming interplay
def test_budget_between_floors_streams_under_it_runs_single_launch():
    c, _ = _ctx()
    # generous budget: no gate trigger, no streaming — the single-launch
    # compiled rung answers
    c.sql(AGG_Q, return_futures=False, config_options={
        "serving.admission.max_estimated_bytes": 1 << 40})
    assert c.metrics.counter("serving.stream.admitted") == 0
    assert c.metrics.counter("serving.stream.partitions") == 0


def test_sheds_only_when_even_one_chunk_cannot_fit():
    c, _ = _ctx()
    # a budget below the floor of even a min_chunk_rows-sized chunk: the
    # last resort fires with the structured taxonomy error
    with pytest.raises(EstimatedBytesExceededError):
        c.sql(AGG_Q, return_futures=False, config_options={
            "serving.admission.max_estimated_bytes": 1 << 10})
    assert c.metrics.counter("serving.shed_estimated_bytes") == 1
    assert c.metrics.counter("serving.stream.admitted") == 0


def test_stream_disabled_restores_plain_shed():
    c, _ = _ctx()
    with pytest.raises(EstimatedBytesExceededError):
        c.sql(AGG_Q, return_futures=False, config_options={
            "serving.admission.max_estimated_bytes": _budget(c),
            "serving.stream.enabled": False})


def test_construction_ineligible_routed_plan_resheds():
    # a shape the static routing walk cannot rule out: PLAIN int group
    # keys whose device span overflows the 1<<22 radix gate.  The rung
    # discovers it at construction — and must RE-SHED with the gate's 429
    # rather than decline down the ladder into a full over-budget
    # single-launch execution (the regression this guards against)
    c = Context()
    c.config.update({"serving.cache.enabled": False,
                     "columnar.encoding": "off"})
    rng = np.random.RandomState(5)
    df = pd.DataFrame({
        "k": rng.choice([0, 1 << 23], N_ROWS).astype(np.int64),
        "v": rng.randint(0, 100, N_ROWS).astype(np.int64),
    })
    c.create_table("t", df)
    with pytest.raises(EstimatedBytesExceededError):
        c.sql("SELECT k, SUM(v) AS s FROM t GROUP BY k",
              return_futures=False, config_options={
                  "serving.admission.max_estimated_bytes": _budget(c)})
    assert c.metrics.counter("serving.shed_estimated_bytes") == 1
    assert c.metrics.counter("serving.stream.partitions") == 0


def test_compile_disabled_sheds_instead_of_routing_past_the_gate():
    # the rungs require sql.compile; routing would bypass the shed and run
    # the full over-budget working set on a lower rung — the decision must
    # mirror the rung preconditions so the 429 contract survives
    c, _ = _ctx()
    with pytest.raises(EstimatedBytesExceededError):
        c.sql(AGG_Q, return_futures=False, config_options={
            "serving.admission.max_estimated_bytes": _budget(c),
            "sql.compile": False})
    with pytest.raises(EstimatedBytesExceededError):
        c.sql(SEL_Q, return_futures=False, config_options={
            "serving.admission.max_estimated_bytes": _budget(c),
            "sql.compile.select": False})
    assert c.metrics.counter("serving.stream.admitted") == 0
    assert c.metrics.counter("serving.shed_estimated_bytes") == 2


def test_streamed_select_repartition_compiles_under_watchdog(monkeypatch):
    # after a mid-stream repartition the NEW chunk shape's mask kernel must
    # run with may_compile=True (per-shape warm tracking), so the compile
    # watchdog covers exactly the OOM-recovery path (regression: the
    # parent's single-boolean warm flag ran every post-first-chunk compile
    # with may_compile=False, outside the watchdog)
    c, _ = _ctx()
    expected = c.sql(SEL_Q, return_futures=False)
    res = c.sql(SEL_Q, return_futures=False, config_options={
        "serving.admission.max_estimated_bytes": _budget(c),
        "resilience.inject": "partition:at2",
        "serving.stream.min_chunk_rows": 512})
    pd.testing.assert_frame_equal(res, expected)
    assert c.metrics.counter("serving.stream.repartitions") == 1
    # white-box: drive the cached streamed executable over fresh chunk
    # shapes and record the hint each mask launch carries
    from dask_sql_tpu.streaming.select import _cache
    import dask_sql_tpu.observability as obs

    obj = next(iter(_cache.values()))
    real = obs.timed_jit_call
    hints = []

    def spy(rung, fn, *args, may_compile=None, **kwargs):
        hints.append(may_compile)
        return real(rung, fn, *args, may_compile=may_compile, **kwargs)

    monkeypatch.setattr(obs, "timed_jit_call", spy)
    from dask_sql_tpu.streaming.partition import slice_chunk

    table = c.schema["root"].tables["t"].table
    # SEL_Q's parameterized literals in rewrite order: the scan filter's
    # 0.9, then the projection's *2 multiplier
    params = (np.float64(0.9), np.int64(2))
    first = []
    for rows in (640, 320, 640):
        hints.clear()
        obj.run(slice_chunk(table, 0, rows), params)
        first.append(hints[0])  # the mask launch's hint
    # new shape -> watched; another new shape (the repartition case) ->
    # watched again; a repeated shape -> known-warm
    assert first == [True, True, False]


def test_stream_verdict_is_per_execution_not_plan_state():
    c, _ = _ctx()
    budget = _budget(c)
    c.sql(AGG_Q, return_futures=False, config_options={
        "serving.admission.max_estimated_bytes": budget})
    assert c.metrics.counter("serving.stream.partitions") > 1
    parts = c.metrics.counter("serving.stream.partitions")
    # same SQL under no budget: the verdict lived on the previous
    # execution's executor, not the cached plan, so this run serves
    # single-launch — and no plan node carries routing marks at all
    c.sql(AGG_Q, return_futures=False)
    assert c.metrics.counter("serving.stream.partitions") == parts
    from dask_sql_tpu.planner.parser import parse_sql

    plan = c._get_ral(parse_sql(AGG_Q)[0], sql_text=AGG_Q)
    from dask_sql_tpu.planner import plan as p

    assert all(getattr(n, "_dsql_stream", None) is None
               for n in p.walk_plan(plan))


def test_second_streamed_family_run_zero_foreground_compiles():
    c, _ = _ctx()
    budget = _budget(c)
    opts = {"serving.admission.max_estimated_bytes": budget}
    q1 = "SELECT k, SUM(v) AS s FROM t WHERE v > 10 GROUP BY k ORDER BY k"
    q2 = "SELECT k, SUM(v) AS s FROM t WHERE v > 500 GROUP BY k ORDER BY k"
    c.sql(q1, return_futures=False, config_options=opts)
    t1 = c.last_trace
    c.sql(q2, return_futures=False, config_options=opts)
    t2 = c.last_trace
    assert t2 is not t1
    compiles1 = [s.name for s in t1.spans if s.name.startswith("compile:")]
    compiles2 = [s.name for s in t2.spans if s.name.startswith("compile:")]
    # first run pays the morsel compile ONCE (not once per partition) ...
    assert compiles1.count("compile:streamed_aggregate") == 1
    assert c.metrics.counter("serving.stream.partitions") > 2
    # ... the second literal variant of the family pays ZERO
    assert compiles2 == []
    # and both runs match the unconstrained answers
    pd.testing.assert_frame_equal(
        c.sql(q2, return_futures=False, config_options=opts),
        c.sql(q2, return_futures=False))


# -------------------------------------------------- mid-stream OOM recovery
def test_midstream_oom_repartitions_and_resumes():
    c, _ = _ctx()
    expected = c.sql(AGG_Q, return_futures=False)
    res = c.sql(AGG_Q, return_futures=False, config_options={
        "serving.admission.max_estimated_bytes": _budget(c),
        "resilience.inject": "partition:at2",
        "serving.stream.min_chunk_rows": 512})
    pd.testing.assert_frame_equal(res, expected)
    snap = _stream_counters(c)
    assert snap["resilience.partition.oom"] == 1
    assert snap["serving.stream.repartitions"] == 1
    # resume, not restart: every logical row was processed EXACTLY once
    # (the completed first partition was never re-executed — a restart
    # would double-count it, corrupting the sums above too)
    assert snap["serving.stream.rows"] == N_ROWS
    assert c.metrics.counter("resilience.degraded") == 0


def test_recovery_exhaustion_steps_down_and_charges_breaker():
    c, _ = _ctx()
    expected = c.sql(AGG_Q, return_futures=False)
    opts = {"serving.admission.max_estimated_bytes": _budget(c),
            "resilience.inject": "partition:always",
            "serving.stream.min_chunk_rows": 4096}
    # streamed -> repartition (until the chunk floor) -> interpreted:
    # the query STILL answers correctly on the lower rung
    res = c.sql(AGG_Q, return_futures=False, config_options=opts)
    pd.testing.assert_frame_equal(res, expected)
    snap = _stream_counters(c)
    assert snap["resilience.partition.exhausted"] >= 1
    assert c.metrics.counter("resilience.degraded.streamed_aggregate") == 1
    # breaker charged per (family, rung): repeated failures trip it and
    # the NEXT submission skips the streamed rung outright
    c.sql(AGG_Q, return_futures=False, config_options=opts)
    c.sql(AGG_Q, return_futures=False, config_options=opts)
    assert c.metrics.counter("resilience.breaker.trip") >= 1
    c.sql(AGG_Q, return_futures=False, config_options=opts)
    assert c.metrics.counter("resilience.breaker.skip.streamed_aggregate") \
        >= 1


def test_at_k_fault_mode_fires_exactly_kth_arm():
    inj = faults.FaultInjector("partition:at3")
    assert not inj.arm("partition")
    assert not inj.arm("partition")
    assert inj.arm("partition")
    assert not inj.arm("partition")
    assert inj.fired("partition") == 1


def test_deadline_checkpoint_between_partitions():
    from dask_sql_tpu.serving.admission import (
        DeadlineExceededError,
        QueryTicket,
    )
    from dask_sql_tpu.serving import runtime as rt

    c, _ = _ctx()
    ticket = QueryTicket("q-stream", deadline=-1.0)  # already expired
    rt._tls.ticket = ticket
    try:
        with pytest.raises(DeadlineExceededError):
            c.sql(AGG_Q, return_futures=False, config_options={
                "serving.admission.max_estimated_bytes": _budget(c)})
    finally:
        rt._tls.ticket = None


# ----------------------------------------------------- scheduler integration
def test_scheduler_reserves_per_chunk_floor_for_streamed_cost():
    from dask_sql_tpu.serving import MetricsRegistry, PackingScheduler
    from dask_sql_tpu.serving.admission import QueryTicket
    from dask_sql_tpu.serving.scheduler import QueryCost

    m = MetricsRegistry()
    s = PackingScheduler(budget_bytes=1000, metrics=m)
    big = QueryTicket("big", "batch")
    s.push_locked(big, lambda: None, None,
                  QueryCost(bytes_lo=10_000, chunk_bytes_lo=600))
    assert s.pop_locked(batch_ok=True) is not None
    # the reservation is the CHUNK floor, not the whole-table floor ...
    assert s.reserved_bytes == 600
    # ... so an interactive query whose floor fits the remainder packs in
    small = QueryTicket("small")
    s.push_locked(small, lambda: None, None, QueryCost(bytes_lo=300))
    assert s.pop_locked(batch_ok=True) is not None
    assert m.counter("serving.scheduler.packed") == 1


def test_release_reconciles_measured_bytes_as_drift():
    from dask_sql_tpu.serving import MetricsRegistry, PackingScheduler
    from dask_sql_tpu.serving.admission import QueryTicket
    from dask_sql_tpu.serving.scheduler import QueryCost

    m = MetricsRegistry()
    s = PackingScheduler(budget_bytes=1000, metrics=m)
    t = QueryTicket("q")
    s.push_locked(t, lambda: None, None, QueryCost(bytes_lo=400))
    assert s.pop_locked(batch_ok=True) is not None
    s.push_locked(QueryTicket("q2"), lambda: None, None,
                  QueryCost(bytes_lo=100))
    assert s.pop_locked(batch_ok=True) is not None
    s.release_locked(t, measured_bytes=640)
    snap = m.snapshot()["histograms"]
    assert snap["serving.scheduler.reserve_drift"]["count"] == 1
    assert snap["serving.scheduler.reserve_drift"]["max"] == 240.0
    assert s.reserved_bytes == 100


def test_ticket_measured_bytes_recorded_through_runtime():
    from dask_sql_tpu.serving import ServingRuntime

    c, _ = _ctx(n=8192)
    rt = ServingRuntime(workers=1, metrics=c.metrics,
                        scheduler_budget_bytes=1 << 30)
    try:
        from dask_sql_tpu.serving.scheduler import QueryCost

        _, fut, ticket = rt.submit(
            lambda t: c.sql("SELECT k, SUM(v) AS s FROM t GROUP BY k",
                            return_futures=False),
            cost=QueryCost(bytes_lo=1024))
        fut.result(60)
        # the executing thread measured its footprint onto the ticket and
        # release reconciled it into the drift histogram
        assert ticket.measured_bytes is not None \
            and ticket.measured_bytes > 0
        hist = c.metrics.snapshot()["histograms"]
        assert hist["serving.scheduler.reserve_drift"]["count"] == 1
    finally:
        rt.shutdown(wait=True)


def test_cost_hint_carries_per_chunk_floor_for_streamed_family():
    c, _ = _ctx()
    budget = _budget(c)
    opts = {"serving.admission.max_estimated_bytes": budget}
    # first execution populates the plan cache and attaches the routing
    # verdict; the submit-time peek must find BOTH (regression: the peek
    # used to compute its key outside the config overlay scope, so any
    # option-carrying submit missed the cache it populated)
    c.sql(AGG_Q, return_futures=False, config_options=opts)
    cost = c.cost_hint(AGG_Q, opts)
    assert cost is not None
    assert cost.chunk_bytes_lo is not None
    assert 0 < cost.chunk_bytes_lo < cost.bytes_lo
    assert cost.chunk_bytes_lo <= budget
    assert cost.reserve_bytes() == cost.chunk_bytes_lo
    # an unconstrained run of the same text reserves the full floor
    c.sql(AGG_Q, return_futures=False)
    plain = c.cost_hint(AGG_Q)
    assert plain is not None and plain.chunk_bytes_lo is None
    assert plain.reserve_bytes() == plain.bytes_lo


# ------------------------------------------------------------- decision unit
def test_stream_decision_sizing_and_eligibility():
    from dask_sql_tpu.planner.parser import parse_sql
    from dask_sql_tpu.streaming import stream_decision

    c, _ = _ctx()
    plan = c._get_ral(parse_sql(AGG_Q)[0], sql_text=AGG_Q)
    est = plan._dsql_estimate
    budget = _budget(c)
    routed = stream_decision(plan, est, c, c.config, budget)
    assert routed is not None
    node, d = routed
    from dask_sql_tpu.planner import plan as p

    # the verdict names the node the sizing was computed for
    assert isinstance(node, p.Aggregate)
    assert d.kind == "aggregate"
    assert d.partitions > 1
    assert d.chunk_bytes_lo <= budget
    assert d.chunk_rows * d.partitions >= d.total_rows
    # per-chunk floor below the whole-scan floor: that is the point
    assert d.chunk_bytes_lo < est.peak_bytes.lo
    # too many partitions -> decline (the shed stays the last resort)
    with c.config.set({"serving.stream.max_partitions": 1}):
        assert stream_decision(plan, est, c, c.config, budget) is None
    # joins (two scans) are not streamable
    c.create_table("u", pd.DataFrame({"k": np.arange(5, dtype=np.int64)}))
    jq = "SELECT t.k, SUM(t.v) AS s FROM t, u WHERE t.k = u.k GROUP BY t.k"
    jplan = c._get_ral(parse_sql(jq)[0], sql_text=jq)
    jest = jplan._dsql_estimate
    assert stream_decision(jplan, jest, c, c.config, budget) is None


def test_chunk_slicing_overlap_masking():
    from dask_sql_tpu.streaming.partition import (
        partition_layout,
        slice_chunk,
    )

    c, df = _ctx(n=1000)
    table = c.schema["root"].tables["t"].table
    layout = partition_layout(1000, 384)
    assert layout == [(0, 384), (384, 768), (768, 1000)]
    covered = np.zeros(1000, dtype=int)
    for lo, _hi in layout:
        chunk = slice_chunk(table, lo, 384)
        assert chunk.padded_rows == 384  # one shape for every chunk
        valid = np.asarray(chunk.row_valid)
        # the masked window covers exactly [lo, hi) of the logical rows
        start = min(lo, 1000 - 384)
        covered[start:start + 384] += valid.astype(int)
    assert (covered == 1).all()  # every row exactly once, no overlap
