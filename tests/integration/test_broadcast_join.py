"""Distributed broadcast join + fused sharded join->aggregate.

VERDICT r3 #4/#5: the joined rows of a Q5-shaped query must NOT materialize
(host or device) between merge and groupby — the fused pipeline keeps the
probe row-sharded and probes replicated small-side LUTs per shard; and a
plain join under `sql.join.broadcast` must take the broadcast path (STATS
counter) instead of shuffling the big side.  Bar: the reference's
small-side broadcast merge (reference join.py:228-246)."""
import numpy as np
import pandas as pd
import pytest

import jax


pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs the virtual multi-device mesh")


@pytest.fixture()
def q5_ctx():
    from dask_sql_tpu import Context

    rng = np.random.RandomState(5)
    n = 40_000
    nation = pd.DataFrame({"n_key": np.arange(8), "n_name": [f"N{i}" for i in range(8)]})
    customer = pd.DataFrame({
        "c_key": np.arange(400), "c_nkey": rng.randint(0, 8, 400)})
    orders = pd.DataFrame({
        "o_key": np.arange(2000), "o_ckey": rng.randint(0, 400, 2000)})
    lineitem = pd.DataFrame({
        "l_okey": rng.randint(0, 2000, n),
        "l_price": rng.rand(n) * 1e4,
        "l_disc": rng.rand(n) * 0.1,
    })
    c = Context()
    c.create_table("nation", nation)
    c.create_table("customer", customer)
    c.create_table("orders", orders)
    c.create_table("lineitem", lineitem, distributed=True)
    frames = dict(nation=nation, customer=customer, orders=orders,
                  lineitem=lineitem)
    return c, frames


def test_q5_shape_fused_no_materialization(q5_ctx):
    c, t = q5_ctx
    from dask_sql_tpu.parallel.dist_plan import STATS
    import dask_sql_tpu.physical.rel.logical.join as J

    materialized = []
    orig = J._materialize

    def spy(left, right, li, ri):
        materialized.append((left.num_rows, right.num_rows))
        return orig(left, right, li, ri)

    fused_before = STATS["sharded_join_agg"]
    J._materialize = spy
    try:
        got = c.sql(
            "SELECT n_name, SUM(l_price * (1 - l_disc)) AS revenue, "
            "COUNT(*) AS n FROM lineitem, orders, customer, nation "
            "WHERE l_okey = o_key AND o_ckey = c_key AND c_nkey = n_key "
            "GROUP BY n_name ORDER BY n_name",
            return_futures=False)
    finally:
        J._materialize = orig
    assert STATS["sharded_join_agg"] > fused_before, (
        "Q5 shape must run the fused sharded pipeline")
    assert materialized == [], (
        f"join output materialized (peak rows {materialized}) — the fused "
        "path must keep rows sharded with no merge->groupby gather")

    li, o, cu, na = t["lineitem"], t["orders"], t["customer"], t["nation"]
    m = (li.merge(o, left_on="l_okey", right_on="o_key")
         .merge(cu, left_on="o_ckey", right_on="c_key")
         .merge(na, left_on="c_nkey", right_on="n_key"))
    exp = (m.assign(rev=m.l_price * (1 - m.l_disc))
           .groupby("n_name", as_index=False)
           .agg(revenue=("rev", "sum"), n=("rev", "size"))
           .sort_values("n_name").reset_index(drop=True))
    assert list(got["n_name"]) == list(exp["n_name"])
    np.testing.assert_allclose(got["revenue"], exp["revenue"], rtol=1e-9)
    assert list(got["n"].astype(np.int64)) == list(exp["n"])


def test_plain_join_broadcast_path(q5_ctx):
    c, t = q5_ctx
    from dask_sql_tpu.parallel.dist_plan import STATS

    bc, jk = STATS["broadcast_join"], STATS["join_kernel"]
    got = c.sql("SELECT l_okey, o_ckey FROM lineitem "
                "JOIN orders ON l_okey = o_key", return_futures=False)
    assert STATS["broadcast_join"] > bc, "broadcast path not taken"
    assert STATS["join_kernel"] == jk, "big side was shuffled"
    exp = t["lineitem"].merge(t["orders"], left_on="l_okey", right_on="o_key")
    assert len(got) == len(exp)
    assert int(got["o_ckey"].sum()) == int(exp["o_ckey"].sum())


def test_broadcast_left_join_values(q5_ctx):
    c, t = q5_ctx
    # drop half the orders so some lineitems lose their match
    small = t["orders"].iloc[:1000]
    c.create_table("orders_half", small)
    got = c.sql("SELECT l_okey, o_ckey FROM lineitem "
                "LEFT JOIN orders_half ON l_okey = o_key",
                return_futures=False)
    exp = t["lineitem"].merge(small, how="left", left_on="l_okey",
                              right_on="o_key")
    assert len(got) == len(exp)
    assert got["o_ckey"].isna().sum() == exp["o_ckey"].isna().sum()


def test_broadcast_disabled_uses_shuffle(q5_ctx):
    c, t = q5_ctx
    from dask_sql_tpu.parallel.dist_plan import STATS

    jk = STATS["join_kernel"]
    got = c.sql(
        "SELECT l_okey, o_ckey FROM lineitem JOIN orders ON l_okey = o_key",
        config_options={"sql.join.broadcast": False}, return_futures=False)
    assert STATS["join_kernel"] > jk, "shuffle engine must run"
    exp = t["lineitem"].merge(t["orders"], left_on="l_okey", right_on="o_key")
    assert len(got) == len(exp)


def test_broadcast_string_key_dim(q5_ctx):
    """A string-keyed dim table must broadcast (sorted probe), not shuffle —
    the reference broadcasts ANY small table (join.py:228-246 there)."""
    c, t = q5_ctx
    from dask_sql_tpu.parallel.dist_plan import STATS

    rng = np.random.RandomState(11)
    n = 40_000
    big = pd.DataFrame({
        "cat": rng.choice(["alpha", "beta", "gamma", "delta"], n),
        "x": rng.rand(n),
    })
    dim = pd.DataFrame({"cat_key": ["alpha", "beta", "gamma", "omega"],
                        "weight": [1.0, 2.0, 3.0, 4.0]})
    c.create_table("sbig", big, distributed=True)
    c.create_table("sdim", dim)
    bc, jk = STATS["broadcast_join"], STATS["join_kernel"]
    got = c.sql("SELECT cat, weight FROM sbig JOIN sdim ON cat = cat_key",
                return_futures=False,
                config_options={"sql.join.broadcast": True})
    # merged dictionary codes are dense ints, so a unique-key string dim may
    # legitimately ride the LUT fast path — what matters is broadcast+no shuffle
    assert STATS["broadcast_join"] > bc, (
        "string-key dim must take a broadcast probe")
    assert STATS["join_kernel"] == jk, "big side was shuffled"
    exp = big.merge(dim, left_on="cat", right_on="cat_key")
    assert len(got) == len(exp)
    np.testing.assert_allclose(
        got.groupby("cat")["weight"].sum().sort_index(),
        exp.groupby("cat")["weight"].sum().sort_index())


def test_broadcast_duplicate_build_keys(q5_ctx):
    """Non-unique build keys multiply matching rows; the broadcast path must
    expand duplicates exactly like the shuffle engine."""
    c, t = q5_ctx
    from dask_sql_tpu.parallel.dist_plan import STATS

    rng = np.random.RandomState(13)
    n = 20_000
    big = pd.DataFrame({"k": rng.randint(0, 50, n), "x": rng.rand(n)})
    # every key appears 0-3 times on the build side, some keys missing
    dim = pd.DataFrame({"dk": np.repeat(np.arange(40), rng.randint(0, 4, 40)),
                        })
    dim["w"] = np.arange(len(dim), dtype=np.float64)
    c.create_table("dbig", big, distributed=True)
    c.create_table("ddim", dim)
    bs, jk = STATS["broadcast_join_sorted"], STATS["join_kernel"]
    got = c.sql("SELECT k, w FROM dbig JOIN ddim ON k = dk",
                return_futures=False,
                config_options={"sql.join.broadcast": True})
    assert STATS["broadcast_join_sorted"] > bs
    assert STATS["join_kernel"] == jk, "big side was shuffled"
    exp = big.merge(dim, left_on="k", right_on="dk")
    assert len(got) == len(exp)
    np.testing.assert_allclose(got["w"].sum(), exp["w"].sum())


def test_broadcast_null_keys_general_path(q5_ctx):
    """NULL build keys never match; NULL probe keys never match."""
    c, t = q5_ctx
    big = pd.DataFrame({"k": [1.0, 2.0, None, 3.0] * 5000, "x": 1.0})
    dim = pd.DataFrame({"dk": [1.0, 1.0, None], "w": [10.0, 20.0, 99.0]})
    c.create_table("nbig", big, distributed=True)
    c.create_table("ndim", dim)
    got = c.sql("SELECT k, w FROM nbig JOIN ndim ON k = dk",
                return_futures=False,
                config_options={"sql.join.broadcast": True})
    # SQL: NULL keys never match (pandas merge would match NaN == NaN)
    exp = big.dropna(subset=["k"]).merge(dim.dropna(subset=["dk"]),
                                         left_on="k", right_on="dk")
    assert len(got) == len(exp)
    np.testing.assert_allclose(got["w"].sum(), exp["w"].sum())


def test_broadcast_semi_anti_general_path(q5_ctx):
    c, t = q5_ctx
    big = pd.DataFrame({"k": np.arange(10_000) % 7})
    dim = pd.DataFrame({"dk": [1, 1, 3]})
    c.create_table("abig", big, distributed=True)
    c.create_table("adim", dim)
    got_in = c.sql("SELECT COUNT(*) AS n FROM abig WHERE k IN (SELECT dk FROM adim)",
                   return_futures=False,
                   config_options={"sql.join.broadcast": True})
    got_out = c.sql("SELECT COUNT(*) AS n FROM abig WHERE k NOT IN (SELECT dk FROM adim)",
                    return_futures=False,
                    config_options={"sql.join.broadcast": True})
    exp_in = int((big.k.isin([1, 3])).sum())
    assert int(got_in["n"][0]) == exp_in
    assert int(got_out["n"][0]) == len(big) - exp_in


def test_sorted_probe_int64_max_key_not_null():
    """A valid build key equal to int64.max must not be confused with the
    NULL suffix (valid-first lexsort, no sentinel collision)."""
    import jax.numpy as jnp
    from dask_sql_tpu.parallel.dist_plan import _broadcast_sorted_pairs

    MAX = np.iinfo(np.int64).max
    small = jnp.asarray(np.array([7, MAX, MAX, 3], dtype=np.int64))
    svalid = jnp.asarray(np.array([False, True, True, True]))  # row0 is NULL
    big = jnp.asarray(np.array([MAX, 7, 3, 5], dtype=np.int64))
    bvalid = jnp.asarray(np.array([True, True, True, True]))
    bi, si, matched = _broadcast_sorted_pairs(big, bvalid, small, svalid)
    pairs = sorted(zip(np.asarray(bi).tolist(), np.asarray(si).tolist()))
    # probe MAX matches build rows 1,2 (not the NULL row 0 whose key is 7);
    # probe 7 matches nothing (row0 invalid); probe 3 matches row 3
    assert pairs == [(0, 1), (0, 2), (2, 3)]
    assert matched.tolist() == [True, False, True, False]


def test_sorted_probe_empty_build_counts_stats():
    from dask_sql_tpu.parallel.dist_plan import STATS, _broadcast_sorted_pairs
    import jax.numpy as jnp

    before = STATS["broadcast_join_sorted"]
    bi, si, matched = _broadcast_sorted_pairs(
        jnp.asarray(np.array([1, 2], dtype=np.int64)),
        jnp.asarray(np.array([True, True])),
        jnp.zeros(0, dtype=jnp.int64), jnp.zeros(0, dtype=bool))
    assert STATS["broadcast_join_sorted"] == before + 1
    assert len(bi) == 0 and not matched.any()


def test_mark_join_distributed(q5_ctx):
    """EXISTS-under-OR on a sharded table: the mark join rides the same
    collectives probe as semi joins (no local resort of global arrays)."""
    c, t = q5_ctx
    got = c.sql(
        "SELECT COUNT(*) AS n FROM lineitem l WHERE "
        "(EXISTS (SELECT 1 FROM orders o WHERE o.o_key = l.l_okey "
        "         AND o.o_ckey < 100) OR l.l_price > 9000)",
        return_futures=False)
    li, o = t["lineitem"], t["orders"]
    ok = set(o[o.o_ckey < 100].o_key)
    exp = int((li.l_okey.isin(ok) | (li.l_price > 9000)).sum())
    assert int(got["n"][0]) == exp
