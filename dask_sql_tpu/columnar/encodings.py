"""First-class column encodings: compressed-domain storage for the TPU backend.

ROADMAP item 2 ("GPU Acceleration of SQL Analytics on Compressed Data",
arXiv:2506.10092, applied to the tensor-runtime operator style of TQP,
arXiv:2203.01877).  Strings have always been dictionary-encoded here
(`Column.dictionary`); this module extends the idea to every other column
family so scans move encoded bytes and decode happens late:

- ``DICT``   — low-cardinality numerics/datetimes: an int16/int32 code array
  in HBM plus a host-side SORTED array of unique values (``enc_values``).
  Sortedness is the operational trick: comparisons and IN-lists translate
  MONOTONICALLY into code space (``x < lit  <=>  code < searchsorted(values,
  lit)``), so the compiled predicates never materialize the values, and
  group-by radix domains come straight from ``len(enc_values)`` with no
  device min/max pull.
- ``FOR``    — frame-of-reference + implicit bit-pack for narrow-range ints
  (and epoch-ns datetimes, whose day-granularity gcd divides out):
  ``value = code * enc_scale + enc_ref`` with codes stored in the narrowest
  int dtype that fits.  Decode is one fused multiply-add inside the kernel;
  HBM traffic is the code width.
- ``RLE``    — run-length for sorted/clustered columns: ``data`` holds the
  run values, ``enc_lengths`` the int32 run lengths, ``enc_rows`` the
  logical row count; ``validity`` is per-RUN.  A storage-at-rest encoding:
  row-positional consumers (take/filter/slice, the compiled pipelines)
  decode first.
- ``PLAIN``  — the dense device buffer, unchanged.

Selection happens once at LOAD time (``input_utils`` registration, arrow
ingest, checkpoint restore) from the host array, so the decoded buffer is
never uploaded at all.  Late materialization: the compiled select path
decodes only the surviving rows inside the per-bucket gather kernel, and
host transfer (``Table.to_pandas`` / packed d2h) pulls the narrow codes and
decodes on the host.
"""
from __future__ import annotations

import contextlib
import contextvars
import enum
from typing import Optional, Tuple

import numpy as np

from .dtypes import SqlType, sql_to_np, STRING_TYPES


class Encoding(enum.Enum):
    """Physical encoding of a Column's device buffer."""

    PLAIN = "PLAIN"
    DICT = "DICT"
    RLE = "RLE"
    FOR = "FOR"

    def __str__(self) -> str:  # pragma: no cover - repr sugar
        return self.value


#: active while InputUtil registers a table (the one boundary where
#: auto-selection applies); Column.from_numpy consults it so intermediate
#: host->device conversions (UDF results, ML frames) stay PLAIN
_load_scope: contextvars.ContextVar = contextvars.ContextVar(
    "dsql_encoding_load_scope", default=False)


@contextlib.contextmanager
def load_scope():
    token = _load_scope.set(True)
    try:
        yield
    finally:
        _load_scope.reset(token)


def in_load_scope() -> bool:
    return bool(_load_scope.get())


def auto_enabled() -> bool:
    """True when load-time auto-selection is configured on."""
    from .. import config

    return str(config.get("columnar.encoding", "auto")).lower() == "auto"


def should_auto_encode() -> bool:
    return in_load_scope() and auto_enabled()


# ---------------------------------------------------------------------------
# selection heuristics (host-side, over the device-representation array)
# ---------------------------------------------------------------------------
#: dtypes eligible per encoding; bool/strings never encode (bool is already
#: 1 byte; strings carry their own dictionary mechanism)
_INT16_MAX_CODES = 1 << 15


def _code_dtype(n_codes: int) -> Optional[np.dtype]:
    """Narrowest signed int dtype holding codes ``[0, n_codes)`` with one
    spare slot (radix NULL code headroom)."""
    if n_codes < _INT16_MAX_CODES:
        return np.dtype(np.int16)
    if n_codes < (1 << 31) - 1:
        return np.dtype(np.int32)
    return None


def maybe_encode(values: np.ndarray, valid: Optional[np.ndarray],
                 sql_type: SqlType, force: bool = False):
    """Pick and build an encoded Column from a HOST array in its device
    representation (ints/floats; datetimes already epoch-ns int64), or
    return None (caller constructs PLAIN).  ``valid`` is a host bool mask
    (True = valid) or None.  ``force=True`` bypasses the load-scope/config
    gate (tests), not the heuristics."""
    from .. import config
    from .column import Column, _dev_mask
    import jax.numpy as jnp

    if not force and not should_auto_encode():
        return None
    if sql_type in STRING_TYPES or sql_type in (SqlType.BOOLEAN, SqlType.NULL,
                                                SqlType.ANY):
        return None
    values = np.asarray(values)
    if values.ndim != 1 or values.dtype.kind not in "if":
        return None
    n = values.shape[0]
    if n < int(config.get("columnar.encoding.min_rows", 1024)):
        return None
    valid_vals = values if valid is None else values[np.asarray(valid, bool)]
    if valid_vals.shape[0] == 0:
        return None
    if values.dtype.kind == "f" and np.isnan(valid_vals).any():
        return None  # NaN-bearing valid values: leave dense
    plain_width = values.dtype.itemsize
    plain_bytes = n * plain_width

    candidates = []  # (bytes, preference_rank, builder)

    # DICT: sorted uniques of the VALID values (invalid rows code to 0)
    if config.get("columnar.encoding.dict", True):
        uniques = np.unique(valid_vals)
        cd = _code_dtype(len(uniques))
        if cd is not None and len(uniques) <= int(
                config.get("columnar.encoding.dict_max_card", 1 << 15)) \
                and len(uniques) <= max(n // 4, 1):
            u = uniques

            def build_dict(u=u, cd=cd):
                filled = values if valid is None else \
                    np.where(np.asarray(valid, bool), values, u[0])
                codes = np.searchsorted(u, filled).astype(cd)
                return Column(jnp.asarray(codes), sql_type, _dev_mask(valid),
                              None, encoding=Encoding.DICT,
                              enc_values=u.astype(sql_to_np(sql_type)))

            candidates.append((n * cd.itemsize, 0, build_dict))

    # FOR: affine frame-of-reference for integer representations
    if config.get("columnar.encoding.for", True) and values.dtype.kind == "i":
        lo = int(valid_vals.min())
        hi = int(valid_vals.max())
        offs = valid_vals.astype(np.int64) - lo
        scale = int(np.gcd.reduce(offs)) if offs.shape[0] else 1
        scale = max(scale, 1)
        span_codes = (hi - lo) // scale
        cd = _code_dtype(span_codes + 1)
        if cd is not None and cd.itemsize < plain_width:

            def build_for(lo=lo, scale=scale, cd=cd):
                filled = values if valid is None else \
                    np.where(np.asarray(valid, bool), values, lo)
                codes = ((filled.astype(np.int64) - lo) // scale).astype(cd)
                return Column(jnp.asarray(codes), sql_type, _dev_mask(valid),
                              None, encoding=Encoding.FOR, enc_ref=lo,
                              enc_scale=scale)

            candidates.append((n * cd.itemsize, 1, build_for))

    # RLE: only when extreme (runs must pay for the lengths array AND the
    # decode-before-positional-use policy)
    if config.get("columnar.encoding.rle", True):
        v = np.asarray(valid, bool) if valid is not None else None
        change = values[1:] != values[:-1]
        if v is not None:
            change = change | (v[1:] != v[:-1])
        n_runs = 1 + int(change.sum())
        rle_bytes = n_runs * (plain_width + 4)
        if rle_bytes * 8 <= plain_bytes:

            def build_rle(change=change, n_runs=n_runs, v=v):
                starts = np.concatenate(
                    [[0], np.flatnonzero(change) + 1]).astype(np.int64)
                lengths = np.diff(np.concatenate(
                    [starts, [n]])).astype(np.int32)
                run_vals = values[starts]
                run_valid = None if v is None else v[starts]
                if run_valid is not None and bool(run_valid.all()):
                    run_valid = None
                return Column(
                    jnp.asarray(run_vals), sql_type,
                    None if run_valid is None else jnp.asarray(run_valid),
                    None, encoding=Encoding.RLE,
                    enc_lengths=jnp.asarray(lengths), enc_rows=n)

            candidates.append((rle_bytes, -1, build_rle))

    # require a real saving (>= 25%) so borderline columns stay PLAIN
    candidates = [c for c in candidates if c[0] * 4 <= plain_bytes * 3]
    if not candidates:
        return None
    candidates.sort(key=lambda c: (c[0], c[1]))
    return candidates[0][2]()


# ---------------------------------------------------------------------------
# decode
# ---------------------------------------------------------------------------
def decode_host_buffers(col, data: np.ndarray, aligned=None):
    """THE host-side decode rule, single-sourced for ``Column.decode_host``
    (d2h late materialization) and ``decode_column`` (host-resident
    columns): DICT maps codes through the value array, FOR applies the
    affine, RLE expands runs — expanding ``aligned`` (a per-run validity
    mask or its inverse) alongside.  PLAIN passes through.  Returns
    ``(values, aligned)``."""
    if col.encoding is Encoding.DICT:
        data = col.enc_values[np.clip(data, 0, len(col.enc_values) - 1)]
    elif col.encoding is Encoding.FOR:
        data = data.astype(sql_to_np(col.sql_type))
        if col.enc_scale != 1:
            data = data * col.enc_scale
        if col.enc_ref:
            data = data + col.enc_ref
    elif col.encoding is Encoding.RLE:
        lengths = np.asarray(col.enc_lengths)
        data = np.repeat(np.asarray(data), lengths)
        if aligned is not None:
            aligned = np.repeat(np.asarray(aligned), lengths)
    return data, aligned


def decode_column(col):
    """Materialize an encoded Column as PLAIN (device ops for device
    buffers, numpy via `decode_host_buffers` for host-resident ones).
    Identity for PLAIN columns."""
    import jax.numpy as jnp
    from dataclasses import replace

    if col.encoding is Encoding.PLAIN:
        return col
    plain = dict(encoding=Encoding.PLAIN, enc_values=None, enc_ref=0,
                 enc_scale=1, enc_lengths=None, enc_rows=None)
    if isinstance(col.data, np.ndarray):
        data, validity = decode_host_buffers(col, col.data, col.validity)
        return replace(col, data=data, validity=validity, **plain)
    target = sql_to_np(col.sql_type)
    if col.encoding is Encoding.DICT:
        lut = jnp.asarray(col.enc_values)
        data = lut[jnp.clip(col.data, 0, len(col.enc_values) - 1)]
        return replace(col, data=data, **plain)
    if col.encoding is Encoding.FOR:
        data = col.data.astype(target)
        if col.enc_scale != 1:
            data = data * col.enc_scale
        if col.enc_ref:
            data = data + jnp.asarray(col.enc_ref, dtype=target)
        return replace(col, data=data, **plain)
    # RLE: expand runs back to rows (static total length keeps this jit-safe)
    n = col.enc_rows
    data = jnp.repeat(col.data, col.enc_lengths, total_repeat_length=n)
    validity = None if col.validity is None else \
        jnp.repeat(col.validity, col.enc_lengths, total_repeat_length=n)
    return replace(col, data=data, validity=validity, **plain)


# ---------------------------------------------------------------------------
# byte accounting (metrics / estimator / bench)
# ---------------------------------------------------------------------------
def encoded_nbytes(col) -> int:
    """Resident bytes of a column AS STORED: data buffer + validity mask +
    RLE lengths; host-side dictionaries (strings and DICT values) included
    since they are part of the working set the estimator answers for.
    THE single byte-accounting rule — serving/cache.table_nbytes and the
    estimator's scan bounds both delegate here so they can never drift.
    getattr-defensive so duck-typed column stand-ins keep working."""
    total = int(getattr(getattr(col, "data", None), "nbytes", 0) or 0)
    validity = getattr(col, "validity", None)
    if validity is not None:
        total += int(getattr(validity, "nbytes", 0) or 0)
    enc_lengths = getattr(col, "enc_lengths", None)
    if enc_lengths is not None:
        total += int(getattr(enc_lengths, "nbytes", 0) or 0)
    enc_values = getattr(col, "enc_values", None)
    if enc_values is not None:
        total += int(enc_values.nbytes)
    dictionary = getattr(col, "dictionary", None)
    if dictionary is not None:
        # host object array of uniques: nbytes only counts pointers
        total += sum(len(str(v)) for v in dictionary) + dictionary.nbytes
    return total


def decoded_nbytes(col) -> int:
    """Bytes the same column would occupy fully decoded (dense device
    representation + its validity mask).  String columns are int32 codes in
    BOTH worlds — their dictionary is the native representation."""
    n = len(col)
    total = n * sql_to_np(col.sql_type).itemsize
    if col.validity is not None:
        total += n  # bool mask, expanded for RLE
    if col.dictionary is not None:
        total += sum(len(str(v)) for v in col.dictionary) \
            + col.dictionary.nbytes
    return total


def scan_bytes(table, names=None) -> Tuple[int, int]:
    """(encoded, decoded) resident bytes of the named columns of a table."""
    names = list(names) if names is not None else list(table.column_names)
    enc = sum(encoded_nbytes(table.columns[n]) for n in names)
    dec = sum(decoded_nbytes(table.columns[n]) for n in names)
    return enc, dec


def resolve_encoded_scan(context, node):
    """``(table, projected names)`` for a TableScan over a REGISTERED table
    whose projected columns include at least one encoded column; None when
    there is no context, the table is unknown, the container is lazy
    (``LazyParquetContainer.table`` is a LOADING property — peeking it
    would defeat lazy registration, and lazy scans read PLAIN buffers per
    query anyway), a projected name is missing, or everything is PLAIN.
    Shared by the estimator's encoded-width scan bounds and the verifier's
    EXPLAIN LINT encoding advisory so the two can never disagree about
    which scans are encoded."""
    if context is None:
        return None
    try:
        dc = context.schema[node.schema_name].tables.get(node.table_name)
    except (KeyError, AttributeError):
        return None
    from ..datacontainer import LazyParquetContainer

    if dc is None or isinstance(dc, LazyParquetContainer):
        return None
    table = getattr(dc, "table", None)
    if table is None:
        return None
    names = [str(c) for c in (node.projection if node.projection is not None
                              else table.column_names)]
    cols = [table.columns.get(n) for n in names]
    if any(c is None for c in cols):
        return None
    if not any(c.encoding is not Encoding.PLAIN for c in cols):
        return None
    return table, names


# ---------------------------------------------------------------------------
# code-space predicate translation (DICT columns, sorted enc_values)
# ---------------------------------------------------------------------------
#: operator mirror for `lit OP col` -> `col OP' lit`
FLIP_CMP = {"eq": "eq", "ne": "ne", "lt": "gt", "le": "ge",
            "gt": "lt", "ge": "le"}


def dict_literal_bounds(values: np.ndarray, op: str, literal):
    """Host translation of ``col OP literal`` into code space for a SORTED
    dictionary.  Returns (kind, code) where kind/code describe a pure
    integer predicate over the codes:

    - ("lt", L)      codes <  L
    - ("ge", L)      codes >= L
    - ("eq", i)      codes == i      (exact dictionary member)
    - ("none", _)    no code matches (eq of an absent literal)
    - ("all", _)     every code matches
    """
    lit = literal
    left = int(np.searchsorted(values, lit, side="left"))
    right = int(np.searchsorted(values, lit, side="right"))
    if op == "lt":
        return ("lt", left)
    if op == "le":
        return ("lt", right)
    if op == "gt":
        return ("ge", right)
    if op == "ge":
        return ("ge", left)
    present = left < len(values) and left < right
    if op == "eq":
        return ("eq", left) if present else ("none", 0)
    if op == "ne":
        # ne of an absent literal is TRUE for every (valid) row
        return ("ne", left) if present else ("all", 0)
    raise ValueError(f"untranslatable op {op!r}")
