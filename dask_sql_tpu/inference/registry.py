"""Per-context lowered-model registry: device-resident programs, swap
detection, ledger accounting.

`programs.try_lower` is pure; this module gives the Context the serving
discipline around it:

- one lowering per registered model object — the verdict (program or
  decline reason) is cached on ``context._model_programs`` keyed by
  ``(schema, name)`` and invalidated by re-registration / DROP MODEL; the
  entry holds the model object itself (a bare ``id()`` could be reused by
  a later allocation and silently serve a stale program);
- params are committed to device once (``jnp.asarray``) on FIRST fused
  use, so PREDICT launches pass device-resident weights instead of
  re-uploading — the bytes surface in the HBM ledger as
  ``serving.ledger.model_bytes``.  Advisory readers (SHOW MODELS,
  DESCRIBE MODEL, the estimator) lower WITHOUT committing: a catalog
  statement must not consume HBM for models that never PREDICT;
- a re-registered model (retrain, ``CREATE OR REPLACE MODEL``) re-lowers
  on first use; when the new program's ``shape_key`` matches the old one
  the swap is ZERO-recompile (the compiled-predict executable keys on the
  shape, weights are runtime args) and is recorded as a ``model.swap``
  flight event + ``inference.model.swap`` metric; a shape change is just a
  fresh ``model.lower``;
- everything is failure-isolated: a lowering bug declines the model to
  the host path, never fails the query.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..runtime import locks
from .programs import ModelProgram, try_lower

logger = logging.getLogger(__name__)

#: registry value: (model_object, program_or_None, reason, committed).
#: The model object is held strongly so identity stays valid — the entry
#: is replaced on next use after a swap and dropped by `invalidate`.
_Entry = Tuple[Any, Optional[ModelProgram], str, bool]

# rank 70: the publish lock.  Lowering AND the h2d weight commit run
# OUTSIDE it (see program_for) — only dict publishes happen under it,
# so it nests safely inside any serving/fleet lock
_lock = locks.named_lock("inference.registry")


def _registry(context) -> Dict[Tuple[str, str], _Entry]:
    reg = getattr(context, "_model_programs", None)
    if reg is None:
        reg = context._model_programs = {}
    return reg


def _commit(program: ModelProgram) -> ModelProgram:
    """Move the params pytree to device once; later PREDICT launches pass
    the committed buffers (no per-query h2d of model weights)."""
    import dataclasses

    import jax.numpy as jnp

    return dataclasses.replace(
        program, params=tuple(jnp.asarray(p) for p in program.params))


def _stamp_use(context, key: Tuple[str, str]) -> None:
    """Record a fused (committing) use — the idleness signal
    `reclaim_idle_models` reads.  Plain dict assignment is GIL-atomic; the
    stamp is advisory, so a torn read only delays one reclaim."""
    uses = getattr(context, "_model_last_use", None)
    if uses is None:
        uses = context._model_last_use = {}
    uses[key] = time.monotonic()


def _still_registered(context, schema_name: str, name: str, model) -> bool:
    """A DROP MODEL (or replacement) racing a lowering must not let the
    lowering re-insert an entry for the gone object — it would pin
    committed weights the ledger charges with no DROP left to evict
    them."""
    try:
        entry = context.schema[schema_name].models.get(name)
    except Exception:  # dsql: allow-broad-except — a torn schema read is
        # a "not registered" verdict, never a query error
        return False
    return entry is not None and entry[0] is model


def program_for(context, schema_name: str, name: str, model: Any,
                commit: bool = False) -> Tuple[Optional[ModelProgram], str]:
    """``(program, reason)`` for a registered model, lowering on first use
    and re-lowering when the registered object changed (model swap).

    ``commit=True`` (the fused rung) moves the params to device on first
    use and keeps the committed pytree cached; advisory callers (SHOW
    MODELS / DESCRIBE MODEL / the estimator) leave the params host-side
    so catalog statements never consume HBM.  Lowering AND the h2d commit
    both run outside ``_lock`` — the lock only publishes — so a large
    ensemble upload never blocks other models' lowerings or the ledger
    scrape."""
    reg = _registry(context)
    key = (schema_name, name)
    metrics = getattr(context, "metrics", None)
    if commit:
        _stamp_use(context, key)
    with _lock:
        entry = reg.get(key)
    if entry is not None and entry[0] is model:
        _model, program, reason, committed = entry
        if not commit or committed or program is None:
            return program, reason
        # h2d outside the lock; the charge is custodied by the registry
        # entry — DROP MODEL / reclaim_idle_models drops the reference and
        # the scrape-based ledger self-corrects
        # dsql: allow-unpaired-effect — registry-entry custody
        program = _commit(program)
        with _lock:
            cur = reg.get(key)
            if cur is not None and cur[0] is model and cur[3]:
                return cur[1], cur[2]  # another thread committed first
            if _still_registered(context, schema_name, name, model):
                reg[key] = (model, program, reason, True)
        return program, reason
    program, reason = try_lower(model)
    if program is not None and commit:
        # dsql: allow-unpaired-effect — registry-entry custody (above)
        program = _commit(program)
    from ..observability import flight

    inserted = False
    with _lock:
        prior = reg.get(key)
        if prior is not None and prior[0] is model:
            # two threads raced the same first lowering (e.g. the advisory
            # estimator vs the committing fused rung): keep the richer
            # entry — never let an uncommitted write demote a committed
            # one (the ledger would under-report) — and emit nothing (the
            # first writer already recorded the model.lower)
            if not (commit and program is not None) or prior[3]:
                return prior[1], prior[2]
            if _still_registered(context, schema_name, name, model):
                reg[key] = (model, program, reason, True)
            return program, reason
        if _still_registered(context, schema_name, name, model):
            reg[key] = (model, program, reason,
                        commit and program is not None)
            inserted = True
    if not inserted:
        # dropped (or replaced) mid-lowering: serve this caller, cache and
        # record nothing — there is no DROP left to evict an entry
        return program, reason
    swapped = prior is not None and prior[0] is not model
    if swapped and program is not None and prior[1] is not None \
            and prior[1].shape_key == program.shape_key:
        # same hyper-shape: the compiled-predict executable keyed on the
        # shape serves the NEW weights with zero recompile
        if metrics is not None:
            metrics.inc("inference.model.swap")
        flight.record("model.swap", model=f"{schema_name}.{name}",
                      kind=program.kind,
                      param_bytes=program.param_bytes)
    else:
        if metrics is not None:
            metrics.inc("inference.model.lowered" if program is not None
                        else "inference.model.declined")
        flight.record("model.lower", model=f"{schema_name}.{name}",
                      tier="compiled" if program is not None else "host",
                      kind=program.kind if program is not None else None,
                      reason=None if program is not None else reason,
                      param_bytes=program.param_bytes
                      if program is not None else None)
    return program, reason


def invalidate(context, schema_name: str, name: str) -> None:
    """Drop a cached lowering (re-registration / DROP MODEL): the next use
    re-lowers against the current model object; the ledger stops charging
    the dropped params, and the fused-rung pipeline cache evicts the
    model's executables so they cannot pin device weights the ledger no
    longer reports."""
    reg = getattr(context, "_model_programs", None)
    if reg is None:
        return
    with _lock:
        reg.pop((schema_name, name), None)
    from ..physical.compiled_predict import drop_model_pipelines

    drop_model_pipelines(context, schema_name, name)


def reclaim_idle_models(context, idle_s: float = 120.0,
                        bytes_needed: Optional[int] = None) -> int:
    """Pressure reclaim (resilience/pressure.py, tier 3 of the cross-tier
    walk): de-commit committed model params whose last fused use is at
    least ``idle_s`` seconds old.  The params move back to host numpy —
    the next PREDICT re-commits with ZERO recompile (the compiled-predict
    executable keys on the shape, weights are runtime args) — and the
    model's pipeline-cache entries are dropped so no executable keeps the
    device buffers pinned.  Returns device bytes freed; stops early once
    ``bytes_needed`` is met.  Models with a fresh stamp are hot (actively
    serving fused PREDICTs) and are never touched."""
    import dataclasses

    reg = getattr(context, "_model_programs", None)
    if not reg:
        return 0
    uses = getattr(context, "_model_last_use", {}) or {}
    now = time.monotonic()
    freed = 0
    with _lock:
        entries = list(reg.items())
    for key, (model, program, reason, committed) in entries:
        if bytes_needed is not None and freed >= bytes_needed:
            break
        if program is None or not committed:
            continue
        last = uses.get(key)
        if last is not None and now - last < idle_s:
            continue
        demoted = dataclasses.replace(
            program, params=tuple(np.asarray(p) for p in program.params))
        with _lock:
            cur = reg.get(key)
            if cur is None or cur[0] is not model or not cur[3]:
                continue  # raced a swap / drop / concurrent reclaim
            reg[key] = (model, demoted, reason, False)
        freed += int(program.param_bytes)
        from ..physical.compiled_predict import drop_model_pipelines

        drop_model_pipelines(context, key[0], key[1])
        logger.info("pressure reclaim de-committed idle model %s.%s "
                    "(%d bytes)", key[0], key[1], program.param_bytes)
    return freed


def context_model_bytes(context) -> int:
    """Device bytes of every lowered model's committed params — the HBM
    ledger's ``serving.ledger.model_bytes`` component.  Uncommitted
    lowerings (advisory verdicts that never served a fused PREDICT) hold
    no HBM and are not charged."""
    reg = getattr(context, "_model_programs", None)
    if not reg:
        return 0
    with _lock:
        entries = list(reg.values())
    total = 0
    for _, program, _, committed in entries:
        if program is not None and committed:
            try:
                total += program.param_bytes
            except Exception:  # dsql: allow-broad-except — advisory
                # accounting must never fail a metrics scrape
                logger.debug("model byte accounting failed", exc_info=True)
    return total


def lowering_verdict(context, schema_name: str, name: str
                     ) -> Dict[str, str]:
    """SHOW MODELS / DESCRIBE MODEL verdict row for one registered model:
    serving tier, device param bytes, and the program's shape summary (or
    the decline reason).  Failure-isolated — unknown models report the
    host tier."""
    try:
        model, _cols = context.get_model(schema_name, name)
        program, reason = program_for(context, schema_name, name, model)
    except Exception:  # dsql: allow-broad-except — a broken model entry
        # must not sink catalog statements
        logger.debug("lowering verdict failed", exc_info=True)
        return {"tier": "host", "param_bytes": "", "shape": ""}
    if program is None:
        return {"tier": "host", "param_bytes": "", "shape": reason}
    return {"tier": "compiled",
            "param_bytes": str(program.param_bytes),
            "shape": program.describe()}


def predict_scratch_bytes(program: Optional[ModelProgram],
                          n_features: int) -> int:
    """Per-row device intermediate floor of one fused PREDICT: the f64
    feature matrix plus, for tree programs, the (row, tree)-shaped
    navigation/leaf buffers (int32 node + f64 value [+ f64 per class for
    probability leaves]).  The estimator multiplies by the padded row
    bucket to charge ``peak_bytes``."""
    per_row = 8 * max(int(n_features), 1)
    if program is None:
        return per_row
    trees = int(program.meta.get("trees", 0))
    if trees:
        per_row += trees * 12
        if program.kind in ("tree_classifier", "forest_classifier"):
            per_row += trees * 8 * int(program.meta.get("classes", 1))
    return per_row
