"""Out-of-core aggregation: stream parquet batches through partial->final states.

Role parity: the reference's partitioned execution — dask runs the chunk/agg/
finalize triple of `dd.Aggregation` per partition and tree-combines
(aggregate.py:117-160, split_every).  Here the "partitions" are parquet
row-group batches: each batch is scanned (with projection + IO filters),
filtered/projected on device, partially aggregated, and only the small
partial-state tables stay resident — rows never all live in HBM at once.
This is the row-axis scaling story (SURVEY.md §5 "long-context" analogue).

Eligibility: the same scan→filter/project→aggregate chains the compiled
pipeline handles, with partial-izable aggregates (sum/count/avg/min/max/
var/std family).  Ineligible shapes silently fall back to the in-memory path.
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp

from ..columnar.column import Column
from ..columnar.dtypes import SqlType
from ..columnar.table import Table
from ..datacontainer import LazyParquetContainer
from ..planner import plan as p
from ..planner.expressions import (
    AggExpr,
    ExistsExpr,
    InSubqueryExpr,
    ScalarSubqueryExpr,
    walk,
)

logger = logging.getLogger(__name__)

#: (partial_name, partial_func) sets per supported aggregate
_PARTIALIZABLE = {
    "sum": ("sum",),
    "count": ("count",),
    "count_star": ("count_star",),
    "avg": ("sum", "count"),
    "min": ("min",),
    "max": ("max",),
    "var_samp": ("count", "sum", "sumsq"),
    "var_pop": ("count", "sum", "sumsq"),
    "stddev_samp": ("count", "sum", "sumsq"),
    "stddev_pop": ("count", "sum", "sumsq"),
}


def _find_stream_axis(plan: p.LogicalPlan, context):
    """Locate the unique lazy-parquet scan and check the path to it is
    batch-distributive (Filter/Projection/Alias freely; joins only where the
    streamed side is the preserved/probe side).  Returns
    (scan, container, off_path_roots) or None."""
    lazy = []
    for node in p.walk_plan(plan):
        if isinstance(node, p.TableScan):
            dc = context.schema.get(node.schema_name)
            dc = dc.tables.get(node.table_name) if dc is not None else None
            if isinstance(dc, LazyParquetContainer):
                lazy.append((node, dc))
    if len(lazy) != 1:
        return None
    scan, dc = lazy[0]

    def path_to(node):
        if node is scan:
            return [node]
        for child in node.inputs():
            sub = path_to(child)
            if sub is not None:
                return [node] + sub
        return None

    path = path_to(plan)
    if path is None:
        return None
    # subquery expressions embed whole plans that walk_plan cannot see; their
    # evaluation inside a batch scope would read the override (wrong results
    # when they reference the streamed table) — decline conservatively
    from ..planner.optimizer.rules import _node_exprs

    for node in p.walk_plan(plan):
        for e in _node_exprs(node):
            if any(isinstance(x, (ScalarSubqueryExpr, InSubqueryExpr, ExistsExpr))
                   for x in walk(e)):
                return None
    off_path: List[p.LogicalPlan] = []
    for parent, child in zip(path[:-1], path[1:]):
        if isinstance(parent, (p.Filter, p.Projection, p.SubqueryAlias)):
            continue
        if isinstance(parent, p.Join):
            on_left = child is parent.left
            jt = parent.join_type
            # union over lazy-side batches == full join only when the lazy
            # side is the preserved/probe side
            ok = (jt == "INNER"
                  or (on_left and jt in ("LEFT", "LEFTSEMI", "LEFTANTI"))
                  or (not on_left and jt == "RIGHT"))
            if not ok:
                return None
            off_path.append(parent.right if on_left else parent.left)
            continue
        return None
    return scan, dc, off_path


def try_streaming_aggregate(rel: p.Aggregate, executor) -> Optional[Table]:
    config = executor.config
    if not config.get("sql.streaming.enabled", True):
        return None
    axis = _find_stream_axis(rel.input, executor.context)
    if axis is None:
        return None
    scan, dc, off_path = axis
    batch_rows = int(config.get("sql.streaming.batch_rows", 2_000_000))
    total = (dc.statistics or {}).get("num-rows", 0)
    if not total or total <= batch_rows:
        return None  # fits comfortably; the compiled in-memory path is faster
    for agg in rel.agg_exprs:
        if agg.func not in _PARTIALIZABLE or agg.distinct:
            return None
    # non-streamed join sides execute ONCE, shared across batches
    shared = {id(node): executor.execute(node) for node in off_path}

    # -- build the per-batch partial plan over the scan schema --------------
    # partial aggs: dedup (func, args, filter) structurally
    partial_specs: List[Tuple[str, AggExpr]] = []
    spec_index: Dict = {}

    def partial_of(agg: AggExpr, kind: str) -> int:
        if kind == "count_star":
            probe = AggExpr("count_star", (), SqlType.BIGINT, False, agg.filter)
        elif kind == "sumsq":
            probe = AggExpr("sumsq", agg.args, SqlType.DOUBLE, False, agg.filter)
        else:
            out_t = SqlType.BIGINT if kind == "count" else (
                SqlType.DOUBLE if kind in ("sum",) else agg.sql_type)
            probe = AggExpr(kind, agg.args, out_t, False, agg.filter)
        key = (probe.func, probe.args, probe.filter)
        if key not in spec_index:
            spec_index[key] = len(partial_specs)
            partial_specs.append((kind, probe))
        return spec_index[key]

    finalize: List[Tuple[str, List[int]]] = []
    for agg in rel.agg_exprs:
        kinds = _PARTIALIZABLE[agg.func]
        finalize.append((agg.func, [partial_of(agg, k) for k in kinds]))

    from ..ops import grouping as g

    # -- stream batches ------------------------------------------------------
    from .executor import Executor

    names = scan.projection if scan.projection is not None else [
        f.name for f in dc.fields]
    from .utils.filter import filters_to_pyarrow

    pa_filters, _ = filters_to_pyarrow(scan.filters, list(names))

    partial_tables: List[Table] = []
    ngroups = len(rel.group_exprs)
    for batch in _iter_batches(dc, names, pa_filters, batch_rows):
        sub = Executor(executor.context)
        sub.table_overrides[(scan.schema_name, scan.table_name)] = batch
        sub._memo.update(shared)
        # execute the original subtree up to (excluding) the aggregate
        inp_table = sub.execute(rel.input)
        gcols = [sub.eval_expr(e, inp_table) for e in rel.group_exprs]
        if inp_table.num_rows == 0:
            continue
        gid, order, num_groups = (g.factorize(g.key_arrays(gcols))
                                  if gcols else
                                  (jnp.zeros(inp_table.num_rows, dtype=jnp.int32),
                                   None, 1))
        cols: Dict[str, Column] = {}
        if gcols and num_groups > 0:
            first = g.group_first_indices(gid, num_groups)
            for i, col in enumerate(gcols):
                cols[f"__g{i}"] = col.take(first)
        for j, (kind, probe) in enumerate(partial_specs):
            cols[f"__p{j}"] = _partial_kernel(kind, probe, inp_table, gid,
                                              num_groups, sub)
        partial_tables.append(Table(cols, num_groups))

    if not partial_tables:
        # no rows anywhere: fall back to the normal path for correct empties
        return None

    # -- final combine -------------------------------------------------------
    combined = Table.concat(partial_tables)
    gcols = [combined.columns[f"__g{i}"] for i in range(ngroups)]
    if gcols:
        gid, order, num_groups = g.factorize(g.key_arrays(gcols))
        first = g.group_first_indices(gid, num_groups)
    else:
        gid = jnp.zeros(combined.num_rows, dtype=jnp.int32)
        num_groups = 1
        first = jnp.zeros(1, dtype=jnp.int64)

    from .rel.base import unique_names

    out_names = unique_names([f.name for f in rel.schema])
    out: Dict[str, Column] = {}
    for name, col in zip(out_names, gcols):
        out[name] = col.take(first)

    def combine(j: int, how: str):
        col = combined.columns[f"__p{j}"]
        if col.dictionary is not None:
            col = col.compact_dictionary()  # sorted codes = lexicographic order
        valid = col.valid_mask()
        if how == "sum":
            vals, ok = g.seg_sum(col.data, valid, gid, num_groups)
        elif how == "min":
            vals, ok = g.seg_min(col.data, valid, gid, num_groups)
        else:
            vals, ok = g.seg_max(col.data, valid, gid, num_groups)
        return vals, ok, col

    for name, agg, (func, idxs) in zip(out_names[ngroups:], rel.agg_exprs, finalize):
        if func in ("sum", "count", "count_star"):
            vals, ok = combine(idxs[0], "sum")[:2]
            out[name] = _typed(vals, ok if func == "sum" else None, agg.sql_type)
        elif func == "avg":
            s = combine(idxs[0], "sum")[0]
            cnt = combine(idxs[1], "sum")[0]
            ok = cnt > 0
            out[name] = _typed(s.astype(jnp.float64) / jnp.maximum(cnt, 1), ok,
                               SqlType.DOUBLE)
        elif func in ("min", "max"):
            vals, ok, src_col = combine(idxs[0], func)
            validity = None if bool(ok.all()) else ok
            out[name] = Column(vals, agg.sql_type, validity, src_col.dictionary)
        else:  # variance family from (count, sum, sumsq)
            cnt = combine(idxs[0], "sum")[0]
            s = combine(idxs[1], "sum")[0]
            s2 = combine(idxs[2], "sum")[0]
            ddof = 1 if func.endswith("samp") else 0
            mean = s / jnp.maximum(cnt, 1)
            var = jnp.maximum(s2 - cnt * mean * mean, 0.0) / jnp.maximum(cnt - ddof, 1)
            vals = jnp.sqrt(var) if func.startswith("stddev") else var
            out[name] = _typed(vals, cnt > ddof, SqlType.DOUBLE)
    logger.info("streaming aggregate over %d batches", len(partial_tables))
    return Table(out, num_groups)


def _typed(vals, ok, sql_type: SqlType) -> Column:
    from ..columnar.dtypes import sql_to_np

    target = sql_to_np(sql_type)
    vals = vals.astype(target) if vals.dtype != target else vals
    validity = None if ok is None or bool(ok.all()) else ok
    return Column(vals, sql_type, validity)


def _partial_kernel(kind: str, probe: AggExpr, inp: Table, gid, num_groups, sub) -> Column:
    from ..ops import grouping as g

    n = inp.num_rows
    fmask = None
    if probe.filter is not None:
        fc = sub.eval_expr(probe.filter, inp)
        fmask = fc.data & fc.valid_mask()
    if kind == "count_star":
        valid = jnp.ones(n, dtype=bool) if fmask is None else fmask
        return Column(g.seg_count(valid, gid, num_groups), SqlType.BIGINT)
    col = sub.eval_expr(probe.args[0], inp)
    valid = col.valid_mask()
    if fmask is not None:
        valid = valid & fmask
    if jnp.issubdtype(col.data.dtype, jnp.floating):
        valid = valid & ~jnp.isnan(col.data)
    if kind == "count":
        return Column(g.seg_count(valid, gid, num_groups), SqlType.BIGINT)
    if kind == "sum":
        # preserve exact int64 accumulation (parity with the in-memory path)
        if jnp.issubdtype(col.data.dtype, jnp.integer) or col.data.dtype == jnp.bool_:
            vals, ok = g.seg_sum(col.data.astype(jnp.int64), valid, gid, num_groups)
            return _typed(vals, ok, SqlType.BIGINT)
        vals, ok = g.seg_sum(col.data.astype(jnp.float64), valid, gid, num_groups)
        return _typed(vals, ok, SqlType.DOUBLE)
    if kind == "sumsq":
        x = col.data.astype(jnp.float64)
        vals, ok = g.seg_sum(x * x, valid, gid, num_groups)
        return _typed(vals, ok, SqlType.DOUBLE)
    if col.dictionary is not None:
        # sorted dictionary => code order == lexicographic order per batch
        col = col.compact_dictionary()
        valid = col.valid_mask() if fmask is None else (col.valid_mask() & fmask)
    if kind == "min":
        vals, ok = g.seg_min(col.data, valid, gid, num_groups)
        return Column(vals, col.sql_type, None if bool(ok.all()) else ok, col.dictionary)
    vals, ok = g.seg_max(col.data, valid, gid, num_groups)
    return Column(vals, col.sql_type, None if bool(ok.all()) else ok, col.dictionary)


def _iter_batches(dc: LazyParquetContainer, columns, pa_filters, batch_rows: int):
    """Stream record batches through the dataset scanner — rows with a filter
    are pruned per row group and never fully materialized on the host."""
    import pyarrow as pa
    import pyarrow.dataset as ds
    import pyarrow.parquet as pq

    from .utils.statistics import _paths_for

    expr = pq.filters_to_expression(pa_filters) if pa_filters else None
    dataset = ds.dataset(_paths_for(dc.location), format="parquet")
    scanner = dataset.scanner(columns=list(columns) if columns else None,
                              filter=expr, batch_size=batch_rows)
    for record_batch in scanner.to_batches():
        if record_batch.num_rows:
            yield Table.from_arrow(pa.Table.from_batches([record_batch]))
