"""CLI: ``python -m dask_sql_tpu.analysis --self`` (engine self-lint) or
``python -m dask_sql_tpu.analysis path.py ...`` (lint specific files).

Exit code 0 = clean, 1 = findings, 2 = bad invocation.  CI runs ``--self``
(also wired as a tier-1 test in tests/unit/test_analysis.py and the
``bench.py --lint`` smoke mode).

``--rule DSQLnnn`` (repeatable) restricts the report to specific rules so
a pre-commit hook can gate on e.g. the concurrency rules alone;
``--format json`` emits a machine-readable report (one object with
``findings`` / ``files`` / ``rules``) so CI can diff findings across
runs; ``--format sarif`` emits a minimal SARIF 2.1.0 log so code-scanning
UIs (GitHub, VS Code SARIF viewers) can render findings in place.
"""
from __future__ import annotations

import argparse
import json
import sys

from .selflint import RULES, lint_paths, package_files, self_lint


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m dask_sql_tpu.analysis",
        description="Static self-lint for the dask_sql_tpu engine")
    parser.add_argument("--self", dest="self_mode", action="store_true",
                        help="lint the installed engine package")
    parser.add_argument("--rules", action="store_true",
                        help="list rule ids and exit")
    parser.add_argument("--rule", action="append", default=[],
                        metavar="DSQLnnn",
                        help="report only this rule id (repeatable)")
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text",
                        help="output format (default: text)")
    parser.add_argument("paths", nargs="*", help="python files to lint")
    args = parser.parse_args(argv)

    if args.rules:
        for rule, doc in sorted(RULES.items()):
            print(f"{rule}: {doc}")
        return 0
    if not args.self_mode and not args.paths:
        parser.print_usage(sys.stderr)
        return 2
    unknown = [r for r in args.rule if r not in RULES and r != "DSQL000"]
    if unknown:
        print(f"unknown rule id(s): {', '.join(unknown)} "
              f"(--rules lists them)", file=sys.stderr)
        return 2

    if args.self_mode:
        findings = self_lint()
        n_files = len(package_files())
    else:
        findings = lint_paths(args.paths)
        n_files = len(args.paths)
    if args.rule:
        wanted = set(args.rule)
        findings = [f for f in findings if f.rule in wanted]

    if args.format == "sarif":
        print(json.dumps(_sarif(findings), indent=2))
    elif args.format == "json":
        print(json.dumps({
            "findings": [
                {"rule": f.rule, "path": f.path, "line": f.line,
                 "message": f.message}
                for f in findings
            ],
            "files": n_files,
            "rules": sorted(args.rule) if args.rule else sorted(RULES),
        }, indent=2))
    else:
        for f in findings:
            print(f.format())
        print(f"self-lint: {len(findings)} finding(s) in "
              f"{n_files} file(s)")
    return 1 if findings else 0


def _sarif(findings) -> dict:
    """Minimal SARIF 2.1.0 log: one run, the full rule catalog in the
    driver, one ``result`` per finding with a physical location."""
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "dask-sql-tpu-selflint",
                "informationUri":
                    "https://github.com/dask-contrib/dask-sql",
                "rules": [
                    {"id": rule,
                     "shortDescription": {"text": doc}}
                    for rule, doc in sorted(RULES.items())
                ],
            }},
            "results": [
                {"ruleId": f.rule,
                 "level": "error",
                 "message": {"text": f.message},
                 "locations": [{"physicalLocation": {
                     "artifactLocation": {"uri": f.path},
                     "region": {"startLine": max(f.line, 1)},
                 }}]}
                for f in findings
            ],
        }],
    }


if __name__ == "__main__":
    sys.exit(main())
