"""Serving-runtime benchmark: repeated-query throughput (cold vs. warm
result cache) and latency percentiles under 32 concurrent clients, through
the real Presto HTTP server.

Prints JSON lines in the bench.py convention:
  {"metric": "serving_warm_qps", "value": ..., "unit": "queries/s", ...}
so the driver's next BENCH_*.json tail can record it.
"""
from __future__ import annotations

import json
import sys
import time
import urllib.error
import urllib.request

import numpy as np

sys.path.insert(0, ".")
sys.path.insert(0, "tests")

N_ROWS = 2_000_000
N_CLIENTS = 32
N_QUERIES = 96  # total across clients, per phase
QUERY = ("SELECT g, SUM(x) AS s, COUNT(*) AS n FROM traffic "
         "GROUP BY g ORDER BY s DESC")


def _post(port: int, sql: str):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/statement", data=sql.encode(),
        method="POST")
    with urllib.request.urlopen(req) as resp:
        return json.loads(resp.read())


def _follow(payload, timeout=120.0):
    deadline = time.time() + timeout
    while "nextUri" in payload and time.time() < deadline:
        time.sleep(0.01)
        with urllib.request.urlopen(payload["nextUri"]) as resp:
            payload = json.loads(resp.read())
    return payload


def _run_phase(port: int, sqls) -> dict:
    """Fire the statements from N_CLIENTS threads; return wall + latencies."""
    import concurrent.futures

    lat = []

    def one(sql):
        t0 = time.perf_counter()
        payload = _follow(_post(port, sql))
        state = payload.get("stats", {}).get("state")
        assert state == "FINISHED", payload.get("error", state)
        lat.append(time.perf_counter() - t0)

    t0 = time.perf_counter()
    with concurrent.futures.ThreadPoolExecutor(max_workers=N_CLIENTS) as pool:
        list(pool.map(one, sqls))
    wall = time.perf_counter() - t0
    lat_s = sorted(lat)

    def pct(q):
        return lat_s[min(len(lat_s) - 1, int(q * (len(lat_s) - 1) + 0.5))]

    return {"wall_s": round(wall, 3), "qps": round(len(sqls) / wall, 1),
            "p50_ms": round(pct(0.5) * 1e3, 1),
            "p99_ms": round(pct(0.99) * 1e3, 1)}


def main():
    import pandas as pd

    from dask_sql_tpu import Context
    from dask_sql_tpu.server.app import run_server

    rng = np.random.RandomState(0)
    c = Context()
    c.create_table("traffic", pd.DataFrame({
        "g": rng.randint(0, 128, N_ROWS),
        "x": rng.rand(N_ROWS),
    }))
    srv = run_server(context=c, host="127.0.0.1", port=0, blocking=False)
    port = srv.port
    try:
        # warm compile caches once so "cold" measures execution, not XLA
        _follow(_post(port, QUERY))

        # cold: distinct statements -> every query misses the result cache
        cold_sqls = [QUERY + f" LIMIT {100 + i}" for i in range(N_QUERIES)]
        cold = _run_phase(port, cold_sqls)
        print(json.dumps({"metric": "serving_cold_qps", "unit": "queries/s",
                          "clients": N_CLIENTS, **cold}))

        # warm: one identical statement -> result cache serves repeats
        warm = _run_phase(port, [QUERY] * N_QUERIES)
        print(json.dumps({"metric": "serving_warm_qps", "unit": "queries/s",
                          "clients": N_CLIENTS, **warm}))

        m = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/v1/metrics").read())
        cache = m.get("resultCache", {})
        print(json.dumps({
            "metric": "serving_cache",
            "hitRate": cache.get("hitRate"),
            "hits": cache.get("hits"), "misses": cache.get("misses"),
            "bytes": cache.get("bytes"),
            "warm_speedup": round(warm["qps"] / max(cold["qps"], 1e-9), 2),
        }))

        # shed behavior against a deliberately tiny queue
        shed = _shed_probe()
        print(json.dumps({"metric": "serving_shed_probe", **shed}))
    finally:
        srv.shutdown()


def _shed_probe() -> dict:
    """Burst 16 instant submits at a 1-worker/1-slot runtime; count sheds."""
    import threading

    from dask_sql_tpu.serving import QueueFullError, ServingRuntime

    rt = ServingRuntime(workers=1, bounds={"interactive": 1, "batch": 1})
    gate = threading.Event()
    rt.submit(lambda t: gate.wait(10))
    accepted, shed, retry_hints = 1, 0, []
    for _ in range(16):
        try:
            rt.submit(lambda t: None)
            accepted += 1
        except QueueFullError as e:
            shed += 1
            retry_hints.append(e.retry_after_s)
    gate.set()
    rt.shutdown()
    return {"accepted": accepted, "shed": shed,
            "retry_after_s": retry_hints[0] if retry_hints else None}


if __name__ == "__main__":
    main()
