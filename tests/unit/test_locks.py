"""Runtime lock sanitizer (runtime/locks.py, ISSUE 19): deliberate
inversions raise a structured LockOrderError with both witness stacks
BEFORE the acquire blocks, correct orders stay silent, and violations
feed the ``analysis.locks.*`` metrics and ``lock.order_violation``
flight events that the chaos campaigns gate on.
"""
import threading

import pytest

from dask_sql_tpu.runtime import locks
from dask_sql_tpu.runtime.locks import (
    DECLARED_RANKS,
    LockOrderError,
    NamedLock,
    named_condition,
    named_lock,
)

pytestmark = pytest.mark.concurrency


@pytest.fixture(autouse=True)
def fresh_sanitizer():
    """Clean order graph/registry per test (production NamedLocks keep
    working — registration only matters at creation time), sanitizer
    forced ON, and the attached metrics registry restored afterwards."""
    saved_metrics = locks._metrics
    locks.reset()
    locks.set_enabled(True)
    yield
    locks.reset()
    locks.set_enabled(True)
    locks.attach_metrics(saved_metrics)


def in_thread(fn):
    """Run fn on a fresh thread (its own held-stack) and re-raise."""
    box = {}

    def runner():
        try:
            fn()
        except BaseException as exc:  # dsql: allow-broad-except — test harness relay
            box["exc"] = exc

    t = threading.Thread(target=runner)
    t.start()
    t.join(10)
    assert not t.is_alive(), "sanitized acquire deadlocked instead of raising"
    if "exc" in box:
        raise box["exc"]


# ------------------------------------------------------------ cycle check
def test_deliberate_inversion_raises_with_both_witness_stacks():
    a = NamedLock("t.cyc.a")
    b = NamedLock("t.cyc.b")

    # record the a -> b edge on another thread (full witness stack kept)
    def forward():
        with a:
            with b:
                pass

    in_thread(forward)

    with b:
        with pytest.raises(LockOrderError) as exc_info:
            a.acquire()
    err = exc_info.value
    assert err.kind == "cycle"
    assert err.holding == "t.cyc.b"
    assert err.acquiring == "t.cyc.a"
    # both witnesses: this thread's stack AND the recorded reverse edge
    assert "-- this thread" in err.witness
    assert "-- recorded edge 't.cyc.a' -> 't.cyc.b'" in err.witness
    assert "forward" in err.witness  # the first witness's frames survive

    # the check ran BEFORE the acquire: nothing was taken, b releases fine
    assert not a.locked()


def test_longer_cycle_through_intermediate_lock():
    a, b, c = NamedLock("t.tri.a"), NamedLock("t.tri.b"), NamedLock("t.tri.c")
    in_thread(lambda: _nest(a, b))
    in_thread(lambda: _nest(b, c))
    with c:
        with pytest.raises(LockOrderError) as exc_info:
            a.acquire()
    assert exc_info.value.kind == "cycle"
    # the witness walks the recorded a -> b -> c chain
    assert "'t.tri.a' -> 't.tri.b'" in exc_info.value.witness
    assert "'t.tri.b' -> 't.tri.c'" in exc_info.value.witness


def _nest(outer, inner):
    with outer:
        with inner:
            pass


def test_consistent_order_is_silent_and_recorded():
    a = NamedLock("t.ok.a")
    b = NamedLock("t.ok.b")
    for _ in range(3):
        _nest(a, b)
    in_thread(lambda: _nest(a, b))
    snap = locks.snapshot()
    edges = {(e["from"], e["to"]): e["count"] for e in snap["edges"]}
    assert edges[("t.ok.a", "t.ok.b")] == 4
    assert snap["violations"] == 0


# ------------------------------------------------------------- rank check
def test_rank_inversion_raises():
    outer = NamedLock("t.rank.outer", rank=10)
    inner = NamedLock("t.rank.inner", rank=20)
    with inner:
        with pytest.raises(LockOrderError) as exc_info:
            outer.acquire()
    err = exc_info.value
    assert err.kind == "rank"
    assert err.holding == "t.rank.inner"
    assert err.acquiring == "t.rank.outer"
    assert "rank inversion" in str(err)


def test_declared_rank_order_is_clean():
    # walking the production rank table outer -> inner never trips
    chain = [NamedLock(f"t.chain.{name}", rank=rank)
             for name, rank in sorted(DECLARED_RANKS.items(),
                                      key=lambda kv: kv[1])]
    for lk in chain:
        lk.acquire()
    for lk in reversed(chain):
        lk.release()
    assert locks.violation_count() == 0


def test_named_lock_resolves_rank_from_declared_table():
    lk = named_lock("fleet.router.apply")
    assert lk.rank == DECLARED_RANKS["fleet.router.apply"]
    assert named_lock("t.not.declared").rank is None


def test_rank_conflict_on_reregistration_raises():
    NamedLock("t.conflict", rank=10)
    with pytest.raises(ValueError, match="re-registered with rank"):
        NamedLock("t.conflict", rank=20)
    # same rank is fine (two instances of one lock class)
    NamedLock("t.conflict", rank=10)


# --------------------------------------------------- same-lock re-acquire
def test_plain_lock_self_reacquire_raises_instead_of_hanging():
    lk = NamedLock("t.self")
    with lk:
        with pytest.raises(LockOrderError) as exc_info:
            lk.acquire()
        assert exc_info.value.kind == "self-deadlock"
        assert "re-acquired" in str(exc_info.value)
    # single release (the re-acquire never took it); usable again
    with lk:
        pass


def test_reentrant_lock_nests():
    lk = NamedLock("t.rlock", reentrant=True)
    with lk:
        with lk:
            with lk:
                pass
    assert locks.violation_count() == 0
    # fully released: another thread can take it
    in_thread(lambda: _nest(lk, NamedLock("t.rlock.peer")))


def test_nonblocking_probe_of_held_lock_returns_false():
    # threading.Condition._is_owned falls back to acquire(False) on the
    # lock its own thread holds — must report False, never raise
    lk = NamedLock("t.probe")
    with lk:
        assert lk.acquire(blocking=False) is False
    assert lk.acquire(blocking=False) is True
    lk.release()


def test_same_name_instances_do_not_false_positive():
    # two replicas' state locks share one name; router-ordered nesting
    # across instances must not look like a self-edge or cycle
    r1 = NamedLock("t.replica.state")
    r2 = NamedLock("t.replica.state")
    with r1:
        with r2:
            pass
    with r2:
        with r1:
            pass
    assert locks.violation_count() == 0


# ----------------------------------------------------- condition variable
def test_named_condition_wait_notify_across_threads():
    cv = named_condition("t.cv")
    state = {"ready": False}

    def producer():
        with cv:
            state["ready"] = True
            cv.notify_all()

    with cv:
        threading.Thread(target=producer).start()
        assert cv.wait_for(lambda: state["ready"], timeout=10)
    assert locks.violation_count() == 0


# ------------------------------------------------------------- reporting
def test_violation_feeds_metrics_flight_and_tally():
    from dask_sql_tpu.observability import flight
    from dask_sql_tpu.serving.metrics import MetricsRegistry

    registry = MetricsRegistry()
    locks.attach_metrics(registry)
    before_events = len(flight.RECORDER.events(name="lock.order_violation"))
    before_count = locks.violation_count()

    a = NamedLock("t.rep.a")
    b = NamedLock("t.rep.b")
    in_thread(lambda: _nest(a, b))
    with b:
        with pytest.raises(LockOrderError):
            a.acquire()

    assert locks.violation_count() == before_count + 1
    assert registry.counter("analysis.locks.order_violation") == 1
    events = flight.RECORDER.events(name="lock.order_violation")
    assert len(events) == before_events + 1
    last = events[-1]
    assert last["kind"] == "cycle"
    assert last["holding"] == "t.rep.b"
    assert last["acquiring"] == "t.rep.a"

    detail = locks.violations()[-1]
    assert detail["kind"] == "cycle"
    assert "-- this thread" in detail["witness"]


def test_snapshot_reports_locks_edges_and_enabled():
    a = NamedLock("t.snap.a", rank=1)
    b = NamedLock("t.snap.b", rank=2)
    _nest(a, b)
    snap = locks.snapshot()
    assert snap["enabled"] is True
    assert snap["locks"]["t.snap.a"] == 1
    assert snap["locks"]["t.snap.b"] == 2
    assert {"from": "t.snap.a", "to": "t.snap.b", "count": 1} in snap["edges"]


# -------------------------------------------------------------- disabled
def test_disabled_sanitizer_is_a_passthrough():
    locks.set_enabled(False)
    try:
        a = NamedLock("t.off.a", rank=20)
        b = NamedLock("t.off.b", rank=10)
        with a:  # rank 20 held...
            with b:  # ...acquiring rank 10: would raise if enabled
                pass
        assert locks.violation_count() == 0
        assert locks.snapshot()["edges"] == []
    finally:
        locks.set_enabled(True)


def test_stress_consistent_order_across_threads():
    # 8 threads hammering a 3-deep consistent order: zero violations and
    # no deadlock (the suite-wide sanitizer gates the real modules the
    # same way; this isolates the wrapper's own thread-safety)
    a = NamedLock("t.stress.a", rank=1)
    b = NamedLock("t.stress.b", rank=2)
    c = NamedLock("t.stress.c", rank=3)
    errors = []

    def worker():
        try:
            for _ in range(200):
                with a:
                    with b:
                        with c:
                            pass
                with b:
                    with c:
                        pass
        except BaseException as exc:  # dsql: allow-broad-except — test harness relay
            errors.append(exc)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert not errors
    assert locks.violation_count() == 0
    edges = {(e["from"], e["to"]) for e in locks.snapshot()["edges"]}
    assert ("t.stress.a", "t.stress.b") in edges
    assert ("t.stress.b", "t.stress.c") in edges
