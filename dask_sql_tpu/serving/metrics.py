"""Metrics registry: counters, gauges, and latency histograms for the
serving runtime.

Role parity: the reference points users at the dask dashboard for this;
an inference-serving stack needs its own registry (admissions, rejections,
timeouts, cache hit rate, queue-depth and latency percentiles) that both
``SHOW METRICS`` and the server's ``/v1/metrics`` endpoint can snapshot.
Aggregation from the per-node `Tracer` happens through `observe_trace`.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple


class Histogram:
    """Bounded-reservoir histogram: O(1) observe, percentile on snapshot.

    The reservoir keeps the most recent `window` observations — serving
    percentiles should reflect *current* traffic, not the process lifetime —
    while count/total stay exact cumulative aggregates."""

    __slots__ = ("window", "count", "total", "vmax", "_ring")

    def __init__(self, window: int = 2048):
        self.window = window
        self.count = 0
        self.total = 0.0
        self.vmax = 0.0
        self._ring: "deque[float]" = deque(maxlen=window)

    def observe(self, value: float) -> None:
        value = float(value)
        self.count += 1
        self.total += value
        if value > self.vmax:
            self.vmax = value
        self._ring.append(value)

    def percentiles(self, qs: Iterable[float] = (0.5, 0.95, 0.99)) -> List[float]:
        data = sorted(self._ring)
        if not data:
            return [0.0 for _ in qs]
        n = len(data)
        return [data[min(n - 1, int(q * (n - 1) + 0.5))] for q in qs]

    def snapshot(self) -> Dict[str, Any]:
        p50, p95, p99 = self.percentiles()
        return {
            "count": self.count,
            "sum": round(self.total, 3),
            "avg": round(self.total / self.count, 3) if self.count else 0.0,
            "p50": round(p50, 3),
            "p95": round(p95, 3),
            "p99": round(p99, 3),
            "max": round(self.vmax, 3),
        }


class MetricsRegistry:
    """Thread-safe named counters / gauges / histograms.

    Flat dotted names (``query.cache.hit``, ``serving.rejected``); the
    snapshot is JSON-ready for ``/v1/metrics`` and row-flattened for
    ``SHOW METRICS``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, float] = {}
        self._hists: Dict[str, Histogram] = {}

    # ------------------------------------------------------------- writes
    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            hist = self._hists.get(name)
            if hist is None:
                hist = self._hists[name] = Histogram()
            hist.observe(value)

    def observe_trace(self, root) -> None:
        """Fold one executor `NodeTrace` tree into per-node-type wall-time
        histograms (``executor.node.<type>.ms``) and row counters."""
        if root is None:
            return
        stack = [root]
        while stack:
            t = stack.pop()
            self.observe(f"executor.node.{t.node_type}.ms", t.wall_ms)
            if t.rows >= 0:
                self.inc(f"executor.node.{t.node_type}.rows", t.rows)
            stack.extend(t.children)

    # -------------------------------------------------------------- reads
    def counter(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def hit_rate(self, hit: str, miss: str) -> float:
        with self._lock:
            h = self._counters.get(hit, 0)
            m = self._counters.get(miss, 0)
        return h / (h + m) if (h + m) else 0.0

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: h.snapshot() for k, h in self._hists.items()},
            }
        out["cacheHitRate"] = round(
            self.hit_rate("query.cache.hit", "query.cache.miss"), 4)
        return out

    def rows(self) -> List[Tuple[str, str]]:
        """Flatten the snapshot to (metric, value) string pairs, sorted by
        name — the ``SHOW METRICS`` result shape."""
        snap = self.snapshot()
        rows: List[Tuple[str, str]] = []
        for name, v in snap["counters"].items():
            rows.append((name, str(v)))
        for name, v in snap["gauges"].items():
            rows.append((name, _fmt(v)))
        for name, h in snap["histograms"].items():
            for stat in ("count", "avg", "p50", "p95", "p99", "max"):
                rows.append((f"{name}.{stat}", _fmt(h[stat])))
        rows.append(("query.cache.hit_rate", _fmt(snap["cacheHitRate"])))
        return sorted(rows)

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


def _fmt(v) -> str:
    if isinstance(v, float) and v == int(v):
        return str(int(v))
    return str(v)
