"""Collectives-routed distributed execution of Aggregate and Join.

Round-1 ran sharded-table SQL on GSPMD auto-layout of the eager kernels
(implicit all-gathers).  This module is the round-2 engine path: when a plan
node's input bottoms out in a mesh-sharded table, Aggregate and Join lower to
purpose-built `shard_map` kernels that communicate ONLY through explicit XLA
collectives (`all_to_all`), with static capacity-bounded shapes and a
capacity-ladder retry on overflow.

Role parity (reference):
- Aggregate: dask's partial->shuffle->final tree with split_out
  (`/root/reference/dask_sql/physical/rel/logical/aggregate.py:321`) — here a
  local segment pre-aggregation per shard, an `all_to_all` key-routed exchange
  of the bounded partial-group tables, and an owner-side combine.
- Join: dask's tasks-shuffle merge
  (`/root/reference/dask_sql/physical/rel/logical/join.py:241-246`) — here an
  `all_to_all` hash shuffle of (gid, row-id) pairs for both sides and a local
  sort/searchsorted probe per device, materializing (left, right) global row
  index pairs (full row output, not counts).

Aggregation state layout per value column (chunk/agg/finalize triples like the
reference's AGGREGATION_MAPPING, aggregate.py:117-231 there):
  int64 states  (isum, imin, imax)  — exact for BIGINT/timestamps/dict codes
  float64 states (cnt, fsum, fsumsq) — for avg/var/stddev and float sums
Floats are carried through imin/imax via an order-preserving int64 bit trick
so min/max stay exact for every dtype.
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
try:
    from jax import shard_map
except ImportError:  # pre-0.4.x top-level export: experimental namespace
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..columnar.column import Column
from ..columnar.dtypes import SqlType, STRING_TYPES, sql_to_np
from ..resilience.errors import ResourceExhaustedError
from .bootstrap import host_read
from .mesh import AXIS, default_mesh, pad_to_multiple, row_sharding

logger = logging.getLogger(__name__)

I64 = jnp.int64
I64_MIN = np.iinfo(np.int64).min
I64_MAX = np.iinfo(np.int64).max

#: capacity ladders (compile-cache friendly: powers of 4)
GROUP_CAPACITY_LADDER = (1024, 16384, 262144, 1 << 22)
PEER_CAPACITY_LADDER = (2048, 16384, 131072, 1 << 20, 1 << 23)

#: test/observability hooks: counts of kernel executions this process.
#: Fallback/degradation events are NOT counted here anymore — they go to the
#: per-context MetricsRegistry as ``resilience.fallback.*`` so SHOW METRICS
#: and /v1/metrics see them (the old ad-hoc "agg_fallback" key is retained
#: at 0 for callers that snapshot the dict).
STATS = {"agg_kernel": 0, "join_kernel": 0, "agg_fallback": 0,
         "broadcast_join": 0, "broadcast_join_sorted": 0,
         "sharded_join_agg": 0, "sort_kernel": 0}


# ---------------------------------------------------------------------------
# sharding predicates
# ---------------------------------------------------------------------------
def array_is_sharded(arr) -> bool:
    sh = getattr(arr, "sharding", None)
    if sh is None or not isinstance(sh, NamedSharding):
        return False
    try:
        return len(sh.device_set) > 1 and not sh.is_fully_replicated
    except Exception as e:  # dsql: allow-broad-except — deleted buffer /
        # backend teardown mid-query; metric-counted fallback below
        # treated as unsharded (single-program path still computes the right
        # answer) — but say so instead of silently swallowing the probe
        logger.debug("sharding probe failed on %r: %s; treating as "
                     "unsharded", type(arr).__name__, e)
        return False


def table_is_sharded(table) -> bool:
    return any(array_is_sharded(c.data) for c in table.columns.values())


def mesh_for_table(table) -> Optional[Mesh]:
    for c in table.columns.values():
        sh = getattr(c.data, "sharding", None)
        if isinstance(sh, NamedSharding) and len(sh.device_set) > 1:
            return sh.mesh
    return None


def _mode(executor, key: str) -> str:
    return str(executor.config.get(key, "auto")).lower()


def plan_has_sharded_scan(plan, context) -> bool:
    """Cheap pre-check: does this subtree scan a mesh-sharded table?
    (Never touches lazy parquet containers, so no accidental loads.)"""
    from ..datacontainer import LazyParquetContainer
    from ..planner import plan as p

    stack = [plan]
    while stack:
        node = stack.pop()
        if isinstance(node, p.TableScan):
            schema = context.schema.get(node.schema_name)
            dc = schema.tables.get(node.table_name) if schema else None
            if dc is not None and not isinstance(dc, LazyParquetContainer):
                if table_is_sharded(dc.table):
                    return True
        stack.extend(node.inputs())
    return False


def should_distribute(executor, key: str, *tables) -> Optional[Mesh]:
    """Return the mesh to use, or None to keep the single-program path."""
    mode = _mode(executor, key)
    if mode in ("off", "false", "0"):
        return None
    for t in tables:
        m = mesh_for_table(t)
        if m is not None and m.devices.size > 1:
            return m
    if mode in ("on", "force", "true", "1"):
        m = default_mesh()
        return m if m.devices.size > 1 else None
    return None


# ---------------------------------------------------------------------------
# host-side encoding: Column -> int64 key/value arrays (stays sharded; the
# transforms are elementwise so GSPMD keeps the row layout)
# ---------------------------------------------------------------------------
def _float_to_ordered_i64(x: jnp.ndarray) -> jnp.ndarray:
    """Monotone float64 -> int64 (IEEE bit trick); NaNs must be pre-masked."""
    b = jax.lax.bitcast_convert_type(x.astype(jnp.float64), jnp.int64)
    return jnp.where(b >= 0, b, I64_MAX - b)


def _ordered_i64_to_float(o: np.ndarray) -> np.ndarray:
    b = np.where(o >= 0, o, I64_MAX - o).astype(np.int64)
    return b.view(np.float64)


def encode_key_column(col: Column) -> Tuple[List[jnp.ndarray], dict]:
    """Encode a group-key column into int64 key arrays + decode info.

    NULL keys form their own group (dropna=False parity): nullable columns
    contribute an extra null-flag key array.
    """
    info = {"sql_type": col.sql_type, "dictionary": col.dictionary,
            "float": False, "nullable": col.validity is not None}
    data = col.data
    if data.dtype == jnp.bool_:
        enc = data.astype(I64)
    elif jnp.issubdtype(data.dtype, jnp.floating):
        clean = jnp.where(jnp.isnan(data), 0.0, data)
        clean = jnp.where(clean == 0.0, 0.0, clean)  # -0.0 == 0.0 for grouping
        enc = _float_to_ordered_i64(clean)
        info["float"] = True
    else:
        enc = data.astype(I64)
    arrays = []
    if col.validity is not None:
        null = ~col.valid_mask()
        enc = jnp.where(null, 0, enc)
        arrays.append(null.astype(I64))
    arrays.append(enc)
    return arrays, info


def decode_key_outputs(key_arrays: List[np.ndarray], infos: List[dict]) -> List[Column]:
    """Rebuild group-key Columns from the kernel's int64 key outputs."""
    cols = []
    i = 0
    for info in infos:
        if info["nullable"]:
            null = key_arrays[i].astype(bool)
            i += 1
        else:
            null = None
        raw = key_arrays[i]
        i += 1
        st = info["sql_type"]
        if info["float"]:
            data = _ordered_i64_to_float(raw)
        elif st in STRING_TYPES:
            data = raw.astype(np.int32)
        elif st == SqlType.BOOLEAN:
            data = raw.astype(bool)
        else:
            data = raw.astype(sql_to_np(st))
        validity = None if null is None or not null.any() else jnp.asarray(~null)
        cols.append(Column(jnp.asarray(data), st, validity, info["dictionary"]))
    return cols


def encode_value_column(col: Optional[Column]) -> Tuple[jnp.ndarray, jnp.ndarray, dict]:
    """Encode an aggregate input column -> (ivals, fvals, info)."""
    if col is None:  # count_star: constant 1
        raise ValueError("encode_value_column requires a column")
    info = {"sql_type": col.sql_type, "dictionary": col.dictionary, "float": False}
    data = col.data
    if data.dtype == jnp.bool_:
        iv = data.astype(I64)
        fv = data.astype(jnp.float64)
    elif jnp.issubdtype(data.dtype, jnp.floating):
        clean = jnp.where(jnp.isnan(data), 0.0, data.astype(jnp.float64))
        iv = _float_to_ordered_i64(clean)
        fv = clean
        info["float"] = True
    else:
        iv = data.astype(I64)
        fv = data.astype(jnp.float64)
    return iv, fv, info


# ---------------------------------------------------------------------------
# jit building blocks (all static shapes; run inside shard_map)
# ---------------------------------------------------------------------------
def _mix(h: jnp.ndarray) -> jnp.ndarray:
    """splitmix64-style finalizer on int64 (wrapping arithmetic)."""
    h = h * jnp.int64(-7046029254386353131)  # 0x9E3779B97F4A7C15 as signed
    h = h ^ (h >> 33)
    h = h * jnp.int64(-4417276706812531889)  # 0xC2B2AE3D27D4EB4F
    h = h ^ (h >> 29)
    return h


def _hash_keys(keys: Sequence[jnp.ndarray]) -> jnp.ndarray:
    h = jnp.zeros_like(keys[0])
    for k in keys:
        h = _mix(h + k)
    return h


def _lex_groups(keys: Sequence[jnp.ndarray], valid: jnp.ndarray, capacity: int):
    """Sort rows by key tuple (invalid rows last) and produce segment ids.

    Returns (order, seg, sorted_valid, uniq_keys, uniq_valid, overflow).
    """
    n = valid.shape[0]
    inv = (~valid).astype(jnp.int32)
    iota = jnp.arange(n, dtype=I64)
    ops = (inv,) + tuple(keys) + (iota,)
    sorted_ops = jax.lax.sort(ops, num_keys=1 + len(keys))
    order = sorted_ops[-1]
    ks = sorted_ops[1:1 + len(keys)]
    vs = valid[order]
    diff = jnp.zeros(n - 1, dtype=bool) if n > 1 else jnp.zeros(0, dtype=bool)
    for k in ks:
        diff = diff | (k[1:] != k[:-1])
    changed = jnp.concatenate([vs[:1], diff & vs[1:]])
    seg_raw = jnp.cumsum(changed.astype(jnp.int32)) - 1
    n_groups = jnp.max(jnp.where(vs, seg_raw + 1, 0), initial=0)
    overflow = n_groups > capacity
    seg = jnp.where(vs, jnp.clip(seg_raw, 0, capacity - 1), capacity - 1)
    uniq_keys = []
    for k in ks:
        uk = jnp.full((capacity,), I64_MIN, dtype=I64).at[seg].max(
            jnp.where(vs, k, I64_MIN))
        uniq_keys.append(uk)
    uniq_valid = jnp.zeros((capacity,), dtype=bool).at[seg].max(vs)
    # a real group parked in the overflow slot would alias invalid rows;
    # overflow is flagged anyway, so the caller retries with more capacity
    return order, seg, vs, uniq_keys, uniq_valid, overflow


def _bucket_rows(dest: jnp.ndarray, valid: jnp.ndarray, iblock: jnp.ndarray,
                 fblock: jnp.ndarray, ndev: int, C: int):
    """Counting-sort rows into [ndev, C] per-peer buckets for all_to_all.

    iblock [n, ni] int64, fblock [n, nf] float64.  Returns bucketed
    (ikeys [ndev, C, ni], fvals [ndev, C, nf], bvalid [ndev, C], overflow).
    """
    n = dest.shape[0]
    d = jnp.where(valid, dest, ndev).astype(jnp.int32)
    iota = jnp.arange(n, dtype=I64)
    ds, order = jax.lax.sort((d, iota), num_keys=1)
    vs = valid[order]
    ib = iblock[order]
    fb = fblock[order]
    idx = jnp.arange(n)
    start_of_dest = jnp.searchsorted(ds, jnp.arange(ndev + 1, dtype=jnp.int32))
    pos = idx - start_of_dest[jnp.clip(ds, 0, ndev)]
    overflow = jnp.any((pos >= C) & vs)
    ok = vs & (pos < C)
    # rows that don't land (invalid or over-capacity) scatter out-of-bounds so
    # mode="drop" discards the write — a clipped index would nondeterministically
    # clobber a real slot
    flat = jnp.where(ok, ds.astype(I64) * C + pos, ndev * C)
    bi = jnp.zeros((ndev * C, ib.shape[1]), dtype=I64).at[flat].set(
        ib, mode="drop")
    bf = jnp.zeros((ndev * C, fb.shape[1]), dtype=jnp.float64).at[flat].set(
        fb, mode="drop")
    bv = jnp.zeros((ndev * C,), dtype=bool).at[flat].set(ok, mode="drop")
    return (bi.reshape(ndev, C, ib.shape[1]), bf.reshape(ndev, C, fb.shape[1]),
            bv.reshape(ndev, C), overflow)


def _exchange(bi, bf, bv):
    """The collective: per-peer buckets <-> devices over ICI/DCN."""
    ndev, C = bv.shape
    ri = jax.lax.all_to_all(bi[None], AXIS, split_axis=1, concat_axis=1)[0]
    rf = jax.lax.all_to_all(bf[None], AXIS, split_axis=1, concat_axis=1)[0]
    rv = jax.lax.all_to_all(bv[None], AXIS, split_axis=1, concat_axis=1)[0]
    return (ri.reshape(ndev * C, bi.shape[-1]),
            rf.reshape(ndev * C, bf.shape[-1]), rv.reshape(ndev * C))


# ---------------------------------------------------------------------------
# distributed groupby-aggregate kernel
# ---------------------------------------------------------------------------
_AGG_KERNELS: Dict[tuple, object] = {}

N_ISTATE = 3  # isum, imin, imax
N_FSTATE = 3  # cnt, fsum, fsumsq


def _local_states(seg, order, vs, ivals, fvals, vvalid, capacity: int):
    """Per value column: segment-reduce the 6 states over sorted rows."""
    istates, fstates = [], []
    for j in range(ivals.shape[0]):
        w = vvalid[j][order] & vs
        iv = ivals[j][order]
        fv = fvals[j][order]
        isum = jnp.zeros((capacity,), I64).at[seg].add(jnp.where(w, iv, 0))
        imin = jnp.full((capacity,), I64_MAX, I64).at[seg].min(
            jnp.where(w, iv, I64_MAX))
        imax = jnp.full((capacity,), I64_MIN, I64).at[seg].max(
            jnp.where(w, iv, I64_MIN))
        cnt = jnp.zeros((capacity,), jnp.float64).at[seg].add(
            w.astype(jnp.float64))
        fsum = jnp.zeros((capacity,), jnp.float64).at[seg].add(
            jnp.where(w, fv, 0.0))
        fsq = jnp.zeros((capacity,), jnp.float64).at[seg].add(
            jnp.where(w, fv * fv, 0.0))
        istates.append(jnp.stack([isum, imin, imax], axis=-1))
        fstates.append(jnp.stack([cnt, fsum, fsq], axis=-1))
    return jnp.stack(istates), jnp.stack(fstates)  # [nv, capacity, 3]


def _combine_states(seg, order, vs, istates, fstates, capacity: int):
    """Merge received partial states by group (the `agg` stage)."""
    nv = istates.shape[0]
    iout, fout = [], []
    for j in range(nv):
        ist = istates[j][order]
        fst = fstates[j][order]
        isum = jnp.zeros((capacity,), I64).at[seg].add(
            jnp.where(vs, ist[:, 0], 0))
        imin = jnp.full((capacity,), I64_MAX, I64).at[seg].min(
            jnp.where(vs, ist[:, 1], I64_MAX))
        imax = jnp.full((capacity,), I64_MIN, I64).at[seg].max(
            jnp.where(vs, ist[:, 2], I64_MIN))
        cnt = jnp.zeros((capacity,), jnp.float64).at[seg].add(
            jnp.where(vs, fst[:, 0], 0.0))
        fsum = jnp.zeros((capacity,), jnp.float64).at[seg].add(
            jnp.where(vs, fst[:, 1], 0.0))
        fsq = jnp.zeros((capacity,), jnp.float64).at[seg].add(
            jnp.where(vs, fst[:, 2], 0.0))
        iout.append(jnp.stack([isum, imin, imax], axis=-1))
        fout.append(jnp.stack([cnt, fsum, fsq], axis=-1))
    return jnp.stack(iout), jnp.stack(fout)


def get_agg_kernel(mesh: Mesh, nk: int, nv: int, capacity: int, cpeer: int):
    key = (tuple(d.id for d in mesh.devices.flat), nk, nv, capacity, cpeer)
    fn = _AGG_KERNELS.get(key)
    if fn is not None:
        return fn
    ndev = mesh.devices.size

    def per_shard(keys, ivals, fvals, vvalid, rowvalid):
        # keys [nk, n]; ivals/fvals [nv, n]; vvalid [nv, n]; rowvalid [n]
        keys = [keys[i] for i in range(nk)]
        # 1. local pre-aggregation (`chunk`)
        order, seg, vs, uk, uv, of1 = _lex_groups(keys, rowvalid, capacity)
        istates, fstates = _local_states(seg, order, vs, ivals, fvals,
                                         vvalid, capacity)
        # 2. route each partial group row to its owner via all_to_all
        dest = jnp.mod(_hash_keys(uk), ndev)
        iblock = jnp.concatenate(
            [jnp.stack(uk, axis=-1)] +
            [istates[j] for j in range(nv)], axis=-1)  # [cap, nk + nv*3]
        fblock = jnp.concatenate(
            [fstates[j] for j in range(nv)], axis=-1) if nv else \
            jnp.zeros((capacity, 0), jnp.float64)
        bi, bf, bv, of2 = _bucket_rows(dest, uv, iblock, fblock, ndev, cpeer)
        ri, rf, rv = _exchange(bi, bf, bv)
        # 3. owner-side combine (`agg`)
        rkeys = [ri[:, i] for i in range(nk)]
        rist = jnp.stack([ri[:, nk + j * N_ISTATE: nk + (j + 1) * N_ISTATE]
                          for j in range(nv)]) if nv else \
            jnp.zeros((0, ri.shape[0], N_ISTATE), I64)
        rfst = jnp.stack([rf[:, j * N_FSTATE:(j + 1) * N_FSTATE]
                          for j in range(nv)]) if nv else \
            jnp.zeros((0, rf.shape[0], N_FSTATE), jnp.float64)
        order2, seg2, vs2, fk, fv_, of3 = _lex_groups(rkeys, rv, capacity)
        iout, fout = _combine_states(seg2, order2, vs2, rist, rfst, capacity)
        overflow = of1 | of2 | of3
        return (jnp.stack(fk)[None], fv_[None], iout[None], fout[None],
                overflow[None])

    mapped = shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(None, AXIS), P(None, AXIS), P(None, AXIS), P(None, AXIS),
                  P(AXIS)),
        out_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
    )
    fn = jax.jit(mapped)
    _AGG_KERNELS[key] = fn
    return fn


# ---------------------------------------------------------------------------
# distributed join kernel
# ---------------------------------------------------------------------------
_JOIN_KERNELS: Dict[tuple, object] = {}


def get_join_kernel(mesh: Mesh, cpeer: int, out_cap: int):
    key = (tuple(d.id for d in mesh.devices.flat), cpeer, out_cap)
    fn = _JOIN_KERNELS.get(key)
    if fn is not None:
        return fn
    ndev = mesh.devices.size

    def shuffle_side(gid, idx, valid):
        dest = jnp.mod(_mix(gid), ndev)
        iblock = jnp.stack([gid, idx], axis=-1)
        fblock = jnp.zeros((gid.shape[0], 0), jnp.float64)
        bi, bf, bv, of = _bucket_rows(dest, valid, iblock, fblock, ndev, cpeer)
        ri, _, rv = _exchange(bi, bf, bv)
        return ri[:, 0], ri[:, 1], rv, of

    def per_shard(lgid, lidx, lvalid, rgid, ridx, rvalid):
        lk, li_orig, lv, of1 = shuffle_side(lgid, lidx, lvalid)
        rk, ri_orig, rv, of2 = shuffle_side(rgid, ridx, rvalid)
        nrecv = rk.shape[0]
        # local probe: sort right, binary-search left.  Empty right slots get
        # the I64_MIN sentinel (real gids are >= 0 for factorized keys and
        # > I64_MIN+1 for the raw fast path, join_ops._single_key_fast_path)
        rk_s = jnp.where(rv, rk, I64_MIN)
        iota = jnp.arange(nrecv, dtype=I64)
        rs, r_order = jax.lax.sort((rk_s, iota), num_keys=1)
        lk_s = jnp.where(lv, lk, I64_MIN + 1)  # counts also masked by lv
        start = jnp.searchsorted(rs, lk_s, side="left")
        end = jnp.searchsorted(rs, lk_s, side="right")
        counts = jnp.where(lv, end - start, 0)
        ends = jnp.cumsum(counts)
        total = ends[-1] if nrecv else jnp.int64(0)
        # static-shape pair expansion into out_cap slots
        t = jnp.arange(out_cap, dtype=I64)
        i = jnp.searchsorted(ends, t, side="right")
        safe_i = jnp.clip(i, 0, max(nrecv - 1, 0))
        pos = t - (ends[safe_i] - counts[safe_i])
        ovalid = t < total
        out_li = jnp.where(ovalid, li_orig[safe_i], -1)
        rpos = jnp.clip(start[safe_i] + pos, 0, max(nrecv - 1, 0))
        out_ri = jnp.where(ovalid, ri_orig[r_order[rpos]], -1)
        of3 = total > out_cap
        matched = (counts > 0) & lv
        overflow = of1 | of2 | of3
        return (out_li[None], out_ri[None], ovalid[None],
                li_orig[None], matched[None], lv[None],
                total[None], overflow[None])

    mapped = shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(AXIS),) * 6,
        out_specs=(P(AXIS),) * 8,
    )
    fn = jax.jit(mapped)
    _JOIN_KERNELS[key] = fn
    return fn


# ---------------------------------------------------------------------------
# distributed range-partition sort
# ---------------------------------------------------------------------------
_SORT_KERNELS: Dict[tuple, object] = {}


def get_sort_kernel(mesh: Mesh, nk: int, nc: int, cpeer: int, cpeer2: int,
                    rows_out: int):
    """Two-exchange distributed sort (parity: the reference's persist +
    range-shuffle sort_values, reference physical/utils/sort.py:9-87 — here
    sample splitters + all_to_all range partition + local sort + a second
    all_to_all that rebalances to equal-size sorted shards).

    nk encoded i64 sort-key arrays, nc i64 payload arrays, cpeer/cpeer2
    per-peer bucket capacities for the two exchanges, rows_out rows per
    device in the dense output."""
    key = (tuple(d.id for d in mesh.devices.flat), nk, nc, cpeer, cpeer2,
           rows_out)
    fn = _SORT_KERNELS.get(key)
    if fn is not None:
        return fn
    ndev = mesh.devices.size

    def per_shard(keys, payload, rowvalid, splitters):
        # keys [nk, n]; payload [nc, n]; rowvalid [n]; splitters [nk, ndev-1]
        n = rowvalid.shape[0]
        # 1. destination by lexicographic rank among the splitters
        dest = jnp.zeros(n, dtype=jnp.int32)
        for s in range(ndev - 1):
            gt = jnp.zeros(n, dtype=bool)
            eq = jnp.ones(n, dtype=bool)
            for i in range(nk):
                ki = keys[i]
                si = splitters[i, s]
                gt = gt | (eq & (ki > si))
                eq = eq & (ki == si)
            dest = dest + (gt | eq).astype(jnp.int32)  # ties go right
        # 2. exchange rows to their range owner
        iblock = jnp.concatenate(
            [jnp.stack([keys[i] for i in range(nk)], axis=-1),
             jnp.stack([payload[j] for j in range(nc)], axis=-1)], axis=-1)
        fblock = jnp.zeros((n, 0), jnp.float64)
        bi, bf, bv, of1 = _bucket_rows(dest, rowvalid, iblock, fblock, ndev,
                                       cpeer)
        ri, _, rv = _exchange(bi, bf, bv)
        nrecv = rv.shape[0]
        # 3. local sort (invalid rows last)
        inv = (~rv).astype(jnp.int32)
        iota = jnp.arange(nrecv, dtype=I64)
        ops = (inv,) + tuple(ri[:, i] for i in range(nk)) + (iota,)
        order = jax.lax.sort(ops, num_keys=1 + nk)[-1]
        rs = ri[order]
        vs = rv[order]
        # 4. global sorted position: device-prefix offset + local rank
        cnt = jnp.sum(rv.astype(I64))
        counts = jax.lax.all_gather(cnt, AXIS)  # [ndev]
        me = jax.lax.axis_index(AXIS)
        offset = jnp.sum(jnp.where(jnp.arange(ndev) < me, counts, 0))
        pos = offset + jnp.arange(nrecv, dtype=I64)  # valid rows come first
        # 5. rebalance so device d owns rows [d*rows_out, (d+1)*rows_out)
        dest2 = jnp.clip(pos // rows_out, 0, ndev - 1).astype(jnp.int32)
        iblock2 = jnp.concatenate([pos[:, None], rs[:, nk:]], axis=-1)
        bi2, bf2, bv2, of2 = _bucket_rows(
            dest2, vs, iblock2, jnp.zeros((nrecv, 0), jnp.float64),
            ndev, cpeer2)
        ri2, _, rv2 = _exchange(bi2, bf2, bv2)
        # 6. order the received rows by global position, keep rows_out
        n2 = rv2.shape[0]
        ops2 = (jnp.where(rv2, ri2[:, 0], I64_MAX),
                jnp.arange(n2, dtype=I64))
        order2 = jax.lax.sort(ops2, num_keys=1)[-1][:rows_out]
        out = ri2[order2][:, 1:]          # [rows_out, nc]
        return out.T[:, None, :], of1[None], of2[None]

    mapped = shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(None, AXIS), P(None, AXIS), P(AXIS), P(None, None)),
        out_specs=(P(None, AXIS, None), P(AXIS), P(AXIS)),
    )
    fn = jax.jit(mapped)
    _SORT_KERNELS[key] = fn
    return fn


def _ladder_next_or_none(ladder, v):
    """Next rung, or None at the top (caller falls back instead of dying)."""
    try:
        return _ladder_next(ladder, v)
    except ResourceExhaustedError as e:
        logger.debug("capacity ladder topped out at %d: %s", v, e)
        return None


def _encode_sort_key(col: Column, ascending: bool, nulls_first: bool):
    """Column -> list of ascending-order int64 arrays (leading null key when
    nullable).  Dictionary strings must be compact (sorted dict) first.

    MUST stay semantically in lockstep with the single-device
    ops/sorting.py:sort_permutation (NaN sorts as +inf, null-indicator key
    leads, descending = monotone reversal): tests compare the two paths
    row-for-row (tests/integration/test_dist_sort.py)."""
    data = col.data
    if col.sql_type in STRING_TYPES:
        col = col.compact_dictionary()
        data = col.data
    if data.dtype == jnp.bool_:
        enc = data.astype(I64)
    elif jnp.issubdtype(data.dtype, jnp.floating):
        clean = jnp.where(jnp.isnan(data), jnp.inf, data)  # NaN sorts last
        enc = _float_to_ordered_i64(clean)
    else:
        enc = data.astype(I64)
    if not ascending:
        enc = -1 - enc  # monotone reversal, no overflow
    arrays = []
    if col.validity is not None:
        valid = col.valid_mask()
        nullkey = jnp.where(valid, 1, 0) if nulls_first else \
            jnp.where(valid, 0, 1)
        arrays.append(nullkey.astype(I64))
        enc = jnp.where(valid, enc, 0)
    arrays.append(enc)
    return arrays


def _encode_payload(col: Column):
    """Column -> (list of i64 transport arrays, decode(arr_list)->Column)."""
    data = col.data
    sql_type = col.sql_type
    dictionary = col.dictionary
    np_dtype = np.dtype(data.dtype)
    if np_dtype.kind == "f":
        enc = jax.lax.bitcast_convert_type(data.astype(jnp.float64), I64)
    elif np_dtype.kind == "b":
        enc = data.astype(I64)
    else:
        enc = data.astype(I64)
    arrays = [enc]
    nullable = col.validity is not None
    if nullable:
        arrays.append(col.valid_mask().astype(I64))

    def decode(dev_arrays: List[jnp.ndarray], n: int, sharding) -> Column:
        # elementwise device ops, then an explicit row-block re-pin: the
        # sorted table stays sharded on the mesh (device order IS the sort
        # order)
        def place(x):
            if sharding is None:
                return x[:n]
            ndev_ = sharding.mesh.devices.size
            if n % ndev_ == 0:
                # divisible: commit the sliced output to the row sharding
                return jax.jit(lambda a: a[:n], out_shardings=sharding)(x)
            # non-divisible lengths cannot be row-block committed; pin the
            # padded layout and slice (same trade as distribute.shard_table)
            return jax.jit(lambda a: a, out_shardings=sharding)(x)[:n]

        raw = dev_arrays[0]
        if np_dtype.kind == "f":
            vals = jax.lax.bitcast_convert_type(
                raw, jnp.float64).astype(np_dtype)
        elif np_dtype.kind == "b":
            vals = raw.astype(bool)
        else:
            vals = raw.astype(np_dtype)
        vals = place(vals)
        validity = None
        if nullable:
            v = place(dev_arrays[1].astype(bool))
            # scalar reduce on device — never pull the whole mask to host
            if not bool(host_read(jnp.all(v))):
                validity = v
        return Column(vals, sql_type, validity, dictionary)

    return arrays, decode


def dist_sort_table(mesh: Mesh, table, sort_cols: List[Column],
                    ascendings: List[bool], nulls_firsts: List[bool],
                    metrics=None):
    """Sort a mesh-sharded Table globally; output stays row-sharded.

    Sample-based splitters + the two-exchange kernel above.  Returns the
    sorted Table (device order IS the sort order) or None when ineligible
    or when the capacity ladder tops out (recorded in `metrics` as a
    ``resilience.fallback`` so the step-down is observable)."""

    def _fallback(why: str):
        logger.debug("dist sort falling back to single-program path: %s", why)
        if metrics is not None:
            metrics.inc("resilience.fallback")
            metrics.inc("resilience.fallback.dist_sort")
        return None

    n = table.num_rows
    ndev = mesh.devices.size
    if n == 0 or ndev <= 1:
        return None

    key_arrays: List[jnp.ndarray] = []
    for col, asc, nf in zip(sort_cols, ascendings, nulls_firsts):
        key_arrays.extend(_encode_sort_key(col, asc, nf))
    # string sort keys whose dictionaries were re-encoded produce NEW code
    # arrays; the payload still carries the ORIGINAL columns
    payload_arrays: List[jnp.ndarray] = []
    decoders = []
    for name in table.column_names:
        arrs, dec = _encode_payload(table.columns[name])
        payload_arrays.append(arrs)
        decoders.append(dec)
    flat_payload = [a for arrs in payload_arrays for a in arrs]

    nk = len(key_arrays)
    nc = len(flat_payload)

    # placement (pad to ndev multiple)
    def place_stack(arrs):
        padded = [pad_to_multiple(a.astype(I64), ndev)[0] for a in arrs]
        return jax.device_put(jnp.stack(padded),
                              NamedSharding(mesh, P(None, AXIS)))

    keys_mat = place_stack(key_arrays)
    pay_mat = place_stack(flat_payload) if nc else jnp.zeros(
        (0, keys_mat.shape[1]), I64)
    rowvalid = jax.device_put(
        pad_to_multiple(jnp.ones(n, bool), ndev, fill=False)[0],
        row_sharding(mesh))

    # splitters from an evenly-spaced sample (host: tiny)
    ns = min(n, max(ndev * 64, 512))
    sample_idx = np.linspace(0, n - 1, ns).astype(np.int64)
    sample = np.stack([host_read(k[jnp.asarray(sample_idx)])
                       for k in key_arrays])  # [nk, ns]
    order = np.lexsort(sample[::-1])
    qs = sample[:, order][:, np.linspace(0, ns - 1, ndev + 1
                                         ).astype(int)[1:-1]]
    splitters = jnp.asarray(qs.reshape(nk, ndev - 1))

    n_padded = keys_mat.shape[1]
    rows_out = n_padded // ndev
    # a source holds exactly rows_out rows, so no src->peer pair can exceed
    # rows_out in either exchange; a target also receives exactly rows_out
    # rows total in exchange 2.  rows_out + slack is therefore overflow-free
    # by construction (the of1/of2 ladders only matter past the ladder top,
    # where the single-program sort takes over).
    cpeer = _ladder_at_least(PEER_CAPACITY_LADDER, rows_out + 16)
    cpeer2 = cpeer
    for _ in range(10):
        fn = get_sort_kernel(mesh, nk, nc, cpeer, cpeer2, rows_out)
        out, of1, of2 = fn(keys_mat, pay_mat, rowvalid, splitters)
        STATS["sort_kernel"] += 1
        if metrics is not None:
            metrics.inc("parallel.dist.sort_kernel")
        grew = False
        if bool(host_read(of1).any()):
            cpeer = _ladder_next_or_none(PEER_CAPACITY_LADDER, cpeer)
            if cpeer is None:
                return _fallback("exchange-1 capacity ladder exhausted")
            grew = True
        if bool(host_read(of2).any()):
            cpeer2 = _ladder_next_or_none(PEER_CAPACITY_LADDER, cpeer2)
            if cpeer2 is None:
                return _fallback("exchange-2 capacity ladder exhausted")
            grew = True
        if not grew:
            break
    else:
        return _fallback("pathological skew: retries exhausted")

    # out [nc, ndev, rows_out] sharded on the device axis; flatten to global
    # row order and slice the padding off (stays sharded, like shard_table)
    from ..columnar.table import Table as _Table

    cols = {}
    i = 0
    flat = out.reshape(nc, n_padded) if nc else out
    sh = row_sharding(mesh)
    for name, arrs, dec in zip(table.column_names, payload_arrays, decoders):
        k = len(arrs)
        cols[name] = dec([flat[i + j] for j in range(k)], n, sh)
        i += k
    return _Table(cols, n)
def _place_rows(arr: jnp.ndarray, mesh: Mesh, fill=0):
    """Pad to a multiple of ndev and row-shard; returns (placed, valid)."""
    ndev = mesh.devices.size
    padded, valid = pad_to_multiple(arr, ndev, fill=fill)
    sh = row_sharding(mesh)
    return jax.device_put(padded, sh), jax.device_put(valid, sh)


def broadcast_inner_pairs(big_gid, big_valid, small_gid, small_valid):
    """Broadcast-join matching: the small side stays replicated, the big
    side is NEVER shuffled (parity: reference join.py:228-246 small-side
    broadcast merge under `sql.join.broadcast` — which broadcasts ANY small
    table, so this must too).

    Fast path: unique-dense-int small keys get a value-indexed LUT — one
    scatter + gather at HBM bandwidth.  General path (string-keyed,
    non-unique, sparse): sort the replicated small side once, probe with two
    searchsorteds per shard — still no collectives, no big-side shuffle.
    Pair compaction happens on host after one packed read (multi-host safe:
    the probe output is what the caller materializes anyway).  Returns
    (big_idx, small_idx, big_matched); never declines a small build side."""
    from ..ops.join import dense_unique_lut

    sv = None if bool(small_valid.all()) else small_valid
    prep = dense_unique_lut(small_gid, sv)
    if prep is None:
        return _broadcast_sorted_pairs(big_gid, big_valid,
                                       small_gid, small_valid)
    rmin, lut = prep
    size = lut.shape[0]
    idx = big_gid.astype(I64) - rmin
    inb = (idx >= 0) & (idx < size) & big_valid
    safe = jnp.clip(idx, 0, size - 1).astype(jnp.int32)
    cand = jnp.where(inb, lut[safe].astype(jnp.int64), jnp.int64(-1))
    STATS["broadcast_join"] += 1
    cand_h = host_read(cand)
    matched = cand_h >= 0
    bi = np.nonzero(matched)[0].astype(np.int64)
    si = cand_h[bi]
    return jnp.asarray(bi), jnp.asarray(si), matched


@jax.jit
def _sorted_probe(big_gid, big_valid, small_gid, small_valid):
    """Replicated-build probe for arbitrary keys: NULL build rows sort to
    the end (valid-first lexsort, so no sentinel value can collide with a
    real key — int64.max is a legal BIGINT) and the match range is clamped
    to the valid prefix, so NULL rows can never match."""
    sg = small_gid.astype(I64)
    # primary: valid first; secondary: key — the valid prefix is key-sorted
    order = jnp.lexsort((sg, ~small_valid))
    n_valid = jnp.sum(small_valid.astype(jnp.int64))
    iota = jnp.arange(sg.shape[0], dtype=jnp.int64)
    # suffix (invalid rows) holds arbitrary key values after the gather —
    # overwrite with +inf so the array is globally sorted for binary search
    sg_sorted = jnp.where(iota < n_valid, sg[order],
                          jnp.iinfo(jnp.int64).max)
    bg = big_gid.astype(I64)
    start = jnp.minimum(jnp.searchsorted(sg_sorted, bg, side="left"), n_valid)
    end = jnp.minimum(jnp.searchsorted(sg_sorted, bg, side="right"), n_valid)
    counts = jnp.where(big_valid, jnp.maximum(end - start, 0), 0)
    return jnp.stack([start.astype(I64), counts.astype(I64)]), order


def _broadcast_sorted_pairs(big_gid, big_valid, small_gid, small_valid):
    ns = int(small_gid.shape[0])
    nb = int(big_gid.shape[0])
    STATS["broadcast_join"] += 1
    STATS["broadcast_join_sorted"] += 1
    if ns == 0 or nb == 0:
        empty = jnp.zeros(0, dtype=I64)
        return empty, empty, np.zeros(nb, dtype=bool)
    packed, order = _sorted_probe(big_gid, big_valid, small_gid, small_valid)
    packed_h = host_read(packed)  # one transfer for both per-row arrays
    order_h = host_read(order)  # replicated small side: tiny
    start_h, counts_h = packed_h[0], packed_h[1]
    matched = counts_h > 0
    total = int(counts_h.sum())
    bi = np.repeat(np.arange(nb, dtype=np.int64), counts_h)
    offsets = np.cumsum(counts_h) - counts_h
    within = np.arange(total, dtype=np.int64) - np.repeat(offsets, counts_h)
    si = order_h[np.repeat(start_h, counts_h) + within].astype(np.int64)
    return jnp.asarray(bi), jnp.asarray(si), matched


def dist_inner_pairs(mesh: Mesh, lgid: jnp.ndarray, lvalid: jnp.ndarray,
                     rgid: jnp.ndarray, rvalid: jnp.ndarray):
    """Distributed equijoin matching: (li, ri) global row-index pairs.

    Shuffles both sides' (gid, row-id) with all_to_all, probes per device,
    and returns host int64 arrays of matching row indices (left-major within
    each device partition).  Also returns (l_matched bool[n_l]) for
    semi/anti/outer handling.
    """
    nl, nr = int(lgid.shape[0]), int(rgid.shape[0])
    ndev = mesh.devices.size
    lg, lrow = _place_rows(lgid.astype(I64), mesh)
    rg, rrow = _place_rows(rgid.astype(I64), mesh)
    lidx = jax.device_put(jnp.arange(lg.shape[0], dtype=I64), row_sharding(mesh))
    ridx = jax.device_put(jnp.arange(rg.shape[0], dtype=I64), row_sharding(mesh))
    lval, _ = _place_rows(lvalid & jnp.ones(nl, bool), mesh, fill=False)
    rval, _ = _place_rows(rvalid & jnp.ones(nr, bool), mesh, fill=False)
    lval = lval & lrow
    rval = rval & rrow

    per_shard_rows = max(lg.shape[0], rg.shape[0]) // ndev
    # uniform-hash expectation + slack; skew is caught by the overflow retry
    cpeer = _ladder_at_least(PEER_CAPACITY_LADDER,
                             2 * per_shard_rows // ndev + 256)
    out_cap = _ladder_at_least(PEER_CAPACITY_LADDER, 2 * per_shard_rows + 256)
    for _ in range(8):
        fn = get_join_kernel(mesh, cpeer, out_cap)
        (li, ri, ovalid, lorig, matched, lrecv_valid, totals,
         overflow) = fn(lg, lidx, lval, rg, ridx, rval)
        STATS["join_kernel"] += 1
        if not bool(np.asarray(overflow).any()):
            break
        # distinguish shuffle vs output overflow: grow both (cheap ladder)
        cpeer = _ladder_next(PEER_CAPACITY_LADDER, cpeer)
        out_cap = _ladder_next(PEER_CAPACITY_LADDER, out_cap)
    else:
        raise ResourceExhaustedError(
            "distributed join exceeded capacity ladder")

    ov = np.asarray(ovalid).reshape(-1)
    li_h = np.asarray(li).reshape(-1)[ov]
    ri_h = np.asarray(ri).reshape(-1)[ov]
    lmatch = np.zeros(nl, dtype=bool)
    lo = np.asarray(lorig).reshape(-1)
    mt = np.asarray(matched).reshape(-1) & np.asarray(lrecv_valid).reshape(-1)
    valid_rows = lo[mt]
    lmatch[valid_rows[valid_rows < nl]] = True
    return jnp.asarray(li_h), jnp.asarray(ri_h), lmatch


def _ladder_at_least(ladder, n):
    for v in ladder:
        if v >= n:
            return v
    return ladder[-1]


def _ladder_next(ladder, cur):
    for v in ladder:
        if v > cur:
            return v
    # taxonomy-degradable: the resilience ladder (resilience/ladder.py)
    # catches this and steps the query down to the single-program path
    raise ResourceExhaustedError(
        f"capacity ladder exhausted at {cur} (top {ladder[-1]})")


# ---------------------------------------------------------------------------
# SQL integration: Aggregate
# ---------------------------------------------------------------------------
#: aggregates decomposable into the 6-state layout
_DECOMPOSABLE = {
    "count", "count_star", "sum", "min", "max", "avg",
    "var_samp", "var_pop", "stddev_samp", "stddev_pop",
    "every", "bool_or", "single_value", "first_value",
    "regr_count", "regr_syy", "regr_sxx",
}


def try_dist_aggregate(rel, executor, inp) -> Optional[object]:
    """Lower a groupby-aggregate over a sharded input through the
    collectives kernel; None falls back to the single-program path."""
    from ..columnar.table import Table

    mesh = should_distribute(executor, "sql.distributed.aggregate", inp)
    if mesh is None:
        return None
    if not rel.group_exprs or inp.num_rows == 0:
        return None  # global aggregates reduce fine under GSPMD psum
    for agg in rel.agg_exprs:
        if agg.func not in _DECOMPOSABLE or agg.distinct:
            logger.debug("dist aggregate declining %s%s: single-program "
                         "path", agg.func, " DISTINCT" if agg.distinct else "")
            executor.context.metrics.inc("resilience.fallback")
            executor.context.metrics.inc("resilience.fallback.dist_aggregate")
            return None

    group_cols = [executor.eval_expr(e, inp) for e in rel.group_exprs]
    key_arrays: List[jnp.ndarray] = []
    key_infos: List[dict] = []
    for col in group_cols:
        if col.sql_type in STRING_TYPES and col.dictionary is None:
            return None
        arrs, info = encode_key_column(col)
        key_arrays.extend(arrs)
        key_infos.append(info)

    # one value slot per aggregate (keeps filter/arg pairing trivial)
    n = inp.num_rows
    ivals, fvals, vvalids, val_infos = [], [], [], []
    for agg in rel.agg_exprs:
        fmask = None
        if agg.filter is not None:
            fc = executor.eval_expr(agg.filter, inp)
            fmask = fc.data & fc.valid_mask()
        if agg.func == "count_star":
            iv = jnp.ones(n, I64)
            fv = jnp.ones(n, jnp.float64)
            valid = jnp.ones(n, bool)
            info = {"sql_type": SqlType.BIGINT, "dictionary": None,
                    "float": False}
        else:
            args = [executor.eval_expr(a, inp) for a in agg.args]
            col = args[0]
            if col.sql_type in STRING_TYPES:
                if col.dictionary is None:
                    return None
                col = col.compact_dictionary()
            valid = col.valid_mask()
            if jnp.issubdtype(col.data.dtype, jnp.floating):
                valid = valid & ~jnp.isnan(col.data)
            if agg.func in ("regr_count", "regr_syy", "regr_sxx"):
                if len(args) < 2:
                    return None
                y, x = args[0], args[1]
                valid = y.valid_mask() & x.valid_mask()
                col = {"regr_count": y, "regr_syy": y, "regr_sxx": x}[agg.func]
                if col.sql_type in STRING_TYPES:
                    return None
            iv, fv, info = encode_value_column(col)
        if fmask is not None:
            valid = valid & fmask
        ivals.append(iv)
        fvals.append(fv)
        vvalids.append(valid)
        val_infos.append(info)

    nv = len(rel.agg_exprs)
    nk = len(key_arrays)
    if nv == 0:
        # pure GROUP BY (distinct keys): one count_star slot keeps shapes sane
        ivals = [jnp.ones(n, I64)]
        fvals = [jnp.ones(n, jnp.float64)]
        vvalids = [jnp.ones(n, bool)]
        val_infos = [{"sql_type": SqlType.BIGINT, "dictionary": None,
                      "float": False}]
        nv = 1

    # pad + place (row-sharded over the mesh)
    ndev = mesh.devices.size
    sh = row_sharding(mesh)
    col_sh = NamedSharding(mesh, P(None, AXIS))

    def place_stack(arrs, dtype):
        padded = [pad_to_multiple(a.astype(dtype), ndev)[0] for a in arrs]
        return jax.device_put(jnp.stack(padded), col_sh)

    keys_mat = place_stack(key_arrays, I64)
    ivals_mat = place_stack(ivals, I64)
    fvals_mat = place_stack(fvals, jnp.float64)
    vvalid_mat = place_stack(vvalids, jnp.bool_)
    rowvalid = jax.device_put(
        pad_to_multiple(jnp.ones(n, bool), ndev, fill=False)[0], sh)

    cap = _ladder_at_least(GROUP_CAPACITY_LADDER, 0)
    for _ in range(8):
        cpeer = _ladder_at_least(PEER_CAPACITY_LADDER,
                                 min(2 * cap // ndev + 256, cap))
        fn = get_agg_kernel(mesh, nk, nv, cap, cpeer)
        fk, fv_, iout, fout, overflow = fn(keys_mat, ivals_mat, fvals_mat,
                                           vvalid_mat, rowvalid)
        STATS["agg_kernel"] += 1
        executor.context.metrics.inc("parallel.dist.agg_kernel")
        if not bool(host_read(overflow).any()):
            break
        cap = _ladder_next(GROUP_CAPACITY_LADDER, cap)
    else:
        raise ResourceExhaustedError(
            "distributed aggregate exceeded capacity ladder")

    # host finalize: concat per-device owned tables (keys are disjoint);
    # host_read all-gathers first when the mesh spans processes
    fk_h = host_read(fk)             # [ndev, nk, cap]
    fv_h = host_read(fv_).reshape(-1)             # [ndev*cap]
    iout_h = host_read(iout)         # [ndev, nv, cap, 3]
    fout_h = host_read(fout)
    keys_flat = [fk_h[:, i, :].reshape(-1) for i in range(nk)]
    sel = fv_h
    key_cols = decode_key_outputs([k[sel] for k in keys_flat], key_infos)
    ngroups = int(sel.sum())

    from ..physical.rel.base import unique_names
    names = unique_names([f.name for f in rel.schema])
    out: Dict[str, Column] = {}
    for name, col in zip(names, key_cols):
        out[name] = col

    agg_names = names[len(group_cols):]
    for j, (name, agg) in enumerate(zip(agg_names, rel.agg_exprs)):
        ist = iout_h[:, j, :, :].reshape(-1, N_ISTATE)[sel]
        fst = fout_h[:, j, :, :].reshape(-1, N_FSTATE)[sel]
        out[name] = _finalize_agg(agg, val_infos[j], ist, fst)
    return Table(out, ngroups)


def _finalize_agg(agg, info: dict, ist: np.ndarray, fst: np.ndarray) -> Column:
    """states -> final aggregate Column (the `finalize` stage)."""
    isum, imin, imax = ist[:, 0], ist[:, 1], ist[:, 2]
    cnt, fsum, fsq = fst[:, 0], fst[:, 1], fst[:, 2]
    func = agg.func
    nonempty = cnt > 0
    st = agg.sql_type

    def mk(vals, ok=None, dictionary=None, np_dtype=None):
        dtype = np_dtype or sql_to_np(st)
        arr = np.asarray(vals).astype(dtype)
        validity = None if ok is None or ok.all() else jnp.asarray(ok)
        return Column(jnp.asarray(arr), st, validity, dictionary)

    if func in ("count", "count_star", "regr_count"):
        return mk(cnt.astype(np.int64))
    if func == "sum":
        if info["float"]:
            return mk(fsum, nonempty)
        return mk(isum, nonempty)
    if func in ("min", "max"):
        raw = imin if func == "min" else imax
        if info["float"]:
            return mk(_ordered_i64_to_float(raw), nonempty)
        if info["sql_type"] in STRING_TYPES:
            return mk(raw.astype(np.int32), nonempty,
                      dictionary=info["dictionary"], np_dtype=np.int32)
        return mk(raw, nonempty)
    if func == "avg":
        return mk(fsum / np.maximum(cnt, 1), nonempty, np_dtype=np.float64)
    if func in ("var_samp", "var_pop", "stddev_samp", "stddev_pop"):
        mean = fsum / np.maximum(cnt, 1)
        m2 = np.maximum(fsq - cnt * mean * mean, 0.0)
        ddof = 1 if func.endswith("samp") else 0
        denom = np.maximum(cnt - ddof, 1)
        v = m2 / denom
        if func.startswith("stddev"):
            v = np.sqrt(v)
        ok = cnt > ddof
        return mk(v, ok, np_dtype=np.float64)
    if func == "every":
        return mk(np.where(nonempty, imin, 0).astype(bool), nonempty,
                  np_dtype=np.bool_)
    if func == "bool_or":
        return mk(np.where(nonempty, imax, 0).astype(bool), nonempty,
                  np_dtype=np.bool_)
    if func in ("single_value", "first_value"):
        raw = imin
        if info["float"]:
            return mk(_ordered_i64_to_float(raw), nonempty)
        if info["sql_type"] in STRING_TYPES:
            return mk(raw.astype(np.int32), nonempty,
                      dictionary=info["dictionary"], np_dtype=np.int32)
        return mk(raw, nonempty)
    if func in ("regr_syy", "regr_sxx"):
        mean = fsum / np.maximum(cnt, 1)
        m2 = np.maximum(fsq - cnt * mean * mean, 0.0)
        return mk(m2, nonempty, np_dtype=np.float64)
    raise NotImplementedError(f"distributed finalize for {func}")
