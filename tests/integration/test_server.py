"""Presto server tests (parity: reference test_server.py — exercised through
HTTP against a background server thread, no external deps)."""
import json
import time
import urllib.request

import pandas as pd
import pytest


@pytest.fixture
def server(c):
    from dask_sql_tpu.server.app import run_server

    srv = run_server(context=c, host="127.0.0.1", port=0, blocking=False)
    yield srv
    srv.shutdown()


def _post(port, sql):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/statement", data=sql.encode(), method="POST")
    with urllib.request.urlopen(req) as resp:
        return json.loads(resp.read())


def _follow(port, payload, timeout=30):
    deadline = time.time() + timeout
    while "nextUri" in payload and time.time() < deadline:
        time.sleep(0.05)
        with urllib.request.urlopen(payload["nextUri"]) as resp:
            payload = json.loads(resp.read())
        if payload.get("stats", {}).get("state") == "RUNNING":
            payload["nextUri"] = payload.get("nextUri",
                f"http://127.0.0.1:{port}/v1/statement/{payload['id']}")
    return payload


def test_server_select(server):
    port = server.port
    payload = _post(port, "SELECT 1 + 1 AS x")
    payload = _follow(port, payload)
    assert payload["stats"]["state"] == "FINISHED"
    assert payload["columns"][0]["name"] == "x"
    assert payload["data"][0][0] == 2


def test_server_query_table(server):
    port = server.port
    payload = _follow(port, _post(port, "SELECT a FROM df_simple ORDER BY a"))
    assert [row[0] for row in payload["data"]] == [1, 2, 3]


def test_server_error(server):
    port = server.port
    payload = _follow(port, _post(port, "SELECT FROM WHERE"))
    assert "error" in payload


def test_server_empty(server):
    port = server.port
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/v1/empty") as resp:
        payload = json.loads(resp.read())
    assert payload["data"] == []


def test_server_jdbc_metadata(c):
    from dask_sql_tpu.server.app import run_server
    from dask_sql_tpu.server.presto_jdbc import SYSTEM_SCHEMA

    srv = run_server(context=c, host="127.0.0.1", port=0, blocking=False,
                     jdbc_metadata=True)
    try:
        assert SYSTEM_SCHEMA in c.schema
        port = srv.port
        payload = _follow(port, _post(
            port, "SELECT * FROM system.jdbc.tables"))  # driver-style path
        cols = [col["name"] for col in payload["columns"]]
        name_idx = cols.index("TABLE_NAME")
        names = [row[name_idx] for row in payload["data"]]
        assert "df_simple" in names
    finally:
        srv.shutdown()


def test_server_concurrent_queries(server):
    import concurrent.futures

    port = server.port

    def run(i):
        payload = _follow(port, _post(port, f"SELECT {i} * a AS v FROM df_simple ORDER BY v"))
        return [row[0] for row in payload["data"]]

    with concurrent.futures.ThreadPoolExecutor(max_workers=6) as pool:
        results = list(pool.map(run, range(1, 7)))
    for i, vals in enumerate(results, start=1):
        assert vals == [i * 1, i * 2, i * 3]


def test_visualize_text_fallback_without_renderer(c, tmp_path, monkeypatch):
    """Without matplotlib the named API still produces a plan artifact."""
    import dask_sql_tpu.context as ctx_mod

    def boom(plan, filename):
        raise ImportError("no renderer")

    monkeypatch.setattr(ctx_mod.Context, "_render_plan_png",
                        staticmethod(boom))
    path = str(tmp_path / "plan.png")
    c.visualize("SELECT a FROM df_simple WHERE a > 1", filename=path)
    import os

    assert os.path.exists(path + ".txt")
    with open(path + ".txt") as f:
        assert "TableScan" in f.read()


def test_visualize_writes_plan_image(c, tmp_path):
    pytest.importorskip("matplotlib", reason="plan rendering needs matplotlib")
    path = str(tmp_path / "plan.png")
    c.visualize("SELECT a FROM df_simple WHERE a > 1", filename=path)
    import os

    assert os.path.exists(path), "visualize must render an image file"
    with open(path, "rb") as f:
        assert f.read(8).startswith(b"\x89PNG"), "output must be a real png"


def test_visualize_join_plan_image(c, tmp_path):
    pytest.importorskip("matplotlib", reason="plan rendering needs matplotlib")
    path = str(tmp_path / "join_plan.png")
    c.visualize(
        "SELECT lhs.user_id FROM user_table_1 lhs JOIN user_table_2 rhs "
        "ON lhs.user_id = rhs.user_id WHERE lhs.b > 1", filename=path)
    import os

    assert os.path.exists(path) and os.path.getsize(path) > 1000


def test_server_concurrent_queries_overlap(server):
    """Two concurrent queries must finish in < 2x one query's wall time:
    host-side plan/decode of one overlaps device compute of the other
    (VERDICT r4 #8; reference overlaps via distributed futures, app.py:89)."""
    import concurrent.futures
    import numpy as np

    port = server.port
    n = 6_000_000
    rng = np.random.RandomState(0)
    server.context.create_table("big_overlap", pd.DataFrame({
        "g": rng.randint(0, 100, n), "x": rng.rand(n)}))
    sql = "SELECT g, SUM(x) AS s, COUNT(*) AS n FROM big_overlap GROUP BY g"

    def run(_=None):
        payload = _follow(port, _post(port, sql), timeout=120)
        assert payload["stats"]["state"] == "FINISHED", payload
        return payload

    run(0)  # warm-up: compile + plan cache
    # best-of-N on both sides so a noisy-neighbor blip can't flip the verdict
    t_single = min(_timed(run) for _ in range(3))

    def pair():
        with concurrent.futures.ThreadPoolExecutor(max_workers=2) as pool:
            list(pool.map(run, range(2)))

    t_pair = min(_timed(pair) for _ in range(3))
    # a fully serialized server lands at ~2.0x; require real overlap
    assert t_pair < 1.8 * t_single + 0.1, (
        f"two concurrent queries took {t_pair:.3f}s vs single {t_single:.3f}s "
        "— no overlap between host work and device compute")


def _timed(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def test_server_metrics_endpoint(server):
    port = server.port
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/v1/metrics") as resp:
        before = json.loads(resp.read())
    for key in ("workers", "queueDepth", "running", "completed", "failed",
                "cancelled", "avgLatencyMillis", "avgQueuedMillis"):
        assert key in before, key
    _follow(port, _post(port, "SELECT 41 + 1 AS x"))
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/v1/metrics") as resp:
        after = json.loads(resp.read())
    assert after["completed"] >= before["completed"] + 1
    assert after["queueDepth"] == 0 and after["running"] == 0


def test_server_status_reports_real_times(server):
    port = server.port
    payload = _follow(port, _post(port, "SELECT 1 + 1 AS x"))
    stats = payload["stats"]
    assert stats["state"] == "FINISHED"
    assert stats["elapsedTimeMillis"] >= 0
    assert stats["queuedTimeMillis"] >= 0
    assert stats["elapsedTimeMillis"] >= stats["queuedTimeMillis"]
