"""ColumnContainer/DataContainer semantics (parity: reference
tests/unit/test_datacontainer.py)."""
import numpy as np
import pandas as pd
import pytest


def _table():
    from dask_sql_tpu.columnar import Table

    return Table.from_pandas(pd.DataFrame({"a": [1, 2], "b": [3.0, 4.0], "c": ["x", "y"]}))


def test_column_container_rename_no_copy():
    from dask_sql_tpu.datacontainer import ColumnContainer

    cc = ColumnContainer(["a", "b", "c"])
    cc2 = cc.rename({"a": "x"})
    assert cc2.columns == ["x", "b", "c"]
    assert cc2.get_backend_by_frontend_name("x") == "a"
    assert cc.columns == ["a", "b", "c"]  # original untouched


def test_column_container_limit_to():
    from dask_sql_tpu.datacontainer import ColumnContainer

    cc = ColumnContainer(["a", "b", "c"]).limit_to(["c", "a"])
    assert cc.columns == ["c", "a"]
    assert cc.get_backend_by_frontend_index(0) == "c"


def test_column_container_add_and_unique():
    from dask_sql_tpu.datacontainer import ColumnContainer

    cc = ColumnContainer(["a"]).add("d", "a")
    assert cc.columns == ["a", "d"]
    assert cc.get_backend_by_frontend_name("d") == "a"
    uniq = cc.make_unique()
    assert uniq.columns == ["col_0", "col_1"]


def test_data_container_assign():
    from dask_sql_tpu.datacontainer import ColumnContainer, DataContainer

    t = _table()
    cc = ColumnContainer(["b", "a"], {"b": "b", "a": "a"})
    dc = DataContainer(t, cc)
    out = dc.assign()
    assert out.column_names == ["b", "a"]
    assert list(out.to_pandas()["a"]) == [1, 2]


def test_statistics():
    from dask_sql_tpu.datacontainer import Statistics

    s = Statistics(100.0)
    assert s.row_count == 100.0


def test_pluggable():
    from dask_sql_tpu.utils import Pluggable

    class MyRegistry(Pluggable):
        pass

    MyRegistry.add_plugin("x", 1)
    assert MyRegistry.get_plugin("x") == 1
    MyRegistry.add_plugin("x", 2, replace=False)
    assert MyRegistry.get_plugin("x") == 1
    MyRegistry.add_plugin("x", 2)
    assert MyRegistry.get_plugin("x") == 2
