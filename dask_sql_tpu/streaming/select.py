"""streamed_select: chunked root select chains, survivors in row order.

Reuses `CompiledSelect` (physical/compiled_select.py) wholesale: ONE
object is built against the full table (so string dictionaries and
parameter slots are table-global), and each partition launch runs its
mask + per-pow2-bucket gather kernels over a fixed-shape chunk — jit
specializes once per chunk shape, so N launches share the executables and
the second streamed run of the family pays zero foreground compiles (the
same per-bucket re-specialization budget the SPMD select rung accepts).

Survivor tables land host-side per chunk and concatenate in ascending
chunk order; within a chunk the sized-nonzero gather already yields
ascending row indices, so the concatenation IS the global row order the
unconstrained single-launch path produces.  Sort/limit windows are global
row properties a chunk cannot see — plans carrying them are never routed
here (streaming/plan.py declines them at decision time).
"""
from __future__ import annotations

import logging
from collections import OrderedDict
from typing import List, Optional, Tuple

from ..columnar.table import Table
from ..observability import trace_event
from ..physical.compiled import _Unsupported, singleflight_get_or_build
from ..physical.compiled_select import CompiledSelect, _extract
from .partition import slice_chunk
from .plan import StreamDecision
from .runner import drive_partitions

logger = logging.getLogger(__name__)


class _StreamableSelect(CompiledSelect):
    """CompiledSelect with PER-SHAPE mask-kernel warm tracking.

    The parent's ``_mask_warm`` is a single boolean — correct for its own
    rung, where one object only ever sees one table shape.  Streamed
    execution feeds the same object different chunk shapes after a
    mid-stream repartition; the recompile for the new shape must run with
    ``may_compile=True`` so the compile watchdog
    (``resilience.compile_timeout_ms``) covers exactly the OOM-recovery
    path (the aggregate rung's ``_warm_shapes`` set, mirrored here).  The
    hint is computed LOCALLY per call, never by mutating a shared flag —
    cached objects serve concurrent worker threads, and a write/read dance
    on shared state would let one thread's warm shape mark another
    thread's cold compile unwatched."""

    _RUNG = "streamed_select"  # compiles attribute to THIS rung's metrics

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._warm_shapes: set = set()

    def run(self, table=None, params=()):
        from ..observability import timed_jit_call
        from ..utils import count_d2h

        t = table if table is not None else self.table
        shape = t.padded_rows
        datas = tuple(t.columns[n].data for n in t.column_names)
        valids = tuple(t.columns[n].validity for n in t.column_names)
        mask, count_dev = timed_jit_call(
            self._RUNG, self._mask_fn, datas, valids, t.row_valid,
            tuple(params), may_compile=shape not in self._warm_shapes)
        self._warm_shapes.add(shape)
        count_d2h()
        return self._finish(datas, valids, mask, int(count_dev),
                            tuple(params))


_CACHE_CAP = 8
_cache: "OrderedDict[Tuple, CompiledSelect]" = OrderedDict()


def reset_cache() -> None:
    """Tests: drop cached streamed select executables."""
    _cache.clear()


def try_streamed_select(root, executor) -> Optional[Table]:
    """The streamed_select ladder rung (physical/executor.py execute_root):
    fires only for plans the admission layer routed to streaming (this
    execution's ``executor.stream_decisions`` entry); None declines down
    the ladder."""
    decision: Optional[StreamDecision] = \
        executor.stream_decisions.get(id(root))
    if decision is None or decision.kind != "select":
        return None
    config = executor.config
    if not config.get("serving.stream.enabled", True):
        return None
    if not config.get("sql.compile", True) \
            or not config.get("sql.compile.select", True):
        return None
    got = _extract(root)
    if got is None:
        return None
    scan, upper_filters, proj, sort_keys, sort_fetch, limit, inner_limit = got
    if sort_keys is not None or limit is not None or inner_limit is not None:
        return None  # global row windows: not a chunk-local shape
    ctx = executor.context
    # -- eligibility + executable build: construction-time ineligibility
    # re-sheds with the gate's 429 (see streaming/aggregate.py) ----------
    try:
        dc = ctx.schema[scan.schema_name].tables.get(scan.table_name)
        if dc is None:
            return None
        table = executor.get_table(scan.schema_name, scan.table_name)
        if scan.projection is not None:
            table = table.select(scan.projection)
        if not table.column_names or table.row_valid is not None:
            return None
        from .. import families

        pz = families.pipeline_parameterizer(config)
        p_upper = [pz.rewrite(f) for f in upper_filters]
        p_scan_flts = [pz.rewrite(f) for f in scan.filters]
        p_exprs = [pz.rewrite(e) for e in proj.exprs]
        params = pz.params
        key = (
            "streamed_select",
            dc.uid,
            tuple(scan.projection or ()),
            tuple(str(f) for f in p_upper),
            tuple(str(f) for f in p_scan_flts),
            tuple(str(e) for e in p_exprs),
            table.num_rows,
        )

        def build():
            obj = _StreamableSelect(table, scan, p_upper, p_scan_flts, proj,
                                    p_exprs, None, None, None, None, params)
            obj.table = None
            with ctx._plan_lock:
                _cache[key] = obj
                while len(_cache) > _CACHE_CAP:
                    _cache.popitem(last=False)
            return obj

        compiled, built_here = singleflight_get_or_build(ctx, _cache, key,
                                                         build)
    except (_Unsupported, ValueError, TypeError, NotImplementedError) as e:
        from .plan import shed_ineligible

        shed_ineligible(decision, ctx.metrics, reason=str(e))
        raise  # unreachable: shed_ineligible always raises
    if compiled is None:
        return None
    if not built_here and params:
        ctx.metrics.inc("families.hit")
        trace_event("family_hit", rung="streamed_select",
                    params=len(params))
    ctx.metrics.inc("serving.stream.queries")
    # -- pipelined partition drive (ladder semantics preserved) -----------
    parts: List[Table] = []

    def launch(lo: int, chunk_rows: int) -> None:
        chunk = slice_chunk(table, lo, chunk_rows)
        out = compiled.run(chunk, params)
        if out.num_rows:
            parts.append(out)

    launches = drive_partitions(executor, decision, launch,
                                "streamed_select")
    trace_event("rung:streamed_select", rung="streamed_select",
                partitions=launches, chunk_rows=decision.chunk_rows)
    if not parts:
        return _empty_like(compiled)
    return Table.concat(parts)


def _empty_like(compiled: CompiledSelect) -> Table:
    """Zero-survivor result with the pipeline's output schema."""
    cols, valids = compiled._decode_packed(None, 0)
    return compiled._assemble(cols, valids, 0)
