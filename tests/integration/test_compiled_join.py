"""Compiled join->aggregate pipeline (physical/compiled_join.py).

Parity role: the reference's merge->aggregate graphs (join.py:241-246,
aggregate.py:321 there); here the whole probe side fuses into one jit when
build keys are unique dense ints.  These tests pin BOTH the mechanism (the
pipeline actually fires) and the values (against pandas), including its
decline-and-fall-back behavior.
"""
import numpy as np
import pandas as pd
import pytest

import dask_sql_tpu.physical.compiled_join as cj


@pytest.fixture
def spy(monkeypatch):
    hits = []
    orig = cj.CompiledJoinAggregate.run

    def wrapper(self, params=()):
        hits.append(self)
        return orig(self, params)

    monkeypatch.setattr(cj.CompiledJoinAggregate, "run", wrapper)
    return hits


@pytest.fixture
def star(spy):
    from dask_sql_tpu import Context

    rng = np.random.RandomState(3)
    n = 5000
    fact = pd.DataFrame({
        "f_dim1": rng.randint(0, 100, n),
        "f_dim2": rng.randint(1000, 1050, n),
        "f_val": rng.rand(n) * 100,
        "f_qty": rng.randint(1, 10, n),
    })
    dim1 = pd.DataFrame({
        "d1_key": np.arange(100),
        "d1_cat": [f"cat{i % 7}" for i in range(100)],
        "d1_flag": (np.arange(100) % 3 == 0),
    })
    dim2 = pd.DataFrame({
        "d2_key": np.arange(1000, 1050),
        "d2_region": [f"r{i % 5}" for i in range(50)],
    })
    c = Context()
    c.create_table("fact", fact)
    c.create_table("dim1", dim1)
    c.create_table("dim2", dim2)
    return c, fact, dim1, dim2, spy


def test_star_join_agg_fires_and_matches(star):
    c, fact, dim1, dim2, spy = star
    q = ("SELECT d1_cat, SUM(f_val) AS s, COUNT(*) AS n "
         "FROM fact JOIN dim1 ON f_dim1 = d1_key "
         "JOIN dim2 ON f_dim2 = d2_key "
         "WHERE d2_region = 'r2' AND f_qty > 3 "
         "GROUP BY d1_cat ORDER BY d1_cat")
    res = c.sql(q).compute()
    assert len(spy) == 1, "compiled join pipeline did not fire"
    m = fact.merge(dim1, left_on="f_dim1", right_on="d1_key")
    m = m.merge(dim2, left_on="f_dim2", right_on="d2_key")
    m = m[(m.d2_region == "r2") & (m.f_qty > 3)]
    exp = m.groupby("d1_cat").agg(s=("f_val", "sum"), n=("f_val", "count"))
    exp = exp.reset_index().sort_values("d1_cat")
    assert list(res["d1_cat"]) == list(exp["d1_cat"])
    np.testing.assert_allclose(res["s"].to_numpy(), exp["s"].to_numpy(), rtol=1e-9)
    np.testing.assert_array_equal(res["n"].to_numpy(), exp["n"].to_numpy())


def test_group_by_join_key_uses_pointer_gid(star):
    c, fact, dim1, _, spy = star
    q = ("SELECT f_dim1, AVG(f_val) AS a FROM fact "
         "JOIN dim1 ON f_dim1 = d1_key WHERE d1_flag GROUP BY f_dim1")
    res = c.sql(q).compute()
    assert len(spy) == 1
    m = fact.merge(dim1[dim1.d1_flag], left_on="f_dim1", right_on="d1_key")
    exp = m.groupby("f_dim1").f_val.mean()
    res = res.sort_values("f_dim1").reset_index(drop=True)
    np.testing.assert_array_equal(res["f_dim1"].to_numpy(), exp.index.to_numpy())
    np.testing.assert_allclose(res["a"].to_numpy(), exp.to_numpy(), rtol=1e-9)


def test_null_join_keys_never_match(spy):
    from dask_sql_tpu import Context

    fact = pd.DataFrame({"k": [1.0, 2.0, None, 3.0, None, 1.0],
                         "v": [10.0, 20, 30, 40, 50, 60]})
    dim = pd.DataFrame({"dk": [1, 2, 4], "cat": ["a", "b", "c"]})
    c = Context()
    c.create_table("fact", fact)
    c.create_table("dim", dim)
    res = c.sql("SELECT cat, SUM(v) AS s FROM fact JOIN dim ON k = dk "
                "GROUP BY cat ORDER BY cat").compute()
    assert list(res["cat"]) == ["a", "b"]
    np.testing.assert_allclose(res["s"].to_numpy(), [70.0, 20.0])


def test_global_agg_over_join(spy):
    from dask_sql_tpu import Context

    fact = pd.DataFrame({"k": np.arange(100) % 10, "v": np.ones(100)})
    dim = pd.DataFrame({"dk": np.arange(5)})  # only half the keys
    c = Context()
    c.create_table("fact", fact)
    c.create_table("dim", dim)
    res = c.sql("SELECT COUNT(*) AS n, SUM(v) AS s FROM fact "
                "JOIN dim ON k = dk").compute()
    assert len(spy) == 1
    assert int(res["n"][0]) == 50 and float(res["s"][0]) == 50.0
    # empty match -> still one row, COUNT 0
    res0 = c.sql("SELECT COUNT(*) AS n FROM fact JOIN dim ON k = dk "
                 "WHERE v > 99").compute()
    assert len(res0) == 1 and int(res0["n"][0]) == 0


def test_duplicate_build_keys_fall_back(spy):
    """Non-unique build side: pipeline declines, generic path still correct."""
    from dask_sql_tpu import Context

    fact = pd.DataFrame({"k": [1, 2, 2, 3], "v": [1.0, 2, 3, 4]})
    dim = pd.DataFrame({"dk": [2, 2, 3], "w": [10.0, 20, 30]})
    c = Context()
    c.create_table("fact", fact)
    c.create_table("dim", dim)
    res = c.sql("SELECT SUM(v * w) AS s FROM fact JOIN dim ON k = dk").compute()
    assert len(spy) == 0  # declined: duplicate keys
    # (2*10)+(2*20)+(3*10)+(3*20)+(4*30) = 20+40+30+60+120
    assert float(res["s"][0]) == 270.0


def test_table_update_invalidates_cache(star):
    c, fact, dim1, dim2, spy = star
    q = ("SELECT SUM(f_val) AS s FROM fact JOIN dim1 ON f_dim1 = d1_key "
         "WHERE d1_flag")
    r1 = c.sql(q).compute()
    dim1b = dim1.copy()
    dim1b["d1_flag"] = ~dim1b["d1_flag"]  # flip the filter
    c.create_table("dim1", dim1b)
    r2 = c.sql(q).compute()
    m1 = fact.merge(dim1[dim1.d1_flag], left_on="f_dim1", right_on="d1_key")
    m2 = fact.merge(dim1b[dim1b.d1_flag], left_on="f_dim1", right_on="d1_key")
    np.testing.assert_allclose(float(r1["s"][0]), m1.f_val.sum(), rtol=1e-9)
    np.testing.assert_allclose(float(r2["s"][0]), m2.f_val.sum(), rtol=1e-9)
