"""Phase profiler for the TPC-H Q1 bench: where does end-to-end time go?

Phases: parse+plan / execute-dispatch / device-sync / to_pandas, plus the raw
compiled-kernel time (direct call on resident device buffers) as the floor.
Prints one JSON line per phase.  Run on the real chip:  python benchmarks/profile_q1.py
"""
from __future__ import annotations

import json
import sys
import time

sys.path.insert(0, ".")

from bench import N_ROWS, QUERY, gen_lineitem, _ensure_backend  # noqa: E402


def main():
    _ensure_backend()
    import jax

    from dask_sql_tpu import Context
    from dask_sql_tpu.planner.parser import parse_sql

    n = int(sys.argv[1]) if len(sys.argv) > 1 else N_ROWS
    df = gen_lineitem(n)

    c = Context()
    t0 = time.perf_counter()
    c.create_table("lineitem", df)
    t_create = time.perf_counter() - t0

    # warm-up: compile + caches
    c.sql(QUERY).compute()

    phases = {"create_table_s": round(t_create, 3), "rows": n,
              "backend": jax.default_backend()}

    # 1. parse + plan
    reps = 5
    t0 = time.perf_counter()
    for _ in range(reps):
        stmt = parse_sql(QUERY)[0]
        plan = c._get_ral(stmt)
    phases["plan_ms"] = round((time.perf_counter() - t0) / reps * 1000, 2)

    # 2. full execute to device table (dispatch incl. any host work)
    from dask_sql_tpu.physical.executor import Executor

    times = {"exec": [], "sync": [], "pandas": []}
    for _ in range(3):
        ex = Executor(c)
        t0 = time.perf_counter()
        table = ex.execute(plan)
        t1 = time.perf_counter()
        for col in table.columns.values():
            jax.block_until_ready(col.data)
        t2 = time.perf_counter()
        table.to_pandas()
        t3 = time.perf_counter()
        times["exec"].append(t1 - t0)
        times["sync"].append(t2 - t1)
        times["pandas"].append(t3 - t2)
    for k, v in times.items():
        phases[f"{k}_ms"] = round(min(v) * 1000, 2)

    # 3. compiled-kernel floor: direct call on the cached CompiledAggregate
    from dask_sql_tpu.physical import compiled as C

    if C._cache:
        ca = next(iter(C._cache.values()))
        datas = [ca.table.columns[nm].data for nm in ca.table.column_names]
        valids = [ca.table.columns[nm].validity for nm in ca.table.column_names]
        flat = ca._fn(tuple(datas), tuple(valids))
        jax.block_until_ready(flat)
        t0 = time.perf_counter()
        for _ in range(5):
            flat = ca._fn(tuple(datas), tuple(valids))
            jax.block_until_ready(flat)
        phases["kernel_ms"] = round((time.perf_counter() - t0) / 5 * 1000, 2)
        t0 = time.perf_counter()
        for _ in range(3):
            ca.run()
        phases["kernel_plus_decode_ms"] = round(
            (time.perf_counter() - t0) / 3 * 1000, 2)

    # 4. end-to-end (the bench number)
    t0 = time.perf_counter()
    c.sql(QUERY).compute()
    phases["end_to_end_ms"] = round((time.perf_counter() - t0) * 1000, 2)
    phases["rows_per_sec"] = round(n / (phases["end_to_end_ms"] / 1000), 0)

    print(json.dumps(phases))


if __name__ == "__main__":
    main()
