"""TPC-H q1-q22 runner (parity: reference tests/unit/test_queries.py — the
q1-q99 suite with its XFAIL list is the coverage yardstick; ours is TPC-H,
matching the BASELINE configs, with pandas cross-checks for the core queries).
"""
import numpy as np
import pandas as pd
import pytest

from tests.tpch import QUERIES, generate

XFAIL_QUERIES = set()


@pytest.fixture(scope="module")
def tpch_context():
    from dask_sql_tpu import Context

    c = Context()
    tables = generate(scale_rows=2000)
    for name, df in tables.items():
        c.create_table(name, df)
    return c, tables


@pytest.mark.parametrize("qnum", sorted(QUERIES))
def test_query(tpch_context, qnum):
    if qnum in XFAIL_QUERIES:
        pytest.xfail(f"q{qnum} not supported yet")
    c, _ = tpch_context
    result = c.sql(QUERIES[qnum]).compute()
    assert result is not None
    assert len(result.columns) > 0


def test_q1_values(tpch_context):
    c, tables = tpch_context
    li = tables["lineitem"]
    result = c.sql(QUERIES[1]).compute()
    sel = li[li.l_shipdate <= pd.Timestamp("1998-09-02")]
    expected = sel.groupby(["l_returnflag", "l_linestatus"]).agg(
        sum_qty=("l_quantity", "sum"),
        count_order=("l_quantity", "count"),
    ).reset_index().sort_values(["l_returnflag", "l_linestatus"]).reset_index(drop=True)
    assert list(result["l_returnflag"]) == list(expected["l_returnflag"])
    np.testing.assert_allclose(result["sum_qty"], expected["sum_qty"])
    np.testing.assert_allclose(result["count_order"], expected["count_order"])


def test_q3_values(tpch_context):
    c, t = tpch_context
    result = c.sql(QUERIES[3]).compute()
    cust = t["customer"]
    orders = t["orders"]
    li = t["lineitem"]
    m = cust[cust.c_mktsegment == "BUILDING"].merge(
        orders[orders.o_orderdate < pd.Timestamp("1995-03-15")],
        left_on="c_custkey", right_on="o_custkey")
    m = m.merge(li[li.l_shipdate > pd.Timestamp("1995-03-15")],
                left_on="o_orderkey", right_on="l_orderkey")
    m["revenue"] = m.l_extendedprice * (1 - m.l_discount)
    expected = (m.groupby(["l_orderkey", "o_orderdate", "o_shippriority"]).revenue.sum()
                .reset_index().sort_values(["revenue", "o_orderdate"],
                                           ascending=[False, True]).head(10))
    np.testing.assert_allclose(result["revenue"], expected["revenue"], rtol=1e-9)
    assert list(result["l_orderkey"]) == list(expected["l_orderkey"])


def test_q5_values(tpch_context):
    c, t = tpch_context
    result = c.sql(QUERIES[5]).compute()
    cust, orders, li = t["customer"], t["orders"], t["lineitem"]
    supp, nation, region = t["supplier"], t["nation"], t["region"]
    m = cust.merge(orders, left_on="c_custkey", right_on="o_custkey")
    m = m[(m.o_orderdate >= pd.Timestamp("1994-01-01")) & (m.o_orderdate < pd.Timestamp("1995-01-01"))]
    m = m.merge(li, left_on="o_orderkey", right_on="l_orderkey")
    m = m.merge(supp, left_on="l_suppkey", right_on="s_suppkey")
    m = m[m.c_nationkey == m.s_nationkey]
    m = m.merge(nation, left_on="s_nationkey", right_on="n_nationkey")
    m = m.merge(region, left_on="n_regionkey", right_on="r_regionkey")
    m = m[m.r_name == "ASIA"]
    m["revenue"] = m.l_extendedprice * (1 - m.l_discount)
    expected = (m.groupby("n_name").revenue.sum().reset_index()
                .sort_values("revenue", ascending=False).reset_index(drop=True))
    assert list(result["n_name"]) == list(expected["n_name"])
    np.testing.assert_allclose(result["revenue"], expected["revenue"], rtol=1e-9)


def test_q6_values(tpch_context):
    c, t = tpch_context
    result = c.sql(QUERIES[6]).compute()
    li = t["lineitem"]
    sel = li[(li.l_shipdate >= pd.Timestamp("1994-01-01"))
             & (li.l_shipdate < pd.Timestamp("1995-01-01"))
             & (li.l_discount >= 0.05) & (li.l_discount <= 0.07)
             & (li.l_quantity < 24)]
    expected = (sel.l_extendedprice * sel.l_discount).sum()
    np.testing.assert_allclose(result["revenue"][0], expected, rtol=1e-9)


def test_q13_values(tpch_context):
    c, t = tpch_context
    result = c.sql(QUERIES[13]).compute()
    cust, orders = t["customer"], t["orders"]
    ok = orders[~orders.o_comment.str.contains("special.*requests", regex=True)]
    m = cust.merge(ok, left_on="c_custkey", right_on="o_custkey", how="left")
    counts = m.groupby("c_custkey").o_orderkey.count()
    expected = (counts.value_counts().rename_axis("c_count").reset_index(name="custdist")
                .sort_values(["custdist", "c_count"], ascending=[False, False])
                .reset_index(drop=True))
    assert list(result["c_count"]) == list(expected["c_count"])
    assert list(result["custdist"]) == list(expected["custdist"])


def test_q4_values(tpch_context):
    c, t = tpch_context
    result = c.sql(QUERIES[4]).compute()
    orders, li = t["orders"], t["lineitem"]
    sel = orders[(orders.o_orderdate >= pd.Timestamp("1993-07-01"))
                 & (orders.o_orderdate < pd.Timestamp("1993-10-01"))]
    late = li[li.l_commitdate < li.l_receiptdate].l_orderkey.unique()
    sel = sel[sel.o_orderkey.isin(late)]
    expected = (sel.groupby("o_orderpriority").size().reset_index(name="order_count")
                .sort_values("o_orderpriority").reset_index(drop=True))
    assert list(result["o_orderpriority"]) == list(expected["o_orderpriority"])
    assert list(result["order_count"]) == list(expected["order_count"])


def test_q10_values(tpch_context):
    c, t = tpch_context
    result = c.sql(QUERIES[10]).compute()
    cust, orders, li, nation = t["customer"], t["orders"], t["lineitem"], t["nation"]
    sel_o = orders[(orders.o_orderdate >= pd.Timestamp("1993-10-01"))
                   & (orders.o_orderdate < pd.Timestamp("1994-01-01"))]
    m = cust.merge(sel_o, left_on="c_custkey", right_on="o_custkey")
    m = m.merge(li[li.l_returnflag == "R"], left_on="o_orderkey", right_on="l_orderkey")
    m = m.merge(nation, left_on="c_nationkey", right_on="n_nationkey")
    m["revenue"] = m.l_extendedprice * (1 - m.l_discount)
    expected = (m.groupby(["c_custkey", "c_name", "c_acctbal", "c_phone", "n_name",
                           "c_address", "c_comment"]).revenue.sum().reset_index()
                .sort_values("revenue", ascending=False).head(20).reset_index(drop=True))
    np.testing.assert_allclose(result["revenue"], expected["revenue"], rtol=1e-9)
    assert list(result["c_custkey"]) == list(expected["c_custkey"])


def test_q12_values(tpch_context):
    c, t = tpch_context
    result = c.sql(QUERIES[12]).compute()
    orders, li = t["orders"], t["lineitem"]
    sel = li[li.l_shipmode.isin(["MAIL", "SHIP"])
             & (li.l_commitdate < li.l_receiptdate)
             & (li.l_shipdate < li.l_commitdate)
             & (li.l_receiptdate >= pd.Timestamp("1994-01-01"))
             & (li.l_receiptdate < pd.Timestamp("1995-01-01"))]
    m = sel.merge(orders, left_on="l_orderkey", right_on="o_orderkey")
    high = m.o_orderpriority.isin(["1-URGENT", "2-HIGH"])
    expected = (m.assign(h=high.astype(int), l=(~high).astype(int))
                .groupby("l_shipmode")[["h", "l"]].sum().reset_index()
                .sort_values("l_shipmode").reset_index(drop=True))
    assert list(result["l_shipmode"]) == list(expected["l_shipmode"])
    assert list(result["high_line_count"]) == list(expected["h"])
    assert list(result["low_line_count"]) == list(expected["l"])


def test_q14_values(tpch_context):
    c, t = tpch_context
    result = c.sql(QUERIES[14]).compute()
    li, part = t["lineitem"], t["part"]
    sel = li[(li.l_shipdate >= pd.Timestamp("1995-09-01"))
             & (li.l_shipdate < pd.Timestamp("1995-10-01"))]
    m = sel.merge(part, left_on="l_partkey", right_on="p_partkey")
    rev = m.l_extendedprice * (1 - m.l_discount)
    promo = rev.where(m.p_type.str.startswith("PROMO"), 0.0)
    expected = 100.0 * promo.sum() / rev.sum()
    np.testing.assert_allclose(result["promo_revenue"][0], expected, rtol=1e-9)


def test_q18_values(tpch_context):
    c, t = tpch_context
    result = c.sql(QUERIES[18]).compute()
    cust, orders, li = t["customer"], t["orders"], t["lineitem"]
    big = li.groupby("l_orderkey").l_quantity.sum()
    big_keys = big[big > 250].index
    m = orders[orders.o_orderkey.isin(big_keys)].merge(
        cust, left_on="o_custkey", right_on="c_custkey")
    m = m.merge(li, left_on="o_orderkey", right_on="l_orderkey")
    expected = (m.groupby(["c_name", "c_custkey", "o_orderkey", "o_orderdate",
                           "o_totalprice"]).l_quantity.sum().reset_index()
                .sort_values(["o_totalprice", "o_orderdate"], ascending=[False, True])
                .head(100).reset_index(drop=True))
    assert list(result["o_orderkey"]) == list(expected["o_orderkey"])
    np.testing.assert_allclose(result["total_qty"], expected["l_quantity"], rtol=1e-9)


def test_q22_values(tpch_context):
    c, t = tpch_context
    result = c.sql(QUERIES[22]).compute()
    cust, orders = t["customer"], t["orders"]
    codes = ("13", "31", "23", "29", "30", "18", "17")
    cc = cust.c_phone.str[:2]
    in_codes = cust[cc.isin(codes)]
    avg_bal = in_codes[in_codes.c_acctbal > 0].c_acctbal.mean()
    sel = in_codes[(in_codes.c_acctbal > avg_bal)
                   & ~in_codes.c_custkey.isin(orders.o_custkey)]
    expected = (sel.assign(cntrycode=sel.c_phone.str[:2])
                .groupby("cntrycode").c_acctbal.agg(["count", "sum"]).reset_index()
                .sort_values("cntrycode").reset_index(drop=True))
    assert list(result["cntrycode"]) == list(expected["cntrycode"])
    assert list(result["numcust"]) == list(expected["count"])
    np.testing.assert_allclose(result["totacctbal"], expected["sum"], rtol=1e-9)


def test_q7_values(tpch_context):
    c, t = tpch_context
    result = c.sql(QUERIES[7]).compute()
    supp, li, orders = t["supplier"], t["lineitem"], t["orders"]
    cust, nation = t["customer"], t["nation"]
    m = supp.merge(li, left_on="s_suppkey", right_on="l_suppkey")
    m = m.merge(orders, left_on="l_orderkey", right_on="o_orderkey")
    m = m.merge(cust, left_on="o_custkey", right_on="c_custkey")
    n1 = nation.rename(columns=lambda x: x + "_1")
    n2 = nation.rename(columns=lambda x: x + "_2")
    m = m.merge(n1, left_on="s_nationkey", right_on="n_nationkey_1")
    m = m.merge(n2, left_on="c_nationkey", right_on="n_nationkey_2")
    m = m[(((m.n_name_1 == "FRANCE") & (m.n_name_2 == "GERMANY"))
           | ((m.n_name_1 == "GERMANY") & (m.n_name_2 == "FRANCE")))
          & (m.l_shipdate >= pd.Timestamp("1995-01-01"))
          & (m.l_shipdate <= pd.Timestamp("1996-12-31"))]
    m = m.assign(l_year=m.l_shipdate.dt.year,
                 volume=m.l_extendedprice * (1 - m.l_discount))
    expected = (m.groupby(["n_name_1", "n_name_2", "l_year"]).volume.sum().reset_index()
                .sort_values(["n_name_1", "n_name_2", "l_year"]).reset_index(drop=True))
    assert len(result) == len(expected)
    if len(expected):
        np.testing.assert_allclose(result["revenue"], expected["volume"], rtol=1e-9)


def test_q15_values(tpch_context):
    c, t = tpch_context
    result = c.sql(QUERIES[15]).compute()
    li, supp = t["lineitem"], t["supplier"]
    sel = li[(li.l_shipdate >= pd.Timestamp("1996-01-01"))
             & (li.l_shipdate < pd.Timestamp("1996-04-01"))]
    rev = (sel.assign(r=sel.l_extendedprice * (1 - sel.l_discount))
           .groupby("l_suppkey").r.sum())
    top = rev[np.isclose(rev, rev.max())]
    expected = supp[supp.s_suppkey.isin(top.index)].sort_values("s_suppkey")
    assert list(result["s_suppkey"]) == list(expected["s_suppkey"])
    np.testing.assert_allclose(result["total_revenue"], rev.max(), rtol=1e-9)


def test_q19_values(tpch_context):
    c, t = tpch_context
    result = c.sql(QUERIES[19]).compute()
    li, part = t["lineitem"], t["part"]
    m = li.merge(part, left_on="l_partkey", right_on="p_partkey")
    def branch(brand, containers, qlo, qhi, smax):
        return ((m.p_brand == brand) & m.p_container.isin(containers)
                & (m.l_quantity >= qlo) & (m.l_quantity <= qhi)
                & (m.p_size >= 1) & (m.p_size <= smax)
                & m.l_shipmode.isin(["AIR", "REG AIR"])
                & (m.l_shipinstruct == "DELIVER IN PERSON"))
    mask = (branch("Brand#12", ["SM CASE", "SM BOX", "SM PACK", "SM PKG"], 1, 11, 5)
            | branch("Brand#23", ["MED BAG", "MED BOX", "MED PKG", "MED PACK"], 10, 20, 10)
            | branch("Brand#34", ["LG CASE", "LG BOX", "LG PACK", "LG PKG"], 20, 30, 15))
    expected = (m[mask].l_extendedprice * (1 - m[mask].l_discount)).sum()
    got = result["revenue"][0]
    if pd.isna(got):
        assert expected == 0
    else:
        np.testing.assert_allclose(got, expected, rtol=1e-9)


def test_q21_values(tpch_context):
    c, t = tpch_context
    result = c.sql(QUERIES[21]).compute()
    supp, li, orders, nation = t["supplier"], t["lineitem"], t["orders"], t["nation"]
    l1 = li[li.l_receiptdate > li.l_commitdate]
    m = supp.merge(l1, left_on="s_suppkey", right_on="l_suppkey")
    m = m.merge(orders[orders.o_orderstatus == "F"],
                left_on="l_orderkey", right_on="o_orderkey")
    m = m.merge(nation[nation.n_name == "SAUDI ARABIA"],
                left_on="s_nationkey", right_on="n_nationkey")
    multi = li.groupby("l_orderkey").l_suppkey.nunique()
    multi_ok = set(multi[multi > 1].index)
    late = li[li.l_receiptdate > li.l_commitdate]
    late_multi = late.groupby("l_orderkey").l_suppkey.nunique()
    only_one_late = set(late_multi[late_multi == 1].index)
    m = m[m.l_orderkey.isin(multi_ok) & m.l_orderkey.isin(only_one_late)]
    expected = (m.groupby("s_name").size().reset_index(name="numwait")
                .sort_values(["numwait", "s_name"], ascending=[False, True])
                .head(100).reset_index(drop=True))
    assert list(result["s_name"]) == list(expected["s_name"])
    assert list(result["numwait"]) == list(expected["numwait"])


def test_q9_values(tpch_context):
    c, t = tpch_context
    result = c.sql(QUERIES[9]).compute()
    part, supp, li = t["part"], t["supplier"], t["lineitem"]
    ps, orders, nation = t["partsupp"], t["orders"], t["nation"]
    m = li.merge(part[part.p_name.str.contains("green")],
                 left_on="l_partkey", right_on="p_partkey")
    m = m.merge(supp, left_on="l_suppkey", right_on="s_suppkey")
    m = m.merge(ps, left_on=["l_suppkey", "l_partkey"],
                right_on=["ps_suppkey", "ps_partkey"])
    m = m.merge(orders, left_on="l_orderkey", right_on="o_orderkey")
    m = m.merge(nation, left_on="s_nationkey", right_on="n_nationkey")
    m = m.assign(o_year=m.o_orderdate.dt.year,
                 amount=m.l_extendedprice * (1 - m.l_discount)
                        - m.ps_supplycost * m.l_quantity)
    expected = (m.groupby(["n_name", "o_year"]).amount.sum().reset_index()
                .sort_values(["n_name", "o_year"], ascending=[True, False])
                .reset_index(drop=True))
    assert list(result["nation"]) == list(expected["n_name"])
    np.testing.assert_allclose(result["sum_profit"], expected["amount"], rtol=1e-9)


def test_q11_values(tpch_context):
    c, t = tpch_context
    result = c.sql(QUERIES[11]).compute()
    ps, supp, nation = t["partsupp"], t["supplier"], t["nation"]
    m = ps.merge(supp, left_on="ps_suppkey", right_on="s_suppkey")
    m = m.merge(nation[nation.n_name == "GERMANY"],
                left_on="s_nationkey", right_on="n_nationkey")
    m = m.assign(value=m.ps_supplycost * m.ps_availqty)
    grouped = m.groupby("ps_partkey").value.sum()
    threshold = m.value.sum() * 0.0001
    expected = (grouped[grouped > threshold].reset_index()
                .sort_values("value", ascending=False).reset_index(drop=True))
    assert list(result["ps_partkey"]) == list(expected["ps_partkey"])
    np.testing.assert_allclose(result["value"], expected["value"], rtol=1e-9)


def test_q16_values(tpch_context):
    c, t = tpch_context
    result = c.sql(QUERIES[16]).compute()
    ps, part, supp = t["partsupp"], t["part"], t["supplier"]
    bad_supp = supp[supp.s_comment.str.contains("Customer.*Complaints")].s_suppkey
    m = ps[~ps.ps_suppkey.isin(bad_supp)].merge(
        part, left_on="ps_partkey", right_on="p_partkey")
    m = m[(m.p_brand != "Brand#45")
          & ~m.p_type.str.startswith("MEDIUM POLISHED")
          & m.p_size.isin([49, 14, 23, 45, 19, 3, 36, 9])]
    expected = (m.groupby(["p_brand", "p_type", "p_size"]).ps_suppkey.nunique()
                .reset_index(name="supplier_cnt")
                .sort_values(["supplier_cnt", "p_brand", "p_type", "p_size"],
                             ascending=[False, True, True, True])
                .reset_index(drop=True))
    assert len(result) == len(expected)
    assert list(result["supplier_cnt"]) == list(expected["supplier_cnt"])
    assert list(result["p_brand"]) == list(expected["p_brand"])


def test_q17_values(tpch_context):
    c, t = tpch_context
    result = c.sql(QUERIES[17]).compute()
    li, part = t["lineitem"], t["part"]
    sel_p = part[(part.p_brand == "Brand#23") & (part.p_container == "MED BOX")]
    m = li.merge(sel_p, left_on="l_partkey", right_on="p_partkey")
    avg_qty = li.groupby("l_partkey").l_quantity.mean()
    m = m[m.l_quantity < 0.2 * m.l_partkey.map(avg_qty)]
    expected = m.l_extendedprice.sum() / 7.0
    got = result["avg_yearly"][0]
    if pd.isna(got):
        assert len(m) == 0
    else:
        np.testing.assert_allclose(got, expected, rtol=1e-9)


def test_q20_values(tpch_context):
    c, t = tpch_context
    result = c.sql(QUERIES[20]).compute()
    supp, nation, ps = t["supplier"], t["nation"], t["partsupp"]
    part, li = t["part"], t["lineitem"]
    forest = part[part.p_name.str.startswith("forest")].p_partkey
    sel_li = li[(li.l_shipdate >= pd.Timestamp("1994-01-01"))
                & (li.l_shipdate < pd.Timestamp("1995-01-01"))]
    half = (sel_li.groupby(["l_partkey", "l_suppkey"]).l_quantity.sum() * 0.5)
    cand = ps[ps.ps_partkey.isin(forest)].copy()
    key = list(zip(cand.ps_partkey, cand.ps_suppkey))
    cand = cand[[half.get(k, np.nan) is not np.nan and cand_avail > half.get(k, np.inf)
                 for k, cand_avail in zip(key, cand.ps_availqty)]] \
        if len(cand) else cand
    good_supp = set(cand.ps_suppkey)
    m = supp[supp.s_suppkey.isin(good_supp)].merge(
        nation[nation.n_name == "CANADA"], left_on="s_nationkey",
        right_on="n_nationkey")
    expected = m.sort_values("s_name").reset_index(drop=True)
    assert list(result["s_name"]) == list(expected["s_name"])


def test_q8_values(tpch_context):
    c, t = tpch_context
    result = c.sql(QUERIES[8]).compute()
    part, supp, li = t["part"], t["supplier"], t["lineitem"]
    orders, cust, nation, region = t["orders"], t["customer"], t["nation"], t["region"]
    m = li.merge(part[part.p_type == "ECONOMY ANODIZED STEEL"],
                 left_on="l_partkey", right_on="p_partkey")
    m = m.merge(supp, left_on="l_suppkey", right_on="s_suppkey")
    m = m.merge(orders, left_on="l_orderkey", right_on="o_orderkey")
    m = m[(m.o_orderdate >= pd.Timestamp("1995-01-01"))
          & (m.o_orderdate <= pd.Timestamp("1996-12-31"))]
    m = m.merge(cust, left_on="o_custkey", right_on="c_custkey")
    n1 = nation.add_suffix("_c")
    m = m.merge(n1, left_on="c_nationkey", right_on="n_nationkey_c")
    m = m.merge(region[region.r_name == "AMERICA"],
                left_on="n_regionkey_c", right_on="r_regionkey")
    n2 = nation.add_suffix("_s")
    m = m.merge(n2, left_on="s_nationkey", right_on="n_nationkey_s")
    m = m.assign(o_year=m.o_orderdate.dt.year,
                 volume=m.l_extendedprice * (1 - m.l_discount))
    if len(m) == 0:
        assert len(result) == 0
        return
    g = m.groupby("o_year")
    expected = (g.apply(lambda x: x[x.n_name_s == "BRAZIL"].volume.sum() / x.volume.sum(),
                        include_groups=False)
                .reset_index(name="share").sort_values("o_year").reset_index(drop=True))
    assert list(result["o_year"]) == list(expected["o_year"])
    np.testing.assert_allclose(result["mkt_share"], expected["share"], rtol=1e-9)


def test_q2_values(tpch_context):
    c, t = tpch_context
    result = c.sql(QUERIES[2]).compute()
    part, supp, ps = t["part"], t["supplier"], t["partsupp"]
    nation, region = t["nation"], t["region"]
    europe = nation.merge(region[region.r_name == "EUROPE"],
                          left_on="n_regionkey", right_on="r_regionkey")
    esupp = supp.merge(europe, left_on="s_nationkey", right_on="n_nationkey")
    eps = ps.merge(esupp, left_on="ps_suppkey", right_on="s_suppkey")
    min_cost = eps.groupby("ps_partkey").ps_supplycost.min()
    sel_p = part[(part.p_size == 15) & part.p_type.str.endswith("BRASS")]
    m = eps.merge(sel_p, left_on="ps_partkey", right_on="p_partkey")
    m = m[m.ps_supplycost == m.ps_partkey.map(min_cost)]
    expected = (m.sort_values(["s_acctbal", "n_name", "s_name", "p_partkey"],
                              ascending=[False, True, True, True])
                .head(100).reset_index(drop=True))
    assert len(result) == len(expected)
    if len(expected):
        assert list(result["p_partkey"]) == list(expected["p_partkey"])
        np.testing.assert_allclose(result["s_acctbal"], expected["s_acctbal"], rtol=1e-9)


@pytest.mark.parametrize("qnum,options", [
    # compile-off must agree everywhere
    (1, {"sql.compile": False}),
    (3, {"sql.compile": False}),
    (6, {"sql.compile": False}),
    (13, {"sql.compile": False}),
    (21, {"sql.compile": False}),
    # optimizer-off is only *feasible* for explicit-join / single-table
    # queries (comma-joins rely on cross-join elimination, like the reference)
    (1, {"sql.optimize": False}),
    (6, {"sql.optimize": False}),
    (13, {"sql.optimize": False}),
])
def test_config_invariance(tpch_context, qnum, options):
    """Uncompiled / unoptimized execution must agree with the default path."""
    c, _ = tpch_context
    baseline = c.sql(QUERIES[qnum]).compute()
    variant = c.sql(QUERIES[qnum], config_options=options).compute()
    assert list(baseline.columns) == list(variant.columns)
    assert len(baseline) == len(variant)
    for col in baseline.columns:
        b = baseline[col]
        v = variant[col]
        if b.dtype.kind in ("f", "i"):
            np.testing.assert_allclose(
                b.astype(float), v.astype(float), rtol=1e-9,
                err_msg=f"q{qnum} col {col} options {options}")
        else:
            assert list(b.astype(str)) == list(v.astype(str)), (qnum, col, options)


@pytest.fixture(scope="module")
def tpch_distributed_context():
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs the virtual multi-device mesh")
    from dask_sql_tpu import Context

    c = Context()
    tables = generate(scale_rows=2000)
    for name, df in tables.items():
        c.create_table(name, df, distributed=True)
    return c, tables


@pytest.mark.parametrize("qnum", [1, 3, 5, 6, 13])
def test_tpch_distributed(tpch_distributed_context, qnum):
    """TPC-H over mesh-sharded tables must match the single-device answers."""
    c, tables = tpch_distributed_context
    result = c.sql(QUERIES[qnum]).compute()
    ref = Context_single(tables).sql(QUERIES[qnum]).compute()
    assert list(result.columns) == list(ref.columns)
    assert len(result) == len(ref)
    for col in result.columns:
        a, b = result[col], ref[col]
        if a.dtype.kind in ("f", "i"):
            np.testing.assert_allclose(a.astype(float), b.astype(float), rtol=1e-9,
                                       err_msg=f"q{qnum} col {col}")
        else:
            assert list(a.astype(str)) == list(b.astype(str)), (qnum, col)


def Context_single(tables):
    from dask_sql_tpu import Context

    c = Context()
    for name, df in tables.items():
        c.create_table(name, df)
    return c
