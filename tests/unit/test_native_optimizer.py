"""Native (C++) optimizer: differential plan equality vs the Python rules.

Parity: the reference's optimizer is a compiled DataFusion rule pipeline
(optimizer.rs:53-98); here native/binder.cpp's Optimizer runs the same
2 x 15-slot structural loop (simplify, unwrap-cast, decorrelate,
disjunctive rewrite, cross-join elimination, limit/filter/projection
pushdowns, outer-join elimination) over the flat plan buffer.  The
differential bar: `dsql_plan` output must decode to EXACTLY the plan the
Python binder + optimize_core produce — TPC-H fallback-off, the full
TPC-DS corpus, and targeted rule cases.
"""
import pandas as pd
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu.config import config
from dask_sql_tpu.planner.binder import Binder
from dask_sql_tpu.planner.native_bridge import native_parse, native_plan
from dask_sql_tpu.planner.optimizer.driver import optimize_core
from dask_sql_tpu.planner.optimizer.join_reorder import maybe_reorder
from dask_sql_tpu.planner.parser import parse_sql

from tests.tpch import QUERIES as TPCH_QUERIES, generate as tpch_generate
from tests.tpcds_queries import QUERIES as TPCDS_QUERIES
from tests.unit.test_native_binder import plans_equal

native_available = native_parse("SELECT 1") is not None
needs_native = pytest.mark.skipif(not native_available,
                                  reason="native library not built")


@pytest.fixture(scope="module")
def tpch_ctx():
    c = Context()
    for name, df in tpch_generate(scale_rows=50).items():
        c.create_table(name, df)
    return c


@pytest.fixture(scope="module")
def tpcds_ctx():
    from tests.tpcds import generate

    c = Context()
    for name, df in generate(scale_rows=1000).items():
        c.create_table(name, df)
    return c


def _python_pipeline(catalog, sql):
    """The native pipeline's Python twin: core rule loop + join reorder."""
    ref = Binder(catalog).bind_statement(parse_sql(sql)[0])
    ref = optimize_core(ref, config, catalog)
    return maybe_reorder(ref, config, catalog)


def _differential(c, sql, require_native=False):
    catalog = c._prepare_catalog()
    nat = native_plan(sql, catalog)
    if nat is None:
        if require_native:
            pytest.fail("fell back to the Python optimizer")
        pytest.skip("native planner declined")
    ref = _python_pipeline(catalog, sql)
    ok, why = plans_equal(nat, ref)
    assert ok, why


@needs_native
@pytest.mark.parametrize("qnum", sorted(TPCH_QUERIES))
def test_tpch_optimizes_natively(tpch_ctx, qnum):
    """Fallback-off: every TPC-H query must optimize through the C++ loop."""
    _differential(tpch_ctx, TPCH_QUERIES[qnum], require_native=True)


@needs_native
def test_tpcds_corpus_differential(tpcds_ctx):
    misses, mismatches = [], []
    catalog = tpcds_ctx._prepare_catalog()
    for qnum, sql in sorted(TPCDS_QUERIES.items()):
        try:
            nat = native_plan(sql, catalog)
        except Exception as e:  # noqa: BLE001
            nat = f"error:{type(e).__name__}"
        if nat is None:
            misses.append(qnum)
            continue
        try:
            ref = _python_pipeline(catalog, sql)
        except Exception as e:  # noqa: BLE001
            ref = f"error:{type(e).__name__}"
        if isinstance(nat, str) or isinstance(ref, str):
            if nat != ref:
                mismatches.append((qnum, f"error surface: {nat} != {ref}"))
            continue
        ok, why = plans_equal(nat, ref)
        if not ok:
            mismatches.append((qnum, why))
    assert not mismatches, f"optimized-plan mismatches: {mismatches[:5]}"
    assert not misses, f"native misses: {misses}"


RULE_CASES = [
    # constant folding + boolean simplification
    "SELECT a + 1 * 2 FROM t WHERE TRUE AND x > 1",
    "SELECT a FROM t WHERE NOT (NOT (x > 1)) AND 1 < 2",
    # unwrap cast in comparison
    "SELECT a FROM t WHERE CAST(k AS BIGINT) = 1",
    "SELECT a FROM t WHERE CAST(d AS TIMESTAMP) < TIMESTAMP '2021-01-01 00:00:00'",
    # disjunctive rewrite
    "SELECT a FROM t WHERE (k = 1 AND x > 2) OR (k = 1 AND x < 1)",
    # cross join elimination (comma join)
    "SELECT t.a FROM t, s WHERE t.k = s.k AND t.a > 1",
    # filter pushdown through projection/aggregate/join
    "SELECT * FROM (SELECT a, k FROM t) sub WHERE k > 1",
    "SELECT * FROM (SELECT k, SUM(a) AS s FROM t GROUP BY k) g WHERE k = 2",
    "SELECT t.a FROM t JOIN s ON t.k = s.k WHERE t.a > 1 AND s.x < 100",
    # limit pushdown/merge
    "SELECT a FROM t ORDER BY a LIMIT 3",
    "SELECT a FROM (SELECT a FROM t LIMIT 10) q LIMIT 5 OFFSET 1",
    # outer join elimination
    "SELECT t.a FROM t LEFT JOIN s ON t.k = s.k WHERE s.x > 0",
    "SELECT t.a FROM t FULL JOIN s ON t.k = s.k WHERE t.a > 0 AND s.x > 0",
    # decorrelation (EXISTS / IN / NOT IN / scalar)
    "SELECT a FROM t WHERE EXISTS (SELECT 1 FROM s WHERE s.k = t.k)",
    "SELECT a FROM t WHERE NOT EXISTS (SELECT 1 FROM s WHERE s.k = t.k AND s.x > t.a)",
    "SELECT a FROM t WHERE k IN (SELECT k FROM s WHERE x > 1)",
    "SELECT a FROM t WHERE x NOT IN (SELECT x FROM s)",
    "SELECT a FROM t WHERE a > (SELECT AVG(x) FROM s WHERE s.k = t.k)",
    "SELECT a FROM t WHERE a = (SELECT COUNT(*) FROM s WHERE s.k = t.k)",
    # projection pruning to the scan
    "SELECT a FROM (SELECT a, k, x, y FROM t) w",
    "SELECT q.a FROM (SELECT t.a, s.x FROM t JOIN s ON t.k = s.k) q",
    # window / distinct shapes pass through unharmed
    "SELECT a, ROW_NUMBER() OVER (PARTITION BY k ORDER BY a) FROM t WHERE x > 1",
    "SELECT DISTINCT k FROM t WHERE a > 1 ORDER BY k LIMIT 2",
    "SELECT k, GROUPING(k) FROM t GROUP BY ROLLUP (k) ORDER BY 1",
]


@needs_native
@pytest.mark.parametrize("idx", range(len(RULE_CASES)))
def test_rule_case(idx):
    import numpy as np

    c = Context()
    c.create_table("t", pd.DataFrame({
        "a": [1, 2, 3],
        "k": [1, 1, 2],
        "x": [1.5, None, 2.5],
        "y": ["p", "q", "r"],
        "d": pd.to_datetime(["2020-01-01", "2021-02-03", "2022-03-04"]),
    }))
    c.create_table("s", pd.DataFrame({"k": [1, 2], "x": [10.0, 20.0]}))
    _differential(c, RULE_CASES[idx], require_native=True)


@needs_native
def test_predicate_pushdown_knob_respected():
    c = Context()
    c.create_table("t", pd.DataFrame({"a": [1, 2], "k": [1, 2]}))
    catalog = c._prepare_catalog()
    sql = "SELECT a FROM t WHERE k = 1"
    with config.set({"sql.predicate_pushdown": False}):
        ref = _python_pipeline(catalog, sql)
        nat = native_plan(sql, catalog, predicate_pushdown=False)
    assert nat is not None
    ok, why = plans_equal(nat, ref)
    assert ok, why


@needs_native
@pytest.mark.parametrize("qnum", sorted(TPCH_QUERIES))
def test_end_to_end_native_planner_values(tpch_ctx, qnum):
    """Engine-path equivalence over the WHOLE TPC-H battery: identical
    values with the native planner on and off (catches any divergence the
    structural differential could mask through execution)."""
    sql = TPCH_QUERIES[qnum]
    on = tpch_ctx.sql(sql, return_futures=False,
                      config_options={"sql.native.binder": "on"})
    off = tpch_ctx.sql(sql, return_futures=False,
                       config_options={"sql.native.binder": "off"})
    on = on.sort_values(list(on.columns)).reset_index(drop=True)
    off = off.sort_values(list(off.columns)).reset_index(drop=True)
    pd.testing.assert_frame_equal(on, off)


@needs_native
def test_join_reorder_differential():
    """Stats-driven reorder: the native tree must equal the Python
    join_reorder on a stats-bearing star-join chain (fact + dims)."""
    import numpy as np

    from dask_sql_tpu import Context
    from dask_sql_tpu.datacontainer import Statistics

    c = Context()
    rng = np.random.RandomState(0)
    fact = pd.DataFrame({"fk1": rng.randint(0, 50, 10000),
                         "fk2": rng.randint(0, 20, 10000),
                         "x": rng.rand(10000)})
    d1 = pd.DataFrame({"k1": np.arange(50), "w1": rng.rand(50)})
    d2 = pd.DataFrame({"k2": np.arange(20), "w2": rng.rand(20)})
    c.create_table("fact", fact, statistics=Statistics(10000))
    c.create_table("d1", d1, statistics=Statistics(50))
    c.create_table("d2", d2, statistics=Statistics(20))
    for sql in [
        "SELECT x, w1, w2 FROM fact, d1, d2 WHERE fk1 = k1 AND fk2 = k2",
        "SELECT x, w1, w2 FROM fact JOIN d1 ON fk1 = k1 JOIN d2 ON fk2 = k2 "
        "WHERE w1 > 0.1",
    ]:
        _differential(c, sql, require_native=True)
        on = c.sql(sql, return_futures=False,
                   config_options={"sql.native.binder": "on"})
        off = c.sql(sql, return_futures=False,
                    config_options={"sql.native.binder": "off"})
        pd.testing.assert_frame_equal(
            on.sort_values(list(on.columns)).reset_index(drop=True),
            off.sort_values(list(off.columns)).reset_index(drop=True))
