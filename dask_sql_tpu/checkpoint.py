"""Session checkpoint / restore: survive a process or host loss.

Role parity (SURVEY §5 failure detection / recovery): the reference leans on
dask.distributed — a lost worker's partitions are recomputed from the task
graph and `persist`/`publish_dataset` pin state on the cluster.  The JAX
multi-controller runtime has no per-worker recovery (a lost process ends the
program), so the TPU-native recovery story is CHECKPOINTING: snapshot the
catalog and re-hydrate a fresh Context after restart — the same pattern TPU
training stacks use (orbax-style atomic save/restore) applied to SQL session
state.

Guarantees:
- column-exact: every column round-trips with its SQL type, storage dtype
  and validity mask intact (arrow arrays are written WITH masks; numeric
  NULLs do not degrade to NaN values);
- atomic: each save writes a fresh `snap-NNNNNN/` directory and then
  atomically repoints the `CURRENT` file, so a crash mid-save leaves the
  previous complete snapshot live; older snapshots are pruned on success;
- name-safe: schema/table/model names are URL-quoted path components.

NOT captured (recorded in the manifest under `not_restored` and warned at
save time): views, registered UDFs/aggregations, and experiment objects —
they hold live plan/callable objects; re-issue their DDL after restore.

Layout under `location/`:
    CURRENT                              name of the live snapshot dir
    snap-NNNNNN/manifest.json            inventories + column specs
    snap-NNNNNN/tables/<schema>/<table>.parquet
    snap-NNNNNN/models/<schema>/<model>.pkl
"""
from __future__ import annotations

import json
import logging
import os
import pickle
import shutil
from dataclasses import replace as _dc_replace
from typing import TYPE_CHECKING
from urllib.parse import quote, unquote

import jax.numpy as jnp
import numpy as np

if TYPE_CHECKING:  # pragma: no cover
    from .context import Context

logger = logging.getLogger(__name__)


def _q(name: str) -> str:
    return quote(name, safe="")


# ----------------------------------------------------------------- columns
def _write_table(table, path: str) -> list:
    """Write a columnar Table as parquet with EXPLICIT validity masks.

    Returns the per-column spec list for the manifest (sql_type + storage
    dtype; arrow alone cannot represent e.g. TIMESTAMP-as-int64-ns or CHAR
    vs VARCHAR)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from .columnar.dtypes import STRING_TYPES

    arrays, names, specs = [], [], []
    for name, col in table.columns.items():
        # snapshots stay portable/plain: compressed columns (DICT/FOR/RLE)
        # decode at write and re-encode at restore (load_state is a load
        # boundary like registration)
        col = col.decode()
        if col.sql_type in STRING_TYPES:
            arrays.append(pa.array(col.to_numpy(), type=pa.string()))
            specs.append({"name": name, "sql_type": col.sql_type.value,
                          "storage": "string"})
        else:
            raw = np.asarray(col.data)
            mask = None if col.validity is None else ~np.asarray(col.validity)
            arrays.append(pa.array(raw, mask=mask))
            specs.append({"name": name, "sql_type": col.sql_type.value,
                          "storage": str(raw.dtype)})
        names.append(name)
    pq.write_table(pa.table(arrays, names=names), path)
    return specs


def _read_table(path: str, specs: list, num_rows: int):
    """Inverse of _write_table: columns come back bit-exact."""
    import pyarrow.parquet as pq

    from .columnar.column import Column
    from .columnar.dtypes import SqlType
    from .columnar.table import Table

    at = pq.read_table(path)
    cols = {}
    for spec in specs:
        name = spec["name"]
        sql_type = SqlType(spec["sql_type"])
        arr = at.column(name).combine_chunks()
        if spec["storage"] == "string":
            col = Column.from_numpy(arr.to_numpy(zero_copy_only=False))
            col = _dc_replace(col, sql_type=sql_type)
        else:
            import pyarrow as pa

            dt = np.dtype(spec["storage"])
            nulls = arr.is_null().to_numpy(zero_copy_only=False)
            fill = False if pa.types.is_boolean(arr.type) else 0
            vals = arr.fill_null(fill).to_numpy(
                zero_copy_only=False).astype(dt)
            valid = None if not nulls.any() else ~nulls
            from .columnar.encodings import maybe_encode, should_auto_encode

            col = maybe_encode(vals, valid, sql_type) \
                if should_auto_encode() else None
            if col is None:
                validity = None if valid is None else jnp.asarray(valid)
                col = Column(jnp.asarray(vals), sql_type, validity)
        cols[name] = col
    return Table(cols, num_rows)


# ------------------------------------------------------------------- save
def save_state(context: "Context", location: str) -> dict:
    """Write a restartable snapshot of every schema; returns the manifest."""
    from .datacontainer import LazyParquetContainer

    os.makedirs(location, exist_ok=True)
    existing = sorted(d for d in os.listdir(location) if d.startswith("snap-"))
    snap = f"snap-{(int(existing[-1][5:]) + 1) if existing else 1:06d}"
    snap_dir = os.path.join(location, snap)

    manifest = {"version": 2, "current_schema": context.schema_name,
                "schemas": {}, "not_restored": {}}
    for schema_name, container in context.schema.items():
        os.makedirs(os.path.join(snap_dir, "tables", _q(schema_name)),
                    exist_ok=True)
        os.makedirs(os.path.join(snap_dir, "models", _q(schema_name)),
                    exist_ok=True)
        entry = {"tables": {}, "models": [], "statistics": {}}
        for tname, dc in container.tables.items():
            if isinstance(dc, LazyParquetContainer):
                entry["tables"][tname] = {"kind": "parquet",
                                          "path": dc.location}
                continue
            rel = os.path.join("tables", _q(schema_name),
                               _q(tname) + ".parquet")
            # exact-length view: pad rows of a sharded table must not be
            # persisted as data (the restore re-shards from logical rows)
            table = dc.assign().depad()
            specs = _write_table(table, os.path.join(snap_dir, rel))
            entry["tables"][tname] = {"kind": "materialized", "file": rel,
                                      "columns": specs,
                                      "num_rows": table.num_rows}
        for mname, (model, train_cols) in container.models.items():
            rel = os.path.join("models", _q(schema_name), _q(mname) + ".pkl")
            with open(os.path.join(snap_dir, rel), "wb") as f:
                pickle.dump((model, train_cols), f)
            entry["models"].append({"name": mname, "file": rel})
        for tname, stats in container.statistics.items():
            if stats is not None and stats.row_count is not None:
                entry["statistics"][tname] = float(stats.row_count)
        manifest["schemas"][schema_name] = entry
        dropped = {}
        if container.function_lists:
            dropped["functions"] = sorted(container.function_lists)
        if getattr(container, "experiments", None):
            dropped["experiments"] = sorted(container.experiments)
        views = context._views.get(schema_name)
        if views:
            dropped["views"] = sorted(views)
        if dropped:
            manifest["not_restored"][schema_name] = dropped
            logger.warning(
                "save_state: schema %r has live objects a snapshot cannot "
                "carry (%s) — re-issue their DDL after load_state",
                schema_name, ", ".join(sorted(dropped)))

    epochs: dict = {}
    for (s, t), e in getattr(context, "_table_epochs", {}).items():
        if e:  # raw names, nested (a "." can legally appear inside either)
            epochs.setdefault(s, {})[t] = e
    if epochs:
        # table delta epochs ride the manifest so a standby restored from
        # this snapshot knows exactly which appends it has seen: the fleet
        # router fences writes on these (fleet/replica.py apply_write) and
        # replays the tail at promotion — a snapshot taken BEFORE an append
        # can therefore never surface a pre-append cached result
        manifest["table_epochs"] = epochs

    profiles = getattr(context, "profiles", None)
    if profiles is not None and len(profiles):
        # per-fingerprint query profiles (observability/profiles.py) ride
        # the snapshot: a restarted process knows its hot fingerprints —
        # the pre-warm input — without replaying traffic
        with open(os.path.join(snap_dir, "profiles.json"), "w") as f:
            json.dump(profiles.snapshot(), f)
        manifest["profiles"] = "profiles.json"

    breaker = getattr(context, "breaker", None)
    if breaker is not None:
        # open circuit-breaker verdicts ride along too: a restarted process
        # must not burn its recovery window re-proving rungs this one
        # already proved bad (restore is TTL-bounded, see load_state)
        bsnap = breaker.snapshot_state()
        if bsnap["open"]:
            with open(os.path.join(snap_dir, "breaker.json"), "w") as f:
                json.dump(bsnap, f)
            manifest["breaker"] = "breaker.json"

    with open(os.path.join(snap_dir, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=1)
    # fault-injection site (resilience/faults.py): a crash HERE — snapshot
    # fully written but CURRENT not yet repointed — must leave the previous
    # snapshot live and loadable (tests/unit/test_checkpoint.py proves it)
    from .resilience import faults

    faults.maybe_inject("checkpoint", context.config)
    # atomic publish: CURRENT flips only after the snapshot is complete
    tmp = os.path.join(location, f".CURRENT.tmp.{os.getpid()}")
    with open(tmp, "w") as f:
        f.write(snap)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(location, "CURRENT"))
    for old in existing:
        shutil.rmtree(os.path.join(location, old), ignore_errors=True)
    return manifest


# ------------------------------------------------------------------- load
def load_state(context: "Context", location: str) -> dict:
    """Re-hydrate the live snapshot under `location` into `context`."""
    from .datacontainer import DataContainer, Statistics

    with open(os.path.join(location, "CURRENT")) as f:
        snap_dir = os.path.join(location, f.read().strip())
    with open(os.path.join(snap_dir, "manifest.json")) as f:
        manifest = json.load(f)

    for schema_name, entry in manifest["schemas"].items():
        if schema_name not in context.schema:
            context.create_schema(schema_name)
        for tname, spec in entry["tables"].items():
            if spec["kind"] == "parquet":
                context.create_table(tname, spec["path"],
                                     schema_name=schema_name)
            else:
                from .columnar.encodings import load_scope

                with load_scope():  # restore = load boundary: re-encode
                    table = _read_table(os.path.join(snap_dir, spec["file"]),
                                        spec["columns"], spec["num_rows"])
                context.schema[schema_name].tables[tname] = DataContainer(table)
                context._views.get(schema_name, {}).pop(tname, None)
        for m in entry["models"]:
            with open(os.path.join(snap_dir, m["file"]), "rb") as f:
                model, train_cols = pickle.load(f)
            context.register_model(m["name"], model, train_cols,
                                   schema_name=schema_name)
        for tname, rows in entry.get("statistics", {}).items():
            context.schema[schema_name].statistics[tname] = Statistics(rows)
    context.schema_name = manifest.get("current_schema", context.schema_name)
    for schema_name, tables in manifest.get("table_epochs", {}).items():
        for tname, epoch in tables.items():
            key = (schema_name, tname)
            # max(): a context that already advanced past the snapshot
            # (live appends during restore) must not rewind — the fleet
            # write fence (fleet/replica.py) relies on epochs being
            # monotone to detect duplicates vs missed writes
            context._table_epochs[key] = max(
                context._table_epochs.get(key, 0), int(epoch))
    profiles_rel = manifest.get("profiles")
    if profiles_rel and getattr(context, "profiles", None) is not None:
        path = os.path.join(snap_dir, profiles_rel)
        if os.path.exists(path):
            with open(path) as f:
                restored = context.profiles.load(json.load(f))
            logger.info("load_state: restored %d query profiles", restored)
    breaker_rel = manifest.get("breaker")
    if breaker_rel and getattr(context, "breaker", None) is not None:
        ttl = float(context.config.get(
            "resilience.breaker.persist_ttl_s", 300.0) or 0.0)
        path = os.path.join(snap_dir, breaker_rel)
        if ttl > 0 and os.path.exists(path):
            with open(path) as f:
                n = context.breaker.load_state(json.load(f), ttl_s=ttl)
            if n:
                context.metrics.inc("resilience.breaker.restored", n)
                logger.info(
                    "load_state: restored %d open breaker verdicts "
                    "(ttl %.0fs)", n, ttl)
    return manifest
