"""Model -> tensor-program lowering: PREDICT as pure jittable algebra.

"Accelerating Machine Learning Queries with Linear Algebra Query
Processing" (arXiv:2306.08367) and the Tensor Relational Algebra framing
(arXiv:2009.00524) both show that classical models — including tree
ensembles — recast as gather/compare/matmul tensor programs fuse into a
relational plan and compile as ONE program.  This module is that compiler
for the engine's CREATE MODEL registry: `lower_model` turns a fitted
estimator into a `ModelProgram`, a params pytree plus a pure traceable
``apply(params, X)`` function the compiled-predict rung
(physical/compiled_predict.py) splices into the same XLA executable as the
scan/filter feeding it.

The contract that makes retraining free (the PR 7 ParamRef discipline,
applied to model weights):

- ``apply`` closes over STRUCTURE only (tree count, padded node/depth
  buckets, feature width, class count) — everything baked into the trace;
- every weight — split features/thresholds/children, leaf values, linear
  coefficients, centroids, class labels — enters as a *runtime argument*
  through ``params``, so ``shape_key`` (the recompile identity) covers
  shapes and dtypes but never values: `CREATE OR REPLACE MODEL` with the
  same hyper-shape swaps params with ZERO recompile.

Tree ensembles lower per 2306.08367: each fitted sklearn tree becomes
split matrices ``features/thresholds/left/right`` padded to a shared pow2
node bucket (leaves self-loop, so padded navigation steps are no-ops), and
navigation is a static-depth ``fori_loop`` of vectorized gather/compare
over ``(rows, trees)`` — no per-row Python, no host sync.  Leaf
aggregation is one matmul (regression / GBDT raw scores) or a mean+argmax
(classifier probability leaves).

Models that cannot lower (wrappers, arbitrary FQCNs, non-numeric classes,
pathological depth) return ``(None, reason)`` from `try_lower` and keep
the host predict path — declining is a verdict, never an error.
"""
from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

#: hard ceiling on the padded navigation depth (loop trip count baked into
#: the trace) — a deeper ensemble declines to the host path
MAX_TREE_DEPTH = 64

#: hard ceiling on the padded per-tree node bucket: beyond this the split
#: matrices stop being "tiny constants-shaped params" and the host path is
#: the better citizen
MAX_TREE_NODES = 1 << 16

#: hard ceiling on TOTAL padded nodes across an ensemble (trees x bucket):
#: bounds the split-matrix footprint (~28 B/node) that lowering
#: materializes host-side and a fused launch carries — a wider ensemble
#: declines rather than building ~100MB+ of matrices for a verdict
MAX_ENSEMBLE_NODES = 1 << 22


def _pow2(n: int) -> int:
    return 1 << max(0, int(n) - 1).bit_length()


@dataclass(frozen=True)
class ModelProgram:
    """One lowered model: weights as a params pytree + a pure apply fn.

    ``apply(params, X)`` must be traceable under `jax.jit` with ``params``
    as traced arguments and ``X`` a float64 ``(rows, n_features)`` matrix;
    it returns a 1-d prediction vector (``output == "vector"``) or a
    transformed matrix (``output == "matrix"``, e.g. StandardScaler —
    ineligible for the fused PREDICT rung, which appends one column)."""

    kind: str
    params: Tuple[Any, ...]
    apply: Callable[[Tuple[Any, ...], Any], Any]
    #: recompile identity: structure + param shapes/dtypes, never values
    shape_key: Tuple
    meta: Dict[str, Any] = field(default_factory=dict)
    output: str = "vector"

    @property
    def param_bytes(self) -> int:
        # numpy arrays/scalars and jax device arrays all expose .nbytes, so
        # committed (device-resident) params are sized WITHOUT a d2h pull —
        # this property sits on the ledger/metrics scrape path
        return int(sum(p.nbytes if hasattr(p, "nbytes")
                       else np.asarray(p).nbytes for p in self.params))

    def describe(self) -> str:
        """Compact human-readable shape summary for SHOW/DESCRIBE MODEL."""
        m = self.meta
        parts = [self.kind]
        if "trees" in m:
            parts.append(f"trees={m['trees']}")
        if "depth" in m:
            parts.append(f"depth={m['depth']}")
        if "nodes" in m:
            parts.append(f"nodes={m['nodes']}")
        if "features" in m:
            parts.append(f"features={m['features']}")
        if "classes" in m:
            parts.append(f"classes={m['classes']}")
        if "clusters" in m:
            parts.append(f"clusters={m['clusters']}")
        return " ".join(parts)


def _shapes_of(params) -> Tuple:
    return tuple((tuple(np.asarray(p).shape), str(np.asarray(p).dtype))
                 for p in params)


# ---------------------------------------------------------------------------
# tree ensembles: split matrices + static-depth gather/compare navigation
# ---------------------------------------------------------------------------
def _tree_split_matrices(trees, node_bucket: int):
    """Stack fitted sklearn ``Tree`` objects into padded split matrices.

    Leaves (and every padded slot) self-loop — ``left == right == self`` —
    so navigating past a leaf, or past the real depth, is a no-op: ONE
    static trip count serves every tree in the ensemble."""
    T = len(trees)
    idx = np.arange(node_bucket, dtype=np.int32)
    feats = np.zeros((T, node_bucket), dtype=np.int32)
    thrs = np.zeros((T, node_bucket), dtype=np.float64)
    lefts = np.tile(idx, (T, 1))
    rights = np.tile(idx, (T, 1))
    for t, tree in enumerate(trees):
        n = tree.node_count
        leaf = tree.children_left[:n] < 0
        feats[t, :n] = np.where(leaf, 0, tree.feature[:n]).astype(np.int32)
        thrs[t, :n] = np.where(leaf, 0.0, tree.threshold[:n])
        lefts[t, :n] = np.where(leaf, idx[:n],
                                tree.children_left[:n]).astype(np.int32)
        rights[t, :n] = np.where(leaf, idx[:n],
                                 tree.children_right[:n]).astype(np.int32)
    return feats, thrs, lefts, rights


def _navigate(feats, thrs, lefts, rights, X, depth: int):
    """Leaf node index per (row, tree): ``depth`` vectorized
    gather/compare steps — the tensorized tree walk of 2306.08367.

    sklearn evaluates splits on float32-cast inputs against float64
    thresholds; the double cast reproduces its boundary behavior exactly."""
    import jax
    import jax.numpy as jnp

    T = feats.shape[0]
    tr = jnp.arange(T)[None, :]
    Xd = X.astype(jnp.float32).astype(jnp.float64)
    node0 = jnp.zeros((X.shape[0], T), dtype=jnp.int32)

    def step(_, node):
        f = feats[tr, node]
        th = thrs[tr, node]
        xv = jnp.take_along_axis(Xd, f.astype(jnp.int32), axis=1)
        return jnp.where(xv <= th, lefts[tr, node], rights[tr, node])

    return jax.lax.fori_loop(0, depth, step, node0)


def _ensemble_shape(trees, max_depth_hint: Optional[int]
                    ) -> Optional[Tuple[int, int]]:
    """(node_bucket, depth) for an ensemble, padded so a RETRAIN with the
    same hyper-shape lands in the same buckets: depth pads to the model's
    ``max_depth`` when set (else the observed pow2), and the node bucket
    pads to the full-tree bound ``2^(depth+1) - 1`` when that is small
    enough — a bounded-depth retrain then provably reuses the executable.
    None = decline (too deep / too wide)."""
    obs_nodes = max(t.node_count for t in trees)
    obs_depth = max(int(t.max_depth) for t in trees)
    if max_depth_hint is not None and max_depth_hint > 0:
        depth = int(max_depth_hint)
    else:
        depth = _pow2(obs_depth)
    depth = max(depth, obs_depth, 1)
    if depth > MAX_TREE_DEPTH:
        return None
    nodes = obs_nodes
    full = (1 << (depth + 1)) - 1
    if full <= MAX_TREE_NODES:
        nodes = max(nodes, full)
    bucket = _pow2(nodes)
    if bucket > MAX_TREE_NODES:
        return None
    if len(trees) * bucket > MAX_ENSEMBLE_NODES:
        return None
    return bucket, depth


def _numeric_classes(model) -> Optional[np.ndarray]:
    classes = getattr(model, "classes_", None)
    if classes is None:
        return None
    arr = np.asarray(classes)
    if arr.dtype.kind not in "iufb":
        return None  # string labels cannot ride the DOUBLE target column
    return arr.astype(np.float64)


def _lower_tree_regression(trees, weights: np.ndarray, baseline: float,
                           n_features: int, kind: str,
                           max_depth_hint: Optional[int]
                           ) -> Optional[ModelProgram]:
    """Shared lowering of additive regression ensembles: prediction =
    ``leaf_values @ weights + baseline`` (DT: weight 1; RF: 1/T mean;
    GBDT: learning rate folded into ``weights``)."""
    import jax.numpy as jnp

    shape = _ensemble_shape(trees, max_depth_hint)
    if shape is None:
        return None
    bucket, depth = shape
    feats, thrs, lefts, rights = _tree_split_matrices(trees, bucket)
    T = len(trees)
    vals = np.zeros((T, bucket), dtype=np.float64)
    for t, tree in enumerate(trees):
        n = tree.node_count
        vals[t, :n] = tree.value[:n, 0, 0]
    params = (feats, thrs, lefts, rights, vals,
              np.asarray(weights, dtype=np.float64),
              np.asarray(baseline, dtype=np.float64))

    def apply(p, X):
        f, th, l, r, v, w, b = p
        node = _navigate(f, th, l, r, X, depth)
        leafv = v[jnp.arange(T)[None, :], node]
        return leafv @ w + b

    meta = {"trees": T, "depth": depth, "nodes": bucket,
            "features": n_features}
    return ModelProgram(kind, params, apply,
                        (kind, T, bucket, depth, n_features,
                         _shapes_of(params)), meta)


def _lower_tree_classifier(trees, classes: np.ndarray, n_features: int,
                           kind: str, max_depth_hint: Optional[int]
                           ) -> Optional[ModelProgram]:
    """DecisionTree/RandomForest classifiers: probability leaves averaged
    across trees, argmax, class-label gather — matching sklearn's
    mean-of-proba vote exactly (first-max tie-breaking included)."""
    import jax.numpy as jnp

    shape = _ensemble_shape(trees, max_depth_hint)
    if shape is None:
        return None
    bucket, depth = shape
    C = len(classes)
    feats, thrs, lefts, rights = _tree_split_matrices(trees, bucket)
    T = len(trees)
    vals = np.zeros((T, bucket, C), dtype=np.float64)
    for t, tree in enumerate(trees):
        n = tree.node_count
        counts = tree.value[:n, 0, :].astype(np.float64)
        totals = counts.sum(axis=1, keepdims=True)
        vals[t, :n] = counts / np.maximum(totals, 1e-300)
    params = (feats, thrs, lefts, rights, vals, classes)

    def apply(p, X):
        f, th, l, r, v, cls = p
        node = _navigate(f, th, l, r, X, depth)
        pv = v[jnp.arange(T)[None, :], node]      # (rows, trees, classes)
        proba = pv.mean(axis=1)
        return cls[jnp.argmax(proba, axis=1)]

    meta = {"trees": T, "depth": depth, "nodes": bucket,
            "features": n_features, "classes": C}
    return ModelProgram(kind, params, apply,
                        (kind, T, bucket, depth, n_features, C,
                         _shapes_of(params)), meta)


def _gbdt_baseline(model, n_features: int) -> Optional[np.ndarray]:
    """Exact raw-score baseline of a fitted GradientBoosting model, probed
    instead of reverse-engineering ``init_``: with the default (or
    ``'zero'``) init the raw scores are ``const + lr * sum(trees)``, so
    one zero-row probe minus the tree sum recovers the constant.  A
    custom ``init`` estimator makes the init term ROW-DEPENDENT — no
    constant baseline exists and the lowering must decline (a probed
    constant would yield silently wrong fused predictions)."""
    init_param = getattr(model, "init", None)
    if init_param is not None and init_param != "zero":
        return None
    raw_fn = getattr(model, "_raw_predict", None)
    if raw_fn is None:
        return None
    probe = np.zeros((1, n_features), dtype=np.float32)
    try:
        raw = np.asarray(raw_fn(probe), dtype=np.float64).reshape(-1)
    except Exception:  # dsql: allow-broad-except — a probe failure is a
        # decline verdict, never a query error
        return None
    lr = float(model.learning_rate)
    tree_sum = np.array([
        lr * sum(float(est.predict(probe)[0])
                 for est in model.estimators_[:, k])
        for k in range(model.estimators_.shape[1])])
    return raw - tree_sum


def _lower_gbdt_classifier(model, classes: np.ndarray, n_features: int,
                           max_depth_hint: Optional[int]
                           ) -> Optional[ModelProgram]:
    """GradientBoostingClassifier: flattened trees matmul into K raw-score
    columns through a constant stage->class routing matrix, then the loss
    link's decision (binary: raw > 0; multiclass: argmax)."""
    import jax.numpy as jnp

    baseline = _gbdt_baseline(model, n_features)
    if baseline is None:
        return None
    stages, K = model.estimators_.shape
    trees = [est.tree_ for k in range(K)
             for est in model.estimators_[:, k]]
    shape = _ensemble_shape(trees, max_depth_hint)
    if shape is None:
        return None
    bucket, depth = shape
    feats, thrs, lefts, rights = _tree_split_matrices(trees, bucket)
    T = len(trees)
    vals = np.zeros((T, bucket), dtype=np.float64)
    route = np.zeros((T, K), dtype=np.float64)
    lr = float(model.learning_rate)
    i = 0
    for k in range(K):
        for est in model.estimators_[:, k]:
            n = est.tree_.node_count
            vals[i, :n] = est.tree_.value[:n, 0, 0]
            route[i, k] = lr
            i += 1
    params = (feats, thrs, lefts, rights, vals, route,
              baseline.astype(np.float64), classes)
    binary = K == 1

    def apply(p, X):
        f, th, l, r, v, m, b, cls = p
        node = _navigate(f, th, l, r, X, depth)
        leafv = v[jnp.arange(T)[None, :], node]
        raw = leafv @ m + b
        if binary:
            idx = (raw[:, 0] > 0).astype(jnp.int32)
        else:
            idx = jnp.argmax(raw, axis=1)
        return cls[idx]

    meta = {"trees": T, "depth": depth, "nodes": bucket,
            "features": n_features, "classes": len(classes)}
    return ModelProgram("gbdt_classifier", params, apply,
                        ("gbdt_classifier", T, bucket, depth, n_features,
                         K, len(classes), _shapes_of(params)), meta)


# ---------------------------------------------------------------------------
# linear / logistic / kmeans / scaler
# ---------------------------------------------------------------------------
def _lower_linear(W: np.ndarray, b: np.ndarray, n_features: int,
                  x_dtype: np.dtype) -> ModelProgram:
    import jax.numpy as jnp

    params = (np.asarray(W), np.asarray(b))
    dt = np.dtype(x_dtype)

    def apply(p, X):
        w, bias = p
        return (X.astype(dt) @ w + bias).astype(jnp.float64)

    meta = {"features": n_features}
    return ModelProgram("linear", params, apply,
                        ("linear", n_features, str(dt), _shapes_of(params)),
                        meta)


def _lower_logistic(W: np.ndarray, b: np.ndarray, classes: np.ndarray,
                    n_features: int, x_dtype: np.dtype) -> ModelProgram:
    """Binary: decision_function > 0 -> classes[1] (sklearn semantics and
    the jax model's ``sigmoid > 0.5`` are the same boundary).  Multiclass
    (one-vs-rest raw scores): argmax."""
    import jax.numpy as jnp

    W = np.asarray(W)
    binary = W.ndim == 1
    params = (W, np.asarray(b), classes)
    dt = np.dtype(x_dtype)

    def apply(p, X):
        w, bias, cls = p
        raw = X.astype(dt) @ (w if binary else w.T) + bias
        if binary:
            idx = (raw > 0).astype(jnp.int32)
        else:
            idx = jnp.argmax(raw, axis=1)
        return cls[idx]

    meta = {"features": n_features, "classes": len(classes)}
    return ModelProgram("logistic", params, apply,
                        ("logistic", n_features, len(classes), binary,
                         str(dt), _shapes_of(params)), meta)


def _lower_kmeans(centers: np.ndarray, n_features: int,
                  x_dtype: np.dtype) -> ModelProgram:
    """Distance-argmin as one matmul: ``argmin(||c||^2 - 2 X c^T)`` (the
    row's own norm is constant under argmin)."""
    import jax.numpy as jnp

    params = (np.asarray(centers),)
    dt = np.dtype(x_dtype)

    def apply(p, X):
        (c,) = p
        Xd = X.astype(dt)
        d = jnp.sum(c * c, axis=1)[None, :] - 2.0 * (Xd @ c.T)
        return jnp.argmin(d, axis=1).astype(jnp.float64)

    meta = {"features": n_features, "clusters": int(centers.shape[0])}
    return ModelProgram("kmeans", params, apply,
                        ("kmeans", n_features, int(centers.shape[0]),
                         str(dt), _shapes_of(params)), meta)


def _lower_scaler(mean: np.ndarray, scale: np.ndarray,
                  n_features: int) -> ModelProgram:
    """StandardScaler transform as subtract+scale — a ``matrix`` program:
    composable in tensor pipelines, ineligible for the one-column fused
    PREDICT rung."""
    params = (np.asarray(mean, dtype=np.float64),
              np.asarray(scale, dtype=np.float64))

    def apply(p, X):
        m, s = p
        return (X - m) / s

    return ModelProgram("scaler", params, apply,
                        ("scaler", n_features, _shapes_of(params)),
                        {"features": n_features}, output="matrix")


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------
def try_lower(model, n_features: Optional[int] = None
              ) -> Tuple[Optional[ModelProgram], str]:
    """``(program, reason)``: the lowered tensor program, or ``(None,
    why)`` when the model keeps the host predict path.  Never raises —
    declining is a verdict, not an error."""
    try:
        program = _dispatch(model, n_features)
    except Exception as exc:  # dsql: allow-broad-except — an exotic fitted
        # model must decline to the host path, never fail the query
        logger.debug("model lowering failed open", exc_info=True)
        return None, f"lowering error: {type(exc).__name__}: {exc}"
    if isinstance(program, str):
        return None, program
    if program is None:
        return None, f"no tensor lowering for {type(model).__name__}"
    return program, "lowered"


def _dispatch(model, n_features: Optional[int]):
    """Returns a ModelProgram, a decline-reason string, or None."""
    from ..ml import jax_models
    from ..ml.wrappers import Incremental, ParallelPostFit

    if isinstance(model, (ParallelPostFit, Incremental)):
        return "wrapped model (wrap_predict/wrap_fit) keeps the host path"

    # --- engine-native jax models -----------------------------------------
    if isinstance(model, jax_models.LinearRegression):
        if model._w is None:
            return "model is not fitted"
        w = np.asarray(model._w, dtype=np.float32)
        if model.fit_intercept:
            return _lower_linear(w[:-1], w[-1], len(w) - 1, np.float32)
        return _lower_linear(w, np.float32(0.0), len(w), np.float32)
    if isinstance(model, jax_models.LogisticRegression):
        if model._w is None:
            return "model is not fitted"
        classes = _numeric_classes(model)
        if classes is None:
            return "non-numeric class labels"
        w = np.asarray(model._w, dtype=np.float32)
        if model.fit_intercept:
            return _lower_logistic(w[:-1], w[-1], classes, len(w) - 1,
                                   np.float32)
        return _lower_logistic(w, np.float32(0.0), classes, len(w),
                               np.float32)
    if isinstance(model, jax_models.KMeans):
        if model.cluster_centers_ is None:
            return "model is not fitted"
        centers = np.asarray(model.cluster_centers_, dtype=np.float32)
        return _lower_kmeans(centers, centers.shape[1], np.float32)

    # --- sklearn ----------------------------------------------------------
    name = type(model).__name__
    mod = type(model).__module__
    if not mod.startswith("sklearn."):
        return None
    nf = getattr(model, "n_features_in_", n_features)
    if nf is None:
        return "model is not fitted"
    nf = int(nf)
    if int(getattr(model, "n_outputs_", 1) or 1) != 1:
        # tree.value[:, 0, :] would silently discard every output but the
        # first — multi-output models keep the host path
        return "multi-output model"
    depth_hint = getattr(model, "max_depth", None)
    if name == "StandardScaler":
        mean = getattr(model, "mean_", None)
        scale = getattr(model, "scale_", None)
        if scale is None:
            return "model is not fitted"
        if mean is None:
            mean = np.zeros(nf)
        return _lower_scaler(mean, scale, nf)
    if name in ("LinearRegression", "Ridge", "Lasso", "SGDRegressor"):
        coef = np.asarray(model.coef_, dtype=np.float64)
        if coef.ndim > 1 and coef.shape[0] != 1:
            return "multi-output model"  # reshape(-1) would mis-shape it
        coef = coef.reshape(-1)
        intercept = np.asarray(model.intercept_,
                               dtype=np.float64).reshape(-1)[0]
        return _lower_linear(coef, np.float64(intercept), nf, np.float64)
    if name in ("LogisticRegression", "SGDClassifier"):
        classes = _numeric_classes(model)
        if classes is None:
            return "non-numeric class labels"
        W = np.asarray(model.coef_, dtype=np.float64)
        b = np.asarray(model.intercept_, dtype=np.float64)
        if W.shape[0] == 1:
            return _lower_logistic(W[0], b[0], classes, nf, np.float64)
        return _lower_logistic(W, b, classes, nf, np.float64)
    if name == "KMeans":
        return _lower_kmeans(np.asarray(model.cluster_centers_,
                                        dtype=np.float64), nf, np.float64)
    if name == "DecisionTreeRegressor":
        return _lower_tree_regression([model.tree_], np.ones(1), 0.0, nf,
                                      "tree_regressor", depth_hint)
    if name == "DecisionTreeClassifier":
        classes = _numeric_classes(model)
        if classes is None:
            return "non-numeric class labels"
        return _lower_tree_classifier([model.tree_], classes, nf,
                                      "tree_classifier", depth_hint)
    if name == "RandomForestRegressor":
        trees = [e.tree_ for e in model.estimators_]
        return _lower_tree_regression(
            trees, np.full(len(trees), 1.0 / len(trees)), 0.0, nf,
            "forest_regressor", depth_hint)
    if name == "RandomForestClassifier":
        classes = _numeric_classes(model)
        if classes is None:
            return "non-numeric class labels"
        return _lower_tree_classifier([e.tree_ for e in model.estimators_],
                                      classes, nf, "forest_classifier",
                                      depth_hint)
    if name == "GradientBoostingRegressor":
        baseline = _gbdt_baseline(model, nf)
        if baseline is None:
            return "gbdt baseline probe failed"
        trees = [e.tree_ for e in model.estimators_[:, 0]]
        lr = float(model.learning_rate)
        return _lower_tree_regression(
            trees, np.full(len(trees), lr), float(baseline[0]), nf,
            "gbdt_regressor", depth_hint)
    if name == "GradientBoostingClassifier":
        classes = _numeric_classes(model)
        if classes is None:
            return "non-numeric class labels"
        return _lower_gbdt_classifier(model, classes, nf, depth_hint)
    return None
