"""Arrow <-> device Table conversion (zero-ish-copy ingest path).

Role parity: the reference's IO boundary is dask's `read_parquet` into pandas
partitions; ours is pyarrow -> numpy -> jax device buffers, with Arrow
dictionary arrays mapping directly onto our dictionary-encoded string columns.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from .column import Column
from .dtypes import STRING_TYPES, SqlType
from .table import Table


def arrow_to_table(at) -> Table:
    import pyarrow as pa
    import pyarrow.compute as pc

    cols = {}
    for name, col in zip(at.column_names, at.columns):
        arr = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
        cols[name] = _arrow_array_to_column(arr)
    return Table(cols, at.num_rows)


def _arrow_array_to_column(arr) -> Column:
    import pyarrow as pa
    import pyarrow.compute as pc

    mask = None
    if arr.null_count:
        mask = np.asarray(pc.is_valid(arr))
    t = arr.type
    if pa.types.is_dictionary(t):
        codes = np.asarray(arr.indices.fill_null(0)).astype(np.int32)
        uniques = np.asarray(arr.dictionary.to_pylist(), dtype=object)
        if len(uniques) == 0:
            uniques = np.array([""], dtype=object)
        return Column(jnp.asarray(codes), SqlType.VARCHAR, _mask(mask), uniques)
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        enc = pc.dictionary_encode(arr)
        return _arrow_array_to_column(enc)
    if pa.types.is_timestamp(t):
        ns = np.asarray(arr.cast(pa.timestamp("ns")).fill_null(0)).astype("datetime64[ns]").view(np.int64)
        return _build(ns, mask, SqlType.TIMESTAMP)
    if pa.types.is_date(t):
        ns = np.asarray(arr.cast(pa.timestamp("ns")).fill_null(0)).astype("datetime64[ns]").view(np.int64)
        return _build(ns, mask, SqlType.DATE)
    if pa.types.is_decimal(t):
        vals = np.asarray(arr.cast(pa.float64()).fill_null(0.0))
        return _build(vals, mask, SqlType.DECIMAL)
    if pa.types.is_boolean(t):
        vals = np.asarray(arr.fill_null(False))
        return Column(jnp.asarray(vals), SqlType.BOOLEAN, _mask(mask))
    vals = np.asarray(arr.fill_null(0)) if arr.null_count else np.asarray(arr)
    return Column.from_numpy(vals, mask)


def _mask(mask):
    if mask is None or mask.all():
        return None
    return jnp.asarray(mask)


def _build(vals, mask, sql_type) -> Column:
    """Device column from an already-device-repr host array; the load scope
    may pick a compressed encoding (columnar/encodings.py)."""
    from .encodings import maybe_encode

    col = maybe_encode(vals, mask, sql_type)
    if col is not None:
        return col
    return Column(jnp.asarray(vals), sql_type, _mask(mask))


def table_to_arrow(table: Table):
    import pyarrow as pa

    arrays, names = [], []
    for name, col in table.columns.items():
        names.append(name)
        if col.sql_type in STRING_TYPES:
            codes = np.asarray(col.data)
            d = col.dictionary if col.dictionary is not None else np.array([""], dtype=object)
            codes = np.clip(codes, 0, len(d) - 1).astype(np.int32)
            valid = None if col.validity is None else np.asarray(col.validity)
            ind = pa.array(codes, mask=None if valid is None else ~valid)
            arrays.append(pa.DictionaryArray.from_arrays(ind, pa.array(d.astype(str))))
        else:
            np_vals = col.to_numpy()
            arrays.append(pa.array(np_vals))
    return pa.table(arrays, names=names)
