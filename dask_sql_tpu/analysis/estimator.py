"""Static cost & memory abstract interpreter over bound plans.

On a TPU the shapes, dtypes and pad buckets of every query are known
statically (TQP, arXiv:2203.01877; Flare's native operator cost models,
arXiv:1703.08219), so most OOMs and doomed compilations are provable before
XLA ever sees the plan.  This module is the general version of the
verifier's narrow radix proof (`verifier.py`): a **bottom-up walk** of the
bound logical plan that propagates, per node,

- a **cardinality interval** ``[rows_lo, rows_hi]`` seeded from catalog
  ``statistics.row_count`` (exact at registration time and versioned into
  every cache key), narrowed by LIMIT / Sample / aggregate-domain clamps
  and widened by joins — filters and joins contribute a *zero* lower bound
  because selectivity is unknowable statically;
- a **byte-footprint interval** for the node's output table, derived from
  the device representation of each declared column dtype
  (``columnar/dtypes.py`` widths; strings are int32 dictionary codes) —
  the lower bound uses the exact row count and data buffers only, the
  upper bound the padded power-of-two bucket the compiled paths key their
  shapes on plus a one-byte validity mask per nullable column (a mask is
  materialized only when nulls occur, so it is never provable).

The whole-plan verdict (`PlanEstimate`) carries two numbers policy layers
act on:

- ``peak_bytes.lo`` — a **provable lower bound** on peak device bytes:
  the referenced base tables are HBM-resident and the root result must
  materialize, whatever rung executes (compiled fusion may skip every
  intermediate, so only those two are provable).  Admission control sheds
  a query whose *lower* bound exceeds the device budget before any
  compilation (`serving/admission.py`).
- ``peak_bytes.hi`` — a **conservative upper bound**: the executor memoizes
  every node's output until the query completes, so the bound sums every
  node's padded output plus the worst-case transient buffers (sort
  scratch, the compiled aggregate's domain-sized packed matrix, capped by
  the shared ``1 << 22`` radix gate).  ``None`` means unbounded (some scan
  had no statistics, or a join's blowup is unknowable).

Consumers:

1. ``EXPLAIN ESTIMATE <query>`` (both the native C++ parser/binder path
   and the Python fallback) renders the estimate as rows;
2. the ``serving.admission.max_estimated_bytes`` gate and result-cache
   admission (skip caching results whose estimated bytes exceed the
   per-entry cap instead of materializing then evicting);
3. the degradation ladder: an Aggregate whose compiled intermediate-buffer
   *lower* bound cannot fit ``analysis.estimate.device_budget_bytes`` has
   its compiled rungs pre-skipped (``_dsql_skip_rungs``), recorded under
   ``analysis.rung_skip.*`` with no breaker charge — the same mechanism
   as the radix proof, generalized to bytes.
"""
from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..columnar.dtypes import SqlType, sql_to_np
from ..planner import plan as p
from ..planner.expressions import (
    ExistsExpr,
    Expr,
    Field,
    InSubqueryExpr,
    ScalarSubqueryExpr,
    SortKey,
    walk,
)
from ..ops.grouping import RADIX_DOMAIN_LIMIT
from .verifier import _pow2_bucket, _Verifier

logger = logging.getLogger(__name__)

#: compiled rungs a too-big aggregate intermediate buffer dooms (the same
#: pair the radix-domain proof skips — both planners share the packed
#: domain-sized output matrix)
_AGG_RUNGS = frozenset({"compiled_aggregate", "compiled_join_aggregate"})

#: bytes per packed-matrix row slot (outputs ride one f64 matrix,
#: physical/compiled.py pack_flat)
_PACKED_SLOT_BYTES = 8


# ---------------------------------------------------------------------------
# interval lattice
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class Interval:
    """Closed integer interval ``[lo, hi]``; ``hi is None`` = unbounded.

    The lattice is the usual interval domain: lo is always a provable
    lower bound, hi a conservative upper bound or None when no finite
    claim can be made.  All arithmetic saturates None."""

    lo: int
    hi: Optional[int]

    @staticmethod
    def exact(n: int) -> "Interval":
        return Interval(int(n), int(n))

    @staticmethod
    def unknown() -> "Interval":
        return Interval(0, None)

    def __add__(self, other: "Interval") -> "Interval":
        hi = None if self.hi is None or other.hi is None \
            else self.hi + other.hi
        return Interval(self.lo + other.lo, hi)

    def __mul__(self, other: "Interval") -> "Interval":
        hi = None if self.hi is None or other.hi is None \
            else self.hi * other.hi
        return Interval(self.lo * other.lo, hi)

    def clamp_hi(self, cap: Optional[int]) -> "Interval":
        """Tighten the upper bound to ``cap`` (lo is clamped along so the
        interval stays well-formed, e.g. LIMIT under a known row count)."""
        if cap is None:
            return self
        hi = cap if self.hi is None else min(self.hi, cap)
        return Interval(min(self.lo, hi), hi)

    def drop_lo(self) -> "Interval":
        """Selectivity unknown: keep the upper bound, lower goes to 0."""
        return Interval(0, self.hi)

    def fmt(self) -> str:
        hi = "unbounded" if self.hi is None else str(self.hi)
        return f"[{self.lo}, {hi}]"


ZERO = Interval(0, 0)


def _dtype_width(t: SqlType) -> int:
    """Device bytes per value for one SQL type (strings are int32 codes;
    the host dictionary is not device memory and is not counted)."""
    try:
        return int(sql_to_np(t).itemsize)
    except Exception:  # dsql: allow-broad-except — exotic type: widest claim
        return 8


def _row_bytes(fields: List[Field]) -> Tuple[int, int]:
    """``(lo, hi)`` device bytes per row of a schema.  Data buffers always
    count; the 1-byte validity mask of a nullable column counts only in
    ``hi`` — columnar/column.py materializes a mask only when nulls
    actually occur, so a nullable *declaration* proves nothing and the
    lower bound (which admission sheds on) must not charge it."""
    data = sum(_dtype_width(f.sql_type) for f in fields)
    masks = sum(1 for f in fields if f.nullable)
    return data, data + masks


def _table_bytes(fields: List[Field], rows: Interval) -> Interval:
    """Output-table bytes for ``rows`` of ``fields``: lo = exact rows x
    mask-free width, hi = padded pow2 bucket (the shape the compiled paths
    actually allocate) x mask-inclusive width."""
    lo_row, hi_row = _row_bytes(fields)
    hi = None if rows.hi is None else (_pow2_bucket(rows.hi) or 0) * hi_row
    return Interval(rows.lo * lo_row, hi)


# ---------------------------------------------------------------------------
# results
# ---------------------------------------------------------------------------
@dataclass
class NodeEstimate:
    label: str
    rows: Interval
    out_bytes: Interval
    #: transient device buffers beyond the output (sort scratch, packed
    #: aggregate matrix); lo stays 0 — which buffers exist depends on which
    #: rung runs, so nothing transient is provable
    scratch_hi: Optional[int] = 0


@dataclass
class PlanEstimate:
    """Whole-plan verdict of one estimation walk."""

    rows: Interval              # root cardinality
    result_bytes: Interval      # d2h bytes of the materialized root table
    peak_bytes: Interval        # peak device bytes (see module docstring)
    nodes: List[NodeEstimate]
    #: [(Aggregate node, rungs, intermediate lower bound)] proofs attached
    #: by apply() — compiled rungs whose buffers provably cannot fit
    rung_proofs: List[Tuple[p.LogicalPlan, frozenset, int]]
    #: mesh width backing the estimate: >1 when a scanned table is
    #: row-sharded, in which case resident-scan LOWER bounds are PER-DEVICE
    #: bytes (the admission gate then budgets per-chip HBM instead of
    #: shedding queries that fit the mesh) — upper bounds stay global,
    #: which is conservative either way
    devices: int = 1
    #: True when profile feedback tightened the UPPER bounds
    #: (`apply_feedback`): his are then empirical *predictions* (observed
    #: family history x a safety margin), no longer worst-case claims.
    #: Lower bounds are untouched — they stay provable, so the admission
    #: shed and every rung proof keep their soundness regardless
    feedback: bool = False
    #: provable floor of the RESIDENT base-table scans alone (the scan part
    #: of ``peak_bytes.lo``): the streaming partitioner (streaming/plan.py)
    #: divides this by the partition count to derive the per-chunk floor —
    #: the non-scan remainder (materialized root, per-device exchange) does
    #: not shrink with partitioning and must stay whole
    scan_bytes_lo: int = 0
    #: one ``model: ...`` line per PREDICT node (inference/): serving tier,
    #: device-resident param bytes, program shape — rendered by EXPLAIN
    #: ESTIMATE so admission decisions over inference plans are explainable
    model_rows: List[str] = None

    def format_rows(self) -> List[str]:
        rows = [
            "estimate: rows_lo={} rows_hi={} bytes_lo={} bytes_hi={}".format(
                self.rows.lo,
                "unbounded" if self.rows.hi is None else self.rows.hi,
                self.peak_bytes.lo,
                "unbounded" if self.peak_bytes.hi is None
                else self.peak_bytes.hi),
            f"result: bytes={self.result_bytes.fmt()} (d2h transfer)",
        ]
        rows.extend(self.model_rows or [])
        if self.devices > 1:
            rows.insert(1, f"mesh: devices={self.devices} "
                           "(sharded scans budgeted per device)")
        if self.feedback:
            rows.insert(1, "feedback: upper bounds tightened from observed "
                           "family history (lower bounds stay provable)")
        for n in self.nodes:
            if n.scratch_hi is None:
                # the node whose transients made bytes_hi unbounded must be
                # findable in the listing, not look scratch-free
                scratch = " scratch_hi=unbounded"
            elif n.scratch_hi:
                scratch = f" scratch_hi={n.scratch_hi}"
            else:
                scratch = ""
            rows.append(f"node {n.label}: rows={n.rows.fmt()} "
                        f"bytes={n.out_bytes.fmt()}{scratch}")
        for node, rungs, lo in self.rung_proofs:
            rows.append(
                f"proof {node._label()}: compiled intermediate >= {lo} "
                f"bytes cannot fit the device budget; rungs pre-skipped "
                f"({', '.join(sorted(rungs))})")
        return rows


# ---------------------------------------------------------------------------
# the abstract interpreter
# ---------------------------------------------------------------------------
class _Estimator:
    def __init__(self, context=None):
        self.context = context
        # the verifier owns the catalog lookups and the radix-domain proof;
        # reuse them so the two walks can never disagree about metadata
        self._v = _Verifier(context=context, collect_info=False)
        self.nodes: List[NodeEstimate] = []
        self.agg_intermediates: List[Tuple[p.Aggregate, int]] \
            = []  # (node, packed-matrix lower bound)
        self._memo: Dict[int, Tuple[Interval, Interval]] = {}
        self._scan_lo: Dict[Tuple[str, str], int] = {}
        self.model_rows: List[str] = []
        #: id(TableScan) -> exact resident bytes when the scanned table is
        #: registered with compressed encodings (columnar/encodings.py)
        self._scan_actual: Dict[int, int] = {}
        #: mesh width: max devices any scanned sharded table spans
        self.devices: int = 1

    # ------------------------------------------------------------- walking
    def estimate(self, node: p.LogicalPlan) -> Tuple[Interval, Interval]:
        """(rows, out_bytes) of one node; memoized so shared CTE subtrees
        are counted once (matching the executor's own memoization)."""
        key = id(node)
        if key in self._memo:
            return self._memo[key]
        rows, out_bytes, scratch_hi = self._node(node)
        self._memo[key] = (rows, out_bytes)
        self.nodes.append(NodeEstimate(_short_label(node), rows, out_bytes,
                                       scratch_hi))
        return rows, out_bytes

    def _node(self, node: p.LogicalPlan
              ) -> Tuple[Interval, Interval, Optional[int]]:
        child = [self.estimate(c) for c in node.inputs()]
        for sub in _nested_plans(node):
            # subquery plans execute too: their outputs join the footprint
            self.estimate(sub)
        rows = self._rows(node, [r for r, _ in child])
        actual = self._scan_actual.get(id(node))
        if actual is not None:
            # a registered table with compressed encodings: the scan's
            # output IS the stored buffers, whose bytes are exact — both
            # bounds tighten to the encoded widths, which is how encodings
            # shrink peak_bytes.hi and admit bigger working sets
            out_bytes = Interval(actual, actual)
        else:
            out_bytes = _table_bytes(list(node.schema), rows)
        scratch_hi: Optional[int] = 0
        if isinstance(node, p.Aggregate):
            scratch_hi = self._aggregate_scratch(node, child)
        elif isinstance(node, p.PredictModelNode):
            scratch_hi = self._predict_scratch(node, rows)
        elif isinstance(node, (p.Sort, p.Distinct, p.Window)):
            # sort-based paths keep permutation indices + a key copy: bound
            # by 2x the input's padded bytes
            in_hi = child[0][1].hi if child else 0
            scratch_hi = None if in_hi is None else 2 * in_hi
        return rows, out_bytes, scratch_hi

    # ---------------------------------------------------------- cardinality
    def _rows(self, node: p.LogicalPlan,
              child_rows: List[Interval]) -> Interval:
        if isinstance(node, p.TableScan):
            n = self._v._table_rows(node.schema_name, node.table_name)
            if n is None:
                return Interval.unknown()
            # the base table is HBM-resident at its FULL row count whatever
            # the scan's pushed filters keep — its projected columns are a
            # provable part of peak device bytes.  When the stored table
            # carries compressed encodings, its ACTUAL (encoded) bytes are
            # both the provable floor and the exact output size.
            key = (node.schema_name, node.table_name)
            actual = self._scan_actual_bytes(node)
            if actual is not None:
                self._scan_actual[id(node)] = actual
                scan_lo = actual
            else:
                scan_lo = int(n) * _row_bytes(list(node.schema))[0]
            ndev = self._scan_mesh_devices(node)
            if ndev > 1:
                # row-sharded table: each chip holds ~1/ndev of the scan, so
                # the PER-DEVICE provable floor (what admission sheds on)
                # divides — the mesh serves working sets a single chip
                # cannot.  Upper bounds stay global (conservative).
                scan_lo = -(-scan_lo // ndev)
                self.devices = max(self.devices, ndev)
            self._scan_lo[key] = max(self._scan_lo.get(key, 0), scan_lo)
            rows = Interval.exact(int(n))
            if node.filters:
                rows = rows.drop_lo()  # pushed-down filters: selectivity 0..1
            return rows
        if isinstance(node, p.Filter):
            return child_rows[0].drop_lo()
        if isinstance(node, p.Projection):
            return child_rows[0]
        if isinstance(node, p.Join):
            l, r = child_rows
            jt = node.join_type.upper()
            if jt in ("LEFTSEMI", "LEFTANTI"):
                return Interval(0, l.hi)
            if jt == "LEFTMARK":
                return l  # left rows + an appended BOOLEAN flag
            unknown = l.hi is None or r.hi is None
            if jt == "LEFT":
                # every left row survives even against an empty right side
                hi = None if unknown else l.hi * max(r.hi, 1)
                return Interval(l.lo, hi)
            if jt == "RIGHT":
                hi = None if unknown else r.hi * max(l.hi, 1)
                return Interval(r.lo, hi)
            if jt == "FULL":
                # matched pairs + unmatched left rows + unmatched right rows
                hi = None if unknown else l.hi * max(r.hi, 1) + r.hi
                return Interval(max(l.lo, r.lo), hi)
            hi = None if unknown else l.hi * r.hi
            return Interval(0, hi)  # INNER: can be empty
        if isinstance(node, p.CrossJoin):
            return child_rows[0] * child_rows[1]
        if isinstance(node, p.Aggregate):
            if not node.group_exprs:
                return Interval.exact(1)
            inp = child_rows[0]
            lo = 1 if inp.lo > 0 else 0
            domain, all_known = self._v._radix_domain(node)
            hi = inp.hi
            if all_known and domain is not None:
                hi = domain if hi is None else min(hi, domain)
            return Interval(lo, hi)
        if isinstance(node, p.Window):
            return child_rows[0]
        if isinstance(node, p.Sort):
            rows = child_rows[0]
            return rows.clamp_hi(node.fetch) if node.fetch is not None \
                else rows
        if isinstance(node, p.Limit):
            rows = child_rows[0].clamp_hi(node.fetch)
            return rows.drop_lo() if node.skip else rows
        if isinstance(node, p.Distinct):
            rows = child_rows[0]
            return Interval(min(rows.lo, 1), rows.hi)
        if isinstance(node, p.Sample):
            return child_rows[0].drop_lo()
        if isinstance(node, p.Union):
            total = ZERO
            for r in child_rows:
                total = total + r
            if not getattr(node, "all", True):
                total = Interval(min(total.lo, 1), total.hi)  # dedup
            return total
        if isinstance(node, (p.Intersect, p.Except)):
            return Interval(0, child_rows[0].hi)
        if isinstance(node, p.Values):
            return Interval.exact(len(node.rows))
        if isinstance(node, p.EmptyRelation):
            return Interval.exact(1 if node.produce_one_row else 0)
        if isinstance(node, p.Explain):
            # plain EXPLAIN/LINT/ESTIMATE renders text, never executes its
            # input; EXPLAIN ANALYZE executes, so it inherits the input walk
            # (already folded in through child_rows' side effects)
            return Interval(1, None)
        if isinstance(node, (p.SubqueryAlias, p.DistributeBy)):
            return child_rows[0]
        if isinstance(node, p.PredictModelNode):
            # PREDICT appends one column per input row — cardinality is the
            # input's, so inference plans get FINITE bounds and admission /
            # packing / streaming see them like any other operator
            return child_rows[0] if child_rows else Interval.unknown()
        if isinstance(node, p.CustomNode):
            return Interval(0, None)
        return child_rows[0] if child_rows else Interval.unknown()

    def _scan_mesh_devices(self, node: p.TableScan) -> int:
        """Mesh width of the scanned table's storage: the number of devices
        its buffers are row-sharded over, or 1 (single-device / lazy /
        unknown) — the shared spmd.core resolution rule."""
        try:
            from ..spmd.core import resolve_sharded_scan

            got = resolve_sharded_scan(self.context, node)
            return int(got[1].devices.size) if got is not None else 1
        except Exception:  # dsql: allow-broad-except — backend teardown /
            # deleted buffers mid-estimate: single-device is the safe claim
            return 1

    def _scan_actual_bytes(self, node: p.TableScan) -> Optional[int]:
        """Exact resident bytes of the scan's projected columns when the
        registered table carries compressed encodings; None keeps the
        declared-width formula (byte-identical estimates for PLAIN tables).
        Encoded widths are what the compiled paths actually read, so both
        peak bounds tighten — the admission gate sheds less and the
        device-budget rung proofs skip fewer rungs."""
        from ..columnar.encodings import encoded_nbytes, resolve_encoded_scan

        got = resolve_encoded_scan(self.context, node)
        if got is None:
            return None
        table, names = got
        total = sum(encoded_nbytes(table.columns[n]) for n in names)
        if table.row_valid is not None:
            total += int(table.row_valid.nbytes)
        return total

    # --------------------------------------------------------- intermediates
    def _aggregate_scratch(self, node: p.Aggregate,
                           child) -> Optional[int]:
        """Worst-case transient bytes of the aggregate, and (side effect)
        the compiled packed-matrix *lower* bound for the rung proof.

        The compiled rungs allocate one f64 matrix of
        ``(len(agg_exprs) + 1) x domain`` (physical/compiled.py pack_flat;
        row 0 is the group-present indicator) plus an int32 gid per input
        row.  The radix-domain lower bound (dictionary sizes + BOOLEAN=3,
        unknown keys contribute factor 1) makes the matrix bound provable;
        the gate caps the domain at ``1 << 22``, which caps the upper
        bound even when the true domain is unknown."""
        domain, all_known = self._v._radix_domain(node)
        slots = len(node.agg_exprs) + 1
        cap_hi = RADIX_DOMAIN_LIMIT * slots * _PACKED_SLOT_BYTES
        if domain is not None and all_known:
            # every key sized (a global aggregate's domain is exactly 1):
            # the gate cap tightens to the true matrix size
            cap_hi = min(domain, RADIX_DOMAIN_LIMIT) * slots \
                * _PACKED_SLOT_BYTES
        if domain is not None and node.group_exprs:
            matrix_lo = domain * slots * _PACKED_SLOT_BYTES
            self.agg_intermediates.append((node, matrix_lo))
        in_rows_hi = child[0][0].hi if child else 0
        gid_hi = None if in_rows_hi is None \
            else (_pow2_bucket(in_rows_hi) or 0) * 4
        if gid_hi is None:
            return None
        return cap_hi + gid_hi + self._exchange_scratch(node, domain,
                                                        all_known)

    def _predict_scratch(self, node: p.PredictModelNode,
                         rows: Interval) -> Optional[int]:
        """Transient device bytes of one PREDICT node, and (side effect)
        the ``model:`` EXPLAIN ESTIMATE row.

        The fused rung (physical/compiled_predict.py) materializes the
        feature matrix and, for tree programs, (rows, trees)-shaped
        navigation buffers over the survivor bucket; the host tier
        materializes the feature matrix host-side but the estimate charges
        it identically (conservative).  Model params feed the UPPER bound
        only: they are device-resident only IF the fused rung serves this
        plan, which per-plan eligibility (lazy/view/sharded scans,
        nullable or string features) can deny — so charging them to the
        provable floor could shed a host-served plan.  Actual committed
        bytes are the HBM ledger's job (``serving.ledger.model_bytes``)."""
        program = None
        param_bytes = 0
        tier = "host"
        label = "?"
        n_features = max(len(node.schema) - 1, 1)
        try:
            ctx = self.context
            # the fused rung is what makes params device-resident: with it
            # disabled every PREDICT serves host-side
            fused_on = ctx is not None \
                and ctx.config.get("sql.compile.predict", True) \
                and ctx.config.get("sql.compile", True)
            if ctx is not None:
                schema_name, name = ctx._table_schema_name(node.model_name)
                label = name
                model, cols = ctx.get_model(schema_name, name)
                n_features = max(len(cols), 1)
                from ..inference import program_for

                program, _reason = program_for(ctx, schema_name, name,
                                               model)
                if program is not None and fused_on:
                    param_bytes = program.param_bytes
                    tier = "compiled"
        except Exception:  # dsql: allow-broad-except — estimation is
            # advisory; an unresolvable model keeps the host-tier claim
            logger.debug("predict estimate model lookup failed",
                         exc_info=True)
        from ..inference import predict_scratch_bytes

        per_row = predict_scratch_bytes(program, n_features)
        self.model_rows.append(
            f"model: name={label} tier={tier} param_bytes={param_bytes} "
            f"features={n_features} row_floor={per_row}")
        if rows.hi is None:
            return None
        return param_bytes + (_pow2_bucket(rows.hi) or 0) * per_row

    def _exchange_scratch(self, node: p.Aggregate, domain, all_known) -> int:
        """Per-device exchange-buffer bytes of the sharded aggregation
        paths (spmd/dist): send + receive [ndev, cpeer] blocks of the
        6-state layout, sized against the capacity ladder rung the group
        domain lands on (parallel/dist_plan.py GROUP/PEER ladders).  Zero
        on single-device plans AND on aggregates whose own input subtree
        is unsharded (they execute single-chip even when another scan in
        the plan is sharded), so those estimates are unchanged."""
        ndev = self.devices
        if ndev <= 1:
            return 0
        try:
            from ..parallel.dist_plan import plan_has_sharded_scan

            inputs = node.inputs()
            if self.context is None or not inputs or \
                    not plan_has_sharded_scan(inputs[0], self.context):
                return 0
        except Exception:  # dsql: allow-broad-except — probe failure keeps
            # the conservative (charged) upper bound
            pass
        from ..parallel.dist_plan import (
            GROUP_CAPACITY_LADDER,
            N_FSTATE,
            N_ISTATE,
            PEER_CAPACITY_LADDER,
            _ladder_at_least,
        )

        need = domain if (domain is not None and all_known) \
            else RADIX_DOMAIN_LIMIT
        cap = _ladder_at_least(GROUP_CAPACITY_LADDER,
                               min(need, RADIX_DOMAIN_LIMIT))
        cpeer = _ladder_at_least(PEER_CAPACITY_LADDER,
                                 min(2 * cap // ndev + 256, cap))
        nk = max(len(node.group_exprs), 1)
        nv = max(len(node.agg_exprs), 1)
        width = (nk + nv * (N_ISTATE + N_FSTATE) + 1) * 8
        return 2 * ndev * cpeer * width

    # -------------------------------------------------------------- verdict
    def finish(self, root: p.LogicalPlan, root_rows: Interval,
               root_bytes: Interval) -> PlanEstimate:
        # provable lower bound: HBM-resident base tables + the materialized
        # root result (compiled fusion may never materialize anything else,
        # and a column-aliasing root shares the scan's buffers outright)
        peak_lo = sum(self._scan_lo.values())
        if not _aliases_scan(root):
            peak_lo += root_bytes.lo
        # conservative upper bound: the executor memoizes every node output
        # until the query completes, plus worst-case transient buffers
        peak_hi: Optional[int] = 0
        for n in self.nodes:
            if peak_hi is None:
                break
            if n.out_bytes.hi is None or n.scratch_hi is None:
                peak_hi = None
            else:
                peak_hi += n.out_bytes.hi + n.scratch_hi
        if peak_hi is not None:
            peak_hi = max(peak_hi, peak_lo)
        return PlanEstimate(
            rows=root_rows,
            result_bytes=root_bytes,
            peak_bytes=Interval(peak_lo, peak_hi),
            nodes=list(reversed(self.nodes)),  # root first for display
            rung_proofs=[],
            devices=self.devices,
            scan_bytes_lo=sum(self._scan_lo.values()),
            model_rows=list(self.model_rows),
        )


def _aliases_scan(node: p.LogicalPlan) -> bool:
    """True when the node's output provably *can* share a base table's
    device buffers (identity projections / aliases over a scan): its
    materialization must then not be double-counted on top of the resident
    table in the peak lower bound."""
    from ..planner.expressions import ColumnRef

    while True:
        if isinstance(node, p.TableScan):
            return True
        if isinstance(node, p.SubqueryAlias):
            node = node.input
            continue
        if isinstance(node, p.Projection) and all(
                isinstance(e, ColumnRef) for e in node.exprs):
            node = node.input
            continue
        return False


def _short_label(node: p.LogicalPlan) -> str:
    label = node._label()
    return label if len(label) <= 64 else label[:61] + "..."


def _nested_plans(node: p.LogicalPlan) -> List[p.LogicalPlan]:
    """Subquery plans hanging off one node's expressions (they execute as
    part of this node, so their footprint belongs to the estimate)."""
    import dataclasses

    out: List[p.LogicalPlan] = []
    if not dataclasses.is_dataclass(node):
        return out

    def exprs_of(v):
        if isinstance(v, Expr):
            yield v
        elif isinstance(v, SortKey):
            yield v.expr
        elif isinstance(v, (list, tuple)):
            for item in v:
                yield from exprs_of(item)

    for f in dataclasses.fields(node):
        for e in exprs_of(getattr(node, f.name, None)):
            for x in walk(e):
                if isinstance(x, (ScalarSubqueryExpr, InSubqueryExpr,
                                  ExistsExpr)) and x.plan is not None:
                    out.append(x.plan)
    return out


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------
def estimate_plan(plan: p.LogicalPlan, context=None) -> PlanEstimate:
    """Walk a bound plan bottom-up and return its `PlanEstimate`."""
    target = plan
    if isinstance(target, p.Explain):
        # estimate what EXPLAIN reports on (and, for EXPLAIN ANALYZE, what
        # actually executes) — never the text render, whose trivial output
        # would otherwise force every bound to unbounded
        target = target.input
    est = _Estimator(context=context)
    rows, out_bytes = est.estimate(target)
    verdict = est.finish(target, rows, out_bytes)
    verdict._agg_intermediates = est.agg_intermediates  # for apply()
    return verdict


def device_budget_bytes(config) -> Optional[int]:
    """The device byte budget the rung proofs compare against:
    ``analysis.estimate.device_budget_bytes`` when set, else None (no
    proof — admission uses its own ``serving.admission`` budget)."""
    from ..config import parse_byte_budget

    return parse_byte_budget(config.get("analysis.estimate.device_budget_bytes"))


def collect_rung_proofs(verdict: PlanEstimate, budget: Optional[int]
                        ) -> List[Tuple[p.LogicalPlan, frozenset, int]]:
    """``[(Aggregate node, doomed rungs, intermediate lower bound)]`` for
    compiled intermediates whose lower bound provably cannot fit ``budget``
    (None = no budget, no proofs).  Pure — callers decide whether to act
    (`estimate_and_apply` marks the nodes; EXPLAIN ESTIMATE only reports)."""
    if budget is None:
        return []
    return [(node, _AGG_RUNGS, matrix_lo)
            for node, matrix_lo in getattr(verdict, "_agg_intermediates", [])
            if matrix_lo > budget]


def _tighten(iv: Interval, pred_hi: int) -> Interval:
    """One feedback-tightened interval: the upper bound drops to the
    prediction but NEVER below the provable lower bound, and the lower
    bound is untouched — the two invariants that keep feedback safe."""
    hi = pred_hi if iv.hi is None else min(iv.hi, pred_hi)
    return Interval(iv.lo, max(iv.lo, hi))


def apply_feedback(verdict: PlanEstimate, profile: Optional[dict],
                   config, metrics=None) -> PlanEstimate:
    """Profile-feedback priors (``analysis.estimate.feedback``): tighten a
    verdict's UPPER bounds from the family's observed history — closing the
    loop from PR 5's profiles back into the estimator so packing density
    and rung choice improve under real traffic instead of staying
    static-analysis-only.

    With at least ``feedback.min_obs`` observed executions:

    - ``rows.hi`` / ``result_bytes.hi`` drop to ``margin x`` the maximum
      observed output cardinality / result bytes;
    - ``peak_bytes.hi`` drops to the provable resident floor plus
      ``margin x`` the observed result footprint — the resident scans are
      the floor, the materialized intermediates are what history predicts.

    Bounded, never violating provable floors: lower bounds are copied
    untouched and an upper bound never drops below its lower bound, so the
    admission shed (lo-gated) and the rung proofs (lo-gated) are provably
    unaffected.  The returned estimate is a NEW object — the family's
    memoized static verdict stays pristine so feedback re-applies with
    fresher history on every later member."""
    if profile is None or not config.get("analysis.estimate.feedback", True):
        return verdict
    min_obs = max(1, int(config.get("analysis.estimate.feedback.min_obs", 2)))
    margin = max(1.0, float(
        config.get("analysis.estimate.feedback.margin", 2.0)))
    obs_rows = profile.get("rows") or []
    obs_bytes = profile.get("result_bytes") or []
    rows = verdict.rows
    result_bytes = verdict.result_bytes
    peak = verdict.peak_bytes
    changed = False
    if len(obs_rows) >= min_obs:
        tightened = _tighten(rows, int(margin * max(obs_rows)))
        changed = changed or tightened != rows
        rows = tightened
    if len(obs_bytes) >= min_obs:
        pred_result = int(margin * max(obs_bytes))
        tightened = _tighten(result_bytes, pred_result)
        changed = changed or tightened != result_bytes
        result_bytes = tightened
        tightened = _tighten(peak, peak.lo + pred_result)
        changed = changed or tightened != peak
        peak = tightened
    if not changed:
        return verdict
    if metrics is not None:
        metrics.inc("analysis.estimate.feedback")
    import dataclasses

    return dataclasses.replace(verdict, rows=rows,
                               result_bytes=result_bytes,
                               peak_bytes=peak, feedback=True)


def estimate_and_apply(plan: p.LogicalPlan, context) -> PlanEstimate:
    """Bind-time entry (Context._get_ral): estimate, record the
    ``analysis.estimate.*`` metrics, attach the verdict to the plan
    (``_dsql_estimate``) for the admission gate and result cache, and
    pre-skip compiled aggregate rungs whose intermediate-buffer lower
    bound provably cannot fit the device budget (``_dsql_skip_rungs`` —
    the ladder records ``analysis.rung_skip.*`` with no breaker charge)."""
    verdict = estimate_plan(plan, context=context)
    metrics = getattr(context, "metrics", None)
    if metrics is not None:
        metrics.inc("analysis.estimate.runs")
        metrics.observe("analysis.estimate.bytes_lo", verdict.peak_bytes.lo)
        if verdict.peak_bytes.hi is not None:
            metrics.observe("analysis.estimate.bytes_hi",
                            verdict.peak_bytes.hi)
        if verdict.rows.hi is not None:
            metrics.observe("analysis.estimate.rows_hi", verdict.rows.hi)
    for node, rungs, matrix_lo in collect_rung_proofs(
            verdict, device_budget_bytes(context.config)):
        existing = getattr(node, "_dsql_skip_rungs", frozenset())
        node._dsql_skip_rungs = frozenset(existing) | rungs
        verdict.rung_proofs.append((node, rungs, matrix_lo))
        if metrics is not None:
            metrics.inc("analysis.estimate.rung_proof")
    plan._dsql_estimate = verdict
    return verdict


# ---------------------------------------------------------------------------
# provable predicate-interval algebra (semantic reuse / subsumption)
# ---------------------------------------------------------------------------
#: comparator ops a single ParamRef predicate maps onto a value interval.
#: ``eq`` included: an equality slot subsumes only the identical value.
COMPARATOR_OPS = frozenset({"lt", "le", "gt", "ge", "eq"})

#: mirror op when the comparison is written ``literal OP column`` —
#: normalizing to column-on-the-left so one interval table covers both
MIRRORED_OPS = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}


@dataclass(frozen=True)
class PredInterval:
    """The value set ``{x : x OP v}`` of one comparator predicate as an
    interval over the column domain.  ``None`` bound = unbounded on that
    side; ``*_open`` marks a strict (exclusive) endpoint.  This is the
    *predicate* lattice the subsumption check reasons in — distinct from
    the cardinality/byte `Interval` above, which is always closed."""

    lo: Optional[float]
    hi: Optional[float]
    lo_open: bool = False
    hi_open: bool = False


def pred_interval(op: str, value) -> Optional[PredInterval]:
    """The interval of column values ``column OP value`` selects, or None
    when ``op`` is not a plain comparator (the slot then declines
    subsumption entirely)."""
    if op not in COMPARATOR_OPS:
        return None
    # keep the native scalar: Python's int/float comparisons are exact
    # (coercing int64 through float would lose precision past 2**53)
    v = int(value) if isinstance(value, bool) else value
    if op == "lt":
        return PredInterval(None, v, hi_open=True)
    if op == "le":
        return PredInterval(None, v)
    if op == "gt":
        return PredInterval(v, None, lo_open=True)
    if op == "ge":
        return PredInterval(v, None)
    return PredInterval(v, v)  # eq


def _bound_contains(outer_v, outer_open: bool, inner_v, inner_open: bool,
                    side: str, float_domain: bool) -> bool:
    """Does the outer interval's ``side`` bound admit the inner's?  PROOF
    ONLY: returns False whenever the decision rests on exact endpoint
    equality in a float domain — host-side equality of the two parameter
    values does not prove the device-cast (e.g. float64 -> float32 column
    dtype) boundary semantics coincide, so equal float endpoints decline
    rather than guess."""
    if outer_v is None:
        return True      # outer unbounded on this side: anything fits
    if inner_v is None:
        return False     # inner unbounded where outer is not
    if side == "lo":
        if outer_v < inner_v:
            return True
        if outer_v > inner_v:
            return False
    else:
        if outer_v > inner_v:
            return True
        if outer_v < inner_v:
            return False
    # endpoints exactly equal on the host: the decision IS the boundary
    if float_domain:
        return False
    return (not outer_open) or inner_open


def interval_contains(outer: PredInterval, inner: PredInterval,
                      float_domain: bool = False) -> bool:
    """PROVABLE containment ``inner ⊆ outer`` — the subsumption oracle.
    True only when every row the inner predicate selects is provably a row
    the outer predicate selected; never heuristic.  ``float_domain`` marks
    a float column or parameter dtype: any containment that would be
    decided by endpoint *equality* then declines (see `_bound_contains`)."""
    return (_bound_contains(outer.lo, outer.lo_open, inner.lo,
                            inner.lo_open, "lo", float_domain)
            and _bound_contains(outer.hi, outer.hi_open, inner.hi,
                                inner.hi_open, "hi", float_domain))


def param_slot_contains(op: str, cached_value, new_value,
                        float_domain: bool = False) -> bool:
    """One family parameter slot's containment verdict: does the cached
    execution's ``column OP cached_value`` provably cover the incoming
    ``column OP new_value``?  Both predicates share the op (same family),
    so this reduces to interval containment of the two value sets."""
    outer = pred_interval(op, cached_value)
    inner = pred_interval(op, new_value)
    if outer is None or inner is None:
        return False
    return interval_contains(outer, inner, float_domain=float_domain)
