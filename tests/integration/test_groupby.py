"""Groupby/aggregation tests (parity: reference test_groupby.py)."""
import numpy as np
import pandas as pd
import pytest

from tests.utils import assert_eq


def test_group_by(c, df):
    result = c.sql("SELECT a, SUM(b) AS s FROM df GROUP BY a").compute()
    expected = df.groupby("a").b.sum().reset_index().rename(columns={"b": "s"})
    assert_eq(result, expected, check_dtype=False, sort_results=True)

def test_group_by_all_aggs(c, df):
    result = c.sql(
        """SELECT a, SUM(b) AS "sum", AVG(b) AS "avg", MIN(b) AS "min",
                  MAX(b) AS "max", COUNT(b) AS "count",
                  STDDEV(b) AS "std", VAR_SAMP(b) AS "var"
           FROM df GROUP BY a"""
    ).compute()
    g = df.groupby("a").b
    expected = pd.DataFrame({
        "a": sorted(df.a.unique()),
        "sum": g.sum().values, "avg": g.mean().values, "min": g.min().values,
        "max": g.max().values, "count": g.count().values,
        "std": g.std().values, "var": g.var().values,
    })
    assert_eq(result, expected, check_dtype=False, sort_results=True)

def test_group_by_filtered(c, user_table_1):
    result = c.sql(
        """SELECT user_id, SUM(b) FILTER (WHERE b = 3) AS "s1", SUM(b) AS "s2"
           FROM user_table_1 GROUP BY user_id"""
    ).compute()
    expected = pd.DataFrame({
        "user_id": [1, 2, 3],
        "s1": [3.0, 3.0, 3.0],
        "s2": [3, 4, 3],
    })
    assert_eq(result, expected, check_dtype=False, sort_results=True)

def test_global_aggregation(c, df):
    result = c.sql("SELECT SUM(a) AS s, COUNT(*) AS c, AVG(b) AS m FROM df").compute()
    assert result["s"][0] == df.a.sum()
    assert result["c"][0] == len(df)
    assert abs(result["m"][0] - df.b.mean()) < 1e-9

def test_count_distinct(c, user_table_1):
    result = c.sql("SELECT COUNT(DISTINCT b) AS cd FROM user_table_1").compute()
    assert result["cd"][0] == 2

def test_group_by_nulls(c):
    df = pd.DataFrame({"a": [1, 1, None, None, 2], "b": [1, 2, 3, 4, 5]})
    c.create_table("nulls_df", df)
    result = c.sql("SELECT a, SUM(b) AS s FROM nulls_df GROUP BY a").compute()
    # NULL forms its own group (dropna=False semantics)
    assert len(result) == 3
    null_row = result[pd.isna(result["a"])]
    assert null_row["s"].iloc[0] == 7

def test_sum_of_nulls_is_null(c):
    df = pd.DataFrame({"g": [1, 1, 2], "v": [None, None, 3.0]})
    c.create_table("sumnull", df)
    result = c.sql("SELECT g, SUM(v) AS s FROM sumnull GROUP BY g").compute()
    result = result.sort_values("g").reset_index(drop=True)
    assert pd.isna(result["s"][0])
    assert result["s"][1] == 3.0

def test_having(c, df):
    result = c.sql(
        "SELECT a, COUNT(*) AS c FROM df GROUP BY a HAVING COUNT(*) > 150"
    ).compute()
    expected = df.groupby("a").size().reset_index(name="c")
    expected = expected[expected["c"] > 150]
    assert_eq(result, expected, check_dtype=False, sort_results=True)

def test_group_by_case(c, df):
    result = c.sql(
        "SELECT CASE WHEN a = 1 THEN 'one' ELSE 'other' END AS k, COUNT(*) AS c FROM df GROUP BY CASE WHEN a = 1 THEN 'one' ELSE 'other' END"
    ).compute()
    assert set(result["k"]) == {"one", "other"}

def test_aggregation_on_expression(c, df):
    result = c.sql("SELECT a + 1 AS k, SUM(b * 2) AS s FROM df GROUP BY a + 1").compute()
    expected = df.assign(k=df.a + 1, s=df.b * 2).groupby("k").s.sum().reset_index()
    assert_eq(result, expected, check_dtype=False, sort_results=True)

def test_min_max_string(c, user_table_1):
    df = pd.DataFrame({"g": [1, 1, 2], "s": ["b", "a", "c"]})
    c.create_table("strs", df)
    result = c.sql("SELECT g, MIN(s) AS lo, MAX(s) AS hi FROM strs GROUP BY g").compute()
    result = result.sort_values("g").reset_index(drop=True)
    assert list(result["lo"]) == ["a", "c"]
    assert list(result["hi"]) == ["b", "c"]

def test_bool_aggs(c):
    df = pd.DataFrame({"g": [1, 1, 2, 2], "b": [True, False, True, True]})
    c.create_table("bools", df)
    result = c.sql(
        "SELECT g, EVERY(b) AS e, BOOL_OR(b) AS o FROM bools GROUP BY g"
    ).compute().sort_values("g").reset_index(drop=True)
    assert list(result["e"]) == [False, True]
    assert list(result["o"]) == [True, True]

def test_stddev_matches_pandas(c, df):
    result = c.sql(
        "SELECT STDDEV_POP(b) AS sp, STDDEV_SAMP(b) AS ss FROM df"
    ).compute()
    assert abs(result["sp"][0] - df.b.std(ddof=0)) < 1e-9
    assert abs(result["ss"][0] - df.b.std(ddof=1)) < 1e-9

def test_group_by_distinct_agg(c, user_table_1):
    result = c.sql(
        "SELECT user_id, COUNT(DISTINCT b) AS cd, SUM(DISTINCT b) AS sd FROM user_table_1 GROUP BY user_id"
    ).compute().sort_values("user_id").reset_index(drop=True)
    expected = user_table_1.groupby("user_id").b.agg([
        ("cd", "nunique"), ("sd", lambda x: x.drop_duplicates().sum())]).reset_index()
    assert_eq(result, expected, check_dtype=False, check_names=False)

def test_distinct_plain(c, df):
    result = c.sql("SELECT DISTINCT a FROM df").compute()
    assert sorted(result["a"]) == [1.0, 2.0, 3.0]

def test_percentile_aggregates(c, df):
    result = c.sql(
        """SELECT a, MEDIAN(b) AS med, APPROX_PERCENTILE(b, 0.9) AS p90,
                  PERCENTILE_CONT(0.25) WITHIN GROUP (ORDER BY b) AS q1
           FROM df GROUP BY a"""
    ).compute().sort_values("a").reset_index(drop=True)
    g = df.groupby("a").b
    np.testing.assert_allclose(result["med"], g.quantile(0.5).values, rtol=1e-9)
    np.testing.assert_allclose(result["p90"], g.quantile(0.9).values, rtol=1e-9)
    np.testing.assert_allclose(result["q1"], g.quantile(0.25).values, rtol=1e-9)

def test_median_with_nulls(c):
    df = pd.DataFrame({"g": [1, 1, 1, 2], "v": [1.0, None, 3.0, 5.0]})
    c.create_table("mednull", df)
    result = c.sql("SELECT g, MEDIAN(v) AS m FROM mednull GROUP BY g").compute()
    result = result.sort_values("g").reset_index(drop=True)
    assert list(result["m"]) == [2.0, 5.0]

def test_coalesce_in_compiled_aggregate(c):
    # regression: the compiled pipeline's COALESCE must treat an always-valid
    # fallback as valid (rows with NULL inputs still contribute)
    df = pd.DataFrame({"g": ["x", "x", "y"], "v": [2.0, None, None]})
    c.create_table("coag", df)
    result = c.sql(
        "SELECT g, AVG(COALESCE(v * v, 0)) AS m, COUNT(*) AS n FROM coag GROUP BY g"
    ).compute().sort_values("g").reset_index(drop=True)
    assert list(result["n"]) == [2, 1]
    np.testing.assert_allclose(result["m"], [2.0, 0.0])


def test_global_aggregate_over_empty_input(c):
    """SQL: a global aggregate with zero qualifying rows yields ONE row —
    COUNT(*)=0 and NULL for value aggregates (regression: the compiled
    pipeline's group compaction dropped the row entirely)."""
    import pandas as pd

    for opts in ({"sql.compile": True}, {"sql.compile": False}):
        df = c.sql(
            "SELECT COUNT(*) AS n, SUM(a) AS s, MIN(a) AS mn FROM df_simple "
            "WHERE a > 1e9", config_options=opts).compute()
        assert len(df) == 1
        assert int(df["n"][0]) == 0
        assert pd.isna(df["s"][0]) and pd.isna(df["mn"][0])
        # grouped aggregates over empty input correctly yield zero rows
        g = c.sql("SELECT a, COUNT(*) AS n FROM df_simple WHERE a > 1e9 "
                  "GROUP BY a", config_options=opts).compute()
        assert len(g) == 0


def test_narrow_int_group_key_span_overflow(c):
    """Regression (r3 review): int8/int16 group keys whose span exceeds the
    dtype's positive range must widen before the radix offset subtraction —
    otherwise rows silently merge into the wrong group."""
    rng = np.random.RandomState(11)
    vals = rng.choice(np.array([-100, -3, 0, 7, 100], dtype=np.int8), 500)
    df = pd.DataFrame({"g": vals, "v": rng.rand(500)})
    c.create_table("narrowkey", df)
    got = c.sql("SELECT g, COUNT(*) AS n, SUM(v) AS s FROM narrowkey GROUP BY g"
                ).compute().sort_values("g").reset_index(drop=True)
    ref = (df.groupby("g", as_index=False)
             .agg(n=("v", "size"), s=("v", "sum"))
             .sort_values("g").reset_index(drop=True))
    assert list(got["g"].astype(np.int64)) == list(ref["g"].astype(np.int64))
    assert list(got["n"].astype(np.int64)) == list(ref["n"].astype(np.int64))
    np.testing.assert_allclose(got["s"], ref["s"], rtol=1e-6)


def test_narrow_int_join_key_span_overflow(c):
    """Regression (r3 review): int16 join keys spanning past the dtype's
    positive range must widen before `key - rmin` in the compiled LUT probe."""
    build = pd.DataFrame({"k": np.array([-30000, -5, 0, 9, 30000], dtype=np.int16),
                          "name": ["a", "b", "c", "d", "e"]})
    rng = np.random.RandomState(12)
    probe = pd.DataFrame({"k": rng.choice(
        np.array([-30000, 0, 30000], dtype=np.int16), 300),
        "v": rng.rand(300)})
    c.create_table("nj_dim", build)
    c.create_table("nj_fact", probe)
    got = c.sql(
        "SELECT d.name, COUNT(*) AS n FROM nj_fact f, nj_dim d "
        "WHERE f.k = d.k GROUP BY d.name"
    ).compute().sort_values("name").reset_index(drop=True)
    ref = (probe.merge(build, on="k").groupby("name", as_index=False)
           .agg(n=("v", "size")).sort_values("name").reset_index(drop=True))
    assert list(got["name"]) == list(ref["name"])
    assert list(got["n"].astype(np.int64)) == list(ref["n"].astype(np.int64))
