"""MXU-native segment reductions for the hot aggregation path.

Scatter-add (`jax.ops.segment_sum`) serializes on the TPU's scatter unit —
and emulated 64-bit scatter is several times slower again.  The MXU-native
formulation is a one-hot matmul: `onehot(gid).T @ contribs`.  Two
implementations:

- `segsum_scan_blocked` — the production path.  Rows are processed in
  fixed-size blocks under `lax.scan`; each step builds the block's one-hot
  in on-chip memory, runs ONE [b, domain] x [b, K] matmul on the MXU for
  ALL K contribution columns at once, and accumulates the per-block partial
  into a float64 carry.  The per-block f64 accumulation bounds the f32
  matmul-accumulation error to the block (measured: ~1e-7..1e-6 max
  relative on 6M uniform rows vs exact f64 — see
  tests/unit/test_pallas_kernels.py::test_blocked_accuracy_bound, asserted
  at 5e-6); 0/1 count columns are EXACT (integer-valued f32 partials below
  2^24 per block, combined exactly in f64).  For float64 inputs the caller
  splits hi/lo (`split_hi_lo`) so representation error is ~2^-48.
- `segsum_pallas` — the same math as a hand-written pallas kernel (one-hot
  built only in VMEM).  Kept as an explicit opt-in probe; remote-compile
  support for pallas on this chip is gated by `pallas_available()`.

`segsum_onehot_jnp` (single unblocked matmul) remains for reference and
verification; its f32 accumulation error grows with rows-per-segment, which
is why the blocked scan is the production path.

See /opt/skills/guides/pallas_guide.md for the pallas programming model.
"""
from __future__ import annotations

import logging
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)

#: error bound (max relative, float sums) the blocked matmul path is tested
#: to meet on-device; `choose_segsum_impl` only auto-selects modes meeting it
MATMUL_FLOAT_REL_ERR_BOUND = 5e-6

_DEFAULT_BLOCK = 32768


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def split_hi_lo(x64: jnp.ndarray):
    """Exact two-float32 decomposition of a float64 array (48-bit mantissa)."""
    hi = x64.astype(jnp.float32)
    lo = (x64 - hi.astype(jnp.float64)).astype(jnp.float32)
    return hi, lo


def segsum_onehot_jnp(gid: jnp.ndarray, contribs: jnp.ndarray, domain: int) -> jnp.ndarray:
    """[n] ids + [n, k] contributions -> [domain, k] sums via one one-hot matmul."""
    onehot = jax.nn.one_hot(gid, domain, dtype=contribs.dtype)
    return onehot.T @ contribs


def segsum_scan_blocked(gid: jnp.ndarray, cols, domain: int,
                        block: int = _DEFAULT_BLOCK) -> jnp.ndarray:
    """Blocked one-hot MXU segment sum with float64 partial accumulation.

    gid: [n] integer ids in [0, domain); cols: list of [n] float32 arrays
    (pre-masked: non-selected rows must carry 0).  Returns [domain, K]
    float64.  Works under jit tracing; block count is static.
    """
    k = len(cols)
    n = gid.shape[0]
    b = min(block, max(_round_up(n, 8), 8))
    npad = max(_round_up(n, b), b)
    nb = npad // b
    pad = npad - n
    gid_p = jnp.pad(gid.astype(jnp.int32), (0, pad))
    stack = jnp.stack([c.astype(jnp.float32) for c in cols], axis=1)  # [n, k]
    if pad:
        # padded rows: gid 0 with zero contributions — add nothing
        stack = jnp.pad(stack, ((0, pad), (0, 0)))
    gid_b = gid_p.reshape(nb, b)
    stack_b = stack.reshape(nb, b, k)

    def step(carry, xs):
        g, c = xs
        onehot = jax.nn.one_hot(g, domain, dtype=jnp.float32)  # [b, domain]
        part = jax.lax.dot_general(
            onehot, c, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # [domain, k]
        return carry + part.astype(jnp.float64), None

    init = jnp.zeros((domain, k), dtype=jnp.float64)
    out, _ = jax.lax.scan(step, init, (gid_b, stack_b))
    return out


def segsum_pallas(gid: jnp.ndarray, contribs: jnp.ndarray, domain: int,
                  block_rows: int = 2048, interpret: bool = False) -> jnp.ndarray:
    """Pallas segment-sum: one-hot built per block in VMEM, MXU accumulate.

    gid: [n] int32 in [0, domain); contribs: [n, k] float32 (pre-masked).
    Returns [domain, k] float32 (f32 accumulation across the whole input —
    use segsum_scan_blocked when f64-bounded accuracy is required).
    """
    from jax.experimental import pallas as pl

    n, k = contribs.shape
    d_pad = max(_round_up(domain, 128), 128)
    k_pad = max(_round_up(k, 128), 128)
    # keep the VMEM-resident one-hot block within a ~4MB budget
    budget_rows = max((4 << 20) // (d_pad * 4), 8)
    b = max(min(block_rows, _round_up(budget_rows, 8) - 7), 8)
    n_pad = max(_round_up(n, b), b)

    gid_p = jnp.zeros((n_pad,), dtype=jnp.int32).at[:n].set(gid.astype(jnp.int32))
    # padded rows carry zero contributions, so their gid (0) adds nothing
    c_p = jnp.zeros((n_pad, k_pad), dtype=jnp.float32).at[:n, :k].set(
        contribs.astype(jnp.float32))

    def kernel(gid_ref, c_ref, out_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _init():
            out_ref[:] = jnp.zeros_like(out_ref)

        ids = gid_ref[:]  # [b]
        onehot = (ids[:, None] == jax.lax.broadcasted_iota(jnp.int32, (1, d_pad), 1)
                  ).astype(jnp.float32)  # [b, d_pad], lives only in VMEM
        out_ref[:] += jax.lax.dot_general(
            onehot, c_ref[:],
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    out = pl.pallas_call(
        kernel,
        grid=(n_pad // b,),
        in_specs=[
            pl.BlockSpec((b,), lambda i: (i,)),
            pl.BlockSpec((b, k_pad), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((d_pad, k_pad), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((d_pad, k_pad), jnp.float32),
        interpret=interpret,
    )(gid_p, c_p)
    return out[:domain, :k]


def segsum_double_float(gid, contribs64, domain: int, use_pallas: bool = False,
                        interpret: bool = False) -> jnp.ndarray:
    """float64-in/out segment sum via hi/lo float32 columns.

    Kept for the explicit 'pallas' opt-in mode and verification.  hi/lo
    removes the f32 *representation* error (~2^-48); the remaining error is
    whole-input f32 accumulation (measured ~2e-5 max relative at 6M rows,
    domain 16 — NOT the blocked bound; prefer segsum_scan_blocked).
    """
    x = contribs64.astype(jnp.float64)
    hi, lo = split_hi_lo(x)
    n, k = x.shape
    stacked = jnp.concatenate([hi, lo], axis=1)  # [n, 2k]
    if use_pallas:
        out = segsum_pallas(gid, stacked, domain, interpret=interpret)
    else:
        out = segsum_onehot_jnp(gid, stacked, domain)
    return out[:, :k].astype(jnp.float64) + out[:, k:].astype(jnp.float64)


_PALLAS_OK: Optional[bool] = None


def pallas_available() -> bool:
    """Probe (once) whether a pallas kernel compiles+runs on this backend.

    The axon remote-compile path has been observed to reject pallas lowering
    (HTTP 500); this keeps 'pallas' mode from taking down a query."""
    global _PALLAS_OK
    if _PALLAS_OK is None:
        try:
            out = segsum_pallas(jnp.zeros(16, jnp.int32),
                                jnp.ones((16, 1), jnp.float32), 4)
            _PALLAS_OK = bool(abs(float(out[0, 0]) - 16.0) < 1e-6)
        except Exception as e:  # dsql: allow-broad-except — any lowering failure fences it
            logger.warning("pallas segsum unavailable on this backend: %s", e)
            _PALLAS_OK = False
    return _PALLAS_OK


def choose_segsum_impl(config, domain: int) -> str:
    """'scatter' | 'matmul' | 'pallas' based on config + platform + domain.

    auto: the blocked MXU matmul ('matmul') where it meets
    MATMUL_FLOAT_REL_ERR_BOUND and the one-hot FLOPs stay cheap (small
    domains); exact scatter otherwise.  Counts and int sums are exact in
    every mode (matmul counts are integer-valued f32 partials < 2^24 /
    block combined in f64; int sums always use int64 scatter)."""
    mode = str(config.get("sql.compile.segsum", "auto"))
    if mode == "pallas":
        return "pallas" if pallas_available() else "matmul"
    if mode in ("scatter", "matmul"):
        return mode
    if mode != "auto":
        raise ValueError(
            f"sql.compile.segsum must be auto/scatter/matmul/pallas, got {mode!r}")
    platform = jax.devices()[0].platform
    if platform in ("tpu", "axon") and domain <= 2048:
        return "matmul"
    return "scatter"
