"""Result-equality helper (parity: reference tests/utils.py:15 assert_eq
wrapping dask's frame comparison; convert_nullable_columns tests/utils.py:21)."""
from __future__ import annotations

import numpy as np
import pandas as pd


def _normalize(df):
    if isinstance(df, pd.Series):
        df = df.to_frame()
    df = df.reset_index(drop=True)
    out = {}
    for i, col in enumerate(df.columns):
        s = df[col] if df.columns.get_loc(col) == i or not df.columns.duplicated().any() else df.iloc[:, i]
        s = df.iloc[:, i]
        if str(s.dtype) in ("string", "str"):
            s = s.astype(object)
        if s.dtype == object:
            s = s.where(pd.notna(s), None)
        out[i] = s
    return df, out


def assert_eq(got, expected, check_dtype: bool = True, check_index: bool = False,
              check_names: bool = True, sort_results: bool = False, **kwargs):
    got = got.compute() if hasattr(got, "compute") else got
    expected = expected.compute() if hasattr(expected, "compute") else expected
    if isinstance(got, pd.Series):
        got = got.to_frame()
    if isinstance(expected, pd.Series):
        expected = expected.to_frame()
    assert list(map(str, got.columns)) == list(map(str, expected.columns)) or not check_names, \
        f"columns differ: {list(got.columns)} vs {list(expected.columns)}"
    assert len(got) == len(expected), f"row counts differ: {len(got)} vs {len(expected)}"
    if sort_results and len(got.columns):
        got = got.sort_values(by=list(got.columns), kind="stable").reset_index(drop=True)
        expected = expected.sort_values(by=list(expected.columns), kind="stable").reset_index(drop=True)
    got = got.reset_index(drop=True)
    expected = expected.reset_index(drop=True)
    for i in range(len(got.columns)):
        g = got.iloc[:, i]
        e = expected.iloc[:, i]
        _assert_series_eq(g, e, check_dtype, str(got.columns[i]))


def _assert_series_eq(g: pd.Series, e: pd.Series, check_dtype: bool, name: str):
    gk = _kind(g)
    ek = _kind(e)
    if check_dtype:
        assert gk == ek, f"column {name}: dtype kind {gk} != {ek} ({g.dtype} vs {e.dtype})"
    gn = pd.isna(g).to_numpy()
    en = pd.isna(e).to_numpy()
    assert (gn == en).all(), f"column {name}: NULL positions differ"
    gv = g[~gn]
    ev = e[~en]
    if gk == "f" or ek == "f":
        np.testing.assert_allclose(gv.astype(float).to_numpy(), ev.astype(float).to_numpy(),
                                   rtol=1e-9, atol=1e-12, err_msg=f"column {name}")
    elif gk == "M":
        got_ns = pd.to_datetime(gv).astype("datetime64[ns]").to_numpy()
        exp_ns = pd.to_datetime(ev).astype("datetime64[ns]").to_numpy()
        assert (got_ns == exp_ns).all(), f"column {name}: datetime values differ"
    elif gk == "i" and ek == "f" or gk == "f" and ek == "i":
        np.testing.assert_allclose(gv.astype(float).to_numpy(), ev.astype(float).to_numpy(),
                                   err_msg=f"column {name}")
    else:
        assert list(gv.astype(str)) == list(ev.astype(str)), \
            f"column {name}: values differ\n{list(gv)[:10]}\nvs\n{list(ev)[:10]}"


def _kind(s: pd.Series) -> str:
    dt = str(s.dtype).lower()
    if "int" in dt:
        return "i"
    if "float" in dt or "decimal" in dt:
        return "f"
    if "bool" in dt:
        return "b"
    if "datetime" in dt:
        return "M"
    if "timedelta" in dt:
        return "m"
    return "O"


def convert_nullable_columns(df: pd.DataFrame) -> pd.DataFrame:
    """Normalize pandas nullable extension dtypes to plain numpy dtypes."""
    out = df.copy()
    for col in out.columns:
        dt = str(out[col].dtype)
        if dt in ("Int64", "Int32", "Float64", "boolean"):
            if out[col].isna().any():
                out[col] = out[col].astype("float64")
            else:
                out[col] = out[col].astype(dt.lower().replace("boolean", "bool"))
    return out
