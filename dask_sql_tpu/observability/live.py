"""In-flight query table: the live state of every admitted query.

Everything before this module was post-hoc: `EXPLAIN ANALYZE` traces,
`SHOW PROFILES` rollups and the slow-query log all exist only *after* a
query finishes.  The reference engine inherits a live dashboard from
Dask/distributed — an operator can see every in-flight task in real time —
and a serving engine with packed co-scheduling (serving/scheduler.py),
family batching (families/batcher.py) and N-launch streams (streaming/)
needs the same: "what is the engine doing right now and why".

`QueryRegistry` is that table.  One `LiveQuery` per admitted query, updated
in place by the layers that know each fact:

- the server front-end / TpuFrame registers the entry (qid, sql, tenant,
  class, ticket, trace);
- `observability.stage(...)` stamps the current lifecycle stage;
- the degradation ladder stamps the rung that answered;
- the family batcher stamps the batch role (leader/member) and size;
- the streaming drive loop stamps partition progress (done/total, current
  chunk rows, rows done);
- the scheduler's `QueryCost` rides the ticket, so reserved bytes and the
  deadline remaining read straight off it.

Surfaced as ``SHOW QUERIES`` (native + Python parser paths) and
``GET /v1/queries``; ``CANCEL QUERY '<qid>'`` / ``POST
/v1/queries/{qid}/cancel`` resolve the entry's `QueryTicket` and cancel it
cooperatively (the executor's per-node checkpoints and the streaming
loop's between-launch checkpoints do the actual stopping).

Thread model: one writer thread per query (the executing worker) plus
concurrent readers (SHOW QUERIES, /v1/queries polls).  Field updates are
single-attribute stores of scalars — atomic under the GIL — and the
registry's dict is guarded by its own lock, so readers always see a
consistent table even mid-update.
"""
from __future__ import annotations

import contextlib
import contextvars
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

#: terminal states; everything else is live
_TERMINAL = ("done", "failed", "cancelled")


class LiveQuery:
    """Mutable live-state record of one admitted query."""

    __slots__ = (
        "qid", "sql", "tenant", "priority_class", "ticket", "trace",
        "submitted_wall", "submitted_perf", "state", "stage", "rung",
        "family", "fingerprint", "batch_role", "batch_size",
        "stream_partitions_total", "stream_partitions_done",
        "stream_rows_total", "stream_rows_done", "stream_chunk_rows",
        "measured_bytes", "error_code", "finished_perf",
    )

    def __init__(self, qid: str, sql: Optional[str] = None, ticket=None,
                 trace=None, tenant: str = "",
                 priority_class: str = "interactive"):
        self.qid = qid
        self.sql = (sql or "").strip()[:500]
        self.tenant = tenant
        self.priority_class = priority_class
        self.ticket = ticket
        self.trace = trace
        self.submitted_wall = time.time()
        self.submitted_perf = time.perf_counter()
        self.state = "queued"
        self.stage: Optional[str] = None
        self.rung: Optional[str] = None
        self.family: Optional[str] = None
        self.fingerprint: Optional[str] = None
        self.batch_role: Optional[str] = None  # "leader" / "member"
        self.batch_size: Optional[int] = None
        self.stream_partitions_total: Optional[int] = None
        self.stream_partitions_done: Optional[int] = None
        self.stream_rows_total: Optional[int] = None
        self.stream_rows_done: Optional[int] = None
        self.stream_chunk_rows: Optional[int] = None
        self.measured_bytes: Optional[int] = None
        self.error_code: Optional[str] = None
        self.finished_perf: Optional[float] = None

    # ------------------------------------------------------------- derived
    def reserved_bytes(self) -> Optional[int]:
        """What the packing scheduler reserved for this query (the cost's
        provable floor — per-chunk for streamed plans), None when it
        submitted without a cost hint."""
        cost = getattr(self.ticket, "cost", None)
        if cost is None:
            return None
        try:
            return int(cost.reserve_bytes())
        except (TypeError, ValueError, AttributeError):
            return None

    def deadline_remaining_ms(self) -> Optional[int]:
        remaining = None
        if self.ticket is not None:
            remaining = self.ticket.remaining_s()
        return None if remaining is None else int(remaining * 1000)

    def elapsed_ms(self) -> int:
        end = self.finished_perf if self.finished_perf is not None \
            else time.perf_counter()
        return int((end - self.submitted_perf) * 1000)

    def cancelled_flag(self) -> bool:
        return bool(self.ticket is not None and self.ticket.cancelled)

    # -------------------------------------------------------------- export
    def as_dict(self) -> Dict[str, Any]:
        """JSON-ready snapshot for ``GET /v1/queries``."""
        out: Dict[str, Any] = {
            "qid": self.qid,
            "state": self.state,
            "class": self.priority_class,
            "tenant": self.tenant or None,
            "stage": self.stage,
            "rung": self.rung,
            "family": self.family,
            "fingerprint": self.fingerprint,
            "batchRole": self.batch_role,
            "batchSize": self.batch_size,
            "reservedBytes": self.reserved_bytes(),
            "measuredBytes": self.measured_bytes,
            "deadlineRemainingMs": self.deadline_remaining_ms(),
            "elapsedMs": self.elapsed_ms(),
            "cancelRequested": self.cancelled_flag(),
            "errorCode": self.error_code,
            "submitted": self.submitted_wall,
            "sql": self.sql,
        }
        if self.stream_partitions_total is not None:
            out["stream"] = {
                "partitionsDone": self.stream_partitions_done or 0,
                "partitionsTotal": self.stream_partitions_total,
                "rowsDone": self.stream_rows_done or 0,
                "rowsTotal": self.stream_rows_total,
                "chunkRows": self.stream_chunk_rows,
            }
        return out

    def fields(self) -> List[Tuple[str, str]]:
        """The populated (field, value) pairs — one ``SHOW QUERIES`` row
        each, in a stable, operator-meaningful order."""
        out: List[Tuple[str, str]] = [
            ("state", self.state),
            ("class", self.priority_class),
        ]
        if self.tenant:
            out.append(("tenant", self.tenant))
        if self.stage:
            out.append(("stage", self.stage))
        if self.rung:
            out.append(("rung", self.rung))
        if self.family:
            out.append(("family", self.family))
        if self.batch_role:
            out.append(("batch", f"{self.batch_role} x{self.batch_size}"
                        if self.batch_size else self.batch_role))
        if self.stream_partitions_total is not None:
            out.append(("stream.partitions",
                        f"{self.stream_partitions_done or 0}"
                        f"/{self.stream_partitions_total}"))
            if self.stream_rows_total is not None:
                out.append(("stream.rows",
                            f"{self.stream_rows_done or 0}"
                            f"/{self.stream_rows_total}"))
            if self.stream_chunk_rows is not None:
                out.append(("stream.chunk_rows",
                            str(self.stream_chunk_rows)))
        reserved = self.reserved_bytes()
        if reserved is not None:
            out.append(("reserved_bytes", str(reserved)))
        if self.measured_bytes is not None:
            out.append(("measured_bytes", str(self.measured_bytes)))
        deadline = self.deadline_remaining_ms()
        if deadline is not None:
            out.append(("deadline_remaining_ms", str(deadline)))
        out.append(("elapsed_ms", str(self.elapsed_ms())))
        if self.cancelled_flag():
            out.append(("cancel_requested", "true"))
        if self.error_code:
            out.append(("error", self.error_code))
        if self.sql:
            out.append(("sql", self.sql))
        return out


class QueryRegistry:
    """qid -> LiveQuery table: every in-flight query plus a bounded tail of
    recently finished ones (so a just-completed query is still inspectable
    in the poll that observes its completion)."""

    def __init__(self, keep_finished: int = 64):
        self.keep_finished = max(0, int(keep_finished))
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, LiveQuery]" = OrderedDict()
        self._finished: List[str] = []

    # ------------------------------------------------------------ lifecycle
    def begin(self, qid: str, sql: Optional[str] = None, ticket=None,
              trace=None, tenant: str = "",
              priority_class: str = "interactive") -> LiveQuery:
        entry = LiveQuery(qid, sql=sql, ticket=ticket, trace=trace,
                          tenant=tenant, priority_class=priority_class)
        with self._lock:
            self._entries[qid] = entry
        return entry

    def start(self, qid: str) -> None:
        entry = self.get(qid)
        if entry is not None and entry.state == "queued":
            entry.state = "running"

    def finish(self, qid: str, state: str = "done",
               error_code: Optional[str] = None) -> None:
        """Mark terminal (idempotent: the first terminal state wins) and
        evict the oldest finished entries past the bound."""
        with self._lock:
            entry = self._entries.get(qid)
            if entry is None or entry.state in _TERMINAL:
                return
            entry.state = state if state in _TERMINAL else "done"
            entry.error_code = error_code
            entry.finished_perf = time.perf_counter()
            self._finished.append(qid)
            while len(self._finished) > self.keep_finished:
                self._entries.pop(self._finished.pop(0), None)
        if entry.state == "done":
            # failures/cancels record their own richer events (query.fail
            # via the flush hook, query.cancel at the request site)
            from . import flight

            flight.record("query.finish", qid=qid,
                          elapsed_ms=entry.elapsed_ms())

    def discard(self, qid: str) -> None:
        """Remove an entry that was never admitted (a shed submit): a
        rejected query must not occupy the live table."""
        with self._lock:
            self._entries.pop(qid, None)

    # ------------------------------------------------------------- queries
    def get(self, qid: str) -> Optional[LiveQuery]:
        with self._lock:
            return self._entries.get(qid)

    def cancel(self, qid: str) -> bool:
        """Cooperative cancel: flag the entry's ticket so the executor's
        next checkpoint (per plan node; between streamed launches) raises.
        True when a live entry with a ticket was flagged."""
        entry = self.get(qid)
        if entry is None or entry.state in _TERMINAL:
            return False
        if entry.ticket is None:
            return False
        entry.ticket.cancel()
        return True

    def live_entries(self) -> List[LiveQuery]:
        with self._lock:
            return [e for e in self._entries.values()
                    if e.state not in _TERMINAL]

    def entries(self) -> List[LiveQuery]:
        with self._lock:
            return list(self._entries.values())

    def inflight_measured_bytes(self) -> int:
        """Sum of the MEASURED footprints live queries have reported so far
        — the ledger's measured-vs-reserved reconciliation input."""
        return sum(e.measured_bytes or 0 for e in self.live_entries())

    def rows(self) -> List[Tuple[str, str, str]]:
        """(Qid, Field, Value) triples — the ``SHOW QUERIES`` result shape
        (live queries first, newest-finished tail after)."""
        entries = self.entries()
        entries.sort(key=lambda e: (e.state in _TERMINAL, e.submitted_perf))
        out: List[Tuple[str, str, str]] = []
        for e in entries:
            out.extend((e.qid, f, v) for f, v in e.fields())
        return out

    def snapshot(self) -> List[Dict[str, Any]]:
        entries = self.entries()
        entries.sort(key=lambda e: (e.state in _TERMINAL, e.submitted_perf))
        return [e.as_dict() for e in entries]

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


# ---------------------------------------------------------------------------
# activation scope: the entry of the query running on this thread
# ---------------------------------------------------------------------------
_current: "contextvars.ContextVar[Optional[LiveQuery]]" = \
    contextvars.ContextVar("dsql_live_query", default=None)


def current_live() -> Optional[LiveQuery]:
    return _current.get()


@contextlib.contextmanager
def activate(entry: Optional[LiveQuery]):
    token = _current.set(entry)
    try:
        yield entry
    finally:
        _current.reset(token)


def update(**fields) -> None:
    """Set fields on the current thread's live entry; no-op without one —
    instrumented engine layers (ladder, batcher, streaming loop) call this
    unconditionally."""
    entry = _current.get()
    if entry is None:
        return
    for name, value in fields.items():
        setattr(entry, name, value)
