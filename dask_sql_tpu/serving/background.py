"""Background recompilation: ladder recompiles off the critical path.

The compiled planners cache their pipelines keyed on (table uid, row
bucket, plan shape) — when a table is replaced or grows past its pow2
bucket, the key misses and the next query pays a full foreground XLA
compile on the serving path.  This module moves that recompile off the
critical path: when a *known plan family* (same shape, new bucket) misses,
the query is served on the interpreted rung while a bounded background
thread rebuilds and compiles the new pipeline, then swaps it into the
plugin cache atomically under the plan-cache lock (`Context._plan_lock`).
Subsequent queries hit the fresh executable.

Discipline: one daemon thread, a bounded pending queue (past the bound
submissions are dropped and the query simply compiles in the foreground
next time), per-family dedup so a hot family enqueues once, and every
compile inside a task runs through `timed_jit_call` — so the compile
watchdog (resilience/watchdog.py) and the persistent executable cache
(compile_cache.py) apply to background compiles exactly as they do to
foreground ones.  A failed task un-marks its family: the next query takes
the foreground path and the degradation ladder handles the failure with
its normal taxonomy/breaker policy.

Off by default (``serving.bg_compile.enabled``): trading the first
post-growth query's latency for an interpreted-rung execution is a
serving-fleet tradeoff, not a notebook default.
"""
from __future__ import annotations

import atexit
import logging
import threading
import time
import weakref
from collections import deque
from typing import Callable, Optional, Set, Tuple

logger = logging.getLogger(__name__)

#: live compilers drained at interpreter exit — a daemon thread killed by
#: teardown mid-XLA segfaults the process (same hazard as warmup.py)
_live: "weakref.WeakSet[BackgroundCompiler]" = weakref.WeakSet()
_ATEXIT_JOIN_S = 10.0


@atexit.register
def _drain_at_exit() -> None:
    compilers = list(_live)
    for c in compilers:
        c.cancel()
    for c in compilers:
        c.join(_ATEXIT_JOIN_S)


class BackgroundCompiler:
    """Single bounded daemon worker running compile-and-swap tasks."""

    def __init__(self, metrics=None, max_pending: int = 8,
                 suspended: Optional[Callable[[], bool]] = None):
        self.metrics = metrics
        self.max_pending = max(1, int(max_pending))
        #: pressure gate (resilience/pressure.py): when this returns True
        #: (YELLOW band or worse) submissions are deferred — the caller
        #: falls back to the foreground path and re-submits on a later
        #: miss, so background compiles resume once headroom recovers
        self.suspended = suspended
        self._cv = threading.Condition()
        self._queue: "deque[Tuple[object, Callable[[], None]]]" = deque()
        self._pending: Set[object] = set()
        self._shutdown = False
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def from_config(cls, config, metrics=None,
                    suspended=None) -> "BackgroundCompiler":
        return cls(metrics=metrics,
                   max_pending=int(config.get(
                       "serving.bg_compile.max_pending", 8)),
                   suspended=suspended)

    # ------------------------------------------------------------- submit
    def submit(self, key, task: Callable[[], None]) -> bool:
        """Enqueue ``task`` under dedup key; False = dropped (full, dup,
        shut down, or deferred under HBM pressure) — the caller should
        fall back to the foreground path."""
        if self.suspended is not None and self.suspended():
            if self.metrics is not None:
                self.metrics.inc("resilience.pressure.suspended")
            return False
        with self._cv:
            if self._shutdown or key in self._pending:
                return False
            if len(self._queue) >= self.max_pending:
                if self.metrics is not None:
                    self.metrics.inc("serving.bg_compile.dropped")
                return False
            self._pending.add(key)
            self._queue.append((key, task))
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name="dsql-bg-compile")
                _live.add(self)
                self._thread.start()
            self._cv.notify()
        if self.metrics is not None:
            self.metrics.inc("serving.bg_compile.submitted")
        return True

    def pending(self, key) -> bool:
        with self._cv:
            return key in self._pending

    # ------------------------------------------------------------- worker
    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._shutdown:
                    self._cv.wait()
                if self._shutdown:
                    return
                key, task = self._queue.popleft()
            t0 = time.perf_counter()
            try:
                task()
            except Exception:  # dsql: allow-broad-except — a background
                # compile failure must not kill the worker; the family is
                # un-marked by the task's own cleanup and the next query
                # takes the foreground path where the ladder applies policy
                if self.metrics is not None:
                    self.metrics.inc("serving.bg_compile.failed")
                logger.warning("background compile failed", exc_info=True)
            else:
                if self.metrics is not None:
                    self.metrics.inc("serving.bg_compile.completed")
                    self.metrics.observe(
                        "serving.bg_compile.ms",
                        (time.perf_counter() - t0) * 1000.0)
            finally:
                with self._cv:
                    self._pending.discard(key)
                    self._cv.notify_all()

    # ----------------------------------------------------------- lifecycle
    def cancel(self) -> None:
        """Drop queued tasks and stop the worker after the in-flight one."""
        with self._cv:
            self._shutdown = True
            self._queue.clear()
            self._pending.clear()
            self._cv.notify_all()

    def join(self, timeout: Optional[float] = None) -> None:
        t = self._thread
        if t is not None:
            t.join(timeout)

    def wait_idle(self, timeout: float = 30.0) -> bool:
        """Block until every submitted task finished (tests/bench)."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._pending or self._queue:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(remaining)
        return True
