"""Static analysis: plan verifier + cost/memory estimator + engine self-lint.

Three parts (see docs/analysis.md):

- **Plan verifier** (`verifier.py`): an independent re-inference of every
  plan node's output schema (names, dtype categories, nullability, shape
  buckets) cross-checked against what the bound plan *declares* and what
  the physical layer will emit.  Inconsistencies raise a taxonomy
  ``PlanError`` at bind time instead of surfacing as a mid-execution
  compile failure; statically-doomed compiled rungs (radix-domain
  overflow of the ``1 << 22`` gate in `physical/compiled*.py`) are
  marked on the plan so the degradation ladder skips them without
  attempting, and recompilation hazards (shapes outside the power-of-two
  bucketing scheme) are reported by ``EXPLAIN LINT``.

- **Cost & memory estimator** (`estimator.py`): a bottom-up abstract
  interpreter propagating cardinality and byte-footprint intervals per
  plan node, yielding a provable lower bound and a conservative upper
  bound on peak device bytes.  Surfaced as ``EXPLAIN ESTIMATE``, consumed
  by the pre-compile admission byte gate
  (``serving.admission.max_estimated_bytes``), result-cache admission,
  and proof-driven ladder rung pre-skips.

- **Engine self-lint** (`selflint.py` + `concurrency.py`): an AST
  analyzer over the engine's own source (``python -m dask_sql_tpu.analysis
  --self``) with rules for broad exception handlers that can swallow
  taxonomy errors (DSQL101), lock-coverage gaps on the serving path
  (DSQL201), host-sync calls inside jit-traced code (DSQL301), metric and
  flight-event vocabulary drift (DSQL401/501), and the concurrency suite
  (DSQL601 repo-wide lock-order cycles, DSQL602 blocking calls under a
  held lock, DSQL603 the ``_locked``-suffix contract).  Run as a tier-1
  test so regressions fail CI; the runtime counterpart of DSQL601 is the
  lock sanitizer in runtime/locks.py.
"""
from .estimator import (
    Interval,
    PlanEstimate,
    estimate_and_apply,
    estimate_plan,
)
from .findings import Finding, SEV_ERROR, SEV_INFO, SEV_WARN
from .selflint import LintFinding, RULES, lint_paths, self_lint
from .verifier import (
    PlanVerdict,
    RADIX_DOMAIN_LIMIT,
    check_plan,
    verify_and_apply,
    verify_plan,
)

__all__ = [
    "Finding",
    "Interval",
    "LintFinding",
    "PlanEstimate",
    "PlanVerdict",
    "RADIX_DOMAIN_LIMIT",
    "RULES",
    "SEV_ERROR",
    "SEV_INFO",
    "SEV_WARN",
    "check_plan",
    "estimate_and_apply",
    "estimate_plan",
    "lint_paths",
    "self_lint",
    "verify_and_apply",
    "verify_plan",
]
