"""Shared utilities (parity: reference dask_sql/utils.py — Pluggable registry
base utils.py:61, convert_sql_kwargs utils.py:144, LoggableDataFrame
utils.py:121-141, new_temporary_column)."""
from __future__ import annotations

import uuid
from typing import Any, Dict


class Pluggable:
    """Registry base: subclasses share a class-level plugin dict."""

    __plugins: Dict[type, Dict[str, Any]] = {}

    @classmethod
    def add_plugin(cls, name: str, plugin: Any, replace: bool = True) -> None:
        registry = Pluggable.__plugins.setdefault(cls, {})
        if name in registry and not replace:
            return
        registry[name] = plugin

    @classmethod
    def get_plugin(cls, name: str) -> Any:
        return Pluggable.__plugins.setdefault(cls, {})[name]

    @classmethod
    def get_plugins(cls):
        return list(Pluggable.__plugins.setdefault(cls, {}).values())


def convert_sql_kwargs(sql_kwargs) -> Dict[str, Any]:
    """Normalize parsed WITH(...) kwargs (nested maps/lists/scalars) into
    plain python values (parity: utils.py:144)."""
    if isinstance(sql_kwargs, dict):
        return {k: convert_sql_kwargs(v) for k, v in sql_kwargs.items()}
    if isinstance(sql_kwargs, (list, tuple)):
        return [convert_sql_kwargs(v) for v in sql_kwargs]
    return sql_kwargs


def new_temporary_column(table) -> str:
    """Unique backend column name (parity: utils.py new_temporary_column)."""
    while True:
        name = f"__tmp_{uuid.uuid4().hex[:12]}"
        if name not in getattr(table, "columns", {}):
            return name


class LoggableDataFrame:
    """Lazy repr wrapper so logging never materializes a frame
    (parity: utils.py:121-141)."""

    def __init__(self, df):
        self.df = df

    def __str__(self):
        df = self.df
        if hasattr(df, "column_names"):
            return f"Table[{getattr(df, 'num_rows', '?')} rows, cols={df.column_names}]"
        if hasattr(df, "columns"):
            return f"DataFrame[cols={list(df.columns)}]"
        return f"{type(df).__name__}"

    __repr__ = __str__


# ---------------------------------------------------------------------------
# device-transfer accounting (perf instrumentation, VERDICT r4 #1)
# ---------------------------------------------------------------------------
#: device->host transfers made through the engine's own seams (the packed
#: result pull, host_read, table materialization).  On a tunneled TPU each
#: transfer is a round trip, so the per-query delta is the number the Q1
#: perf work drives toward 1.  Reset with `TRANSFER_STATS.clear()`.
TRANSFER_STATS: Dict[str, int] = {"d2h": 0}


def count_d2h(n: int = 1) -> None:
    TRANSFER_STATS["d2h"] = TRANSFER_STATS.get("d2h", 0) + n


def host_ints(*vals):
    """Pull several device scalars in ONE device_get (each separate int()
    call blocks on its own round trip on a tunneled chip)."""
    import jax

    count_d2h()
    return tuple(int(v) for v in jax.device_get(vals))
