"""Profile-driven pre-warm: replay hot fingerprints before taking traffic.

The checkpoint subsystem persists the per-fingerprint ProfileStore
(observability/profiles.py) next to each catalog snapshot, so a restarted
process knows exactly which query families its predecessor served hottest.
This module turns that knowledge into readiness: on `Context.load_state`
(and Presto-server boot) a background thread replays the top-N profiled
statements through the full parse->bind->compile->execute path, populating
the plan cache, the jit caches of every compiled rung, and — when the
persistent executable cache (compile_cache.py) is enabled — deserializing
XLA executables from disk instead of recompiling them.

Readiness is a first-class state machine the server's ``/v1/health``
endpoint reports (``warming (k/N)`` with HTTP 503 -> ``ready`` with 200),
so a load balancer keeps traffic off a cold process until its hot paths
are compiled.  Warm-up is best-effort by design: a statement that fails to
replay (table dropped since the snapshot, injected fault) is counted
(``serving.warmup.failed``) and skipped — a broken profile entry must
never wedge readiness.

Lifecycle: the manager registers with the ServingRuntime when a server
front-end is attached, so ``ServingRuntime.shutdown(wait=True)`` cancels
and joins the warm thread deterministically (cancellation takes effect
between statements; the in-flight statement finishes).
"""
from __future__ import annotations

import atexit
import logging
import threading
import time
import weakref
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

#: warm-up states surfaced by ``/v1/health``
IDLE, WARMING, READY = "idle", "warming", "ready"

#: live managers drained at interpreter exit: a daemon thread killed by
#: teardown MID-XLA segfaults the process (observed ~1 in 5 exits), so
#: atexit cancels every pass (cooperative, takes effect at the executor's
#: per-node checkpoints) and joins it bounded
_live: "weakref.WeakSet[WarmupManager]" = weakref.WeakSet()
_ATEXIT_JOIN_S = 10.0


@atexit.register
def _drain_at_exit() -> None:
    managers = list(_live)
    for m in managers:
        m.cancel()
    for m in managers:
        m.join(_ATEXIT_JOIN_S)


class WarmupManager:
    """One warm-up pass over the profile store's hottest fingerprints."""

    def __init__(self, context, top_n: int = 8,
                 throttle_s: float = 0.0):
        self.context = context
        self.top_n = max(0, int(top_n))
        self.throttle_s = max(0.0, float(throttle_s))
        self._lock = threading.Lock()
        self._state = IDLE
        self._thread: Optional[threading.Thread] = None
        self._cancel = threading.Event()
        self.total = 0
        self.warmed = 0
        self.failed = 0
        self.skipped = 0
        #: ticket of the in-flight warm statement (cooperative cancel)
        self._current_ticket = None

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "WarmupManager":
        with self._lock:
            if self._thread is not None:
                return self
            self._state = WARMING
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="dsql-warmup")
        _live.add(self)
        self.context.metrics.inc("serving.warmup.started")
        self._thread.start()
        return self

    def cancel(self) -> None:
        """Stop the pass: the in-flight statement aborts at the executor's
        next cancellation checkpoint (its ticket is cancelled), later
        entries never start; ``join`` afterwards for determinism."""
        self._cancel.set()
        with self._lock:
            ticket = self._current_ticket
        if ticket is not None:
            ticket.cancel()

    def join(self, timeout: Optional[float] = None) -> None:
        t = self._thread
        if t is not None:
            t.join(timeout)

    # ----------------------------------------------------------- the pass
    @staticmethod
    def _replayable(sql: str) -> bool:
        """Only single read-only statements replay: a profiled SCRIPT can
        carry DDL ("CREATE TABLE ...; SELECT ...") whose re-execution at
        boot would mutate the restored catalog."""
        head = sql.lstrip().lower()
        if not head.startswith(("select", "with", "values", "(")):
            return False
        try:
            from ..planner.parser import parse_sql

            return len(parse_sql(sql)) == 1
        except Exception:  # dsql: allow-broad-except — an unparseable
            # profile is simply not warmable; never block the pass
            return False

    def _candidates(self) -> List[Tuple[str, str]]:
        return [(fp, sql) for fp, sql
                in self.context.profiles.warm_candidates(self.top_n)
                if self._replayable(sql)]

    def _run(self) -> None:
        ctx = self.context
        entries = self._candidates()
        n_ranked = len(ctx.profiles.top_fingerprints(self.top_n))
        with self._lock:
            self.total = len(entries)
            self.skipped = n_ranked - len(entries)
        if self.skipped:
            # hot fingerprints whose SQL was lost to truncation or never
            # recorded: visible, so an operator knows the warm set is partial
            ctx.metrics.inc("serving.warmup.skipped", self.skipped)
        t_start = time.perf_counter()
        from .admission import QueryTicket
        from . import runtime as _runtime

        for fp, sql in entries:
            # YELLOW-band pressure gate (resilience/pressure.py): warm-up
            # replays are speculative device work — pause BETWEEN entries
            # while headroom is tight and resume when the band recovers
            # (cancel still takes effect immediately)
            paused = False
            pressure = getattr(ctx, "pressure", None)
            while (pressure is not None and not self._cancel.is_set()
                    and pressure.suspend_speculative()):
                if not paused:
                    paused = True
                    ctx.metrics.inc("resilience.pressure.suspended")
                    logger.info("warm-up paused under HBM pressure")
                self._cancel.wait(0.05)
            if self._cancel.is_set():
                ctx.metrics.inc("serving.warmup.cancelled")
                logger.info("warm-up cancelled after %d/%d fingerprints",
                            self.warmed, self.total)
                break
            t0 = time.perf_counter()
            # the warm statement runs under a cancellable ticket: cancel()
            # (shutdown drain, interpreter exit) aborts it at the
            # executor's next per-node checkpoint instead of letting a
            # daemon thread die mid-XLA during teardown (segfault)
            ticket = QueryTicket(f"warmup-{fp}")
            with self._lock:
                self._current_ticket = ticket
            _runtime._tls.ticket = ticket
            try:
                from ..observability import flight

                flight.record("warmup.replay", fingerprint=fp)
                frame = ctx.sql(sql)
                if frame is not None:
                    if frame._trace is not None:
                        # causality: this trace exists because the warm-up
                        # replayed the profile of fingerprint `fp` — the
                        # export shows why an idle process ran a query
                        frame._trace.event("warmup_replay",
                                           source_profile=fp)
                    # device-side execute only: warming compiles + caches;
                    # the d2h/pandas tail is per-request work
                    frame.execute()
                with self._lock:
                    self.warmed += 1
                ctx.metrics.inc("serving.warmup.warmed")
                ctx.metrics.observe("serving.warmup.ms",
                                    (time.perf_counter() - t0) * 1000.0)
            except Exception:  # dsql: allow-broad-except — warm-up is
                # best-effort: one unreplayable profile (stale table,
                # injected fault) must not block readiness or later entries
                if self._cancel.is_set():
                    continue  # cancelled mid-statement, not a failure
                with self._lock:
                    self.failed += 1
                ctx.metrics.inc("serving.warmup.failed")
                logger.warning("warm-up replay failed for fingerprint %s",
                               fp, exc_info=True)
            finally:
                _runtime._tls.ticket = None
                with self._lock:
                    self._current_ticket = None
            if self.throttle_s:
                self._cancel.wait(self.throttle_s)
        with self._lock:
            self._state = READY
        logger.info(
            "warm-up ready: %d/%d fingerprints warmed (%d failed) in %.0fms",
            self.warmed, self.total, self.failed,
            (time.perf_counter() - t_start) * 1000.0)

    # -------------------------------------------------------------- reads
    @property
    def ready(self) -> bool:
        with self._lock:
            return self._state == READY

    def status(self) -> Dict[str, object]:
        """The ``/v1/health`` payload body."""
        with self._lock:
            state = self._state
            return {
                "status": state,
                "warmed": self.warmed,
                "total": self.total,
                "failed": self.failed,
            }
