"""Synthetic TPC-H data generator + the 22 query texts.

The analogue of the reference's TPC-DS-style q1-q99 suite
(tests/unit/test_queries.py there) — the coverage yardstick for the engine.
Data is random but schema-faithful, tiny by default (scale via n_*).
"""
from __future__ import annotations

import numpy as np
import pandas as pd

REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1), ("EGYPT", 4),
    ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3), ("INDIA", 2), ("INDONESIA", 2),
    ("IRAN", 4), ("IRAQ", 4), ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0),
    ("MOROCCO", 0), ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3), ("UNITED KINGDOM", 3),
    ("UNITED STATES", 1),
]
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
MODES = ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"]
INSTRUCTS = ["COLLECT COD", "DELIVER IN PERSON", "NONE", "TAKE BACK RETURN"]
TYPES = [f"{a} {b} {c}" for a in ("ECONOMY", "LARGE", "MEDIUM", "PROMO", "SMALL", "STANDARD")
         for b in ("ANODIZED", "BRUSHED", "BURNISHED", "PLATED", "POLISHED")
         for c in ("BRASS", "COPPER", "NICKEL", "STEEL", "TIN")]
CONTAINERS = [f"{a} {b}" for a in ("JUMBO", "LG", "MED", "SM", "WRAP")
              for b in ("BAG", "BOX", "CAN", "CASE", "DRUM", "JAR", "PACK", "PKG")]


def _dates(rng, n, start="1992-01-01", days=2526):
    base = np.datetime64(start)
    return base + rng.randint(0, days, n).astype("timedelta64[D]")


def generate(scale_rows: int = 2000, seed: int = 7):
    """All 8 TPC-H tables; `scale_rows` ~ lineitem row count."""
    rng = np.random.RandomState(seed)
    n_li = scale_rows
    n_ord = max(scale_rows // 4, 10)
    n_cust = max(scale_rows // 10, 10)
    n_part = max(scale_rows // 10, 10)
    n_supp = max(scale_rows // 100, 5)

    region = pd.DataFrame({
        "r_regionkey": np.arange(5, dtype=np.int64),
        "r_name": REGIONS,
        "r_comment": ["" for _ in REGIONS],
    })
    nation = pd.DataFrame({
        "n_nationkey": np.arange(len(NATIONS), dtype=np.int64),
        "n_name": [n for n, _ in NATIONS],
        "n_regionkey": np.array([r for _, r in NATIONS], dtype=np.int64),
        "n_comment": ["" for _ in NATIONS],
    })
    supplier = pd.DataFrame({
        "s_suppkey": np.arange(1, n_supp + 1, dtype=np.int64),
        "s_name": [f"Supplier#{i:09d}" for i in range(1, n_supp + 1)],
        "s_address": [f"addr{i}" for i in range(n_supp)],
        "s_nationkey": rng.randint(0, len(NATIONS), n_supp).astype(np.int64),
        "s_phone": [f"{rng.randint(10, 35)}-{i:03d}" for i in range(n_supp)],
        "s_acctbal": np.round(rng.rand(n_supp) * 11000 - 1000, 2),
        "s_comment": ["Customer Complaints" if rng.rand() < 0.05 else "fine" for _ in range(n_supp)],
    })
    part = pd.DataFrame({
        "p_partkey": np.arange(1, n_part + 1, dtype=np.int64),
        "p_name": [f"{rng.choice(['green','blue','red','ivory','forest'])} part {i}" for i in range(n_part)],
        "p_mfgr": [f"Manufacturer#{rng.randint(1, 6)}" for _ in range(n_part)],
        "p_brand": [f"Brand#{rng.randint(1, 6)}{rng.randint(1, 6)}" for _ in range(n_part)],
        "p_type": rng.choice(TYPES, n_part),
        "p_size": rng.randint(1, 51, n_part).astype(np.int64),
        "p_container": rng.choice(CONTAINERS, n_part),
        "p_retailprice": np.round(900 + rng.rand(n_part) * 1200, 2),
        "p_comment": ["" for _ in range(n_part)],
    })
    partsupp_rows = []
    for pk in range(1, n_part + 1):
        for s in rng.choice(np.arange(1, n_supp + 1), size=min(2, n_supp), replace=False):
            partsupp_rows.append((pk, int(s)))
    partsupp = pd.DataFrame(partsupp_rows, columns=["ps_partkey", "ps_suppkey"])
    partsupp["ps_availqty"] = rng.randint(1, 10000, len(partsupp)).astype(np.int64)
    partsupp["ps_supplycost"] = np.round(1 + rng.rand(len(partsupp)) * 1000, 2)
    partsupp["ps_comment"] = ""

    customer = pd.DataFrame({
        "c_custkey": np.arange(1, n_cust + 1, dtype=np.int64),
        "c_name": [f"Customer#{i:09d}" for i in range(1, n_cust + 1)],
        "c_address": [f"caddr{i}" for i in range(n_cust)],
        "c_nationkey": rng.randint(0, len(NATIONS), n_cust).astype(np.int64),
        "c_phone": [f"{rng.randint(10, 35)}-{i:04d}" for i in range(n_cust)],
        "c_acctbal": np.round(rng.rand(n_cust) * 11000 - 1000, 2),
        "c_mktsegment": rng.choice(SEGMENTS, n_cust),
        "c_comment": ["" for _ in range(n_cust)],
    })
    orders = pd.DataFrame({
        "o_orderkey": np.arange(1, n_ord + 1, dtype=np.int64),
        "o_custkey": rng.randint(1, n_cust + 1, n_ord).astype(np.int64),
        "o_orderstatus": rng.choice(["F", "O", "P"], n_ord),
        "o_totalprice": np.round(1000 + rng.rand(n_ord) * 400000, 2),
        "o_orderdate": _dates(rng, n_ord),
        "o_orderpriority": rng.choice(PRIORITIES, n_ord),
        "o_clerk": [f"Clerk#{rng.randint(1, 100):09d}" for _ in range(n_ord)],
        "o_shippriority": np.zeros(n_ord, dtype=np.int64),
        "o_comment": rng.choice(["", "special requests", "deposits"], n_ord),
    })
    okeys = rng.randint(1, n_ord + 1, n_li).astype(np.int64)
    odate_by_key = orders.set_index("o_orderkey").o_orderdate
    shipbase = odate_by_key.loc[okeys].to_numpy()
    lineitem = pd.DataFrame({
        "l_orderkey": okeys,
        "l_partkey": rng.randint(1, n_part + 1, n_li).astype(np.int64),
        "l_suppkey": rng.randint(1, n_supp + 1, n_li).astype(np.int64),
        "l_linenumber": (np.arange(n_li) % 7 + 1).astype(np.int64),
        "l_quantity": rng.randint(1, 51, n_li).astype(np.float64),
        "l_extendedprice": np.round(rng.rand(n_li) * 100000, 2),
        "l_discount": np.round(rng.randint(0, 11, n_li) / 100.0, 2),
        "l_tax": np.round(rng.randint(0, 9, n_li) / 100.0, 2),
        "l_returnflag": rng.choice(["A", "N", "R"], n_li),
        "l_linestatus": rng.choice(["F", "O"], n_li),
        "l_shipdate": shipbase + rng.randint(1, 122, n_li).astype("timedelta64[D]"),
        "l_commitdate": shipbase + rng.randint(30, 91, n_li).astype("timedelta64[D]"),
        "l_receiptdate": shipbase + rng.randint(1, 153, n_li).astype("timedelta64[D]"),
        "l_shipinstruct": rng.choice(INSTRUCTS, n_li),
        "l_shipmode": rng.choice(MODES, n_li),
        "l_comment": ["" for _ in range(n_li)],
    })
    return {
        "region": region, "nation": nation, "supplier": supplier, "part": part,
        "partsupp": partsupp, "customer": customer, "orders": orders,
        "lineitem": lineitem,
    }


QUERIES = {
    1: """
        SELECT l_returnflag, l_linestatus,
               SUM(l_quantity) AS sum_qty,
               SUM(l_extendedprice) AS sum_base_price,
               SUM(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
               SUM(l_extendedprice * (1 - l_discount) * (1 + l_tax)) AS sum_charge,
               AVG(l_quantity) AS avg_qty,
               AVG(l_extendedprice) AS avg_price,
               AVG(l_discount) AS avg_disc,
               COUNT(*) AS count_order
        FROM lineitem
        WHERE l_shipdate <= DATE '1998-09-02'
        GROUP BY l_returnflag, l_linestatus
        ORDER BY l_returnflag, l_linestatus
    """,
    2: """
        SELECT s_acctbal, s_name, n_name, p_partkey, p_mfgr, s_address, s_phone, s_comment
        FROM part, supplier, partsupp, nation, region
        WHERE p_partkey = ps_partkey AND s_suppkey = ps_suppkey
          AND p_size = 15 AND p_type LIKE '%BRASS'
          AND s_nationkey = n_nationkey AND n_regionkey = r_regionkey
          AND r_name = 'EUROPE'
          AND ps_supplycost = (
              SELECT MIN(ps_supplycost) FROM partsupp, supplier, nation, region
              WHERE p_partkey = ps_partkey AND s_suppkey = ps_suppkey
                AND s_nationkey = n_nationkey AND n_regionkey = r_regionkey
                AND r_name = 'EUROPE')
        ORDER BY s_acctbal DESC, n_name, s_name, p_partkey
        LIMIT 100
    """,
    3: """
        SELECT l_orderkey, SUM(l_extendedprice * (1 - l_discount)) AS revenue,
               o_orderdate, o_shippriority
        FROM customer, orders, lineitem
        WHERE c_mktsegment = 'BUILDING' AND c_custkey = o_custkey
          AND l_orderkey = o_orderkey AND o_orderdate < DATE '1995-03-15'
          AND l_shipdate > DATE '1995-03-15'
        GROUP BY l_orderkey, o_orderdate, o_shippriority
        ORDER BY revenue DESC, o_orderdate
        LIMIT 10
    """,
    4: """
        SELECT o_orderpriority, COUNT(*) AS order_count
        FROM orders
        WHERE o_orderdate >= DATE '1993-07-01' AND o_orderdate < DATE '1993-10-01'
          AND EXISTS (SELECT 1 FROM lineitem
                      WHERE l_orderkey = o_orderkey AND l_commitdate < l_receiptdate)
        GROUP BY o_orderpriority
        ORDER BY o_orderpriority
    """,
    5: """
        SELECT n_name, SUM(l_extendedprice * (1 - l_discount)) AS revenue
        FROM customer, orders, lineitem, supplier, nation, region
        WHERE c_custkey = o_custkey AND l_orderkey = o_orderkey
          AND l_suppkey = s_suppkey AND c_nationkey = s_nationkey
          AND s_nationkey = n_nationkey AND n_regionkey = r_regionkey
          AND r_name = 'ASIA' AND o_orderdate >= DATE '1994-01-01'
          AND o_orderdate < DATE '1995-01-01'
        GROUP BY n_name
        ORDER BY revenue DESC
    """,
    6: """
        SELECT SUM(l_extendedprice * l_discount) AS revenue
        FROM lineitem
        WHERE l_shipdate >= DATE '1994-01-01' AND l_shipdate < DATE '1995-01-01'
          AND l_discount BETWEEN 0.05 AND 0.07 AND l_quantity < 24
    """,
    7: """
        SELECT supp_nation, cust_nation, l_year, SUM(volume) AS revenue
        FROM (SELECT n1.n_name AS supp_nation, n2.n_name AS cust_nation,
                     EXTRACT(YEAR FROM l_shipdate) AS l_year,
                     l_extendedprice * (1 - l_discount) AS volume
              FROM supplier, lineitem, orders, customer, nation n1, nation n2
              WHERE s_suppkey = l_suppkey AND o_orderkey = l_orderkey
                AND c_custkey = o_custkey AND s_nationkey = n1.n_nationkey
                AND c_nationkey = n2.n_nationkey
                AND ((n1.n_name = 'FRANCE' AND n2.n_name = 'GERMANY')
                     OR (n1.n_name = 'GERMANY' AND n2.n_name = 'FRANCE'))
                AND l_shipdate BETWEEN DATE '1995-01-01' AND DATE '1996-12-31'
             ) AS shipping
        GROUP BY supp_nation, cust_nation, l_year
        ORDER BY supp_nation, cust_nation, l_year
    """,
    8: """
        SELECT o_year,
               SUM(CASE WHEN nation = 'BRAZIL' THEN volume ELSE 0 END) / SUM(volume) AS mkt_share
        FROM (SELECT EXTRACT(YEAR FROM o_orderdate) AS o_year,
                     l_extendedprice * (1 - l_discount) AS volume,
                     n2.n_name AS nation
              FROM part, supplier, lineitem, orders, customer, nation n1, nation n2, region
              WHERE p_partkey = l_partkey AND s_suppkey = l_suppkey
                AND l_orderkey = o_orderkey AND o_custkey = c_custkey
                AND c_nationkey = n1.n_nationkey AND n1.n_regionkey = r_regionkey
                AND r_name = 'AMERICA' AND s_nationkey = n2.n_nationkey
                AND o_orderdate BETWEEN DATE '1995-01-01' AND DATE '1996-12-31'
                AND p_type = 'ECONOMY ANODIZED STEEL'
             ) AS all_nations
        GROUP BY o_year
        ORDER BY o_year
    """,
    9: """
        SELECT nation, o_year, SUM(amount) AS sum_profit
        FROM (SELECT n_name AS nation, EXTRACT(YEAR FROM o_orderdate) AS o_year,
                     l_extendedprice * (1 - l_discount) - ps_supplycost * l_quantity AS amount
              FROM part, supplier, lineitem, partsupp, orders, nation
              WHERE s_suppkey = l_suppkey AND ps_suppkey = l_suppkey
                AND ps_partkey = l_partkey AND p_partkey = l_partkey
                AND o_orderkey = l_orderkey AND s_nationkey = n_nationkey
                AND p_name LIKE '%green%'
             ) AS profit
        GROUP BY nation, o_year
        ORDER BY nation, o_year DESC
    """,
    10: """
        SELECT c_custkey, c_name, SUM(l_extendedprice * (1 - l_discount)) AS revenue,
               c_acctbal, n_name, c_address, c_phone, c_comment
        FROM customer, orders, lineitem, nation
        WHERE c_custkey = o_custkey AND l_orderkey = o_orderkey
          AND o_orderdate >= DATE '1993-10-01' AND o_orderdate < DATE '1994-01-01'
          AND l_returnflag = 'R' AND c_nationkey = n_nationkey
        GROUP BY c_custkey, c_name, c_acctbal, c_phone, n_name, c_address, c_comment
        ORDER BY revenue DESC
        LIMIT 20
    """,
    11: """
        SELECT ps_partkey, SUM(ps_supplycost * ps_availqty) AS "value"
        FROM partsupp, supplier, nation
        WHERE ps_suppkey = s_suppkey AND s_nationkey = n_nationkey
          AND n_name = 'GERMANY'
        GROUP BY ps_partkey
        HAVING SUM(ps_supplycost * ps_availqty) > (
            SELECT SUM(ps_supplycost * ps_availqty) * 0.0001
            FROM partsupp, supplier, nation
            WHERE ps_suppkey = s_suppkey AND s_nationkey = n_nationkey
              AND n_name = 'GERMANY')
        ORDER BY "value" DESC
    """,
    12: """
        SELECT l_shipmode,
               SUM(CASE WHEN o_orderpriority = '1-URGENT' OR o_orderpriority = '2-HIGH'
                        THEN 1 ELSE 0 END) AS high_line_count,
               SUM(CASE WHEN o_orderpriority <> '1-URGENT' AND o_orderpriority <> '2-HIGH'
                        THEN 1 ELSE 0 END) AS low_line_count
        FROM orders, lineitem
        WHERE o_orderkey = l_orderkey AND l_shipmode IN ('MAIL', 'SHIP')
          AND l_commitdate < l_receiptdate AND l_shipdate < l_commitdate
          AND l_receiptdate >= DATE '1994-01-01' AND l_receiptdate < DATE '1995-01-01'
        GROUP BY l_shipmode
        ORDER BY l_shipmode
    """,
    13: """
        SELECT c_count, COUNT(*) AS custdist
        FROM (SELECT c_custkey, COUNT(o_orderkey) AS c_count
              FROM customer LEFT JOIN orders
                ON c_custkey = o_custkey AND o_comment NOT LIKE '%special%requests%'
              GROUP BY c_custkey) AS c_orders
        GROUP BY c_count
        ORDER BY custdist DESC, c_count DESC
    """,
    14: """
        SELECT 100.00 * SUM(CASE WHEN p_type LIKE 'PROMO%'
                                 THEN l_extendedprice * (1 - l_discount) ELSE 0 END)
               / SUM(l_extendedprice * (1 - l_discount)) AS promo_revenue
        FROM lineitem, part
        WHERE l_partkey = p_partkey AND l_shipdate >= DATE '1995-09-01'
          AND l_shipdate < DATE '1995-10-01'
    """,
    15: """
        WITH revenue AS (
            SELECT l_suppkey AS supplier_no,
                   SUM(l_extendedprice * (1 - l_discount)) AS total_revenue
            FROM lineitem
            WHERE l_shipdate >= DATE '1996-01-01' AND l_shipdate < DATE '1996-04-01'
            GROUP BY l_suppkey)
        SELECT s_suppkey, s_name, s_address, s_phone, total_revenue
        FROM supplier, revenue
        WHERE s_suppkey = supplier_no
          AND total_revenue = (SELECT MAX(total_revenue) FROM revenue)
        ORDER BY s_suppkey
    """,
    16: """
        SELECT p_brand, p_type, p_size, COUNT(DISTINCT ps_suppkey) AS supplier_cnt
        FROM partsupp, part
        WHERE p_partkey = ps_partkey AND p_brand <> 'Brand#45'
          AND p_type NOT LIKE 'MEDIUM POLISHED%'
          AND p_size IN (49, 14, 23, 45, 19, 3, 36, 9)
          AND ps_suppkey NOT IN (SELECT s_suppkey FROM supplier
                                 WHERE s_comment LIKE '%Customer%Complaints%')
        GROUP BY p_brand, p_type, p_size
        ORDER BY supplier_cnt DESC, p_brand, p_type, p_size
    """,
    17: """
        SELECT SUM(l_extendedprice) / 7.0 AS avg_yearly
        FROM lineitem, part
        WHERE p_partkey = l_partkey AND p_brand = 'Brand#23'
          AND p_container = 'MED BOX'
          AND l_quantity < (SELECT 0.2 * AVG(l_quantity) FROM lineitem
                            WHERE l_partkey = p_partkey)
    """,
    18: """
        SELECT c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice, SUM(l_quantity) AS total_qty
        FROM customer, orders, lineitem
        WHERE o_orderkey IN (SELECT l_orderkey FROM lineitem
                             GROUP BY l_orderkey HAVING SUM(l_quantity) > 250)
          AND c_custkey = o_custkey AND o_orderkey = l_orderkey
        GROUP BY c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
        ORDER BY o_totalprice DESC, o_orderdate
        LIMIT 100
    """,
    19: """
        SELECT SUM(l_extendedprice * (1 - l_discount)) AS revenue
        FROM lineitem, part
        WHERE (p_partkey = l_partkey AND p_brand = 'Brand#12'
               AND p_container IN ('SM CASE', 'SM BOX', 'SM PACK', 'SM PKG')
               AND l_quantity >= 1 AND l_quantity <= 11 AND p_size BETWEEN 1 AND 5
               AND l_shipmode IN ('AIR', 'REG AIR')
               AND l_shipinstruct = 'DELIVER IN PERSON')
           OR (p_partkey = l_partkey AND p_brand = 'Brand#23'
               AND p_container IN ('MED BAG', 'MED BOX', 'MED PKG', 'MED PACK')
               AND l_quantity >= 10 AND l_quantity <= 20 AND p_size BETWEEN 1 AND 10
               AND l_shipmode IN ('AIR', 'REG AIR')
               AND l_shipinstruct = 'DELIVER IN PERSON')
           OR (p_partkey = l_partkey AND p_brand = 'Brand#34'
               AND p_container IN ('LG CASE', 'LG BOX', 'LG PACK', 'LG PKG')
               AND l_quantity >= 20 AND l_quantity <= 30 AND p_size BETWEEN 1 AND 15
               AND l_shipmode IN ('AIR', 'REG AIR')
               AND l_shipinstruct = 'DELIVER IN PERSON')
    """,
    20: """
        SELECT s_name, s_address
        FROM supplier, nation
        WHERE s_suppkey IN (
            SELECT ps_suppkey FROM partsupp
            WHERE ps_partkey IN (SELECT p_partkey FROM part WHERE p_name LIKE 'forest%')
              AND ps_availqty > (SELECT 0.5 * SUM(l_quantity) FROM lineitem
                                 WHERE l_partkey = ps_partkey AND l_suppkey = ps_suppkey
                                   AND l_shipdate >= DATE '1994-01-01'
                                   AND l_shipdate < DATE '1995-01-01'))
          AND s_nationkey = n_nationkey AND n_name = 'CANADA'
        ORDER BY s_name
    """,
    21: """
        SELECT s_name, COUNT(*) AS numwait
        FROM supplier, lineitem l1, orders, nation
        WHERE s_suppkey = l1.l_suppkey AND o_orderkey = l1.l_orderkey
          AND o_orderstatus = 'F' AND l1.l_receiptdate > l1.l_commitdate
          AND EXISTS (SELECT 1 FROM lineitem l2
                      WHERE l2.l_orderkey = l1.l_orderkey
                        AND l2.l_suppkey <> l1.l_suppkey)
          AND NOT EXISTS (SELECT 1 FROM lineitem l3
                          WHERE l3.l_orderkey = l1.l_orderkey
                            AND l3.l_suppkey <> l1.l_suppkey
                            AND l3.l_receiptdate > l3.l_commitdate)
          AND s_nationkey = n_nationkey AND n_name = 'SAUDI ARABIA'
        GROUP BY s_name
        ORDER BY numwait DESC, s_name
        LIMIT 100
    """,
    22: """
        SELECT cntrycode, COUNT(*) AS numcust, SUM(c_acctbal) AS totacctbal
        FROM (SELECT SUBSTRING(c_phone FROM 1 FOR 2) AS cntrycode, c_acctbal
              FROM customer
              WHERE SUBSTRING(c_phone FROM 1 FOR 2) IN ('13', '31', '23', '29', '30', '18', '17')
                AND c_acctbal > (SELECT AVG(c_acctbal) FROM customer
                                 WHERE c_acctbal > 0.00
                                   AND SUBSTRING(c_phone FROM 1 FOR 2)
                                       IN ('13', '31', '23', '29', '30', '18', '17'))
                AND NOT EXISTS (SELECT 1 FROM orders WHERE o_custkey = c_custkey)
             ) AS custsale
        GROUP BY cntrycode
        ORDER BY cntrycode
    """,
}
