"""Per-fingerprint query profiles.

One rolling profile per plan fingerprint (resilience/ladder.py
`plan_fingerprint`): hit counts, recent execute wall times, result bytes,
and per-ladder-rung compile wall times.  Three consumers:

- ``SHOW PROFILES [LIKE 'pat']`` renders the store as a result set
  (native and Python parser paths, physical/rel/custom/ddl.py);
- the checkpoint subsystem persists a JSON snapshot next to each catalog
  snapshot (`profiles.json`), so a restarted process knows its hot
  fingerprints — the input the zero-cold-start pre-warm (ROADMAP item 3)
  needs before it can pre-compile anything;
- the slow-query log and EXPLAIN ANALYZE read compile history to explain
  where a cold p99 went.

Everything is plain-JSON state (dicts, lists, floats) so snapshot/load is
`json.dump`/`json.load` with no schema mapping.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

#: truncation for the remembered SQL text of a fingerprint.  Sized so that
#: realistic serving statements survive whole: the pre-warm pass
#: (serving/warmup.py) REPLAYS this text after a restart, and a truncated
#: statement is unreplayable (flagged `sql_truncated`, skipped by warm-up).
_SQL_KEEP = 4096


def _percentile(values: List[float], q: float) -> float:
    # lazy import: serving/__init__ may still be mid-import when this
    # module loads through the observability package
    from ..serving.metrics import nearest_rank

    return nearest_rank(sorted(values), q)


def _fmt(v: float) -> str:
    if isinstance(v, float) and v == int(v):
        return str(int(v))
    return f"{v:.3f}" if isinstance(v, float) else str(v)


class ProfileStore:
    """Thread-safe bounded store: fingerprint -> rolling profile dict."""

    def __init__(self, window: int = 64, keep: int = 512):
        self.window = max(1, int(window))
        self.keep = max(1, int(keep))
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()

    # ------------------------------------------------------------ writes
    def _entry_locked(self, fingerprint: str, sql: Optional[str],
                      family: Optional[str] = None) -> Dict[str, Any]:
        e = self._entries.get(fingerprint)
        if e is None:
            e = self._entries[fingerprint] = {
                "sql": (sql or "")[:_SQL_KEEP],
                "sql_truncated": len(sql or "") > _SQL_KEEP,
                #: the literal-stripped family fingerprint (families/);
                #: "" for profiles recorded with families disabled or
                #: restored from pre-family snapshots.  With families on,
                #: entries are KEYED by family, so hit counts roll up
                #: across every literal variant of the statement.
                "family": family or "",
                "hits": 0,
                "cache_hits": 0,
                "exec_ms": [],
                "result_bytes": [],
                #: observed output cardinalities (rolling) — the feedback
                #: prior the estimator tightens its upper bounds with
                #: (analysis/estimator.py apply_feedback)
                "rows": [],
                #: the estimator's most recent rows upper bound for this
                #: family (None = unbounded/never estimated): SHOW PROFILES
                #: renders it beside the observed rows so operators can see
                #: where feedback tightened the estimate
                "est_rows_hi": None,
                "compile": {},  # rung -> {"count": n, "ms": [rolling]}
                #: per-ladder-rung exec wall times, surfaced as SHOW
                #: PROFILES ``rung.<rung>.*`` rows so operators can compare
                #: what each rung actually costs a family.  (The cost-based
                #: selector itself decides on the family-level exec history
                #: plus the global per-rung compile priors — see
                #: resilience/ladder.py cost_skip.)
                "rungs": {},  # rung -> {"count": n, "ms": [rolling]}
                "last_seen": 0.0,
            }
        else:
            if sql and not e["sql"]:
                e["sql"] = sql[:_SQL_KEEP]
                e["sql_truncated"] = len(sql) > _SQL_KEEP
            if family and not e.get("family"):
                e["family"] = family
        self._entries.move_to_end(fingerprint)
        while len(self._entries) > self.keep:
            self._entries.popitem(last=False)
        e["last_seen"] = time.time()
        return e

    def record_exec(self, fingerprint: str, sql: Optional[str] = None,
                    exec_ms: Optional[float] = None,
                    result_bytes: Optional[int] = None,
                    cache_hit: bool = False,
                    family: Optional[str] = None,
                    rows: Optional[int] = None) -> None:
        with self._lock:
            e = self._entry_locked(fingerprint, sql, family)
            e["hits"] += 1
            if cache_hit:
                e["cache_hits"] += 1
            if exec_ms is not None:
                e["exec_ms"].append(round(float(exec_ms), 3))
                del e["exec_ms"][:-self.window]
            if result_bytes is not None:
                e["result_bytes"].append(int(result_bytes))
                del e["result_bytes"][:-self.window]
            if rows is not None:
                e["rows"].append(int(rows))
                del e["rows"][:-self.window]

    def record_rung_exec(self, fingerprint: str, rung: str, ms: float,
                         family: Optional[str] = None) -> None:
        """One successful ladder-rung execution for this fingerprint — the
        per-(family, rung) cost evidence behind cost-based rung selection
        (resilience/ladder.py `attempt`)."""
        with self._lock:
            e = self._entry_locked(fingerprint, None, family)
            r = e["rungs"].setdefault(rung, {"count": 0, "ms": []})
            r["count"] += 1
            r["ms"].append(round(float(ms), 3))
            del r["ms"][:-self.window]

    def record_estimate(self, fingerprint: str,
                        rows_hi: Optional[int],
                        family: Optional[str] = None) -> None:
        """The estimator's latest rows upper bound for this fingerprint —
        paired with the observed ``rows`` history in SHOW PROFILES so the
        estimated-vs-observed gap (what feedback closes) is visible.

        Updates EXISTING entries only (no create, no LRU bump): estimation
        also runs for EXPLAIN ESTIMATE and never-executed plans, and a
        nominally read-only statement must not evict hot execution
        profiles that feed warm-up ordering and drain hints."""
        with self._lock:
            e = self._entries.get(fingerprint)
            if e is None:
                return
            e["est_rows_hi"] = None if rows_hi is None else int(rows_hi)

    def record_compile(self, fingerprint: str, rung: str, ms: float,
                       sql: Optional[str] = None,
                       family: Optional[str] = None) -> None:
        with self._lock:
            e = self._entry_locked(fingerprint, sql, family)
            r = e["compile"].setdefault(rung, {"count": 0, "ms": []})
            r["count"] += 1
            r["ms"].append(round(float(ms), 3))
            del r["ms"][:-self.window]

    # ------------------------------------------------------------- reads
    def rows(self) -> List[Tuple[str, str, str, str]]:
        """(fingerprint, family, metric, value) rows for ``SHOW PROFILES``
        — same flat shape as SHOW METRICS plus the family column, one
        group of rows per profile."""
        with self._lock:
            entries = {fp: _copy_entry(e) for fp, e in self._entries.items()}
        out: List[Tuple[str, str, str, str]] = []
        for fp in sorted(entries):
            e = entries[fp]
            fam = e.get("family", "")
            out.append((fp, fam, "sql", e["sql"]))
            out.append((fp, fam, "hits", str(e["hits"])))
            out.append((fp, fam, "cache_hits", str(e["cache_hits"])))
            if e["exec_ms"]:
                out.append((fp, fam, "exec_ms.p50",
                            _fmt(_percentile(e["exec_ms"], 0.5))))
                out.append((fp, fam, "exec_ms.max", _fmt(max(e["exec_ms"]))))
                out.append((fp, fam, "exec_ms.last", _fmt(e["exec_ms"][-1])))
            if e["result_bytes"]:
                out.append((fp, fam, "result_bytes.last",
                            str(e["result_bytes"][-1])))
            # estimated-vs-observed cardinality: where the estimator's
            # upper bound sits against what the family actually returned
            # (the gap profile feedback tightens, docs/analysis.md)
            if e.get("est_rows_hi") is not None:
                out.append((fp, fam, "rows.est_hi", str(e["est_rows_hi"])))
            if e.get("rows"):
                out.append((fp, fam, "rows.observed.last",
                            str(e["rows"][-1])))
                out.append((fp, fam, "rows.observed.max",
                            str(max(e["rows"]))))
            for rung in sorted(e.get("rungs", {})):
                r = e["rungs"][rung]
                out.append((fp, fam, f"rung.{rung}.count", str(r["count"])))
                if r["ms"]:
                    out.append((fp, fam, f"rung.{rung}.ms.p50",
                                _fmt(_percentile(r["ms"], 0.5))))
            for rung in sorted(e["compile"]):
                r = e["compile"][rung]
                out.append((fp, fam, f"compile.{rung}.count",
                            str(r["count"])))
                if r["ms"]:
                    out.append((fp, fam, f"compile.{rung}.ms.p50",
                                _fmt(_percentile(r["ms"], 0.5))))
                    out.append((fp, fam, f"compile.{rung}.ms.max",
                                _fmt(max(r["ms"]))))
        return out

    def predicted_exec_ms(self, fingerprint: str) -> Optional[float]:
        """The rolling p50 of observed exec wall times for one fingerprint
        — the packing scheduler's predicted exec_ms (drain hints, deadline
        ordering) and the ladder's interpreted-cost prior.  None when the
        fingerprint has no exec history (an unknown query earns no made-up
        prediction)."""
        with self._lock:
            e = self._entries.get(fingerprint)
            samples = list(e["exec_ms"]) if e is not None else []
        if not samples:
            return None
        return _percentile(samples, 0.5)

    def top_fingerprints(self, n: int = 10) -> List[str]:
        """Hottest fingerprints by hit count — the pre-warm ordering."""
        with self._lock:
            ranked = sorted(self._entries.items(),
                            key=lambda kv: kv[1]["hits"], reverse=True)
        return [fp for fp, _ in ranked[:max(0, int(n))]]

    def warm_candidates(self, n: int = 10) -> List[Tuple[str, str]]:
        """(fingerprint, sql) for the hottest REPLAYABLE fingerprints — the
        pre-warm work list (serving/warmup.py).  Entries with no recorded
        SQL or a truncation-lossy one are excluded: replaying a prefix
        would warm (or fail) the wrong statement.  Deduped by family —
        one compiled executable serves every literal variant, so pre-warm
        replays ONE representative statement per family.  With the
        engine's current recording (entries KEYED by family fingerprint)
        the collapse is structural and this dedupe is a no-op guard; it
        exists to keep the store's contract honest for callers that key
        by literal fingerprint and pass `family` as the rollup field
        (the record_* API explicitly allows that split)."""
        with self._lock:
            ranked = sorted(self._entries.items(),
                            key=lambda kv: kv[1]["hits"], reverse=True)
            out: List[Tuple[str, str]] = []
            seen_families: set = set()
            for fp, e in ranked:
                if len(out) >= max(0, int(n)):
                    break
                if not e["sql"] or e.get("sql_truncated"):
                    continue
                family = e.get("family") or fp
                if family in seen_families:
                    continue
                seen_families.add(family)
                out.append((fp, e["sql"]))
            return out

    def get(self, fingerprint: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            e = self._entries.get(fingerprint)
            return None if e is None else _copy_entry(e)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    # ------------------------------------------------------- persistence
    #: the pre-version-2 truncation cap: entries in legacy snapshots whose
    #: SQL reaches it may be silent prefixes of the real statement
    _LEGACY_SQL_KEEP = 200

    def snapshot(self) -> Dict[str, Any]:
        """JSON-ready snapshot (checkpoint.py writes this as
        profiles.json next to the catalog snapshot).  Version 2 carries
        the per-entry ``sql_truncated`` flag the warm-up relies on."""
        with self._lock:
            return {
                "version": 2,
                "window": self.window,
                "profiles": {fp: _copy_entry(e)
                             for fp, e in self._entries.items()},
            }

    def load(self, data: Dict[str, Any]) -> int:
        """Replace the store's contents with a `snapshot()` payload;
        returns the number of profiles restored.  Unknown versions load
        best-effort (the schema is additive)."""
        profiles = (data or {}).get("profiles") or {}
        # a version-1 snapshot predates the flag AND used a 200-char cap:
        # an entry whose SQL reaches that cap may be a silent prefix of the
        # real statement — mark it truncated so warm-up never replays a
        # prefix that happens to parse as a different (wrong) query
        legacy = int((data or {}).get("version") or 1) < 2
        with self._lock:
            self._entries.clear()
            for fp, e in profiles.items():
                sql = str(e.get("sql", ""))[:_SQL_KEEP]
                self._entries[fp] = {
                    "sql": sql,
                    "sql_truncated": bool(e.get(
                        "sql_truncated",
                        legacy and len(sql) >= self._LEGACY_SQL_KEEP)),
                    # pre-family snapshots carry no family: "" (unknown),
                    # so warm-up dedupes them by fingerprint as before
                    "family": str(e.get("family", "") or ""),
                    "hits": int(e.get("hits", 0)),
                    "cache_hits": int(e.get("cache_hits", 0)),
                    "exec_ms": [float(v) for v in
                                e.get("exec_ms", [])][-self.window:],
                    "result_bytes": [int(v) for v in
                                     e.get("result_bytes", [])][-self.window:],
                    # additive since version 2: pre-scheduler snapshots
                    # simply restore with no observed-rows / rung history
                    "rows": [int(v) for v in
                             e.get("rows", [])][-self.window:],
                    "est_rows_hi": (None if e.get("est_rows_hi") is None
                                    else int(e["est_rows_hi"])),
                    "compile": {
                        rung: {"count": int(r.get("count", 0)),
                               "ms": [float(v) for v in
                                      r.get("ms", [])][-self.window:]}
                        for rung, r in (e.get("compile") or {}).items()
                    },
                    "rungs": {
                        rung: {"count": int(r.get("count", 0)),
                               "ms": [float(v) for v in
                                      r.get("ms", [])][-self.window:]}
                        for rung, r in (e.get("rungs") or {}).items()
                    },
                    "last_seen": float(e.get("last_seen", 0.0)),
                }
                if len(self._entries) >= self.keep:
                    break
            return len(self._entries)


def _copy_entry(e: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(e)
    out["exec_ms"] = list(e["exec_ms"])
    out["result_bytes"] = list(e["result_bytes"])
    out["rows"] = list(e.get("rows", []))
    out["compile"] = {rung: {"count": r["count"], "ms": list(r["ms"])}
                      for rung, r in e["compile"].items()}
    out["rungs"] = {rung: {"count": r["count"], "ms": list(r["ms"])}
                    for rung, r in e.get("rungs", {}).items()}
    return out
