"""Randomized differential testing vs sqlite.

Parity-plus: the reference's eq_sqlite suite uses a fixed query list
(test_compatibility.py); this generates seeded random query trees
(projections, arithmetic, CASE, filters, group-bys, joins, order/limit) over
random frames and cross-checks every result against sqlite.
"""
import sqlite3

import numpy as np
import pandas as pd
import pytest

from tests.utils import assert_eq

NUM_COLS = ["a", "b", "d"]


def _frames(seed):
    rng = np.random.RandomState(seed)
    n = rng.randint(30, 120)
    t = pd.DataFrame({
        "a": rng.randint(0, 8, n),
        "b": np.round(rng.rand(n) * 50, 2),
        "c": rng.choice(["red", "green", "blue", "teal"], n),
        "d": rng.randint(-10, 10, n),
    })
    m = rng.randint(10, 40)
    u = pd.DataFrame({
        "a": rng.randint(0, 8, m),
        "e": np.round(rng.rand(m) * 9, 3),
    })
    return t, u


class QueryGen:
    def __init__(self, seed):
        self.rng = np.random.RandomState(seed + 1000)

    def scalar(self, depth=0, prefix=""):
        r = self.rng.rand()
        if depth > 2 or r < 0.35:
            return prefix + self.rng.choice(NUM_COLS)
        if r < 0.5:
            return f"{self.rng.randint(-5, 20)}"
        if r < 0.7:
            op = self.rng.choice(["+", "-", "*"])
            return f"({self.scalar(depth + 1, prefix)} {op} {self.scalar(depth + 1, prefix)})"
        if r < 0.8:
            return f"ABS({self.scalar(depth + 1, prefix)})"
        if r < 0.9:
            return (f"CASE WHEN {self.predicate(depth + 1, prefix)} THEN {self.scalar(depth + 1, prefix)} "
                    f"ELSE {self.scalar(depth + 1, prefix)} END")
        return f"COALESCE({self.scalar(depth + 1, prefix)}, 0)"

    def predicate(self, depth=0, prefix=""):
        r = self.rng.rand()
        if depth > 2 or r < 0.5:
            op = self.rng.choice(["<", "<=", ">", ">=", "=", "<>"])
            if self.rng.rand() < 0.3:
                return (f"{prefix}c {self.rng.choice(['=', '<>'])} "
                        f"'{self.rng.choice(['red', 'green', 'blue'])}'")
            return f"{self.scalar(depth + 1, prefix)} {op} {self.scalar(depth + 1, prefix)}"
        if r < 0.65:
            return f"({self.predicate(depth + 1, prefix)} AND {self.predicate(depth + 1, prefix)})"
        if r < 0.8:
            return f"({self.predicate(depth + 1, prefix)} OR {self.predicate(depth + 1, prefix)})"
        if r < 0.9:
            vals = ", ".join(str(v) for v in self.rng.randint(0, 8, 3))
            return f"{prefix}a IN ({vals})"
        return f"{prefix}d BETWEEN {self.rng.randint(-8, 0)} AND {self.rng.randint(0, 8)}"

    def query(self):
        kind = self.rng.rand()
        if kind < 0.35:  # plain select
            exprs = ", ".join(f"{self.scalar()} AS x{i}" for i in range(self.rng.randint(1, 4)))
            q = f"SELECT {exprs} FROM t"
            if self.rng.rand() < 0.8:
                q += f" WHERE {self.predicate()}"
            return q
        if kind < 0.7:  # group by
            aggf = self.rng.choice(["SUM", "MIN", "MAX", "COUNT", "AVG"])
            key = self.rng.choice(["a", "c"])
            q = (f"SELECT {key}, {aggf}({self.scalar()}) AS agg1, COUNT(*) AS n "
                 f"FROM t")
            if self.rng.rand() < 0.6:
                q += f" WHERE {self.predicate()}"
            q += f" GROUP BY {key}"
            if self.rng.rand() < 0.3:
                q += " HAVING COUNT(*) > 1"
            return q
        if kind < 0.9:  # join
            q = (f"SELECT t.c, SUM(u.e) AS s FROM t JOIN u ON t.a = u.a ")
            if self.rng.rand() < 0.5:
                q += f"WHERE {self.predicate(prefix='t.')} "
            q += "GROUP BY t.c"
            return q
        # order/limit
        return (f"SELECT a, b, d FROM t WHERE {self.predicate()} "
                f"ORDER BY b DESC, a, d LIMIT {self.rng.randint(1, 20)}")


@pytest.mark.parametrize("seed", range(40))
def test_fuzz_vs_sqlite(seed):
    from dask_sql_tpu import Context

    t, u = _frames(seed)
    gen = QueryGen(seed)
    query = gen.query()

    conn = sqlite3.connect(":memory:")
    t.to_sql("t", conn, index=False)
    u.to_sql("u", conn, index=False)
    expected = pd.read_sql_query(query, conn)

    c = Context()
    c.create_table("t", t)
    c.create_table("u", u)
    got = c.sql(query, return_futures=False)

    if "ORDER BY" not in query:
        expected = expected.sort_values(list(expected.columns)).reset_index(drop=True)
        got = got.sort_values(list(got.columns)).reset_index(drop=True)
    try:
        assert_eq(got, expected, check_dtype=False, check_names=False)
    except AssertionError as e:  # pragma: no cover - debugging aid
        raise AssertionError(f"seed={seed} query={query!r}\n{e}") from e


class QueryGen2(QueryGen):
    """Harder shapes: windows, set ops, subqueries, derived tables."""

    def query(self):
        kind = self.rng.rand()
        if kind < 0.25:
            wf = self.rng.choice(["ROW_NUMBER()", "RANK()", "SUM(b)", "COUNT(*)",
                                  "AVG(b)", "LAG(b)", "MIN(d)"])
            return (f"SELECT a, b, {wf} OVER (PARTITION BY a ORDER BY b, d, c) AS w "
                    f"FROM t ORDER BY a, b, d, c")
        if kind < 0.45:
            op = self.rng.choice(["UNION", "UNION ALL", "INTERSECT", "EXCEPT"])
            return f"SELECT a FROM t WHERE {self.predicate()} {op} SELECT a FROM u"
        if kind < 0.65:
            style = self.rng.rand()
            if style < 0.4:
                return "SELECT a, d FROM t WHERE a IN (SELECT a FROM u WHERE e > 3)"
            if style < 0.7:
                return ("SELECT a, d FROM t WHERE EXISTS "
                        "(SELECT 1 FROM u WHERE u.a = t.a AND u.e > 2)")
            return "SELECT a, b - (SELECT AVG(e) FROM u) AS r FROM t"
        if kind < 0.85:
            return (f"SELECT s.a, MAX(s.bb) AS m FROM "
                    f"(SELECT a, b + d AS bb FROM t WHERE {self.predicate()}) AS s "
                    f"GROUP BY s.a")
        return f"SELECT DISTINCT a, c FROM t WHERE {self.predicate()} ORDER BY a, c"


@pytest.mark.parametrize("seed", range(300, 325))
def test_fuzz_hard_shapes_vs_sqlite(seed):
    from dask_sql_tpu import Context

    t, u = _frames(seed)
    query = QueryGen2(seed).query()
    conn = sqlite3.connect(":memory:")
    t.to_sql("t", conn, index=False)
    u.to_sql("u", conn, index=False)
    expected = pd.read_sql_query(query, conn)
    c = Context()
    c.create_table("t", t)
    c.create_table("u", u)
    got = c.sql(query, return_futures=False)
    if "ORDER BY" not in query:
        expected = expected.sort_values(list(expected.columns)).reset_index(drop=True)
        got = got.sort_values(list(got.columns)).reset_index(drop=True)
    try:
        assert_eq(got, expected, check_dtype=False, check_names=False)
    except AssertionError as e:  # pragma: no cover - debugging aid
        raise AssertionError(f"seed={seed} query={query!r}\n{e}") from e


def _null_frames(seed):
    """The standard fuzz frames with ~25% NULLs injected per column."""
    t, u = _frames(seed)
    rng = np.random.RandomState(seed + 77)

    def inject(df):
        out = {}
        for col in df.columns:
            vals = df[col].to_numpy().astype(object)
            vals[rng.rand(len(vals)) < 0.25] = None
            if pd.api.types.is_numeric_dtype(df[col]):
                out[col] = pd.array(vals, dtype="float64")
            else:
                out[col] = vals
        return pd.DataFrame(out)

    return inject(t), inject(u)


def _explicit_null_order(query: str) -> str:
    """Align NULL ordering between the engines (sqlite defaults nulls-first
    ASC; we follow Calcite/Postgres nulls-last ASC) by spelling it out."""
    return (query
            .replace("OVER (PARTITION BY a ORDER BY b, d, c)",
                     "OVER (PARTITION BY a ORDER BY b NULLS FIRST, d NULLS FIRST, c NULLS FIRST)")
            .replace("ORDER BY b DESC, a, d",
                     "ORDER BY b DESC NULLS LAST, a NULLS FIRST, d NULLS FIRST")
            .replace("ORDER BY a, b, d, c",
                     "ORDER BY a NULLS FIRST, b NULLS FIRST, d NULLS FIRST, c NULLS FIRST")
            .replace("ORDER BY a, c",
                     "ORDER BY a NULLS FIRST, c NULLS FIRST"))


@pytest.mark.parametrize("seed", range(500, 530))
def test_fuzz_nulls_vs_sqlite(seed):
    from dask_sql_tpu import Context

    t, u = _null_frames(seed)
    gen = (QueryGen2 if seed % 2 else QueryGen)(seed)
    query = _explicit_null_order(gen.query())
    # the rewrite is text-coupled to the generators: fail loudly if it no-ops
    assert "ORDER BY" not in query or "NULLS" in query, query
    conn = sqlite3.connect(":memory:")
    t.to_sql("t", conn, index=False)
    u.to_sql("u", conn, index=False)
    expected = pd.read_sql_query(query, conn)
    c = Context()
    c.create_table("t", t)
    c.create_table("u", u)
    got = c.sql(query, return_futures=False)
    if "ORDER BY" not in query:
        expected = expected.sort_values(list(expected.columns)).reset_index(drop=True)
        got = got.sort_values(list(got.columns)).reset_index(drop=True)
    try:
        assert_eq(got, expected, check_dtype=False, check_names=False)
    except AssertionError as e:  # pragma: no cover - debugging aid
        raise AssertionError(f"seed={seed} query={query!r}\n{e}") from e


# ---------------------------------------------------------------------------
# dual-oracle mode: the same seeded corpus cross-checked against duckdb when
# it is installed (VERDICT r4 #7 — fills the reference's postgres-in-docker
# role, tests/integration/test_postgres.py:13-53 there).  Skip-if-absent so
# the contract is pinned even on images without the wheel — the skip must
# scope to these tests only, not the module (the sqlite corpus always runs).
@pytest.mark.parametrize("seed", range(20))
def test_fuzz_vs_duckdb(seed):
    pytest.importorskip("duckdb", reason="duckdb oracle not installed")
    from dask_sql_tpu import Context
    from tests.ds_oracle import duckdb_query, make_duckdb

    t, u = _frames(seed)
    query = QueryGen(seed).query()

    conn = make_duckdb({"t": t, "u": u})
    expected = duckdb_query(conn, query)

    c = Context()
    c.create_table("t", t)
    c.create_table("u", u)
    got = c.sql(query, return_futures=False)

    if "ORDER BY" not in query:
        expected = expected.sort_values(list(expected.columns)).reset_index(drop=True)
        got = got.sort_values(list(got.columns)).reset_index(drop=True)
    try:
        assert_eq(got, expected, check_dtype=False, check_names=False)
    except AssertionError as e:  # pragma: no cover - debugging aid
        raise AssertionError(f"seed={seed} query={query!r}\n{e}") from e
