"""TPC-DS q1-q99 runner with an explicit xfail list.

Parity: the reference's coverage yardstick (reference
tests/unit/test_queries.py:5-44 — 99 TPC-DS-style queries with a 38-query
XFAIL list; 61 expected passes on CPU).  Here 99 standard TPC-DS queries run
against generated in-memory tables; the xfail list below is the honest
record of what the engine cannot do yet, grouped by root cause.
"""
import pytest

from tests.tpcds import generate
from tests.tpcds_queries import QUERIES

# Root causes (round 2 state):
#   grouping   — GROUPING() function not implemented
#   cte-reuse  — IndexError when a CTE/view is self-joined 3+ times
#   having     — HAVING/qualify references a select alias of an aggregate
#   decorrelate— correlated subquery shape not decorrelated
#   misc       — see message in the probe log
XFAIL_QUERIES = {
    4: "cte-reuse", 8: "misc: empty intermediate", 10: "decorrelate",
    11: "cte-reuse", 17: "cte-reuse", 25: "cte-reuse",
    27: "grouping", 29: "cte-reuse", 31: "cte-reuse",
    33: "having", 35: "decorrelate", 36: "grouping", 41: "decorrelate",
    47: "cte-reuse", 56: "having", 57: "cte-reuse",
    58: "misc: ambiguous column via CTE triple join", 60: "having",
    70: "grouping", 71: "having",
    72: "cte-reuse", 74: "cte-reuse", 77: "misc: empty channel gather",
    83: "cte-reuse", 84: "misc: non-integer gather index", 85: "misc",
    86: "grouping",
}
# too slow at any scale without the compiled join pipeline — skipped, not xfail
SLOW_QUERIES = {23: "4 CTE scans x self-joins", 24: "ssales CTE x2",
                64: "18-table join at test scale"}


@pytest.fixture(scope="module")
def tpcds_context():
    from dask_sql_tpu import Context

    c = Context()
    for name, df in generate(scale_rows=1000).items():
        c.create_table(name, df)
    return c


def _params():
    for qnum in sorted(QUERIES):
        marks = []
        if qnum in SLOW_QUERIES:
            marks.append(pytest.mark.skip(reason=f"q{qnum}: {SLOW_QUERIES[qnum]}"))
        elif qnum in XFAIL_QUERIES:
            # declarative xfail: the query still RUNS, so a query that starts
            # passing surfaces as XPASS instead of silently going stale
            marks.append(pytest.mark.xfail(
                reason=f"q{qnum}: {XFAIL_QUERIES[qnum]}", strict=False))
        yield pytest.param(qnum, marks=marks)


@pytest.mark.parametrize("qnum", _params())
def test_query(tpcds_context, qnum):
    result = tpcds_context.sql(QUERIES[qnum]).compute()
    assert result is not None
    assert len(result.columns) > 0
