"""SPMD query execution: device-sharded storage + sharded compiled rungs.

The subsystem that turns the mesh from a proven-but-idle capability
(`parallel/`'s 8-device suite) into the serving path's first-class compiled
tier (ROADMAP item 1, docs/spmd.md):

- `storage` — ``parallel.auto_shard``: row-shard eligible registrations
  over the default mesh at create_table/load time, preserving DICT/FOR
  encodings;
- `select` / `aggregate` / `join` — the ``spmd_select`` /
  ``spmd_aggregate`` / ``spmd_join_aggregate`` degradation-ladder rungs:
  shard_map SPMD programs sharing the single-chip compiled pipelines'
  traced bodies, with psum/pmin/pmax tree-reduced aggregation states and
  broadcast build sides;
- `core` — the shard_map wrapping shared by the rungs.

Each rung sits ABOVE its single-chip counterpart in the ladder and is
breaker-isolated per (family, rung): a flaky SPMD path degrades to the
single-chip compiled rung without poisoning the family.
"""
from .aggregate import SpmdAggregate, try_spmd_aggregate
from .core import mesh_of_sharded_table, rung_enabled, spmd_enabled
from .join import SpmdJoinAggregate, try_spmd_join_aggregate
from .select import SpmdSelect, try_spmd_select
from .storage import auto_shard_enabled, maybe_auto_shard, truthy_option

__all__ = [
    "SpmdAggregate",
    "SpmdJoinAggregate",
    "SpmdSelect",
    "auto_shard_enabled",
    "maybe_auto_shard",
    "mesh_of_sharded_table",
    "rung_enabled",
    "spmd_enabled",
    "truthy_option",
    "try_spmd_aggregate",
    "try_spmd_join_aggregate",
    "try_spmd_select",
]
