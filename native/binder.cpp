// Native binder: AST -> typed LogicalPlan, in C++.
//
// Role parity: DataFusion's SqlToRel as driven by the reference
// (src/sql.rs:586-674 logical_relational_algebra / statement_to_plan) —
// the reference's entire bind stage is compiled code; this file migrates
// dask_sql_tpu/planner/binder.py (same semantics, differentially tested
// for bound-plan equality over the TPC-H/TPC-DS corpora by
// tests/unit/test_native_binder.py).
//
// Layering: dsql_bind() calls the native parser (dsql_parse, parser.cpp)
// for the flat AST buffer, decodes the catalog buffer the Python side
// serializes (schemas/tables/columns/UDF signatures), binds, and emits a
// flat *plan* buffer that planner/native_bridge.py decodes into the same
// plan.py/expressions.py dataclasses the Python binder produces.
//
// Plan-buffer ABI (binder version 1, little-endian): identical framing to
// the parser buffer (header int32[7] {magic 'DSQB', n_nodes, n_children,
// n_strings, str_bytes, root, 0}; 40B nodes {kind, flags, ival, dval, s0,
// s1, child_off, nchild}; children; string table).  Node kinds are the
// P_* / E_* enums below; see native_bridge._decode_plan for the decoder.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <functional>
#include <map>
#include <memory>
#include <optional>
#include <set>
#include <string>
#include <vector>

extern "C" int32_t dsql_parse(const char* sql, int64_t n, uint8_t** out,
                              int64_t* out_len);
extern "C" void dsql_buf_free(uint8_t* p);

namespace {

constexpr int32_t PLAN_MAGIC = 0x44535142;  // "DSQB"
constexpr int32_t AST_MAGIC = 0x44535131;   // "DSQ1" (parser buffer)

// ---------------------------------------------------------------------------
// SQL types: ids = declaration order of columnar/dtypes.py SqlType
// ---------------------------------------------------------------------------
enum Ty : int32_t {
  TY_NULL = 0, TY_BOOLEAN, TY_TINYINT, TY_SMALLINT, TY_INTEGER, TY_BIGINT,
  TY_FLOAT, TY_REAL, TY_DOUBLE, TY_DECIMAL, TY_VARCHAR, TY_CHAR, TY_DATE,
  TY_TIME, TY_TIMESTAMP, TY_TIMESTAMP_TZ, TY_INTERVAL_DAY_TIME,
  TY_INTERVAL_YEAR_MONTH, TY_BINARY, TY_VARBINARY, TY_ANY,
};

const char* TY_NAMES[] = {
    "NULL", "BOOLEAN", "TINYINT", "SMALLINT", "INTEGER", "BIGINT", "FLOAT",
    "REAL", "DOUBLE", "DECIMAL", "VARCHAR", "CHAR", "DATE", "TIME",
    "TIMESTAMP", "TIMESTAMP_WITH_LOCAL_TIME_ZONE", "INTERVAL_DAY_TIME",
    "INTERVAL_YEAR_MONTH", "BINARY", "VARBINARY", "ANY"};

bool is_string(int t) { return t == TY_VARCHAR || t == TY_CHAR; }
bool is_datetime(int t) {
  return t == TY_DATE || t == TY_TIME || t == TY_TIMESTAMP || t == TY_TIMESTAMP_TZ;
}
bool is_interval(int t) {
  return t == TY_INTERVAL_DAY_TIME || t == TY_INTERVAL_YEAR_MONTH;
}
bool is_integer(int t) {
  return t == TY_TINYINT || t == TY_SMALLINT || t == TY_INTEGER || t == TY_BIGINT;
}
bool is_float(int t) {
  return t == TY_FLOAT || t == TY_REAL || t == TY_DOUBLE || t == TY_DECIMAL;
}
bool is_numeric(int t) { return is_integer(t) || is_float(t); }

struct BindErr {
  std::string msg;
  int klass = 0;  // 0 = BindError, 1 = KeyError (missing table/schema)
};
struct Unsupported {};  // -> rc 1, Python binder fallback

[[noreturn]] void bind_error(const std::string& msg) { throw BindErr{msg, 0}; }
[[noreturn]] void key_error(const std::string& msg) { throw BindErr{msg, 1}; }

// promotion lattice (dtypes.promote parity)
int promo_rank(int t) {
  switch (t) {
    case TY_BOOLEAN: return 0;
    case TY_TINYINT: return 1;
    case TY_SMALLINT: return 2;
    case TY_INTEGER: return 3;
    case TY_BIGINT: return 4;
    case TY_FLOAT: return 5;
    case TY_REAL: return 6;
    case TY_DOUBLE: return 7;
    case TY_DECIMAL: return 8;
    default: return -1;
  }
}

int promote(int a, int b) {
  if (a == b) return a;
  if (a == TY_NULL) return b;
  if (b == TY_NULL) return a;
  if (is_string(a) && is_string(b)) return TY_VARCHAR;
  if (is_datetime(a) && is_datetime(b)) return TY_TIMESTAMP;
  if (is_datetime(a) && is_interval(b)) return a;
  if (is_datetime(b) && is_interval(a)) return b;
  int ra = promo_rank(a), rb = promo_rank(b);
  if (ra >= 0 && rb >= 0) {
    int hi = ra >= rb ? a : b;
    int lo = ra >= rb ? b : a;
    if ((hi == TY_FLOAT || hi == TY_REAL) && (lo == TY_INTEGER || lo == TY_BIGINT))
      return TY_DOUBLE;
    return hi;
  }
  if (is_datetime(a) && is_numeric(b)) return a;
  if (is_datetime(b) && is_numeric(a)) return b;
  bind_error(std::string("Cannot promote ") + TY_NAMES[a] + " and " + TY_NAMES[b]);
}

bool similar_type(int a, int b) {
  if (is_integer(a) && is_integer(b)) return true;
  if (is_float(a) && is_float(b)) return true;
  if (is_string(a) && is_string(b)) return true;
  if (is_datetime(a) && is_datetime(b)) return true;
  if (is_interval(a) && is_interval(b)) return true;
  if (a == TY_BOOLEAN && b == TY_BOOLEAN) return true;
  return a == b;
}

std::string upper(const std::string& s) {
  std::string u = s;
  for (auto& c : u)
    if (c >= 'a' && c <= 'z') c -= 32;
  return u;
}
std::string lower(const std::string& s) {
  std::string u = s;
  for (auto& c : u)
    if (c >= 'A' && c <= 'Z') c += 32;
  return u;
}

int parse_sql_type(const std::string& raw) {
  // dtypes.parse_sql_type parity (CAST target names + aliases)
  std::string name = upper(raw);
  // strip leading/trailing space
  while (!name.empty() && name.front() == ' ') name.erase(name.begin());
  while (!name.empty() && name.back() == ' ') name.pop_back();
  std::string base = name.substr(0, name.find('('));
  while (!base.empty() && base.back() == ' ') base.pop_back();
  static const std::map<std::string, int> aliases = {
      {"INT", TY_INTEGER}, {"INT2", TY_SMALLINT}, {"INT4", TY_INTEGER},
      {"INT8", TY_BIGINT}, {"LONG", TY_BIGINT}, {"STRING", TY_VARCHAR},
      {"TEXT", TY_VARCHAR}, {"BOOL", TY_BOOLEAN}, {"NUMERIC", TY_DECIMAL},
      {"FLOAT4", TY_FLOAT}, {"FLOAT8", TY_DOUBLE},
      {"DOUBLE PRECISION", TY_DOUBLE},
      {"TIMESTAMP WITHOUT TIME ZONE", TY_TIMESTAMP},
      {"TIMESTAMP WITH TIME ZONE", TY_TIMESTAMP_TZ},
      {"DATETIME", TY_TIMESTAMP}};
  auto it = aliases.find(base);
  if (it != aliases.end()) return it->second;
  std::string key = base;
  for (auto& c : key)
    if (c == ' ') c = '_';
  for (int i = 0; i <= TY_ANY; ++i)
    if (key == TY_NAMES[i]) return i;
  throw Unsupported{};  // Python raises NotImplementedError -> fallback
}

// ---------------------------------------------------------------------------
// built-in function tables (planner/functions.py parity)
// ---------------------------------------------------------------------------
// result rules: 0 double, 1 bigint, 2 integer, 3 boolean, 4 string,
// 5 timestamp, 6 interval, 7 arg0, 8 promote, 9 sum
enum Rule { R_DOUBLE, R_BIGINT, R_INT, R_BOOL, R_STRING, R_TS, R_IV, R_ARG0, R_PROMOTE, R_SUM };

struct ScalarSig {
  const char* op;
  int rule;
  int lo;
  int hi;
};

const std::map<std::string, ScalarSig>& scalar_functions() {
  static const std::map<std::string, ScalarSig> m = {
      {"ABS", {"abs", R_ARG0, 1, 1}}, {"ACOS", {"acos", R_DOUBLE, 1, 1}},
      {"ASIN", {"asin", R_DOUBLE, 1, 1}}, {"ATAN", {"atan", R_DOUBLE, 1, 1}},
      {"ATAN2", {"atan2", R_DOUBLE, 2, 2}}, {"CBRT", {"cbrt", R_DOUBLE, 1, 1}},
      {"CEIL", {"ceil", R_ARG0, 1, 1}}, {"CEILING", {"ceil", R_ARG0, 1, 1}},
      {"COS", {"cos", R_DOUBLE, 1, 1}}, {"COT", {"cot", R_DOUBLE, 1, 1}},
      {"DEGREES", {"degrees", R_DOUBLE, 1, 1}}, {"EXP", {"exp", R_DOUBLE, 1, 1}},
      {"FLOOR", {"floor", R_ARG0, 1, 1}}, {"LN", {"ln", R_DOUBLE, 1, 1}},
      {"LOG", {"log", R_DOUBLE, 1, 2}}, {"LOG10", {"log10", R_DOUBLE, 1, 1}},
      {"LOG2", {"log2", R_DOUBLE, 1, 1}}, {"POWER", {"power", R_DOUBLE, 2, 2}},
      {"POW", {"power", R_DOUBLE, 2, 2}}, {"RADIANS", {"radians", R_DOUBLE, 1, 1}},
      {"ROUND", {"round", R_ARG0, 1, 2}}, {"SIGN", {"sign", R_ARG0, 1, 1}},
      {"SIN", {"sin", R_DOUBLE, 1, 1}}, {"SQRT", {"sqrt", R_DOUBLE, 1, 1}},
      {"TAN", {"tan", R_DOUBLE, 1, 1}}, {"TRUNCATE", {"truncate", R_ARG0, 1, 2}},
      {"TRUNC", {"truncate", R_ARG0, 1, 2}}, {"MOD", {"mod", R_PROMOTE, 2, 2}},
      {"RAND", {"rand", R_DOUBLE, 0, 1}}, {"RANDOM", {"rand", R_DOUBLE, 0, 1}},
      {"RAND_INTEGER", {"rand_integer", R_INT, 1, 2}}, {"PI", {"pi", R_DOUBLE, 0, 0}},
      {"CHAR_LENGTH", {"char_length", R_BIGINT, 1, 1}},
      {"CHARACTER_LENGTH", {"char_length", R_BIGINT, 1, 1}},
      {"LENGTH", {"char_length", R_BIGINT, 1, 1}},
      {"UPPER", {"upper", R_STRING, 1, 1}}, {"LOWER", {"lower", R_STRING, 1, 1}},
      {"CONCAT", {"concat", R_STRING, 1, 99}},
      {"INITCAP", {"initcap", R_STRING, 1, 1}},
      {"REPLACE", {"replace", R_STRING, 3, 3}},
      {"REVERSE", {"reverse", R_STRING, 1, 1}},
      {"LEFT", {"left", R_STRING, 2, 2}}, {"RIGHT", {"right", R_STRING, 2, 2}},
      {"REPEAT", {"repeat_str", R_STRING, 2, 2}},
      {"LPAD", {"lpad", R_STRING, 2, 3}}, {"RPAD", {"rpad", R_STRING, 2, 3}},
      {"ASCII", {"ascii", R_INT, 1, 1}}, {"CHR", {"chr", R_STRING, 1, 1}},
      {"STRPOS", {"position", R_INT, 2, 2}},
      {"SPLIT_PART", {"split_part", R_STRING, 3, 3}},
      {"SUBSTR", {"substring", R_STRING, 2, 3}},
      {"SUBSTRING", {"substring", R_STRING, 2, 3}},
      {"BTRIM", {"btrim", R_STRING, 1, 2}}, {"LTRIM", {"ltrim", R_STRING, 1, 2}},
      {"RTRIM", {"rtrim", R_STRING, 1, 2}}, {"TRIM", {"btrim", R_STRING, 1, 2}},
      {"COALESCE", {"coalesce", R_PROMOTE, 1, 99}},
      {"NULLIF", {"nullif", R_ARG0, 2, 2}},
      {"NVL", {"coalesce", R_PROMOTE, 2, 2}},
      {"IFNULL", {"coalesce", R_PROMOTE, 2, 2}},
      {"GREATEST", {"greatest", R_PROMOTE, 1, 99}},
      {"LEAST", {"least", R_PROMOTE, 1, 99}},
      {"YEAR", {"extract_year", R_BIGINT, 1, 1}},
      {"MONTH", {"extract_month", R_BIGINT, 1, 1}},
      {"DAY", {"extract_day", R_BIGINT, 1, 1}},
      {"HOUR", {"extract_hour", R_BIGINT, 1, 1}},
      {"MINUTE", {"extract_minute", R_BIGINT, 1, 1}},
      {"SECOND", {"extract_second", R_BIGINT, 1, 1}},
      {"QUARTER", {"extract_quarter", R_BIGINT, 1, 1}},
      {"DAYOFWEEK", {"extract_dow", R_BIGINT, 1, 1}},
      {"DAYOFYEAR", {"extract_doy", R_BIGINT, 1, 1}},
      {"WEEK", {"extract_week", R_BIGINT, 1, 1}},
      {"LAST_DAY", {"last_day", R_TS, 1, 1}},
      {"TO_TIMESTAMP", {"to_timestamp", R_TS, 1, 2}},
      {"DSQL_TOTIMESTAMP", {"to_timestamp", R_TS, 1, 2}},
      {"TIMESTAMPADD", {"timestampadd", R_TS, 3, 3}},
      {"TIMESTAMPDIFF", {"timestampdiff", R_BIGINT, 3, 3}},
      {"DATEDIFF", {"timestampdiff", R_BIGINT, 3, 3}},
      {"DATE_TRUNC", {"date_trunc", R_TS, 2, 2}},
      {"CURRENT_TIMESTAMP", {"current_timestamp", R_TS, 0, 0}},
      {"CURRENT_DATE", {"current_date", R_TS, 0, 0}},
      {"NOW", {"current_timestamp", R_TS, 0, 0}},
      {"MD5", {"md5", R_STRING, 1, 1}},
      {"HASH", {"hash64", R_BIGINT, 1, 99}},
  };
  return m;
}

struct AggSig {
  const char* op;
  int rule;
};

const std::map<std::string, AggSig>& aggregate_functions() {
  static const std::map<std::string, AggSig> m = {
      {"SUM", {"sum", R_SUM}}, {"MIN", {"min", R_ARG0}}, {"MAX", {"max", R_ARG0}},
      {"COUNT", {"count", R_BIGINT}}, {"AVG", {"avg", R_DOUBLE}},
      {"MEAN", {"avg", R_DOUBLE}}, {"STDDEV", {"stddev_samp", R_DOUBLE}},
      {"STDDEV_SAMP", {"stddev_samp", R_DOUBLE}},
      {"STDDEV_POP", {"stddev_pop", R_DOUBLE}},
      {"VARIANCE", {"var_samp", R_DOUBLE}}, {"VAR_SAMP", {"var_samp", R_DOUBLE}},
      {"VAR_POP", {"var_pop", R_DOUBLE}}, {"BIT_AND", {"bit_and", R_ARG0}},
      {"BIT_OR", {"bit_or", R_ARG0}}, {"BIT_XOR", {"bit_xor", R_ARG0}},
      {"EVERY", {"every", R_BOOL}}, {"BOOL_AND", {"every", R_BOOL}},
      {"BOOL_OR", {"bool_or", R_BOOL}}, {"ANY_VALUE", {"single_value", R_ARG0}},
      {"SINGLE_VALUE", {"single_value", R_ARG0}},
      {"FIRST_VALUE", {"first_value", R_ARG0}},
      {"LAST_VALUE", {"last_value", R_ARG0}},
      {"REGR_COUNT", {"regr_count", R_BIGINT}},
      {"REGR_SXX", {"regr_sxx", R_DOUBLE}}, {"REGR_SYY", {"regr_syy", R_DOUBLE}},
      {"APPROX_COUNT_DISTINCT", {"approx_count_distinct", R_BIGINT}},
      {"MEDIAN", {"percentile", R_DOUBLE}},
      {"APPROX_PERCENTILE", {"percentile", R_DOUBLE}},
      {"PERCENTILE_CONT", {"percentile", R_DOUBLE}},
      {"QUANTILE", {"percentile", R_DOUBLE}},
  };
  return m;
}

const std::map<std::string, int>& window_functions() {
  static const std::map<std::string, int> m = {
      {"ROW_NUMBER", R_BIGINT}, {"RANK", R_BIGINT}, {"DENSE_RANK", R_BIGINT},
      {"PERCENT_RANK", R_DOUBLE}, {"CUME_DIST", R_DOUBLE}, {"NTILE", R_BIGINT},
      {"LAG", R_ARG0}, {"LEAD", R_ARG0}, {"NTH_VALUE", R_ARG0},
  };
  return m;
}

int resolve_type(int rule, const std::vector<int>& arg_types) {
  switch (rule) {
    case R_DOUBLE: return TY_DOUBLE;
    case R_BIGINT: return TY_BIGINT;
    case R_INT: return TY_INTEGER;
    case R_BOOL: return TY_BOOLEAN;
    case R_STRING: return TY_VARCHAR;
    case R_TS: return TY_TIMESTAMP;
    case R_IV: return TY_INTERVAL_DAY_TIME;
    case R_ARG0: return arg_types.empty() ? TY_DOUBLE : arg_types[0];
    case R_PROMOTE: {
      int t = arg_types[0];
      for (size_t i = 1; i < arg_types.size(); ++i) t = promote(t, arg_types[i]);
      return t;
    }
    case R_SUM: {
      int t = arg_types[0];
      if (is_integer(t)) return TY_BIGINT;
      if (is_float(t)) return t == TY_DECIMAL ? TY_DOUBLE : t;
      return t;
    }
  }
  bind_error("bad type rule");
}

// ---------------------------------------------------------------------------
// datetime / interval literal parsing (binder._bind_literal/_bind_interval)
// ---------------------------------------------------------------------------
// days since 1970-01-01 for a civil date (Hinnant's algorithm)
int64_t days_from_civil(int64_t y, int m, int d) {
  y -= m <= 2;
  int64_t era = (y >= 0 ? y : y - 399) / 400;
  int64_t yoe = y - era * 400;
  int64_t doy = (153 * (m + (m > 2 ? -3 : 9)) + 2) / 5 + d - 1;
  int64_t doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
  return era * 146097 + doe - 719468;
}

// "YYYY-MM-DD[ HH:MM[:SS[.frac]]]" -> epoch nanoseconds; throws BindErr
int64_t parse_datetime_ns(const std::string& raw) {
  std::string s = raw;
  while (!s.empty() && s.front() == ' ') s.erase(s.begin());
  while (!s.empty() && s.back() == ' ') s.pop_back();
  const char* p = s.c_str();
  auto read_int = [&](int n_min, int n_max, int64_t* out) -> bool {
    int64_t v = 0;
    int n = 0;
    while (*p >= '0' && *p <= '9' && n < n_max) {
      v = v * 10 + (*p - '0');
      ++p;
      ++n;
    }
    if (n < n_min) return false;
    *out = v;
    return true;
  };
  int64_t y, mo, d;
  bool neg = false;
  if (*p == '-') {
    neg = true;
    ++p;
  }
  if (!read_int(1, 6, &y) || *p != '-') bind_error("Cannot bind literal '" + raw + "'");
  ++p;
  if (neg) y = -y;
  if (!read_int(1, 2, &mo) || *p != '-') bind_error("Cannot bind literal '" + raw + "'");
  ++p;
  if (!read_int(1, 2, &d)) bind_error("Cannot bind literal '" + raw + "'");
  int64_t ns = days_from_civil(y, (int)mo, (int)d) * 86400000000000LL;
  if (*p == ' ' || *p == 'T') {
    ++p;
    int64_t hh, mi, ss = 0;
    if (!read_int(1, 2, &hh) || *p != ':') bind_error("Cannot bind literal '" + raw + "'");
    ++p;
    if (!read_int(1, 2, &mi)) bind_error("Cannot bind literal '" + raw + "'");
    if (*p == ':') {
      ++p;
      if (!read_int(1, 2, &ss)) bind_error("Cannot bind literal '" + raw + "'");
    }
    ns += (hh * 3600 + mi * 60 + ss) * 1000000000LL;
    if (*p == '.') {
      ++p;
      int64_t frac = 0;
      int n = 0;
      while (*p >= '0' && *p <= '9' && n < 9) {
        frac = frac * 10 + (*p - '0');
        ++p;
        ++n;
      }
      while (*p >= '0' && *p <= '9') ++p;  // truncate past ns
      for (; n < 9; ++n) frac *= 10;
      ns += frac;
    }
  }
  if (*p != '\0') bind_error("Cannot bind literal '" + raw + "'");
  return ns;
}

const std::map<std::string, int64_t>& interval_ns_units() {
  static const std::map<std::string, int64_t> m = {
      {"NANOSECOND", 1},
      {"MICROSECOND", 1000},
      {"MILLISECOND", 1000000},
      {"SECOND", 1000000000},
      {"MINUTE", 60LL * 1000000000},
      {"HOUR", 3600LL * 1000000000},
      {"DAY", 86400LL * 1000000000},
      {"WEEK", 7LL * 86400 * 1000000000},
  };
  return m;
}

const std::map<std::string, int64_t>& interval_month_units() {
  static const std::map<std::string, int64_t> m = {
      {"MONTH", 1}, {"QUARTER", 3}, {"YEAR", 12}};
  return m;
}

// ---------------------------------------------------------------------------
// flat AST reader (over the parser's serialized buffer)
// ---------------------------------------------------------------------------
// parser node kinds (keep in sync with parser.cpp)
enum AstKind : int32_t {
  K_STMT_LIST = 0, K_QUERY_STMT = 1, K_EXPLAIN_STMT = 2,
  K_SELECT = 10, K_PROJ_ITEM = 11, K_FROM_CLAUSE = 12, K_WHERE_CLAUSE = 13,
  K_GROUP_ITEM = 14, K_HAVING_CLAUSE = 15, K_ORDER_ITEM = 16,
  K_LIMIT_CLAUSE = 17, K_OFFSET_CLAUSE = 18, K_CTE = 19, K_SETOP = 20,
  K_DISTRIBUTE_ITEM = 21, K_VALUES_ROW = 22, K_NAMED_WINDOW = 23,
  K_NAMED_TABLE = 30, K_DERIVED_TABLE = 31, K_TABLE_FUNC = 32, K_JOIN = 33,
  K_PART = 34, K_ALIAS_COL = 35, K_USING_COL = 36,
  K_IDENT = 40, K_WILDCARD = 41, K_LIT_NULL = 42, K_LIT_INT = 43,
  K_LIT_FLOAT = 44, K_LIT_STR = 45, K_LIT_BOOL = 46, K_LIT_TYPED = 47,
  K_INTERVAL = 48, K_UNARY = 49, K_BINARY = 50, K_CAST = 51, K_CASE = 52,
  K_FUNCALL = 53, K_WINSPEC = 54, K_FRAME = 55, K_BETWEEN = 56,
  K_INLIST = 57, K_INSUBQ = 58, K_EXISTS = 59, K_SCALARSUBQ = 60,
  K_LIKE = 61, K_ISNULL = 62, K_ISBOOL = 63, K_ISDIST = 64, K_EXTRACT = 65,
  K_SUBSTRING = 66, K_TRIM = 67, K_POSITION = 68, K_OVERLAY = 69,
  K_CEILFLOORTO = 70, K_GROUPING_SETS = 71, K_SET_NODE = 72, K_ROLLUP = 73,
  K_CUBE = 74,
  K_QNAME = 79, K_CREATE_TABLE_WITH = 80, K_CREATE_TABLE_AS = 81,
  K_DROP_TABLE = 82, K_CREATE_SCHEMA = 83, K_DROP_SCHEMA = 84,
  K_USE_SCHEMA = 85, K_ALTER_SCHEMA = 86, K_ALTER_TABLE = 87,
  K_SHOW_SCHEMAS = 88, K_SHOW_TABLES = 89, K_SHOW_COLUMNS = 90,
  K_SHOW_MODELS = 91, K_ANALYZE_TABLE = 92, K_CREATE_MODEL = 93,
  K_DROP_MODEL = 94, K_DESCRIBE_MODEL = 95, K_EXPORT_MODEL = 96,
  K_CREATE_EXPERIMENT = 97, K_KWARGS = 98, K_KV = 99, K_KWLIST = 100,
  K_SHOW_METRICS = 101, K_SHOW_PROFILES = 102,
  K_SHOW_QUERIES = 103, K_CANCEL_QUERY = 104,
};

struct AstNode {
  int32_t kind, flags;
  int64_t ival;
  double dval;
  int32_t s0, s1, child_off, nchild;
};

struct Ast {
  std::vector<AstNode> nodes;
  std::vector<int32_t> children;
  std::vector<std::string> strings;
  int32_t root = -1;

  bool load(const uint8_t* buf, int64_t len) {
    if (len < 28) return false;
    int32_t hdr[7];
    std::memcpy(hdr, buf, 28);
    if (hdr[0] != AST_MAGIC) return false;
    int32_t n_nodes = hdr[1], n_children = hdr[2], n_strings = hdr[3],
            str_bytes = hdr[4];
    root = hdr[5];
    const uint8_t* p = buf + 28;
    nodes.resize(n_nodes);
    for (int i = 0; i < n_nodes; ++i) {
      std::memcpy(&nodes[i].kind, p, 4); p += 4;
      std::memcpy(&nodes[i].flags, p, 4); p += 4;
      std::memcpy(&nodes[i].ival, p, 8); p += 8;
      std::memcpy(&nodes[i].dval, p, 8); p += 8;
      std::memcpy(&nodes[i].s0, p, 4); p += 4;
      std::memcpy(&nodes[i].s1, p, 4); p += 4;
      std::memcpy(&nodes[i].child_off, p, 4); p += 4;
      std::memcpy(&nodes[i].nchild, p, 4); p += 4;
    }
    children.resize(n_children);
    std::memcpy(children.data(), p, 4 * n_children);
    p += 4 * n_children;
    std::vector<int32_t> offs(n_strings + 1);
    std::memcpy(offs.data(), p, 4 * (n_strings + 1));
    p += 4 * (n_strings + 1);
    strings.resize(n_strings);
    for (int i = 0; i < n_strings; ++i)
      strings[i].assign(reinterpret_cast<const char*>(p) + offs[i],
                        offs[i + 1] - offs[i]);
    (void)str_bytes;
    return true;
  }

  const AstNode& n(int id) const { return nodes[id]; }
  std::vector<int32_t> kids(int id) const {
    const AstNode& nd = nodes[id];
    return std::vector<int32_t>(children.begin() + nd.child_off,
                                children.begin() + nd.child_off + nd.nchild);
  }
  std::string s(int32_t idx) const { return idx < 0 ? std::string() : strings[idx]; }
  bool has_s(int32_t idx) const { return idx >= 0; }
};

// ---------------------------------------------------------------------------
// catalog (decoded from the Python-serialized buffer)
// ---------------------------------------------------------------------------
struct CField {
  std::string name;
  int type;
  bool nullable;
};

struct CTable {
  std::string schema_name, name;
  double row_count = -1.0;  // -1 = unknown statistics
  std::vector<CField> fields;
};

struct CFnOverload {
  std::string name;  // registered spelling
  std::vector<int> param_types;
  int return_type;
  bool aggregation;
  bool row_udf;
};

struct Catalog {
  bool case_sensitive = true;
  std::string current_schema;
  // schema -> table name -> table
  std::map<std::string, std::map<std::string, CTable>> schemas;
  // schema -> fn name -> overloads
  std::map<std::string, std::map<std::string, std::vector<CFnOverload>>> functions;

  bool load(const uint8_t* buf, int64_t len) {
    const uint8_t* p = buf;
    const uint8_t* end = buf + len;
    auto r32 = [&]() -> int32_t {
      if (p + 4 > end) throw Unsupported{};
      int32_t v;
      std::memcpy(&v, p, 4);
      p += 4;
      return v;
    };
    auto rstr = [&]() -> std::string {
      int32_t n = r32();
      if (p + n > end) throw Unsupported{};
      std::string s(reinterpret_cast<const char*>(p), n);
      p += n;
      return s;
    };
    if (r32() != 0x44535143) return false;  // 'DSQC'
    case_sensitive = r32() != 0;
    current_schema = rstr();
    int32_t n_schemas = r32();
    for (int i = 0; i < n_schemas; ++i) {
      std::string sname = rstr();
      auto& tables = schemas[sname];
      int32_t n_tables = r32();
      for (int j = 0; j < n_tables; ++j) {
        CTable t;
        t.schema_name = sname;
        t.name = rstr();
        if (p + 8 > end) throw Unsupported{};
        std::memcpy(&t.row_count, p, 8);
        p += 8;
        int32_t n_cols = r32();
        for (int k = 0; k < n_cols; ++k) {
          CField f;
          f.name = rstr();
          f.type = r32();
          f.nullable = r32() != 0;
          t.fields.push_back(std::move(f));
        }
        tables.emplace(t.name, std::move(t));
      }
      auto& fns = functions[sname];
      int32_t n_fns = r32();
      for (int j = 0; j < n_fns; ++j) {
        std::string key = rstr();
        int32_t n_ov = r32();
        std::vector<CFnOverload> ovs;
        for (int k = 0; k < n_ov; ++k) {
          CFnOverload ov;
          ov.name = rstr();
          int32_t np = r32();
          for (int q = 0; q < np; ++q) ov.param_types.push_back(r32());
          ov.return_type = r32();
          ov.aggregation = r32() != 0;
          ov.row_udf = r32() != 0;
          ovs.push_back(std::move(ov));
        }
        fns.emplace(key, std::move(ovs));
      }
    }
    return true;
  }

  const CTable* resolve_table(const std::vector<std::string>& parts) const {
    std::string schema_name, table_name;
    if (parts.size() == 1) {
      schema_name = current_schema;
      table_name = parts[0];
    } else {
      schema_name = parts[parts.size() - 2];
      table_name = parts.back();
    }
    auto sit = schemas.find(schema_name);
    if (sit == schemas.end())
      key_error("Schema '" + schema_name + "' not found");
    auto tit = sit->second.find(table_name);
    if (tit == sit->second.end() && !case_sensitive) {
      std::string want = lower(table_name);
      for (auto& kv : sit->second)
        if (lower(kv.first) == want) return &kv.second;
    }
    if (tit == sit->second.end())
      key_error("Table '" + table_name + "' not found in schema '" +
                schema_name + "'");
    return &tit->second;
  }

  const std::vector<CFnOverload>* resolve_function(const std::string& name) const {
    auto sit = functions.find(current_schema);
    if (sit == functions.end()) return nullptr;
    auto fit = sit->second.find(name);
    if (fit == sit->second.end()) {
      // binder tries exact then lowercase spelling
      fit = sit->second.find(lower(name));
    }
    if (fit == sit->second.end() && !case_sensitive) {
      std::string want = lower(name);
      for (auto& kv : sit->second)
        if (lower(kv.first) == want) return &kv.second;
    }
    if (fit == sit->second.end()) return nullptr;
    return &fit->second;
  }
};

// ---------------------------------------------------------------------------
// output (bound-plan) flat buffer
// ---------------------------------------------------------------------------
// plan node kinds
enum PKind : int32_t {
  P_TABLESCAN = 1, P_PROJECTION = 2, P_FILTER = 3, P_JOIN = 4, P_CROSSJOIN = 5,
  P_AGGREGATE = 6, P_WINDOW = 7, P_SORT = 8, P_LIMIT = 9, P_UNION = 10,
  P_INTERSECT = 11, P_EXCEPT = 12, P_DISTINCT = 13, P_VALUES = 14,
  P_EMPTY = 15, P_SUBQUERY_ALIAS = 16, P_SAMPLE = 17, P_DISTRIBUTE_BY = 18,
  P_EXPLAIN = 19,
  P_CREATE_TABLE = 20, P_CREATE_MEMORY_TABLE = 21, P_DROP_TABLE = 22,
  P_CREATE_SCHEMA = 23, P_DROP_SCHEMA = 24, P_USE_SCHEMA = 25,
  P_ALTER_SCHEMA = 26, P_ALTER_TABLE = 27, P_SHOW_SCHEMAS = 28,
  P_SHOW_TABLES = 29, P_SHOW_COLUMNS = 30, P_SHOW_MODELS = 31,
  P_ANALYZE_TABLE = 32, P_CREATE_MODEL = 33, P_DROP_MODEL = 34,
  P_DESCRIBE_MODEL = 35, P_EXPORT_MODEL = 36, P_CREATE_EXPERIMENT = 37,
  P_PREDICT_MODEL = 38, P_SHOW_METRICS = 39, P_SHOW_PROFILES = 40,
  P_SHOW_QUERIES = 41, P_CANCEL_QUERY = 42,
  // aux
  P_FIELD = 50, P_SORTKEY = 51, P_ON_PAIR = 52, P_VALUES_ROW = 53,
  P_PART = 54, P_KWARGS = 55, P_KV = 56, P_KWLIST = 57, P_WINSPEC = 58,
  P_FRAME_BOUND = 59,
  P_KW_STR = 60, P_KW_INT = 61, P_KW_FLOAT = 62, P_KW_BOOL = 63, P_KW_NULL = 64,
  // expressions
  E_COLREF = 70, E_LITERAL = 71, E_SCALARFN = 72, E_AGG = 73, E_WINDOW = 74,
  E_CAST = 75, E_CASE = 76, E_INLIST = 77, E_INSUBQ = 78, E_EXISTS = 79,
  E_SCALARSUBQ = 80, E_UDF = 81, E_OUTERREF = 82, E_GROUPING = 83,
};

// literal tags (E_LITERAL flags low byte)
enum { LT_NULL = 0, LT_BOOL = 1, LT_INT = 2, LT_FLOAT = 3, LT_STR = 4 };

// E_* flag packing: bits 0..7 node-specific, bits 8+ sql_type id
inline int32_t ty_flags(int ty, int32_t low = 0) { return (ty << 8) | low; }
inline int ty_of_flags(int32_t flags) { return flags >> 8; }

struct PNode {
  int32_t kind, flags;
  int64_t ival;
  double dval;
  int32_t s0, s1, child_off, nchild;
};

class PBuilder {
 public:
  std::vector<PNode> nodes;
  std::vector<int32_t> children;
  std::vector<std::string> strings;
  std::map<std::string, int32_t> intern_map;

  int32_t intern(const std::string& s) {
    auto it = intern_map.find(s);
    if (it != intern_map.end()) return it->second;
    int32_t id = static_cast<int32_t>(strings.size());
    strings.push_back(s);
    intern_map.emplace(s, id);
    return id;
  }

  int32_t intern_mut(const std::string& s) const {
    return const_cast<PBuilder*>(this)->intern(s);
  }

  int32_t add(int32_t kind, const std::vector<int32_t>& kids,
              int32_t flags = 0, int64_t ival = 0, double dval = 0.0,
              int32_t s0 = -1, int32_t s1 = -1) const {
    return const_cast<PBuilder*>(this)->add_impl(kind, kids, flags, ival,
                                                 dval, s0, s1);
  }

  int32_t add_impl(int32_t kind, const std::vector<int32_t>& kids,
              int32_t flags = 0, int64_t ival = 0, double dval = 0.0,
              int32_t s0 = -1, int32_t s1 = -1) {
    PNode n;
    n.kind = kind;
    n.flags = flags;
    n.ival = ival;
    n.dval = dval;
    n.s0 = s0;
    n.s1 = s1;
    n.child_off = static_cast<int32_t>(children.size());
    n.nchild = static_cast<int32_t>(kids.size());
    children.insert(children.end(), kids.begin(), kids.end());
    nodes.push_back(n);
    return static_cast<int32_t>(nodes.size() - 1);
  }

  std::vector<int32_t> kids(int32_t id) const {
    const PNode n = nodes[id];
    return std::vector<int32_t>(children.begin() + n.child_off,
                                children.begin() + n.child_off + n.nchild);
  }

  // structural equality of two node trees (string ids are content-unique)
  bool eq(int32_t a, int32_t b) const {
    if (a == b) return true;
    const PNode x = nodes[a];
    const PNode y = nodes[b];
    if (x.kind != y.kind || x.flags != y.flags || x.ival != y.ival ||
        x.dval != y.dval || x.s0 != y.s0 || x.s1 != y.s1 ||
        x.nchild != y.nchild)
      return false;
    for (int i = 0; i < x.nchild; ++i)
      if (!eq(children[x.child_off + i], children[y.child_off + i]))
        return false;
    return true;
  }

  uint8_t* serialize(int32_t root, int64_t* out_len) const {
    size_t str_bytes = 0;
    for (auto& s : strings) str_bytes += s.size();
    size_t total = 7 * 4 + nodes.size() * 40 + children.size() * 4 +
                   (strings.size() + 1) * 4 + str_bytes;
    uint8_t* buf = static_cast<uint8_t*>(std::malloc(total));
    if (!buf) return nullptr;
    uint8_t* p = buf;
    auto w32 = [&p](int32_t v) { std::memcpy(p, &v, 4); p += 4; };
    auto w64 = [&p](int64_t v) { std::memcpy(p, &v, 8); p += 8; };
    auto wf64 = [&p](double v) { std::memcpy(p, &v, 8); p += 8; };
    w32(PLAN_MAGIC);
    w32(static_cast<int32_t>(nodes.size()));
    w32(static_cast<int32_t>(children.size()));
    w32(static_cast<int32_t>(strings.size()));
    w32(static_cast<int32_t>(str_bytes));
    w32(root);
    w32(0);
    for (auto& n : nodes) {
      w32(n.kind); w32(n.flags); w64(n.ival); wf64(n.dval);
      w32(n.s0); w32(n.s1); w32(n.child_off); w32(n.nchild);
    }
    for (auto c : children) w32(c);
    int32_t off = 0;
    for (auto& s : strings) { w32(off); off += static_cast<int32_t>(s.size()); }
    w32(off);
    for (auto& s : strings) { std::memcpy(p, s.data(), s.size()); p += s.size(); }
    *out_len = static_cast<int64_t>(total);
    return buf;
  }
};

// shared literal-cast over plan-buffer nodes (binder._cast_literal parity);
// throws BindErr on unparseable strings — ONE implementation for bind-time
// coercion and optimizer-time folding so the semantics cannot drift
int32_t cast_literal_node(const PBuilder& b, int32_t lit, int target);


// literal constructors over an arbitrary PBuilder (shared by the binder's
// coercion and the optimizer's constant folding)
int32_t mk_lit_int_b(const PBuilder& b, int64_t v, int ty) {
  return b.add(E_LITERAL, {}, ty_flags(ty, LT_INT), v);
}
int32_t mk_lit_float_b(const PBuilder& b, double v, int ty) {
  return b.add(E_LITERAL, {}, ty_flags(ty, LT_FLOAT), 0, v);
}
int32_t mk_lit_bool_b(const PBuilder& b, bool v, int ty) {
  return b.add(E_LITERAL, {}, ty_flags(ty, LT_BOOL), v ? 1 : 0);
}

// string-literal cast for comparisons and constant folding
// (binder._cast_literal parity; known divergences from Python are the
// int->datetime raw-ns and bool->datetime no-op corners, where Python's
// np.datetime64(str(v)) semantics are not replicated)
int32_t cast_literal_node(const PBuilder& b, int32_t lit, int target) {
  const PNode n = b.nodes[lit];
  int lt = ty_of_flags(n.flags);
  int tag = n.flags & 0xFF;
  if (is_datetime(target)) {
    int64_t ns;
    if (is_datetime(lt)) {
      ns = n.ival;
    } else if (tag == LT_STR) {
      ns = parse_datetime_ns((n.s0 < 0 ? std::string() : b.strings[n.s0]));
    } else if (tag == LT_INT) {
      ns = n.ival;
    } else if (tag == LT_FLOAT) {
      ns = (int64_t)n.dval;
    } else {
      return lit;
    }
    if (target == TY_DATE) ns = (ns / 86400000000000LL) * 86400000000000LL;
    return mk_lit_int_b(b, ns, target);
  }
  if (is_datetime(lt) || is_interval(lt)) {
    if (is_integer(target)) return mk_lit_int_b(b, n.ival, target);
    return lit;
  }
  if (is_integer(target)) {
    if (tag == LT_INT || tag == LT_BOOL) return mk_lit_int_b(b, n.ival, target);
    if (tag == LT_FLOAT) return mk_lit_int_b(b, (int64_t)n.dval, target);
    if (tag == LT_STR) {
      // Python int(str) raises for non-numeric strings -> BindError-ish;
      // match by parsing strictly
      const std::string s = (n.s0 < 0 ? std::string() : b.strings[n.s0]);
      char* endp;
      long long v = std::strtoll(s.c_str(), &endp, 10);
      if (*endp != '\0') bind_error("Cannot bind literal '" + s + "'");
      return mk_lit_int_b(b, v, target);
    }
    return lit;
  }
  if (target == TY_FLOAT || target == TY_DOUBLE || target == TY_DECIMAL ||
      target == TY_REAL) {
    if (tag == LT_INT || tag == LT_BOOL)
      return mk_lit_float_b(b, (double)n.ival, target);
    if (tag == LT_FLOAT) return mk_lit_float_b(b, n.dval, target);
    if (tag == LT_STR) {
      const std::string s = (n.s0 < 0 ? std::string() : b.strings[n.s0]);
      char* endp;
      double v = std::strtod(s.c_str(), &endp);
      if (*endp != '\0') bind_error("Cannot bind literal '" + s + "'");
      return mk_lit_float_b(b, v, target);
    }
    return lit;
  }
  if (target == TY_BOOLEAN) {
    std::string sv;
    if (tag == LT_STR) sv = (n.s0 < 0 ? std::string() : b.strings[n.s0]);
    else if (tag == LT_INT || tag == LT_BOOL) sv = std::to_string(n.ival);
    else if (tag == LT_FLOAT) sv = std::to_string(n.dval);
    std::string t = lower(sv);
    while (!t.empty() && t.front() == ' ') t.erase(t.begin());
    while (!t.empty() && t.back() == ' ') t.pop_back();
    bool v = t == "true" || t == "t" || t == "1" || t == "yes";
    return mk_lit_bool_b(b, v, TY_BOOLEAN);
  }
  return lit;
}


// ---------------------------------------------------------------------------
// binder
// ---------------------------------------------------------------------------
struct BField {
  std::string name;
  int type;
  bool nullable;
};

struct ScopeEntry {
  bool has_qual;
  std::string qual;
  BField field;
};

struct Scope {
  std::vector<ScopeEntry> entries;
  const Scope* parent = nullptr;
  bool case_sensitive = true;

  bool match_name(const std::string& a, const std::string& b) const {
    return case_sensitive ? a == b : lower(a) == lower(b);
  }

  // resolve -> (index, field) or nullopt; throws BindErr on ambiguity
  std::optional<std::pair<int, BField>> resolve(
      const std::vector<std::string>& parts) const {
    std::string qualifier, name;
    bool has_qual = false;
    if (parts.size() == 1) {
      name = parts[0];
    } else {
      qualifier = parts[parts.size() - 2];
      name = parts.back();
      has_qual = true;
    }
    std::vector<std::pair<int, BField>> matches;
    for (size_t i = 0; i < entries.size(); ++i) {
      const ScopeEntry& e = entries[i];
      if (!match_name(e.field.name, name)) continue;
      if (has_qual && (!e.has_qual || !match_name(e.qual, qualifier))) continue;
      matches.emplace_back((int)i, e.field);
    }
    if (matches.empty()) return std::nullopt;
    if (matches.size() > 1 && !has_qual) {
      std::vector<std::pair<int, BField>> exact;
      for (auto& m : matches)
        if (m.second.name == name) exact.push_back(m);
      if (exact.size() == 1) {
        matches = exact;
      } else {
        std::string full;
        for (size_t i = 0; i < parts.size(); ++i)
          full += (i ? "." : "") + parts[i];
        bind_error("Ambiguous column reference '" + full + "'");
      }
    }
    return matches[0];
  }
};

// nullability of a bound expr node (binder._nullable)
bool expr_nullable(const PBuilder& b, int32_t e) {
  const PNode n = b.nodes[e];
  if (n.kind == E_LITERAL) return (n.flags & 0xFF) == LT_NULL;
  if (n.kind == E_COLREF || n.kind == E_OUTERREF) return (n.flags & 1) != 0;
  return true;
}

int expr_type(const PBuilder& b, int32_t e) { return ty_of_flags(b.nodes[e].flags); }

class Binder {
 public:
  Binder(const Ast& ast, const Catalog& cat, PBuilder& out)
      : a(ast), cat(cat), b(out), case_sensitive(cat.case_sensitive) {}

  const Ast& a;
  const Catalog& cat;
  PBuilder& b;
  bool case_sensitive;
  // CTE stack: frames of name -> bound plan node (+ its fields)
  struct CtePlan {
    int32_t plan;
    std::vector<BField> fields;
  };
  std::vector<std::map<std::string, CtePlan>> cte_stack;
  // per-SELECT state (saved/restored like the Python instance attrs)
  std::map<std::string, int32_t> named_windows;          // name -> K_WINSPEC ast id
  std::map<std::string, int32_t>* select_alias_asts = nullptr;  // folded alias -> ast id

  std::string fold(const std::string& s) const {
    return case_sensitive ? s : lower(s);
  }

  // ---------------- helpers over bound nodes ----------------
  int32_t mk_field(const BField& f) {
    return b.add(P_FIELD, {}, (f.type << 8) | (f.nullable ? 1 : 0), 0, 0.0,
                 b.intern(f.name));
  }
  std::vector<int32_t> mk_fields(const std::vector<BField>& fs) {
    std::vector<int32_t> out;
    out.reserve(fs.size());
    for (auto& f : fs) out.push_back(mk_field(f));
    return out;
  }
  int32_t mk_colref(int idx, const std::string& name, int ty, bool nullable,
                    bool outer = false) {
    return b.add(outer ? E_OUTERREF : E_COLREF, {},
                 ty_flags(ty, nullable ? 1 : 0), idx, 0.0, b.intern(name));
  }
  int32_t mk_lit_null() { return b.add(E_LITERAL, {}, ty_flags(TY_NULL, LT_NULL)); }
  int32_t mk_lit_bool(bool v, int ty = TY_BOOLEAN) {
    return b.add(E_LITERAL, {}, ty_flags(ty, LT_BOOL), v ? 1 : 0);
  }
  int32_t mk_lit_int(int64_t v, int ty) {
    return b.add(E_LITERAL, {}, ty_flags(ty, LT_INT), v);
  }
  int32_t mk_lit_float(double v, int ty) {
    return b.add(E_LITERAL, {}, ty_flags(ty, LT_FLOAT), 0, v);
  }
  int32_t mk_lit_str(const std::string& v, int ty) {
    return b.add(E_LITERAL, {}, ty_flags(ty, LT_STR), 0, 0.0, b.intern(v));
  }
  int32_t mk_fn(const std::string& op, const std::vector<int32_t>& args, int ty) {
    return b.add(E_SCALARFN, args, ty_flags(ty), 0, 0.0, b.intern(op));
  }
  int32_t mk_cast(int32_t arg, int ty, bool safe = false) {
    return b.add(E_CAST, {arg}, ty_flags(ty, safe ? 1 : 0));
  }
  int32_t cast_to(int32_t e, int ty) {
    return expr_type(b, e) == ty ? e : mk_cast(e, ty);
  }
  int32_t mk_sortkey(int32_t expr, bool asc, bool has_nf, bool nf) {
    return b.add(P_SORTKEY, {expr},
                 (asc ? 1 : 0) | (has_nf ? 2 : 0) | (nf ? 4 : 0));
  }

  // walk a bound expr tree collecting nodes of one kind (pre-order, like
  // expressions.walk: node first, then children in children() order)
  void collect_kind(int32_t e, int32_t kind, std::vector<int32_t>& out) {
    if (b.nodes[e].kind == kind) out.push_back(e);
    for (int32_t k : expr_children(e)) collect_kind(k, kind, out);
  }

  bool contains_kind(int32_t e, int32_t kind) {
    if (b.nodes[e].kind == kind) return true;
    for (int32_t k : expr_children(e))
      if (contains_kind(k, kind)) return true;
    return false;
  }

  // children() parity with expressions.py (traversal order matters for
  // walk-based dedup): plan-valued kids (subqueries) are NOT expr children
  std::vector<int32_t> expr_children(int32_t e) {
    const PNode n = b.nodes[e];
    std::vector<int32_t> ks = b.kids(e);
    switch (n.kind) {
      case E_COLREF: case E_OUTERREF: case E_LITERAL:
        return {};
      case E_SCALARFN: case E_UDF: case E_GROUPING:
        return ks;
      case E_CAST:
        return ks;
      case E_CASE:
        return ks;  // when/then pairs flattened + optional else
      case E_INLIST:
        return ks;  // arg + items
      case E_INSUBQ:
        return {ks[0]};  // arg only (plan kid excluded)
      case E_EXISTS: case E_SCALARSUBQ:
        return {};
      case E_AGG: {
        // args + optional filter — all kids are expr-valued
        return ks;
      }
      case E_WINDOW: {
        // args... + P_WINSPEC: children() = args + partition + order exprs
        std::vector<int32_t> out(ks.begin(), ks.end() - 1);
        int32_t spec = ks.back();
        auto sk = b.kids(spec);
        int npart = (int)b.nodes[spec].ival;
        for (int i = 0; i < npart; ++i) out.push_back(sk[i]);
        for (size_t i = npart; i < sk.size(); ++i)
          if (b.nodes[sk[i]].kind == P_SORTKEY)
            out.push_back(b.kids(sk[i])[0]);
        return out;
      }
    }
    return {};
  }

  // rebuild an expr with new children (with_children parity)
  int32_t with_expr_children(int32_t e, const std::vector<int32_t>& ch) {
    const PNode n = b.nodes[e];
    switch (n.kind) {
      case E_COLREF: case E_OUTERREF: case E_LITERAL:
      case E_EXISTS: case E_SCALARSUBQ:
        return e;
      case E_SCALARFN: case E_UDF: case E_GROUPING: case E_CAST:
      case E_CASE: case E_INLIST: case E_AGG:
        return b.add(n.kind, ch, n.flags, n.ival, n.dval, n.s0, n.s1);
      case E_INSUBQ: {
        auto ks = b.kids(e);
        return b.add(n.kind, {ch[0], ks[1]}, n.flags, n.ival, n.dval, n.s0, n.s1);
      }
      case E_WINDOW: {
        auto ks = b.kids(e);
        int32_t spec = ks.back();
        const PNode sn = b.nodes[spec];
        auto sk = b.kids(spec);
        int npart = (int)sn.ival;
        int nargs = (int)ks.size() - 1;
        std::vector<int32_t> nsk;
        size_t ci = nargs;  // children: args, then partition, then order exprs
        for (int i = 0; i < npart; ++i) nsk.push_back(ch[ci++]);
        for (size_t i = npart; i < sk.size(); ++i) {
          if (b.nodes[sk[i]].kind == P_SORTKEY) {
            const PNode kn = b.nodes[sk[i]];
            nsk.push_back(b.add(P_SORTKEY, {ch[ci++]}, kn.flags));
          } else {
            nsk.push_back(sk[i]);  // frame bounds pass through
          }
        }
        int32_t nspec = b.add(P_WINSPEC, nsk, sn.flags, sn.ival, sn.dval,
                              sn.s0, sn.s1);
        std::vector<int32_t> nks(ch.begin(), ch.begin() + nargs);
        nks.push_back(nspec);
        return b.add(n.kind, nks, n.flags, n.ival, n.dval, n.s0, n.s1);
      }
    }
    return e;
  }

  // ---------------- literals ----------------
  int32_t bind_literal(int32_t nid) {
    const AstNode& n = a.n(nid);
    switch (n.kind) {
      case K_LIT_NULL: return mk_lit_null();
      case K_LIT_BOOL: return mk_lit_bool(n.ival != 0);
      case K_LIT_INT: {
        int64_t v = n.ival;
        int ty = (v >= -(1LL << 31) && v < (1LL << 31)) ? TY_INTEGER : TY_BIGINT;
        return mk_lit_int(v, ty);
      }
      case K_LIT_FLOAT: return mk_lit_float(n.dval, TY_DOUBLE);
      case K_LIT_STR: return mk_lit_str(a.s(n.s0), TY_VARCHAR);
      case K_LIT_TYPED: {
        std::string tn = upper(a.s(n.s1));
        std::string v = a.s(n.s0);
        if (tn == "DATE") {
          int64_t ns = parse_datetime_ns(v);
          ns = (ns / 86400000000000LL) * 86400000000000LL;
          return mk_lit_int(ns, TY_DATE);
        }
        if (tn == "TIMESTAMP" || tn == "TIME")
          return mk_lit_int(parse_datetime_ns(v), TY_TIMESTAMP);
        // other typed literals: unreachable via this parser
        throw Unsupported{};
      }
      case K_INTERVAL: return bind_interval(nid);
    }
    throw Unsupported{};
  }

  int32_t bind_interval(int32_t nid) {
    const AstNode& n = a.n(nid);
    std::string unit = upper(a.s(n.s1));
    size_t to = unit.find(" TO ");
    if (to != std::string::npos) unit = unit.substr(0, to);
    std::string text = a.s(n.s0);
    while (!text.empty() && text.front() == ' ') text.erase(text.begin());
    while (!text.empty() && text.back() == ' ') text.pop_back();
    auto& months = interval_month_units();
    auto mit = months.find(unit);
    auto all_digits = [](const std::string& s, size_t from) {
      if (from >= s.size()) return false;
      for (size_t i = from; i < s.size(); ++i)
        if (s[i] < '0' || s[i] > '9') return false;
      return true;
    };
    if (mit != months.end() &&
        all_digits(text, text.size() && text[0] == '-' ? 1 : 0)) {
      int64_t v = std::strtoll(text.c_str(), nullptr, 10);
      return mk_lit_int(v * mit->second, TY_INTERVAL_YEAR_MONTH);
    }
    bool neg = !text.empty() && text[0] == '-';
    std::string body = neg ? text.substr(1) : text;
    int64_t total_ns = 0;
    // plain number (optionally fractional)
    bool plain = !body.empty();
    bool seen_dot = false;
    for (char c : body) {
      if (c == '.' && !seen_dot) { seen_dot = true; continue; }
      if (c < '0' || c > '9') { plain = false; break; }
    }
    if (plain) {
      auto& nsu = interval_ns_units();
      auto uit = nsu.find(unit);
      int64_t scale = uit != nsu.end() ? uit->second : 1000000000LL;
      total_ns = (int64_t)(std::strtod(body.c_str(), nullptr) * (double)scale);
    } else {
      // compound 'D HH:MM[:SS[.f]]'
      const char* p = body.c_str();
      auto read_num = [&](double* out) -> bool {
        char* endp;
        double v = std::strtod(p, &endp);
        if (endp == p) return false;
        p = endp;
        *out = v;
        return true;
      };
      double days = 0, h = 0, mi = 0, ss = 0;
      double first;
      if (!read_num(&first)) bind_error("Bad interval literal '" + a.s(n.s0) + "'");
      if (*p == ' ') {
        days = first;
        while (*p == ' ') ++p;
        if (!read_num(&h) || *p != ':') bind_error("Bad interval literal '" + a.s(n.s0) + "'");
        ++p;
      } else if (*p == ':') {
        h = first;
        ++p;
      } else {
        bind_error("Bad interval literal '" + a.s(n.s0) + "'");
      }
      if (!read_num(&mi)) bind_error("Bad interval literal '" + a.s(n.s0) + "'");
      if (*p == ':') {
        ++p;
        if (!read_num(&ss)) bind_error("Bad interval literal '" + a.s(n.s0) + "'");
      }
      if (*p != '\0') bind_error("Bad interval literal '" + a.s(n.s0) + "'");
      total_ns = (int64_t)((((days * 24 + h) * 3600) + mi * 60 + ss) * 1e9);
    }
    if (neg) total_ns = -total_ns;
    return mk_lit_int(total_ns, TY_INTERVAL_DAY_TIME);
  }

  // string-literal cast for comparisons — one shared implementation with
  // the optimizer's constant folding (cast_literal_node)
  int32_t cast_literal(int32_t lit, int target) {
    return cast_literal_node(b, lit, target);
  }

  // string content of an interned id in the OUTPUT builder
  std::string a_str(int32_t sid) { return sid < 0 ? std::string() : b.strings[sid]; }

  // ---------------- coercion ----------------
  int32_t coerce_bool(int32_t e) {
    int t = expr_type(b, e);
    if (t == TY_BOOLEAN) return e;
    if (is_numeric(t) || t == TY_NULL) return mk_cast(e, TY_BOOLEAN);
    bind_error(std::string("Expected boolean expression, got ") + TY_NAMES[t]);
  }

  std::pair<int32_t, int32_t> coerce_pair(int32_t l, int32_t r) {
    int lt = expr_type(b, l), rt = expr_type(b, r);
    if (lt == rt) return {l, r};
    bool l_lit = b.nodes[l].kind == E_LITERAL;
    bool r_lit = b.nodes[r].kind == E_LITERAL;
    if (r_lit && is_string(rt) && !is_string(lt)) return {l, cast_literal(r, lt)};
    if (l_lit && is_string(lt) && !is_string(rt)) return {cast_literal(l, rt), r};
    int target = promote(lt, rt);  // BindErr on failure (message differs ok)
    int32_t l2 = lt == target ? l : mk_cast(l, target);
    int32_t r2 = rt == target ? r : mk_cast(r, target);
    return {l2, r2};
  }

  // ---------------- expressions ----------------
  // subst map: folded select alias -> AST id, consulted only when
  // subst_active and scope resolution fails (binder._subst_select_aliases)
  int32_t bind_expr(int32_t nid, const Scope& scope, bool subst_active = false) {
    const AstNode& n = a.n(nid);
    switch (n.kind) {
      case K_LIT_NULL: case K_LIT_INT: case K_LIT_FLOAT: case K_LIT_STR:
      case K_LIT_BOOL: case K_LIT_TYPED: case K_INTERVAL:
        return bind_literal(nid);
      case K_IDENT: {
        std::vector<std::string> parts;
        for (int32_t p : a.kids(nid)) parts.push_back(a.s(a.n(p).s0));
        auto ref = scope.resolve(parts);
        if (!ref) {
          // select-alias substitution (HAVING / ORDER BY / GROUPING args)
          if (subst_active && parts.size() == 1 && select_alias_asts) {
            auto it = select_alias_asts->find(fold(parts[0]));
            if (it != select_alias_asts->end())
              return bind_expr(it->second, scope, false);
          }
          std::string up = upper(parts.back());
          if (parts.size() == 1) {
            auto& sf = scalar_functions();
            auto it = sf.find(up);
            if (it != sf.end() && it->second.lo == 0)
              return mk_fn(it->second.op,
                           {}, resolve_type(it->second.rule, {}));
          }
          if (scope.parent != nullptr) {
            auto outer = scope.parent->resolve(parts);
            if (outer) {
              return mk_colref(outer->first, outer->second.name,
                               outer->second.type, outer->second.nullable,
                               /*outer=*/true);
            }
          }
          std::string full;
          for (size_t i = 0; i < parts.size(); ++i)
            full += (i ? "." : "") + parts[i];
          bind_error("Column '" + full + "' not found");
        }
        return mk_colref(ref->first, ref->second.name, ref->second.type,
                         ref->second.nullable);
      }
      case K_UNARY: {
        std::string op = upper(a.s(n.s0));
        int32_t arg = bind_expr(a.kids(nid)[0], scope, subst_active);
        if (op == "NOT")
          return mk_fn("not", {coerce_bool(arg)}, TY_BOOLEAN);
        if (op == "-")
          return mk_fn("neg", {arg}, expr_type(b, arg));
        return arg;
      }
      case K_BINARY: return bind_binary(nid, scope, subst_active);
      case K_CAST: {
        int32_t arg = bind_expr(a.kids(nid)[0], scope, subst_active);
        return mk_cast(arg, parse_sql_type(a.s(n.s0)), (n.flags & 1) != 0);
      }
      case K_CASE: return bind_case(nid, scope, subst_active);
      case K_FUNCALL: return bind_function(nid, scope, subst_active);
      case K_BETWEEN: {
        auto ks = a.kids(nid);
        int32_t arg = bind_expr(ks[0], scope, subst_active);
        int32_t low = bind_expr(ks[1], scope, subst_active);
        int32_t high = bind_expr(ks[2], scope, subst_active);
        bool negated = (n.flags & 1) != 0;
        bool symmetric = (n.flags & 2) != 0;
        if (symmetric) {
          int t = promote(expr_type(b, low), expr_type(b, high));
          int32_t lo2 = mk_fn("least", {low, high}, t);
          int32_t hi2 = mk_fn("greatest", {low, high}, t);
          low = lo2;
          high = hi2;
        }
        auto [arg_l, low2] = coerce_pair(arg, low);
        auto [arg_h, high2] = coerce_pair(arg, high);
        int32_t cond = mk_fn(
            "and",
            {mk_fn("ge", {arg_l, low2}, TY_BOOLEAN),
             mk_fn("le", {arg_h, high2}, TY_BOOLEAN)},
            TY_BOOLEAN);
        if (negated) return mk_fn("not", {cond}, TY_BOOLEAN);
        return cond;
      }
      case K_INLIST: {
        auto ks = a.kids(nid);
        int32_t arg = bind_expr(ks[0], scope, subst_active);
        std::vector<int32_t> items{arg};
        for (size_t i = 1; i < ks.size(); ++i) {
          int32_t it = bind_expr(ks[i], scope, subst_active);
          auto [_, it2] = coerce_pair(arg, it);
          items.push_back(it2);
        }
        return b.add(E_INLIST, items, ty_flags(TY_BOOLEAN, n.flags & 1));
      }
      case K_INSUBQ: {
        auto ks = a.kids(nid);
        int32_t arg = bind_expr(ks[0], scope, subst_active);
        auto [plan, fields] = bind_query(ks[1], &scope);
        if (fields.size() != 1)
          bind_error("IN subquery must return exactly one column");
        return b.add(E_INSUBQ, {arg, plan}, ty_flags(TY_BOOLEAN, n.flags & 1));
      }
      case K_EXISTS: {
        auto [plan, fields] = bind_query(a.kids(nid)[0], &scope);
        (void)fields;
        return b.add(E_EXISTS, {plan}, ty_flags(TY_BOOLEAN, n.flags & 1));
      }
      case K_SCALARSUBQ: {
        auto [plan, fields] = bind_query(a.kids(nid)[0], &scope);
        if (fields.size() != 1)
          bind_error("Scalar subquery must return exactly one column");
        return b.add(E_SCALARSUBQ, {plan}, ty_flags(fields[0].type));
      }
      case K_LIKE: {
        auto ks = a.kids(nid);
        int32_t arg = bind_expr(ks[0], scope, subst_active);
        int32_t pat = bind_expr(ks[1], scope, subst_active);
        bool negated = (n.flags & 1) != 0;
        bool ci = (n.flags & 2) != 0;
        bool similar = (n.flags & 4) != 0;
        std::string op = similar ? "similar" : (ci ? "ilike" : "like");
        std::vector<int32_t> args{arg, pat};
        if (n.flags & 8) args.push_back(mk_lit_str(a.s(n.s0), TY_VARCHAR));
        int32_t out = mk_fn(op, args, TY_BOOLEAN);
        if (negated) return mk_fn("not", {out}, TY_BOOLEAN);
        return out;
      }
      case K_ISNULL: {
        int32_t arg = bind_expr(a.kids(nid)[0], scope, subst_active);
        return mk_fn((n.flags & 1) ? "is_not_null" : "is_null", {arg}, TY_BOOLEAN);
      }
      case K_ISBOOL: {
        int32_t arg = coerce_bool(bind_expr(a.kids(nid)[0], scope, subst_active));
        bool value = (n.flags & 2) != 0;
        bool negated = (n.flags & 1) != 0;
        const char* op = value ? (negated ? "is_not_true" : "is_true")
                               : (negated ? "is_not_false" : "is_false");
        return mk_fn(op, {arg}, TY_BOOLEAN);
      }
      case K_ISDIST: {
        auto ks = a.kids(nid);
        int32_t l = bind_expr(ks[0], scope, subst_active);
        int32_t r = bind_expr(ks[1], scope, subst_active);
        auto [l2, r2] = coerce_pair(l, r);
        const char* op = (n.flags & 1) ? "is_not_distinct_from" : "is_distinct_from";
        return mk_fn(op, {l2, r2}, TY_BOOLEAN);
      }
      case K_EXTRACT: {
        int32_t arg = bind_expr(a.kids(nid)[0], scope, subst_active);
        return mk_fn("extract_" + lower(a.s(n.s0)), {arg}, TY_BIGINT);
      }
      case K_SUBSTRING: {
        auto ks = a.kids(nid);
        int32_t arg = bind_expr(ks[0], scope, subst_active);
        int32_t start = (n.flags & 1) ? bind_expr(ks[1], scope, subst_active)
                                      : mk_lit_int(1, TY_BIGINT);
        std::vector<int32_t> args{arg, start};
        if (n.flags & 2) args.push_back(bind_expr(ks[2], scope, subst_active));
        return mk_fn("substring", args, TY_VARCHAR);
      }
      case K_TRIM: {
        auto ks = a.kids(nid);
        int32_t arg = bind_expr(ks[0], scope, subst_active);
        std::string where = upper(a.s(n.s0));
        const char* op = where == "LEADING" ? "ltrim"
                         : where == "TRAILING" ? "rtrim" : "btrim";
        std::vector<int32_t> args{arg};
        if (n.flags & 1) args.push_back(bind_expr(ks[1], scope, subst_active));
        return mk_fn(op, args, TY_VARCHAR);
      }
      case K_POSITION: {
        auto ks = a.kids(nid);
        return mk_fn("position",
                     {bind_expr(ks[0], scope, subst_active),
                      bind_expr(ks[1], scope, subst_active)},
                     TY_INTEGER);
      }
      case K_OVERLAY: {
        auto ks = a.kids(nid);
        std::vector<int32_t> args{bind_expr(ks[0], scope, subst_active),
                                  bind_expr(ks[1], scope, subst_active),
                                  bind_expr(ks[2], scope, subst_active)};
        if (n.flags & 1) args.push_back(bind_expr(ks[3], scope, subst_active));
        return mk_fn("overlay", args, TY_VARCHAR);
      }
      case K_CEILFLOORTO: {
        int32_t arg = bind_expr(a.kids(nid)[0], scope, subst_active);
        std::string fn = upper(a.s(n.s0));
        const char* op = fn == "CEIL" ? "datetime_ceil" : "datetime_floor";
        return mk_fn(op, {arg, mk_lit_str(a.s(n.s1), TY_VARCHAR)},
                     expr_type(b, arg));
      }
      case K_WILDCARD:
        bind_error("Wildcard not allowed here");
    }
    throw Unsupported{};
  }

  int32_t bind_binary(int32_t nid, const Scope& scope, bool subst_active) {
    const AstNode& n = a.n(nid);
    std::string op = upper(a.s(n.s0));
    auto ks = a.kids(nid);
    if (op == "AND" || op == "OR") {
      int32_t l = coerce_bool(bind_expr(ks[0], scope, subst_active));
      int32_t r = coerce_bool(bind_expr(ks[1], scope, subst_active));
      return mk_fn(lower(op), {l, r}, TY_BOOLEAN);
    }
    int32_t l = bind_expr(ks[0], scope, subst_active);
    int32_t r = bind_expr(ks[1], scope, subst_active);
    if (op == "||") return mk_fn("concat", {l, r}, TY_VARCHAR);
    static const std::map<std::string, const char*> cmp = {
        {"=", "eq"}, {"<>", "ne"}, {"<", "lt"}, {"<=", "le"},
        {">", "gt"}, {">=", "ge"}};
    auto cit = cmp.find(op);
    if (cit != cmp.end()) {
      auto [l2, r2] = coerce_pair(l, r);
      return mk_fn(cit->second, {l2, r2}, TY_BOOLEAN);
    }
    static const std::map<std::string, const char*> arith = {
        {"+", "add"}, {"-", "sub"}, {"*", "mul"}, {"/", "div"}, {"%", "mod"}};
    auto ait = arith.find(op);
    if (ait != arith.end()) return bind_arith(op, ait->second, l, r);
    bind_error("Unknown binary operator " + op);
  }

  int32_t bind_arith(const std::string& op, const char* canon, int32_t l,
                     int32_t r) {
    int lt = expr_type(b, l), rt = expr_type(b, r);
    if (is_datetime(lt) || is_datetime(rt)) {
      if (op == "-" && is_datetime(lt) && is_datetime(rt))
        return mk_fn("datetime_sub", {l, r}, TY_INTERVAL_DAY_TIME);
      if (is_datetime(lt) && is_interval(rt))
        return mk_fn(op == "+" ? "datetime_add" : "datetime_sub_interval",
                     {l, r}, lt);
      if (is_datetime(rt) && is_interval(lt) && op == "+")
        return mk_fn("datetime_add", {r, l}, rt);
      if (is_datetime(lt) && is_integer(rt)) {
        int32_t iv = mk_fn("int_to_interval_days", {r}, TY_INTERVAL_DAY_TIME);
        return mk_fn(op == "+" ? "datetime_add" : "datetime_sub_interval",
                     {l, iv}, lt);
      }
      if (is_datetime(rt) && is_integer(lt) && op == "+") {
        int32_t iv = mk_fn("int_to_interval_days", {l}, TY_INTERVAL_DAY_TIME);
        return mk_fn("datetime_add", {r, iv}, rt);
      }
    }
    if (is_interval(lt) || is_interval(rt)) {
      if ((op == "+" || op == "-") && is_interval(lt) && is_interval(rt))
        return mk_fn(canon, {l, r}, lt);
      if (op == "*")
        return mk_fn("mul", {l, r}, is_interval(lt) ? lt : rt);
    }
    auto [l2, r2] = coerce_pair(l, r);
    int result = promote(expr_type(b, l2), expr_type(b, r2));
    if (op == "/") return mk_fn("div", {l2, r2}, result);
    return mk_fn(canon, {l2, r2}, result);
  }

  int32_t bind_case(int32_t nid, const Scope& scope, bool subst_active) {
    const AstNode& n = a.n(nid);
    auto ks = a.kids(nid);
    size_t i = 0;
    std::vector<std::pair<int32_t, int32_t>> whens;
    if (n.flags & 1) {  // CASE <operand> WHEN ...
      int32_t operand = bind_expr(ks[0], scope, subst_active);
      i = 1;
      size_t n_when = (ks.size() - i - ((n.flags & 2) ? 1 : 0)) / 2;
      for (size_t j = 0; j < n_when; ++j) {
        int32_t c = bind_expr(ks[i + 2 * j], scope, subst_active);
        auto [o2, c2] = coerce_pair(operand, c);
        int32_t res = bind_expr(ks[i + 2 * j + 1], scope, subst_active);
        whens.emplace_back(mk_fn("eq", {o2, c2}, TY_BOOLEAN), res);
      }
    } else {
      size_t n_when = (ks.size() - ((n.flags & 2) ? 1 : 0)) / 2;
      for (size_t j = 0; j < n_when; ++j) {
        int32_t c = coerce_bool(bind_expr(ks[2 * j], scope, subst_active));
        int32_t res = bind_expr(ks[2 * j + 1], scope, subst_active);
        whens.emplace_back(c, res);
      }
    }
    int32_t else_ = -1;
    if (n.flags & 2) else_ = bind_expr(ks.back(), scope, subst_active);
    int rt = expr_type(b, whens.empty() ? else_ : whens[0].second);
    for (auto& w : whens) rt = promote(rt, expr_type(b, w.second));
    if (else_ >= 0) rt = promote(rt, expr_type(b, else_));
    std::vector<int32_t> kids;
    for (auto& w : whens) {
      kids.push_back(w.first);
      kids.push_back(cast_to(w.second, rt));
    }
    if (else_ >= 0) kids.push_back(cast_to(else_, rt));
    return b.add(E_CASE, kids, ty_flags(rt, (else_ >= 0) ? 1 : 0));
  }

  int32_t bind_filter_clause(int32_t funcall_nid, int32_t filter_kid,
                             const Scope& scope, bool subst_active) {
    if (filter_kid < 0) return -1;
    return coerce_bool(bind_expr(filter_kid, scope, subst_active));
  }

  int32_t bind_function(int32_t nid, const Scope& scope, bool subst_active) {
    const AstNode& n = a.n(nid);
    std::string name = upper(a.s(n.s0));
    auto ks = a.kids(nid);
    int nargs = (int)n.ival;
    bool distinct = (n.flags & 1) != 0;
    bool ignore_nulls = (n.flags & 2) != 0;
    bool has_filter = (n.flags & 4) != 0;
    bool has_over_spec = (n.flags & 8) != 0;
    bool has_over_name = (n.flags & 16) != 0;
    int32_t filter_kid = has_filter ? ks[nargs] : -1;
    int32_t over_spec_kid = has_over_spec ? ks[nargs + (has_filter ? 1 : 0)] : -1;

    if (name == "GROUPING" && !has_over_spec && !has_over_name) {
      if (nargs == 0) bind_error("GROUPING requires column arguments");
      std::vector<int32_t> bound;
      for (int i = 0; i < nargs; ++i) {
        if (a.n(ks[i]).kind == K_WILDCARD)
          bind_error("GROUPING requires column arguments");
        // select aliases may serve as GROUPING args (bind with fallback)
        try {
          bound.push_back(bind_expr(ks[i], scope, false));
        } catch (const BindErr&) {
          const AstNode& an = a.n(ks[i]);
          if (an.kind == K_IDENT && an.nchild == 1 && select_alias_asts) {
            std::string part = a.s(a.n(a.kids(ks[i])[0]).s0);
            auto it = select_alias_asts->find(fold(part));
            if (it != select_alias_asts->end()) {
              bound.push_back(bind_expr(it->second, scope, false));
              continue;
            }
          }
          throw;
        }
      }
      return b.add(E_GROUPING, bound, ty_flags(TY_INTEGER));
    }

    // bind args; star (COUNT(*)) -> sentinel -1
    std::vector<int32_t> args;
    for (int i = 0; i < nargs; ++i) {
      if (a.n(ks[i]).kind == K_WILDCARD)
        args.push_back(-1);
      else
        args.push_back(bind_expr(ks[i], scope, subst_active));
    }

    if (has_over_spec || has_over_name) {
      int32_t spec_nid = over_spec_kid;
      if (has_over_name) {
        std::string wname = a.s(n.s1);
        auto it = named_windows.find(wname);
        if (it == named_windows.end() && !case_sensitive) {
          for (auto& kv : named_windows)
            if (lower(kv.first) == lower(wname)) { it = named_windows.find(kv.first); break; }
        }
        if (it == named_windows.end())
          bind_error("Unknown window name '" + wname + "'");
        spec_nid = it->second;
      }
      return bind_window_call(name, args, spec_nid, filter_kid, ignore_nulls,
                              distinct, scope, subst_active);
    }

    auto& aggs = aggregate_functions();
    auto agg_it = aggs.find(name);
    if (agg_it != aggs.end())
      return make_agg(name, agg_it->second, args, distinct, filter_kid, nid,
                      scope, subst_active);

    // UDF / user aggregation
    const std::vector<CFnOverload>* fns = cat.resolve_function(a.s(n.s0));
    if (fns != nullptr && !fns->empty()) {
      const CFnOverload& fd = pick_overload(*fns, args);
      int32_t filt = bind_filter_clause(nid, filter_kid, scope, subst_active);
      if (fd.aggregation) {
        std::vector<int32_t> kids2 = args;
        for (auto aid : kids2)
          if (aid < 0) bind_error("* argument only allowed in COUNT");
        int32_t fl = ty_flags(fd.return_type, (distinct ? 1 : 0) |
                                               (filt >= 0 ? 2 : 0));
        if (filt >= 0) kids2.push_back(filt);
        return b.add(E_AGG, kids2, fl, 0, 0.0,
                     b.intern("udaf:" + fd.name));
      }
      std::vector<int32_t> cast_args;
      for (size_t i = 0; i < args.size(); ++i) {
        int32_t arg = args[i];
        if (arg < 0) bind_error("* argument only allowed in COUNT");
        if (i < fd.param_types.size() &&
            expr_type(b, arg) != fd.param_types[i])
          arg = mk_cast(arg, fd.param_types[i]);
        cast_args.push_back(arg);
      }
      return b.add(E_UDF, cast_args,
                   ty_flags(fd.return_type, fd.row_udf ? 1 : 0), 0, 0.0,
                   b.intern(fd.name));
    }

    auto& sf = scalar_functions();
    auto sit = sf.find(name);
    if (sit != sf.end()) {
      const ScalarSig& sig = sit->second;
      if ((int)args.size() < sig.lo || (int)args.size() > sig.hi)
        bind_error(name + " expects " + std::to_string(sig.lo) + ".." +
                   std::to_string(sig.hi) + " args, got " +
                   std::to_string(args.size()));
      std::vector<int> ats;
      for (auto arg : args) {
        if (arg < 0) bind_error("* argument only allowed in COUNT");
        ats.push_back(expr_type(b, arg));
      }
      return mk_fn(sig.op, args, resolve_type(sig.rule, ats));
    }
    bind_error("Unknown function '" + a.s(n.s0) + "'");
  }

  const CFnOverload& pick_overload(const std::vector<CFnOverload>& fns,
                                   const std::vector<int32_t>& args) {
    size_t nargs = args.size();
    std::vector<const CFnOverload*> exact;
    for (auto& fd : fns)
      if (fd.param_types.size() == nargs) exact.push_back(&fd);
    if (!exact.empty()) {
      for (auto* fd : exact) {
        bool ok = true;
        for (size_t i = 0; i < nargs; ++i) {
          if (args[i] < 0 || !similar_type(expr_type(b, args[i]),
                                           fd->param_types[i])) {
            ok = false;
            break;
          }
        }
        if (ok) return *fd;
      }
      return *exact[0];
    }
    return fns[0];
  }

  int32_t make_agg(const std::string& name, const AggSig& sig,
                   const std::vector<int32_t>& args, bool distinct,
                   int32_t filter_kid, int32_t nid, const Scope& scope,
                   bool subst_active) {
    int32_t filt = bind_filter_clause(nid, filter_kid, scope, subst_active);
    bool star = args.empty() || args[0] < 0;
    if (name == "COUNT" && star) {
      int32_t fl = ty_flags(TY_BIGINT, (distinct ? 1 : 0) | (filt >= 0 ? 2 : 0));
      std::vector<int32_t> kids2;
      if (filt >= 0) kids2.push_back(filt);
      return b.add(E_AGG, kids2, fl, 0, 0.0, b.intern("count_star"));
    }
    for (auto arg : args)
      if (arg < 0) bind_error("* argument only allowed in COUNT");
    std::vector<int> ats;
    for (auto arg : args) ats.push_back(expr_type(b, arg));
    int rt = resolve_type(sig.rule, ats);
    int32_t fl = ty_flags(rt, (distinct ? 1 : 0) | (filt >= 0 ? 2 : 0));
    std::vector<int32_t> kids2 = args;
    if (filt >= 0) kids2.push_back(filt);
    return b.add(E_AGG, kids2, fl, 0, 0.0, b.intern(sig.op));
  }

  // frame bound ast node payload: parser K_FRAME — fival = start|end<<8,
  // fflags 1/2 = offset exprs present (offsets are literal ast exprs)
  int32_t mk_frame_bound(int kind, bool has_off, bool is_float, int64_t iv,
                         double dv) {
    return b.add(P_FRAME_BOUND, {},
                 (kind << 4) | (has_off ? 1 : 0) | (is_float ? 2 : 0), iv, dv);
  }

  // evaluate a frame offset AST (literal int/float or interval)
  void frame_offset(int32_t off_nid, const std::string& units, bool* has,
                    bool* is_float, int64_t* iv, double* dv) {
    *has = false;
    *is_float = false;
    *iv = 0;
    *dv = 0;
    if (off_nid < 0) return;
    const AstNode& n = a.n(off_nid);
    if (n.kind == K_INTERVAL) {
      if (units != "RANGE")
        bind_error("Interval frame offsets require RANGE frames");
      int32_t lit = bind_interval(off_nid);
      if (expr_type(b, lit) == TY_INTERVAL_YEAR_MONTH)
        bind_error(
            "Year-month intervals are not supported as RANGE offsets; use "
            "day-time intervals (e.g. INTERVAL '30' DAY)");
      *has = true;
      *iv = b.nodes[lit].ival;
      return;
    }
    if (n.kind == K_LIT_INT) {
      *has = true;
      *iv = n.ival;
      return;
    }
    if (n.kind == K_LIT_FLOAT) {
      if (units == "ROWS")
        bind_error("ROWS frame offsets must be integer literals");
      *has = true;
      *is_float = true;
      *dv = n.dval;
      return;
    }
    bind_error("Window frame offsets must be numeric or interval literals");
  }

  int32_t bind_window_call(const std::string& name,
                           const std::vector<int32_t>& args, int32_t spec_nid,
                           int32_t filter_kid, bool ignore_nulls, bool distinct,
                           const Scope& scope, bool subst_active) {
    (void)filter_kid;
    (void)distinct;
    // decode the K_WINSPEC ast node
    const AstNode& sn = a.n(spec_nid);
    auto sk = a.kids(spec_nid);
    bool has_frame = (sn.flags & 1) != 0;
    int npart = (int)sn.ival;
    int32_t frame_id = -1;
    size_t n_items = sk.size();
    if (has_frame) {
      frame_id = sk.back();
      n_items -= 1;
    }
    std::vector<int32_t> partition;
    for (int i = 0; i < npart; ++i)
      partition.push_back(bind_expr(sk[i], scope, subst_active));
    std::vector<int32_t> order_keys;  // P_SORTKEY nodes
    for (size_t i = npart; i < n_items; ++i) {
      const AstNode& on = a.n(sk[i]);
      int32_t e = bind_expr(a.kids(sk[i])[0], scope, subst_active);
      bool asc = (on.flags & 1) != 0;
      bool has_nf = (on.flags & 2) != 0;
      bool nf = (on.flags & 4) != 0;
      order_keys.push_back(mk_sortkey(e, asc, has_nf, nf));
    }

    int rt_rule = -1;
    std::string func;
    int sql_type;
    std::vector<int32_t> out_args = args;
    auto& wf = window_functions();
    auto wit = wf.find(name);
    std::vector<int> ats;
    for (auto arg : args)
      if (arg >= 0) ats.push_back(expr_type(b, arg));
    if (wit != wf.end()) {
      rt_rule = wit->second;
      func = lower(name);
      sql_type = resolve_type(rt_rule, ats);
    } else {
      auto& aggs = aggregate_functions();
      auto ait = aggs.find(name);
      if (ait == aggs.end()) bind_error("Unknown window function '" + name + "'");
      bool star = args.empty() || args[0] < 0;
      if (name == "COUNT" && star) {
        func = "count_star";
        sql_type = TY_BIGINT;
        out_args.clear();
      } else {
        for (auto arg : args)
          if (arg < 0) bind_error("* argument only allowed in COUNT");
        func = ait->second.op;
        sql_type = resolve_type(ait->second.rule, ats);
      }
    }

    // frame: parser K_FRAME node -> bounds; defaults otherwise
    std::string units = "ROWS";
    int start_kind, end_kind;
    bool s_has, s_f, e_has, e_f;
    int64_t s_iv, e_iv;
    double s_dv, e_dv;
    bool explicit_frame;
    if (frame_id >= 0) {
      const AstNode& fn = a.n(frame_id);
      units = upper(a.s(fn.s0));
      start_kind = (int)(fn.ival & 0xFF);
      end_kind = (int)((fn.ival >> 8) & 0xFF);
      auto fk = a.kids(frame_id);
      size_t fi = 0;
      int32_t s_off = (fn.flags & 1) ? fk[fi++] : -1;
      int32_t e_off = (fn.flags & 2) ? fk[fi++] : -1;
      frame_offset(s_off, units, &s_has, &s_f, &s_iv, &s_dv);
      frame_offset(e_off, units, &e_has, &e_f, &e_iv, &e_dv);
      explicit_frame = true;
    } else if (!order_keys.empty()) {
      units = "RANGE";
      start_kind = 0;  // UNBOUNDED_PRECEDING
      end_kind = 2;    // CURRENT_ROW
      s_has = s_f = e_has = e_f = false;
      s_iv = e_iv = 0;
      s_dv = e_dv = 0;
      explicit_frame = false;
    } else {
      units = "ROWS";
      start_kind = 0;
      end_kind = 4;  // UNBOUNDED_FOLLOWING
      s_has = s_f = e_has = e_f = false;
      s_iv = e_iv = 0;
      s_dv = e_dv = 0;
      explicit_frame = false;
    }
    std::vector<int32_t> spec_kids = partition;
    for (auto k : order_keys) spec_kids.push_back(k);
    spec_kids.push_back(mk_frame_bound(start_kind, s_has, s_f, s_iv, s_dv));
    spec_kids.push_back(mk_frame_bound(end_kind, e_has, e_f, e_iv, e_dv));
    int32_t spec = b.add(P_WINSPEC, spec_kids, explicit_frame ? 1 : 0,
                         npart, 0.0, b.intern(units));
    std::vector<int32_t> kids2;
    for (auto arg : out_args)
      if (arg >= 0) kids2.push_back(arg);
    kids2.push_back(spec);
    return b.add(E_WINDOW, kids2,
                 ty_flags(sql_type, ignore_nulls ? 1 : 0),
                 (int64_t)(kids2.size() - 1), 0.0, b.intern(func));
  }

  // ---------------- plans ----------------
  struct BPlan {
    int32_t id;
    std::vector<BField> fields;
  };

  // Python str() of a literal for derived projection names
  static std::string py_float_repr(double v) {
    if (std::isnan(v)) return "nan";
    if (std::isinf(v)) return v > 0 ? "inf" : "-inf";
    char buf[64];
    for (int prec = 1; prec <= 17; ++prec) {
      std::snprintf(buf, sizeof buf, "%.*g", prec, v);
      if (std::strtod(buf, nullptr) == v) break;
    }
    std::string s(buf);
    // Python always shows a fraction or exponent for floats
    if (s.find('.') == std::string::npos && s.find('e') == std::string::npos &&
        s.find("inf") == std::string::npos && s.find("nan") == std::string::npos)
      s += ".0";
    // Python exponent formatting: 1e+20 -> '1e+20' (matches %g)
    return s;
  }

  std::string derive_name(int32_t nid) {
    const AstNode& n = a.n(nid);
    switch (n.kind) {
      case K_IDENT: {
        auto ks = a.kids(nid);
        return a.s(a.n(ks.back()).s0);
      }
      case K_FUNCALL: return a.s(n.s0);
      case K_CAST: return derive_name(a.kids(nid)[0]);
      case K_LIT_NULL: return "None";
      case K_LIT_BOOL: return n.ival ? "True" : "False";
      case K_LIT_INT: return std::to_string(n.ival);
      case K_LIT_FLOAT: return py_float_repr(n.dval);
      case K_LIT_STR: case K_LIT_TYPED: return a.s(n.s0);
      case K_EXTRACT: return "EXTRACT";
      case K_CASE: return "CASE";
    }
    return "EXPR";
  }

  std::string derive_group_name(int32_t bound, int i) {
    if (b.nodes[bound].kind == E_COLREF || b.nodes[bound].kind == E_OUTERREF)
      return a_str(b.nodes[bound].s0);
    return "__group" + std::to_string(i);
  }

  // ---------------- FROM refs ----------------
  std::pair<BPlan, Scope> bind_table_ref(int32_t nid, const Scope* outer) {
    const AstNode& n = a.n(nid);
    if (n.kind == K_NAMED_TABLE) {
      auto out = bind_named_table(nid, outer);
      if (n.flags & 1) {  // TABLESAMPLE
        std::string method = a.s(n.s1);
        double frac = n.dval;
        int64_t seed = n.ival;  // -1 = none
        int32_t plan = b.add(
            P_SAMPLE, concat({out.first.id}, mk_fields(out.first.fields)),
            seed >= 0 ? 1 : 0, seed, frac, b.intern(method));
        out.first.id = plan;
      }
      return out;
    }
    if (n.kind == K_DERIVED_TABLE) {
      auto ks = a.kids(nid);
      auto [sub, sub_fields] = bind_query(ks[0], outer);
      std::string alias = a.s(n.s0);
      std::vector<std::string> col_aliases;
      for (size_t i = 1; i < ks.size(); ++i)
        if (a.n(ks[i]).kind == K_ALIAS_COL)
          col_aliases.push_back(a.s(a.n(ks[i]).s0));
      std::vector<BField> fields = sub_fields;
      for (size_t i = 0; i < fields.size() && i < col_aliases.size(); ++i)
        fields[i].name = col_aliases[i];
      int32_t plan = sub;
      if (a.has_s(n.s0)) {
        plan = b.add(P_SUBQUERY_ALIAS, concat({sub}, mk_fields(fields)), 0, 0,
                     0.0, b.intern(alias));
      }
      Scope scope;
      scope.parent = outer;
      scope.case_sensitive = case_sensitive;
      for (auto& f : fields)
        scope.entries.push_back({a.has_s(n.s0), alias, f});
      return {{plan, fields}, scope};
    }
    if (n.kind == K_TABLE_FUNC) {
      // PREDICT(MODEL name, <query>) table function
      auto ks = a.kids(nid);
      std::vector<std::string> parts;
      int32_t sel = -1;
      for (int32_t k : ks) {
        if (a.n(k).kind == K_PART) parts.push_back(a.s(a.n(k).s0));
        else if (a.n(k).kind == K_SELECT) sel = k;
      }
      auto [sub, sub_fields] = bind_query(sel, outer);
      std::vector<BField> fields = sub_fields;
      fields.push_back({"target", TY_DOUBLE, true});
      std::vector<int32_t> name_kids;
      for (auto& pt : parts)
        name_kids.push_back(b.add(P_PART, {}, 0, 0, 0.0, b.intern(pt)));
      int32_t plan = b.add(
          P_PREDICT_MODEL,
          concat(concat({sub}, mk_fields(fields)), name_kids), 0,
          (int64_t)fields.size());
      std::string alias = a.s(n.s1);
      Scope scope;
      scope.parent = outer;
      scope.case_sensitive = case_sensitive;
      for (auto& f : fields)
        scope.entries.push_back({a.has_s(n.s1), alias, f});
      return {{plan, fields}, scope};
    }
    if (n.kind == K_JOIN) return bind_join(nid, outer);
    throw Unsupported{};
  }

  static std::vector<int32_t> concat(std::vector<int32_t> x,
                                     const std::vector<int32_t>& y) {
    x.insert(x.end(), y.begin(), y.end());
    return x;
  }

  std::pair<BPlan, Scope> bind_named_table(int32_t nid, const Scope* outer) {
    const AstNode& n = a.n(nid);
    std::string alias = a.s(n.s0);
    bool has_alias = a.has_s(n.s0);
    std::vector<std::string> parts, col_aliases;
    for (int32_t k : a.kids(nid)) {
      if (a.n(k).kind == K_PART) parts.push_back(a.s(a.n(k).s0));
      else if (a.n(k).kind == K_ALIAS_COL)
        col_aliases.push_back(a.s(a.n(k).s0));
    }
    // CTE lookup first (innermost wins)
    if (parts.size() == 1) {
      for (auto it = cte_stack.rbegin(); it != cte_stack.rend(); ++it) {
        auto f = it->find(parts[0]);
        if (f != it->end()) {
          std::vector<BField> fields = f->second.fields;
          for (size_t i = 0; i < fields.size() && i < col_aliases.size(); ++i)
            fields[i].name = col_aliases[i];
          std::string qname = has_alias ? alias : parts[0];
          Scope scope;
          scope.parent = outer;
          scope.case_sensitive = case_sensitive;
          for (auto& fl : fields) scope.entries.push_back({true, qname, fl});
          return {{f->second.plan, fields}, scope};
        }
      }
    }
    const CTable* table = cat.resolve_table(parts);
    std::vector<BField> fields;
    for (auto& c : table->fields) fields.push_back({c.name, c.type, c.nullable});
    int32_t scan = b.add(P_TABLESCAN, mk_fields(fields), 0, 0, 0.0,
                         b.intern(table->schema_name), b.intern(table->name));
    for (size_t i = 0; i < fields.size() && i < col_aliases.size(); ++i)
      fields[i].name = col_aliases[i];
    std::string qname = has_alias ? alias : table->name;
    Scope scope;
    scope.parent = outer;
    scope.case_sensitive = case_sensitive;
    for (auto& fl : fields) scope.entries.push_back({true, qname, fl});
    return {{scan, fields}, scope};
  }

  std::pair<BPlan, Scope> bind_join(int32_t nid, const Scope* outer) {
    const AstNode& n = a.n(nid);
    auto ks = a.kids(nid);
    auto [lp, lscope] = bind_table_ref(ks[0], outer);
    auto [rp, rscope] = bind_table_ref(ks[1], outer);
    int nleft = (int)lscope.entries.size();
    std::string jt = upper(a.s(n.s0));
    Scope scope;
    scope.parent = outer;
    scope.case_sensitive = case_sensitive;
    scope.entries = lscope.entries;
    scope.entries.insert(scope.entries.end(), rscope.entries.begin(),
                         rscope.entries.end());
    auto mk_out_fields = [&]() {
      std::vector<BField> out;
      for (size_t i = 0; i < scope.entries.size(); ++i) {
        BField f = scope.entries[i].field;
        if ((jt == "LEFT" || jt == "FULL") && (int)i >= nleft) f.nullable = true;
        if ((jt == "RIGHT" || jt == "FULL") && (int)i < nleft) f.nullable = true;
        out.push_back(f);
      }
      return out;
    };
    if (jt == "CROSS") {
      auto fields = mk_out_fields();
      int32_t plan = b.add(P_CROSSJOIN, concat({lp.id, rp.id}, mk_fields(fields)));
      return {{plan, fields}, scope};
    }
    bool has_using = (n.flags & 2) != 0;
    bool has_cond = (n.flags & 1) != 0;
    std::vector<int32_t> rest(ks.begin() + 2, ks.end());
    if (has_using) {
      std::vector<std::string> using_cols;
      for (int32_t k : rest)
        if (a.n(k).kind == K_USING_COL) using_cols.push_back(a.s(a.n(k).s0));
      if (using_cols.empty()) {
        // NATURAL JOIN (parser encodes it as an empty USING list):
        // shared names in right-entry order
        std::set<std::string> lnames;
        for (auto& e : lscope.entries) lnames.insert(e.field.name);
        for (auto& e : rscope.entries)
          if (lnames.count(e.field.name)) using_cols.push_back(e.field.name);
      }
      std::vector<int32_t> on_pairs;
      for (auto& name : using_cols) {
        auto lref = lscope.resolve({name});
        auto rref = rscope.resolve({name});
        if (!lref || !rref)
          bind_error("USING column '" + name + "' not present on both sides");
        int32_t le = mk_colref(lref->first, lref->second.name,
                               lref->second.type, lref->second.nullable);
        int32_t re = mk_colref(rref->first + nleft, rref->second.name,
                               rref->second.type, rref->second.nullable);
        on_pairs.push_back(b.add(P_ON_PAIR, {le, re}));
      }
      auto fields = mk_out_fields();
      int32_t plan = b.add(
          P_JOIN,
          concat(concat({lp.id, rp.id}, mk_fields(fields)), on_pairs),
          0, (int64_t)fields.size(), 0.0, b.intern(jt));
      return {{plan, fields}, scope};
    }
    int32_t cond = has_cond ? bind_expr(rest[0], scope)
                            : mk_lit_bool(true, TY_BOOLEAN);
    auto [on_pairs, residual] = split_join_condition(cond, nleft);
    auto fields = mk_out_fields();
    Scope out_scope = scope;
    if (jt == "LEFTSEMI" || jt == "LEFTANTI") {
      fields.resize(nleft);
      out_scope.entries.resize(nleft);
    }
    std::vector<int32_t> kids2 =
        concat(concat({lp.id, rp.id}, mk_fields(fields)), on_pairs);
    int32_t flags = 0;
    if (residual >= 0) {
      kids2.push_back(residual);
      flags |= 1;
    }
    int32_t plan = b.add(P_JOIN, kids2, flags, (int64_t)fields.size(), 0.0,
                         b.intern(jt));
    return {{plan, fields}, out_scope};
  }

  void referenced_columns(int32_t e, std::set<int64_t>& out) {
    const PNode n = b.nodes[e];
    if (n.kind == E_COLREF || n.kind == E_OUTERREF) out.insert(n.ival);
    for (int32_t k : expr_children(e)) referenced_columns(k, out);
  }

  void flatten_and(int32_t e, std::vector<int32_t>& out) {
    const PNode n = b.nodes[e];
    if (n.kind == E_SCALARFN && a_str(n.s0) == "and") {
      for (int32_t k : b.kids(e)) flatten_and(k, out);
      return;
    }
    out.push_back(e);
  }

  std::pair<std::vector<int32_t>, int32_t> split_join_condition(int32_t cond,
                                                                int nleft) {
    std::vector<int32_t> conjuncts;
    flatten_and(cond, conjuncts);
    std::vector<int32_t> on;
    std::vector<int32_t> residual;
    for (int32_t c : conjuncts) {
      const PNode n = b.nodes[c];
      if (n.kind == E_LITERAL && (n.flags & 0xFF) == LT_BOOL && n.ival == 1)
        continue;
      if (n.kind == E_SCALARFN && a_str(n.s0) == "eq") {
        auto ks = b.kids(c);
        std::set<int64_t> lcols, rcols;
        referenced_columns(ks[0], lcols);
        referenced_columns(ks[1], rcols);
        if (!lcols.empty() && !rcols.empty()) {
          int64_t lmax = *lcols.rbegin(), lmin = *lcols.begin();
          int64_t rmax = *rcols.rbegin(), rmin = *rcols.begin();
          if (lmax < nleft && rmin >= nleft) {
            on.push_back(b.add(P_ON_PAIR, {ks[0], ks[1]}));
            continue;
          }
          if (rmax < nleft && lmin >= nleft) {
            on.push_back(b.add(P_ON_PAIR, {ks[1], ks[0]}));
            continue;
          }
        }
      }
      residual.push_back(c);
    }
    int32_t resid = -1;
    if (!residual.empty()) {
      resid = residual[0];
      for (size_t i = 1; i < residual.size(); ++i)
        resid = mk_fn("and", {resid, residual[i]}, TY_BOOLEAN);
    }
    return {on, resid};
  }

  // ---------------- query / set ops ----------------
  std::pair<int32_t, std::vector<BField>> bind_query(int32_t sel_nid,
                                                     const Scope* outer) {
    // gather clause kids
    std::vector<std::pair<std::string, int32_t>> ctes;
    int32_t setop = -1;
    std::vector<int32_t> order_items;
    bool has_limit = false, has_offset = false;
    int64_t limit = 0, offset = 0;
    for (int32_t k : a.kids(sel_nid)) {
      const AstNode& kn = a.n(k);
      if (kn.kind == K_CTE) ctes.emplace_back(a.s(kn.s0), a.kids(k)[0]);
      else if (kn.kind == K_SETOP) setop = k;
      else if (kn.kind == K_ORDER_ITEM) order_items.push_back(k);
      else if (kn.kind == K_LIMIT_CLAUSE) { has_limit = true; limit = kn.ival; }
      else if (kn.kind == K_OFFSET_CLAUSE) { has_offset = true; offset = kn.ival; }
    }
    std::map<std::string, CtePlan> frame;
    for (auto& [name, sub_nid] : ctes) {
      cte_stack.push_back(frame);
      std::pair<int32_t, std::vector<BField>> sub;
      try {
        sub = bind_query(sub_nid, outer);
      } catch (...) {
        cte_stack.pop_back();
        throw;
      }
      cte_stack.pop_back();
      // wrap in SubqueryAlias named after the CTE
      std::vector<BField> fields = sub.second;
      int32_t aliased = b.add(P_SUBQUERY_ALIAS,
                              concat({sub.first}, mk_fields(fields)), 0, 0, 0.0,
                              b.intern(name));
      frame[name] = {aliased, fields};
    }
    cte_stack.push_back(frame);
    std::pair<BPlan, Scope> out;
    try {
      bool has_values = false;
      for (int32_t k : a.kids(sel_nid))
        if (a.n(k).kind == K_VALUES_ROW) has_values = true;
      if (setop < 0 && !has_values) {
        out = bind_select_core(sel_nid, outer, &order_items);
      } else {
        out = bind_set_expr(sel_nid, outer);
        if (!order_items.empty())
          out.first = bind_order_by_output(out.first, order_items, out.second);
      }
      if (has_limit || has_offset) {
        int32_t plan = b.add(
            P_LIMIT, concat({out.first.id}, mk_fields(out.first.fields)),
            has_limit ? 1 : 0, limit, 0.0,
            b.intern(std::to_string(offset)));
        out.first.id = plan;
      }
    } catch (...) {
      cte_stack.pop_back();
      throw;
    }
    cte_stack.pop_back();
    return {out.first.id, out.first.fields};
  }

  std::pair<BPlan, Scope> bind_set_expr(int32_t sel_nid, const Scope* outer) {
    auto left = bind_select_core(sel_nid, outer, nullptr);
    int32_t setop = -1;
    for (int32_t k : a.kids(sel_nid))
      if (a.n(k).kind == K_SETOP) setop = k;
    if (setop < 0) return left;
    const AstNode& sn = a.n(setop);
    std::string op = upper(a.s(sn.s0));
    bool all = (sn.flags & 1) != 0;
    int32_t rhs = a.kids(setop)[0];
    // rhs with own CTEs / ORDER BY / LIMIT binds as a full query
    bool rhs_full = false;
    for (int32_t k : a.kids(rhs)) {
      int kk = a.n(k).kind;
      if (kk == K_CTE || kk == K_ORDER_ITEM || kk == K_LIMIT_CLAUSE)
        rhs_full = true;
    }
    BPlan right;
    if (rhs_full) {
      auto [rp, rf] = bind_query(rhs, outer);
      right = {rp, rf};
    } else {
      right = bind_set_expr(rhs, outer).first;
    }
    if (left.first.fields.size() != right.fields.size())
      bind_error(op + " requires equal column counts (" +
                 std::to_string(left.first.fields.size()) + " vs " +
                 std::to_string(right.fields.size()) + ")");
    std::vector<BField> fields;
    for (size_t i = 0; i < left.first.fields.size(); ++i) {
      const BField& lf = left.first.fields[i];
      const BField& rf = right.fields[i];
      fields.push_back({lf.name, promote(lf.type, rf.type),
                        lf.nullable || rf.nullable});
    }
    int32_t plan;
    if (op == "UNION") {
      plan = b.add(P_UNION,
                   concat(mk_fields(fields), {left.first.id, right.id}),
                   all ? 1 : 0, (int64_t)fields.size());
      if (!all)
        plan = b.add(P_DISTINCT, concat({plan}, mk_fields(fields)));
    } else if (op == "INTERSECT") {
      plan = b.add(P_INTERSECT,
                   concat({left.first.id, right.id}, mk_fields(fields)),
                   all ? 1 : 0);
    } else {
      plan = b.add(P_EXCEPT,
                   concat({left.first.id, right.id}, mk_fields(fields)),
                   all ? 1 : 0);
    }
    Scope scope;
    scope.parent = outer;
    scope.case_sensitive = case_sensitive;
    for (auto& f : fields) scope.entries.push_back({false, "", f});
    return {{plan, fields}, scope};
  }

  BPlan bind_order_by_output(const BPlan& plan,
                             const std::vector<int32_t>& order_items,
                             const Scope& scope) {
    std::vector<int32_t> keys;
    for (int32_t item : order_items) {
      const AstNode& on = a.n(item);
      int32_t e_nid = a.kids(item)[0];
      bool asc = (on.flags & 1) != 0;
      bool has_nf = (on.flags & 2) != 0;
      bool nf = (on.flags & 4) != 0;
      const AstNode& en = a.n(e_nid);
      if (en.kind == K_LIT_INT) {
        int64_t idx = en.ival - 1;
        if (idx < 0 || idx >= (int64_t)plan.fields.size())
          bind_error("ORDER BY position " + std::to_string(en.ival) +
                     " out of range");
        const BField& f = plan.fields[idx];
        keys.push_back(mk_sortkey(
            mk_colref((int)idx, f.name, f.type, f.nullable), asc, has_nf, nf));
        continue;
      }
      int32_t bound = bind_expr(e_nid, scope);
      keys.push_back(mk_sortkey(bound, asc, has_nf, nf));
    }
    int32_t p = b.add(P_SORT, concat(concat({plan.id}, mk_fields(plan.fields)),
                                     keys),
                      0, (int64_t)plan.fields.size());
    return {p, plan.fields};
  }

  // ---------------- select core ----------------
  struct OrderSpec {
    bool is_pos;
    int pos;          // when is_pos
    int32_t bound;    // when !is_pos (bound expr id)
    bool asc, has_nf, nf;
  };

  std::pair<BPlan, Scope> bind_select_core(
      int32_t sel_nid, const Scope* outer,
      const std::vector<int32_t>* order_items_in) {
    // named windows + select-alias maps are per-SELECT (saved/restored so
    // nested subquery binds don't clobber the outer maps)
    auto prev_windows = named_windows;
    auto* prev_aliases = select_alias_asts;
    std::map<std::string, int32_t> alias_map_storage;
    try {
      auto out = bind_select_core_inner(sel_nid, outer, order_items_in,
                                        alias_map_storage);
      named_windows = prev_windows;
      select_alias_asts = prev_aliases;
      return out;
    } catch (...) {
      named_windows = prev_windows;
      select_alias_asts = prev_aliases;
      throw;
    }
  }

  std::pair<BPlan, Scope> bind_select_core_inner(
      int32_t sel_nid, const Scope* outer,
      const std::vector<int32_t>* order_items_in,
      std::map<std::string, int32_t>& alias_map_storage) {
    const AstNode& sn = a.n(sel_nid);
    bool distinct = (sn.flags & 1) != 0;
    int32_t from = -1, where = -1, having = -1;
    std::vector<int32_t> proj_items, group_items, distribute_items, values_rows;
    std::vector<std::pair<std::string, int32_t>> named_window_items;
    for (int32_t k : a.kids(sel_nid)) {
      const AstNode& kn = a.n(k);
      switch (kn.kind) {
        case K_PROJ_ITEM: proj_items.push_back(k); break;
        case K_FROM_CLAUSE: from = a.kids(k)[0]; break;
        case K_WHERE_CLAUSE: where = a.kids(k)[0]; break;
        case K_GROUP_ITEM: group_items.push_back(a.kids(k)[0]); break;
        case K_HAVING_CLAUSE: having = a.kids(k)[0]; break;
        case K_DISTRIBUTE_ITEM: distribute_items.push_back(a.kids(k)[0]); break;
        case K_VALUES_ROW: values_rows.push_back(k); break;
        case K_NAMED_WINDOW:
          named_window_items.emplace_back(a.s(kn.s0), a.kids(k)[0]);
          break;
        default: break;
      }
    }
    if (!values_rows.empty()) return bind_values(values_rows, outer);

    BPlan plan;
    Scope scope;
    scope.parent = outer;
    scope.case_sensitive = case_sensitive;
    if (from < 0) {
      plan.id = b.add(P_EMPTY, {}, 1);  // produce_one_row
    } else {
      auto got = bind_table_ref(from, outer);
      plan = got.first;
      scope = got.second;
    }
    if (where >= 0) {
      int32_t pred = coerce_bool(bind_expr(where, scope));
      if (contains_kind(pred, E_GROUPING))
        bind_error("GROUPING is not allowed in WHERE");
      plan.id = b.add(P_FILTER,
                      concat(concat({plan.id}, mk_fields(plan.fields)), {pred}),
                      0, (int64_t)plan.fields.size());
    }
    named_windows.clear();
    for (auto& [nm, spec] : named_window_items) named_windows[nm] = spec;
    // select-alias AST map (folded), for GROUPING args / HAVING / ORDER BY
    alias_map_storage.clear();
    for (int32_t item : proj_items) {
      const AstNode& in = a.n(item);
      if (a.has_s(in.s0) && a.n(a.kids(item)[0]).kind != K_WILDCARD)
        alias_map_storage.emplace(fold(a.s(in.s0)), a.kids(item)[0]);
    }
    select_alias_asts = &alias_map_storage;

    // bind select items (wildcards expand against the scope)
    std::vector<int32_t> proj_exprs;
    std::vector<std::string> proj_names;
    for (int32_t item : proj_items) {
      const AstNode& in = a.n(item);
      int32_t e_nid = a.kids(item)[0];
      const AstNode& en = a.n(e_nid);
      if (en.kind == K_WILDCARD) {
        std::string qual;
        bool has_qual = (en.flags & 1) != 0;
        if (has_qual) {
          auto qs = a.kids(e_nid);
          qual = a.s(a.n(qs.back()).s0);
        }
        for (size_t i = 0; i < scope.entries.size(); ++i) {
          const ScopeEntry& e = scope.entries[i];
          if (has_qual && (!e.has_qual || e.qual != qual)) continue;
          proj_exprs.push_back(mk_colref((int)i, e.field.name, e.field.type,
                                         e.field.nullable));
          proj_names.push_back(e.field.name);
        }
        continue;
      }
      int32_t bound = bind_expr(e_nid, scope);
      proj_exprs.push_back(bound);
      if (a.has_s(in.s0))
        proj_names.push_back(a.s(in.s0));
      else if (b.nodes[bound].kind == E_COLREF ||
               b.nodes[bound].kind == E_OUTERREF)
        proj_names.push_back(a_str(b.nodes[bound].s0));
      else
        proj_names.push_back(derive_name(e_nid));
    }

    // HAVING: select aliases substitute when they don't shadow a column
    int32_t having_expr = -1;
    if (having >= 0)
      having_expr = bind_expr(having, scope, /*subst_active=*/true);

    // ORDER BY specs
    std::vector<OrderSpec> order_specs;
    std::vector<int32_t> order_exprs;
    if (order_items_in != nullptr) {
      for (int32_t item : *order_items_in) {
        const AstNode& on = a.n(item);
        int32_t e_nid = a.kids(item)[0];
        bool asc = (on.flags & 1) != 0;
        bool has_nf = (on.flags & 2) != 0;
        bool nf = (on.flags & 4) != 0;
        const AstNode& en = a.n(e_nid);
        if (en.kind == K_LIT_INT) {
          int64_t idx = en.ival - 1;
          if (idx < 0 || idx >= (int64_t)proj_exprs.size())
            bind_error("ORDER BY position " + std::to_string(en.ival) +
                       " out of range");
          order_specs.push_back({true, (int)idx, -1, asc, has_nf, nf});
          continue;
        }
        if (en.kind == K_IDENT && en.nchild == 1) {
          std::string nm = a.s(a.n(a.kids(e_nid)[0]).s0);
          std::vector<int> matches;
          for (size_t i = 0; i < proj_names.size(); ++i)
            if (fold(proj_names[i]) == fold(nm)) matches.push_back((int)i);
          if (matches.size() == 1) {
            order_specs.push_back({true, matches[0], -1, asc, has_nf, nf});
            continue;
          }
        }
        int32_t bound = bind_expr(e_nid, scope, /*subst_active=*/true);
        order_specs.push_back({false, -1, bound, asc, has_nf, nf});
        order_exprs.push_back(bound);
      }
    }
    // GROUP BY alias matching mirrors Python's zip(q.projections,
    // proj_exprs) positionally (including its wildcard misalignment)
    std::vector<std::pair<int32_t, int32_t>> item_expr_zip;
    for (size_t i = 0; i < proj_items.size() && i < proj_exprs.size(); ++i)
      item_expr_zip.emplace_back(proj_items[i], proj_exprs[i]);

    // aggregate context?
    std::vector<int32_t> all_post = proj_exprs;
    all_post.insert(all_post.end(), order_exprs.begin(), order_exprs.end());
    bool any_agg = false;
    for (int32_t e : all_post)
      if (contains_kind(e, E_AGG)) any_agg = true;
    if (having_expr >= 0 && contains_kind(having_expr, E_AGG)) any_agg = true;
    std::vector<BField> post_fields;  // scope after aggregation
    if (!group_items.empty() || any_agg) {
      auto res = bind_aggregate(group_items, plan, scope, all_post,
                                having_expr, proj_items, item_expr_zip);
      plan = res.plan;
      for (size_t i = 0; i < proj_exprs.size(); ++i)
        proj_exprs[i] = res.rewritten[i];
      for (size_t i = 0; i < order_exprs.size(); ++i)
        order_exprs[i] = res.rewritten[proj_exprs.size() + i];
      // re-point order_specs at the rewritten exprs
      {
        size_t oi = 0;
        for (auto& spec : order_specs)
          if (!spec.is_pos) spec.bound = order_exprs[oi++];
      }
      having_expr = res.having;
      post_fields = res.post_fields;
    } else {
      for (int32_t e : all_post)
        if (contains_kind(e, E_GROUPING))
          bind_error("GROUPING requires a GROUP BY context");
      if (having_expr >= 0 && contains_kind(having_expr, E_GROUPING))
        bind_error("GROUPING requires a GROUP BY context");
    }
    if (having_expr >= 0) {
      plan.id = b.add(
          P_FILTER,
          concat(concat({plan.id}, mk_fields(plan.fields)),
                 {coerce_bool(having_expr)}),
          0, (int64_t)plan.fields.size());
      having_expr = -1;
    }

    // window functions (after grouping, SQL semantics)
    std::vector<int32_t> all_exprs = proj_exprs;
    all_exprs.insert(all_exprs.end(), order_exprs.begin(), order_exprs.end());
    bool any_win = false;
    for (int32_t e : all_exprs)
      if (contains_kind(e, E_WINDOW)) any_win = true;
    if (any_win) {
      auto res = bind_window_plan(plan, all_exprs);
      plan = res.first;
      all_exprs = res.second;
      for (size_t i = 0; i < proj_exprs.size(); ++i) proj_exprs[i] = all_exprs[i];
      for (size_t i = 0; i < order_exprs.size(); ++i)
        order_exprs[i] = all_exprs[proj_exprs.size() + i];
      size_t oi = 0;
      for (auto& spec : order_specs)
        if (!spec.is_pos) spec.bound = order_exprs[oi++];
    }

    // final projection fields
    std::vector<BField> fields;
    for (size_t i = 0; i < proj_exprs.size(); ++i)
      fields.push_back({proj_names[i], expr_type(b, proj_exprs[i]),
                        expr_nullable(b, proj_exprs[i])});

    // sort keys: reuse an output column when the order expr matches one
    std::vector<int32_t> sort_keys;
    std::vector<int32_t> extra_exprs;
    for (auto& spec : order_specs) {
      int idx;
      if (spec.is_pos) {
        idx = spec.pos;
      } else {
        idx = -1;
        for (size_t i = 0; i < proj_exprs.size(); ++i)
          if (b.eq(proj_exprs[i], spec.bound)) {
            idx = (int)i;
            break;
          }
        if (idx < 0) {
          if (distinct)
            bind_error(
                "For SELECT DISTINCT, ORDER BY expressions must appear in the "
                "select list");
          idx = (int)(fields.size() + extra_exprs.size());
          extra_exprs.push_back(spec.bound);
        }
      }
      BField f;
      if (idx < (int)fields.size()) {
        f = fields[idx];
      } else {
        int32_t x = extra_exprs[idx - fields.size()];
        f = {"__sort" + std::to_string(idx - fields.size()), expr_type(b, x),
             expr_nullable(b, x)};
      }
      sort_keys.push_back(mk_sortkey(
          mk_colref(idx, f.name, f.type, f.nullable), spec.asc, spec.has_nf,
          spec.nf));
    }

    int32_t out_plan;
    std::vector<BField> out_fields = fields;
    if (!extra_exprs.empty()) {
      std::vector<BField> ext_fields = fields;
      for (size_t j = 0; j < extra_exprs.size(); ++j)
        ext_fields.push_back({"__sort" + std::to_string(j),
                              expr_type(b, extra_exprs[j]),
                              expr_nullable(b, extra_exprs[j])});
      std::vector<int32_t> all2 = proj_exprs;
      all2.insert(all2.end(), extra_exprs.begin(), extra_exprs.end());
      int32_t proj = b.add(
          P_PROJECTION,
          concat(concat({plan.id}, mk_fields(ext_fields)), all2), 0,
          (int64_t)ext_fields.size());
      int32_t sorted = b.add(
          P_SORT,
          concat(concat({proj}, mk_fields(ext_fields)), sort_keys), 0,
          (int64_t)ext_fields.size());
      std::vector<int32_t> final_refs;
      for (size_t i = 0; i < fields.size(); ++i)
        final_refs.push_back(mk_colref((int)i, fields[i].name, fields[i].type,
                                       fields[i].nullable));
      out_plan = b.add(
          P_PROJECTION,
          concat(concat({sorted}, mk_fields(fields)), final_refs), 0,
          (int64_t)fields.size());
    } else {
      out_plan = b.add(
          P_PROJECTION,
          concat(concat({plan.id}, mk_fields(fields)), proj_exprs), 0,
          (int64_t)fields.size());
      if (distinct)
        out_plan = b.add(P_DISTINCT, concat({out_plan}, mk_fields(fields)));
      if (!sort_keys.empty())
        out_plan = b.add(
            P_SORT, concat(concat({out_plan}, mk_fields(fields)), sort_keys),
            0, (int64_t)fields.size());
    }
    Scope scope_out;
    scope_out.parent = outer;
    scope_out.case_sensitive = case_sensitive;
    for (auto& f : fields) scope_out.entries.push_back({false, "", f});
    if (!distribute_items.empty()) {
      std::vector<int32_t> keys;
      for (int32_t d : distribute_items) keys.push_back(bind_expr(d, scope_out));
      out_plan = b.add(
          P_DISTRIBUTE_BY,
          concat(concat({out_plan}, mk_fields(fields)), keys), 0,
          (int64_t)fields.size());
    }
    return {{out_plan, fields}, scope_out};
  }

  std::pair<BPlan, Scope> bind_values(const std::vector<int32_t>& rows,
                                      const Scope* outer) {
    Scope empty;
    empty.case_sensitive = case_sensitive;
    std::vector<std::vector<int32_t>> bound;
    for (int32_t row : rows) {
      std::vector<int32_t> r;
      for (int32_t e : a.kids(row)) r.push_back(bind_expr(e, empty));
      bound.push_back(std::move(r));
    }
    size_t ncols = bound[0].size();
    std::vector<BField> fields;
    for (size_t i = 0; i < ncols; ++i) {
      int t = expr_type(b, bound[0][i]);
      for (size_t rr = 1; rr < bound.size(); ++rr)
        t = promote(t, expr_type(b, bound[rr][i]));
      fields.push_back({"column" + std::to_string(i + 1), t, true});
    }
    std::vector<int32_t> row_nodes;
    for (auto& r : bound) {
      std::vector<int32_t> cells;
      for (size_t i = 0; i < ncols; ++i) cells.push_back(cast_to(r[i], fields[i].type));
      row_nodes.push_back(b.add(P_VALUES_ROW, cells));
    }
    int32_t plan = b.add(P_VALUES, concat(mk_fields(fields), row_nodes), 0,
                         (int64_t)fields.size());
    Scope scope;
    scope.case_sensitive = case_sensitive;
    for (auto& f : fields) scope.entries.push_back({false, "", f});
    (void)outer;
    return {{plan, fields}, scope};
  }

  // ---------------- aggregate ----------------
  struct AggResult {
    BPlan plan;
    std::vector<int32_t> rewritten;
    int32_t having;
    std::vector<BField> post_fields;
  };

  AggResult bind_aggregate(
      const std::vector<int32_t>& group_items_in, const BPlan& input,
      const Scope& scope, const std::vector<int32_t>& post_exprs_in,
      int32_t having_expr, const std::vector<int32_t>& proj_items,
      const std::vector<std::pair<int32_t, int32_t>>& item_expr_zip) {
    // split GROUPING SETS / ROLLUP / CUBE from plain group items
    std::vector<int32_t> plain_asts;
    int32_t construct = -1;
    for (int32_t ge : group_items_in) {
      int k = a.n(ge).kind;
      if (k == K_GROUPING_SETS || k == K_ROLLUP || k == K_CUBE)
        construct = ge;
      else
        plain_asts.push_back(ge);
    }
    std::vector<int32_t> group_asts = plain_asts;
    std::vector<std::vector<int>> sets;
    bool has_sets = false;
    if (construct >= 0) {
      has_sets = true;
      int n_plain = (int)plain_asts.size();
      std::vector<int32_t> extra;
      std::vector<std::vector<int>> raw_sets;
      int ck = a.n(construct).kind;
      if (ck == K_ROLLUP) {
        for (int32_t e : a.kids(construct)) extra.push_back(e);
        for (int k = (int)extra.size(); k >= 0; --k) {
          std::vector<int> s;
          for (int i = 0; i < k; ++i) s.push_back(i);
          raw_sets.push_back(s);
        }
      } else if (ck == K_CUBE) {
        for (int32_t e : a.kids(construct)) extra.push_back(e);
        int m = (int)extra.size();
        for (int mask = (1 << m) - 1; mask >= 0; --mask) {
          std::vector<int> s;
          for (int i = 0; i < m; ++i)
            if (mask & (1 << i)) s.push_back(i);
          raw_sets.push_back(s);
        }
      } else {  // GROUPING SETS: dedupe expressions structurally via binding
        std::vector<int32_t> bound_cache;  // bound ids, parallel to extra
        for (int32_t sn2 : a.kids(construct)) {
          std::vector<int> idxs;
          for (int32_t e : a.kids(sn2)) {
            int32_t bnd = bind_expr(e, scope);
            int found = -1;
            for (size_t i = 0; i < bound_cache.size(); ++i)
              if (b.eq(bound_cache[i], bnd)) {
                found = (int)i;
                break;
              }
            if (found < 0) {
              found = (int)extra.size();
              bound_cache.push_back(bnd);
              extra.push_back(e);
            }
            idxs.push_back(found);
          }
          raw_sets.push_back(idxs);
        }
      }
      group_asts = plain_asts;
      group_asts.insert(group_asts.end(), extra.begin(), extra.end());
      for (auto& s : raw_sets) {
        std::vector<int> full;
        for (int i = 0; i < n_plain; ++i) full.push_back(i);
        for (int i : s) full.push_back(n_plain + i);
        sets.push_back(full);
      }
    }

    // bind group exprs (positions / select aliases / plain binds)
    std::vector<int32_t> group_exprs;
    for (int32_t ge : group_asts) {
      const AstNode& gn = a.n(ge);
      if (gn.kind == K_LIT_INT) {
        int64_t idx = gn.ival - 1;
        if (idx < 0 || idx >= (int64_t)post_exprs_in.size())
          bind_error("GROUP BY position " + std::to_string(gn.ival) +
                     " out of range");
        group_exprs.push_back(post_exprs_in[idx]);
        continue;
      }
      if (gn.kind == K_IDENT && gn.nchild == 1) {
        std::string nm = a.s(a.n(a.kids(ge)[0]).s0);
        bool resolved = scope.resolve({nm}).has_value();
        if (!resolved) {
          bool matched = false;
          for (auto& [item, bound] : item_expr_zip) {
            const AstNode& in = a.n(item);
            if (a.has_s(in.s0) && a.s(in.s0) == nm) {
              group_exprs.push_back(bound);
              matched = true;
              break;
            }
          }
          if (matched) continue;
        }
      }
      group_exprs.push_back(bind_expr(ge, scope));
    }

    // collect aggregates (dedup by equality, discovery order)
    std::vector<int32_t> agg_calls;
    auto collect = [&](int32_t e) {
      std::vector<int32_t> found;
      collect_kind(e, E_AGG, found);
      for (int32_t x : found) {
        bool seen = false;
        for (int32_t y : agg_calls)
          if (b.eq(x, y)) {
            seen = true;
            break;
          }
        if (!seen) agg_calls.push_back(x);
      }
    };
    for (int32_t e : post_exprs_in) collect(e);
    if (having_expr >= 0) collect(having_expr);

    std::vector<BField> group_fields;
    for (size_t i = 0; i < group_exprs.size(); ++i)
      group_fields.push_back({derive_group_name(group_exprs[i], (int)i),
                              expr_type(b, group_exprs[i]),
                              expr_nullable(b, group_exprs[i])});
    std::vector<BField> agg_fields;
    for (size_t i = 0; i < agg_calls.size(); ++i)
      agg_fields.push_back({"__agg" + std::to_string(i),
                            expr_type(b, agg_calls[i]), true});

    // GROUPING(...) markers
    std::vector<int32_t> grouping_exprs;
    auto collect_grouping = [&](int32_t e) {
      std::vector<int32_t> found;
      collect_kind(e, E_GROUPING, found);
      for (int32_t x : found) {
        bool seen = false;
        for (int32_t y : grouping_exprs)
          if (b.eq(x, y)) {
            seen = true;
            break;
          }
        if (!seen) grouping_exprs.push_back(x);
      }
    };
    for (int32_t e : post_exprs_in) collect_grouping(e);
    if (having_expr >= 0) collect_grouping(having_expr);
    for (int32_t ac : agg_calls)
      for (int32_t kid : b.kids(ac))
        if (contains_kind(kid, E_GROUPING))
          bind_error("GROUPING cannot appear inside an aggregate");
    for (int32_t ge : group_exprs)
      if (contains_kind(ge, E_GROUPING))
        bind_error("GROUPING cannot appear in GROUP BY");

    auto grouping_value = [&](int32_t g, const std::vector<int>& s) -> int64_t {
      int64_t val = 0;
      for (int32_t arg : b.kids(g)) {
        int gi = -1;
        for (size_t i = 0; i < group_exprs.size(); ++i)
          if (b.eq(group_exprs[i], arg)) {
            gi = (int)i;
            break;
          }
        if (gi < 0)
          bind_error("GROUPING argument must be a grouping expression");
        bool in_set = std::find(s.begin(), s.end(), gi) != s.end();
        val = (val << 1) | (in_set ? 0 : 1);
      }
      return val;
    };

    std::vector<BField> out_fields;
    // grouping marker -> replacement expr id
    std::vector<std::pair<int32_t, int32_t>> grouping_map;
    int32_t agg_plan;
    if (!has_sets) {
      out_fields = group_fields;
      out_fields.insert(out_fields.end(), agg_fields.begin(), agg_fields.end());
      std::vector<int> all_set;
      for (size_t i = 0; i < group_exprs.size(); ++i) all_set.push_back((int)i);
      for (int32_t g : grouping_exprs) {
        grouping_value(g, all_set);  // validate args
        grouping_map.emplace_back(g, mk_lit_int(0, TY_INTEGER));
      }
      std::vector<int32_t> kids2 = {input.id};
      for (auto fid : mk_fields(out_fields)) kids2.push_back(fid);
      for (int32_t ge : group_exprs) kids2.push_back(ge);
      for (int32_t ac : agg_calls) kids2.push_back(ac);
      agg_plan = b.add(P_AGGREGATE, kids2, (int32_t)group_exprs.size(),
                       (int64_t)out_fields.size());
    } else {
      // union of one aggregate per grouping set, NULL-padded
      for (auto& f : group_fields)
        out_fields.push_back({f.name, f.type, true});
      out_fields.insert(out_fields.end(), agg_fields.begin(), agg_fields.end());
      for (size_t j = 0; j < grouping_exprs.size(); ++j)
        out_fields.push_back({"__grouping" + std::to_string(j), TY_INTEGER,
                              false});
      std::vector<int32_t> branches;
      for (auto& s : sets) {
        std::vector<int32_t> sub_groups;
        std::vector<BField> sub_fields;
        for (int gi : s) {
          sub_groups.push_back(group_exprs[gi]);
          sub_fields.push_back(group_fields[gi]);
        }
        sub_fields.insert(sub_fields.end(), agg_fields.begin(),
                          agg_fields.end());
        std::vector<int32_t> akids = {input.id};
        for (auto fid : mk_fields(sub_fields)) akids.push_back(fid);
        for (int32_t gexp : sub_groups) akids.push_back(gexp);
        for (int32_t ac : agg_calls) akids.push_back(ac);
        int32_t sub_agg = b.add(P_AGGREGATE, akids, (int32_t)sub_groups.size(),
                                (int64_t)sub_fields.size());
        std::vector<int32_t> proj;
        for (size_t gi = 0; gi < group_fields.size(); ++gi) {
          auto pos_it = std::find(s.begin(), s.end(), (int)gi);
          if (pos_it != s.end()) {
            int pos = (int)(pos_it - s.begin());
            proj.push_back(mk_colref(pos, group_fields[gi].name,
                                     group_fields[gi].type, true));
          } else {
            proj.push_back(mk_cast(mk_lit_null(), group_fields[gi].type));
          }
        }
        for (size_t ai = 0; ai < agg_fields.size(); ++ai)
          proj.push_back(mk_colref((int)(s.size() + ai), agg_fields[ai].name,
                                   agg_fields[ai].type, true));
        for (int32_t g : grouping_exprs)
          proj.push_back(mk_lit_int(grouping_value(g, s), TY_INTEGER));
        branches.push_back(b.add(
            P_PROJECTION,
            concat(concat({sub_agg}, mk_fields(out_fields)), proj), 0,
            (int64_t)out_fields.size()));
      }
      agg_plan = b.add(P_UNION, concat(mk_fields(out_fields), branches), 1,
                       (int64_t)out_fields.size());
      int base = (int)(group_fields.size() + agg_fields.size());
      for (size_t j = 0; j < grouping_exprs.size(); ++j)
        grouping_map.emplace_back(
            grouping_exprs[j],
            mk_colref(base + (int)j, "__grouping" + std::to_string(j),
                      TY_INTEGER, false));
    }

    // rewrite post-agg expressions: group/agg subtrees -> column refs
    std::vector<std::pair<int32_t, int32_t>> mapping;
    for (size_t i = 0; i < group_exprs.size(); ++i) {
      bool dup = false;
      for (auto& [k, v] : mapping)
        if (b.eq(k, group_exprs[i])) {
          dup = true;
          break;
        }
      if (!dup)
        mapping.emplace_back(
            group_exprs[i],
            mk_colref((int)i, group_fields[i].name,
                      expr_type(b, group_exprs[i]),
                      expr_nullable(b, group_exprs[i])));
    }
    for (size_t i = 0; i < agg_calls.size(); ++i) {
      // agg mapping overrides any equal earlier entry (dict assignment)
      bool replaced = false;
      int32_t ref = mk_colref((int)(group_exprs.size() + i),
                              agg_fields[i].name, expr_type(b, agg_calls[i]),
                              true);
      for (auto& kv : mapping)
        if (b.eq(kv.first, agg_calls[i])) {
          kv.second = ref;
          replaced = true;
          break;
        }
      if (!replaced) mapping.emplace_back(agg_calls[i], ref);
    }

    std::function<int32_t(int32_t)> rewrite = [&](int32_t e) -> int32_t {
      if (b.nodes[e].kind == E_GROUPING) {
        for (auto& [k, v] : grouping_map)
          if (b.eq(k, e)) return v;
        bind_error("GROUPING argument must be a grouping expression");
      }
      for (auto& [k, v] : mapping)
        if (b.eq(k, e)) return v;
      auto kids2 = expr_children(e);
      if (kids2.empty()) {
        if (b.nodes[e].kind == E_COLREF || b.nodes[e].kind == E_OUTERREF)
          bind_error("Column '" + a_str(b.nodes[e].s0) +
                     "' must appear in the GROUP BY clause or be used in an "
                     "aggregate function");
        return e;
      }
      std::vector<int32_t> nk;
      for (int32_t k : kids2) nk.push_back(rewrite(k));
      return with_expr_children(e, nk);
    };

    AggResult res;
    res.plan = {agg_plan, out_fields};
    for (int32_t e : post_exprs_in) res.rewritten.push_back(rewrite(e));
    res.having = having_expr >= 0 ? rewrite(having_expr) : -1;
    res.post_fields = out_fields;
    (void)proj_items;
    return res;
  }

  // ---------------- window plan ----------------
  std::pair<BPlan, std::vector<int32_t>> bind_window_plan(
      const BPlan& input, const std::vector<int32_t>& exprs) {
    std::vector<int32_t> win_calls;
    for (int32_t e : exprs) {
      std::vector<int32_t> found;
      collect_kind(e, E_WINDOW, found);
      for (int32_t x : found) {
        bool seen = false;
        for (int32_t y : win_calls)
          if (b.eq(x, y)) {
            seen = true;
            break;
          }
        if (!seen) win_calls.push_back(x);
      }
    }
    int base = (int)input.fields.size();
    std::vector<BField> fields = input.fields;
    for (size_t i = 0; i < win_calls.size(); ++i)
      fields.push_back({"__win" + std::to_string(i),
                        expr_type(b, win_calls[i]), true});
    std::vector<int32_t> kids2 = {input.id};
    for (auto fid : mk_fields(fields)) kids2.push_back(fid);
    for (int32_t w : win_calls) kids2.push_back(w);
    int32_t win_plan = b.add(P_WINDOW, kids2, 0, (int64_t)fields.size());

    std::function<int32_t(int32_t)> rewrite = [&](int32_t e) -> int32_t {
      for (size_t i = 0; i < win_calls.size(); ++i)
        if (b.eq(win_calls[i], e))
          return mk_colref(base + (int)i, "__win" + std::to_string(i),
                           expr_type(b, e), true);
      auto kids3 = expr_children(e);
      if (kids3.empty()) return e;
      std::vector<int32_t> nk;
      for (int32_t k : kids3) nk.push_back(rewrite(k));
      return with_expr_children(e, nk);
    };
    std::vector<int32_t> out;
    for (int32_t e : exprs) out.push_back(rewrite(e));
    return {{win_plan, fields}, out};
  }

  // ---------------- statements ----------------
  // copy an AST kwargs subtree (K_KWARGS/K_KV/K_LIT_*/K_KWLIST) into the
  // plan buffer (P_KWARGS/P_KV/P_KW_*)
  int32_t copy_kwargs(int32_t nid) {
    const AstNode& n = a.n(nid);
    switch (n.kind) {
      case K_KWARGS: {
        std::vector<int32_t> kvs;
        for (int32_t kv : a.kids(nid)) {
          const AstNode& kn = a.n(kv);
          kvs.push_back(b.add(P_KV, {copy_kwargs(a.kids(kv)[0])}, 0, 0, 0.0,
                              b.intern(a.s(kn.s0))));
        }
        return b.add(P_KWARGS, kvs);
      }
      case K_KWLIST: {
        std::vector<int32_t> items;
        for (int32_t k : a.kids(nid)) items.push_back(copy_kwargs(k));
        return b.add(P_KWLIST, items);
      }
      case K_LIT_STR: return b.add(P_KW_STR, {}, 0, 0, 0.0, b.intern(a.s(n.s0)));
      case K_LIT_INT: return b.add(P_KW_INT, {}, 0, n.ival);
      case K_LIT_FLOAT: return b.add(P_KW_FLOAT, {}, 0, 0, n.dval);
      case K_LIT_BOOL: return b.add(P_KW_BOOL, {}, 0, n.ival);
      case K_LIT_NULL: return b.add(P_KW_NULL, {});
    }
    throw Unsupported{};
  }

  std::vector<int32_t> mk_qname_kids(int32_t nid) {
    std::vector<int32_t> parts;
    for (int32_t p : a.kids(nid))
      parts.push_back(b.add(P_PART, {}, 0, 0, 0.0, b.intern(a.s(a.n(p).s0))));
    return parts;
  }

  int32_t bind_statement(int32_t sid) {
    const AstNode& n = a.n(sid);
    auto ks = a.kids(sid);
    bool ine = (n.flags & 1) != 0;
    bool orr = (n.flags & 2) != 0;
    int32_t st_flags = (ine ? 1 : 0) | (orr ? 2 : 0);
    switch (n.kind) {
      case K_QUERY_STMT: {
        auto [plan, fields] = bind_query(ks[0], nullptr);
        (void)fields;
        return plan;
      }
      case K_EXPLAIN_STMT: {
        auto [plan, fields] = bind_query(ks[0], nullptr);
        (void)fields;
        // EXPLAIN LINT (flag bit 2) returns verifier findings in a LINT
        // column; EXPLAIN ESTIMATE (bit 4) cost/memory intervals in an
        // ESTIMATE column; FORMAT JSON (bit 8) rides through for the
        // Chrome-trace variant of ANALYZE
        std::vector<BField> efields{
            {(n.flags & 2) ? "LINT" : (n.flags & 4) ? "ESTIMATE" : "PLAN",
             TY_VARCHAR, true}};
        return b.add(P_EXPLAIN, concat({plan}, mk_fields(efields)),
                     ((n.flags & 1) ? 1 : 0) | ((n.flags & 2) ? 2 : 0) |
                         ((n.flags & 4) ? 4 : 0) | ((n.flags & 8) ? 8 : 0),
                     1);
      }
      case K_CREATE_TABLE_WITH:
        return b.add(P_CREATE_TABLE,
                     concat(mk_qname_kids(ks[0]), {copy_kwargs(ks[1])}),
                     st_flags);
      case K_CREATE_TABLE_AS: {
        auto [plan, fields] = bind_query(ks[1], nullptr);
        (void)fields;
        int32_t fl = st_flags | ((n.flags & 4) ? 4 : 0);
        return b.add(P_CREATE_MEMORY_TABLE,
                     concat(mk_qname_kids(ks[0]), {plan}), fl,
                     (int64_t)a.n(ks[0]).nchild);
      }
      case K_DROP_TABLE:
        return b.add(P_DROP_TABLE, mk_qname_kids(ks[0]), (n.flags & 1) ? 1 : 0);
      case K_CREATE_SCHEMA:
        return b.add(P_CREATE_SCHEMA, {}, st_flags, 0, 0.0,
                     b.intern(a.s(n.s0)));
      case K_DROP_SCHEMA:
        return b.add(P_DROP_SCHEMA, {}, (n.flags & 1) ? 1 : 0, 0, 0.0,
                     b.intern(a.s(n.s0)));
      case K_USE_SCHEMA:
        return b.add(P_USE_SCHEMA, {}, 0, 0, 0.0, b.intern(a.s(n.s0)));
      case K_ALTER_SCHEMA:
        return b.add(P_ALTER_SCHEMA, {}, 0, 0, 0.0, b.intern(a.s(n.s0)),
                     b.intern(a.s(n.s1)));
      case K_ALTER_TABLE:
        return b.add(P_ALTER_TABLE, mk_qname_kids(ks[0]),
                     (n.flags & 1) ? 1 : 0, 0, 0.0, b.intern(a.s(n.s0)));
      case K_SHOW_SCHEMAS: {
        std::vector<BField> f{{"Schema", TY_VARCHAR, true}};
        return b.add(P_SHOW_SCHEMAS, mk_fields(f),
                     a.has_s(n.s0) ? 1 : 0, 0, 0.0,
                     a.has_s(n.s0) ? b.intern(a.s(n.s0)) : -1);
      }
      case K_SHOW_TABLES: {
        std::vector<BField> f{{"Table", TY_VARCHAR, true}};
        return b.add(P_SHOW_TABLES, mk_fields(f), a.has_s(n.s0) ? 1 : 0, 0,
                     0.0, a.has_s(n.s0) ? b.intern(a.s(n.s0)) : -1);
      }
      case K_SHOW_COLUMNS: {
        std::vector<BField> f{{"Column", TY_VARCHAR, true},
                              {"Type", TY_VARCHAR, true},
                              {"Extra", TY_VARCHAR, true},
                              {"Comment", TY_VARCHAR, true}};
        return b.add(P_SHOW_COLUMNS,
                     concat(mk_fields(f), mk_qname_kids(ks[0])), 0, 4);
      }
      case K_SHOW_MODELS: {
        std::vector<BField> f{{"Model", TY_VARCHAR, true}};
        return b.add(P_SHOW_MODELS, mk_fields(f), a.has_s(n.s0) ? 1 : 0, 0,
                     0.0, a.has_s(n.s0) ? b.intern(a.s(n.s0)) : -1);
      }
      case K_SHOW_METRICS: {
        std::vector<BField> f{{"Metric", TY_VARCHAR, true},
                              {"Value", TY_VARCHAR, true}};
        return b.add(P_SHOW_METRICS, mk_fields(f), a.has_s(n.s0) ? 1 : 0, 0,
                     0.0, a.has_s(n.s0) ? b.intern(a.s(n.s0)) : -1);
      }
      case K_SHOW_PROFILES: {
        std::vector<BField> f{{"Fingerprint", TY_VARCHAR, true},
                              {"Metric", TY_VARCHAR, true},
                              {"Value", TY_VARCHAR, true}};
        return b.add(P_SHOW_PROFILES, mk_fields(f), a.has_s(n.s0) ? 1 : 0, 0,
                     0.0, a.has_s(n.s0) ? b.intern(a.s(n.s0)) : -1);
      }
      case K_SHOW_QUERIES: {
        std::vector<BField> f{{"Qid", TY_VARCHAR, true},
                              {"Field", TY_VARCHAR, true},
                              {"Value", TY_VARCHAR, true}};
        return b.add(P_SHOW_QUERIES, mk_fields(f), a.has_s(n.s0) ? 1 : 0, 0,
                     0.0, a.has_s(n.s0) ? b.intern(a.s(n.s0)) : -1);
      }
      case K_CANCEL_QUERY: {
        std::vector<BField> f{{"Qid", TY_VARCHAR, true},
                              {"Cancelled", TY_VARCHAR, true}};
        return b.add(P_CANCEL_QUERY, mk_fields(f), 0, 0, 0.0,
                     b.intern(a.s(n.s0)));
      }
      case K_ANALYZE_TABLE: {
        std::vector<int32_t> cols;
        for (size_t i = 1; i < ks.size(); ++i)
          cols.push_back(b.add(P_PART, {}, 1, 0, 0.0,
                               b.intern(a.s(a.n(ks[i]).s0))));
        // table parts have flags 0, column parts flags 1
        return b.add(P_ANALYZE_TABLE, concat(mk_qname_kids(ks[0]), cols));
      }
      case K_CREATE_MODEL: {
        auto [plan, fields] = bind_query(ks[2], nullptr);
        (void)fields;
        return b.add(P_CREATE_MODEL,
                     concat(mk_qname_kids(ks[0]),
                            {copy_kwargs(ks[1]), plan}),
                     st_flags, (int64_t)a.n(ks[0]).nchild);
      }
      case K_DROP_MODEL:
        return b.add(P_DROP_MODEL, mk_qname_kids(ks[0]), (n.flags & 1) ? 1 : 0);
      case K_DESCRIBE_MODEL: {
        std::vector<BField> f{{"Params", TY_VARCHAR, true},
                              {"Value", TY_VARCHAR, true}};
        return b.add(P_DESCRIBE_MODEL,
                     concat(mk_fields(f), mk_qname_kids(ks[0])), 0, 2);
      }
      case K_EXPORT_MODEL:
        return b.add(P_EXPORT_MODEL,
                     concat(mk_qname_kids(ks[0]), {copy_kwargs(ks[1])}), 0,
                     (int64_t)a.n(ks[0]).nchild);
      case K_CREATE_EXPERIMENT: {
        auto [plan, fields] = bind_query(ks[2], nullptr);
        (void)fields;
        return b.add(P_CREATE_EXPERIMENT,
                     concat(mk_qname_kids(ks[0]),
                            {copy_kwargs(ks[1]), plan}),
                     st_flags, (int64_t)a.n(ks[0]).nchild);
      }
    }
    throw Unsupported{};
  }
};


// ===========================================================================
// Native optimizer: the structural rule pipeline in C++ (parity:
// src/sql/optimizer.rs:53-98 — the reference's rules run compiled in
// DataFusion; this ports dask_sql_tpu/planner/optimizer/rules.py's core
// 15-slot loop.  Join reordering, dynamic partition pruning and
// embedded-subquery passes stay in Python (they read statistics/data).
// ===========================================================================

class Optimizer {
 public:
  explicit Optimizer(PBuilder& b, bool predicate_pushdown)
      : b(b), predicate_pushdown(predicate_pushdown) {}

  PBuilder& b;
  bool predicate_pushdown;

  std::string str_of(int32_t sid) const {
    return sid < 0 ? std::string() : b.strings[sid];
  }

  bool is_plan_kind(int32_t k) const {
    return (k >= P_TABLESCAN && k <= P_PREDICT_MODEL);
  }

  // ---------------- node accessors ----------------
  std::vector<int32_t> inputs_of(int32_t id) const {
    const PNode n = b.nodes[id];
    auto ks = b.kids(id);
    switch (n.kind) {
      case P_PROJECTION: case P_FILTER: case P_AGGREGATE: case P_WINDOW:
      case P_SORT: case P_LIMIT: case P_DISTINCT: case P_SUBQUERY_ALIAS:
      case P_SAMPLE: case P_DISTRIBUTE_BY: case P_EXPLAIN:
        return {ks[0]};
      case P_JOIN: case P_CROSSJOIN: case P_INTERSECT: case P_EXCEPT:
        return {ks[0], ks[1]};
      case P_UNION: {
        std::vector<int32_t> out;
        for (size_t i = n.ival; i < ks.size(); ++i) out.push_back(ks[i]);
        return out;
      }
      case P_CREATE_MEMORY_TABLE: case P_CREATE_MODEL:
      case P_CREATE_EXPERIMENT: {
        // input plan is a kid but these are handled by the default
        // child-rewrite only; find the plan-kind kid
        std::vector<int32_t> out;
        for (int32_t k : ks)
          if (is_plan_kind(b.nodes[k].kind)) out.push_back(k);
        return out;
      }
      case P_PREDICT_MODEL:
        return {ks[0]};
      default:
        return {};
    }
  }

  // schema field node ids of a plan node
  std::vector<int32_t> schema_of(int32_t id) const {
    const PNode n = b.nodes[id];
    auto ks = b.kids(id);
    std::vector<int32_t> out;
    auto take_fields = [&](size_t from, size_t count) {
      for (size_t i = from; i < from + count && i < ks.size(); ++i)
        out.push_back(ks[i]);
    };
    switch (n.kind) {
      case P_TABLESCAN:
        if (n.flags & 3) take_fields(0, (size_t)n.ival);
        else for (int32_t k : ks) out.push_back(k);
        break;
      case P_PROJECTION: case P_FILTER: case P_AGGREGATE: case P_WINDOW:
      case P_SORT: case P_DISTRIBUTE_BY: case P_EXPLAIN:
      case P_PREDICT_MODEL:
        take_fields(1, (size_t)n.ival);
        break;
      case P_JOIN:
        take_fields(2, (size_t)n.ival);
        break;
      case P_CROSSJOIN: case P_INTERSECT: case P_EXCEPT:
        for (size_t i = 2; i < ks.size(); ++i) out.push_back(ks[i]);
        break;
      case P_LIMIT: case P_DISTINCT: case P_SUBQUERY_ALIAS: case P_SAMPLE:
        for (size_t i = 1; i < ks.size(); ++i) out.push_back(ks[i]);
        break;
      case P_UNION:
        take_fields(0, (size_t)n.ival);
        break;
      case P_VALUES:
        take_fields(0, (size_t)n.ival);
        break;
      case P_EMPTY:
        for (int32_t k : ks) out.push_back(k);
        break;
      default:
        break;
    }
    return out;
  }

  int schema_width(int32_t id) const { return (int)schema_of(id).size(); }

  // rebuild a node with new inputs (payload preserved)
  int32_t with_inputs(int32_t id, const std::vector<int32_t>& ni) const {
    const PNode n = b.nodes[id];
    auto ks = b.kids(id);
    std::vector<int32_t> nk = ks;
    switch (n.kind) {
      case P_PROJECTION: case P_FILTER: case P_AGGREGATE: case P_WINDOW:
      case P_SORT: case P_LIMIT: case P_DISTINCT: case P_SUBQUERY_ALIAS:
      case P_SAMPLE: case P_DISTRIBUTE_BY: case P_EXPLAIN:
      case P_PREDICT_MODEL:
        nk[0] = ni[0];
        break;
      case P_JOIN: case P_CROSSJOIN: case P_INTERSECT: case P_EXCEPT:
        nk[0] = ni[0];
        nk[1] = ni[1];
        break;
      case P_UNION: {
        for (size_t i = 0; i < ni.size(); ++i) nk[n.ival + i] = ni[i];
        break;
      }
      case P_CREATE_MEMORY_TABLE: case P_CREATE_MODEL:
      case P_CREATE_EXPERIMENT: {
        size_t j = 0;
        for (size_t i = 0; i < nk.size(); ++i)
          if (is_plan_kind(b.nodes[nk[i]].kind)) nk[i] = ni[j++];
        break;
      }
      default:
        return id;
    }
    return b.add(n.kind, nk, n.flags, n.ival, n.dval, n.s0, n.s1);
  }

  // ---------------- expr helpers (PBuilder-side twins of the binder's) ----
  std::vector<int32_t> expr_children(int32_t e) const {
    const PNode n = b.nodes[e];
    std::vector<int32_t> ks = b.kids(e);
    switch (n.kind) {
      case E_COLREF: case E_OUTERREF: case E_LITERAL:
      case E_EXISTS: case E_SCALARSUBQ:
        return {};
      case E_SCALARFN: case E_UDF: case E_GROUPING: case E_CAST:
      case E_CASE: case E_INLIST: case E_AGG:
        return ks;
      case E_INSUBQ:
        return {ks[0]};
      case E_WINDOW: {
        std::vector<int32_t> out(ks.begin(), ks.end() - 1);
        int32_t spec = ks.back();
        auto sk = b.kids(spec);
        int npart = (int)b.nodes[spec].ival;
        for (int i = 0; i < npart; ++i) out.push_back(sk[i]);
        for (size_t i = npart; i < sk.size(); ++i)
          if (b.nodes[sk[i]].kind == P_SORTKEY)
            out.push_back(b.kids(sk[i])[0]);
        return out;
      }
    }
    return {};
  }

  int32_t with_expr_children(int32_t e, const std::vector<int32_t>& ch) const {
    const PNode n = b.nodes[e];
    switch (n.kind) {
      case E_COLREF: case E_OUTERREF: case E_LITERAL:
      case E_EXISTS: case E_SCALARSUBQ:
        return e;
      case E_SCALARFN: case E_UDF: case E_GROUPING: case E_CAST:
      case E_CASE: case E_INLIST: case E_AGG:
        return b.add(n.kind, ch, n.flags, n.ival, n.dval, n.s0, n.s1);
      case E_INSUBQ: {
        auto ks = b.kids(e);
        return b.add(n.kind, {ch[0], ks[1]}, n.flags, n.ival, n.dval, n.s0,
                     n.s1);
      }
      case E_WINDOW: {
        auto ks = b.kids(e);
        int32_t spec = ks.back();
        const PNode sn = b.nodes[spec];
        auto sk = b.kids(spec);
        int npart = (int)sn.ival;
        int nargs = (int)ks.size() - 1;
        std::vector<int32_t> nsk;
        size_t ci = nargs;
        for (int i = 0; i < npart; ++i) nsk.push_back(ch[ci++]);
        for (size_t i = npart; i < sk.size(); ++i) {
          if (b.nodes[sk[i]].kind == P_SORTKEY) {
            const PNode kn = b.nodes[sk[i]];
            nsk.push_back(b.add(P_SORTKEY, {ch[ci++]}, kn.flags));
          } else {
            nsk.push_back(sk[i]);
          }
        }
        int32_t nspec = b.add(P_WINSPEC, nsk, sn.flags, sn.ival, sn.dval,
                              sn.s0, sn.s1);
        std::vector<int32_t> nks(ch.begin(), ch.begin() + nargs);
        nks.push_back(nspec);
        return b.add(n.kind, nks, n.flags, n.ival, n.dval, n.s0, n.s1);
      }
    }
    return e;
  }

  int32_t transform_expr(int32_t e,
                         const std::function<int32_t(int32_t)>& fn) const {
    auto ks = expr_children(e);
    if (!ks.empty()) {
      std::vector<int32_t> nk;
      bool changed = false;
      for (int32_t k : ks) {
        int32_t t = transform_expr(k, fn);
        changed |= (t != k);
        nk.push_back(t);
      }
      if (changed) e = with_expr_children(e, nk);
    }
    return fn(e);
  }

  void walk_expr(int32_t e, const std::function<void(int32_t)>& fn) const {
    fn(e);
    for (int32_t k : expr_children(e)) walk_expr(k, fn);
  }

  bool expr_contains(int32_t e, const std::function<bool(const PNode&)>& pred) const {
    bool found = false;
    walk_expr(e, [&](int32_t x) { found = found || pred(b.nodes[x]); });
    return found;
  }

  void referenced_cols(int32_t e, std::set<int64_t>& out) const {
    walk_expr(e, [&](int32_t x) {
      const PNode n = b.nodes[x];
      if (n.kind == E_COLREF || n.kind == E_OUTERREF) out.insert(n.ival);
    });
  }

  int32_t remap_cols(int32_t e, const std::map<int64_t, int64_t>& m) const {
    return transform_expr(e, [&](int32_t x) -> int32_t {
      const PNode n = b.nodes[x];
      if (n.kind == E_COLREF || n.kind == E_OUTERREF) {
        auto it = m.find(n.ival);
        int64_t ni = it == m.end() ? n.ival : it->second;
        if (ni == n.ival) return x;
        return b.add(n.kind, {}, n.flags, ni, n.dval, n.s0, n.s1);
      }
      return x;
    });
  }

  int32_t shift_cols(int32_t e, int64_t delta) const {
    if (delta == 0) return e;
    return transform_expr(e, [&](int32_t x) -> int32_t {
      const PNode n = b.nodes[x];
      if (n.kind == E_COLREF || n.kind == E_OUTERREF)
        return b.add(n.kind, {}, n.flags, n.ival + delta, n.dval, n.s0, n.s1);
      return x;
    });
  }

  void conjuncts_of(int32_t e, std::vector<int32_t>& out) const {
    const PNode n = b.nodes[e];
    if (n.kind == E_SCALARFN && str_of(n.s0) == "and") {
      for (int32_t k : b.kids(e)) conjuncts_of(k, out);
      return;
    }
    out.push_back(e);
  }

  int32_t conjoin(const std::vector<int32_t>& parts) const {
    if (parts.empty()) return -1;
    int32_t out = parts[0];
    for (size_t i = 1; i < parts.size(); ++i)
      out = b.add(E_SCALARFN, {out, parts[i]}, ty_flags(TY_BOOLEAN), 0, 0.0,
                  b.intern_mut("and"));
    return out;
  }

  void disjuncts_of(int32_t e, std::vector<int32_t>& out) const {
    const PNode n = b.nodes[e];
    if (n.kind == E_SCALARFN && str_of(n.s0) == "or") {
      for (int32_t k : b.kids(e)) disjuncts_of(k, out);
      return;
    }
    out.push_back(e);
  }

  int32_t disjoin(const std::vector<int32_t>& parts) const {
    int32_t out = parts[0];
    for (size_t i = 1; i < parts.size(); ++i)
      out = b.add(E_SCALARFN, {out, parts[i]}, ty_flags(TY_BOOLEAN), 0, 0.0,
                  b.intern_mut("or"));
    return out;
  }

  bool is_fn(int32_t e, const char* op) const {
    const PNode n = b.nodes[e];
    return n.kind == E_SCALARFN && str_of(n.s0) == op;
  }

  bool is_volatile(int32_t e) const {
    return expr_contains(e, [&](const PNode n) {
      if (n.kind != E_SCALARFN) return false;
      std::string op = str_of(n.s0);
      return op == "rand" || op == "rand_integer";
    });
  }

  bool has_subquery(int32_t e) const {
    return expr_contains(e, [](const PNode n) {
      return n.kind == E_SCALARSUBQ || n.kind == E_INSUBQ || n.kind == E_EXISTS;
    });
  }

  bool is_bool_lit(int32_t e, bool v) const {
    const PNode n = b.nodes[e];
    return n.kind == E_LITERAL && (n.flags & 0xFF) == LT_BOOL &&
           (n.ival != 0) == v;
  }


  // ---------------- literal utilities ----------------
  bool lit_num(int32_t e, bool* is_float, int64_t* iv, double* dv) const {
    const PNode n = b.nodes[e];
    if (n.kind != E_LITERAL) return false;
    int tag = n.flags & 0xFF;
    if (tag == LT_INT || tag == LT_BOOL) {
      *is_float = false;
      *iv = n.ival;
      *dv = (double)n.ival;
      return true;
    }
    if (tag == LT_FLOAT) {
      *is_float = true;
      *iv = (int64_t)n.dval;
      *dv = n.dval;
      return true;
    }
    return false;
  }

  int32_t mk_bool(bool v) const {
    return b.add(E_LITERAL, {}, ty_flags(TY_BOOLEAN, LT_BOOL), v ? 1 : 0);
  }

  // optimizer-side literal cast: shares cast_literal_node with the binder;
  // -1 = cannot fold (NULL literal or unparseable string)
  int32_t cast_lit_node(int32_t lit, int target) const {
    if ((b.nodes[lit].flags & 0xFF) == LT_NULL) return -1;
    try {
      return cast_literal_node(b, lit, target);
    } catch (const BindErr&) {
      return -1;
    }
  }

  // ---------------- SimplifyExpressions ----------------
  int32_t simplify_expr(int32_t e) const {
    return transform_expr(e, [&](int32_t x) -> int32_t {
      const PNode n = b.nodes[x];
      if (n.kind == E_SCALARFN) {
        auto args = b.kids(x);
        std::string op = str_of(n.s0);
        if ((op == "and" || op == "or") && args.size() == 2) {
          const PNode a = b.nodes[args[0]];
          const PNode bb = b.nodes[args[1]];
          if (a.kind == E_LITERAL && (a.flags & 0xFF) == LT_BOOL) {
            bool av = a.ival != 0;
            if (op == "and") return av ? args[1] : mk_bool(false);
            return av ? mk_bool(true) : args[1];
          }
          if (bb.kind == E_LITERAL && (bb.flags & 0xFF) == LT_BOOL) {
            bool bv = bb.ival != 0;
            if (op == "and") return bv ? args[0] : mk_bool(false);
            return bv ? mk_bool(true) : args[0];
          }
        }
        if (op == "not" && !args.empty()) {
          const PNode a = b.nodes[args[0]];
          if (a.kind == E_LITERAL && (a.flags & 0xFF) == LT_BOOL)
            return mk_bool(a.ival == 0);
          if (is_fn(args[0], "not")) return b.kids(args[0])[0];
        }
        static const std::set<std::string> foldable = {
            "add", "sub", "mul", "eq", "ne", "lt", "le", "gt", "ge"};
        if (foldable.count(op) && args.size() == 2) {
          bool f1, f2;
          int64_t i1, i2;
          double d1, d2;
          if (lit_num(args[0], &f1, &i1, &d1) &&
              lit_num(args[1], &f2, &i2, &d2)) {
            int ty = ty_of_flags(n.flags);
            if (op == "add" || op == "sub" || op == "mul") {
              if (f1 || f2) {
                double v = op == "add" ? d1 + d2
                           : op == "sub" ? d1 - d2 : d1 * d2;
                return b.add(E_LITERAL, {}, ty_flags(ty, LT_FLOAT), 0, v);
              }
              int64_t v = op == "add" ? i1 + i2
                          : op == "sub" ? i1 - i2 : i1 * i2;
              if (ty == TY_BOOLEAN)
                return b.add(E_LITERAL, {}, ty_flags(ty, LT_BOOL),
                             v != 0 ? 1 : 0);
              return b.add(E_LITERAL, {}, ty_flags(ty, LT_INT), v);
            }
            bool v;
            if (!f1 && !f2) {
              v = op == "eq" ? i1 == i2 : op == "ne" ? i1 != i2
                  : op == "lt" ? i1 < i2 : op == "le" ? i1 <= i2
                  : op == "gt" ? i1 > i2 : i1 >= i2;
            } else {
              double l = f1 ? d1 : (double)i1;
              double r = f2 ? d2 : (double)i2;
              v = op == "eq" ? l == r : op == "ne" ? l != r
                  : op == "lt" ? l < r : op == "le" ? l <= r
                  : op == "gt" ? l > r : l >= r;
            }
            int ty2 = ty_of_flags(n.flags);
            return b.add(E_LITERAL, {}, ty_flags(ty2, LT_BOOL), v ? 1 : 0);
          }
        }
      }
      if (n.kind == E_CAST) {
        int32_t arg = b.kids(x)[0];
        const PNode an = b.nodes[arg];
        int ty = ty_of_flags(n.flags);
        if (an.kind == E_LITERAL) {
          if ((an.flags & 0xFF) == LT_NULL)
            return b.add(E_LITERAL, {}, ty_flags(ty, LT_NULL));
          int32_t lit = cast_lit_node(arg, ty);
          if (lit >= 0) {
            const PNode ln = b.nodes[lit];
            return b.add(E_LITERAL, {}, ty_flags(ty, ln.flags & 0xFF),
                         ln.ival, ln.dval, ln.s0);
          }
          return x;
        }
        if (ty_of_flags(an.flags) == ty) return arg;
      }
      return x;
    });
  }

  int32_t map_node_exprs(int32_t id,
                         const std::function<int32_t(int32_t)>& fn) const {
    const PNode n = b.nodes[id];
    auto ks = b.kids(id);
    std::vector<int32_t> nk = ks;
    bool changed = false;
    auto apply_range = [&](size_t from, size_t to) {
      for (size_t i = from; i < to && i < nk.size(); ++i) {
        int32_t t = fn(nk[i]);
        changed |= t != nk[i];
        nk[i] = t;
      }
    };
    switch (n.kind) {
      case P_PROJECTION:
        apply_range(1 + n.ival, nk.size());
        break;
      case P_FILTER:
        apply_range(nk.size() - 1, nk.size());
        break;
      case P_JOIN: {
        size_t start = 2 + n.ival;
        for (size_t i = start; i < nk.size(); ++i) {
          if (b.nodes[nk[i]].kind == P_ON_PAIR) {
            auto pk = b.kids(nk[i]);
            int32_t l = fn(pk[0]);
            int32_t r = fn(pk[1]);
            if (l != pk[0] || r != pk[1]) {
              nk[i] = b.add(P_ON_PAIR, {l, r});
              changed = true;
            }
          } else {
            int32_t t = fn(nk[i]);
            changed |= t != nk[i];
            nk[i] = t;
          }
        }
        break;
      }
      case P_AGGREGATE:
        apply_range(1 + n.ival, nk.size());
        break;
      case P_SORT: {
        for (size_t i = 1 + n.ival; i < nk.size(); ++i) {
          const PNode kn = b.nodes[nk[i]];
          auto kk = b.kids(nk[i]);
          int32_t t = fn(kk[0]);
          if (t != kk[0]) {
            nk[i] = b.add(P_SORTKEY, {t}, kn.flags);
            changed = true;
          }
        }
        break;
      }
      case P_WINDOW:
        apply_range(1 + n.ival, nk.size());
        break;
      case P_TABLESCAN: {
        if (!(n.flags & 2)) return id;
        for (size_t i = 0; i < nk.size(); ++i) {
          int k = b.nodes[nk[i]].kind;
          if (k != P_FIELD && k != P_PART) {
            int32_t t = fn(nk[i]);
            changed |= t != nk[i];
            nk[i] = t;
          }
        }
        break;
      }
      default:
        return id;
    }
    if (!changed) return id;
    return b.add(n.kind, nk, n.flags, n.ival, n.dval, n.s0, n.s1);
  }

  int32_t rewrite_plan(int32_t id,
                       const std::function<int32_t(int32_t)>& fn) const {
    auto ins = inputs_of(id);
    if (!ins.empty()) {
      std::vector<int32_t> ni;
      bool changed = false;
      for (int32_t k : ins) {
        int32_t t = rewrite_plan(k, fn);
        changed |= t != k;
        ni.push_back(t);
      }
      if (changed) id = with_inputs(id, ni);
    }
    return fn(id);
  }

  int32_t rule_simplify(int32_t plan) const {
    return rewrite_plan(plan, [&](int32_t node) {
      return map_node_exprs(node,
                            [&](int32_t e) { return simplify_expr(e); });
    });
  }

  // ---------------- UnwrapCastInComparison ----------------
  bool cast_injective_monotone(int src, int dst) const {
    auto int_rank = [](int t) -> int {
      switch (t) {
        case TY_TINYINT: return 8;
        case TY_SMALLINT: return 16;
        case TY_INTEGER: return 32;
        case TY_BIGINT: return 64;
      }
      return -1;
    };
    int rs = int_rank(src), rd = int_rank(dst);
    if (rs > 0 && rd > 0) return rs <= rd;
    if (rs > 0 && dst == TY_DOUBLE) return rs <= 32;
    if (rs > 0 && dst == TY_FLOAT) return rs <= 16;
    if (src == TY_FLOAT && dst == TY_DOUBLE) return true;
    if (src == TY_DATE && dst == TY_TIMESTAMP) return true;
    return false;
  }

  bool lit_equal_value(int32_t a, int32_t c) const {
    const PNode x = b.nodes[a];
    const PNode y = b.nodes[c];
    int tx = x.flags & 0xFF, ty = y.flags & 0xFF;
    if (tx == LT_STR || ty == LT_STR) return tx == ty && x.s0 == y.s0;
    if (tx == LT_NULL || ty == LT_NULL) return tx == ty;
    bool f1, f2;
    int64_t i1, i2;
    double d1, d2;
    if (!lit_num(a, &f1, &i1, &d1) || !lit_num(c, &f2, &i2, &d2)) return false;
    if (!f1 && !f2) return i1 == i2;
    return d1 == d2;
  }

  int32_t try_unwrap_cast(const std::string& op, int32_t cast_e,
                          int32_t lit_e) const {
    const PNode cn = b.nodes[cast_e];
    const PNode ln = b.nodes[lit_e];
    if ((ln.flags & 0xFF) == LT_NULL) return -1;
    int32_t arg = b.kids(cast_e)[0];
    int src = ty_of_flags(b.nodes[arg].flags);
    int dst = ty_of_flags(cn.flags);
    if (!cast_injective_monotone(src, dst)) return -1;
    int32_t down = cast_lit_node(lit_e, src);
    if (down < 0) return -1;
    int32_t back = cast_lit_node(down, ty_of_flags(ln.flags));
    if (back < 0) return -1;
    if (!lit_equal_value(back, lit_e)) return -1;
    auto int_range = [](int t, int64_t* lo, int64_t* hi) -> bool {
      switch (t) {
        case TY_TINYINT: *lo = -(1LL << 7); *hi = (1LL << 7) - 1; return true;
        case TY_SMALLINT: *lo = -(1LL << 15); *hi = (1LL << 15) - 1; return true;
        case TY_INTEGER: *lo = -(1LL << 31); *hi = (1LL << 31) - 1; return true;
        case TY_BIGINT: *lo = INT64_MIN; *hi = INT64_MAX; return true;
      }
      return false;
    };
    int64_t lo, hi;
    if (int_range(src, &lo, &hi)) {
      bool f;
      int64_t iv;
      double dv;
      if (!lit_num(down, &f, &iv, &dv)) return -1;
      int64_t v = f ? (int64_t)dv : iv;
      if (!(lo <= v && v <= hi)) return -1;
    }
    return b.add(E_SCALARFN, {arg, down}, ty_flags(TY_BOOLEAN), 0, 0.0,
                 b.intern_mut(op));
  }

  int32_t unwrap_cast_expr(int32_t e) const {
    static const std::map<std::string, std::string> flip = {
        {"lt", "gt"}, {"le", "ge"}, {"gt", "lt"}, {"ge", "le"},
        {"eq", "eq"}, {"ne", "ne"}};
    return transform_expr(e, [&](int32_t x) -> int32_t {
      const PNode n = b.nodes[x];
      if (n.kind != E_SCALARFN) return x;
      std::string op = str_of(n.s0);
      if (!flip.count(op)) return x;
      auto args = b.kids(x);
      if (args.size() != 2) return x;
      const PNode a = b.nodes[args[0]];
      const PNode bb = b.nodes[args[1]];
      if (a.kind == E_CAST && bb.kind == E_LITERAL) {
        int32_t out = try_unwrap_cast(op, args[0], args[1]);
        if (out >= 0) return out;
      }
      if (bb.kind == E_CAST && a.kind == E_LITERAL) {
        int32_t out = try_unwrap_cast(flip.at(op), args[1], args[0]);
        if (out >= 0) return out;
      }
      return x;
    });
  }

  int32_t rule_unwrap_cast(int32_t plan) const {
    return rewrite_plan(plan, [&](int32_t node) {
      return map_node_exprs(node,
                            [&](int32_t e) { return unwrap_cast_expr(e); });
    });
  }

  // ---------------- RewriteDisjunctivePredicate ----------------
  int32_t rewrite_disjunction(int32_t e) const {
    return transform_expr(e, [&](int32_t x) -> int32_t {
      if (!is_fn(x, "or")) return x;
      std::vector<int32_t> djs;
      disjuncts_of(x, djs);
      if (djs.size() < 2) return x;
      std::vector<std::vector<int32_t>> branches;
      for (int32_t d : djs) {
        std::vector<int32_t> cs;
        conjuncts_of(d, cs);
        branches.push_back(cs);
      }
      std::vector<int32_t> common;
      for (int32_t c : branches[0]) {
        bool in_all = true;
        for (size_t i = 1; i < branches.size(); ++i) {
          bool found = false;
          for (int32_t c2 : branches[i])
            if (b.eq(c, c2)) { found = true; break; }
          if (!found) { in_all = false; break; }
        }
        if (in_all) common.push_back(c);
      }
      if (common.empty()) return x;
      std::vector<std::vector<int32_t>> residuals;
      for (auto& br : branches) {
        std::vector<int32_t> rem;
        for (int32_t c : br) {
          bool is_common = false;
          for (int32_t cm : common)
            if (b.eq(c, cm)) { is_common = true; break; }
          if (!is_common) rem.push_back(c);
        }
        residuals.push_back(rem);
      }
      for (auto& rem : residuals)
        if (rem.empty()) return conjoin(common);
      std::vector<int32_t> parts = common;
      std::vector<int32_t> djparts;
      for (auto& rem : residuals) djparts.push_back(conjoin(rem));
      parts.push_back(disjoin(djparts));
      return conjoin(parts);
    });
  }

  int32_t rule_disjunctive(int32_t plan) const {
    return rewrite_plan(plan, [&](int32_t node) -> int32_t {
      const PNode n = b.nodes[node];
      if (n.kind != P_FILTER) return node;
      auto ks = b.kids(node);
      int32_t pred = ks.back();
      int32_t np = rewrite_disjunction(pred);
      if (np == pred) return node;
      std::vector<int32_t> nk = ks;
      nk.back() = np;
      return b.add(n.kind, nk, n.flags, n.ival, n.dval, n.s0, n.s1);
    });
  }


  // ---------------- node constructors ----------------
  int32_t mk_filter(int32_t input, int32_t pred) const {
    auto fields = schema_of(input);
    std::vector<int32_t> nk{input};
    nk.insert(nk.end(), fields.begin(), fields.end());
    nk.push_back(pred);
    return b.add(P_FILTER, nk, 0, (int64_t)fields.size());
  }

  int32_t mk_filter_with_fields(int32_t input, int32_t pred,
                                const std::vector<int32_t>& fields) const {
    std::vector<int32_t> nk{input};
    nk.insert(nk.end(), fields.begin(), fields.end());
    nk.push_back(pred);
    return b.add(P_FILTER, nk, 0, (int64_t)fields.size());
  }

  int32_t mk_limit(int32_t input, int64_t skip, bool has_fetch, int64_t fetch,
                   const std::vector<int32_t>& fields) const {
    std::vector<int32_t> nk{input};
    nk.insert(nk.end(), fields.begin(), fields.end());
    return b.add(P_LIMIT, nk, has_fetch ? 1 : 0, fetch, 0.0,
                 b.intern_mut(std::to_string(skip)));
  }

  // decode P_LIMIT payload
  void limit_parts(int32_t id, int64_t* skip, bool* has_fetch,
                   int64_t* fetch) const {
    const PNode n = b.nodes[id];
    *skip = std::strtoll(str_of(n.s0).c_str(), nullptr, 10);
    *has_fetch = (n.flags & 1) != 0;
    *fetch = n.ival;
  }

  struct JoinParts {
    int32_t left, right;
    std::vector<int32_t> fields;
    std::vector<int32_t> on;  // P_ON_PAIR ids
    int32_t residual;         // -1 none
    std::string jt;
    bool null_aware;
  };

  JoinParts join_parts(int32_t id) const {
    const PNode n = b.nodes[id];
    auto ks = b.kids(id);
    JoinParts jp;
    jp.left = ks[0];
    jp.right = ks[1];
    int nf = (int)n.ival;
    for (int i = 0; i < nf; ++i) jp.fields.push_back(ks[2 + i]);
    size_t i = 2 + nf;
    bool has_resid = (n.flags & 1) != 0;
    size_t end = ks.size() - (has_resid ? 1 : 0);
    for (; i < end; ++i) jp.on.push_back(ks[i]);
    jp.residual = has_resid ? ks.back() : -1;
    jp.jt = str_of(n.s0);
    jp.null_aware = (n.flags & 2) != 0;
    return jp;
  }

  int32_t mk_join(const JoinParts& jp) const {
    std::vector<int32_t> nk{jp.left, jp.right};
    nk.insert(nk.end(), jp.fields.begin(), jp.fields.end());
    nk.insert(nk.end(), jp.on.begin(), jp.on.end());
    int32_t flags = jp.null_aware ? 2 : 0;
    if (jp.residual >= 0) {
      nk.push_back(jp.residual);
      flags |= 1;
    }
    return b.add(P_JOIN, nk, flags, (int64_t)jp.fields.size(), 0.0,
                 b.intern_mut(jp.jt));
  }

  // split_join_condition twin (binder.split_join_condition parity)
  std::pair<std::vector<int32_t>, int32_t> split_cond(int32_t cond,
                                                      int nleft) const {
    std::vector<int32_t> cjs;
    conjuncts_of(cond, cjs);
    std::vector<int32_t> on, residual;
    for (int32_t c : cjs) {
      const PNode n = b.nodes[c];
      if (n.kind == E_LITERAL && (n.flags & 0xFF) == LT_BOOL && n.ival == 1)
        continue;
      if (is_fn(c, "eq")) {
        auto ks = b.kids(c);
        std::set<int64_t> lcols, rcols;
        referenced_cols(ks[0], lcols);
        referenced_cols(ks[1], rcols);
        if (!lcols.empty() && !rcols.empty()) {
          int64_t lmax = *lcols.rbegin(), lmin = *lcols.begin();
          int64_t rmax = *rcols.rbegin(), rmin = *rcols.begin();
          if (lmax < nleft && rmin >= nleft) {
            on.push_back(b.add(P_ON_PAIR, {ks[0], ks[1]}));
            continue;
          }
          if (rmax < nleft && lmin >= nleft) {
            on.push_back(b.add(P_ON_PAIR, {ks[1], ks[0]}));
            continue;
          }
        }
      }
      residual.push_back(c);
    }
    int32_t resid = -1;
    if (!residual.empty()) resid = conjoin(residual);
    return {on, resid};
  }

  // ---------------- EliminateCrossJoin ----------------
  int32_t rule_elim_cross_join(int32_t plan) const {
    return rewrite_plan(plan, [&](int32_t node) -> int32_t {
      const PNode n = b.nodes[node];
      if (n.kind != P_FILTER) return node;
      auto ks = b.kids(node);
      int32_t child = ks[0];
      int32_t pred = ks.back();
      const PNode cn = b.nodes[child];
      if (cn.kind == P_CROSSJOIN) {
        auto ck = b.kids(child);
        int nleft = schema_width(ck[0]);
        auto [on, residual] = split_cond(pred, nleft);
        if (!on.empty()) {
          std::vector<int32_t> cj_fields(ck.begin() + 2, ck.end());
          JoinParts jp{ck[0], ck[1], cj_fields, on, -1, "INNER", false};
          int32_t join = mk_join(jp);
          if (residual >= 0)
            return mk_filter_with_fields(join, residual, cj_fields);
          return join;
        }
      }
      if (cn.kind == P_JOIN) {
        JoinParts jp = join_parts(child);
        if (jp.jt == "INNER") {
          int nleft = schema_width(jp.left);
          auto [on, residual] = split_cond(pred, nleft);
          if (!on.empty()) {
            jp.on.insert(jp.on.end(), on.begin(), on.end());
            int32_t join = mk_join(jp);
            if (residual >= 0)
              return mk_filter_with_fields(join, residual, jp.fields);
            return join;
          }
        }
      }
      return node;
    });
  }

  // ---------------- EliminateLimit ----------------
  int32_t rule_elim_limit(int32_t plan) const {
    return rewrite_plan(plan, [&](int32_t node) -> int32_t {
      const PNode n = b.nodes[node];
      if (n.kind != P_LIMIT) return node;
      auto ks = b.kids(node);
      int64_t skip, fetch;
      bool has_fetch;
      limit_parts(node, &skip, &has_fetch, &fetch);
      if (!has_fetch && skip == 0) return ks[0];
      const PNode cn = b.nodes[ks[0]];
      if (cn.kind == P_LIMIT) {
        int64_t iskip, ifetch;
        bool ihas;
        limit_parts(ks[0], &iskip, &ihas, &ifetch);
        int64_t nskip = iskip + skip;
        bool nhas = false;
        int64_t nfetch = 0;
        if (ihas) {
          nhas = true;
          nfetch = ifetch - skip > 0 ? ifetch - skip : 0;
        }
        if (has_fetch) {
          nfetch = nhas ? std::min(nfetch, fetch) : fetch;
          nhas = true;
        }
        auto inner_ks = b.kids(ks[0]);
        std::vector<int32_t> fields(ks.begin() + 1, ks.end());
        return mk_limit(inner_ks[0], nskip, nhas, nfetch, fields);
      }
      return node;
    });
  }

  // ---------------- PushDownLimit ----------------
  int32_t rule_pushdown_limit(int32_t plan) const {
    return rewrite_plan(plan, [&](int32_t node) -> int32_t {
      const PNode n = b.nodes[node];
      if (n.kind != P_LIMIT) return node;
      int64_t skip, fetch;
      bool has_fetch;
      limit_parts(node, &skip, &has_fetch, &fetch);
      if (!has_fetch) return node;
      int64_t want = skip + fetch;
      auto ks = b.kids(node);
      int32_t child = ks[0];
      std::vector<int32_t> lim_fields(ks.begin() + 1, ks.end());
      const PNode cn = b.nodes[child];
      if (cn.kind == P_SORT) {
        bool s_has = (cn.flags & 1) != 0;
        int64_t s_fetch = (int64_t)cn.dval;
        if (!s_has || s_fetch > want) {
          auto cks = b.kids(child);
          std::vector<int32_t> nk = cks;
          int32_t sorted = b.add(P_SORT, nk, cn.flags | 1, cn.ival,
                                 (double)want, cn.s0, cn.s1);
          return mk_limit(sorted, skip, true, fetch, lim_fields);
        }
      }
      if (cn.kind == P_PROJECTION) {
        auto cks = b.kids(child);
        auto inner_fields = schema_of(cks[0]);
        int32_t pushed = mk_limit(cks[0], 0, true, want, inner_fields);
        std::vector<int32_t> pk = cks;
        pk[0] = pushed;
        int32_t proj = b.add(P_PROJECTION, pk, cn.flags, cn.ival, cn.dval,
                             cn.s0, cn.s1);
        return mk_limit(proj, skip, true, fetch, lim_fields);
      }
      if (cn.kind == P_UNION && (cn.flags & 1)) {
        auto cks = b.kids(child);
        int nf = (int)cn.ival;
        std::vector<int32_t> nk(cks.begin(), cks.begin() + nf);
        for (size_t i = nf; i < cks.size(); ++i) {
          auto kid_fields = schema_of(cks[i]);
          nk.push_back(mk_limit(cks[i], 0, true, want, kid_fields));
        }
        int32_t u = b.add(P_UNION, nk, cn.flags, cn.ival, cn.dval, cn.s0,
                          cn.s1);
        return mk_limit(u, skip, true, fetch, lim_fields);
      }
      return node;
    });
  }

  // ---------------- EliminateOuterJoin ----------------
  bool strong(int32_t e) const {
    static const std::set<std::string> null_prop = {
        "eq", "ne", "lt", "le", "gt", "ge", "add", "sub", "mul", "div",
        "mod", "neg", "not", "like", "ilike", "similar", "between"};
    const PNode n = b.nodes[e];
    if (n.kind == E_COLREF || n.kind == E_OUTERREF || n.kind == E_LITERAL)
      return true;
    if (n.kind == E_CAST) return strong(b.kids(e)[0]);
    if (n.kind == E_SCALARFN && null_prop.count(str_of(n.s0))) {
      for (int32_t k : b.kids(e))
        if (!strong(k)) return false;
      return true;
    }
    return false;
  }

  bool refs_in_range(int32_t e, int64_t lo, int64_t hi) const {
    bool found = false;
    walk_expr(e, [&](int32_t x) {
      const PNode n = b.nodes[x];
      if ((n.kind == E_COLREF || n.kind == E_OUTERREF) && lo <= n.ival &&
          n.ival < hi)
        found = true;
    });
    return found;
  }

  bool rejects_nulls(int32_t e, int64_t lo, int64_t hi) const {
    static const std::set<std::string> null_prop = {
        "eq", "ne", "lt", "le", "gt", "ge", "add", "sub", "mul", "div",
        "mod", "neg", "not", "like", "ilike", "similar", "between"};
    const PNode n = b.nodes[e];
    if (n.kind != E_SCALARFN) return false;
    std::string op = str_of(n.s0);
    auto ks = b.kids(e);
    if (op == "and") {
      for (int32_t k : ks)
        if (rejects_nulls(k, lo, hi)) return true;
      return false;
    }
    if (op == "or") {
      for (int32_t k : ks)
        if (!rejects_nulls(k, lo, hi)) return false;
      return true;
    }
    if (op == "is_not_null" || op == "isnotnull")
      return strong(ks[0]) && refs_in_range(ks[0], lo, hi);
    if (null_prop.count(op)) {
      for (int32_t k : ks)
        if (!strong(k)) return false;
      return refs_in_range(e, lo, hi);
    }
    return false;
  }

  int32_t rule_elim_outer_join(int32_t plan) const {
    return rewrite_plan(plan, [&](int32_t node) -> int32_t {
      const PNode n = b.nodes[node];
      if (n.kind != P_FILTER) return node;
      auto ks = b.kids(node);
      if (b.nodes[ks[0]].kind != P_JOIN) return node;
      JoinParts jp = join_parts(ks[0]);
      if (jp.jt != "LEFT" && jp.jt != "RIGHT" && jp.jt != "FULL") return node;
      int nleft = schema_width(jp.left);
      int total = (int)jp.fields.size();
      bool rej_left = false, rej_right = false;
      std::vector<int32_t> cjs;
      conjuncts_of(ks.back(), cjs);
      for (int32_t c : cjs) {
        rej_left = rej_left || rejects_nulls(c, 0, nleft);
        rej_right = rej_right || rejects_nulls(c, nleft, total);
      }
      std::string new_jt;
      if (jp.jt == "LEFT" && rej_right) new_jt = "INNER";
      else if (jp.jt == "RIGHT" && rej_left) new_jt = "INNER";
      else if (jp.jt == "FULL") {
        if (rej_left && rej_right) new_jt = "INNER";
        else if (rej_left) new_jt = "LEFT";
        else if (rej_right) new_jt = "RIGHT";
      }
      if (new_jt.empty()) return node;
      jp.jt = new_jt;
      int32_t join = mk_join(jp);
      std::vector<int32_t> nk = ks;
      nk[0] = join;
      return b.add(P_FILTER, nk, n.flags, n.ival, n.dval, n.s0, n.s1);
    });
  }


  // ---------------- PushDownFilter ----------------
  int32_t rule_pushdown_filter(int32_t plan) const {
    std::function<int32_t(int32_t)> go = [&](int32_t node0) -> int32_t {
      // bottom-up first
      int32_t node = node0;
      auto ins = inputs_of(node);
      if (!ins.empty()) {
        std::vector<int32_t> ni;
        bool changed = false;
        for (int32_t k : ins) {
          int32_t t = go(k);
          changed |= t != k;
          ni.push_back(t);
        }
        if (changed) node = with_inputs(node, ni);
      }
      const PNode n = b.nodes[node];
      if (n.kind != P_FILTER) return node;
      auto ks = b.kids(node);
      int32_t child = ks[0];
      int32_t pred = ks.back();
      std::vector<int32_t> parts;
      conjuncts_of(pred, parts);
      const PNode cn = b.nodes[child];

      if (cn.kind == P_FILTER) {
        auto cks = b.kids(child);
        std::vector<int32_t> all = parts;
        conjuncts_of(cks.back(), all);
        return go(mk_filter_with_fields(
            cks[0], conjoin(all),
            std::vector<int32_t>(cks.begin() + 1, cks.end() - 1)));
      }

      if (cn.kind == P_PROJECTION) {
        auto cks = b.kids(child);
        int nf = (int)cn.ival;
        std::vector<int32_t> proj_exprs(cks.begin() + 1 + nf, cks.end());
        std::vector<int32_t> pushable, kept;
        for (int32_t c : parts) {
          if (is_volatile(c) || has_subquery(c)) {
            kept.push_back(c);
            continue;
          }
          std::set<int64_t> cols;
          referenced_cols(c, cols);
          bool ok = true;
          for (int64_t i : cols) {
            if (i < 0 || i >= (int64_t)proj_exprs.size()) { ok = false; break; }
            int k = b.nodes[proj_exprs[i]].kind;
            // Python: expr must be ColumnRef/Literal/Cast/ScalarFunc/Case
            // and contain no Agg/Window anywhere
            if (!(k == E_COLREF || k == E_LITERAL || k == E_CAST ||
                  k == E_SCALARFN || k == E_CASE)) {
              ok = false;
              break;
            }
            if (expr_contains(proj_exprs[i], [](const PNode m) {
                  return m.kind == E_AGG || m.kind == E_WINDOW;
                })) {
              ok = false;
              break;
            }
          }
          if (ok) pushable.push_back(c);
          else kept.push_back(c);
        }
        if (!pushable.empty()) {
          std::vector<int32_t> substed;
          for (int32_t c : pushable) {
            substed.push_back(transform_expr(c, [&](int32_t x) -> int32_t {
              const PNode m = b.nodes[x];
              if (m.kind == E_COLREF) return proj_exprs[m.ival];
              return x;
            }));
          }
          int32_t new_input = go(mk_filter(cks[0], conjoin(substed)));
          std::vector<int32_t> pk = cks;
          pk[0] = new_input;
          int32_t proj = b.add(P_PROJECTION, pk, cn.flags, cn.ival, cn.dval,
                               cn.s0, cn.s1);
          if (!kept.empty())
            return mk_filter_with_fields(
                proj, conjoin(kept),
                std::vector<int32_t>(cks.begin() + 1, cks.begin() + 1 + nf));
          return proj;
        }
        return node;
      }

      if (cn.kind == P_SUBQUERY_ALIAS) {
        auto cks = b.kids(child);
        int32_t inner = go(mk_filter(cks[0], pred));
        std::vector<int32_t> nk = cks;
        nk[0] = inner;
        return b.add(P_SUBQUERY_ALIAS, nk, cn.flags, cn.ival, cn.dval, cn.s0,
                     cn.s1);
      }

      if (cn.kind == P_SORT) {
        auto cks = b.kids(child);
        int32_t inner = go(mk_filter(cks[0], pred));
        std::vector<int32_t> nk = cks;
        nk[0] = inner;
        return b.add(P_SORT, nk, cn.flags, cn.ival, cn.dval, cn.s0, cn.s1);
      }

      if (cn.kind == P_JOIN || cn.kind == P_CROSSJOIN) {
        auto cins = inputs_of(child);
        int nleft = schema_width(cins[0]);
        std::string jt = cn.kind == P_JOIN ? str_of(cn.s0) : "CROSS";
        std::vector<int32_t> left_parts, right_parts, kept;
        for (int32_t c : parts) {
          if (is_volatile(c) || has_subquery(c)) {
            kept.push_back(c);
            continue;
          }
          std::set<int64_t> cols;
          referenced_cols(c, cols);
          bool to_left = !cols.empty() && *cols.rbegin() < nleft &&
                         (jt == "INNER" || jt == "LEFT" || jt == "CROSS" ||
                          jt == "LEFTSEMI" || jt == "LEFTANTI" ||
                          jt == "LEFTMARK");
          bool to_right = !cols.empty() && *cols.begin() >= nleft &&
                          (jt == "INNER" || jt == "RIGHT" || jt == "CROSS");
          if (to_left) left_parts.push_back(c);
          else if (to_right) right_parts.push_back(shift_cols(c, -nleft));
          else kept.push_back(c);
        }
        if (!left_parts.empty() || !right_parts.empty()) {
          int32_t l = cins[0], r = cins[1];
          if (!left_parts.empty()) l = go(mk_filter(l, conjoin(left_parts)));
          if (!right_parts.empty()) r = go(mk_filter(r, conjoin(right_parts)));
          int32_t new_child = with_inputs(child, {l, r});
          if (!kept.empty())
            return mk_filter_with_fields(
                new_child, conjoin(kept),
                std::vector<int32_t>(ks.begin() + 1, ks.end() - 1));
          return new_child;
        }
        return node;
      }

      if (cn.kind == P_UNION) {
        auto cks = b.kids(child);
        int nf = (int)cn.ival;
        std::vector<int32_t> nk(cks.begin(), cks.begin() + nf);
        for (size_t i = nf; i < cks.size(); ++i)
          nk.push_back(go(mk_filter(cks[i], pred)));
        return b.add(P_UNION, nk, cn.flags, cn.ival, cn.dval, cn.s0, cn.s1);
      }

      if (cn.kind == P_AGGREGATE) {
        auto cks = b.kids(child);
        int nf = (int)cn.ival;
        int ngroups = cn.flags;
        std::vector<int32_t> group_exprs(cks.begin() + 1 + nf,
                                         cks.begin() + 1 + nf + ngroups);
        std::vector<int32_t> pushable, kept;
        for (int32_t c : parts) {
          std::set<int64_t> cols;
          referenced_cols(c, cols);
          if (!cols.empty() && *cols.rbegin() < ngroups && !is_volatile(c) &&
              !has_subquery(c))
            pushable.push_back(c);
          else
            kept.push_back(c);
        }
        if (!pushable.empty()) {
          std::vector<int32_t> substed;
          for (int32_t c : pushable)
            substed.push_back(transform_expr(c, [&](int32_t x) -> int32_t {
              const PNode m = b.nodes[x];
              if (m.kind == E_COLREF) return group_exprs[m.ival];
              return x;
            }));
          int32_t inner = go(mk_filter(cks[0], conjoin(substed)));
          std::vector<int32_t> nk = cks;
          nk[0] = inner;
          int32_t agg = b.add(P_AGGREGATE, nk, cn.flags, cn.ival, cn.dval,
                              cn.s0, cn.s1);
          if (!kept.empty())
            return mk_filter_with_fields(
                agg, conjoin(kept),
                std::vector<int32_t>(cks.begin() + 1, cks.begin() + 1 + nf));
          return agg;
        }
        return node;
      }

      if (cn.kind == P_TABLESCAN && predicate_pushdown) {
        std::vector<int32_t> ok, kept;
        for (int32_t c : parts) {
          if (is_volatile(c) || has_subquery(c)) kept.push_back(c);
          else ok.push_back(c);
        }
        if (!ok.empty()) {
          // extend the scan: fields + parts + existing filters + new ones
          auto fields = schema_of(child);
          auto cks = b.kids(child);
          std::vector<int32_t> pparts, old_filters;
          if (cn.flags & 3) {
            for (size_t i = fields.size(); i < cks.size(); ++i) {
              if (b.nodes[cks[i]].kind == P_PART) pparts.push_back(cks[i]);
              else old_filters.push_back(cks[i]);
            }
          }
          std::vector<int32_t> nk = fields;
          nk.insert(nk.end(), pparts.begin(), pparts.end());
          nk.insert(nk.end(), old_filters.begin(), old_filters.end());
          nk.insert(nk.end(), ok.begin(), ok.end());
          int32_t flags = (cn.flags & 1) | 2;
          int32_t scan = b.add(P_TABLESCAN, nk, flags,
                               (int64_t)fields.size(), 0.0, cn.s0, cn.s1);
          if (!kept.empty())
            return mk_filter_with_fields(scan, conjoin(kept), fields);
          return scan;
        }
        return node;
      }
      return node;
    };
    return go(plan);
  }


  // ---------------- PushDownProjection (_prune) ----------------
  // exprs held by a node (rules._node_exprs)
  std::vector<int32_t> node_exprs(int32_t id) const {
    const PNode n = b.nodes[id];
    auto ks = b.kids(id);
    switch (n.kind) {
      case P_PROJECTION:
        return std::vector<int32_t>(ks.begin() + 1 + n.ival, ks.end());
      case P_FILTER:
        return {ks.back()};
      case P_SORT: {
        std::vector<int32_t> out;
        for (size_t i = 1 + n.ival; i < ks.size(); ++i)
          out.push_back(b.kids(ks[i])[0]);
        return out;
      }
      case P_AGGREGATE:
        return std::vector<int32_t>(ks.begin() + 1 + n.ival, ks.end());
      case P_WINDOW:
        return std::vector<int32_t>(ks.begin() + 1 + n.ival, ks.end());
      case P_DISTRIBUTE_BY:
        return std::vector<int32_t>(ks.begin() + 1 + n.ival, ks.end());
      default:
        return {};
    }
  }

  struct Pruned {
    int32_t plan;
    std::map<int64_t, int64_t> mapping;
  };

  Pruned prune(int32_t id, const std::set<int64_t>& required) const {
    const PNode n = b.nodes[id];
    auto ks = b.kids(id);
    std::map<int64_t, int64_t> ident;
    int width = schema_width(id);
    for (int i = 0; i < width; ++i) ident[i] = i;

    if (n.kind == P_TABLESCAN) {
      auto fields = schema_of(id);
      std::vector<int32_t> pparts, filters;
      if (n.flags & 3) {
        for (size_t i = fields.size(); i < ks.size(); ++i) {
          if (b.nodes[ks[i]].kind == P_PART) pparts.push_back(ks[i]);
          else filters.push_back(ks[i]);
        }
      }
      std::set<int64_t> keep_set = required;
      for (int32_t f : filters) referenced_cols(f, keep_set);
      std::vector<int64_t> keep(keep_set.begin(), keep_set.end());
      bool has_proj = (n.flags & 1) != 0;
      if ((int)keep.size() == (int)fields.size() && !has_proj)
        return {id, ident};
      std::map<int64_t, int64_t> mapping;
      for (size_t i = 0; i < keep.size(); ++i) mapping[keep[i]] = (int64_t)i;
      std::vector<int32_t> nfields, nparts, nfilters;
      for (int64_t i : keep) {
        nfields.push_back(fields[i]);
        // projection names = kept field names
        nparts.push_back(b.add(P_PART, {}, 0, 0, 0.0, b.nodes[fields[i]].s0));
      }
      for (int32_t f : filters) nfilters.push_back(remap_cols(f, mapping));
      std::vector<int32_t> nk = nfields;
      nk.insert(nk.end(), nparts.begin(), nparts.end());
      nk.insert(nk.end(), nfilters.begin(), nfilters.end());
      int32_t scan = b.add(P_TABLESCAN, nk,
                           1 | (nfilters.empty() ? 0 : 2),
                           (int64_t)nfields.size(), 0.0, n.s0, n.s1);
      return {scan, mapping};
    }

    if (n.kind == P_PROJECTION) {
      int nf = (int)n.ival;
      std::vector<int32_t> exprs(ks.begin() + 1 + nf, ks.end());
      std::vector<int64_t> keep(required.begin(), required.end());
      std::set<int64_t> child_req;
      for (int64_t i : keep) referenced_cols(exprs[i], child_req);
      Pruned c = prune(ks[0], child_req);
      std::map<int64_t, int64_t> mapping;
      for (size_t i = 0; i < keep.size(); ++i) mapping[keep[i]] = (int64_t)i;
      std::vector<int32_t> nfields, nexprs;
      for (int64_t i : keep) {
        nfields.push_back(ks[1 + i]);
        nexprs.push_back(remap_cols(exprs[i], c.mapping));
      }
      std::vector<int32_t> nk{c.plan};
      nk.insert(nk.end(), nfields.begin(), nfields.end());
      nk.insert(nk.end(), nexprs.begin(), nexprs.end());
      return {b.add(P_PROJECTION, nk, 0, (int64_t)nfields.size()), mapping};
    }

    if (n.kind == P_FILTER) {
      int32_t pred = ks.back();
      std::set<int64_t> child_req = required;
      referenced_cols(pred, child_req);
      Pruned c = prune(ks[0], child_req);
      int32_t npred = remap_cols(pred, c.mapping);
      std::map<int64_t, int64_t> mapping;
      for (int64_t old : child_req) mapping[old] = c.mapping.at(old);
      auto nfields = schema_of(c.plan);
      return {mk_filter_with_fields(c.plan, npred, nfields), mapping};
    }

    if (n.kind == P_JOIN && str_of(n.s0) == "LEFTMARK") {
      auto ins = inputs_of(id);
      std::vector<int32_t> ni;
      bool changed = false;
      for (int32_t k : ins) {
        std::set<int64_t> full;
        for (int i2 = 0; i2 < schema_width(k); ++i2) full.insert(i2);
        Pruned c = prune(k, full);
        changed |= c.plan != k;
        ni.push_back(c.plan);
      }
      if (changed) id = with_inputs(id, ni);
      std::map<int64_t, int64_t> ident2;
      for (int i2 = 0; i2 < schema_width(id); ++i2) ident2[i2] = i2;
      return {id, ident2};
    }

    if (n.kind == P_JOIN) {
      JoinParts jp = join_parts(id);
      int nleft = schema_width(jp.left);
      std::set<int64_t> need = required;
      for (int32_t pr : jp.on) {
        auto pk = b.kids(pr);
        referenced_cols(pk[0], need);
        referenced_cols(pk[1], need);
      }
      if (jp.residual >= 0) referenced_cols(jp.residual, need);
      std::set<int64_t> lreq, rreq;
      for (int64_t i : need) {
        if (i < nleft) lreq.insert(i);
        else rreq.insert(i - nleft);
      }
      Pruned lc = prune(jp.left, lreq);
      Pruned rc = prune(jp.right, rreq);
      int new_nleft = schema_width(lc.plan);
      std::map<int64_t, int64_t> cmap;
      for (int64_t old : lreq) cmap[old] = lc.mapping.at(old);
      for (int64_t old : rreq)
        cmap[old + nleft] = rc.mapping.at(old) + new_nleft;
      std::vector<int32_t> non;
      for (int32_t pr : jp.on) {
        auto pk = b.kids(pr);
        non.push_back(b.add(P_ON_PAIR, {remap_cols(pk[0], cmap),
                                        remap_cols(pk[1], cmap)}));
      }
      int32_t nresid = jp.residual >= 0 ? remap_cols(jp.residual, cmap) : -1;
      std::vector<int32_t> nfields;
      std::map<int64_t, int64_t> mapping;
      if (jp.jt == "LEFTSEMI" || jp.jt == "LEFTANTI") {
        nfields = schema_of(lc.plan);
        for (int64_t old : required) mapping[old] = lc.mapping.at(old);
      } else {
        auto lf = schema_of(lc.plan);
        auto rf = schema_of(rc.plan);
        nfields = lf;
        nfields.insert(nfields.end(), rf.begin(), rf.end());
        for (int64_t old : required) mapping[old] = cmap.at(old);
      }
      JoinParts njp{lc.plan, rc.plan, nfields, non, nresid, jp.jt,
                    jp.null_aware};
      return {mk_join(njp), mapping};
    }

    if (n.kind == P_CROSSJOIN) {
      int nleft = schema_width(ks[0]);
      std::set<int64_t> lreq, rreq;
      for (int64_t i : required) {
        if (i < nleft) lreq.insert(i);
        else rreq.insert(i - nleft);
      }
      Pruned lc = prune(ks[0], lreq);
      Pruned rc = prune(ks[1], rreq);
      int new_nleft = schema_width(lc.plan);
      std::map<int64_t, int64_t> mapping;
      for (int64_t old : lreq) mapping[old] = lc.mapping.at(old);
      for (int64_t old : rreq)
        mapping[old + nleft] = rc.mapping.at(old) + new_nleft;
      auto lf = schema_of(lc.plan);
      auto rf = schema_of(rc.plan);
      std::vector<int32_t> nk{lc.plan, rc.plan};
      nk.insert(nk.end(), lf.begin(), lf.end());
      nk.insert(nk.end(), rf.begin(), rf.end());
      std::map<int64_t, int64_t> out;
      for (int64_t old : required) out[old] = mapping.at(old);
      return {b.add(P_CROSSJOIN, nk), out};
    }

    if (n.kind == P_AGGREGATE) {
      int nf = (int)n.ival;
      int ngroups = n.flags;
      std::vector<int32_t> groups(ks.begin() + 1 + nf,
                                  ks.begin() + 1 + nf + ngroups);
      std::vector<int32_t> aggs(ks.begin() + 1 + nf + ngroups, ks.end());
      std::set<int64_t> keep_agg_set;
      for (int64_t i : required)
        if (i >= ngroups) keep_agg_set.insert(i - ngroups);
      std::vector<int64_t> keep_aggs(keep_agg_set.begin(), keep_agg_set.end());
      std::set<int64_t> child_req;
      for (int32_t g : groups) referenced_cols(g, child_req);
      for (int64_t i : keep_aggs) referenced_cols(aggs[i], child_req);
      Pruned c = prune(ks[0], child_req);
      std::vector<int32_t> ngroups_v, naggs_v, nfields;
      for (int32_t g : groups) ngroups_v.push_back(remap_cols(g, c.mapping));
      for (int64_t i : keep_aggs)
        naggs_v.push_back(remap_cols(aggs[i], c.mapping));
      for (int i = 0; i < ngroups; ++i) nfields.push_back(ks[1 + i]);
      for (int64_t i : keep_aggs) nfields.push_back(ks[1 + ngroups + i]);
      std::map<int64_t, int64_t> mapping;
      for (int64_t i : required) {
        if (i < ngroups) mapping[i] = i;
        else {
          auto it = std::find(keep_aggs.begin(), keep_aggs.end(), i - ngroups);
          mapping[i] = ngroups + (it - keep_aggs.begin());
        }
      }
      std::vector<int32_t> nk{c.plan};
      nk.insert(nk.end(), nfields.begin(), nfields.end());
      nk.insert(nk.end(), ngroups_v.begin(), ngroups_v.end());
      nk.insert(nk.end(), naggs_v.begin(), naggs_v.end());
      return {b.add(P_AGGREGATE, nk, ngroups, (int64_t)nfields.size()),
              mapping};
    }

    if (n.kind == P_SORT || n.kind == P_DISTRIBUTE_BY) {
      auto exprs = node_exprs(id);
      std::set<int64_t> child_req = required;
      for (int32_t e : exprs) referenced_cols(e, child_req);
      Pruned c = prune(ks[0], child_req);
      std::map<int64_t, int64_t> mapping;
      for (int64_t old : required) mapping[old] = c.mapping.at(old);
      auto nfields = schema_of(c.plan);
      if (n.kind == P_SORT) {
        std::vector<int32_t> nk{c.plan};
        nk.insert(nk.end(), nfields.begin(), nfields.end());
        for (size_t i = 1 + n.ival; i < ks.size(); ++i) {
          const PNode kn = b.nodes[ks[i]];
          nk.push_back(b.add(P_SORTKEY,
                             {remap_cols(b.kids(ks[i])[0], c.mapping)},
                             kn.flags));
        }
        return {b.add(P_SORT, nk, n.flags, (int64_t)nfields.size(), n.dval),
                mapping};
      }
      std::vector<int32_t> nk{c.plan};
      nk.insert(nk.end(), nfields.begin(), nfields.end());
      for (int32_t e : exprs) nk.push_back(remap_cols(e, c.mapping));
      return {b.add(P_DISTRIBUTE_BY, nk, 0, (int64_t)nfields.size()), mapping};
    }

    if (n.kind == P_LIMIT) {
      Pruned c = prune(ks[0], required);
      std::map<int64_t, int64_t> mapping;
      for (int64_t old : required) mapping[old] = c.mapping.at(old);
      auto nfields = schema_of(c.plan);
      int64_t skip, fetch;
      bool has_fetch;
      limit_parts(id, &skip, &has_fetch, &fetch);
      return {mk_limit(c.plan, skip, has_fetch, fetch, nfields), mapping};
    }

    if (n.kind == P_SUBQUERY_ALIAS) {
      Pruned c = prune(ks[0], required);
      std::map<int64_t, int64_t> mapping;
      for (int64_t old : required) mapping[old] = c.mapping.at(old);
      // alias fields keep alias-schema entries for surviving columns
      std::map<int64_t, int64_t> inv;
      for (auto& [k2, v] : c.mapping) inv[v] = k2;
      auto child_fields = schema_of(c.plan);
      auto own_fields = schema_of(id);
      std::vector<int32_t> nfields;
      for (size_t ni = 0; ni < child_fields.size(); ++ni) {
        auto it = inv.find((int64_t)ni);
        if (it != inv.end() && it->second < (int64_t)own_fields.size())
          nfields.push_back(own_fields[it->second]);
        else
          nfields.push_back(child_fields[ni]);
      }
      std::vector<int32_t> nk{c.plan};
      nk.insert(nk.end(), nfields.begin(), nfields.end());
      return {b.add(P_SUBQUERY_ALIAS, nk, n.flags, n.ival, n.dval, n.s0,
                    n.s1),
              mapping};
    }

    // default: children pruned with full requirements
    auto ins = inputs_of(id);
    if (!ins.empty()) {
      std::vector<int32_t> ni;
      bool changed = false;
      for (int32_t k : ins) {
        std::set<int64_t> full;
        for (int i = 0; i < schema_width(k); ++i) full.insert(i);
        Pruned c = prune(k, full);
        changed |= c.plan != k;
        ni.push_back(c.plan);
      }
      if (changed) id = with_inputs(id, ni);
    }
    return {id, ident};
  }

  int32_t rule_pushdown_projection(int32_t plan) const {
    std::set<int64_t> required;
    int width = schema_width(plan);
    for (int i = 0; i < width; ++i) required.insert(i);
    Pruned out = prune(plan, required);
    bool identity = true;
    for (int64_t i : required)
      if (out.mapping.at(i) != i) identity = false;
    if (!identity) {
      auto own_fields = schema_of(plan);
      std::vector<int32_t> exprs, nfields;
      for (int64_t i : required) {
        const PNode f = b.nodes[own_fields[i]];
        exprs.push_back(b.add(E_COLREF, {},
                              ((f.flags >> 8) << 8) | (f.flags & 1),
                              out.mapping.at(i), 0.0, f.s0));
        nfields.push_back(own_fields[i]);
      }
      std::vector<int32_t> nk{out.plan};
      nk.insert(nk.end(), nfields.begin(), nfields.end());
      nk.insert(nk.end(), exprs.begin(), exprs.end());
      return b.add(P_PROJECTION, nk, 0, (int64_t)nfields.size());
    }
    return out.plan;
  }


  // ---------------- DecorrelateSubqueries ----------------
  bool has_outer_ref(int32_t e) const {
    return expr_contains(e, [](const PNode n) { return n.kind == E_OUTERREF; });
  }

  // match `outer_expr = inner_expr` (either side); (-1,-1) when no match
  std::pair<int32_t, int32_t> outer_eq_pair(int32_t c) const {
    if (!is_fn(c, "eq")) return {-1, -1};
    auto ks = b.kids(c);
    auto side_info = [&](int32_t e, bool* all_outer, bool* has) {
      *all_outer = true;
      *has = false;
      walk_expr(e, [&](int32_t x) {
        const PNode n = b.nodes[x];
        if (n.kind == E_OUTERREF) *has = true;
        else if (n.kind == E_COLREF) *all_outer = false;
      });
    };
    bool a_all, a_has, b_all, b_has;
    side_info(ks[0], &a_all, &a_has);
    side_info(ks[1], &b_all, &b_has);
    if (a_has && a_all && !b_has) return {ks[0], ks[1]};
    if (b_has && b_all && !a_has) return {ks[1], ks[0]};
    return {-1, -1};
  }

  int32_t outer_to_local(int32_t e) const {
    return transform_expr(e, [&](int32_t x) -> int32_t {
      const PNode n = b.nodes[x];
      if (n.kind == E_OUTERREF)
        return b.add(E_COLREF, {}, n.flags, n.ival, n.dval, n.s0, n.s1);
      return x;
    });
  }

  bool nullable_expr(int32_t e) const {
    bool out = false;
    walk_expr(e, [&](int32_t x) {
      const PNode n = b.nodes[x];
      if ((n.kind == E_COLREF || n.kind == E_OUTERREF) && (n.flags & 1))
        out = true;
      if (n.kind == E_LITERAL && (n.flags & 0xFF) == LT_NULL) out = true;
    });
    return out;
  }

  void all_exprs_below(int32_t plan, std::vector<int32_t>& out) const {
    for (int32_t e : node_exprs(plan)) out.push_back(e);
    // TableScan filters count as node exprs in Python? _node_exprs has no
    // TableScan case -> no.  walk_plan order: node then children.
    for (int32_t k : inputs_of(plan)) all_exprs_below(k, out);
  }

  int32_t mk_field_node(const std::string& name, int ty, bool nullable) const {
    return b.add(P_FIELD, {}, (ty << 8) | (nullable ? 1 : 0), 0, 0.0,
                 b.intern_mut(name));
  }

  int32_t mk_colref_e(int64_t idx, const std::string& name, int ty,
                      bool nullable) const {
    return b.add(E_COLREF, {}, ty_flags(ty, nullable ? 1 : 0), idx, 0.0,
                 b.intern_mut(name));
  }

  struct Correlation {
    int32_t core = -1;                        // plan id (or -1: no match)
    std::vector<int32_t> proj_exprs;          // exprs of the top projection
    std::vector<std::pair<int32_t, int32_t>> pairs;  // (outer, inner)
    std::vector<int32_t> corr_residuals;
  };

  Correlation extract_correlation(int32_t sub) const {
    Correlation out;
    int32_t node = sub;
    while (b.nodes[node].kind == P_SUBQUERY_ALIAS ||
           b.nodes[node].kind == P_DISTINCT)
      node = b.kids(node)[0];
    if (b.nodes[node].kind != P_PROJECTION) return out;
    const PNode pn = b.nodes[node];
    auto pks = b.kids(node);
    std::vector<int32_t> proj_exprs(pks.begin() + 1 + pn.ival, pks.end());
    std::vector<int32_t> kept;
    int32_t core = pks[0];
    std::vector<std::pair<int32_t, int32_t>> pairs;
    std::vector<int32_t> corr_residuals;
    while (b.nodes[core].kind == P_FILTER) {
      auto fks = b.kids(core);
      std::vector<int32_t> cjs;
      conjuncts_of(fks.back(), cjs);
      for (int32_t c : cjs) {
        auto pr = outer_eq_pair(c);
        if (pr.first >= 0) {
          pairs.push_back(pr);
        } else if (has_outer_ref(c)) {
          if (has_subquery(c)) return out;
          corr_residuals.push_back(c);
        } else {
          kept.push_back(c);
        }
      }
      core = fks[0];
    }
    std::vector<int32_t> below;
    all_exprs_below(core, below);
    for (int32_t e : below)
      if (has_outer_ref(e)) return out;
    for (int32_t e : proj_exprs)
      if (has_outer_ref(e)) return out;
    if (!kept.empty()) core = mk_filter(core, conjoin(kept));
    out.core = core;
    out.proj_exprs = proj_exprs;
    out.pairs = pairs;
    out.corr_residuals = corr_residuals;
    return out;
  }

  int expr_ty(int32_t e) const { return ty_of_flags(b.nodes[e].flags); }

  int32_t rewrite_exists(int32_t plan_e, int32_t child, bool anti,
                         bool mark = false) const {
    Correlation c = extract_correlation(plan_e);
    if (c.core < 0 || (c.pairs.empty() && c.corr_residuals.empty()))
      return -1;
    int nleft = schema_width(child);
    std::vector<int32_t> key_exprs;
    for (auto& pr : c.pairs) key_exprs.push_back(pr.second);
    std::set<int64_t> resid_inner_set;
    for (int32_t r : c.corr_residuals)
      walk_expr(r, [&](int32_t x) {
        const PNode n = b.nodes[x];
        if (n.kind == E_COLREF) resid_inner_set.insert(n.ival);
      });
    std::vector<int64_t> resid_inner(resid_inner_set.begin(),
                                     resid_inner_set.end());
    std::vector<int32_t> out_exprs = key_exprs;
    auto core_fields = schema_of(c.core);
    for (int64_t i : resid_inner) {
      const PNode f = b.nodes[core_fields[i]];
      out_exprs.push_back(b.add(E_COLREF, {}, f.flags, i, 0.0, f.s0));
    }
    std::vector<int32_t> fields;
    for (size_t i = 0; i < out_exprs.size(); ++i)
      fields.push_back(mk_field_node("__ckey" + std::to_string(i),
                                     expr_ty(out_exprs[i]), true));
    std::vector<int32_t> sk{c.core};
    sk.insert(sk.end(), fields.begin(), fields.end());
    sk.insert(sk.end(), out_exprs.begin(), out_exprs.end());
    int32_t sub = b.add(P_PROJECTION, sk, 0, (int64_t)fields.size());
    std::vector<int32_t> on;
    for (size_t i = 0; i < c.pairs.size(); ++i) {
      int32_t le = outer_to_local(c.pairs[i].first);
      int32_t re = mk_colref_e(nleft + i, "__ckey" + std::to_string(i),
                               expr_ty(key_exprs[i]), true);
      on.push_back(b.add(P_ON_PAIR, {le, re}));
    }
    std::map<int64_t, int64_t> inner_pos;
    for (size_t j = 0; j < resid_inner.size(); ++j)
      inner_pos[resid_inner[j]] = nleft + key_exprs.size() + j;
    std::vector<int32_t> fixed;
    for (int32_t r : c.corr_residuals) {
      fixed.push_back(transform_expr(r, [&](int32_t x) -> int32_t {
        const PNode n = b.nodes[x];
        if (n.kind == E_OUTERREF)
          return b.add(E_COLREF, {}, n.flags, n.ival, n.dval, n.s0, n.s1);
        if (n.kind == E_COLREF)
          return b.add(E_COLREF, {}, n.flags, inner_pos.at(n.ival), n.dval,
                       n.s0, n.s1);
        return x;
      }));
    }
    int32_t jfilter = fixed.empty() ? -1 : conjoin(fixed);
    if (mark) {
      std::vector<int32_t> mfields = schema_of(child);
      mfields.push_back(mk_field_node("__mark", TY_BOOLEAN, false));
      JoinParts jp{child, sub, mfields, on, jfilter, "LEFTMARK", false};
      return mk_join(jp);
    }
    JoinParts jp{child, sub, schema_of(child), on, jfilter,
                 anti ? "LEFTANTI" : "LEFTSEMI", false};
    return mk_join(jp);
  }

  int32_t rewrite_in(int32_t arg, int32_t plan_e, int32_t child,
                     bool anti) const {
    Correlation c = extract_correlation(plan_e);
    if (c.core < 0 || !c.corr_residuals.empty()) return -1;
    auto sub_schema = schema_of(plan_e);
    bool sub_nullable = (b.nodes[sub_schema[0]].flags & 1) != 0;
    bool null_aware = anti && (sub_nullable || nullable_expr(arg));
    int nleft = schema_width(child);
    std::vector<int32_t> out_exprs{c.proj_exprs[0]};
    for (auto& pr : c.pairs) out_exprs.push_back(pr.second);
    std::vector<int32_t> fields;
    for (size_t i = 0; i < out_exprs.size(); ++i)
      fields.push_back(mk_field_node("__ckey" + std::to_string(i),
                                     expr_ty(out_exprs[i]), true));
    std::vector<int32_t> sk{c.core};
    sk.insert(sk.end(), fields.begin(), fields.end());
    sk.insert(sk.end(), out_exprs.begin(), out_exprs.end());
    int32_t sub = b.add(P_PROJECTION, sk, 0, (int64_t)fields.size());
    std::vector<int32_t> on;
    on.push_back(b.add(P_ON_PAIR, {arg, mk_colref_e(
        nleft, "__ckey0", expr_ty(out_exprs[0]), true)}));
    for (size_t i = 0; i < c.pairs.size(); ++i) {
      on.push_back(b.add(P_ON_PAIR, {
          outer_to_local(c.pairs[i].first),
          mk_colref_e(nleft + 1 + i, "__ckey" + std::to_string(1 + i),
                      expr_ty(out_exprs[1 + i]), true)}));
    }
    JoinParts jp{child, sub, schema_of(child), on, -1,
                 anti ? "LEFTANTI" : "LEFTSEMI", null_aware};
    return mk_join(jp);
  }

  // try_rewrite for one conjunct; -1 when not applicable
  int32_t try_rewrite_conjunct(int32_t pred, int32_t child) const {
    const PNode n = b.nodes[pred];
    if (n.kind == E_EXISTS)
      return rewrite_exists(b.kids(pred)[0], child, (n.flags & 1) != 0);
    if (is_fn(pred, "not")) {
      int32_t inner = b.kids(pred)[0];
      const PNode in_ = b.nodes[inner];
      if (in_.kind == E_EXISTS)
        return rewrite_exists(b.kids(inner)[0], child, !(in_.flags & 1));
      if (in_.kind == E_INSUBQ) {
        auto iks = b.kids(inner);
        return rewrite_in(iks[0], iks[1], child, !(in_.flags & 1));
      }
    }
    if (n.kind == E_INSUBQ) {
      auto ks = b.kids(pred);
      return rewrite_in(ks[0], ks[1], child, (n.flags & 1) != 0);
    }
    return -1;
  }

  // scalar-subquery rewrite; returns (new_child, new_conjunct) or (-1, _)
  std::pair<int32_t, int32_t> rewrite_scalar(int32_t conjunct,
                                             int32_t child) const {
    std::vector<int32_t> subqs;
    walk_expr(conjunct, [&](int32_t x) {
      if (b.nodes[x].kind == E_SCALARSUBQ) subqs.push_back(x);
    });
    if (subqs.size() != 1) return {-1, -1};
    int32_t sq = subqs[0];
    int32_t node = b.kids(sq)[0];
    while (b.nodes[node].kind == P_SUBQUERY_ALIAS) node = b.kids(node)[0];
    if (b.nodes[node].kind != P_PROJECTION) return {-1, -1};
    const PNode pn = b.nodes[node];
    auto pks = b.kids(node);
    std::vector<int32_t> proj_exprs(pks.begin() + 1 + pn.ival, pks.end());
    if (proj_exprs.size() != 1) return {-1, -1};
    int32_t agg = pks[0];
    if (b.nodes[agg].kind != P_AGGREGATE || b.nodes[agg].flags != 0)
      return {-1, -1};
    const PNode an = b.nodes[agg];
    auto aks = b.kids(agg);
    std::vector<int32_t> agg_exprs(aks.begin() + 1 + an.ival, aks.end());
    int32_t core = aks[0];
    std::vector<std::pair<int32_t, int32_t>> pairs;
    std::vector<int32_t> kept;
    while (b.nodes[core].kind == P_FILTER) {
      auto fks = b.kids(core);
      std::vector<int32_t> cjs;
      conjuncts_of(fks.back(), cjs);
      for (int32_t cj : cjs) {
        auto pr = outer_eq_pair(cj);
        if (pr.first >= 0) pairs.push_back(pr);
        else if (has_outer_ref(cj)) return {-1, -1};
        else kept.push_back(cj);
      }
      core = fks[0];
    }
    if (pairs.empty()) return {-1, -1};
    std::vector<int32_t> below;
    all_exprs_below(core, below);
    for (int32_t e : agg_exprs) below.push_back(e);
    for (int32_t e : below)
      if (has_outer_ref(e)) return {-1, -1};
    if (!kept.empty()) core = mk_filter(core, conjoin(kept));
    std::vector<int32_t> key_exprs;
    for (auto& pr : pairs) key_exprs.push_back(pr.second);
    int ngroups = (int)key_exprs.size();
    int naggs = (int)agg_exprs.size();
    std::vector<int32_t> agg_fields;
    for (int i = 0; i < ngroups; ++i)
      agg_fields.push_back(mk_field_node("__sckey" + std::to_string(i),
                                         expr_ty(key_exprs[i]), true));
    for (int j = 0; j < naggs; ++j)
      agg_fields.push_back(mk_field_node("__scagg" + std::to_string(j),
                                         expr_ty(agg_exprs[j]), true));
    std::vector<int32_t> ak{core};
    ak.insert(ak.end(), agg_fields.begin(), agg_fields.end());
    ak.insert(ak.end(), key_exprs.begin(), key_exprs.end());
    ak.insert(ak.end(), agg_exprs.begin(), agg_exprs.end());
    int32_t agg2 = b.add(P_AGGREGATE, ak, ngroups,
                         (int64_t)agg_fields.size());
    std::vector<int32_t> sub_fields;
    for (int j = 0; j < naggs; ++j)
      sub_fields.push_back(mk_field_node("__scagg" + std::to_string(j),
                                         expr_ty(agg_exprs[j]), true));
    for (int i = 0; i < ngroups; ++i)
      sub_fields.push_back(mk_field_node("__sckey" + std::to_string(i),
                                         expr_ty(key_exprs[i]), true));
    std::vector<int32_t> sub_exprs;
    for (int j = 0; j < naggs; ++j)
      sub_exprs.push_back(mk_colref_e(ngroups + j,
                                      "__scagg" + std::to_string(j),
                                      expr_ty(agg_exprs[j]), true));
    for (int i = 0; i < ngroups; ++i)
      sub_exprs.push_back(mk_colref_e(i, "__sckey" + std::to_string(i),
                                      expr_ty(key_exprs[i]), true));
    std::vector<int32_t> pk2{agg2};
    pk2.insert(pk2.end(), sub_fields.begin(), sub_fields.end());
    pk2.insert(pk2.end(), sub_exprs.begin(), sub_exprs.end());
    int32_t sub = b.add(P_PROJECTION, pk2, 0, (int64_t)sub_fields.size());
    int nleft = schema_width(child);
    std::vector<int32_t> on;
    for (int i = 0; i < ngroups; ++i)
      on.push_back(b.add(P_ON_PAIR, {
          outer_to_local(pairs[i].first),
          mk_colref_e(nleft + naggs + i, "__sckey" + std::to_string(i),
                      expr_ty(key_exprs[i]), true)}));
    std::vector<int32_t> join_fields = schema_of(child);
    join_fields.insert(join_fields.end(), sub_fields.begin(),
                       sub_fields.end());
    JoinParts jp{child, sub, join_fields, on, -1, "LEFT", false};
    int32_t join = mk_join(jp);
    // rebuild the subquery's projected expression against the join output
    static const std::set<std::string> count_like = {"count", "count_star",
                                                     "regr_count"};
    int32_t val_expr = transform_expr(proj_exprs[0], [&](int32_t x) -> int32_t {
      const PNode m = b.nodes[x];
      if (m.kind == E_COLREF) {
        int64_t j = m.ival;
        int32_t a = agg_exprs[j];
        int aty = expr_ty(a);
        int32_t ref = mk_colref_e(nleft + j, "__scagg" + std::to_string(j),
                                  aty, true);
        std::string fname = str_of(b.nodes[a].s0);
        if (count_like.count(fname)) {
          int32_t zero = b.add(E_LITERAL, {}, ty_flags(aty, LT_INT), 0);
          return b.add(E_SCALARFN, {ref, zero}, ty_flags(aty), 0, 0.0,
                       b.intern_mut("coalesce"));
        }
        return ref;
      }
      return x;
    });
    int32_t new_conjunct = transform_expr(conjunct, [&](int32_t x) -> int32_t {
      if (x == sq || b.eq(x, sq)) return val_expr;
      return x;
    });
    return {join, new_conjunct};
  }

  bool plan_has_outer_ref(int32_t plan) const {
    std::vector<int32_t> below;
    all_exprs_below(plan, below);
    for (int32_t e : below)
      if (has_outer_ref(e)) return true;
    return false;
  }

  // correlated EXISTS under OR / mixed boolean logic: each becomes a MARK
  // JOIN appending a boolean matched column (rules._rewrite_marks twin)
  std::pair<int32_t, int32_t> rewrite_marks(int32_t conjunct,
                                            int32_t child) const {
    std::vector<int32_t> marks;
    walk_expr(conjunct, [&](int32_t x) {
      if (b.nodes[x].kind == E_EXISTS &&
          plan_has_outer_ref(b.kids(x)[0]))
        marks.push_back(x);
    });
    if (marks.empty()) return {-1, -1};
    // nodes are immutable: a mid-loop decline just discards the local chain
    std::map<int32_t, int32_t> replacements;
    for (int32_t sub : marks) {
      int32_t mark_join = rewrite_exists(b.kids(sub)[0], child, false, true);
      if (mark_join < 0) return {-1, -1};
      int nleft = schema_width(child);
      child = mark_join;
      int32_t ref = mk_colref_e(nleft, "__mark", TY_BOOLEAN, false);
      if (b.nodes[sub].flags & 1)  // NOT EXISTS
        ref = b.add(E_SCALARFN, {ref}, ty_flags(TY_BOOLEAN), 0, 0.0,
                    b.intern_mut("not"));
      replacements[sub] = ref;
    }
    int32_t out = transform_expr(conjunct, [&](int32_t x) -> int32_t {
      auto it = replacements.find(x);
      return it == replacements.end() ? x : it->second;
    });
    return {child, out};
  }

  int32_t rule_decorrelate(int32_t plan) const {
    std::function<int32_t(int32_t)> go = [&](int32_t node0) -> int32_t {
      int32_t node = node0;
      auto ins = inputs_of(node);
      if (!ins.empty()) {
        std::vector<int32_t> ni;
        bool changed = false;
        for (int32_t k : ins) {
          int32_t t = go(k);
          changed |= t != k;
          ni.push_back(t);
        }
        if (changed) node = with_inputs(node, ni);
      }
      // recurse into subquery plans embedded in expressions
      node = map_node_exprs(node, [&](int32_t e) {
        return transform_expr(e, [&](int32_t x) -> int32_t {
          const PNode m = b.nodes[x];
          if (m.kind == E_SCALARSUBQ || m.kind == E_EXISTS) {
            auto ks = b.kids(x);
            int32_t np = go(ks[0]);
            if (np == ks[0]) return x;
            return b.add(m.kind, {np}, m.flags, m.ival, m.dval, m.s0, m.s1);
          }
          if (m.kind == E_INSUBQ) {
            auto ks = b.kids(x);
            int32_t np = go(ks[1]);
            if (np == ks[1]) return x;
            return b.add(m.kind, {ks[0], np}, m.flags, m.ival, m.dval, m.s0,
                         m.s1);
          }
          return x;
        });
      });
      const PNode n = b.nodes[node];
      if (n.kind != P_FILTER) return node;
      auto ks = b.kids(node);
      int32_t child = ks[0];
      // factor common conjuncts out of disjunctions first (q41: the
      // correlation hides as (corr AND a) OR (corr AND b))
      int32_t factored = rewrite_disjunction(ks.back());
      std::vector<int32_t> parts;
      conjuncts_of(factored, parts);
      int orig_width = schema_width(child);
      auto orig_fields = schema_of(child);
      bool changed = false;
      std::vector<int32_t> kept;
      for (int32_t c : parts) {
        int32_t new_child = try_rewrite_conjunct(c, child);
        if (new_child >= 0) {
          child = new_child;
          changed = true;
          continue;
        }
        auto res = rewrite_scalar(c, child);
        if (res.first >= 0) {
          child = res.first;
          kept.push_back(res.second);
          changed = true;
          continue;
        }
        auto mres = rewrite_marks(c, child);
        if (mres.first >= 0) {
          child = mres.first;
          kept.push_back(mres.second);
          changed = true;
          continue;
        }
        kept.push_back(c);
      }
      if (!changed) {
        if (b.eq(factored, ks.back())) return node;
        // keep the factored predicate for the outer extraction walk
        return mk_filter_with_fields(
            child, factored,
            std::vector<int32_t>(ks.begin() + 1, ks.end() - 1));
      }
      int32_t out = kept.empty() ? child : mk_filter(child, conjoin(kept));
      if (schema_width(out) != orig_width) {
        std::vector<int32_t> refs, nfields;
        for (int i = 0; i < orig_width; ++i) {
          const PNode f = b.nodes[orig_fields[i]];
          refs.push_back(b.add(E_COLREF, {}, f.flags, i, 0.0, f.s0));
          nfields.push_back(orig_fields[i]);
        }
        std::vector<int32_t> nk{out};
        nk.insert(nk.end(), nfields.begin(), nfields.end());
        nk.insert(nk.end(), refs.begin(), refs.end());
        out = b.add(P_PROJECTION, nk, 0, (int64_t)nfields.size());
      }
      return out;
    };
    return go(plan);
  }

  // ---------------- JoinReorder (join_reorder.rs parity) ----------------
  // fact/dimension heuristic over a flattened filter-free INNER-join chain;
  // twin of planner/optimizer/join_reorder.py (the differential reference)
  mutable const Catalog* cat_ptr = nullptr;
  mutable double jr_ratio = 0.7;
  mutable int jr_max_facts = 2;
  mutable bool jr_preserve = true;
  mutable double jr_selectivity = 1.0;

  double table_rows(int32_t node) const {
    while (true) {
      int k = b.nodes[node].kind;
      if (k == P_FILTER || k == P_SUBQUERY_ALIAS || k == P_PROJECTION ||
          k == P_AGGREGATE || k == P_WINDOW || k == P_LIMIT || k == P_DISTINCT)
        node = b.kids(node)[0];
      else
        break;
    }
    const PNode n = b.nodes[node];
    if (n.kind != P_TABLESCAN || cat_ptr == nullptr) return -1.0;
    auto sit = cat_ptr->schemas.find(str_of(n.s0));
    if (sit == cat_ptr->schemas.end()) return -1.0;
    auto tit = sit->second.find(str_of(n.s1));
    if (tit == sit->second.end()) return -1.0;
    return tit->second.row_count;
  }

  bool is_not_null_pred(int32_t e) const {
    const PNode n = b.nodes[e];
    if (n.kind != E_SCALARFN) return false;
    std::string op = str_of(n.s0);
    return op == "is_not_null" || op == "isnotnull";
  }

  bool has_real_filter(int32_t node) const {
    const PNode n = b.nodes[node];
    if (n.kind == P_FILTER) {
      std::vector<int32_t> cjs;
      conjuncts_of(b.kids(node).back(), cjs);
      for (int32_t c : cjs)
        if (!is_not_null_pred(c)) return true;
      return has_real_filter(b.kids(node)[0]);
    }
    if (n.kind == P_TABLESCAN) {
      if (!(n.flags & 2)) return false;
      auto ks = b.kids(node);
      for (int32_t k : ks) {
        int kk = b.nodes[k].kind;
        if (kk != P_FIELD && kk != P_PART && !is_not_null_pred(k))
          return true;
      }
      return false;
    }
    for (int32_t k : inputs_of(node))
      if (has_real_filter(k)) return true;
    return false;
  }

  // (column index, outermost cast wrapper or -1); {-1,-1} for computed keys
  std::pair<int64_t, int32_t> single_col(int32_t e) const {
    int32_t wrap = -1;
    int32_t x = e;
    while (b.nodes[x].kind == E_CAST) {
      wrap = e;
      x = b.kids(x)[0];
    }
    if (b.nodes[x].kind == E_COLREF) return {b.nodes[x].ival, wrap};
    return {-1, -1};
  }

  int32_t rewrap(int32_t wrap, int32_t ref) const {
    if (wrap < 0) return ref;
    const PNode n = b.nodes[wrap];
    if (n.kind == E_CAST) {
      int32_t inner = rewrap(b.kids(wrap)[0], ref);
      return b.add(E_CAST, {inner}, n.flags, n.ival, n.dval, n.s0, n.s1);
    }
    return ref;
  }

  struct JrLeaf {
    int32_t plan;
    int start;
    int width;
    double size;
    bool filtered;
  };
  struct JrCond {
    int la, oa;
    int32_t wa;
    int lb, ob;
    int32_t wb;
  };

  bool jr_flatten(int32_t node, int base, std::vector<JrLeaf>& leaves,
                  std::vector<std::array<int64_t, 4>>& conds) const {
    const PNode n = b.nodes[node];
    if (n.kind == P_JOIN) {
      JoinParts jp = join_parts(node);
      if (jp.jt == "INNER" && jp.residual < 0 && !jp.null_aware) {
        int nleft = schema_width(jp.left);
        if (!jr_flatten(jp.left, base, leaves, conds)) return false;
        if (!jr_flatten(jp.right, base + nleft, leaves, conds)) return false;
        for (int32_t pr : jp.on) {
          auto pk = b.kids(pr);
          auto lc = single_col(pk[0]);
          auto rc = single_col(pk[1]);
          if (lc.first < 0 || rc.first < 0) return false;
          conds.push_back({base + lc.first, base + rc.first,
                           (int64_t)lc.second, (int64_t)rc.second});
        }
        return true;
      }
    }
    if (n.kind == P_CROSSJOIN) {
      auto ks = b.kids(node);
      int nleft = schema_width(ks[0]);
      return jr_flatten(ks[0], base, leaves, conds) &&
             jr_flatten(ks[1], base + nleft, leaves, conds);
    }
    double size = table_rows(node);
    leaves.push_back({node, base, schema_width(node),
                      size < 0 ? 100.0 : size, has_real_filter(node)});
    return true;
  }

  struct JrTree {
    int32_t plan;
    std::vector<int> leaf_order;
  };

  struct JrBuilder {
    const Optimizer& opt;
    const std::vector<JrLeaf>& leaves;
    // [((leaf, off, wrap), (leaf, off, wrap))]
    std::vector<std::array<int64_t, 6>> remaining;
    JrTree cur;

    int offset_of(const JrTree& t, int leaf_idx) const {
      int off = 0;
      for (int li : t.leaf_order) {
        if (li == leaf_idx) return off;
        off += leaves[li].width;
      }
      return -1;
    }

    std::vector<std::array<int64_t, 6>> conds_between(
        const std::set<int>& in_tree, const std::set<int>& leaf_set) {
      std::vector<std::array<int64_t, 6>> found, rest;
      for (auto& c : remaining) {
        int la = (int)c[0], lb = (int)c[3];
        if (in_tree.count(la) && leaf_set.count(lb)) {
          found.push_back(c);
        } else if (in_tree.count(lb) && leaf_set.count(la)) {
          found.push_back({c[3], c[4], c[5], c[0], c[1], c[2]});
        } else {
          rest.push_back(c);
        }
      }
      remaining = rest;
      return found;
    }

    JrTree make_join(const JrTree& t, const JrTree& other,
                     const std::vector<std::array<int64_t, 6>>& pairs) {
      PBuilder& b = opt.b;
      int lwidth = 0;
      for (int li : t.leaf_order) lwidth += leaves[li].width;
      std::vector<int32_t> on;
      for (auto& pr : pairs) {
        int ll = (int)pr[0], lo = (int)pr[1];
        int32_t lw = (int32_t)pr[2];
        int rl = (int)pr[3], ro = (int)pr[4];
        int32_t rw = (int32_t)pr[5];
        auto lfields = opt.schema_of(leaves[ll].plan);
        auto rfields = opt.schema_of(leaves[rl].plan);
        const PNode lf = b.nodes[lfields[lo]];
        const PNode rf = b.nodes[rfields[ro]];
        int lpos = offset_of(t, ll) + lo;
        int rpos = lwidth + offset_of(other, rl) + ro;
        int32_t le = opt.rewrap(
            lw, b.add(E_COLREF, {}, lf.flags, lpos, 0.0, lf.s0));
        int32_t re = opt.rewrap(
            rw, b.add(E_COLREF, {}, rf.flags, rpos, 0.0, rf.s0));
        on.push_back(b.add(P_ON_PAIR, {le, re}));
      }
      std::vector<int32_t> fields = opt.schema_of(t.plan);
      auto of = opt.schema_of(other.plan);
      fields.insert(fields.end(), of.begin(), of.end());
      JoinParts jp{t.plan, other.plan, fields, on, -1, "INNER", false};
      JrTree out;
      out.plan = opt.mk_join(jp);
      out.leaf_order = t.leaf_order;
      out.leaf_order.insert(out.leaf_order.end(), other.leaf_order.begin(),
                            other.leaf_order.end());
      return out;
    }

    void start(int leaf_idx) { cur = {leaves[leaf_idx].plan, {leaf_idx}}; }

    bool try_join(int leaf_idx) {
      std::set<int> in_tree(cur.leaf_order.begin(), cur.leaf_order.end());
      auto pairs = conds_between(in_tree, {leaf_idx});
      if (pairs.empty()) return false;
      cur = make_join(cur, {leaves[leaf_idx].plan, {leaf_idx}}, pairs);
      return true;
    }
  };

  int32_t reorder_chain(int32_t join_id) const {
    std::vector<JrLeaf> leaves;
    std::vector<std::array<int64_t, 4>> conds4;
    if (!jr_flatten(join_id, 0, leaves, conds4)) return -1;
    if (leaves.size() < 3) return -1;
    double largest = 0;
    for (auto& l : leaves) largest = std::max(largest, l.size);
    std::vector<int> facts, dims;
    for (size_t i = 0; i < leaves.size(); ++i) {
      if (leaves[i].size / std::max(largest, 1e-9) > jr_ratio)
        facts.push_back((int)i);
      else
        dims.push_back((int)i);
    }
    if (facts.empty() || dims.empty() || (int)facts.size() > jr_max_facts)
      return -1;
    std::vector<int> unfiltered, filtered;
    for (int i : dims)
      (leaves[i].filtered ? filtered : unfiltered).push_back(i);
    auto stable_by_size = [&](std::vector<int>& v, double scale) {
      std::stable_sort(v.begin(), v.end(), [&](int a2, int b2) {
        return leaves[a2].size * scale < leaves[b2].size * scale;
      });
    };
    if (!jr_preserve) stable_by_size(unfiltered, 1.0);
    stable_by_size(filtered, jr_selectivity);
    std::vector<int> ordered;
    size_t fi = 0, ui = 0;
    while (fi < filtered.size() || ui < unfiltered.size()) {
      if (fi < filtered.size() &&
          (ui >= unfiltered.size() ||
           leaves[filtered[fi]].size * jr_selectivity <
               leaves[unfiltered[ui]].size)) {
        ordered.push_back(filtered[fi++]);
      } else {
        ordered.push_back(unfiltered[ui++]);
      }
    }
    // global position -> (leaf, offset)
    std::map<int, std::pair<int, int>> pos_to_leaf;
    for (size_t li = 0; li < leaves.size(); ++li)
      for (int off = 0; off < leaves[li].width; ++off)
        pos_to_leaf[leaves[li].start + off] = {(int)li, off};
    JrBuilder builder{*this, leaves, {}, {}};
    for (auto& c : conds4) {
      auto a = pos_to_leaf.at((int)c[0]);
      auto d = pos_to_leaf.at((int)c[1]);
      builder.remaining.push_back({(int64_t)a.first, (int64_t)a.second, c[2],
                                   (int64_t)d.first, (int64_t)d.second, c[3]});
    }
    std::vector<int> unused = ordered;
    std::vector<JrTree> trees;
    for (int f : facts) {
      builder.start(f);
      for (int pass = 0; pass < 2 && !unused.empty(); ++pass) {
        std::vector<int> still;
        for (int d : unused)
          if (!builder.try_join(d)) still.push_back(d);
        unused = still;
      }
      trees.push_back(builder.cur);
    }
    if (!unused.empty()) return -1;
    JrTree tree = trees[0];
    for (size_t i = 1; i < trees.size(); ++i) {
      std::set<int> a(tree.leaf_order.begin(), tree.leaf_order.end());
      std::set<int> d(trees[i].leaf_order.begin(), trees[i].leaf_order.end());
      auto pairs = builder.conds_between(a, d);
      if (pairs.empty()) return -1;
      tree = builder.make_join(tree, trees[i], pairs);
    }
    if (!builder.remaining.empty()) return -1;
    // restore the original column order
    std::map<std::pair<int, int>, int> new_pos;
    int off = 0;
    for (int li : tree.leaf_order) {
      for (int o = 0; o < leaves[li].width; ++o) new_pos[{li, o}] = off + o;
      off += leaves[li].width;
    }
    auto out_fields = schema_of(join_id);
    std::vector<int32_t> exprs;
    for (size_t i = 0; i < out_fields.size(); ++i) {
      const PNode f = b.nodes[out_fields[i]];
      exprs.push_back(b.add(E_COLREF, {}, f.flags,
                            new_pos.at(pos_to_leaf.at((int)i)), 0.0, f.s0));
    }
    std::vector<int32_t> nk{tree.plan};
    nk.insert(nk.end(), out_fields.begin(), out_fields.end());
    nk.insert(nk.end(), exprs.begin(), exprs.end());
    return b.add(P_PROJECTION, nk, 0, (int64_t)out_fields.size());
  }

  bool is_inner_chain_node(int32_t id) const {
    const PNode n = b.nodes[id];
    if (n.kind != P_JOIN) return false;
    JoinParts jp = join_parts(id);
    return jp.jt == "INNER" && jp.residual < 0 && !jp.null_aware;
  }

  int32_t rule_join_reorder(int32_t plan) const {
    std::function<int32_t(int32_t, bool)> go =
        [&](int32_t node, bool parent_is_chain) -> int32_t {
      bool in_chain = is_inner_chain_node(node);
      bool is_chain_head = in_chain && !parent_is_chain;
      auto ins = inputs_of(node);
      if (!ins.empty()) {
        std::vector<int32_t> ni;
        bool changed = false;
        for (int32_t k : ins) {
          int32_t t = go(k, in_chain);
          changed |= t != k;
          ni.push_back(t);
        }
        if (changed) node = with_inputs(node, ni);
      }
      if (is_chain_head) {
        int32_t nw = reorder_chain(node);
        if (nw >= 0) return nw;
      }
      return node;
    };
    return go(plan, false);
  }

  // ---------------- driver ----------------
  int32_t optimize(int32_t plan) const {
    for (int pass = 0; pass < 2; ++pass) {
      plan = rule_simplify(plan);
      plan = rule_unwrap_cast(plan);
      plan = rule_decorrelate(plan);
      plan = rule_simplify(plan);
      plan = rule_disjunctive(plan);
      plan = rule_elim_cross_join(plan);
      plan = rule_elim_limit(plan);
      // FilterNullJoinKeys: no-op (join kernels drop NULL keys natively)
      plan = rule_elim_outer_join(plan);
      plan = rule_pushdown_limit(plan);
      plan = rule_pushdown_filter(plan);
      plan = rule_simplify(plan);
      plan = rule_unwrap_cast(plan);
      plan = rule_pushdown_projection(plan);
      plan = rule_pushdown_limit(plan);
    }
    return plan;
  }
};

}  // namespace

extern "C" {

// rc: 0 = ok (*out = flat plan buffer); 1 = unsupported (Python fallback);
// 2 = bind error (*out = utf-8 message); 3 = parse error (*out = int64 pos +
// msg, same payload as dsql_parse rc 2).
int32_t dsql_bind(const char* sql, int64_t n, const uint8_t* catalog_buf,
                  int64_t catalog_len, uint8_t** out, int64_t* out_len) {
  *out = nullptr;
  *out_len = 0;
  uint8_t* ast_buf = nullptr;
  int64_t ast_len = 0;
  int32_t prc = dsql_parse(sql, n, &ast_buf, &ast_len);
  if (prc == 1) return 1;
  if (prc == 2) {  // parse error: forward payload as rc 3
    *out = ast_buf;
    *out_len = ast_len;
    return 3;
  }
  Ast ast;
  bool ok = ast.load(ast_buf, ast_len);
  dsql_buf_free(ast_buf);
  if (!ok) return 1;
  try {
    Catalog cat;
    if (!cat.load(catalog_buf, catalog_len)) return 1;
    auto stmts = ast.kids(ast.root);
    if (stmts.size() != 1) return 1;  // one statement per bind call
    PBuilder pb;
    Binder binder(ast, cat, pb);
    int32_t root = binder.bind_statement(stmts[0]);
    uint8_t* buf = pb.serialize(root, out_len);
    if (!buf) return 1;
    *out = buf;
    return 0;
  } catch (const BindErr& e) {
    // payload: 1 error-class byte (0 BindError / 1 KeyError) + utf-8 message
    uint8_t* buf = static_cast<uint8_t*>(std::malloc(1 + e.msg.size()));
    if (!buf) return 1;
    buf[0] = static_cast<uint8_t>(e.klass);
    std::memcpy(buf + 1, e.msg.data(), e.msg.size());
    *out = buf;
    *out_len = static_cast<int64_t>(1 + e.msg.size());
    return 2;
  } catch (const Unsupported&) {
    return 1;
  } catch (...) {
    return 1;
  }
}

// version 5: SHOW PROFILES (P_SHOW_PROFILES) + EXPLAIN ... FORMAT JSON
// (flag bit 8 riding through P_EXPLAIN)
// version 6: SHOW QUERIES (P_SHOW_QUERIES) + CANCEL QUERY (P_CANCEL_QUERY)
int32_t dsql_binder_abi_version() { return 6; }

// Parse + bind + run the structural optimizer rule loop, all native.
// Same rc codes as dsql_bind; `predicate_pushdown` mirrors the
// sql.predicate_pushdown config knob.  Join reordering / DPP / embedded
// subqueries remain Python post-passes on the decoded plan.
int32_t dsql_plan(const char* sql, int64_t n, const uint8_t* catalog_buf,
                  int64_t catalog_len, int32_t predicate_pushdown,
                  int32_t reorder, double fact_dimension_ratio,
                  int32_t max_fact_tables, int32_t preserve_user_order,
                  double filter_selectivity, uint8_t** out,
                  int64_t* out_len) {
  *out = nullptr;
  *out_len = 0;
  uint8_t* ast_buf = nullptr;
  int64_t ast_len = 0;
  int32_t prc = dsql_parse(sql, n, &ast_buf, &ast_len);
  if (prc == 1) return 1;
  if (prc == 2) {
    *out = ast_buf;
    *out_len = ast_len;
    return 3;
  }
  Ast ast;
  bool ok = ast.load(ast_buf, ast_len);
  dsql_buf_free(ast_buf);
  if (!ok) return 1;
  try {
    Catalog cat;
    if (!cat.load(catalog_buf, catalog_len)) return 1;
    auto stmts = ast.kids(ast.root);
    if (stmts.size() != 1) return 1;
    PBuilder pb;
    Binder binder(ast, cat, pb);
    int32_t root = binder.bind_statement(stmts[0]);
    Optimizer opt(pb, predicate_pushdown != 0);
    root = opt.optimize(root);
    if (reorder) {
      opt.cat_ptr = &cat;
      opt.jr_ratio = fact_dimension_ratio;
      opt.jr_max_facts = max_fact_tables;
      opt.jr_preserve = preserve_user_order != 0;
      opt.jr_selectivity = filter_selectivity;
      root = opt.rule_join_reorder(root);
    }
    uint8_t* buf = pb.serialize(root, out_len);
    if (!buf) return 1;
    *out = buf;
    return 0;
  } catch (const BindErr& e) {
    uint8_t* buf = static_cast<uint8_t*>(std::malloc(1 + e.msg.size()));
    if (!buf) return 1;
    buf[0] = static_cast<uint8_t>(e.klass);
    std::memcpy(buf + 1, e.msg.data(), e.msg.size());
    *out = buf;
    *out_len = static_cast<int64_t>(1 + e.msg.size());
    return 2;
  } catch (const Unsupported&) {
    return 1;
  } catch (...) {
    return 1;
  }
}

// bumped in lockstep with the binder: dsql_plan shares its EXPLAIN encoding
int32_t dsql_optimizer_abi_version() { return 6; }

}  // extern "C"
