"""Relational converters: scan/project/filter/limit/sort/union/values/etc.

Role parity (one class per reference plugin file under
physical/rel/logical/ there): table_scan.py, project.py, filter.py,
limit.py, sort.py, union.py, values.py, empty_relation.py,
subquery_alias.py, sample.py, explain.py, distributeby.py (custom).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ....columnar.column import Column
from ....columnar.dtypes import SqlType
from ....columnar.table import Table
from ....ops.grouping import factorize, group_first_indices, key_arrays
from ....ops.sorting import sort_permutation, topk_permutation
from ....planner import plan as p
from ..base import BaseRelPlugin, unique_names
from ...executor import Executor


@Executor.add_plugin_class
class TableScanPlugin(BaseRelPlugin):
    """Parity: reference table_scan.py:21 (projection + DNF filter pushdown)."""

    class_name = "TableScan"

    def convert(self, rel: p.TableScan, executor) -> Table:
        from ....datacontainer import LazyParquetContainer

        override = executor.table_overrides.get((rel.schema_name, rel.table_name))
        dc = executor.context.schema.get(rel.schema_name)
        dc = dc.tables.get(rel.table_name) if dc is not None else None
        if override is not None:
            # batch-streaming execution: the batch replaces the scan source;
            # projection subset here, filters apply via the common block below
            # (the IO layer only pre-filtered the *convertible* conjuncts)
            table = override
            if rel.projection is not None:
                table = table.select([c for c in rel.projection if c in table.columns])
        elif isinstance(dc, LazyParquetContainer):
            # lazy parquet: read only projected columns; convertible filter
            # conjuncts prune row groups at the IO layer (pyarrow `filters=`,
            # parity: reference table_scan.py:80-119 DNF pushdown)
            from ....physical.utils.filter import filters_to_pyarrow

            names = rel.projection if rel.projection is not None else [
                f.name for f in dc.fields]
            pa_filters, _ = filters_to_pyarrow(rel.filters, list(names))
            table = dc.scan(columns=rel.projection, filters=pa_filters)
        else:
            table = executor.get_table(rel.schema_name, rel.table_name)
            if rel.projection is not None:
                table = table.select(rel.projection)
            # eager operators index rows positionally: exact-length view
            # (padding-aware consumers bypass this plugin entirely)
            table = table.depad()
        if table.has_encoded_columns():
            # eager operators work in value space: compressed columns
            # (columnar/encodings.py) materialize ONCE at the scan — the
            # encoding-aware compiled pipelines never reach this plugin
            executor.context.metrics.inc("columnar.encoding.decode")
            table = table.decode()
        if rel.filters:
            # filters are bound against the *projected* schema
            mask = None
            for f in rel.filters:
                col = executor.eval_expr(f, table)
                m = col.data & col.valid_mask()
                mask = m if mask is None else (mask & m)
            table = table.filter(mask)
        return self.fix_column_to_row_type(table, rel.schema)


@Executor.add_plugin_class
class ProjectionPlugin(BaseRelPlugin):
    """Parity: reference project.py:17 (column-ref shortcut project.py:48-54)."""

    class_name = "Projection"

    def convert(self, rel: p.Projection, executor) -> Table:
        (inp,) = self.assert_inputs(rel, 1, executor)
        from ....planner.expressions import ColumnRef

        names = unique_names([f.name for f in rel.schema])
        cols = {}
        for name, expr in zip(names, rel.exprs):
            if isinstance(expr, ColumnRef) and type(expr) is ColumnRef:
                cols[name] = inp.columns[inp.column_names[expr.index]]
            else:
                cols[name] = executor.eval_expr(expr, inp)
        return Table(cols, inp.num_rows)


@Executor.add_plugin_class
class FilterPlugin(BaseRelPlugin):
    """Parity: reference filter.py:48 (NULL -> False, filter.py:20-45)."""

    class_name = "Filter"

    def convert(self, rel: p.Filter, executor) -> Table:
        (inp,) = self.assert_inputs(rel, 1, executor)
        cond = executor.eval_expr(rel.predicate, inp)
        mask = cond.data & cond.valid_mask()
        return inp.filter(mask)


@Executor.add_plugin_class
class LimitPlugin(BaseRelPlugin):
    """Parity: reference limit.py:18."""

    class_name = "Limit"

    def convert(self, rel: p.Limit, executor) -> Table:
        (inp,) = self.assert_inputs(rel, 1, executor)
        start = rel.skip or 0
        stop = inp.num_rows if rel.fetch is None else start + rel.fetch
        return inp.slice(start, stop)


@Executor.add_plugin_class
class SortPlugin(BaseRelPlugin):
    """Parity: reference sort.py:12 + utils/sort.py (top-k when fetch set)."""

    class_name = "Sort"

    def convert(self, rel: p.Sort, executor) -> Table:
        (inp,) = self.assert_inputs(rel, 1, executor)
        if inp.num_rows == 0:
            return inp
        cols = [executor.eval_expr(k.expr, inp) for k in rel.keys]
        # mesh-sharded input + full sort: sample-based range-partition sort
        # over the mesh (output stays row-sharded; device order IS the sort
        # order).  LIMIT keeps the top-k path below — the k survivors are
        # tiny regardless of sharding.
        if rel.fetch is None and cols:
            from ....parallel import dist_plan
            from ....resilience import ladder

            mesh = dist_plan.should_distribute(
                executor, "sql.distributed.sort", inp)
            if mesh is not None:
                # ladder rung: a capacity overflow inside the collectives
                # sort degrades to the single-program sort below (recorded
                # as resilience.degraded.dist_sort / resilience.fallback)
                sorted_t = ladder.attempt(
                    executor, "dist_sort",
                    lambda: dist_plan.dist_sort_table(
                        mesh, inp, cols,
                        [k.ascending for k in rel.keys],
                        [k.nulls_first_resolved() for k in rel.keys],
                        metrics=executor.context.metrics),
                    rel=rel)
                if sorted_t is not None:
                    return self.fix_column_to_row_type(sorted_t, rel.schema)
        limit = executor.config.get("sql.sort.topk-nelem-limit", 1_000_000)
        if (rel.fetch is not None and len(cols) >= 1
                and rel.fetch * max(len(inp.columns), 1) <= limit):
            # top-k on the primary key then exact sort of the k survivors —
            # parity: reference topk_sort utils/sort.py:78 eligibility
            idx = topk_permutation(cols[0], rel.keys[0].ascending, rel.fetch * 4,
                                   exact_ties=len(cols) > 1)
            if idx is not None:
                sub = inp.take(idx)
                sub_cols = [executor.eval_expr(k.expr, sub) for k in rel.keys]
                perm = sort_permutation(
                    sub_cols, [k.ascending for k in rel.keys],
                    [k.nulls_first_resolved() for k in rel.keys])
                return sub.take(perm[: rel.fetch])
        perm = sort_permutation(
            cols, [k.ascending for k in rel.keys],
            [k.nulls_first_resolved() for k in rel.keys])
        if rel.fetch is not None:
            perm = perm[: rel.fetch]
        return inp.take(perm)


@Executor.add_plugin_class
class UnionPlugin(BaseRelPlugin):
    """Parity: reference union.py (rename to common schema + concat)."""

    class_name = "Union"

    def convert(self, rel: p.Union, executor) -> Table:
        tables = [executor.execute(c) for c in rel.inputs()]
        names = unique_names([f.name for f in rel.schema])
        renamed = []
        for t in tables:
            t = self.fix_dtype_to_row_type(t, rel.schema)
            renamed.append(Table(dict(zip(names, t.columns.values())), t.num_rows))
        return Table.concat(renamed)


@Executor.add_plugin_class
class DistinctPlugin(BaseRelPlugin):
    """DISTINCT via group-id factorization (first occurrence per key)."""

    class_name = "Distinct"

    def convert(self, rel: p.Distinct, executor) -> Table:
        (inp,) = self.assert_inputs(rel, 1, executor)
        if inp.num_rows == 0:
            return inp
        keys = key_arrays([inp.columns[n] for n in inp.column_names])
        gid, order, num_groups = factorize(keys)
        first = group_first_indices(gid, num_groups)
        return inp.take(jnp.sort(first))


def _intersect_except(rel, executor, plugin, anti: bool) -> Table:
    from ....ops.join import join_key_gids, semi_join_mask

    left = executor.execute(rel.inputs()[0])
    right = executor.execute(rel.inputs()[1])
    left = plugin.fix_dtype_to_row_type(left, rel.schema)
    right = plugin.fix_dtype_to_row_type(right, rel.schema)
    lcols = [left.columns[n] for n in left.column_names]
    rcols = [right.columns[n] for n in right.column_names]
    if left.num_rows == 0:
        return left
    # NULLs compare equal in set operations (IS NOT DISTINCT semantics)
    lgid, rgid = join_key_gids(lcols, rcols, null_equals_null=True)
    if rel.all:
        # multiset semantics: INTERSECT ALL -> min(count_l, count_r) copies,
        # EXCEPT ALL -> max(count_l - count_r, 0) copies of each distinct row.
        # lgid/rgid are already dense joint ids (null_equals_null path), so
        # counting needs no second factorize
        num = int(jnp.maximum(lgid.max(), rgid.max() if right.num_rows else 0)) + 1
        gl, gr = lgid, rgid
        cl = jax.ops.segment_sum(jnp.ones_like(gl, dtype=jnp.int64), gl, num)
        cr = jax.ops.segment_sum(jnp.ones_like(gr, dtype=jnp.int64), gr, num)
        out_counts = jnp.maximum(cl - cr, 0) if anti else jnp.minimum(cl, cr)
        first = group_first_indices(gl, num)
        present = jnp.nonzero((out_counts > 0) & (first < left.num_rows))[0]
        reps = out_counts[present]
        rows = jnp.repeat(first[present], reps, total_repeat_length=int(reps.sum()))
        return left.take(rows)
    mask = semi_join_mask(lgid, rgid, anti=anti)
    out = left.filter(mask)
    keys = key_arrays([out.columns[n] for n in out.column_names])
    if out.num_rows:
        gid, _, num = factorize(keys)
        out = out.take(jnp.sort(group_first_indices(gid, num)))
    return out


@Executor.add_plugin_class
class IntersectPlugin(BaseRelPlugin):
    class_name = "Intersect"

    def convert(self, rel, executor) -> Table:
        return _intersect_except(rel, executor, self, anti=False)


@Executor.add_plugin_class
class ExceptPlugin(BaseRelPlugin):
    class_name = "Except"

    def convert(self, rel, executor) -> Table:
        return _intersect_except(rel, executor, self, anti=True)


@Executor.add_plugin_class
class ValuesPlugin(BaseRelPlugin):
    """Parity: reference values.py (literal rows -> one-partition frame)."""

    class_name = "Values"

    def convert(self, rel: p.Values, executor) -> Table:
        from ..base import unique_names as _un
        from ....physical.rex.convert import _literal_column

        names = _un([f.name for f in rel.schema])
        cols = {}
        nrows = len(rel.rows)
        for j, (name, f) in enumerate(zip(names, rel.schema)):
            vals = []
            one_row = Table({}, 1)
            for row in rel.rows:
                c = executor.eval_expr(row[j], one_row)
                vals.append(c)
            from ....columnar.concat import concat_columns

            col = concat_columns(vals) if vals else Column.from_scalar(None, 0, f.sql_type)
            cols[name] = col.cast(f.sql_type) if col.sql_type != f.sql_type else col
        return Table(cols, nrows)


@Executor.add_plugin_class
class EmptyRelationPlugin(BaseRelPlugin):
    """Parity: reference empty_relation.py (SELECT without FROM)."""

    class_name = "EmptyRelation"

    def convert(self, rel: p.EmptyRelation, executor) -> Table:
        n = 1 if rel.produce_one_row else 0
        names = unique_names([f.name for f in rel.schema])
        cols = {name: Column.from_scalar(None, n, f.sql_type)
                for name, f in zip(names, rel.schema)}
        return Table(cols, n)


@Executor.add_plugin_class
class SubqueryAliasPlugin(BaseRelPlugin):
    """Parity: reference subquery_alias.py (pass-through rename)."""

    class_name = "SubqueryAlias"

    def convert(self, rel: p.SubqueryAlias, executor) -> Table:
        (inp,) = self.assert_inputs(rel, 1, executor)
        return self.fix_column_to_row_type(inp, rel.schema)


@Executor.add_plugin_class
class SamplePlugin(BaseRelPlugin):
    """Parity: reference sample.py (TABLESAMPLE SYSTEM / BERNOULLI)."""

    class_name = "Sample"

    def convert(self, rel: p.Sample, executor) -> Table:
        (inp,) = self.assert_inputs(rel, 1, executor)
        frac = rel.fraction / 100.0
        seed = rel.seed if rel.seed is not None else np.random.randint(0, 2**31 - 1)
        key = jax.random.PRNGKey(seed)
        if rel.method == "SYSTEM":
            # partition-level sampling: with device-sharded tables this keeps
            # or drops whole shards; single shard here -> block sampling
            nblocks = 16
            bounds = jnp.linspace(0, inp.num_rows, nblocks + 1).astype(jnp.int64)
            chosen = jax.random.uniform(key, (nblocks,)) < frac
            row_block = jnp.searchsorted(bounds[1:], jnp.arange(inp.num_rows), side="right")
            mask = chosen[jnp.clip(row_block, 0, nblocks - 1)]
        else:
            mask = jax.random.uniform(key, (inp.num_rows,)) < frac
        return inp.filter(mask)


@Executor.add_plugin_class
class DistributeByPlugin(BaseRelPlugin):
    """Parity: reference distributeby.py:15 — explicit hash re-shard.

    Single-device: a hash-clustered reorder (rows grouped by key hash), which
    is exactly what the multi-chip path needs per shard after its all_to_all.
    """

    class_name = "DistributeBy"

    def convert(self, rel: p.DistributeBy, executor) -> Table:
        (inp,) = self.assert_inputs(rel, 1, executor)
        cols = [executor.eval_expr(k, inp) for k in rel.keys]
        if inp.num_rows == 0:
            return inp
        gid, order, _ = factorize(key_arrays(cols))
        return inp.take(order)


@Executor.add_plugin_class
class ExplainPlugin(BaseRelPlugin):
    """Parity: reference explain.py (plan string result)."""

    class_name = "Explain"

    def convert(self, rel: p.Explain, executor) -> Table:
        if getattr(rel, "lint", False):
            # EXPLAIN LINT: static plan verifier findings (analysis/),
            # errors and doomed-rung warnings first, then shape/recompile
            # advisories — nothing executes
            from ....analysis import verify_plan

            verdict = verify_plan(rel.input, context=executor.context,
                                  collect_info=True)
            executor.context.metrics.inc("analysis.explain_lint")
            rows = verdict.format_rows()
            lines = np.array(rows, dtype=object)
        elif getattr(rel, "estimate", False):
            # EXPLAIN ESTIMATE: static cost & memory abstract interpreter
            # (analysis/estimator.py) — cardinality + byte intervals per
            # node and the whole-plan peak-bytes verdict; nothing executes
            from ....analysis import estimator

            est = estimator.estimate_plan(rel.input, context=executor.context)
            # report (not apply) the budget proofs so EXPLAIN shows which
            # compiled rungs execution would pre-skip
            est.rung_proofs = estimator.collect_rung_proofs(
                est, estimator.device_budget_bytes(executor.context.config))
            # profile feedback under the same family identity execution
            # uses, so EXPLAIN ESTIMATE shows the bounds the scheduler
            # actually packs with once the family has observed history
            from ....families import family_of

            fam = family_of(rel.input, executor.config,
                            metrics=executor.context.metrics)
            est = executor.context._feedback_estimate(rel.input, est, fam)
            executor.context.metrics.inc("analysis.explain_estimate")
            lines = np.array(est.format_rows(), dtype=object)
        elif rel.analyze:
            # EXPLAIN ANALYZE: run the plan with per-node tracing, headed
            # by the query-lifecycle stages (observability/spans.py) the
            # active trace collected so far — queue wait, parse, bind,
            # verify, estimate, per-rung compiles.  The execute stage is
            # still open while this renders (the report IS the query's
            # result), so it prints as "(open)"; the complete trace stays
            # downloadable at /v1/trace/{qid} after the query finishes.
            import json as _json

            from ....observability import QueryTrace, current_trace
            from ...executor import Executor

            traced = Executor(executor.context, trace=True)
            traced.execute(rel.input)
            root = traced.tracer.root
            tr = current_trace()
            if tr is not None and root is not None:
                tr.attach_node_tree(root)
            if getattr(rel, "fmt_json", False):
                if tr is None:
                    # tracing disabled: export the node tree alone so
                    # FORMAT JSON still yields a loadable Chrome trace
                    tr = QueryTrace(sql="EXPLAIN ANALYZE")
                    tr.attach_node_tree(root)
                lines = np.array([_json.dumps(tr.to_chrome_trace())],
                                 dtype=object)
            else:
                out = []
                if tr is not None:
                    out.extend(tr.format_lines())
                    out.append("")
                text = root.format() if root else ""
                out.extend(text.split("\n"))
                lines = np.array(out, dtype=object)
        else:
            lines = np.array(rel.input.explain().split("\n"), dtype=object)
        col = rel.schema[0].name if rel.schema else "PLAN"
        return Table({col: Column.from_numpy(lines)}, len(lines))
