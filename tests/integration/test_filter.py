"""Filter tests (parity: reference test_filter.py incl. pushdown checks)."""
import numpy as np
import pandas as pd
import pytest

from tests.utils import assert_eq


def test_filter(c, df):
    result = c.sql("SELECT * FROM df WHERE a < 2").compute()
    expected = df[df["a"] < 2]
    assert_eq(result, expected, check_dtype=False)

def test_filter_scalar(c, df):
    result = c.sql("SELECT * FROM df WHERE True").compute()
    assert_eq(result, df, check_dtype=False)
    result = c.sql("SELECT * FROM df WHERE False").compute()
    assert len(result) == 0
    result = c.sql("SELECT * FROM df WHERE (1 = 1)").compute()
    assert_eq(result, df, check_dtype=False)

def test_filter_complicated(c, df):
    result = c.sql("SELECT * FROM df WHERE a < 3 AND (b > 1 AND b < 3)").compute()
    expected = df[(df["a"] < 3) & ((df["b"] > 1) & (df["b"] < 3))]
    assert_eq(result, expected, check_dtype=False)

def test_filter_with_nan(c, user_table_nan):
    result = c.sql("SELECT * FROM user_table_nan WHERE c = 3").compute()
    assert list(result["c"]) == [3.0]

def test_filter_null_is_false(c):
    df = pd.DataFrame({"a": [1.0, None, 3.0]})
    c.create_table("fnull", df)
    result = c.sql("SELECT * FROM fnull WHERE a > 0").compute()
    assert len(result) == 2  # NULL comparison filters out

def test_filter_between(c, df):
    result = c.sql("SELECT * FROM df WHERE b BETWEEN 2 AND 4").compute()
    expected = df[(df.b >= 2) & (df.b <= 4)]
    assert_eq(result, expected, check_dtype=False)

def test_filter_in(c, user_table_1):
    result = c.sql("SELECT * FROM user_table_1 WHERE user_id IN (1, 3)").compute()
    expected = user_table_1[user_table_1.user_id.isin([1, 3])]
    assert_eq(result, expected, check_dtype=False, sort_results=True)

def test_filter_not_in(c, user_table_1):
    result = c.sql("SELECT * FROM user_table_1 WHERE user_id NOT IN (1, 3)").compute()
    expected = user_table_1[~user_table_1.user_id.isin([1, 3])]
    assert_eq(result, expected, check_dtype=False, sort_results=True)

def test_filter_string_like(c, string_table):
    result = c.sql("SELECT * FROM string_table WHERE a LIKE '%normal%'").compute()
    assert list(result["a"]) == ["a normal string"]
    result = c.sql("SELECT * FROM string_table WHERE a LIKE '^|()-*[]$'").compute()
    assert list(result["a"]) == ["^|()-*[]$"]
    result = c.sql("SELECT * FROM string_table WHERE a LIKE '%\\%^%' ESCAPE '\\'").compute()
    assert list(result["a"]) == []
    result = c.sql("SELECT * FROM string_table WHERE a LIKE '_\\_\\%' ESCAPE '\\'").compute()
    assert list(result["a"]) == ["%_%"]

def test_filter_is_null(c):
    df = pd.DataFrame({"a": [1.0, None, 3.0]})
    c.create_table("isn", df)
    assert len(c.sql("SELECT * FROM isn WHERE a IS NULL").compute()) == 1
    assert len(c.sql("SELECT * FROM isn WHERE a IS NOT NULL").compute()) == 2

def test_filter_or(c, user_table_1):
    result = c.sql("SELECT * FROM user_table_1 WHERE user_id = 1 OR b = 1").compute()
    expected = user_table_1[(user_table_1.user_id == 1) | (user_table_1.b == 1)]
    assert_eq(result, expected, check_dtype=False, sort_results=True)

def test_filter_datetime(c, datetime_table):
    result = c.sql(
        "SELECT * FROM datetime_table WHERE no_timezone > '2014-08-01 23:00'"
    ).compute()
    expected = datetime_table[datetime_table.no_timezone > "2014-08-01 23:00"]
    assert len(result) == len(expected)

def test_filter_pushdown_into_scan(c, df):
    # the optimized plan should carry the predicate inside the TableScan
    plan_text = c.explain("SELECT a FROM df WHERE a < 2")
    assert "TableScan" in plan_text
    assert "filters=" in plan_text
