"""Coordinated HBM pressure response (resilience/pressure.py, ISSUE 17).

The acceptance surface: headroom bands classify strictly against the
scheduler device budget (never the admission fallback); YELLOW provably
suspends speculative work (warm-up replays, background recompiles, new
stem materialization) and resumes on recovery; RED reclaims cross-tier in
priority order (cold result cache -> pinned stems -> idle model params)
verified against the ledger's per-component gauges; an in-flight
RESOURCE_EXHAUSTED with reclaimable cold bytes retries the SAME rung once
(zero degradations, breaker uncharged) while an unreclaimable one degrades
exactly as before; CRITICAL forces admissions onto streamed rungs where
eligible and sheds the rest with a capped, drain-predicted Retry-After.
Satellites: the 60s Retry-After cap, the retryable ``d2h`` fault site, the
per-chunk stream-launch watchdog, and CANCEL racing a mid-stream OOM.
"""
import time

import numpy as np
import pandas as pd
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu import config as config_module
from dask_sql_tpu.observability import flight
from dask_sql_tpu.resilience import faults
from dask_sql_tpu.serving.cache import table_nbytes

N_ROWS = 40_000


@pytest.fixture(autouse=True)
def _fresh_state():
    """Fault budgets, morsel-executable caches and the global config are
    process-wide; every test starts clean and leaves nothing behind."""
    from dask_sql_tpu.streaming import aggregate as stream_agg
    from dask_sql_tpu.streaming import select as stream_sel

    saved = dict(config_module.config._values)
    faults.reset()
    stream_agg.reset_cache()
    stream_sel.reset_cache()
    yield
    config_module.config._values = saved
    faults.reset()
    stream_agg.reset_cache()
    stream_sel.reset_cache()


def _df(n=2000, seed=0):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "a": rng.integers(0, 100, n).astype(np.int64),
        "b": rng.random(n) * 100.0,
        "k": rng.integers(0, 5, n).astype(np.int64),
    })


def _used_bytes(c):
    snap = c.ledger.snapshot()
    return (snap["reservedBytes"] + snap["resultCacheBytes"]
            + snap["tableBytes"] + snap["modelBytes"]
            + snap["materializedBytes"])


def _stream_ctx(n=N_ROWS):
    c = Context()
    c.config.update({"serving.cache.enabled": False})
    rng = np.random.RandomState(7)
    df = pd.DataFrame({
        "k": rng.randint(0, 5, n).astype(np.int64),
        "v": rng.randint(0, 1000, n).astype(np.int64),
        "f": rng.rand(n),
    })
    c.create_table("t", df)
    return c, df


def _stream_budget(c, frac=3):
    return table_nbytes(c.schema["root"].tables["t"].table) // frac


AGG_Q = ("SELECT k, SUM(v) AS s, COUNT(*) AS n, AVG(v) AS a, "
         "MIN(v) AS mn, MAX(f) AS mx FROM t GROUP BY k ORDER BY k")


# ------------------------------------------------------------------ bands
def test_bands_classify_against_device_budget():
    c = Context()
    c.create_table("t", _df())
    # no device budget configured: banding is off, everything is GREEN
    assert c.pressure.band() == "green"
    used = _used_bytes(c)
    assert used > 0
    flight.RECORDER.clear()
    # headroom fraction 0.15 -> YELLOW (<= 0.25, > 0.10)
    c.config.update({"serving.scheduler.device_budget_bytes":
                     int(used / 0.85)})
    assert c.pressure.band() == "yellow"
    # 0.087 -> RED (<= 0.10, > 0.05)
    c.config.update({"serving.scheduler.device_budget_bytes":
                     int(used / 0.92)})
    assert c.pressure.band() == "red"
    # negative headroom -> CRITICAL
    c.config.update({"serving.scheduler.device_budget_bytes": used // 2})
    assert c.pressure.band() == "critical"
    assert c.metrics.snapshot()["gauges"]["resilience.pressure.band"] == 3
    # recovery -> GREEN again
    c.config.update({"serving.scheduler.device_budget_bytes": used * 10})
    assert c.pressure.band() == "green"
    assert c.metrics.snapshot()["gauges"]["resilience.pressure.band"] == 0
    assert c.metrics.counter("resilience.pressure.transitions") == 4
    bands = [e["band"] for e in flight.RECORDER.events(name="pressure.band")]
    assert bands == ["yellow", "red", "critical", "green"]
    snap = c.pressure.snapshot()
    assert snap["band"] == "green" and snap["enabled"]
    assert snap["budgetBytes"] == used * 10


def test_band_ignores_admission_fallback_budget():
    """The admission byte gate bounds ONE query's estimate, not the
    device: banding on it would mark every deployment whose tables exceed
    the per-query gate CRITICAL.  Only the scheduler device budget bands."""
    from dask_sql_tpu.serving.admission import EstimatedBytesExceededError

    c = Context()
    c.create_table("t", _df())
    c.config.update({"serving.admission.max_estimated_bytes": 10})
    assert c.pressure.budget_bytes() is None
    assert c.pressure.band() == "green"
    # the per-query gate still sheds with its own (non-pressure) proof
    with pytest.raises(EstimatedBytesExceededError):
        c.sql("SELECT SUM(a) AS s FROM t", return_futures=False)
    assert c.metrics.counter("resilience.pressure.critical_shed") == 0


def test_pressure_disabled_is_inert():
    c = Context()
    c.create_table("t", _df())
    c.config.update({"resilience.pressure.enabled": False,
                     "serving.scheduler.device_budget_bytes": 1})
    assert c.pressure.band() == "green"
    assert c.pressure.reclaim(None, reason="oom") == 0
    out = c.sql("SELECT SUM(a) AS s FROM t", return_futures=False)
    assert len(out) == 1


# -------------------------------------------- YELLOW suspends speculation
def test_yellow_suspends_then_resumes_materialization():
    c = Context()
    c.create_table("t", _df(4000, seed=1))
    # the result cache stays ON (stem observation rides the cache's
    # family/key machinery); the highly selective filter keeps cached
    # results tiny so the band cannot drift out of YELLOW mid-test
    c.config.update({"serving.materialize.min_bytes": 1})
    used = _used_bytes(c)
    c.config.update({"serving.scheduler.device_budget_bytes":
                     int(used / 0.82)})
    assert c.pressure.band() == "yellow"
    # two siblings over one scan->filter stem would normally pin it
    c.sql("SELECT a FROM t WHERE a > 96").compute()
    c.sql("SELECT b FROM t WHERE a > 96").compute()
    assert c.metrics.counter("serving.materialize.stored") == 0
    assert c.metrics.counter("resilience.pressure.suspended") >= 1
    # recovery: the earned hit count was retained, the next sibling pins
    c.config.update({"serving.scheduler.device_budget_bytes": used * 20})
    assert c.pressure.band() == "green"
    c.sql("SELECT k FROM t WHERE a > 96").compute()
    assert c.metrics.counter("serving.materialize.stored") == 1


def test_yellow_defers_background_recompiles():
    from dask_sql_tpu.serving.background import BackgroundCompiler

    c = Context()
    bg = BackgroundCompiler(metrics=c.metrics, suspended=lambda: True)
    assert bg.submit("family", lambda: None) is False
    assert c.metrics.counter("resilience.pressure.suspended") == 1
    assert c.metrics.counter("serving.bg_compile.submitted") == 0
    ok = BackgroundCompiler(metrics=c.metrics, suspended=lambda: False)
    try:
        assert ok.submit("family", lambda: None) is True
        assert ok.wait_idle(10.0)
    finally:
        ok.cancel()


def test_yellow_pauses_warmup_and_resumes():
    c = Context()
    c.create_table("t", _df(500, seed=2))
    c.sql("SELECT SUM(a) AS s FROM t", return_futures=False)  # profile it
    used = _used_bytes(c)
    # the warm thread reads the PROCESS config: set the tight budget
    # globally before starting the pass
    config_module.config.update({
        "serving.warmup.enabled": True,
        "serving.warmup.top_n": 4,
        "serving.scheduler.device_budget_bytes": int(used / 0.85)})
    mgr = c.maybe_start_warmup()
    assert mgr is not None
    try:
        deadline = time.monotonic() + 5.0
        while (time.monotonic() < deadline and
               c.metrics.counter("resilience.pressure.suspended") == 0):
            time.sleep(0.01)
        assert c.metrics.counter("resilience.pressure.suspended") >= 1
        assert mgr.warmed == 0 and not mgr.ready  # provably paused
        # recovery: the pass resumes and finishes
        config_module.config.update(
            {"serving.scheduler.device_budget_bytes": None})
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline and not mgr.ready:
            time.sleep(0.02)
        assert mgr.ready
        assert mgr.warmed >= 1
    finally:
        mgr.cancel()
        mgr.join(10.0)


# ------------------------------------------------------- RED-band reclaim
def test_red_reclaim_walks_tiers_in_priority_order():
    c = Context()
    c.create_table("t", _df(4000, seed=3))
    c.config.update({"serving.materialize.min_bytes": 1})
    c.sql("SELECT a FROM t WHERE a > 3").compute()
    c.sql("SELECT b FROM t WHERE a > 3").compute()
    snap = c.ledger.snapshot()
    assert snap["resultCacheBytes"] > 0 and snap["materializedBytes"] > 0
    flight.RECORDER.clear()
    # a small target is satisfied ENTIRELY from tier 1 (cold cache);
    # pinned stems are untouched
    freed = c.pressure.reclaim(1, reason="band")
    assert freed > 0
    after = c.ledger.snapshot()
    assert after["materializedBytes"] == snap["materializedBytes"]
    assert after["resultCacheBytes"] < snap["resultCacheBytes"]
    ev = flight.RECORDER.events(name="pressure.reclaim")[-1]
    assert ev["cache_bytes"] == freed
    assert ev["stem_bytes"] == 0 and ev["model_bytes"] == 0
    # an OOM reclaim is unbounded: every reclaimable tier drains
    c.pressure.reclaim(None, reason="oom")
    drained = c.ledger.snapshot()
    assert drained["resultCacheBytes"] == 0
    assert drained["materializedBytes"] == 0
    ev2 = flight.RECORDER.events(name="pressure.reclaim")[-1]
    assert ev2["reason"] == "oom" and ev2["stem_bytes"] > 0
    assert c.metrics.counter("resilience.pressure.reclaims") == 2
    assert c.metrics.counter("resilience.pressure.reclaimed_bytes") >= freed


# ------------------------------------------- reclaim-before-degrade (OOM)
@pytest.mark.faults
def test_reclaimable_oom_retries_same_rung_without_degrading():
    """A forced device OOM with reclaimable cold cache serves on the SAME
    rung after one reclaim: zero degradations, breaker never charged."""
    clean_ctx = Context()
    clean_ctx.create_table("t", _df(500, seed=4))
    clean = clean_ctx.sql("SELECT SUM(b) AS s FROM t", return_futures=False)
    c = Context()
    c.create_table("t", _df(500, seed=4))
    c.sql("SELECT SUM(a) AS s FROM t", return_futures=False)  # warm cache
    assert c.ledger.snapshot()["resultCacheBytes"] > 0
    flight.RECORDER.clear()
    with config_module.set({"resilience.inject": "oom:once"}):
        hurt = c.sql("SELECT SUM(b) AS s FROM t", return_futures=False)
    pd.testing.assert_frame_equal(hurt, clean)
    assert c.metrics.counter("resilience.degraded") == 0
    assert c.metrics.counter("resilience.pressure.rung_retry") == 1
    assert c.metrics.counter("resilience.pressure.rung_retry_ok") == 1
    assert c.breaker.snapshot()["keys"] == 0  # never charged
    ev = flight.RECORDER.events(name="pressure.reclaim")[-1]
    assert ev["reason"] == "oom" and ev["freed"] > 0


@pytest.mark.faults
def test_unreclaimable_oom_degrades_exactly_as_before():
    c = Context()
    c.create_table("t", _df(500, seed=5))
    with config_module.set({"resilience.inject": "oom:once",
                            "serving.cache.enabled": False}):
        out = c.sql("SELECT SUM(a) AS s FROM t", return_futures=False)
    assert int(out["s"][0]) == int(_df(500, seed=5)["a"].sum())
    assert c.metrics.counter("resilience.degraded") == 1
    assert c.metrics.counter("resilience.pressure.rung_retry") == 0
    assert c.metrics.counter("resilience.pressure.rung_retry_ok") == 0


# ------------------------------------------------- Retry-After cap (60s)
def test_retry_after_cap_config_and_default():
    from dask_sql_tpu.serving.admission import retry_after_cap

    assert retry_after_cap() == 60.0
    with config_module.set({"serving.retry_after.cap_s": 5.0}):
        assert retry_after_cap() == 5.0
    with config_module.set({"serving.retry_after.cap_s": "bogus"}):
        assert retry_after_cap() == 60.0
    with config_module.set({"serving.retry_after.cap_s": -3}):
        assert retry_after_cap() == 60.0


def test_queue_full_retry_after_is_capped():
    from dask_sql_tpu.serving.admission import (
        AdmissionController,
        QueueFullError,
    )

    ac = AdmissionController({"interactive": 1, "batch": 1}, workers=1,
                             retry_after_s=100.0)
    with config_module.set({"serving.retry_after.cap_s": 2.0}):
        ac.admit("q1")
        with pytest.raises(QueueFullError) as ei:
            ac.admit("q2")
    assert ei.value.retry_after_s == 2.0


# --------------------------------------------------- d2h fault satellite
@pytest.mark.faults
def test_d2h_fault_retried_at_worker_never_charges_breaker():
    """The packed device-to-host transfer is retryable-transient: the
    serving worker's backoff absorbs a dropped transfer; the rung breaker
    is never charged and the ladder never steps down.  (The CPU backend's
    result path keeps columns host-resident, so the transfer is driven
    directly with device buffers — the same code the accelerator path
    calls from ``Table.to_pandas``.)"""
    import jax.numpy as jnp

    from dask_sql_tpu.columnar.pack import packed_host_arrays
    from dask_sql_tpu.resilience.errors import TransientExecutionError
    from dask_sql_tpu.resilience.faults import SITE_ERRORS
    from dask_sql_tpu.resilience.retry import BackoffPolicy
    from dask_sql_tpu.serving import ServingRuntime

    err = SITE_ERRORS["d2h"]("x")
    assert err.retryable and not err.degradable
    assert isinstance(err, TransientExecutionError)
    c = Context()
    config_module.config.update({"resilience.inject": "d2h:once"})
    bufs = [jnp.asarray([1.0, 2.0, 3.0]), jnp.asarray([4.0, 5.0, 6.0])]
    rt = ServingRuntime(workers=1, retry_policy=BackoffPolicy(
        max_attempts=3, base_s=0.01, jitter=0.0))
    try:
        _, fut, _ = rt.submit(lambda t: packed_host_arrays(bufs),
                              deadline_s=30.0)
        host = fut.result(30)
        assert [h.tolist() for h in host] == [[1.0, 2.0, 3.0],
                                              [4.0, 5.0, 6.0]]
        assert rt.metrics.counter("resilience.retry.recovered") == 1
        assert c.metrics.counter("resilience.degraded") == 0
        assert c.breaker.snapshot()["keys"] == 0
    finally:
        rt.shutdown(wait=True)


# --------------------------------- streamed per-chunk launch watchdog
@pytest.mark.faults
@pytest.mark.streaming
def test_wedged_midstream_launch_degrades_between_chunks():
    """compile-watchdog pattern extended to streamed launches: a launch
    wedged mid-stream (``compile_hang`` armed on chunk 2) raises a
    degradable deadline error between chunks; the ladder steps the rung
    down and the query still answers byte-identically."""
    c, _ = _stream_ctx()
    clean = c.sql(AGG_Q, return_futures=False)
    c2, _ = _stream_ctx()
    opts = {"serving.admission.max_estimated_bytes": _stream_budget(c2),
            "serving.stream.min_chunk_rows": 512}
    # warm the morsel executable so chunk launches are compile-free and
    # the injected hang is the ONLY thing that can trip the deadline
    warm = c2.sql(AGG_Q, return_futures=False, config_options=dict(opts))
    pd.testing.assert_frame_equal(warm, clean)
    assert c2.metrics.counter("resilience.rung.streamed_aggregate") == 1
    hurt = c2.sql(AGG_Q, return_futures=False, config_options={
        **opts,
        "serving.stream.launch_timeout_ms": 100.0,
        "resilience.inject": "compile_hang:at2",
        "resilience.inject.hang_s": 0.5})
    pd.testing.assert_frame_equal(hurt, clean)
    assert c2.metrics.counter("resilience.watchdog.timeout") >= 1
    assert c2.metrics.counter(
        "resilience.degraded.streamed_aggregate") == 1
    # the wedged run never completed the streamed rung
    assert c2.metrics.counter("resilience.rung.streamed_aggregate") == 1


# ------------------------------------ CANCEL racing a mid-stream OOM
@pytest.mark.faults
@pytest.mark.streaming
def test_cancel_racing_midstream_oom_releases_reservation_once():
    """CANCEL QUERY arriving while a streamed query is absorbing an OOM
    repartition: the cancellation lands at the next between-chunk
    checkpoint and the scheduler reservation is released exactly once —
    the ledger returns to idle."""
    from dask_sql_tpu.serving import ServingRuntime
    from dask_sql_tpu.serving.admission import QueryCancelledError
    from dask_sql_tpu.serving.scheduler import QueryCost

    c, _ = _stream_ctx()
    budget = _stream_budget(c)
    # the worker thread reads the PROCESS config; compile_hang:always +
    # a generous launch deadline slow every chunk (~100ms) WITHOUT
    # tripping the watchdog, so the cancel has a wide window to land
    config_module.config.update({
        "serving.admission.max_estimated_bytes": budget,
        "serving.stream.min_chunk_rows": 512,
        "serving.stream.launch_timeout_ms": 10_000.0,
        "resilience.inject": "partition:at2,compile_hang:always",
        "resilience.inject.hang_s": 0.1,
        "serving.cache.enabled": False})
    rt = ServingRuntime(workers=1, metrics=c.metrics,
                        scheduler_budget_bytes=budget * 10)
    c.serving = rt
    try:
        _, fut, ticket = rt.submit(
            lambda t: c.sql(AGG_Q, return_futures=False),
            cost=QueryCost(bytes_lo=4096))
        deadline = time.monotonic() + 30.0
        while (time.monotonic() < deadline and
               c.metrics.counter("serving.stream.repartitions") == 0):
            time.sleep(0.005)
        assert c.metrics.counter("serving.stream.repartitions") >= 1
        ticket.cancel()
        with pytest.raises(QueryCancelledError):
            fut.result(30)
    finally:
        rt.shutdown(wait=True)
        c.serving = None
    snap = c.ledger.snapshot()
    assert snap["reservedBytes"] == 0
    assert snap["inflightMeasuredBytes"] == 0


# --------------------------------------------------- CRITICAL admission
def test_critical_forces_new_admissions_onto_streamed_rung():
    c, _ = _stream_ctx()
    clean = c.sql(AGG_Q, return_futures=False)
    c2, _ = _stream_ctx()
    # device budget far below the resident table: CRITICAL at admission,
    # but the plan has a streamed rung sized to the device budget
    got = c2.sql(AGG_Q, return_futures=False, config_options={
        "serving.scheduler.device_budget_bytes": _stream_budget(c2)})
    pd.testing.assert_frame_equal(got, clean)
    assert c2.metrics.counter("resilience.pressure.critical_streamed") == 1
    assert c2.metrics.counter("serving.stream.admitted") == 1
    assert c2.metrics.counter("serving.stream.partitions") > 1
    assert c2.metrics.counter("resilience.pressure.critical_shed") == 0


def test_critical_sheds_unstreamable_with_capped_retry_after():
    from dask_sql_tpu.resilience.pressure import PressureShedError

    c, _ = _stream_ctx()
    with pytest.raises(PressureShedError) as ei:
        c.sql(AGG_Q, return_futures=False, config_options={
            "serving.scheduler.device_budget_bytes": _stream_budget(c),
            "serving.stream.enabled": False})
    assert ei.value.retryable
    assert ei.value.payload()["code"] == "PRESSURE_SHED"
    assert 0.0 < ei.value.retry_after_s <= 60.0
    assert c.metrics.counter("resilience.pressure.critical_shed") == 1
    shed = flight.RECORDER.events(name="query.shed")[-1]
    assert shed["reason"] == "pressure"
