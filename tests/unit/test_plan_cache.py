"""Plan-cache correctness: repeated SQL reuses the bound plan, and every
catalog/config change invalidates it."""
import numpy as np
import pandas as pd

from dask_sql_tpu import Context


def _ctx():
    c = Context()
    c.create_table("t", pd.DataFrame({"a": [1, 2, 3], "b": [1.5, 2.5, 3.5]}))
    return c


def test_repeated_sql_hits_cache():
    c = _ctx()
    q = "SELECT SUM(a) AS s FROM t"
    assert int(c.sql(q, return_futures=False)["s"][0]) == 6
    assert len(c._plan_cache) == 1
    key = next(iter(c._plan_cache))
    c.sql(q, return_futures=False)
    assert list(c._plan_cache) == [key]  # same entry, no re-plan


def test_table_replacement_invalidates():
    c = _ctx()
    q = "SELECT SUM(a) AS s FROM t"
    assert int(c.sql(q, return_futures=False)["s"][0]) == 6
    c.create_table("t", pd.DataFrame({"a": [10, 20]}))
    assert int(c.sql(q, return_futures=False)["s"][0]) == 30


def test_config_options_partition_cache():
    c = _ctx()
    q = "SELECT SUM(a) AS s FROM t"
    a = c.sql(q, return_futures=False)
    b = c.sql(q, config_options={"sql.compile": False}, return_futures=False)
    np.testing.assert_allclose(a["s"], b["s"])
    assert len(c._plan_cache) == 2  # distinct entries per config


def test_unhashable_config_skips_cache():
    c = _ctx()
    r = c.sql("SELECT SUM(a) AS s FROM t",
              config_options={"sql.weird": ["not", "hashable"]},
              return_futures=False)
    assert int(r["s"][0]) == 6
    assert len(c._plan_cache) == 0


def test_view_redefinition_not_stale():
    c = _ctx()
    c.sql("CREATE VIEW v AS SELECT a FROM t")
    r1 = c.sql("SELECT * FROM v", return_futures=False)
    c.sql("DROP VIEW v")
    c.sql("CREATE VIEW v AS SELECT b FROM t")
    r2 = c.sql("SELECT * FROM v", return_futures=False)
    assert list(r1.columns) == ["a"]
    assert list(r2.columns) == ["b"]


def test_multi_statement_not_cached_but_correct():
    c = _ctx()
    script = ("CREATE OR REPLACE TABLE ms AS (SELECT a FROM t); "
              "SELECT SUM(a) AS s FROM ms")
    assert int(c.sql(script, return_futures=False)["s"][0]) == 6
    # second run replans statement-by-statement (scripts are never cached)
    assert int(c.sql(script, return_futures=False)["s"][0]) == 6
    assert len(c._plan_cache) == 0
