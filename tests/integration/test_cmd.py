"""CLI meta-command tests (parity: reference test_cmd.py — handlers exercised
directly, the interactive loop is driven in the verify harness)."""
import pandas as pd
import pytest


def test_meta_commands(c, capsys):
    from dask_sql_tpu.cmd import _handle_meta

    assert _handle_meta(c, "\\l")
    assert "root" in capsys.readouterr().out
    assert _handle_meta(c, "\\dt")
    assert "df_simple" in capsys.readouterr().out
    assert _handle_meta(c, "\\conf sql.optimize")
    assert "sql.optimize" in capsys.readouterr().out
    assert not _handle_meta(c, "\\nonsense")


def test_meta_schema_switch(c, capsys):
    from dask_sql_tpu.cmd import _handle_meta

    c.create_schema("side")
    assert _handle_meta(c, "\\dss side")
    assert c.schema_name == "side"
    _handle_meta(c, "\\dss root")
    assert _handle_meta(c, "\\dsc root")
    assert "df_simple" in capsys.readouterr().out


def test_run_query_prints_result(c, capsys):
    from dask_sql_tpu.cmd import _run_query

    _run_query(c, "SELECT 40 + 2 AS answer")
    out = capsys.readouterr().out
    assert "42" in out and "answer" in out


def test_run_query_prints_error(c, capsys):
    from dask_sql_tpu.cmd import _run_query

    _run_query(c, "SELECT * FROM not_a_table")
    err = capsys.readouterr().err
    assert "ERROR" in err


def test_quit_raises(c):
    from dask_sql_tpu.cmd import _handle_meta

    with pytest.raises(EOFError):
        _handle_meta(c, "\\q")
