"""Estimator-driven packing scheduler: memory-budget query packing,
deadline-aware ordering, and per-tenant token-bucket quotas.

The FIFO pop path (serving/runtime.py `_pop_locked`) schedules like a toy:
two class deques and nothing byte-aware, so the only *provably safe*
concurrency under a device byte budget is one query at a time — one admitted
batch scan head-of-line blocks every small interactive query even when the
budget could hold both.  TQP (arXiv:2203.01877) argues the tensor-runtime
cost model *is* the scheduler; this module closes that loop over inputs the
engine already computes:

- each plan family's memoized ``peak_bytes`` interval (analysis/estimator.py,
  PR 4/7) gives a **provable floor** per query — the scheduler *packs*
  concurrently admitted queries against the real device budget
  (``serving.scheduler.device_budget_bytes``), reserving each dispatched
  query's floor and admitting any query whose floor fits the remainder.  A
  query waits only while its floor provably cannot fit; when nothing is in
  flight the head query always dispatches (liveness), matching the admission
  gate's own rule that a single over-budget query is *shed*, never queued
  forever.
- per-family observed exec profiles (observability/profiles.py, PR 5) give a
  **predicted exec_ms** used for deadline-aware ordering and for the 429
  ``Retry-After`` hint: instead of a static value, a shed client is told the
  scheduler's predicted drain time (remaining predicted exec of running
  queries plus the queued backlog, spread over the workers).
- per-tenant **token buckets** (``X-Dsql-Tenant`` header,
  ``serving.tenant.rate_qps`` / ``serving.tenant.burst``) bound a greedy
  tenant's share: a tenant out of tokens is passed over while *other*
  tenants have dispatchable work, and dispatches anyway when nothing else
  can run (work-conserving — quotas reorder, they never fail queries).

Locking: the scheduler owns NO lock.  Every mutating method is named
``*_locked`` or documented as called under the owning runtime's condition
variable (`ServingRuntime._cv`, sanitizer name "serving.runtime.cv", rank
40 in the declared order — runtime/locks.py) — the same discipline the
legacy deques had.  Metric gauges/counters are leaf calls
(MetricsRegistry's own lock is the rank-90 leaf "serving.metrics"); the
static side of this contract is checked by DSQL603 (a ``*_locked`` method
here must never acquire a lock itself).

``serving.scheduler.enabled = false`` removes this module from the pop path
entirely — the runtime keeps its original FIFO deques, byte-unaware and
order-identical to every release before this one.
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from .admission import CLASSES, QueryTicket

#: bound on the per-tenant bucket map: the tenant name is a CLIENT-supplied
#: header, so an adversarial (or request-id-misconfigured) client could
#: otherwise grow the dict by one bucket per request for the process
#: lifetime.  At the cap, idle full buckets are pruned first; an evicted
#: active tenant simply restarts with a fresh full bucket (bounded memory
#: beats perfect burst accounting for a hostile key space).
_TENANT_BUCKET_CAP = 1024


@dataclass
class QueryCost:
    """Submit-time cost descriptor of one query — the scheduler's only view
    of the estimator/profile layers, so front-ends that know nothing (a cold
    SQL text, a direct runtime user) submit the zero cost and degrade to
    FIFO-equivalent treatment.

    ``bytes_lo`` is the PROVABLE floor on peak device bytes (the estimate's
    lower bound): it is what the packer reserves, because only it can never
    over-release.  ``pred_exec_ms`` is a prediction (profile feedback
    sharpens it) used for ordering and drain hints only — a wrong
    prediction degrades latency, never safety."""

    bytes_lo: int = 0
    pred_exec_ms: Optional[float] = None
    #: literal-stripped family fingerprint (families/) when known: lets the
    #: packer count same-family batch-mates it co-scheduled, which the
    #: family batcher's rendezvous window consults
    family: Optional[str] = None
    tenant: str = ""
    #: streamed partitioned execution (streaming/): the provable PER-CHUNK
    #: floor.  When set, the packer reserves THIS instead of ``bytes_lo`` —
    #: a streaming batch scan only ever holds one chunk's working set, so
    #: interactive queries keep packing beside it instead of waiting out
    #: the whole-table floor
    chunk_bytes_lo: Optional[int] = None

    def reserve_bytes(self) -> int:
        """What the packer actually reserves for this query."""
        return int(self.chunk_bytes_lo if self.chunk_bytes_lo is not None
                   else self.bytes_lo)


class TokenBucket:
    """Classic token bucket; ``clock`` injectable for deterministic tests."""

    __slots__ = ("rate", "burst", "tokens", "_last", "_clock")

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self.tokens = self.burst
        self._clock = clock
        self._last = clock()

    def _refill(self) -> None:
        now = self._clock()
        self.tokens = min(self.burst,
                          self.tokens + (now - self._last) * self.rate)
        self._last = now

    def peek(self) -> bool:
        self._refill()
        return self.tokens >= 1.0

    def take(self) -> bool:
        self._refill()
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


@dataclass
class _Item:
    """One queued query; ``seq`` is the FIFO tiebreak within (class,
    deadline) so equal-deadline queries keep submission order."""

    seq: int
    ticket: QueryTicket
    fn: Any
    fut: Any
    cost: QueryCost
    #: byte-budget pass-overs, for the waited counter's once-per-episode
    #: accounting (a 100-pop wait is one wait, not 100)
    waited: bool = False
    throttled: bool = False
    #: when this item first failed the byte-fit check; past fair_horizon_s
    #: it becomes a head-of-line BARRIER (nothing may pack in behind it),
    #: so a stream of small queries cannot starve a big one forever
    blocked_since: float = 0.0


@dataclass
class _Running:
    cost: QueryCost
    started: float
    reserved: int


class PackingScheduler:
    """Byte-budget packing + deadline ordering + tenant quotas.

    Replaces the two FIFO deques when ``serving.scheduler.enabled``.  All
    methods are called under the owning runtime's ``_cv`` lock (see module
    docstring); the runtime still owns worker wakeups, the batch running
    cap, and admission bounds — this class only decides *which* queued
    query a freed worker dispatches next and *whether* it fits."""

    def __init__(self, budget_bytes: Optional[int] = None,
                 tenant_rate: Optional[float] = None,
                 tenant_burst: float = 4.0,
                 fair_horizon_s: float = 30.0,
                 metrics=None,
                 clock: Callable[[], float] = time.monotonic):
        #: device byte budget packed against (None = packing inactive: the
        #: scheduler still orders by class/deadline/quota, FIFO otherwise)
        self.budget_bytes = budget_bytes
        self.tenant_rate = tenant_rate
        self.tenant_burst = float(tenant_burst)
        #: anti-starvation bound for deadline ordering: a query with no
        #: deadline sorts as if its deadline were admission + this horizon,
        #: so a sustained stream of deadline-bearing queries can delay it
        #: at most ~this long (pure inf ordering would starve it forever)
        self.fair_horizon_s = float(fair_horizon_s)
        self.metrics = metrics
        self._clock = clock
        self._seq = 0
        self._queued: Dict[str, List[_Item]] = {c: [] for c in CLASSES}
        self._running: Dict[str, _Running] = {}  # qid -> record
        self.reserved_bytes = 0
        self._buckets: Dict[str, TokenBucket] = {}
        #: rolling mean of observed pred_exec_ms, the drain-time stand-in
        #: for queries submitted with no prediction
        self._pred_sum = 0.0
        self._pred_n = 0

    # ------------------------------------------------------------ queueing
    def push_locked(self, ticket: QueryTicket, fn, fut,
                    cost: Optional[QueryCost]) -> None:
        self._seq += 1
        item = _Item(self._seq, ticket, fn, fut, cost or QueryCost())
        self._queued[ticket.priority_class].append(item)
        self._gauges()

    def pop_locked(self, batch_ok: bool
                   ) -> Optional[Tuple[QueryTicket, Any, Any]]:
        """Choose the next dispatchable query for a freed worker, or None.

        Sweep 1 considers only tenants holding quota tokens; sweep 2 admits
        the rest (work-conserving: quotas bound a tenant's share only while
        other tenants have runnable work).  Within a sweep: classes in
        priority order, then earliest deadline, then FIFO.  A candidate
        whose provable floor cannot fit the remaining budget is passed over
        (``serving.scheduler.waited``) — unless nothing is in flight, in
        which case the head candidate always dispatches so a lone big query
        can never deadlock behind its own reservation.  A candidate
        byte-blocked for longer than ``fair_horizon_s`` becomes a BARRIER:
        nothing dispatches past it, so in-flight work drains until it fits
        (otherwise a rotating stream of small queries keeps the budget
        partially reserved and starves a big one forever)."""
        now = self._clock()
        throttled: List[_Item] = []
        chosen: Optional[_Item] = None
        barrier = False
        ordered = {cls: sorted(self._queued[cls], key=self._order_key)
                   for cls in CLASSES}
        for require_tokens in (True, False):
            for cls in CLASSES:
                if cls == "batch" and not batch_ok:
                    continue
                for item in ordered[cls]:
                    if item.ticket.cancelled or item.ticket.expired():
                        # dispatch immediately: the worker finalizes these
                        # without running them, freeing admission state fast
                        chosen = item
                        break
                    if require_tokens and not self._has_tokens(item):
                        throttled.append(item)
                        continue
                    if not self._fits(item):
                        if not item.waited:
                            item.waited = True
                            item.blocked_since = now
                            self._inc("serving.scheduler.waited")
                        elif now - item.blocked_since > self.fair_horizon_s:
                            barrier = True
                            break
                        continue
                    chosen = item
                    break
                if chosen is not None or barrier:
                    break
            if chosen is not None or barrier:
                break
        if chosen is None:
            return None
        # a token-less tenant made way for the chosen query: that is the
        # quota actually biting (counted once per item per episode)
        from ..observability import flight

        for item in throttled:
            if item is not chosen and not item.throttled:
                item.throttled = True
                self._inc("serving.scheduler.quota_throttled")
                flight.record("sched.quota_throttle", qid=item.ticket.qid,
                              tenant=item.cost.tenant or None)
        self._dispatch(chosen)
        return chosen.ticket, chosen.fn, chosen.fut

    def _order_key(self, item: _Item) -> Tuple[float, int]:
        # earliest effective deadline first, then FIFO.  The effective
        # deadline of a deadline-free query is admission + fair_horizon_s:
        # real deadlines tighter than the horizon still outrank it, but it
        # cannot be passed over indefinitely
        synthetic = item.ticket.admitted_at + self.fair_horizon_s
        d = item.ticket.deadline
        return (min(d, synthetic) if d is not None else synthetic, item.seq)

    def _has_tokens(self, item: _Item) -> bool:
        if self.tenant_rate is None:
            return True
        bucket = self._buckets.get(item.cost.tenant)
        if bucket is None:
            if len(self._buckets) >= _TENANT_BUCKET_CAP:
                self._prune_buckets_locked()
            bucket = self._buckets[item.cost.tenant] = TokenBucket(
                self.tenant_rate, self.tenant_burst, self._clock)
        return bucket.peek()

    def _prune_buckets_locked(self) -> None:
        """Bound the client-keyed bucket map: drop idle-refilled buckets of
        tenants with no admitted work; if every bucket is active, evict the
        oldest entries outright (they restart full)."""
        live = {item.cost.tenant
                for q in self._queued.values() for item in q}
        live.update(rec.cost.tenant for rec in self._running.values())
        for tenant in [t for t, b in self._buckets.items()
                       if t not in live and b.peek()
                       and b.tokens >= b.burst]:
            del self._buckets[tenant]
        while len(self._buckets) >= _TENANT_BUCKET_CAP:
            self._buckets.pop(next(iter(self._buckets)))

    def _fits(self, item: _Item) -> bool:
        if self.budget_bytes is None:
            return True
        if not self._running:
            # liveness: with nothing in flight the head query always runs.
            # (A floor that exceeds the WHOLE budget is the admission
            # gate's problem — it sheds; the scheduler must not also
            # deadlock it.)
            return True
        return self.reserved_bytes + item.cost.reserve_bytes() \
            <= self.budget_bytes

    def _dispatch(self, item: _Item) -> None:
        self._queued[item.ticket.priority_class].remove(item)
        # a cancelled/expired item is only handed out so the worker can
        # finalize it: it runs nothing, so it must not consume a quota
        # token, reserve budget, or pollute the packed/drain statistics
        dead = item.ticket.cancelled or item.ticket.expired()
        reserve = 0 if dead or self.budget_bytes is None \
            else item.cost.reserve_bytes()
        # queue-wait attribution for the slow-query log: why did this
        # query sit in the queue?  byte-blocked and quota-throttled beat
        # plain workers-busy (the runtime defaults the rest)
        if item.throttled:
            item.ticket.queue_reason = "quota_throttled"
        elif item.waited:
            item.ticket.queue_reason = "byte_blocked"
        if not dead:
            if self._running:
                self._inc("serving.scheduler.packed")
                from ..observability import flight

                flight.record("sched.pack", qid=item.ticket.qid,
                              reserved=reserve,
                              inflight=len(self._running))
            if self.tenant_rate is not None:
                bucket = self._buckets.get(item.cost.tenant)
                if bucket is not None:
                    bucket.take()
            if item.cost.pred_exec_ms is not None:
                self._pred_sum += float(item.cost.pred_exec_ms)
                self._pred_n += 1
        self.reserved_bytes += reserve
        self._running[item.ticket.qid] = _Running(
            item.cost, self._clock(), reserve)
        self._gauges()

    def release_locked(self, ticket: QueryTicket,
                       measured_bytes: Optional[int] = None) -> None:
        """Return a dispatched query's reservation — called from the
        runtime's `_release` on EVERY outcome (success, failure, deadline,
        cancel, mid-pack fault), so reserved bytes can never leak.

        ``measured_bytes`` is the execution's MEASURED footprint when the
        executing thread recorded one (TpuFrame.execute writes
        ``ticket.measured_bytes`` from `serving/cache.table_nbytes`-style
        accounting): the packer reconciles it against what it reserved and
        surfaces the signed drift as ``serving.scheduler.reserve_drift``
        (measured - reserved, bytes) — the estimator-calibration signal
        behind packing against measured rather than estimated bytes."""
        rec = self._running.pop(ticket.qid, None)
        if rec is not None:
            self.reserved_bytes -= rec.reserved
            if measured_bytes is not None and rec.reserved > 0 \
                    and self.metrics is not None:
                self.metrics.observe("serving.scheduler.reserve_drift",
                                     float(int(measured_bytes)
                                           - rec.reserved))
        self._gauges()

    # ------------------------------------------------------------- queries
    def depth_locked(self, cls: Optional[str] = None) -> int:
        if cls is not None:
            return len(self._queued[cls])
        return sum(len(q) for q in self._queued.values())

    def drain_all_locked(self) -> List[Tuple[QueryTicket, Any, Any]]:
        """Shutdown: hand every queued item back (the runtime fails them
        with the structured ShutdownError, same as the FIFO path)."""
        out = []
        for cls in CLASSES:
            for item in self._queued[cls]:
                out.append((item.ticket, item.fn, item.fut))
            self._queued[cls] = []
        self._gauges()
        return out

    def family_mates_locked(self, family: Optional[str],
                            exclude_qid: Optional[str] = None) -> int:
        """How many OTHER queries of ``family`` are currently admitted
        (queued or running).  The family batcher's leader consults this:
        a positive count means the packer co-scheduled batch-mates that
        are worth waiting the rendezvous window for."""
        if not family:
            return 0
        n = 0
        for q in self._queued.values():
            n += sum(1 for item in q if item.cost.family == family)
        for qid, rec in self._running.items():
            if rec.cost.family == family and qid != exclude_qid:
                n += 1
        return n

    def predicted_drain_s(self, workers: int) -> float:
        """Predicted seconds until the current load drains: remaining
        predicted exec of running queries plus the queued backlog's
        predictions, spread over the workers.  Queries with no prediction
        use the rolling mean of the predictions seen so far (0 when none:
        an unknown workload earns no inflated hint)."""
        now = self._clock()
        default = self._pred_sum / self._pred_n if self._pred_n else 0.0
        total_ms = 0.0
        for rec in self._running.values():
            pred = rec.cost.pred_exec_ms if rec.cost.pred_exec_ms is not None \
                else default
            total_ms += max(0.0, pred - (now - rec.started) * 1000.0)
        for q in self._queued.values():
            for item in q:
                pred = item.cost.pred_exec_ms \
                    if item.cost.pred_exec_ms is not None else default
                total_ms += pred
        return total_ms / 1000.0 / max(1, int(workers))

    def snapshot_locked(self) -> Dict[str, Any]:
        return {
            "budgetBytes": self.budget_bytes,
            "reservedBytes": self.reserved_bytes,
            "running": len(self._running),
            "queued": {c: len(self._queued[c]) for c in CLASSES},
            "tenants": sorted(self._buckets),
        }

    # ------------------------------------------------------------- metrics
    def _inc(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.inc(name)

    def _gauges(self) -> None:
        if self.metrics is None:
            return
        self.metrics.gauge("serving.scheduler.inflight_bytes",
                           self.reserved_bytes)
        self.metrics.gauge("serving.scheduler.running", len(self._running))
        for cls in CLASSES:
            self.metrics.gauge(f"serving.scheduler.queue_depth.{cls}",
                               len(self._queued[cls]))
