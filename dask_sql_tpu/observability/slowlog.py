"""Slow-query log: the full span tree of latency outliers.

A p99 regression is only debuggable if the outlier queries left their
traces behind.  When ``observability.slow_query_ms`` is set (None = off;
0 logs every query — useful in tests and short repros), any query whose
trace spans a total wall time at or above the threshold is written out
once, at trace finish:

- to ``observability.slow_query_path`` as one JSON line per query
  (qid, trace id, sql, total_ms, fingerprint, and every span with
  timestamps/durations/attrs — the machine-readable span tree), or
- to this module's logger at WARNING when no path is configured.

Each write increments the ``observability.slow_query`` counter so SHOW
METRICS shows the outlier *rate* even when nobody tails the log file.
"""
from __future__ import annotations

import json
import logging
import threading
import time

logger = logging.getLogger(__name__)

#: serializes appends from concurrent worker threads so JSONL lines never
#: interleave mid-record
_write_lock = threading.Lock()


def _threshold_ms(config) -> float:
    """The configured threshold in ms, or None when the log is off.
    Unlike the byte budgets, 0 is a real value here (log everything)."""
    raw = config.get("observability.slow_query_ms")
    if raw is None or raw is False or raw == "":
        return None
    if isinstance(raw, str) and raw.strip().lower() in ("none", "off",
                                                        "false"):
        return None
    try:
        ms = float(raw)
    except (TypeError, ValueError):
        logger.warning("unparseable observability.slow_query_ms %r; "
                       "slow-query log disabled", raw)
        return None
    return ms if ms >= 0 else None


def maybe_log_slow(trace, config, metrics=None) -> bool:
    """Write `trace` to the slow-query log if it crossed the threshold.
    Called from `QueryTrace.finish`; at most one write per trace."""
    threshold = _threshold_ms(config)
    if threshold is None:
        return False
    total = trace.total_ms()
    if total < threshold:
        return False
    if trace.slow_logged:
        return False
    trace.slow_logged = True
    if metrics is not None:
        metrics.inc("observability.slow_query")
    record = {
        "ts": time.time(),
        "qid": trace.qid,
        "trace_id": trace.trace_id,
        "fingerprint": trace.fingerprint,
        "sql": trace.sql,
        "total_ms": round(total, 3),
        "threshold_ms": threshold,
        "spans": [
            {"name": s.name, "kind": s.kind, "parent": s.parent,
             "start_ms": round((s.t0 - trace.created_perf) * 1e3, 3),
             "dur_ms": None if s.dur_ms is None else round(s.dur_ms, 3),
             "attrs": {k: v for k, v in s.attrs.items() if v is not None}}
            for s in sorted(trace.spans, key=lambda s: s.t0)
        ],
    }
    path = config.get("observability.slow_query_path")
    if path:
        try:
            with _write_lock, open(path, "a", encoding="utf-8") as f:
                f.write(json.dumps(record) + "\n")
        except OSError:
            logger.warning("slow-query log write to %r failed", path,
                           exc_info=True)
            logger.warning("slow query %s (%.1f ms >= %.1f ms): %s",
                           trace.qid, total, threshold, json.dumps(record))
    else:
        logger.warning("slow query %s (%.1f ms >= %.1f ms): %s",
                       trace.qid, total, threshold, json.dumps(record))
    return True
