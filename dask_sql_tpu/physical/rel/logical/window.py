"""Window-function converter.

Role parity: reference window.py:201 (groupby(partition).apply with per-group
sort + pandas expanding/rolling Indexers, window.py:96-198; ops row_number/
sum/count/max/min/avg/first/last window.py:214-225 — we add the rank family
and lag/lead).

TPU-first mechanism (SURVEY.md §7 "windows"): ONE device lexsort by
(partition keys, order keys), segment boundaries from key-change flags, then
every window function is a vectorized segmented prefix-scan / prefix-sum
difference over the sorted layout, scattered back through the inverse
permutation.  No per-group host loops.
"""
from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ....columnar.column import Column
from ....columnar.dtypes import STRING_TYPES, SqlType, sql_to_np
from ....columnar.table import Table
from ....ops.grouping import key_arrays
from ....ops.sorting import sort_permutation
from ....planner import plan as p
from ....planner.expressions import WindowExpr, WindowFrameBound
from ..base import BaseRelPlugin, unique_names
from ...executor import Executor


@Executor.add_plugin_class
class WindowPlugin(BaseRelPlugin):
    class_name = "Window"

    def convert(self, rel: p.Window, executor) -> Table:
        (inp,) = self.assert_inputs(rel, 1, executor)
        names = unique_names([f.name for f in rel.schema])
        out_cols = dict(zip(names[: len(inp.column_names)],
                            [inp.columns[c] for c in inp.column_names]))
        n = inp.num_rows
        # group window exprs by identical (partition, order) so one sort serves many
        by_spec = {}
        for i, w in enumerate(rel.window_exprs):
            key = (w.spec.partition_by, w.spec.order_by)
            by_spec.setdefault(key, []).append((i, w))
        results: List[Column] = [None] * len(rel.window_exprs)
        for (part, order), items in by_spec.items():
            part_cols = [executor.eval_expr(e, inp) for e in part]
            order_cols = [executor.eval_expr(k.expr, inp) for k in order]
            layout = _SortedLayout(part_cols, order_cols,
                                   [k.ascending for k in order],
                                   [k.nulls_first_resolved() for k in order], n)
            for i, w in items:
                args = [executor.eval_expr(a, inp) for a in w.args]
                results[i] = _compute_window(w, args, layout)
        # densify all-valid masks back to None in ONE device round trip for
        # the whole node (per-expr bool(v.all()) syncs were a round trip
        # each on a tunneled chip; downstream fast paths want None masks)
        with_masks = [(name, col) for name, col in
                      zip(names[len(inp.column_names):], results)
                      if col.validity is not None]
        if with_masks:
            from ....utils import count_d2h

            count_d2h()
            flags = np.asarray(jax.device_get(jnp.stack(
                [jnp.all(col.validity) for _, col in with_masks])))
            dense = {name: bool(f) for (name, _), f in zip(with_masks, flags)}
        for name, col in zip(names[len(inp.column_names):], results):
            if col.validity is not None and dense.get(name):
                col = Column(col.data, col.sql_type, None, col.dictionary)
            out_cols[name] = col
        return Table(out_cols, n)


class _SortedLayout:
    """Shared sorted layout for one (partition, order) spec."""

    def __init__(self, part_cols, order_cols, ascendings, nulls_firsts, n: int):
        self.n = n
        if n == 0:
            self.perm = jnp.zeros(0, dtype=jnp.int64)
            self.inv = jnp.zeros(0, dtype=jnp.int64)
            return
        keys_cols = list(part_cols) + list(order_cols)
        asc = [True] * len(part_cols) + list(ascendings)
        nf = [False] * len(part_cols) + list(nulls_firsts)
        if keys_cols:
            self.perm = sort_permutation(keys_cols, asc, nf)
        else:
            self.perm = jnp.arange(n, dtype=jnp.int64)
        self.inv = jnp.zeros(n, dtype=jnp.int64).at[self.perm].set(
            jnp.arange(n, dtype=jnp.int64))
        # segment flags in sorted space
        self.new_seg = _change_flags(part_cols, self.perm, n)
        self.new_peer = self.new_seg | _change_flags(order_cols, self.perm, n) \
            if order_cols else self.new_seg.copy()
        if not order_cols:
            self.new_peer = self.new_seg
        idx = jnp.arange(n, dtype=jnp.int64)
        self.seg_start = _running_latest(jnp.where(self.new_seg, idx, -1))
        self.peer_start = _running_latest(jnp.where(self.new_peer, idx, -1))
        # segment/peer end (exclusive): next start, scanned from the right
        self.seg_end = _next_start(self.new_seg, n)
        self.peer_end = _next_start(self.new_peer, n)
        # single numeric/datetime order key: value source for RANGE offsets
        # (materialized lazily — only RANGE-offset frames pay for it)
        self._order_col = order_cols[0] if len(order_cols) == 1 else None
        self._order_asc = ascendings[0] if ascendings else True
        self._order_sorted = None

    def order_values(self):
        """Ascending-within-segment order-key values, or None when RANGE
        offsets are unsupported (multi-key, strings, bools, NULLs/NaNs —
        the binary-search invariant needs a monotone segment)."""
        if self._order_sorted is not None:
            return self._order_sorted
        col = self._order_col
        if col is None or col.dictionary is not None \
                or col.data.dtype == jnp.bool_ or col.validity is not None:
            return None
        v = col.data[self.perm]
        if jnp.issubdtype(v.dtype, jnp.floating) and bool(jnp.isnan(v).any()):
            # NaN breaks the monotone-segment invariant (and SQL orders NaN
            # above +inf, so folding them together would mis-frame peers).
            # The device round trip this costs is confined to explicit
            # RANGE-offset frames over float keys — the only caller.
            return None
        self._order_sorted = v if self._order_asc else -v
        return self._order_sorted

    def scatter_back(self, sorted_vals, validity=None):
        data = sorted_vals[self.inv]
        v = None if validity is None else validity[self.inv]
        return data, v


def _change_flags(cols, perm, n):
    flags = jnp.zeros(n, dtype=bool).at[0].set(True)
    for k in key_arrays(cols):
        ks = k[perm]
        flags = flags.at[1:].set(flags[1:] | (ks[1:] != ks[:-1]))
    if not cols:
        flags = jnp.zeros(n, dtype=bool).at[0].set(True)
    return flags


def _running_latest(marked):
    """Per position, the latest index where marked >= 0 (cummax)."""
    return jax.lax.cummax(marked)


def _next_start(flags, n):
    idx = jnp.arange(n, dtype=jnp.int64)
    nxt = jnp.where(flags, idx, n)
    rev = jax.lax.cummin(nxt[::-1])[::-1]
    # next start *after* each position
    shifted = jnp.concatenate([rev[1:], jnp.array([n], dtype=rev.dtype)])
    return shifted


def _prefix(vals):
    """P[k] = sum of first k entries (length n+1)."""
    return jnp.concatenate([jnp.zeros(1, dtype=vals.dtype), jnp.cumsum(vals)])


def _segmented_searchsorted(vals, lo_bound, hi_bound, targets, side: str):
    """Per-row binary search of `targets[i]` within vals[lo_bound[i]:hi_bound[i]].

    `vals` is sorted ascending within each segment; a fixed log2(n) round count
    of gathers keeps everything vectorized (no per-segment slices).
    """
    n = vals.shape[0]
    lo = lo_bound.astype(jnp.int64)
    hi = hi_bound.astype(jnp.int64)
    rounds = max(int(np.ceil(np.log2(max(n, 2)))) + 1, 1)

    def body(_, state):
        lo, hi = state
        mid = (lo + hi) // 2
        mv = vals[jnp.clip(mid, 0, n - 1)]
        if side == "left":
            go_right = mv < targets
        else:
            go_right = mv <= targets
        new_lo = jnp.where((lo < hi) & go_right, mid + 1, lo)
        new_hi = jnp.where((lo < hi) & ~go_right, mid, hi)
        return (new_lo, new_hi)

    lo, hi = jax.lax.fori_loop(0, rounds, body, (lo, hi))
    return lo


def _frame_bounds(w: WindowExpr, lay: _SortedLayout):
    """Per sorted row: [lo, hi) frame range."""
    n = lay.n
    i = jnp.arange(n, dtype=jnp.int64)
    spec = w.spec
    if spec.units == "RANGE" or not spec.explicit_frame and spec.order_by:
        # default ordered frame: start of segment .. end of current peer group
        lo = lay.seg_start
        hi = lay.peer_end
        if spec.explicit_frame:
            s, e = spec.start, spec.end
            if s.kind == "CURRENT_ROW":
                lo = lay.peer_start
            if e.kind == "UNBOUNDED_FOLLOWING":
                hi = lay.seg_end
            if s.kind == "UNBOUNDED_PRECEDING":
                lo = lay.seg_start
            if e.kind == "CURRENT_ROW":
                hi = lay.peer_end
            if s.kind in ("PRECEDING", "FOLLOWING") and s.offset is not None \
                    or e.kind in ("PRECEDING", "FOLLOWING") and e.offset is not None:
                # value-based offsets: per-segment binary search on the order key
                v = lay.order_values()
                if v is None:
                    raise NotImplementedError(
                        "RANGE offset frames need a single non-null numeric/datetime "
                        "ORDER BY key")
                if s.kind == "PRECEDING":
                    lo = _segmented_searchsorted(v, lay.seg_start, lay.seg_end,
                                                 v - s.offset, "left")
                elif s.kind == "FOLLOWING":
                    lo = _segmented_searchsorted(v, lay.seg_start, lay.seg_end,
                                                 v + s.offset, "left")
                if e.kind == "PRECEDING":
                    hi = _segmented_searchsorted(v, lay.seg_start, lay.seg_end,
                                                 v - e.offset, "right")
                elif e.kind == "FOLLOWING":
                    hi = _segmented_searchsorted(v, lay.seg_start, lay.seg_end,
                                                 v + e.offset, "right")
        return lo, hi
    # ROWS frames
    s, e = w.spec.start, w.spec.end
    if s.kind == "UNBOUNDED_PRECEDING":
        lo = lay.seg_start
    elif s.kind == "PRECEDING":
        lo = jnp.maximum(lay.seg_start, i - int(s.offset))
    elif s.kind == "CURRENT_ROW":
        lo = i
    elif s.kind == "FOLLOWING":
        lo = jnp.minimum(lay.seg_end, i + int(s.offset))
    else:
        lo = lay.seg_start
    if e.kind == "UNBOUNDED_FOLLOWING":
        hi = lay.seg_end
    elif e.kind == "FOLLOWING":
        hi = jnp.minimum(lay.seg_end, i + int(e.offset) + 1)
    elif e.kind == "CURRENT_ROW":
        hi = i + 1
    elif e.kind == "PRECEDING":
        hi = jnp.maximum(lay.seg_start, i - int(e.offset) + 1)
    else:
        hi = lay.seg_end
    return lo, hi


def _compute_window(w: WindowExpr, args: List[Column], lay: _SortedLayout) -> Column:
    n = lay.n
    if n == 0:
        return Column(jnp.zeros(0, dtype=sql_to_np(w.sql_type)), w.sql_type)
    i = jnp.arange(n, dtype=jnp.int64)
    func = w.func

    if func == "row_number":
        vals = i - lay.seg_start + 1
        data, _ = lay.scatter_back(vals)
        return Column(data.astype(jnp.int64), SqlType.BIGINT)
    if func == "rank":
        vals = lay.peer_start - lay.seg_start + 1
        data, _ = lay.scatter_back(vals)
        return Column(data.astype(jnp.int64), SqlType.BIGINT)
    if func == "dense_rank":
        np_int = lay.new_peer.astype(jnp.int64)
        c = jnp.cumsum(np_int)
        vals = c - c[lay.seg_start] + 1
        data, _ = lay.scatter_back(vals)
        return Column(data.astype(jnp.int64), SqlType.BIGINT)
    if func == "percent_rank":
        seg_len = lay.seg_end - lay.seg_start
        rank = lay.peer_start - lay.seg_start + 1
        vals = jnp.where(seg_len > 1, (rank - 1) / jnp.maximum(seg_len - 1, 1), 0.0)
        data, _ = lay.scatter_back(vals)
        return Column(data.astype(jnp.float64), SqlType.DOUBLE)
    if func == "cume_dist":
        seg_len = lay.seg_end - lay.seg_start
        vals = (lay.peer_end - lay.seg_start) / jnp.maximum(seg_len, 1)
        data, _ = lay.scatter_back(vals)
        return Column(data.astype(jnp.float64), SqlType.DOUBLE)
    if func == "ntile":
        k = int(np.asarray(args[0].data)[0]) if args else 1
        seg_len = lay.seg_end - lay.seg_start
        rn = i - lay.seg_start
        vals = jnp.minimum((rn * k) // jnp.maximum(seg_len, 1), k - 1) + 1
        data, _ = lay.scatter_back(vals)
        return Column(data.astype(jnp.int64), SqlType.BIGINT)
    if func in ("lag", "lead"):
        x = args[0]
        off = int(np.asarray(args[1].data)[0]) if len(args) > 1 else 1
        default = args[2] if len(args) > 2 else None
        xs = x.data[lay.perm]
        xv = x.valid_mask()[lay.perm]
        if w.ignore_nulls:
            # k-th previous/next VALID value: rank rows among valid ones
            P = jnp.cumsum(xv.astype(jnp.int64))  # valids among rows [0..i]
            valid_pos = jnp.nonzero(xv)[0]
            nvalid = int(valid_pos.shape[0])
            if func == "lag":
                rank = P - xv.astype(jnp.int64) - off  # 0-based among prior valids
            else:
                rank = P + off - 1  # 0-based among valids up to target
            ok = (rank >= 0) & (rank < nvalid)
            j = valid_pos[jnp.clip(rank, 0, max(nvalid - 1, 0))] if nvalid else jnp.zeros(n, dtype=jnp.int64)
            inside = ok & (j >= lay.seg_start) & (j < lay.seg_end)
        else:
            j = i - off if func == "lag" else i + off
            inside = (j >= lay.seg_start) & (j < lay.seg_end)
        j_safe = jnp.clip(j, 0, n - 1)
        vals = xs[j_safe]
        valid = xv[j_safe] & inside
        dictionary = x.dictionary
        if default is not None:
            dv = default.cast(x.sql_type)
            if dictionary is not None:
                # dv's codes index dv's OWN dictionary: translate into x's
                # space, extending it when the default value is new
                dictionary, dv = _remap_into_dictionary(dictionary, dv)
            ds = dv.data[lay.perm]
            vals = jnp.where(inside, vals, ds)
            valid = jnp.where(inside, valid, dv.valid_mask()[lay.perm])
        data, v = lay.scatter_back(vals, valid)
        return Column(data, w.sql_type, v, dictionary)

    # frame-based functions
    lo, hi = _frame_bounds(w, lay)
    if func in ("first_value", "last_value", "nth_value"):
        x = args[0]
        xs = x.data[lay.perm]
        xv = x.valid_mask()[lay.perm]
        if w.ignore_nulls and func in ("first_value", "last_value"):
            idx64 = jnp.arange(n, dtype=jnp.int64)
            if func == "first_value":
                # next valid index at-or-after each position (reverse cummin)
                marked = jnp.where(xv, idx64, n)
                nxt = jax.lax.cummin(marked[::-1])[::-1]
                j = nxt[jnp.clip(lo, 0, n - 1)]
            else:
                marked = jnp.where(xv, idx64, -1)
                prev = jax.lax.cummax(marked)
                j = prev[jnp.clip(hi - 1, 0, n - 1)]
        elif func == "first_value":
            j = lo
        elif func == "last_value":
            j = hi - 1
        else:
            if w.ignore_nulls:
                raise NotImplementedError("NTH_VALUE ... IGNORE NULLS is not supported")
            k = int(np.asarray(args[1].data)[0])
            j = lo + (k - 1)
        inside = (j >= lo) & (j < hi) & (hi > lo)
        j_safe = jnp.clip(j, 0, n - 1)
        vals = xs[j_safe]
        valid = xv[j_safe] & inside
        data, v = lay.scatter_back(vals, valid)
        return Column(data, w.sql_type, v, x.dictionary)

    if func == "count_star":
        vals = (hi - lo).astype(jnp.int64)
        data, _ = lay.scatter_back(vals)
        return Column(data, SqlType.BIGINT)

    x = args[0] if args else None
    xs = x.data[lay.perm] if x is not None else None
    xv = x.valid_mask()[lay.perm] if x is not None else None

    if func == "count":
        P = _prefix(xv.astype(jnp.int64))
        vals = P[hi] - P[lo]
        data, _ = lay.scatter_back(vals)
        return Column(data, SqlType.BIGINT)
    if func in ("sum", "avg"):
        acc = xs.astype(jnp.float64) if func == "avg" or xs.dtype.kind == "f" \
            else xs.astype(jnp.int64)
        acc = jnp.where(xv, acc, jnp.zeros_like(acc))
        P = _prefix(acc)
        s = P[hi] - P[lo]
        Pc = _prefix(xv.astype(jnp.int64))
        cnt = Pc[hi] - Pc[lo]
        if func == "avg":
            vals = s / jnp.maximum(cnt, 1)
        else:
            vals = s
        valid = cnt > 0
        data, v = lay.scatter_back(vals, valid)
        target = sql_to_np(w.sql_type)
        return Column(data.astype(target), w.sql_type, v)
    if func in ("min", "max"):
        big = _extreme_val(xs.dtype, func == "min")
        masked = jnp.where(xv, xs, big)
        # segmented running min/max handles prefix frames; bounded frames use
        # a log-shift sparse table (O(n log w)).  Prefix-ness is decided
        # STATICALLY from the frame spec — a device comparison here would be
        # a host round trip per query on a tunneled chip
        if _is_prefix_frame(w.spec):
            op = jnp.minimum if func == "min" else jnp.maximum
            run = _segmented_scan(masked, lay.new_seg, op)
            peer_adjusted = run[jnp.clip(hi - 1, 0, n - 1)]
            vals = peer_adjusted
        else:
            vals = _range_minmax(masked, lo, hi, func == "min")
        Pc = _prefix(xv.astype(jnp.int64))
        cnt = Pc[hi] - Pc[lo]
        valid = cnt > 0
        data, v = lay.scatter_back(vals, valid)
        return Column(data, w.sql_type, v, x.dictionary)
    if func in ("stddev_samp", "stddev_pop", "var_samp", "var_pop"):
        acc = jnp.where(xv, xs.astype(jnp.float64), 0.0)
        P1 = _prefix(acc)
        P2 = _prefix(acc * acc)
        Pc = _prefix(xv.astype(jnp.int64))
        cnt = Pc[hi] - Pc[lo]
        s1 = P1[hi] - P1[lo]
        s2 = P2[hi] - P2[lo]
        ddof = 1 if func.endswith("samp") else 0
        mean = s1 / jnp.maximum(cnt, 1)
        var = (s2 - cnt * mean * mean) / jnp.maximum(cnt - ddof, 1)
        var = jnp.maximum(var, 0.0)
        vals = jnp.sqrt(var) if func.startswith("stddev") else var
        valid = cnt > ddof
        data, v = lay.scatter_back(vals, valid)
        return Column(data, SqlType.DOUBLE, v)
    raise NotImplementedError(f"window function {func}")


def _remap_into_dictionary(base_dict, col: Column):
    """Translate `col`'s dictionary codes into `base_dict`'s code space,
    appending values base_dict lacks.  Returns (new_dict, remapped_col)."""
    src = np.asarray(col.dictionary if col.dictionary is not None
                     else np.array([], dtype=object), dtype=object)
    base = np.asarray(base_dict, dtype=object)
    pos = {str(v): i for i, v in enumerate(base)}
    extended = list(base)
    mapping = np.zeros(max(len(src), 1), dtype=np.int32)
    for i, v in enumerate(src):
        key = str(v)
        if key not in pos:
            pos[key] = len(extended)
            extended.append(v)
        mapping[i] = pos[key]
    codes = jnp.asarray(mapping)[jnp.clip(col.data, 0, max(len(src) - 1, 0))]
    return (np.asarray(extended, dtype=object),
            Column(codes, col.sql_type, col.validity,
                   np.asarray(extended, dtype=object)))


def _is_prefix_frame(spec) -> bool:
    """Frame always spans [segment start, current row/peer end): the shapes
    _frame_bounds emits lo = seg_start and hi = i+1 or peer_end for."""
    if not spec.explicit_frame:
        return True  # default frames are prefix frames either way
    s, e = spec.start, spec.end
    if s.kind != "UNBOUNDED_PRECEDING":
        return False
    if spec.units == "RANGE" or spec.order_by:
        return e.kind == "CURRENT_ROW" and e.offset is None
    return e.kind == "CURRENT_ROW"


def _extreme_val(dtype, for_min: bool):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf if for_min else -jnp.inf, dtype=dtype)
    info = jnp.iinfo(dtype)
    return jnp.array(info.max if for_min else info.min, dtype=dtype)


def _segmented_scan(vals, new_seg, op):
    """Running op within segments via associative scan with reset flags."""

    def combine(a, b):
        af, av = a
        bf, bv = b
        return (af | bf, jnp.where(bf, bv, op(av, bv)))

    flags, out = jax.lax.associative_scan(combine, (new_seg, vals))
    return out


def _range_minmax(masked, lo, hi, is_min: bool):
    """Sparse-table (doubling) range min/max query for arbitrary frames."""
    n = masked.shape[0]
    op = jnp.minimum if is_min else jnp.maximum
    big = _extreme_val(masked.dtype, is_min)
    levels = [masked]
    length = 1
    while length < n:
        prev = levels[-1]
        shifted = jnp.concatenate([prev[length:], jnp.full(min(length, n), big, dtype=prev.dtype)])
        levels.append(op(prev, shifted))
        length *= 2
    width = jnp.maximum(hi - lo, 1)
    k = jnp.floor(jnp.log2(width.astype(jnp.float64))).astype(jnp.int32)
    table = jnp.stack(levels)  # [levels, n]
    idx1 = jnp.clip(lo, 0, n - 1)
    idx2 = jnp.clip(hi - (1 << k.astype(jnp.int64)), 0, n - 1)
    a = table[k, idx1]
    b = table[k, idx2]
    return op(a, b)
