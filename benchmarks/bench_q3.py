"""TPC-H Q3 local benchmark (BASELINE config 3 shape): 3-way hash join + topN.

Not the driver's bench (that's bench.py / Q1) — a development yardstick for
the join path, vs the same pipeline in pandas.
"""
from __future__ import annotations

import sys
import time

import numpy as np
import pandas as pd

sys.path.insert(0, ".")
sys.path.insert(0, "tests")


def main(scale_rows: int = 1_000_000):
    from tpch import QUERIES, generate

    from dask_sql_tpu import Context

    tables = generate(scale_rows=scale_rows)
    c = Context()
    # result cache off: measure execution, not serving-cache lookups
    c.config.update({"serving.cache.enabled": False})
    for name, df in tables.items():
        c.create_table(name, df)

    q3 = QUERIES[3]
    _ = c.sql(q3).compute()  # warm-up
    times = []
    for _i in range(3):
        t0 = time.perf_counter()
        res = c.sql(q3).compute()
        times.append(time.perf_counter() - t0)
    ours = min(times)

    cust, orders, li = tables["customer"], tables["orders"], tables["lineitem"]

    def pandas_q3():
        m = cust[cust.c_mktsegment == "BUILDING"].merge(
            orders[orders.o_orderdate < pd.Timestamp("1995-03-15")],
            left_on="c_custkey", right_on="o_custkey")
        m = m.merge(li[li.l_shipdate > pd.Timestamp("1995-03-15")],
                    left_on="o_orderkey", right_on="l_orderkey")
        m = m.assign(revenue=m.l_extendedprice * (1 - m.l_discount))
        return (m.groupby(["l_orderkey", "o_orderdate", "o_shippriority"]).revenue.sum()
                .reset_index().sort_values(["revenue", "o_orderdate"],
                                           ascending=[False, True]).head(10))

    t0 = time.perf_counter()
    expected = pandas_q3()
    pt = time.perf_counter() - t0
    t0 = time.perf_counter()
    expected = pandas_q3()
    pt = min(pt, time.perf_counter() - t0)

    np.testing.assert_allclose(res["revenue"].to_numpy(),
                               expected["revenue"].to_numpy(), rtol=1e-9)
    print(f"rows={scale_rows}  ours={ours*1000:.0f}ms  pandas={pt*1000:.0f}ms  "
          f"speedup={pt/ours:.2f}x  throughput={scale_rows/ours/1e6:.2f}M rows/s")


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000)
