"""Compiled SELECT pipelines: one kernel + one transfer for root-level
`scan -> filter* -> project [-> sort -> limit]` queries.

The eager converters dispatch one XLA op per expression and per filter, then
materialize column-by-column — on a tunneled TPU every dispatch and every
pull is a round trip.  For the plan ROOT (the result goes straight to the
host anyway), this module compiles the whole chain into ONE jitted program
whose output is a single packed f64 matrix: row 0 is the selection mask,
then each projected column (and its validity) — pulled in ONE device_get,
compacted/ordered/limited with numpy on the host.

Two static-shape kernels: kernel 1 evaluates the filter mask and its count
(one scalar pull); kernel 2 — specialized per power-of-two survivor bucket,
so XLA re-traces at most log2(n) times — compacts the input columns with a
sized nonzero, evaluates the projections over the bucket, and packs
everything into one matrix whose transfer size tracks the SURVIVORS, not
the scan.  Sort/limit run on the compacted host result — the root is
host-bound regardless, and np.lexsort on the survivor set replaces a device
sort plus per-column gathers.

Parity note: the reference executes the same shape as a dask task tree with
one pandas kernel per operator; this is the TPU-native replacement.
"""
from __future__ import annotations

import logging
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar.column import Column
from ..columnar.dtypes import STRING_TYPES, SqlType, sql_to_np
from ..columnar.table import Table
from ..planner import plan as p
from ..planner.expressions import ColumnRef
from .compiled import (
    PARAMS_SLOT,
    _TableMeta,
    _TraceEval,
    _Unsupported,
    check_no_rle,
    count_codespace_predicates,
    pack_flat,
    singleflight_get_or_build,
)

logger = logging.getLogger(__name__)


def _extract(root):
    """Match [Limit]? [Sort]? Projection Filter* TableScan; None otherwise."""
    node = root
    limit = None
    if isinstance(node, p.Limit):
        limit = (node.skip, node.fetch)
        node = node.input
    sort_keys = None
    sort_fetch = None
    if isinstance(node, p.Sort):
        sort_keys = list(node.keys)
        sort_fetch = node.fetch  # caps the window INSIDE any outer Limit
        node = node.input
    if not isinstance(node, p.Projection):
        return None
    proj = node
    node = proj.input
    filters = []
    while isinstance(node, p.Filter):
        filters.append(node.predicate)
        node = node.input
    inner_limit = None
    while isinstance(node, p.Limit):
        # PushDownLimit parks (possibly stacked) Limits right above the
        # scan: compose them (EliminateLimit's rule) into one row window
        # baked into the mask
        if inner_limit is None:
            inner_limit = (node.skip, node.fetch)
        else:
            oskip, ofetch = inner_limit  # applied AFTER this inner node
            iskip, ifetch = node.skip, node.fetch
            fetches = [f for f in (
                None if ifetch is None else max(ifetch - oskip, 0),
                ofetch) if f is not None]
            inner_limit = (iskip + oskip, min(fetches) if fetches else None)
        node = node.input
    if not isinstance(node, p.TableScan):
        return None
    # upper Filter-node predicates stay separate from scan.filters: a Limit
    # parked between them windows only the scan-filtered rows
    # (limit-then-filter), so the mask builder needs both lists
    return (node, list(filters), proj, sort_keys, sort_fetch, limit,
            inner_limit)


class CompiledSelect:
    #: the ladder-rung label this pipeline's compiles are recorded under
    #: (``resilience.compile_ms.<rung>`` histograms, ``compile:<rung>``
    #: trace spans) — subclasses that serve a DIFFERENT rung (the streamed
    #: select, streaming/select.py) override it so their compiles never
    #: pollute this rung's compile-cost prior (ladder.cost_skip reads it)
    _RUNG = "compiled_select"

    def __init__(self, table: Table, scan, upper_filters, scan_filters,
                 proj, proj_exprs, sort_keys, sort_fetch, limit, inner_limit,
                 params=()):
        self.scan = scan
        self.proj = proj
        self.sort_keys = sort_keys
        self.sort_fetch = sort_fetch
        self.limit = limit
        self.inner_limit = inner_limit
        self.table: Optional[Table] = table

        # eligibility: every output expr must trace; string outputs only as
        # plain column refs (codes + dictionary pass through); sort keys must
        # be output positions over non-string columns (host lexsort order on
        # dictionary codes is only lexicographic for sorted dictionaries)
        check_no_rle(table)
        from ..columnar.encodings import Encoding

        #: compressed-domain accounting: encoded inputs mean the mask phase
        #: reads codes and the survivor gather late-materializes values
        self.has_encoded = any(
            c.encoding is not Encoding.PLAIN for c in table.columns.values())
        self.codespace_preds = count_codespace_predicates(
            list(upper_filters) + list(scan_filters) + list(proj_exprs),
            table) if self.has_encoded else 0
        self.out_meta: List[Tuple[str, SqlType, Optional[object]]] = []
        for e, f in zip(proj_exprs, proj.schema):
            if f.sql_type in STRING_TYPES:
                if not (isinstance(e, ColumnRef) and type(e) is ColumnRef):
                    raise _Unsupported("computed string output")
                dictionary = table.columns[table.column_names[e.index]].dictionary
            else:
                dictionary = None
            self.out_meta.append((f.name, f.sql_type, dictionary))
        if sort_keys is not None:
            for k in sort_keys:
                e = k.expr
                if not (isinstance(e, ColumnRef) and type(e) is ColumnRef):
                    raise _Unsupported("sort key is not an output column")
                if proj.schema[e.index].sql_type in STRING_TYPES:
                    dic = self.out_meta[e.index][2]
                    if dic is None or not _dictionary_sorted(dic):
                        raise _Unsupported("string sort key w/o sorted dict")

        ev = _TraceEval(_TableMeta(table))
        n_cols = len(table.column_names)
        exprs = list(proj_exprs)
        upper_flts = list(upper_filters)
        scan_flts = list(scan_filters)
        self._pack_tags: List[Tuple[str, np.dtype]] = []

        inner_limit = self.inner_limit

        def mask_fn(datas, valids, row_valid, params=()):
            slots = {i: (datas[i], valids[i]) for i in range(n_cols)}
            slots[PARAMS_SLOT] = params
            nr = datas[0].shape[0] if datas else 0

            def fold(mask, f):
                d, v = ev.eval(f, slots)
                m = d if v is None else (d & v)
                return m if mask is None else (mask & m)

            def as_rows(mask):
                if mask is None:
                    return jnp.ones(nr, dtype=bool)
                if mask.ndim == 0:  # constant predicate (e.g. WHERE 1 = 1)
                    return jnp.broadcast_to(mask, (nr,))
                return mask

            mask = row_valid
            for f in scan_flts:
                mask = fold(mask, f)
            if inner_limit is not None:
                # a Limit parked above the scan windows the rows the SCAN's
                # own filters keep — the plan order is limit-then-filter
                # (Projection <- Filter* <- Limit <- TableScan), so upper
                # Filter-node predicates must apply AFTER the window, not
                # shrink it (ADVICE r5).  The survivor ordinal makes the
                # window a static-shape mask refinement.
                mask = as_rows(mask)
                skip_i, fetch_i = inner_limit
                ordinal = self._survivor_ordinal(mask)
                w = ordinal > skip_i
                if fetch_i is not None:
                    w &= ordinal <= skip_i + fetch_i
                mask = mask & w
            for f in upper_flts:
                mask = fold(mask, f)
            mask = as_rows(mask)
            return mask, jnp.sum(mask.astype(jnp.int64))

        def gather_fn(datas, valids, mask, params, bucket):
            # bucket is static per trace: sized nonzero keeps shapes static,
            # and jit re-specializes per distinct bucket (<= log2 n traces)
            (idx,) = jnp.nonzero(mask, size=bucket, fill_value=0)
            slots = {}
            for i in range(n_cols):
                d = datas[i][idx]
                v = valids[i][idx] if valids[i] is not None else None
                slots[i] = (d, v)
            slots[PARAMS_SLOT] = params
            flat = []
            for e in exprs:
                d, v = ev.eval(e, slots)
                if d.ndim == 0:  # scalar literal output: broadcast
                    d = jnp.broadcast_to(d, (bucket,))
                if v is not None and v.ndim == 0:
                    # kernels may emit a scalar validity (e.g. a literal arg
                    # folded into the op's mask): broadcast to the row shape
                    v = jnp.broadcast_to(v, (bucket,))
                flat.append(d)
                flat.append(v if v is not None else jnp.ones(bucket, dtype=bool))
            # extension seam: the fused PREDICT rung (compiled_predict.py)
            # appends its model-program outputs here, INSIDE the same
            # traced gather — one executable, one packed transfer
            for d, v in self._extra_pack_outputs(ev, slots, bucket):
                if d.ndim == 0:
                    d = jnp.broadcast_to(d, (bucket,))
                flat.append(d)
                flat.append(v if v is not None
                            else jnp.ones(bucket, dtype=bool))
            tags: List[Tuple[str, np.dtype]] = []
            out = pack_flat(flat, tags)
            self._pack_tags = tags
            return out

        # trace-check now so ineligible expressions fall back BEFORE the
        # plugin cache ever sees this object
        datas_s = tuple(table.columns[n].data for n in table.column_names)
        valids_s = tuple(table.columns[n].validity for n in table.column_names)
        # eval_shape needs only shapes/dtypes: anything already exposing
        # them (numpy values, committed DEVICE weight arrays from the
        # fused PREDICT rung) passes through without a d2h pull
        params_s = tuple(v if hasattr(v, "shape") and hasattr(v, "dtype")
                         else np.asarray(v) for v in params)
        jax.eval_shape(mask_fn, datas_s, valids_s, table.row_valid, params_s)
        jax.eval_shape(lambda d, v, m, q: gather_fn(d, v, m, q, 8), datas_s,
                       valids_s,
                       jax.ShapeDtypeStruct((table.padded_rows,), jnp.bool_),
                       params_s)
        self._mask_fn_raw = mask_fn
        self._mask_fn = jax.jit(mask_fn)
        self._gather_fn_raw = gather_fn  # for the SPMD rung's shard_map
        self._gather_fn = jax.jit(gather_fn, static_argnames=("bucket",))
        #: lazily-built vmapped mask variant for the family batcher: ONE
        #: stacked launch evaluates every co-admitted member's filter over
        #: a single scan; compiled per pow2 batch bucket
        self._mask_batched = None
        self._warm_mask_batch: set = set()
        #: compile-watchdog hints: the mask kernel compiles once, the
        #: gather kernel once per distinct pow2 survivor bucket
        self._mask_warm = False
        self._warm_buckets: set = set()

    def _extra_pack_outputs(self, ev, slots, bucket):
        """Extra (data, validity_or_None) pairs appended to the packed
        gather output under trace — the seam the fused PREDICT rung
        (CompiledPredict) overrides to run its model program over the
        gathered survivors in the SAME jit.  ``slots`` holds the gathered
        per-column (data, valid) pairs plus the runtime parameter vector
        under PARAMS_SLOT."""
        return ()

    def _survivor_ordinal(self, mask):
        """1-based running survivor count the inner-LIMIT window slices.
        Local cumsum on a single device; the SPMD rung (spmd/select.py)
        overrides with a cross-shard prefix so the window stays a GLOBAL
        row ordinal under shard_map."""
        return jnp.cumsum(mask.astype(jnp.int64))

    def run(self, table: Optional[Table] = None, params: Tuple = ()) -> Table:
        from ..utils import count_d2h
        from ..observability import timed_jit_call

        # parameter, not shared state: cached pipelines serve concurrent
        # worker threads (see CompiledAggregate.run)
        t = table if table is not None else self.table
        datas = tuple(t.columns[n].data for n in t.column_names)
        valids = tuple(t.columns[n].validity for n in t.column_names)
        mask, count_dev = timed_jit_call(
            self._RUNG, self._mask_fn, datas, valids, t.row_valid,
            tuple(params), may_compile=not self._mask_warm)
        self._mask_warm = True
        count_d2h()
        count = int(count_dev)  # one scalar round trip
        return self._finish(datas, valids, mask, count, tuple(params))

    def _batched_param_split(self) -> Optional[int]:
        """Count of leading runtime-parameter slots the batched vmap maps
        over the batch axis; None = every slot (the family literal
        vector).  The fused PREDICT rung (CompiledPredict) returns its
        family-prefix length so the shared model weight tail rides
        UNMAPPED instead of being stacked per batch slot."""
        return None

    def run_batched(self, table: Table, params_list: List[Tuple]
                    ) -> List[Table]:
        """Family-batched execution: member literal vectors stack along a
        new leading axis and ONE vmapped launch computes every member's
        selection mask over a single shared scan (batch padded to the pow2
        bucket by repeating the last member).  Survivor gathers then run
        per member — they share the per-bucket gather executables."""
        from ..families import stack_params
        from ..utils import count_d2h
        from ..observability import timed_jit_call

        n = len(params_list)
        base = self._batched_param_split()
        if base is None:
            stacked, bucket = stack_params(params_list)
            launch_params, axes = stacked, 0
            member_params = params_list
        else:
            # shared unmapped tail (e.g. model weights): every member
            # references the same arrays, so stacking would copy them per
            # batch slot for a mask kernel that never reads them
            tail = tuple(params_list[0][base:])
            stacked, bucket = stack_params([m[:base] for m in params_list])
            launch_params = tuple(stacked) + tail
            axes = tuple([0] * base) + tuple([None] * len(tail))
            member_params = [tuple(m[:base]) + tail for m in params_list]
        if self._mask_batched is None:
            self._mask_batched = jax.jit(
                jax.vmap(self._mask_fn_raw,
                         in_axes=(None, None, None, axes)))
        datas = tuple(table.columns[c].data for c in table.column_names)
        valids = tuple(table.columns[c].validity
                       for c in table.column_names)
        masks, counts_dev = timed_jit_call(
            self._RUNG, self._mask_batched, datas, valids,
            table.row_valid, launch_params,
            may_compile=bucket not in self._warm_mask_batch)
        self._warm_mask_batch.add(bucket)
        count_d2h()
        counts = np.asarray(jax.device_get(counts_dev))
        return [self._finish(datas, valids, masks[b], int(counts[b]),
                             member_params[b]) for b in range(n)]

    def _finish(self, datas, valids, mask, count: int,
                params: Tuple) -> Table:
        from ..utils import count_d2h
        from ..observability import timed_jit_call

        # without an ORDER BY, a LIMIT caps how many survivors we even pull:
        # sized nonzero returns ascending indices, so the first `want` rows
        # ARE the eager path's first `want` rows
        count = self._limit_trim(count)
        if count == 0:
            host = None
        else:
            bucket = 1 << (count - 1).bit_length()
            # jit re-specializes per bucket: each new bucket is a fresh
            # XLA compile the observability layer records per rung
            packed = timed_jit_call(self._RUNG, self._gather_fn,
                                    datas, valids, mask, params,
                                    bucket=bucket,
                                    may_compile=bucket not in
                                    self._warm_buckets)
            self._warm_buckets.add(bucket)
            count_d2h()
            host = np.asarray(jax.device_get(packed))
        cols, valid_arrs = self._decode_packed(host, count)
        return self._assemble(cols, valid_arrs, count)

    def _limit_trim(self, count: int) -> int:
        """Sort-free LIMIT: survivor indices ascend, so the first `want`
        rows ARE the eager path's — cap the pull."""
        if self.sort_keys is None and self.limit is not None \
                and self.limit[1] is not None:
            return min(count, self.limit[0] + self.limit[1])
        return count

    def _decode_packed(self, host: Optional[np.ndarray], count: int):
        """Packed host matrix -> per-output (data, validity) numpy arrays.
        `host` is None when there are zero survivors."""
        from .compiled import unpack_row

        cols: List[np.ndarray] = []
        valid_arrs: List[Optional[np.ndarray]] = []
        if count == 0 or host is None:
            for name, sql_type, dictionary in self.out_meta:
                cols.append(np.zeros(0, dtype=sql_to_np(sql_type)))
                valid_arrs.append(None)
            return cols, valid_arrs
        tags = self._pack_tags
        for i, (name, sql_type, dictionary) in enumerate(self.out_meta):
            d = unpack_row(host, 2 * i, tags)[:count]
            v = unpack_row(host, 1 + 2 * i, tags).astype(bool)[:count]
            target = sql_to_np(sql_type)
            if d.dtype != target:
                d = d.astype(target)
            cols.append(d)
            valid_arrs.append(None if bool(v.all()) else v)
        return cols, valid_arrs

    def _assemble(self, cols: List[np.ndarray],
                  valid_arrs: List[Optional[np.ndarray]],
                  count: int) -> Table:
        """Host-side tail shared with the SPMD rung (spmd/select.py):
        ORDER BY + window slicing + output naming over decoded survivor
        columns."""
        # host-side ORDER BY: the same host-numpy sort the engine uses for
        # tiny post-aggregate tables (ops/sorting.sort_permutation — NaN
        # sorts as +inf, NULL placement per nulls_first)
        order = None
        if self.sort_keys:
            from ..ops.sorting import sort_permutation

            key_cols = []
            for k in self.sort_keys:
                idx = k.expr.index
                _, sql_type, dictionary = self.out_meta[idx]
                key_cols.append(Column(cols[idx], sql_type, valid_arrs[idx],
                                       dictionary))
            order = np.asarray(sort_permutation(
                key_cols, [k.ascending for k in self.sort_keys],
                [k.nulls_first_resolved() for k in self.sort_keys]))

        from .rel.base import unique_names

        names = [m[0] for m in self.out_meta]
        uniq = unique_names(names)
        out: Dict[str, Column] = {}
        n_out = count
        if self.sort_fetch is not None:
            n_out = min(n_out, self.sort_fetch)
        lo, hi = 0, n_out
        if self.limit is not None:
            skip, fetch = self.limit
            lo = min(skip, n_out)
            hi = n_out if fetch is None else min(skip + fetch, n_out)
        for i, (uname, (name, sql_type, dictionary)) in enumerate(
                zip(uniq, self.out_meta)):
            d = cols[i]
            v = valid_arrs[i]
            if order is not None:
                d = d[order]
                v = v[order] if v is not None else None
            d = d[lo:hi]
            v = v[lo:hi] if v is not None else None
            out[uname] = Column(d, sql_type, v, dictionary)
        return Table(out, hi - lo)


def _dictionary_sorted(dic) -> bool:
    a = np.asarray(dic, dtype=object)
    return bool(all(str(a[i]) <= str(a[i + 1]) for i in range(len(a) - 1)))


_CACHE_CAP = 32
_cache: "OrderedDict[Tuple, CompiledSelect]" = OrderedDict()
def _family_of(key: Tuple) -> Tuple:
    """Plan family = cache key minus (uid, num_rows, padded_rows): a miss
    for a family the context already compiled under a DIFFERENT table
    bucket means the table grew or was replaced — the background-recompile
    trigger (see physical/compiled.py for the pattern; family -> bucket
    lives on context._compiled_families)."""
    return ("compiled_select",) + key[1:-2]


def _bucket_of(key: Tuple) -> Tuple:
    return (key[0], key[-2], key[-1])  # (uid, num_rows, padded_rows)


def resolve_pipeline_inputs(scan, upper_filters, proj, executor):
    """Shared eligibility preamble + family parameterization of a root
    select chain — used by BOTH try_compiled_select and the fused PREDICT
    rung (compiled_predict.py), so a new eligibility rule can never
    silently apply to one and not the other.  Returns ``(dc, table,
    p_upper, p_scan_flts, p_exprs, params)`` or None (decline)."""
    dc = executor.context.schema[scan.schema_name].tables.get(scan.table_name)
    if dc is None:
        return None  # view-backed scans take the eager path
    from ..datacontainer import LazyParquetContainer

    if isinstance(dc, LazyParquetContainer):
        return None  # IO-pushdown path already minimizes transfers
    table = executor.get_table(scan.schema_name, scan.table_name)
    if scan.projection is not None:
        table = table.select(scan.projection)
    if not table.column_names:
        return None
    from ..parallel.dist_plan import table_is_sharded

    if table_is_sharded(table):
        # mesh-sharded scans keep the distributed operators (range-
        # partition sort leaves results sharded in sort order; pulling
        # the whole table to one host defeats the layout)
        return None
    # parameterize (families/): filter and projection literals become
    # runtime parameters so the cache key — and the mask/gather
    # executables — are shared by the whole query family.  LIMIT /
    # sort-fetch windows stay static (they steer host slicing and the
    # survivor pull), so each window is its own family.
    from .. import families

    pz = families.pipeline_parameterizer(executor.config)
    p_upper = [pz.rewrite(f) for f in upper_filters]
    p_scan_flts = [pz.rewrite(f) for f in scan.filters]
    p_exprs = [pz.rewrite(e) for e in proj.exprs]
    return dc, table, p_upper, p_scan_flts, p_exprs, pz.params


def try_compiled_select(root, executor) -> Optional[Table]:
    """Attempt the one-kernel/one-transfer path for a ROOT select chain."""
    mode = executor.config.get("sql.compile.select", True)
    if not mode or not executor.config.get("sql.compile", True):
        return None
    got = _extract(root)
    if got is None:
        return None
    scan, upper_filters, proj, sort_keys, sort_fetch, limit, inner_limit = got
    try:
        from .. import families

        resolved = resolve_pipeline_inputs(scan, upper_filters, proj,
                                           executor)
        if resolved is None:
            return None
        dc, table, p_upper, p_scan_flts, p_exprs, params = resolved
        key = (
            dc.uid,
            tuple(scan.projection or ()),
            tuple(str(f) for f in p_upper),
            tuple(str(f) for f in p_scan_flts),
            tuple(str(e) for e in p_exprs),
            tuple(str(k.expr) + str(k.ascending) + str(k.nulls_first)
                  for k in sort_keys) if sort_keys else None,
            sort_fetch,
            limit,
            inner_limit,
            table.num_rows,
            table.padded_rows,
        )
        ctx = executor.context

        def build():
            if _defer_to_background(ctx, key, table, scan, p_upper,
                                    p_scan_flts, proj, p_exprs, sort_keys,
                                    sort_fetch, limit, inner_limit, params):
                return None  # served on a lower rung this time
            obj = CompiledSelect(table, scan, p_upper, p_scan_flts, proj,
                                 p_exprs, sort_keys, sort_fetch, limit,
                                 inner_limit, params)
            # cached pipelines must not pin the construction table's HBM
            obj.table = None
            from .compiled import _remember_family_locked

            with ctx._plan_lock:
                _cache[key] = obj
                while len(_cache) > _CACHE_CAP:
                    _cache.popitem(last=False)
                _remember_family_locked(ctx, _family_of(key),
                                        _bucket_of(key))
            return obj

        compiled, built_here = singleflight_get_or_build(ctx, _cache, key,
                                                         build)
        if compiled is None:
            return None  # deferred to the background compiler
        if not built_here and params:
            ctx.metrics.inc("families.hit")
            from ..observability import trace_event

            trace_event("family_hit", rung="compiled_select",
                        params=len(params))
        if built_here and compiled.codespace_preds:
            ctx.metrics.inc("columnar.encoding.codespace_pred",
                            compiled.codespace_preds)
        from ..resilience import faults

        faults.maybe_inject("oom", executor.config)
        batcher = families.batcher_of(ctx)
        if batcher is not None and params:
            result = batcher.run(
                ("compiled_select",) + key, params,
                solo=lambda: compiled.run(table, params),
                batched=lambda members: compiled.run_batched(table, members))
        else:
            result = compiled.run(table, params)
        if compiled.has_encoded:
            # late materialization: only surviving rows decoded (in the
            # per-bucket gather), and only at the root
            ctx.metrics.inc("columnar.encoding.late_rows", result.num_rows)
        return result
    except _Unsupported as e:
        logger.debug("compiled select unsupported: %s", e)
        return None
    except (ValueError, TypeError, NotImplementedError) as e:
        # an expression the trace evaluator mis-shapes must never sink the
        # query — the eager converters are always correct
        logger.debug("compiled select declined: %s", e)
        return None


def _defer_to_background(ctx, key, table, scan, upper_filters, scan_filters,
                         proj, proj_exprs, sort_keys, sort_fetch, limit,
                         inner_limit, params=()) -> bool:
    """Background-recompile hook for root select chains: the shared
    `defer_rebuild` policy (physical/compiled.py) with this rung's
    constructor.  Returns True when deferred."""
    from .compiled import defer_rebuild

    def build_and_warm():
        obj = CompiledSelect(table, scan, upper_filters, scan_filters,
                             proj, proj_exprs, sort_keys, sort_fetch,
                             limit, inner_limit, params)
        obj.run(table, params)  # compiles mask + first gather
        obj.table = None
        return obj

    return defer_rebuild(ctx, "compiled_select", _cache, _CACHE_CAP, key,
                         _family_of(key), _bucket_of(key), build_and_warm)
