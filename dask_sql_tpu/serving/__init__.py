"""Serving runtime: the layer between the front-ends (Presto server,
`Context.sql`) and the executor for multi-query traffic.

Three cooperating parts (TCR, arXiv:2203.01877 — once kernels are XLA-bound,
end-to-end serving wins come from the runtime around them; Flare,
arXiv:1703.08219 makes the same point for compiled Spark):

- :mod:`.admission` — bounded per-class admission control with deadlines and
  load shedding (structured retry-after errors instead of unbounded queues);
- :mod:`.cache` — an LRU-by-bytes cache of materialized result Tables keyed
  on (plan fingerprint, catalog signature, config), invalidated by DDL/DML
  through the same versioning the plan cache uses;
- :mod:`.metrics` — counters + latency/queue-depth histograms aggregated
  from the per-node Tracer, surfaced as ``SHOW METRICS`` and ``/v1/metrics``.

Zero-cold-start additions (docs/serving.md "Cold starts"):

- :mod:`.compile_cache` — the persistent XLA executable cache, so a
  restart deserializes hot executables instead of recompiling them;
- :mod:`.warmup` — profile-driven pre-warm after load_state / server boot,
  reported by ``/v1/health`` as ``warming`` -> ``ready``;
- :mod:`.background` — the bounded background recompile thread that takes
  bucket-growth recompiles off the serving path.

Estimator-driven scheduling (docs/serving.md "Scheduling and
multi-tenancy"):

- :mod:`.scheduler` — the packing scheduler: concurrently admitted queries
  are packed against the device byte budget using each family's provable
  ``peak_bytes`` floor, ordered deadline-first, with per-tenant
  token-bucket quotas (``X-Dsql-Tenant``) so one tenant's batch scan
  cannot starve interactive traffic.

:mod:`.runtime` ties them together into the worker pool the Presto server
runs queries on.
"""
from ..resilience.errors import ShutdownError
from .admission import (
    AdmissionController,
    DeadlineExceededError,
    QueryCancelledError,
    QueryTicket,
    QueueFullError,
)
from .background import BackgroundCompiler
from .cache import ResultCache, table_nbytes
from .metrics import Histogram, MetricsRegistry
from .runtime import ServingRuntime, current_ticket
from .scheduler import PackingScheduler, QueryCost, TokenBucket
from .warmup import WarmupManager

__all__ = [
    "AdmissionController",
    "BackgroundCompiler",
    "DeadlineExceededError",
    "Histogram",
    "MetricsRegistry",
    "PackingScheduler",
    "QueryCancelledError",
    "QueryCost",
    "QueryTicket",
    "QueueFullError",
    "ResultCache",
    "ServingRuntime",
    "ShutdownError",
    "TokenBucket",
    "WarmupManager",
    "current_ticket",
    "table_nbytes",
]
