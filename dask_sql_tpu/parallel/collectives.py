"""Distributed aggregation and shuffle kernels: shard_map + XLA collectives.

Role parity: the reference's distribution strategies (SURVEY.md §2.3) —
partial→final tree aggregation (dd.Aggregation chunk/agg/finalize +
split_out/split_every), tasks-based hash shuffle, broadcast join — rebuilt as
jit-compiled SPMD programs: every kernel below is `shard_map`ped over a 1-D
device mesh, uses static shapes (capacity-padded, validity-masked), and
communicates only through XLA collectives (all_gather / all_to_all / psum)
so the compiler schedules them onto ICI/DCN.

Key design (SURVEY.md §7 hard parts — dynamic shapes): each shard reduces its
rows into a CAPACITY-bounded sorted partial table (keys, states, valid).
Exactness is preserved by construction: if a shard sees more than CAPACITY
distinct keys an overflow flag is raised so the caller re-runs with doubled
capacity (compile-cache friendly: capacities come from a fixed ladder).
"""
from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
try:
    from jax import shard_map
except ImportError:  # pre-0.4.x top-level export: experimental namespace
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import AXIS, default_mesh

#: capacity ladder keeps recompiles bounded (capacity-doubling strategy)
CAPACITY_LADDER = (256, 4096, 65536, 1 << 20)

# ---------------------------------------------------------------------------
# Local (per-shard) building blocks — pure jnp, jit-safe static shapes
# ---------------------------------------------------------------------------


def _local_sorted_groups(keys: jnp.ndarray, valid: jnp.ndarray, capacity: int):
    """Sort rows by key and produce segment ids, bounded by `capacity`.

    Returns (order, seg_of_sorted_row, uniq_keys[capacity], uniq_valid[capacity],
    overflow: bool scalar).  Invalid rows sort last and take no segment.
    """
    n = keys.shape[0]
    big = jnp.iinfo(keys.dtype).max
    sort_keys = jnp.where(valid, keys, big)
    order = jnp.argsort(sort_keys)
    ks = sort_keys[order]
    vs = valid[order]
    changed = jnp.concatenate([vs[:1], (ks[1:] != ks[:-1]) & vs[1:]])
    seg = jnp.cumsum(changed.astype(jnp.int32)) - 1
    seg = jnp.where(vs, seg, capacity - 1)  # park invalid rows in the last slot
    n_groups = jnp.max(jnp.where(vs, seg + 1, 0), initial=0)
    overflow = n_groups > capacity
    seg = jnp.minimum(seg, capacity - 1)
    uniq_keys = jnp.zeros((capacity,), dtype=keys.dtype).at[seg].max(
        jnp.where(vs, ks, jnp.zeros_like(ks)))
    uniq_valid = jnp.zeros((capacity,), dtype=bool).at[seg].max(vs)
    return order, seg, uniq_keys, uniq_valid, overflow


# aggregation state layout: (count, sum, min, max, sumsq) per value column —
# the same chunk/agg/finalize triple family as the reference's
# AGGREGATION_MAPPING (aggregate.py:117-231 there)
N_STATE = 5


def _partial_states(values: jnp.ndarray, valid: jnp.ndarray, seg, order, capacity: int):
    v = values[order].astype(jnp.float64)
    val = valid[order]
    zero = jnp.zeros((capacity,), dtype=jnp.float64)
    cnt = zero.at[seg].add(val.astype(jnp.float64))
    s = zero.at[seg].add(jnp.where(val, v, 0.0))
    mn = jnp.full((capacity,), jnp.inf).at[seg].min(jnp.where(val, v, jnp.inf))
    mx = jnp.full((capacity,), -jnp.inf).at[seg].max(jnp.where(val, v, -jnp.inf))
    s2 = zero.at[seg].add(jnp.where(val, v * v, 0.0))
    return jnp.stack([cnt, s, mn, mx, s2], axis=-1)  # [capacity, N_STATE]


def _combine_states(keys, valid, states, capacity: int):
    """Merge duplicate keys in a concatenated partial table (the `agg` stage)."""
    order, seg, uniq_keys, uniq_valid, overflow = _local_sorted_groups(keys, valid, capacity)
    st = states[order]
    val = valid[order]
    zero = jnp.zeros((capacity,), dtype=jnp.float64)
    cnt = zero.at[seg].add(jnp.where(val, st[:, 0], 0.0))
    s = zero.at[seg].add(jnp.where(val, st[:, 1], 0.0))
    mn = jnp.full((capacity,), jnp.inf).at[seg].min(jnp.where(val, st[:, 2], jnp.inf))
    mx = jnp.full((capacity,), -jnp.inf).at[seg].max(jnp.where(val, st[:, 3], -jnp.inf))
    s2 = zero.at[seg].add(jnp.where(val, st[:, 4], 0.0))
    return uniq_keys, uniq_valid, jnp.stack([cnt, s, mn, mx, s2], axis=-1), overflow


# ---------------------------------------------------------------------------
# Distributed groupby-aggregate (partial -> shuffle-by-key -> final)
# ---------------------------------------------------------------------------
def make_dist_groupby(mesh: Optional[Mesh] = None, capacity: int = 4096):
    """Build the jitted distributed groupby-sum/min/max/count/avg kernel.

    Input arrays are row-sharded over the mesh; output partial tables are
    key-sharded (hash(key) % n_devices == device_id) — the split_out analogue.
    """
    mesh = mesh or default_mesh()
    ndev = mesh.devices.size

    def per_shard(keys, values, valid):
        # 1. local partial aggregation (the `chunk` stage)
        order, seg, uk, uv, overflow = _local_sorted_groups(keys, valid, capacity)
        states = _partial_states(values, valid, seg, order, capacity)
        states = jnp.where(uv[:, None], states, _identity_states(capacity))
        # 2. route each partial group to its owner device and combine there.
        #    all_gather over ICI: every device sees all partial tables, keeps
        #    the keys it owns (hash % ndev) — one collective, static shapes.
        all_keys = jax.lax.all_gather(uk, AXIS).reshape(-1)
        all_valid = jax.lax.all_gather(uv, AXIS).reshape(-1)
        all_states = jax.lax.all_gather(states, AXIS).reshape(-1, N_STATE)
        me = jax.lax.axis_index(AXIS)
        mine = all_valid & ((all_keys % ndev) == me)
        fk, fv, fstates, overflow2 = _combine_states(all_keys, mine, all_states, capacity)
        return fk[None], fv[None], fstates[None], (overflow | overflow2)[None]

    fn = shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(AXIS), P(AXIS), P(AXIS)),
        out_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
    )
    return jax.jit(fn)


def _identity_states(capacity: int):
    return jnp.stack([
        jnp.zeros((capacity,)), jnp.zeros((capacity,)),
        jnp.full((capacity,), jnp.inf), jnp.full((capacity,), -jnp.inf),
        jnp.zeros((capacity,)),
    ], axis=-1)


def finalize_states(keys, valid, states):
    """Host-side: sharded partial tables -> dense (keys, count, sum, min, max,
    mean, var) arrays."""
    k = np.asarray(keys).reshape(-1)
    v = np.asarray(valid).reshape(-1)
    st = np.asarray(states).reshape(-1, N_STATE)
    k, st = k[v], st[v]
    order = np.argsort(k, kind="stable")
    k, st = k[order], st[order]
    cnt, s, mn, mx, s2 = st.T
    mean = s / np.maximum(cnt, 1)
    var = np.maximum(s2 - cnt * mean * mean, 0) / np.maximum(cnt - 1, 1)
    return k, cnt, s, mn, mx, mean, var


# ---------------------------------------------------------------------------
# Hash shuffle (DISTRIBUTE BY / join partitioning)
# ---------------------------------------------------------------------------
def make_hash_shuffle(mesh: Optional[Mesh] = None, capacity_per_peer: int = 4096,
                      n_payloads: int = 1):
    """Build the jitted all_to_all hash shuffle.

    Each shard routes its rows to `hash(key) % ndev`; per-(src,dst) traffic is
    bounded by `capacity_per_peer` rows (overflow flagged).  Payload columns
    ride along as a [n, n_payloads] float64 block.

    Parity: the reference's tasks-based shuffle (`shuffle_method="tasks"`,
    dask_sql/__init__.py:16 there) — here one `all_to_all` on ICI.
    """
    mesh = mesh or default_mesh()
    ndev = mesh.devices.size
    C = capacity_per_peer

    def per_shard(keys, payload, valid):
        n = keys.shape[0]
        dest = (keys % ndev).astype(jnp.int32)
        dest = jnp.where(valid, dest, ndev)  # invalid rows route nowhere
        # stable counting sort by destination into [ndev, C] buckets
        order = jnp.argsort(dest)
        ks = keys[order]
        ps = payload[order]
        ds = dest[order]
        vs = valid[order]
        # position within destination bucket
        idx = jnp.arange(n)
        start_of_dest = jnp.searchsorted(ds, jnp.arange(ndev + 1))
        pos_in_bucket = idx - start_of_dest[jnp.clip(ds, 0, ndev)]
        overflow = jnp.any((pos_in_bucket >= C) & vs)
        slot_ok = vs & (pos_in_bucket < C)
        # non-landing rows scatter out-of-bounds so mode="drop" discards them;
        # a clipped index would nondeterministically clobber a real slot
        flat = jnp.where(slot_ok, ds * C + pos_in_bucket, ndev * C)
        bk = jnp.zeros((ndev * C,), dtype=keys.dtype).at[flat].set(
            ks, mode="drop")
        bv = jnp.zeros((ndev * C,), dtype=bool).at[flat].set(
            slot_ok, mode="drop")
        bp = jnp.zeros((ndev * C, payload.shape[1]), dtype=payload.dtype).at[flat].set(
            ps, mode="drop")
        # the collective: exchange bucket b with device b
        bk = bk.reshape(ndev, C)
        bv = bv.reshape(ndev, C)
        bp = bp.reshape(ndev, C, payload.shape[1])
        rk = jax.lax.all_to_all(bk[None], AXIS, split_axis=1, concat_axis=1)[0]
        rv = jax.lax.all_to_all(bv[None], AXIS, split_axis=1, concat_axis=1)[0]
        rp = jax.lax.all_to_all(bp[None], AXIS, split_axis=1, concat_axis=1)[0]
        return (rk.reshape(1, -1), rv.reshape(1, -1),
                rp.reshape(1, -1, payload.shape[1]), overflow[None])

    fn = shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(AXIS), P(AXIS), P(AXIS)),
        out_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
    )
    return jax.jit(fn)


# ---------------------------------------------------------------------------
# Broadcast join: replicate the small (build) side, probe locally — no
# shuffle of the big side at all (parity: reference broadcast joins,
# join.py:228 + `sql.join.broadcast` config)
# ---------------------------------------------------------------------------
def make_broadcast_join_count(mesh: Optional[Mesh] = None):
    """Jitted broadcast equijoin match-count: the probe side stays put
    (row-sharded); the build side is all_gather'ed to every device over ICI.
    Returns per-probe-row match counts, row-sharded like the probe input."""
    mesh = mesh or default_mesh()

    def per_shard(probe_keys, probe_valid, build_keys, build_valid):
        # build side arrives shard-local; replicate it
        all_bk = jax.lax.all_gather(build_keys, AXIS).reshape(-1)
        all_bv = jax.lax.all_gather(build_valid, AXIS).reshape(-1)
        big = jnp.iinfo(all_bk.dtype).max
        b_sorted = jnp.sort(jnp.where(all_bv, all_bk, big))
        start = jnp.searchsorted(b_sorted, probe_keys, side="left")
        end = jnp.searchsorted(b_sorted, probe_keys, side="right")
        counts = jnp.where(probe_valid, end - start, 0)
        return counts

    fn = shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
        out_specs=P(AXIS),
    )
    return jax.jit(fn)


# ---------------------------------------------------------------------------
# Distributed hash join: shuffle both sides, local sort/searchsorted probe
# ---------------------------------------------------------------------------
def make_dist_join_count(mesh: Optional[Mesh] = None, capacity_per_peer: int = 4096):
    """Distributed equijoin *match-count* kernel (the shuffle + probe core).

    Returns per-shard match counts — the shape-static part of the join; the
    eager layer materializes pairs per shard afterwards.  Demonstrates the
    full collectives path: 2 shuffles + local probe, all inside one jit.
    """
    mesh = mesh or default_mesh()
    ndev = mesh.devices.size
    shuffle = make_hash_shuffle(mesh, capacity_per_peer)

    def probe(lk, lv, rk, rv):
        big = jnp.iinfo(rk.dtype).max
        r_sorted = jnp.sort(jnp.where(rv, rk, big))
        n_valid_r = jnp.sum(rv.astype(jnp.int64))
        start = jnp.searchsorted(r_sorted, lk, side="left")
        end = jnp.searchsorted(r_sorted, lk, side="right")
        counts = jnp.where(lv, end - start, 0)
        return counts

    def per_shard(lk, lval, rk, rval):
        counts = probe(lk, lval, rk, rval)
        total = jnp.sum(counts)
        return counts[None], total[None]

    def run(lkeys, lvalid, rkeys, rvalid):
        one = jnp.zeros((lkeys.shape[0], 1), dtype=jnp.float64)
        slk, slv, _, of1 = shuffle(lkeys, one, lvalid)
        oner = jnp.zeros((rkeys.shape[0], 1), dtype=jnp.float64)
        srk, srv, _, of2 = shuffle(rkeys, oner, rvalid)
        fn = shard_map(
            per_shard, mesh=mesh,
            in_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
            out_specs=(P(AXIS), P(AXIS)),
        )
        counts, totals = fn(slk.reshape(-1), slv.reshape(-1),
                            srk.reshape(-1), srv.reshape(-1))
        return counts, totals, of1 | of2

    return jax.jit(run)
