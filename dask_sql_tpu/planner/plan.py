"""Logical plan nodes.

Role parity: DataFusion `LogicalPlan` as surfaced by the reference's
`PyLogicalPlan` (src/sql/logical.rs: node-type dispatch logical.rs:300-377,
typed per-node accessors logical.rs:102-253, per-node binding files
src/sql/logical/*.rs).  Every node carries its output `Schema`; the physical
layer dispatches on `node_type` through a plugin registry just like the
reference's RelConverter (physical/rel/convert.py:50-61 there).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .expressions import AggExpr, Expr, Field, Schema, SortKey, WindowExpr


class LogicalPlan:
    schema: Schema

    @property
    def node_type(self) -> str:
        return type(self).__name__

    def inputs(self) -> List["LogicalPlan"]:
        return []

    def with_inputs(self, inputs: List["LogicalPlan"]) -> "LogicalPlan":
        return self

    # -- plan display (EXPLAIN; parity logical.rs:380 explain_original) -----
    def _label(self) -> str:
        return self.node_type

    def explain(self, indent: int = 0) -> str:
        lines = ["  " * indent + self._label()]
        for child in self.inputs():
            lines.append(child.explain(indent + 1))
        return "\n".join(lines)

    @property
    def field_names(self) -> List[str]:
        return [f.name for f in self.schema]


@dataclass(eq=False)
class TableScan(LogicalPlan):
    """Parity: src/sql/logical/table_scan.rs (projections + DNF filter pushdown)."""

    schema_name: str
    table_name: str
    schema: Schema
    projection: Optional[List[str]] = None  # backend column names to read
    filters: List[Expr] = field(default_factory=list)  # conjunctive pushed-down filters

    def _label(self):
        proj = f" projection={self.projection}" if self.projection is not None else ""
        filt = f" filters={[str(f) for f in self.filters]}" if self.filters else ""
        return f"TableScan: {self.schema_name}.{self.table_name}{proj}{filt}"


@dataclass(eq=False)
class Projection(LogicalPlan):
    input: LogicalPlan
    exprs: List[Expr]
    schema: Schema

    def inputs(self):
        return [self.input]

    def with_inputs(self, inputs):
        return Projection(inputs[0], self.exprs, self.schema)

    def _label(self):
        return "Projection: " + ", ".join(
            f"{e} AS {f.name}" for e, f in zip(self.exprs, self.schema)
        )


@dataclass(eq=False)
class Filter(LogicalPlan):
    input: LogicalPlan
    predicate: Expr
    schema: Schema

    def inputs(self):
        return [self.input]

    def with_inputs(self, inputs):
        return Filter(inputs[0], self.predicate, self.schema)

    def _label(self):
        return f"Filter: {self.predicate}"


@dataclass(eq=False)
class Join(LogicalPlan):
    """Parity: src/sql/logical/join.rs (getCondition/getJoinType join.rs:26,106)."""

    left: LogicalPlan
    right: LogicalPlan
    join_type: str  # INNER, LEFT, RIGHT, FULL, LEFTSEMI, LEFTANTI
    on: List[Tuple[Expr, Expr]]  # equi-join key pairs (left expr, right expr)
    filter: Optional[Expr]  # residual non-equi condition over combined schema
    schema: Schema
    # LEFTANTI only: SQL `NOT IN` 3VL semantics (empty build side passes every
    # probe row; any NULL build key passes none; NULL probe keys never pass).
    # on[0] is the IN-arg pair, on[1:] are correlation pairs.
    null_aware: bool = False

    def inputs(self):
        return [self.left, self.right]

    def with_inputs(self, inputs):
        return Join(inputs[0], inputs[1], self.join_type, self.on, self.filter,
                    self.schema, self.null_aware)

    def _label(self):
        on = ", ".join(f"{l} = {r}" for l, r in self.on)
        resid = f" filter={self.filter}" if self.filter is not None else ""
        na = " null_aware" if self.null_aware else ""
        return f"Join({self.join_type}{na}): on [{on}]{resid}"


@dataclass(eq=False)
class CrossJoin(LogicalPlan):
    left: LogicalPlan
    right: LogicalPlan
    schema: Schema

    def inputs(self):
        return [self.left, self.right]

    def with_inputs(self, inputs):
        return CrossJoin(inputs[0], inputs[1], self.schema)


@dataclass(eq=False)
class Aggregate(LogicalPlan):
    """Parity: src/sql/logical/aggregate.rs (getGroupSets/getNamedAggCalls)."""

    input: LogicalPlan
    group_exprs: List[Expr]
    agg_exprs: List[AggExpr]
    schema: Schema  # group fields then agg fields

    def inputs(self):
        return [self.input]

    def with_inputs(self, inputs):
        return Aggregate(inputs[0], self.group_exprs, self.agg_exprs, self.schema)

    def _label(self):
        return (
            "Aggregate: groupBy=["
            + ", ".join(map(str, self.group_exprs))
            + "] aggs=["
            + ", ".join(map(str, self.agg_exprs))
            + "]"
        )


@dataclass(eq=False)
class Window(LogicalPlan):
    """Parity: src/sql/logical/window.rs (getGroups/getWindowFrame)."""

    input: LogicalPlan
    window_exprs: List[WindowExpr]
    schema: Schema  # input fields + one per window expr

    def inputs(self):
        return [self.input]

    def with_inputs(self, inputs):
        return Window(inputs[0], self.window_exprs, self.schema)


@dataclass(eq=False)
class Sort(LogicalPlan):
    """Parity: src/sql/logical/sort.rs (getCollation + getNumRows for top-k)."""

    input: LogicalPlan
    keys: List[SortKey]
    schema: Schema
    fetch: Optional[int] = None

    def inputs(self):
        return [self.input]

    def with_inputs(self, inputs):
        return Sort(inputs[0], self.keys, self.schema, self.fetch)

    def _label(self):
        ks = ", ".join(
            f"{k.expr} {'ASC' if k.ascending else 'DESC'}"
            + ("" if k.nulls_first is None
               else (" NULLS FIRST" if k.nulls_first else " NULLS LAST"))
            for k in self.keys
        )
        return f"Sort: [{ks}]" + (f" fetch={self.fetch}" if self.fetch is not None else "")


@dataclass(eq=False)
class Limit(LogicalPlan):
    """Parity: src/sql/logical/limit.rs (getSkip/getFetch)."""

    input: LogicalPlan
    skip: int
    fetch: Optional[int]
    schema: Schema

    def inputs(self):
        return [self.input]

    def with_inputs(self, inputs):
        return Limit(inputs[0], self.skip, self.fetch, self.schema)

    def _label(self):
        return f"Limit: skip={self.skip} fetch={self.fetch}"


@dataclass(eq=False)
class Union(LogicalPlan):
    children: List[LogicalPlan]
    all: bool
    schema: Schema

    def inputs(self):
        return list(self.children)

    def with_inputs(self, inputs):
        return Union(list(inputs), self.all, self.schema)


@dataclass(eq=False)
class Intersect(LogicalPlan):
    left: LogicalPlan
    right: LogicalPlan
    all: bool
    schema: Schema

    def inputs(self):
        return [self.left, self.right]

    def with_inputs(self, inputs):
        return Intersect(inputs[0], inputs[1], self.all, self.schema)


@dataclass(eq=False)
class Except(LogicalPlan):
    left: LogicalPlan
    right: LogicalPlan
    all: bool
    schema: Schema

    def inputs(self):
        return [self.left, self.right]

    def with_inputs(self, inputs):
        return Except(inputs[0], inputs[1], self.all, self.schema)


@dataclass(eq=False)
class Distinct(LogicalPlan):
    input: LogicalPlan
    schema: Schema

    def inputs(self):
        return [self.input]

    def with_inputs(self, inputs):
        return Distinct(inputs[0], self.schema)


@dataclass(eq=False)
class Values(LogicalPlan):
    rows: List[List[Expr]]  # literal expressions
    schema: Schema


@dataclass(eq=False)
class EmptyRelation(LogicalPlan):
    schema: Schema
    produce_one_row: bool = False


@dataclass(eq=False)
class SubqueryAlias(LogicalPlan):
    input: LogicalPlan
    alias: str
    schema: Schema

    def inputs(self):
        return [self.input]

    def with_inputs(self, inputs):
        return SubqueryAlias(inputs[0], self.alias, self.schema)

    def _label(self):
        return f"SubqueryAlias: {self.alias}"


@dataclass(eq=False)
class Sample(LogicalPlan):
    input: LogicalPlan
    method: str  # SYSTEM | BERNOULLI
    fraction: float  # percentage 0-100
    seed: Optional[int]
    schema: Schema

    def inputs(self):
        return [self.input]

    def with_inputs(self, inputs):
        return Sample(inputs[0], self.method, self.fraction, self.seed, self.schema)


@dataclass(eq=False)
class DistributeBy(LogicalPlan):
    """Parity: physical/rel/custom/distributeby.py — explicit re-shard."""

    input: LogicalPlan
    keys: List[Expr]
    schema: Schema

    def inputs(self):
        return [self.input]

    def with_inputs(self, inputs):
        return DistributeBy(inputs[0], self.keys, self.schema)


@dataclass(eq=False)
class Explain(LogicalPlan):
    input: LogicalPlan
    schema: Schema
    analyze: bool = False
    lint: bool = False  # EXPLAIN LINT: static verifier findings as rows
    estimate: bool = False  # EXPLAIN ESTIMATE: static cost/memory intervals
    fmt_json: bool = False  # FORMAT JSON: Chrome-trace JSON (with ANALYZE)

    def inputs(self):
        return [self.input]

    def with_inputs(self, inputs):
        return Explain(inputs[0], self.schema, self.analyze, self.lint,
                       self.estimate, self.fmt_json)


# ---------------------------------------------------------------------------
# Custom nodes: DDL / ML / introspection (parity: Extension nodes, sql.rs:668-814)
# ---------------------------------------------------------------------------
@dataclass(eq=False)
class CustomNode(LogicalPlan):
    """Base for statement nodes handled by `physical/rel/custom` plugins."""

    schema: Schema = field(default_factory=list)


@dataclass(eq=False)
class CreateTableNode(CustomNode):
    name: List[str] = None
    kwargs: Dict[str, Any] = None
    if_not_exists: bool = False
    or_replace: bool = False


@dataclass(eq=False)
class CreateMemoryTableNode(CustomNode):
    name: List[str] = None
    input: LogicalPlan = None
    persist: bool = True  # TABLE persists, VIEW stays lazy
    if_not_exists: bool = False
    or_replace: bool = False

    def inputs(self):
        return [self.input]

    def with_inputs(self, inputs):
        return CreateMemoryTableNode([], self.name, inputs[0], self.persist,
                                     self.if_not_exists, self.or_replace)


@dataclass(eq=False)
class DropTableNode(CustomNode):
    name: List[str] = None
    if_exists: bool = False


@dataclass(eq=False)
class CreateSchemaNode(CustomNode):
    schema_name: str = ""
    if_not_exists: bool = False
    or_replace: bool = False


@dataclass(eq=False)
class DropSchemaNode(CustomNode):
    schema_name: str = ""
    if_exists: bool = False


@dataclass(eq=False)
class UseSchemaNode(CustomNode):
    schema_name: str = ""


@dataclass(eq=False)
class AlterSchemaNode(CustomNode):
    old_name: str = ""
    new_name: str = ""


@dataclass(eq=False)
class AlterTableNode(CustomNode):
    old_name: List[str] = None
    new_name: str = ""
    if_exists: bool = False


@dataclass(eq=False)
class ShowSchemasNode(CustomNode):
    like: Optional[str] = None


@dataclass(eq=False)
class ShowTablesNode(CustomNode):
    schema_name: Optional[str] = None


@dataclass(eq=False)
class ShowColumnsNode(CustomNode):
    table: List[str] = None


@dataclass(eq=False)
class ShowModelsNode(CustomNode):
    schema_name: Optional[str] = None


@dataclass(eq=False)
class ShowMetricsNode(CustomNode):
    """SHOW METRICS — serving runtime observability (serving/metrics.py)."""

    like: Optional[str] = None


@dataclass(eq=False)
class ShowProfilesNode(CustomNode):
    """SHOW PROFILES — per-fingerprint query profiles
    (observability/profiles.py)."""

    like: Optional[str] = None


@dataclass(eq=False)
class ShowQueriesNode(CustomNode):
    """SHOW QUERIES — the in-flight query table + HBM-ledger summary
    (observability/live.py, observability/ledger.py)."""

    like: Optional[str] = None


@dataclass(eq=False)
class ShowMaterializedNode(CustomNode):
    """SHOW MATERIALIZED — the semantic-reuse state (materialize/):
    pinned sub-plan stems and incremental aggregate states."""

    like: Optional[str] = None


@dataclass(eq=False)
class ShowReplicasNode(CustomNode):
    """SHOW REPLICAS — the fleet router's member table (fleet/router.py):
    state, pressure band, headroom, routed tally per replica."""

    like: Optional[str] = None


@dataclass(eq=False)
class InsertIntoNode(CustomNode):
    """INSERT INTO — the append path (Context.append_rows): delta-epoch
    bump + incremental maintenance instead of wholesale invalidation."""

    name: List[str] = None
    input: LogicalPlan = None

    def inputs(self):
        return [self.input]

    def with_inputs(self, inputs):
        return InsertIntoNode(self.schema, self.name, inputs[0])


@dataclass(eq=False)
class CancelQueryNode(CustomNode):
    """CANCEL QUERY '<qid>' — cooperative in-flight cancellation
    (observability/live.py -> QueryTicket)."""

    qid: str = ""


@dataclass(eq=False)
class AnalyzeTableNode(CustomNode):
    table: List[str] = None
    columns: List[str] = None


@dataclass(eq=False)
class CreateModelNode(CustomNode):
    name: List[str] = None
    kwargs: Dict[str, Any] = None
    input: LogicalPlan = None
    if_not_exists: bool = False
    or_replace: bool = False

    def inputs(self):
        return [self.input] if self.input is not None else []


@dataclass(eq=False)
class DropModelNode(CustomNode):
    name: List[str] = None
    if_exists: bool = False


@dataclass(eq=False)
class DescribeModelNode(CustomNode):
    name: List[str] = None


@dataclass(eq=False)
class ExportModelNode(CustomNode):
    name: List[str] = None
    kwargs: Dict[str, Any] = None


@dataclass(eq=False)
class CreateExperimentNode(CustomNode):
    name: List[str] = None
    kwargs: Dict[str, Any] = None
    input: LogicalPlan = None
    if_not_exists: bool = False
    or_replace: bool = False

    def inputs(self):
        return [self.input] if self.input is not None else []


@dataclass(eq=False)
class PredictModelNode(CustomNode):
    model_name: List[str] = None
    input: LogicalPlan = None

    def inputs(self):
        return [self.input]

    def with_inputs(self, inputs):
        return PredictModelNode(self.schema, self.model_name, inputs[0])


# ---------------------------------------------------------------------------
# Traversal
# ---------------------------------------------------------------------------
def transform_plan(plan: LogicalPlan, fn) -> LogicalPlan:
    """Bottom-up plan rewrite."""
    kids = [transform_plan(c, fn) for c in plan.inputs()]
    return fn(plan.with_inputs(kids))


def walk_plan(plan: LogicalPlan):
    yield plan
    for c in plan.inputs():
        yield from walk_plan(c)
