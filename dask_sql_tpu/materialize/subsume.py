"""Subsumption answering: serve a tighter query by re-filtering a cached
result — when containment is PROVABLE, never heuristically.

A family (families/parameterize.py) fixes everything about a query except
its parameter values, so when ``price < 100`` has a cached result and
``price < 50`` arrives, the only semantic difference between the two is
one interval endpoint.  If every parameter slot's new predicate provably
selects a subset of the cached predicate's rows (the interval algebra in
analysis/estimator.py: `param_slot_contains`), the answer is the cached
result re-filtered by the new predicates — no scan, no compile, no
executor walk.

The analysis half (`analyze`) runs once per distinct SQL text (memoized on
the cached plan object): it re-derives the literal-stripped family plan —
the Parameterizer's traversal is deterministic, so slot numbering matches
`FamilyInfo.key_values` exactly — and classifies each parameter slot:

- ``cmp``: the slot is the comparison value of a top-level AND conjunct
  ``column OP ?slot`` (OP in lt/le/gt/ge/eq) over a NON-nullable column
  whose value survives to the result (bare-ColumnRef projection lineage).
  Serving re-applies the conjunct with the NEW value on the result column;
  admissibility is interval containment of new-vs-cached values.
- ``exact``: any other slot position (nullable column, no lineage into the
  result, non-comparator conjunct).  Admissible only when the cached and
  new values are equal — the two predicates are then literally identical,
  so no re-filtering is needed for that slot.

Anything outside the supported plan shape — TableScan / Filter chains /
one bare-ColumnRef Projection / SubqueryAlias wrappers — declines
entirely: aggregates and sorts are not row-subset-stable, joins and
limits change row multiplicity.  NULL-able filter columns and float
boundary equality decline inside the algebra (three-valued logic and
device-cast boundary semantics are not provable from host values).
"""
from __future__ import annotations

import dataclasses
import logging
from typing import List, Optional, Tuple

import numpy as np

from ..analysis.estimator import COMPARATOR_OPS, MIRRORED_OPS, \
    param_slot_contains
from ..columnar.dtypes import sql_to_np
from ..columnar.encodings import Encoding
from ..columnar.table import Table
from ..families.parameterize import Parameterizer
from ..planner import plan as p
from ..planner.expressions import ColumnRef, ParamRef, ScalarFunc, walk

logger = logging.getLogger(__name__)

#: memoization attribute on the plan object (plans are cached per SQL
#: text, so the family-level analysis is stable for the plan's lifetime)
_ATTR = "_dsql_subsume_spec"

#: sentinel distinguishing "analyzed: ineligible" from "not yet analyzed"
_INELIGIBLE = "ineligible"


@dataclasses.dataclass(frozen=True)
class SlotSpec:
    """One parameter slot's serving classification (see module doc)."""

    index: int
    kind: str                  # "cmp" | "exact"
    op: str = ""               # comparator, column-on-the-left (cmp only)
    result_pos: int = -1       # column position in the result table (cmp)
    float_domain: bool = False
    np_dtype: str = ""         # parameter device dtype (cmp only)


@dataclasses.dataclass(frozen=True)
class SubsumeSpec:
    slots: Tuple[SlotSpec, ...]


def _conjuncts(expr) -> List:
    """Flatten a top-level AND tree into its conjunct list."""
    if isinstance(expr, ScalarFunc) and expr.op == "and":
        out: List = []
        for a in expr.args:
            out.extend(_conjuncts(a))
        return out
    return [expr]


def _param_indices(expr) -> List[int]:
    return [e.index for e in walk(expr) if isinstance(e, ParamRef)]


def _comparator_slot(conj) -> Optional[Tuple[str, ColumnRef, ParamRef]]:
    """``(op, column, param)`` normalized column-on-the-left, or None when
    the conjunct is not a plain single-param comparison."""
    if not (isinstance(conj, ScalarFunc) and conj.op in COMPARATOR_OPS
            and len(conj.args) == 2):
        return None
    a, b = conj.args
    if type(a) is ColumnRef and isinstance(b, ParamRef):
        return conj.op, a, b
    if isinstance(a, ParamRef) and type(b) is ColumnRef:
        return MIRRORED_OPS[conj.op], b, a
    return None


def analyze(plan: p.LogicalPlan, family) -> Optional[SubsumeSpec]:
    """The plan's subsumption spec, or None when ineligible.  Memoized on
    the plan object; `family` is its `FamilyInfo` (slot count oracle)."""
    spec = getattr(plan, _ATTR, None)
    if spec is not None:
        return None if spec is _INELIGIBLE else spec
    spec = _analyze(plan, family)
    try:
        setattr(plan, _ATTR, spec if spec is not None else _INELIGIBLE)
    except AttributeError:  # exotic node without a writable __dict__
        pass
    return spec


def _analyze(plan: p.LogicalPlan, family) -> Optional[SubsumeSpec]:
    if family is None or family.n_params == 0:
        return None
    # deterministic re-parameterization: slot numbering matches
    # family.key_values (same traversal that produced the fingerprint)
    pz = Parameterizer(enabled=True, recurse_subplans=True)
    fam_plan = pz.rewrite_plan(plan)
    if len(pz.values) != family.n_params:
        return None

    # ---- supported shape: Alias* -> [Projection] -> Filter* -> TableScan
    node = fam_plan
    while isinstance(node, p.SubqueryAlias):
        node = node.input
    proj: Optional[p.Projection] = None
    if isinstance(node, p.Projection):
        if not all(type(e) is ColumnRef for e in node.exprs):
            return None  # computed outputs: no row-value lineage
        proj = node
        node = node.input
    filters: List = []
    while isinstance(node, p.Filter):
        filters.append(node.predicate)
        node = node.input
    if not isinstance(node, p.TableScan):
        return None  # aggregates / sorts / joins are not subset-stable
    scan = node
    filters.extend(scan.filters)

    # ---- classify every parameter slot ---------------------------------
    slots: List[SlotSpec] = []
    seen: set = set()
    for conj in [c for f in filters for c in _conjuncts(f)]:
        idxs = _param_indices(conj)
        if not idxs:
            continue  # literal-free conjunct: identical across the family
        cmp = _comparator_slot(conj) if len(idxs) == 1 else None
        if cmp is None:
            for i in idxs:
                slots.append(SlotSpec(i, "exact"))
                seen.add(i)
            continue
        op, col, param = cmp
        field = scan.schema[col.index]
        pos = col.index
        if proj is not None:
            pos = next((j for j, e in enumerate(proj.exprs)
                        if e.index == col.index), -1)
        col_dtype = sql_to_np(field.sql_type)
        par_dtype = sql_to_np(param.sql_type)
        if field.nullable or col.nullable or pos < 0:
            # NULL-able column (three-valued logic is not re-provable from
            # the result rows alone) or the filter column was projected
            # away: the slot degrades to exact-value matching
            slots.append(SlotSpec(param.index, "exact"))
        else:
            slots.append(SlotSpec(
                param.index, "cmp", op=op, result_pos=pos,
                float_domain=(col_dtype.kind == "f"
                              or par_dtype.kind == "f"),
                np_dtype=str(par_dtype)))
        seen.add(param.index)
    if seen != set(range(family.n_params)):
        # a slot lives outside the filter conjuncts (nested subquery plan,
        # unsupported position): no provable claim about it — decline
        return None
    return SubsumeSpec(tuple(sorted(slots, key=lambda s: s.index)))


def contains(spec: SubsumeSpec, cached_values: Tuple, new_values: Tuple
             ) -> bool:
    """PROVABLE verdict: does the cached execution's parameter vector cover
    the new one?  Per-slot interval containment for ``cmp`` slots, exact
    equality for ``exact`` slots; any doubt is False."""
    if len(cached_values) != len(spec.slots) \
            or len(new_values) != len(spec.slots):
        return False
    for slot in spec.slots:
        cv, nv = cached_values[slot.index], new_values[slot.index]
        if slot.kind == "exact":
            if not (type(cv) is type(nv) and cv == nv):
                return False
        elif not param_slot_contains(slot.op, cv, nv,
                                     float_domain=slot.float_domain):
            return False
    return True


_OP_FNS = {
    "lt": lambda d, v: d < v,
    "le": lambda d, v: d <= v,
    "gt": lambda d, v: d > v,
    "ge": lambda d, v: d >= v,
    "eq": lambda d, v: d == v,
}


def serve(result: Table, spec: SubsumeSpec, new_values: Tuple
          ) -> Optional[Table]:
    """Re-filter the cached result with the new parameter values — the
    subsumption answer.  Returns None when the result's physical layout
    breaks the proof (encoded or masked columns: the comparison domain
    would differ from the cold path's decoded values)."""
    if result.row_valid is not None:
        return None
    cols = list(result.columns.values())
    mask = None
    for slot in spec.slots:
        if slot.kind != "cmp":
            continue
        if slot.result_pos >= len(cols):
            return None
        col = cols[slot.result_pos]
        if col.encoding is not Encoding.PLAIN or col.validity is not None \
                or col.dictionary is not None:
            return None
        value = np.asarray(new_values[slot.index],
                           dtype=np.dtype(slot.np_dtype))
        m = _OP_FNS[slot.op](col.data, value)
        mask = m if mask is None else (mask & m)
    if mask is None:
        # every slot was exact-matched: the queries are identical
        return result
    return result.filter(mask)
