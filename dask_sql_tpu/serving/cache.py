"""Result cache: LRU-by-bytes of materialized result Tables.

The plan cache (`Context._plan_cache`) removes re-parse/re-bind/re-optimize
cost; repeated identical queries still re-execute the kernels.  For serving
traffic (dashboards, retried requests) the result itself is the hot object,
so this cache keys the *materialized* Table on (normalized plan fingerprint,
catalog signature, config options) — the same catalog-versioning scheme the
plan cache uses (table uids + `_catalog_serial` + statistics), so any
DDL/DML that replaces or drops a referenced table changes the key and the
stale entry simply can never be hit again (LRU pressure reclaims it).

Byte accounting is explicit: eviction is by total resident bytes (a result
Table pins HBM/host buffers, entry *count* is meaningless), a per-entry cap
keeps one huge result from evicting the whole working set, and a TTL bounds
staleness of anything keyed on out-of-band state.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, FrozenSet, Hashable, Iterable, Optional, Tuple


def table_nbytes(table) -> int:
    """Estimated resident bytes of a columnar Table (device buffers +
    validity masks + host dictionaries + compressed-encoding metadata).
    Per-column accounting delegates to `encodings.encoded_nbytes` — the
    one rule the estimator's scan bounds also use, so measured-vs-estimate
    byte comparisons can never drift."""
    from ..columnar.encodings import encoded_nbytes

    total = sum(encoded_nbytes(col) for col in table.columns.values())
    if table.row_valid is not None:
        total += int(table.row_valid.nbytes)
    return total


@dataclass
class _Entry:
    value: Any
    nbytes: int
    created: float
    hits: int = 0
    #: (schema, table) pairs this result was computed from — the epoch-scoped
    #: invalidation scope: an append/replace of one table drops exactly the
    #: entries depending on it (`invalidate_tables`), never the whole cache
    deps: FrozenSet[Tuple[str, str]] = frozenset()


@dataclass
class CacheStats:
    hits: int = 0
    misses: int = 0
    inserts: int = 0
    evictions: int = 0
    expirations: int = 0
    oversize_rejects: int = 0
    bytes: int = 0
    entries: int = 0

    def as_dict(self) -> Dict[str, int]:
        return dict(self.__dict__)


class ResultCache:
    """LRU-by-bytes cache with TTL and a per-entry byte cap.

    Thread-safe; values are immutable columnar Tables (frozen dataclass
    Columns over jax arrays), so sharing one instance across queries and
    server worker threads is safe.
    """

    def __init__(self, max_bytes: int = 256 << 20,
                 max_entry_bytes: int = 64 << 20,
                 ttl_s: Optional[float] = 300.0,
                 metrics=None,
                 clock=time.monotonic):
        self.max_bytes = int(max_bytes)
        self.max_entry_bytes = int(max_entry_bytes)
        self.ttl_s = ttl_s
        self.metrics = metrics
        self._clock = clock
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Hashable, _Entry]" = OrderedDict()
        self.stats = CacheStats()

    # ------------------------------------------------------------------ ops
    def get(self, key: Hashable) -> Optional[Any]:
        now = self._clock()
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and self.ttl_s is not None \
                    and now - entry.created > self.ttl_s:
                self._drop_locked(key, entry)
                self.stats.expirations += 1
                entry = None
            if entry is None:
                self.stats.misses += 1
                self._mark("query.cache.miss")
                return None
            entry.hits += 1
            self.stats.hits += 1
            self._entries.move_to_end(key)
            self._mark("query.cache.hit")
            return entry.value

    def put(self, key: Hashable, value: Any,
            nbytes: Optional[int] = None,
            deps: Optional[Iterable[Tuple[str, str]]] = None) -> bool:
        """Insert (or refresh) an entry; returns False when the value is
        over the per-entry cap and was not cached.  ``deps`` is the set of
        (schema, table) names the result was computed from — the scope
        `invalidate_tables` drops on a targeted DML/DDL invalidation."""
        if nbytes is None:
            nbytes = table_nbytes(value)
        nbytes = int(nbytes)
        if nbytes > self.max_entry_bytes or nbytes > self.max_bytes:
            with self._lock:
                self.stats.oversize_rejects += 1
            self._mark("query.cache.oversize")
            return False
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self.stats.bytes -= old.nbytes
                self.stats.entries -= 1
            self._entries[key] = _Entry(value, nbytes, self._clock(),
                                        deps=frozenset(deps or ()))
            self.stats.bytes += nbytes
            self.stats.entries += 1
            self.stats.inserts += 1
            while self.stats.bytes > self.max_bytes and len(self._entries) > 1:
                k, e = next(iter(self._entries.items()))
                self._drop_locked(k, e)
                self.stats.evictions += 1
                self._mark("query.cache.evicted")
        return True

    def reclaim_bytes(self, bytes_needed: Optional[int] = None) -> int:
        """Pressure reclaim (resilience/pressure.py tier 1): evict
        LRU-coldest entries until at least ``bytes_needed`` are freed
        (``None`` = drain everything); returns bytes actually freed.
        Every result here is re-computable, so under HBM pressure cold
        cache is the cheapest memory on the device."""
        freed = 0
        with self._lock:
            while self._entries and (bytes_needed is None
                                     or freed < bytes_needed):
                key, entry = next(iter(self._entries.items()))
                self._drop_locked(key, entry)
                self.stats.evictions += 1
                freed += entry.nbytes
                self._mark("query.cache.evicted")
        return freed

    def invalidate_all(self) -> int:
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self.stats.bytes = 0
            self.stats.entries = 0
        return n

    def invalidate_tables(self, tables: Iterable[Tuple[str, str]]) -> int:
        """Drop exactly the entries whose deps intersect ``tables`` —
        the epoch-scoped invalidation an append/replace of one table
        triggers.  Entries inserted without deps (legacy callers, direct
        test puts) are dropped too: an unknown provenance must never
        survive a catalog change it might depend on."""
        targets = set(tables)
        if not targets:
            return 0
        with self._lock:
            doomed = [(k, e) for k, e in self._entries.items()
                      if not e.deps or (e.deps & targets)]
            for k, e in doomed:
                self._drop_locked(k, e)
        return len(doomed)

    # ------------------------------------------------------------- helpers
    def _drop_locked(self, key, entry) -> None:
        # caller holds the lock (self-lint DSQL201 *_locked convention)
        self._entries.pop(key, None)
        self.stats.bytes -= entry.nbytes
        self.stats.entries -= 1

    def _mark(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.inc(name)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            out = self.stats.as_dict()
        total = out["hits"] + out["misses"]
        out["hitRate"] = round(out["hits"] / total, 4) if total else 0.0
        out["maxBytes"] = self.max_bytes
        out["maxEntryBytes"] = self.max_entry_bytes
        out["ttlSeconds"] = self.ttl_s
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)
