"""Fault-tolerant replica fleet: router, replicas, warm-standby promotion.

Layer map (docs/fleet.md has the full protocol write-up):

- `fleet.replica.Replica`   — one Context + ServingRuntime with a
  standby/ready/draining/dead lifecycle and epoch-fenced write apply;
- `fleet.router.Router`     — health-gated cost-aware routing, mid-query
  failover with idempotent re-dispatch, write fan-out, standby
  promotion, graceful drain;
- `fleet.replication.StandbyReplicator` — checkpoint snapshots + the
  persistent compile cache + the profile store as the replication
  transport (the PR 6 cold-start machinery, reused).

`build_fleet` wires the common test/chaos topology: N replicas over
identically-built contexts plus an optional warm standby.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Tuple

from .replica import DEAD, DRAINING, READY, STANDBY, Replica
from .replication import StandbyReplicator
from .router import Router

__all__ = [
    "Replica", "Router", "StandbyReplicator", "build_fleet",
    "STANDBY", "READY", "DRAINING", "DEAD",
]


def build_fleet(context_factory: Callable[[], object], replicas: int = 3,
                standby: bool = False,
                sync_dir: Optional[str] = None,
                ) -> Tuple[Router, List[Replica],
                           Optional[StandbyReplicator]]:
    """Build an in-process fleet: ``replicas`` serving members (named
    ``replica-0..N-1``) over contexts minted by ``context_factory``, plus
    an optional warm standby wired to a `StandbyReplicator` fed by
    ``replica-0``.  Returns ``(router, members, replicator)``."""
    members = [Replica(f"replica-{i}", context_factory())
               for i in range(max(1, int(replicas)))]
    spare = Replica("standby", context_factory(), standby=True) \
        if standby else None
    router = Router(members, standby=spare)
    replicator = None
    if spare is not None:
        replicator = StandbyReplicator(members[0], spare,
                                       directory=sync_dir,
                                       metrics=router.metrics)
    return router, members, replicator
