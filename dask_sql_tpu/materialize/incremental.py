"""Incremental maintenance: streamed combine states as view states.

The streamed aggregate rung (streaming/aggregate.py) already produces
checkpointable partial-combine states whose time-axis algebra is exactly
incremental view maintenance: an appended chunk of rows is one more
partition to fold.  This module stores those states per (aggregate family,
parameter values) and keeps them current across `Context.append_rows` /
``INSERT INTO``:

- **register** (query time, free): when an eligible aggregate query
  executes, remember its plan + family.  No state is computed here — a
  state build costs a full-table pass, and tables that never see appends
  never need one.
- **capture + fold** (append time): on the FIRST append to a table with
  registered aggregates, build the `StreamedAggregate` state over the
  pre-append rows (the one unavoidable bootstrap scan), then fold the
  appended chunk through it as one `run_partition` over the delta slice.
  Every later append folds ONLY its delta — history is never rescanned.
- **answer** (query time): a re-query of the family with the same
  parameter values finalizes the stored state — one host pull, zero scans,
  zero compiles — provided the state is current (same table uid, same
  delta epoch, rows covered == table rows).

Eligibility is conservative and validated at every fold; a violated
invariant drops the state (``serving.reuse.incremental.declined``), never
serves a wrong answer:

- plan root is the Aggregate (optionally under a bare-ColumnRef Projection
  / SubqueryAlias) whose scan->filter*->aggregate chain covers the whole
  plan;
- every projected input column is PLAIN-encoded, non-string, and keeps its
  dtype across the append (`concat_columns` promotes dtypes and remaps
  string dictionaries — either would silently shift the frozen trace's
  comparison/code domain);
- integer group-key values in the delta stay inside the construction-time
  radix bounds ``[offset, offset + radix - 2]`` — outside values would be
  silently clamped into the wrong group by the kernel's code clip.
"""
from __future__ import annotations

import dataclasses
import logging
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..columnar.encodings import Encoding
from ..columnar.table import Table
from ..planner import plan as p
from ..planner.expressions import ColumnRef

logger = logging.getLogger(__name__)

#: registered families per (schema, table) — a bounded working set; LRU
#: beyond this would evict dashboards' own aggregates, so keep it small
#: and per-table
_MAX_PER_TABLE = 16


@dataclasses.dataclass
class _Registration:
    """One observed aggregate family over one table (no state yet)."""

    plan: p.LogicalPlan          # the literal-baked cached plan object
    family_fp: str
    key_values: Tuple
    schema_name: str
    table_name: str


@dataclasses.dataclass
class _State:
    """One live incremental view state."""

    reg: _Registration
    compiled: object             # StreamedAggregate (frozen radix plan)
    params: Tuple
    acc: List                    # running combined states (device arrays)
    proj_names: Tuple[str, ...]  # projected input column order
    col_dtypes: Tuple[str, ...]  # construction dtypes, append-validated
    group_names: Tuple[str, ...]
    uid: int                     # DataContainer identity the state tracks
    rows_covered: int
    epoch: int
    hits: int = 0


def _chain_of(plan: p.LogicalPlan):
    """(aggregate node, projection-or-None) when the plan is a whole-plan
    scan->filter*->aggregate chain, else None."""
    node = plan
    while isinstance(node, p.SubqueryAlias):
        node = node.input
    proj = None
    if isinstance(node, p.Projection):
        if not all(type(e) is ColumnRef for e in node.exprs):
            return None
        names = [f.name for f in node.schema]
        if len(set(names)) != len(names):
            return None  # duplicate output names: manual apply is ambiguous
        proj = node
        node = node.input
    if not isinstance(node, p.Aggregate):
        return None
    return node, proj


class IncrementalStates:
    """The per-Context incremental view-state store."""

    def __init__(self, context):
        self.context = context
        self._lock = threading.RLock()
        #: (schema, table) -> key -> _Registration | _State, insertion-LRU
        self._tables: Dict[Tuple[str, str],
                           "OrderedDict[Tuple, object]"] = {}

    def enabled(self) -> bool:
        return bool(self.context.config.get("serving.reuse.incremental",
                                            True))

    # ------------------------------------------------------------ register
    def register(self, plan: p.LogicalPlan, family) -> bool:
        """Query-time observation: remember this aggregate family so the
        next append can capture its state.  Cheap — shape checks only."""
        if not self.enabled() or family is None:
            return False
        got = _chain_of(plan)
        if got is None:
            return False
        agg, _ = got
        from ..physical.compiled import _extract_chain

        chain = _extract_chain(agg)
        if chain is None:
            return False
        scan = chain[0]
        ctx = self.context
        container = ctx.schema.get(scan.schema_name)
        dc = container.tables.get(scan.table_name) if container else None
        if dc is None:
            return False
        from ..datacontainer import LazyParquetContainer

        if isinstance(dc, LazyParquetContainer):
            return False
        key = (family.fingerprint, family.key_values)
        tkey = (scan.schema_name, scan.table_name)
        with self._lock:
            slot = self._tables.setdefault(tkey, OrderedDict())
            if key in slot:
                slot.move_to_end(key)
                return True
            slot[key] = _Registration(plan, family.fingerprint,
                                      family.key_values, *tkey)
            while len(slot) > _MAX_PER_TABLE:
                slot.popitem(last=False)
        return True

    # ------------------------------------------------------------- capture
    def _capture(self, reg: _Registration, dc, rows: int,
                 epoch: int) -> Optional[_State]:
        """Build the bootstrap state over the CURRENT first ``rows`` rows —
        the one full pass that turns a registration into a live view state.
        Called at append time with the pre-append row count."""
        from .. import families
        from ..physical.compiled import _Unsupported, _extract_chain
        from ..streaming.aggregate import StreamedAggregate
        from ..streaming.partition import slice_chunk

        got = _chain_of(reg.plan)
        if got is None:
            return None
        agg, _ = got
        chain = _extract_chain(agg)
        if chain is None:
            return None
        scan, filters, group_exprs, agg_exprs = chain
        table = dc.table
        if table.row_valid is not None:
            return None
        if scan.projection is not None:
            table = table.select([c for c in scan.projection
                                  if c in table.columns])
        names = tuple(table.column_names)
        for n in names:
            col = table.columns[n]
            if col.encoding is not Encoding.PLAIN \
                    or col.dictionary is not None:
                # encoded codes / string dictionaries are frozen into the
                # trace; an append remaps both (concat.py) — not foldable
                return None
        if not all(isinstance(e, ColumnRef) and type(e) is ColumnRef
                   for e in group_exprs):
            return None
        group_names = tuple(names[e.index] for e in group_exprs)
        pz = families.pipeline_parameterizer(self.context.config)
        filters = [pz.rewrite(f) for f in filters]
        agg_exprs = [pz.rewrite_agg(a) for a in agg_exprs]
        try:
            compiled = StreamedAggregate(agg, table, scan, filters,
                                         group_exprs, agg_exprs)
        except (_Unsupported, ValueError, TypeError, NotImplementedError):
            return None
        compiled.table = None  # never pin the construction table's HBM
        if rows <= 0:
            acc = None
        else:
            chunk = slice_chunk(table.slice(0, rows), 0, rows)
            acc = compiled.combine(None,
                                   compiled.run_partition(chunk, pz.params))
        return _State(
            reg=reg, compiled=compiled, params=pz.params, acc=acc or [],
            proj_names=names,
            col_dtypes=tuple(str(table.columns[n].data.dtype)
                             for n in names),
            group_names=group_names, uid=dc.uid, rows_covered=rows,
            epoch=epoch)

    def _delta_in_bounds(self, state: _State, delta: Table) -> bool:
        """Host-validate the delta's group-key values against the frozen
        radix plan: a value outside ``[offset, offset + radix - 2]`` would
        be silently clamped into a neighboring group by the kernel's code
        clip — the one corruption the static checks cannot rule out."""
        compiled = state.compiled
        for name, radix, offset, meta in zip(
                state.group_names, compiled.radices, compiled.offsets,
                compiled.gcols):
            col = delta.columns.get(name)
            if col is None:
                return False
            kind = np.dtype(meta.data.dtype).kind
            if kind == "b":
                continue  # bool radix 3 covers {0, 1} by construction
            vals = np.asarray(col.data)
            if col.validity is not None:
                vals = vals[np.asarray(col.validity)]
            if not len(vals):
                continue
            lo, hi = int(vals.min()), int(vals.max())
            if lo < int(offset) or hi > int(offset) + int(radix) - 2:
                return False
        return True

    # ---------------------------------------------------------------- fold
    def on_append(self, schema_name: str, table_name: str, dc,
                  old_rows: int, epoch: int) -> Tuple[int, int]:
        """Append notification: capture missing states (bootstrap over the
        pre-append rows) and fold the delta partition through every state
        for this table.  Returns (folded, dropped) counts."""
        from ..streaming.partition import slice_chunk

        tkey = (schema_name, table_name)
        metrics = self.context.metrics
        folded = dropped = 0
        with self._lock:
            slot = self._tables.get(tkey)
            if not slot or not self.enabled():
                return 0, 0
            new_table = dc.table
            new_rows = int(new_table.num_rows)
            delta_rows = new_rows - old_rows
            for key in list(slot):
                entry = slot[key]
                if isinstance(entry, _Registration):
                    state = self._capture(entry, dc, old_rows, epoch - 1)
                    if state is None:
                        del slot[key]
                        dropped += 1
                        metrics.inc("serving.reuse.incremental.declined")
                        continue
                    slot[key] = entry = state
                state = entry
                ok = (state.uid == dc.uid
                      and state.rows_covered == old_rows
                      and delta_rows > 0)
                if ok:
                    proj = new_table
                    if set(state.proj_names) <= set(new_table.column_names):
                        proj = new_table.select(list(state.proj_names))
                    else:
                        ok = False
                if ok:
                    ok = tuple(str(proj.columns[n].data.dtype)
                               for n in state.proj_names) \
                        == state.col_dtypes \
                        and all(proj.columns[n].encoding is Encoding.PLAIN
                                and proj.columns[n].dictionary is None
                                for n in state.proj_names)
                if ok:
                    delta = slice_chunk(proj, old_rows, delta_rows)
                    ok = self._delta_in_bounds(state, delta)
                if not ok:
                    del slot[key]
                    dropped += 1
                    metrics.inc("serving.reuse.incremental.declined")
                    continue
                try:
                    states = state.compiled.run_partition(delta,
                                                          state.params)
                    state.acc = state.compiled.combine(
                        state.acc or None, states)
                except Exception:  # dsql: allow-broad-except — advisory
                    # reuse state: a fold failure falls back to full
                    # recomputation at the next query, never a wrong answer
                    logger.debug("incremental fold failed; dropping state",
                                 exc_info=True)
                    del slot[key]
                    dropped += 1
                    metrics.inc("serving.reuse.incremental.declined")
                    continue
                state.rows_covered = new_rows
                state.epoch = epoch
                folded += 1
                metrics.inc("serving.reuse.incremental.folds")
        return folded, dropped

    # -------------------------------------------------------------- answer
    def answer(self, plan: p.LogicalPlan, family) -> Optional[Table]:
        """Serve a query from its stored state: finalize (one host pull),
        then apply the plan's bare-ColumnRef root projection manually.
        None unless a CURRENT state exists for the exact family + values."""
        if not self.enabled() or family is None:
            return None
        got = _chain_of(plan)
        if got is None:
            return None
        agg, proj = got
        key = (family.fingerprint, family.key_values)
        ctx = self.context
        with self._lock:
            state = None
            for slot in self._tables.values():
                entry = slot.get(key)
                if isinstance(entry, _State):
                    state = entry
                    break
            if state is None:
                return None
            sname, tname = state.reg.schema_name, state.reg.table_name
            container = ctx.schema.get(sname)
            dc = container.tables.get(tname) if container else None
            if dc is None or dc.uid != state.uid \
                    or state.epoch != ctx.table_epoch(sname, tname) \
                    or state.rows_covered != int(dc.table.num_rows) \
                    or not state.acc:
                return None
            try:
                out = state.compiled.finalize(list(state.acc))
            except Exception:  # dsql: allow-broad-except — advisory reuse:
                # a finalize failure must fall back to normal execution
                logger.debug("incremental finalize failed", exc_info=True)
                return None
            state.hits += 1
        if proj is not None:
            cols = list(out.columns.values())
            if any(e.index >= len(cols) for e in proj.exprs):
                return None
            out = Table({f.name: cols[e.index]
                         for e, f in zip(proj.exprs, proj.schema)},
                        out.num_rows)
        return out

    # --------------------------------------------------------- invalidation
    def invalidate_tables(self, tables) -> int:
        n = 0
        with self._lock:
            for tkey in set(tables):
                slot = self._tables.pop(tkey, None)
                n += len(slot) if slot else 0
        return n

    def invalidate_all(self) -> int:
        with self._lock:
            n = sum(len(s) for s in self._tables.values())
            self._tables.clear()
        return n

    def rows(self) -> List[Tuple]:
        """(fingerprint, schema, table, rows_covered, epoch, hits) for the
        live states — the SHOW MATERIALIZED incremental section."""
        out = []
        with self._lock:
            for slot in self._tables.values():
                for entry in slot.values():
                    if isinstance(entry, _State):
                        out.append((entry.reg.family_fp,
                                    entry.reg.schema_name,
                                    entry.reg.table_name,
                                    entry.rows_covered, entry.epoch,
                                    entry.hits))
        return out
