from .executor import Executor

# importing the plugin modules registers them (parity: the reference's
# explicit plugin registration in context.py:118-166)
from .rel.logical import basic as _basic  # noqa: F401,E402
from .rel.logical import join as _join  # noqa: F401,E402
from .rel.logical import aggregate as _aggregate  # noqa: F401,E402
from .rel.logical import window as _window  # noqa: F401,E402
from .rel.custom import ddl as _ddl  # noqa: F401,E402
from .rel.custom import ml as _ml  # noqa: F401,E402

__all__ = ["Executor"]
