"""FugueSQL execution-engine adapter.

Parity: reference integrations/fugue.py — a SqlEngine that routes FugueSQL
SELECT statements through this engine (DaskSQLEngine, fugue.py:41-70 there),
a full ExecutionEngine subclass with that SQL engine pre-configured
(DaskSQLExecutionEngine, fugue.py:73-92), and entrypoint registration that
overwrites fugue's default engine (fugue.py:21-38).  Gated on the optional
`fugue` dependency exactly like the reference.
"""
from __future__ import annotations

try:  # pragma: no cover - optional dependency
    import fugue
    from fugue import SqlEngine

    _HAS_FUGUE = True
except ImportError:  # pragma: no cover
    _HAS_FUGUE = False


if _HAS_FUGUE:  # pragma: no cover - optional dependency

    class TpuSQLEngine(SqlEngine):
        """Fugue SqlEngine backed by a dask_sql_tpu Context
        (parity: DaskSQLEngine, reference fugue.py:41)."""

        @property
        def is_distributed(self) -> bool:
            return True

        def select(self, dfs, statement):
            from ..context import Context

            context = Context()
            for name, df in dfs.items():
                context.create_table(name, df.as_pandas())
            result = context.sql(
                statement if isinstance(statement, str) else statement.construct())
            return fugue.dataframe.PandasDataFrame(result.compute())

    try:
        from fugue import NativeExecutionEngine

        class TpuSQLExecutionEngine(NativeExecutionEngine):
            """ExecutionEngine with the TPU SQL engine pre-configured
            (parity: DaskSQLExecutionEngine, reference fugue.py:73)."""

            def create_default_sql_engine(self) -> SqlEngine:
                return TpuSQLEngine(self)

    except ImportError:
        TpuSQLExecutionEngine = None  # type: ignore[assignment]

    def register_engines() -> None:
        """Register (overwrite) fugue's engine to route SQL through this
        engine (parity: _register_engines entrypoint, reference fugue.py:21)."""
        from fugue import register_execution_engine

        if TpuSQLExecutionEngine is not None:
            register_execution_engine(
                "tpu",
                lambda conf, **kwargs: TpuSQLExecutionEngine(conf=conf),
                on_dup="overwrite",
            )

    try:  # auto-register like the reference's @run_at_def
        register_engines()
    except Exception:  # dsql: allow-broad-except — registration best-effort
        pass

else:

    class TpuSQLEngine:  # type: ignore[no-redef]
        def __init__(self, *args, **kwargs):
            raise ImportError(
                "fugue is not installed; `pip install fugue` to use the adapter")

    class TpuSQLExecutionEngine:  # type: ignore[no-redef]
        def __init__(self, *args, **kwargs):
            raise ImportError(
                "fugue is not installed; `pip install fugue` to use the adapter")

    def register_engines() -> None:
        raise ImportError(
            "fugue is not installed; `pip install fugue` to use the adapter")
