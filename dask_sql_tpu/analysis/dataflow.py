"""Intraprocedural CFG + forward dataflow framework for the self-lint.

PR 19's concurrency rules (DSQL601-603) showed that AST pattern matching
alone cannot prove *path* properties: "this reservation is released on
every way out of the function" is a statement about control flow, not
about any single call site.  This module supplies the missing layer — a
control-flow graph built from a function's AST and a small worklist
engine for forward dataflow over it — so rules like DSQL701
(paired-effect release) can produce genuine all-paths proofs with a
``file:line`` witness for every edge of a counterexample path.

Graph shape
-----------
One node per *statement* (plus a handful of synthetic nodes: entry, the
two exits, branch joins, except-dispatch and finally anchors).  Statement
granularity keeps witness paths readable — every node on a reported path
is a real source line — and functions are small enough that the extra
nodes cost nothing measurable.

Two distinct exits model the two ways control leaves a function:

* ``exit``        — normal completion (``return`` or falling off the end)
* ``raise_exit``  — an exception escaping the function

Exception edges are *approximate by design*: any statement whose
immediately-executed expressions contain a call (or an explicit
``raise`` / ``assert``) gets an edge to the innermost enclosing handler
dispatch / ``finally`` anchor, or to ``raise_exit``.  Pure
name/constant moves get none.  This over-approximates raising (most
calls never throw) and that is the conservative direction for a
release-on-all-paths proof: extra paths can only make the proof
stricter, never hide a leak.  Calls deferred inside a ``lambda`` are
excluded — they do not run at the statement's site.

``try``/``finally`` uses the standard conflation: the ``finally`` suite
is built once, every continuation that enters it (normal fall, return,
exception, break, continue) is recorded, and the suite's end fans back
out to each recorded continuation.  Paths that pair one entry kind with
another's continuation are spurious but, again, only over-approximate.

``while True:`` (constant test) gets no test-false edge — the loop exits
only via ``break``/``return``/``raise``.  Without this the serving
worker's dispatch loop would appear to fall through to the function exit
on a path that cannot execute.

Dataflow
--------
`ForwardAnalysis` is a generic forward engine over a user-supplied
lattice: subclass and provide ``initial()`` / ``transfer(node, fact)`` /
``join(facts)``.  The one CFG-specific rule the engine owns: a value
propagated along an ``except`` edge is ``transfer_except(node,
pre_state)`` — by default the source node's *pre*-state, because if the
statement itself blew up, its effect did not happen.  Clients may
override asymmetrically (DSQL701 counts reaching a *release* statement
as settlement even when the release raises, while an acquire that raised
stays un-acquired).

`find_path` extracts counterexample witnesses: a concrete entry-to-exit
path avoiding "blocking" nodes; the blocker callback distinguishes
nodes that settle on every outgoing edge ("all": release sites) from
ones crossable via their own ``except`` edge ("normal": a handoff
``return`` that raised before returning).
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "CFG", "Node", "Edge", "build_cfg", "ForwardAnalysis",
    "enumerate_paths", "path_lines", "find_path", "format_witness",
    "calls_in", "node_calls", "may_raise",
]


# ---------------------------------------------------------------------------
# graph
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class Edge:
    src: int
    dst: int
    kind: str   # "step" | "branch" | "back" | "except" | "return" |
                # "break" | "continue" | "handler"
    line: int


@dataclass
class Node:
    nid: int
    label: str  # "entry" | "exit" | "raise_exit" | "stmt" | "join" |
                # "dispatch" | "handler" | "finally"
    line: int = 0
    stmt: Optional[ast.stmt] = None


class CFG:
    """Control-flow graph of one function body."""

    def __init__(self) -> None:
        self.nodes: Dict[int, Node] = {}
        self.succ: Dict[int, List[Edge]] = {}
        self.pred: Dict[int, List[Edge]] = {}
        self.entry = -1
        self.exit = -1
        self.raise_exit = -1
        self._next = 0

    def add_node(self, label: str, line: int = 0,
                 stmt: Optional[ast.stmt] = None) -> int:
        nid = self._next
        self._next += 1
        self.nodes[nid] = Node(nid, label, line, stmt)
        return nid

    def add_edge(self, src: int, dst: int, kind: str, line: int) -> None:
        e = Edge(src, dst, kind, line)
        self.succ.setdefault(src, []).append(e)
        self.pred.setdefault(dst, []).append(e)

    def stmt_nodes(self) -> Iterable[Node]:
        return (n for n in self.nodes.values() if n.stmt is not None)


# ---------------------------------------------------------------------------
# expression helpers
# ---------------------------------------------------------------------------
def calls_in(node: ast.AST) -> Iterable[ast.Call]:
    """Calls executed *at* this node, skipping deferred lambda bodies."""
    stack: List[ast.AST] = [node]
    while stack:
        nd = stack.pop()
        if isinstance(nd, ast.Lambda):
            continue
        if isinstance(nd, ast.Call):
            yield nd
        stack.extend(ast.iter_child_nodes(nd))


def _immediate_exprs(stmt: ast.stmt) -> List[ast.AST]:
    """The expressions a compound statement evaluates *itself* (its suites
    are separate nodes); a simple statement evaluates all of itself."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [item.context_expr for item in stmt.items]
    if isinstance(stmt, ast.Try):
        return []
    if hasattr(ast, "Match") and isinstance(stmt, ast.Match):
        return [stmt.subject]
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return []  # a def/class statement itself does not call its body
    return [stmt]


def node_calls(node: Node) -> List[ast.Call]:
    """Calls a CFG node executes itself (compound statements evaluate only
    their immediate expressions; suites are separate nodes)."""
    if node.stmt is None:
        return []
    out: List[ast.Call] = []
    for expr in _immediate_exprs(node.stmt):
        out.extend(calls_in(expr))
    return out


def may_raise(stmt: ast.stmt) -> bool:
    """Could executing this statement's own expressions raise?  Calls,
    explicit raises and asserts; not pure data movement."""
    if isinstance(stmt, (ast.Raise, ast.Assert)):
        return True
    for expr in _immediate_exprs(stmt):
        for _ in calls_in(expr):
            return True
    return False


# ---------------------------------------------------------------------------
# builder
# ---------------------------------------------------------------------------
@dataclass
class _Frame:
    kind: str                 # "except" | "finally" | "loop"
    dispatch: int = -1        # except: dispatch node
    anchor: int = -1          # finally: suite entry anchor
    head: int = -1            # loop: header node
    after: int = -1           # loop: join after the loop
    pending: Set[str] = field(default_factory=set)   # finally continuations


_CATCH_ALL = {"Exception", "BaseException"}


def _handler_names(h: ast.ExceptHandler) -> List[str]:
    if h.type is None:
        return ["*"]
    types = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
    out = []
    for t in types:
        if isinstance(t, ast.Attribute):
            out.append(t.attr)
        elif isinstance(t, ast.Name):
            out.append(t.id)
    return out


class _Builder:
    def __init__(self) -> None:
        self.cfg = CFG()
        self._frames: List[_Frame] = []

    def build(self, fn: ast.AST) -> CFG:
        cfg = self.cfg
        cfg.entry = cfg.add_node("entry", getattr(fn, "lineno", 0))
        cfg.exit = cfg.add_node("exit")
        cfg.raise_exit = cfg.add_node("raise_exit")
        end = self._body(fn.body, cfg.entry)
        if end is not None:
            cfg.add_edge(end, cfg.exit, "return", cfg.nodes[end].line)
        return cfg

    # -- continuation routing -------------------------------------------
    def _route(self, src: int, kind: str, line: int) -> None:
        """Route a non-local continuation ("except" / "return" / "break" /
        "continue") from `src` through enclosing finally frames."""
        for fr in reversed(self._frames):
            if fr.kind == "finally":
                self.cfg.add_edge(src, fr.anchor, kind, line)
                fr.pending.add(kind)
                return
            if fr.kind == "except" and kind == "except":
                self.cfg.add_edge(src, fr.dispatch, "except", line)
                return
            if fr.kind == "loop" and kind in ("break", "continue"):
                dst = fr.after if kind == "break" else fr.head
                self.cfg.add_edge(src, dst, kind, line)
                return
        if kind == "return":
            self.cfg.add_edge(src, self.cfg.exit, "return", line)
        elif kind == "except":
            self.cfg.add_edge(src, self.cfg.raise_exit, "except", line)
        # break/continue outside any loop is a syntax error upstream

    # -- construction ----------------------------------------------------
    def _node(self, stmt: ast.stmt, cur: int, kind: str = "step") -> int:
        n = self.cfg.add_node("stmt", stmt.lineno, stmt)
        self.cfg.add_edge(cur, n, kind, stmt.lineno)
        return n

    def _join(self) -> int:
        return self.cfg.add_node("join")

    def _body(self, stmts: Sequence[ast.stmt],
              cur: Optional[int]) -> Optional[int]:
        for stmt in stmts:
            if cur is None:
                break  # unreachable tail
            cur = self._stmt(stmt, cur)
        return cur

    def _stmt(self, s: ast.stmt, cur: int) -> Optional[int]:
        if isinstance(s, ast.If):
            return self._if(s, cur)
        if isinstance(s, (ast.While,)):
            return self._while(s, cur)
        if isinstance(s, (ast.For, ast.AsyncFor)):
            return self._for(s, cur)
        if isinstance(s, (ast.With, ast.AsyncWith)):
            return self._with(s, cur)
        if isinstance(s, ast.Try):
            return self._try(s, cur)
        if hasattr(ast, "Match") and isinstance(s, ast.Match):
            return self._match(s, cur)

        n = self._node(s, cur)
        if isinstance(s, ast.Return):
            if s.value is not None and any(True for _ in calls_in(s.value)):
                self._route(n, "except", s.lineno)
            self._route(n, "return", s.lineno)
            return None
        if isinstance(s, ast.Raise):
            self._route(n, "except", s.lineno)
            return None
        if isinstance(s, ast.Break):
            self._route(n, "break", s.lineno)
            return None
        if isinstance(s, ast.Continue):
            self._route(n, "continue", s.lineno)
            return None
        if may_raise(s):
            self._route(n, "except", s.lineno)
        return n

    def _if(self, s: ast.If, cur: int) -> Optional[int]:
        test = self._node(s, cur)
        if may_raise(s):
            self._route(test, "except", s.lineno)
        t_end = self._body(s.body, test)
        f_end = self._body(s.orelse, test) if s.orelse else test
        ends = [e for e in (t_end, f_end) if e is not None]
        if not ends:
            return None
        if len(ends) == 1:
            return ends[0]
        join = self._join()
        for e in ends:
            self.cfg.add_edge(e, join, "step", self.cfg.nodes[e].line)
        return join

    @staticmethod
    def _const_true(test: ast.expr) -> bool:
        return isinstance(test, ast.Constant) and bool(test.value) \
            and test.value is not None

    def _while(self, s: ast.While, cur: int) -> Optional[int]:
        head = self._node(s, cur)
        if may_raise(s):
            self._route(head, "except", s.lineno)
        after = self._join()
        self._frames.append(_Frame("loop", head=head, after=after))
        b_end = self._body(s.body, head)
        self._frames.pop()
        if b_end is not None:
            self.cfg.add_edge(b_end, head, "back", self.cfg.nodes[b_end].line)
        if not self._const_true(s.test):
            # test-false: run the else suite (if any), then fall out
            e_end = self._body(s.orelse, head) if s.orelse else head
            if e_end is not None:
                self.cfg.add_edge(e_end, after, "step", s.lineno)
        return after if self.cfg.pred.get(after) else None

    def _for(self, s, cur: int) -> Optional[int]:
        head = self._node(s, cur)
        if may_raise(s):
            self._route(head, "except", s.lineno)
        after = self._join()
        self._frames.append(_Frame("loop", head=head, after=after))
        b_end = self._body(s.body, head)
        self._frames.pop()
        if b_end is not None:
            self.cfg.add_edge(b_end, head, "back", self.cfg.nodes[b_end].line)
        e_end = self._body(s.orelse, head) if s.orelse else head
        if e_end is not None:
            self.cfg.add_edge(e_end, after, "step", s.lineno)
        return after if self.cfg.pred.get(after) else None

    def _with(self, s, cur: int) -> Optional[int]:
        n = self._node(s, cur)
        if may_raise(s):
            self._route(n, "except", s.lineno)
        return self._body(s.body, n)

    def _match(self, s, cur: int) -> Optional[int]:
        subj = self._node(s, cur)
        if may_raise(s):
            self._route(subj, "except", s.lineno)
        join = self._join()
        for case in s.cases:
            c_end = self._body(case.body, subj)
            if c_end is not None:
                self.cfg.add_edge(c_end, join, "step",
                                  self.cfg.nodes[c_end].line)
        # no case may match
        self.cfg.add_edge(subj, join, "branch", s.lineno)
        return join

    def _try(self, s: ast.Try, cur: int) -> Optional[int]:
        fin: Optional[_Frame] = None
        if s.finalbody:
            anchor = self.cfg.add_node("finally", s.finalbody[0].lineno)
            fin = _Frame("finally", anchor=anchor)
            self._frames.append(fin)
        disp = -1
        if s.handlers:
            disp = self.cfg.add_node("dispatch", s.lineno)
            self._frames.append(_Frame("except", dispatch=disp))

        body_end = self._body(s.body, cur)
        if s.handlers:
            self._frames.pop()  # handlers/else run outside the except frame
        if body_end is not None and s.orelse:
            body_end = self._body(s.orelse, body_end)

        h_ends: List[int] = []
        if s.handlers and self.cfg.pred.get(disp):
            catch_all = False
            for h in s.handlers:
                names = _handler_names(h)
                if "*" in names or any(n in _CATCH_ALL for n in names):
                    catch_all = True
                hn = self.cfg.add_node("handler", h.lineno)
                self.cfg.add_edge(disp, hn, "handler", h.lineno)
                h_end = self._body(h.body, hn)
                if h_end is not None:
                    h_ends.append(h_end)
            if not catch_all:
                # typed handlers may not match: the exception continues out
                self._route(disp, "except", s.lineno)

        if fin is not None:
            self._frames.pop()
            if body_end is not None:
                self.cfg.add_edge(body_end, fin.anchor, "step",
                                  self.cfg.nodes[body_end].line)
                fin.pending.add("fall")
            for he in h_ends:
                self.cfg.add_edge(he, fin.anchor, "step",
                                  self.cfg.nodes[he].line)
                fin.pending.add("fall")
            if not self.cfg.pred.get(fin.anchor):
                return None
            fin_end = self._body(s.finalbody, fin.anchor)
            if fin_end is None:
                return None  # the finally suite itself diverges
            after: Optional[int] = None
            line = s.finalbody[-1].lineno
            for kind in sorted(fin.pending):
                if kind == "fall":
                    after = self._join()
                    self.cfg.add_edge(fin_end, after, "step", line)
                else:
                    self._route(fin_end, kind, line)
            return after

        ends = ([body_end] if body_end is not None else []) + h_ends
        if not ends:
            return None
        if len(ends) == 1:
            return ends[0]
        join = self._join()
        for e in ends:
            self.cfg.add_edge(e, join, "step", self.cfg.nodes[e].line)
        return join


def build_cfg(fn: ast.AST) -> CFG:
    """CFG of one ``FunctionDef`` / ``AsyncFunctionDef`` body.  Nested
    function/class definitions are single statement nodes (their bodies
    are separate CFGs)."""
    return _Builder().build(fn)


# ---------------------------------------------------------------------------
# forward dataflow engine
# ---------------------------------------------------------------------------
class ForwardAnalysis:
    """Generic forward worklist dataflow.  Subclass with a lattice:

    * ``initial()``            -- fact at function entry
    * ``transfer(node, fact)`` -- fact after executing `node`
    * ``join(facts)``          -- merge at control-flow confluences

    Facts must be hashable/comparable values (frozensets work well).
    ``except`` edges propagate ``transfer_except(node, pre_state)``; the
    default is the source's *pre*-state unchanged — if the statement
    raised, its own effect did not take place.  A client may override it
    asymmetrically (DSQL701 applies *releases* even on the except edge —
    demanding a release-of-the-release would be unsatisfiable — while
    acquires stay pre-state).
    """

    def initial(self):
        return frozenset()

    def transfer(self, node: Node, fact):
        return fact

    def transfer_except(self, node: Node, fact):
        return fact

    def join(self, facts):
        merged = set()
        for f in facts:
            merged |= f
        return frozenset(merged)

    def run(self, cfg: CFG) -> Tuple[Dict[int, object], Dict[int, object]]:
        """Fixpoint; returns (fact_in, fact_out) per node id.  Unreached
        nodes are absent from both maps."""
        fact_in: Dict[int, object] = {cfg.entry: self.initial()}
        fact_out: Dict[int, object] = {}
        work = [cfg.entry]
        while work:
            nid = work.pop()
            fi = fact_in[nid]
            fo = self.transfer(cfg.nodes[nid], fi)
            fact_out[nid] = fo
            for e in cfg.succ.get(nid, []):
                val = self.transfer_except(cfg.nodes[nid], fi) \
                    if e.kind == "except" else fo
                old = fact_in.get(e.dst)
                new = val if old is None else self.join([old, val])
                if new != old:
                    fact_in[e.dst] = new
                    work.append(e.dst)
        return fact_in, fact_out


# ---------------------------------------------------------------------------
# path extraction
# ---------------------------------------------------------------------------
def enumerate_paths(cfg: CFG, limit: int = 2000) -> List[List[Edge]]:
    """All simple entry-to-exit paths (each node at most once, so loop
    bodies appear at most one iteration).  For tests and witnesses, not
    for analysis — the dataflow engine handles cycles by fixpoint."""
    out: List[List[Edge]] = []
    targets = {cfg.exit, cfg.raise_exit}

    def dfs(nid: int, path: List[Edge], on_path: Set[int]) -> None:
        if len(out) >= limit:
            return
        if nid in targets:
            out.append(list(path))
            return
        for e in cfg.succ.get(nid, []):
            if e.dst in on_path:
                continue
            path.append(e)
            on_path.add(e.dst)
            dfs(e.dst, path, on_path)
            on_path.discard(e.dst)
            path.pop()

    dfs(cfg.entry, [], {cfg.entry})
    return out


def path_lines(cfg: CFG, limit: int = 2000) -> Set[Tuple]:
    """Each simple path as a tuple of visited statement lines plus a
    terminal marker ("exit" or "raise"), for exact-shape assertions."""
    shapes: Set[Tuple] = set()
    for path in enumerate_paths(cfg, limit):
        lines: List[object] = []
        for e in path:
            node = cfg.nodes[e.dst]
            if node.stmt is not None:
                lines.append(node.line)
        terminal = "raise" if path and path[-1].dst == cfg.raise_exit \
            else "exit"
        shapes.add(tuple(lines) + (terminal,))
    return shapes


def find_path(cfg: CFG, start: int, targets: Set[int],
              blocks: Callable[[Node], object]) -> Optional[List[Edge]]:
    """Shortest path from `start` to any target on which no intermediate
    node "blocks".  ``blocks(node)`` returns ``"all"`` (the node settles
    the effect even on its own except edge — a release statement),
    ``"normal"`` (crossable only via its own except edge — a handoff
    ``return`` that raised before returning), or a falsy value.  `start`'s
    own ``except`` edges are excluded: if the acquire raised, no effect
    took place."""
    from collections import deque

    parent: Dict[int, Edge] = {}
    seen = {start}
    q = deque([start])
    while q:
        nid = q.popleft()
        node = cfg.nodes[nid]
        verdict = None if nid == start else blocks(node)
        if verdict == "all":
            continue
        for e in cfg.succ.get(nid, []):
            if nid == start and e.kind == "except":
                continue
            if verdict and e.kind != "except":
                continue
            if e.dst in seen:
                continue
            seen.add(e.dst)
            parent[e.dst] = e
            if e.dst in targets:
                path = [e]
                while path[0].src != start:
                    path.insert(0, parent[path[0].src])
                return path
            q.append(e.dst)
    return None


def format_witness(cfg: CFG, path: List[Edge]) -> str:
    """`10 -> 12 -> except 14 -> raise-exit` — one hop per edge, statement
    lines only; exceptional hops are labelled."""
    if not path:
        return "<empty>"
    parts: List[str] = [str(cfg.nodes[path[0].src].line)]
    for e in path:
        node = cfg.nodes[e.dst]
        if node.nid == cfg.exit:
            label = "exit"
        elif node.nid == cfg.raise_exit:
            label = "raise-exit"
        elif node.stmt is None:
            continue  # synthetic join/dispatch/finally anchor
        else:
            label = str(node.line)
        if e.kind in ("except", "return", "back", "break", "continue"):
            label = f"{e.kind} {label}"
        parts.append(label)
    return " -> ".join(parts)
