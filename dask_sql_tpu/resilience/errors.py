"""Structured error taxonomy for every failure crossing the executor boundary.

Role parity: the reference delegates fault tolerance to dask.distributed —
a failed task is retried by the scheduler and the user sees dask's exception
chain.  The TPU-native rewrite dropped that layer; this module replaces it
with an explicit taxonomy so the serving runtime, the degradation ladder
(resilience/ladder.py) and the Presto wire (server/responses.py) can make
policy decisions from three flags instead of string-matching tracebacks:

- ``code``       stable machine-readable name (also the Presto errorName);
- ``retryable``  a bounded-backoff retry at the ServingRuntime worker may
                 succeed (transient device/runtime hiccup, NOT a user error);
- ``degradable`` a lower execution rung (compiled -> interpreted,
                 sharded -> single-device, device -> CPU) may succeed.

This module must stay import-light (no jax, no package-internal imports):
planner/serving/executor modules all base their exceptions on it.
"""
from __future__ import annotations

import re as _re
from typing import Optional

#: Presto wire errorType values (server/responses.py maps code -> payload)
USER_ERROR = "USER_ERROR"
INTERNAL_ERROR = "INTERNAL_ERROR"
INSUFFICIENT_RESOURCES = "INSUFFICIENT_RESOURCES"


class QueryError(RuntimeError):
    """Base of the taxonomy.  Subclasses pin class-level defaults; an
    instance can override any of them via keyword arguments (e.g. a
    compile failure that is known-permanent can set ``retryable=False``)."""

    code: str = "QUERY_ERROR"
    error_type: str = INTERNAL_ERROR
    retryable: bool = False
    degradable: bool = False

    def __init__(self, message: str = "", *,
                 code: Optional[str] = None,
                 error_type: Optional[str] = None,
                 retryable: Optional[bool] = None,
                 degradable: Optional[bool] = None,
                 query_id: Optional[str] = None):
        super().__init__(message or self.__class__.code)
        if code is not None:
            self.code = code
        if error_type is not None:
            self.error_type = error_type
        if retryable is not None:
            self.retryable = retryable
        if degradable is not None:
            self.degradable = degradable
        self.query_id = query_id

    def payload(self) -> dict:
        """The structured fields the Presto wire error embeds."""
        return {
            "code": self.code,
            "errorType": self.error_type,
            "retryable": bool(self.retryable),
            "degradable": bool(self.degradable),
        }


# --------------------------------------------------------------- frontend
class ParseError(QueryError, ValueError):
    """SQL text rejected by the lexer/parser.  ValueError base kept for
    compatibility with the planner's historical ParsingException/LexError."""

    code = "PARSE_ERROR"
    error_type = USER_ERROR


class BindingError(QueryError, ValueError):
    """Name/type resolution failed (unknown table/column/function)."""

    code = "BIND_ERROR"
    error_type = USER_ERROR


class PlanError(QueryError):
    """Logical planning or optimization failed irrecoverably (the driver
    normally falls back to the unoptimized plan instead)."""

    code = "PLAN_ERROR"


# --------------------------------------------------------------- execution
class CompileError(QueryError):
    """The compiled fast path (whole-pipeline jit, compiled select, XLA
    lowering) failed.  Degradable: the interpreted per-op path computes the
    same answer without that compiler."""

    code = "COMPILE_ERROR"
    degradable = True


class CompileTimeoutError(CompileError):
    """An XLA compile exceeded ``resilience.compile_timeout_ms`` and was
    abandoned by the watchdog (resilience/watchdog.py).  Degradable like
    any CompileError — the ladder serves the query on a lower rung and the
    breaker is charged so the fingerprint stops re-attempting the hang."""

    code = "COMPILE_TIMEOUT"


class StreamLaunchTimeoutError(QueryError):
    """A streamed per-chunk launch exceeded
    ``serving.stream.launch_timeout_ms`` and was abandoned by the watchdog
    BETWEEN chunks (streaming/runner.py) instead of wedging the ticket's
    reservation forever.  Degradable — the ladder steps the streamed rung
    down and charges the breaker — but deliberately NOT a
    `ResourceExhaustedError`: a wedged launch is not memory pressure, so
    the reclaim-before-degrade retry does not apply."""

    code = "STREAM_LAUNCH_TIMEOUT"
    error_type = INSUFFICIENT_RESOURCES
    degradable = True


class ExecutionError(QueryError):
    """A plan node failed while executing device kernels."""

    code = "EXECUTION_ERROR"


class TransientExecutionError(ExecutionError):
    """An execution failure that is expected to succeed on retry (device
    runtime hiccup, transient transfer failure)."""

    code = "TRANSIENT_EXECUTION_ERROR"
    retryable = True


class ResourceExhaustedError(QueryError):
    """Device memory / capacity exhausted (XLA RESOURCE_EXHAUSTED, capacity
    ladder tops out).  Degradable — a smaller-footprint rung (interpreted
    ops, single device, CPU host memory) may fit."""

    code = "RESOURCE_EXHAUSTED"
    error_type = INSUFFICIENT_RESOURCES
    degradable = True


class DeadlineError(QueryError):
    """The query ran past its deadline and was cancelled at a checkpoint."""

    code = "EXCEEDED_TIME_LIMIT"
    error_type = INSUFFICIENT_RESOURCES


class CancelledError(QueryError):
    """The client cancelled the query; raised at the next checkpoint."""

    code = "USER_CANCELED"
    error_type = USER_ERROR


class ShutdownError(QueryError):
    """The serving runtime shut down before this query could run; queued
    futures fail with this instead of hanging forever."""

    code = "SERVER_SHUTTING_DOWN"
    retryable = True  # another replica (or a restart) can take the query


class ReplicaFailedError(QueryError):
    """The replica a query was routed to died (or was draining / timed
    out) before the query reached a terminal state.  Retryable: a
    re-dispatch to a SURVIVING replica — deduped by the idempotency key,
    which is the result-cache key's ingredients — can succeed; the fleet
    router (fleet/router.py) does exactly that with bounded backoff.  The
    serving worker's in-replica retry loop never sees this error (it is
    set on routed futures by the kill/drain paths, above the worker), so
    the flag cannot make a dead replica retry onto itself."""

    code = "REPLICA_FAILED"
    error_type = INSUFFICIENT_RESOURCES
    retryable = True


class UnroutableStatementError(QueryError, ValueError):
    """A catalog- or session-mutating statement the fleet router cannot
    safely fan out (CREATE/DROP/ALTER, model statements, USE SCHEMA,
    multi-statement scripts containing a mutation).  Only single-statement
    ``INSERT INTO`` mutates through the router's epoch-fenced write
    fan-out; executing any other mutation on a single routed replica would
    silently diverge the members' catalogs and poison the per-table epoch
    fences, so the router rejects it up front — apply such DDL to every
    replica at fleet build time instead."""

    code = "FLEET_UNROUTABLE"
    error_type = USER_ERROR


class ModelError(QueryError, ValueError):
    """CREATE MODEL / PREDICT / EXPORT MODEL failed on the model layer
    (unresolvable model_class, fit/predict raising, bad WITH options).
    USER_ERROR: the statement — not the engine — is wrong, so the Presto
    wire reports it as such instead of an INTERNAL_ERROR traceback.
    ValueError base kept for compatibility with the historical raw raises
    (the ParseError/BindingError pattern)."""

    code = "MODEL_ERROR"
    error_type = USER_ERROR


class ModelNotFoundError(ModelError):
    """The referenced model is not registered in the target schema."""

    code = "MODEL_NOT_FOUND"


class InjectedFault(QueryError):
    """Marker mixin-style base for faults raised by resilience/faults.py so
    tests and logs can tell injected failures from organic ones."""

    code = "INJECTED_FAULT"


#: markers of low-level runtime errors that mean "out of device memory".
#: OOM must be word-bounded — a bare substring would match ROOM/ZOOM/BOOM
#: and misroute an unrelated bug onto the degradation ladder.
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "out of memory", "Out of memory",
                "failed to allocate")
_OOM_RE = _re.compile(r"\bOOM\b")


def classify(exc: BaseException, *, query_id: Optional[str] = None) -> QueryError:
    """Wrap an arbitrary exception into the taxonomy (idempotent).

    XLA surfaces device OOM as an XlaRuntimeError whose message leads with
    RESOURCE_EXHAUSTED; jax re-raises various transient runtime failures the
    same way.  Everything unrecognized becomes a non-retryable
    ExecutionError so the wire payload is structured either way."""
    if isinstance(exc, QueryError):
        if query_id is not None and exc.query_id is None:
            exc.query_id = query_id
        return exc
    msg = f"{type(exc).__name__}: {exc}"
    text = str(exc)
    if any(m in text for m in _OOM_MARKERS) or _OOM_RE.search(text):
        err: QueryError = ResourceExhaustedError(msg, query_id=query_id)
    elif isinstance(exc, MemoryError):
        err = ResourceExhaustedError(msg, query_id=query_id)
    elif isinstance(exc, (ConnectionError, TimeoutError)):
        # deliberately NOT all OSError: FileNotFoundError/PermissionError are
        # permanent — retrying them burns the deadline and tells clients to
        # resubmit a query that can never succeed
        err = TransientExecutionError(msg, query_id=query_id)
    else:
        err = ExecutionError(msg, query_id=query_id)
    err.__cause__ = exc
    return err


def is_retryable(exc: BaseException) -> bool:
    return isinstance(exc, QueryError) and exc.retryable


def is_degradable(exc: BaseException) -> bool:
    return isinstance(exc, QueryError) and exc.degradable
