"""Input plugin base (parity: reference input_utils/base.py:4)."""
from __future__ import annotations


class BaseInputPlugin:
    """Converts one kind of user input into a device-backed DataContainer."""

    def is_correct_input(self, input_item, table_name: str, format: str = None, **kwargs) -> bool:
        raise NotImplementedError

    def to_dc(self, input_item, table_name: str, format: str = None, **kwargs):
        raise NotImplementedError
